"""Serve a classification model online (the sibling of train_net.py /
test_net.py; no reference analogue — the reference stops at offline eval).

Loads any zoo arch from an orbax checkpoint or torch pickle
(``MODEL.WEIGHTS``) or the pretrained URL zoo (``MODEL.PRETRAINED``),
applies the val transform pipeline to incoming images, and serves
predictions through the dynamic micro-batching engine
(distribuuuu_tpu/serve/) over a length-prefixed socket. SIGTERM drains
gracefully: stop accepting, finish every in-flight request, exit.

``--fleet N`` runs an N-replica serving FLEET instead of one engine
(distribuuuu_tpu/serve/fleet/): this process becomes the router on
``SERVE.HOST:PORT`` (least-loaded dispatch, idempotent retry, verbatim
backpressure passthrough) and spawns N replicas — each a plain
``serve_net.py`` on an ephemeral port — warm-up gated, health-checked,
and autoscaled against the ``SERVE.FLEET`` policy. SIGTERM drains the
whole fleet: stop accepting, drain every replica, exit.

Usage:
    # socket service (SERVE.* config node controls batching/port):
    python serve_net.py --cfg config/resnet50.yaml MODEL.WEIGHTS path/to/ckpt

    # an autoscaling 2..4-replica fleet behind one router port
    # (--fleet before the KEY VALUE overrides — those are greedy):
    python serve_net.py --cfg config/resnet50.yaml --fleet 2 \\
        MODEL.WEIGHTS path/to/ckpt SERVE.FLEET.MAX_REPLICAS 4

    # one-shot batch mode (tests/CI): val-transformed .npy in, logits out
    python serve_net.py --cfg config/resnet50.yaml \\
        --batch-input imgs.npy --batch-output logits.npy
"""

import argparse
import os
import sys

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Serve a classification model."
    )
    parser.add_argument(
        "--cfg", dest="cfg_file", required=True, type=str,
        help="Config file location",
    )
    parser.add_argument(
        "--fleet", type=int, default=0, metavar="N",
        help="run an N-replica fleet (router + pool + autoscaler) instead "
             "of a single engine; 0 = single-replica mode",
    )
    parser.add_argument(
        "--batch-input", default=None,
        help="one-shot batch mode: .npy of val-transformed images "
             "('-' = stdin) instead of the socket server",
    )
    parser.add_argument(
        "--batch-output", default="-",
        help="batch-mode logits .npy destination ('-' = stdout)",
    )
    parser.add_argument(
        "opts", help="See distribuuuu_tpu/config.py for all options",
        default=None, nargs=argparse.REMAINDER,
    )
    args = parser.parse_args(sys.argv[1:] if argv is None else argv)
    config.merge_from_file(args.cfg_file)
    cfg.merge_from_list(args.opts)
    cfg.freeze()

    if args.fleet:
        return run_fleet(args.fleet)

    from distribuuuu_tpu import telemetry, trainer
    from distribuuuu_tpu.serve import admission, engine_from_cfg, protocol
    from distribuuuu_tpu.utils.jsonlog import setup_metrics_log
    from distribuuuu_tpu.utils.logger import get_logger, setup_logger

    setup_logger()
    logger = get_logger()
    # per-rank telemetry (TELEMETRY node): a standalone replica is rank 0;
    # a fleet replica gets its rank from the pool (DTPU_REPLICA_RANK), so
    # N replicas sharing OUT_DIR write N distinct telemetry sinks — bucket
    # AOT compiles land as kind="compile" records per replica
    telemetry.setup_from_cfg(
        cfg, rank=int(os.environ.get("DTPU_REPLICA_RANK", "0"))
    )
    # persistent compilation cache (COMPILE_CACHE): a restarted or
    # replacement replica deserializes its AOT bucket executables from
    # disk instead of paying the warm-up compile storm again
    from distribuuuu_tpu.asyncplane import compile_cache

    compile_cache.setup_from_cfg(cfg)
    if cfg.MODEL.ARCH.startswith("gpt"):
        # the LM generation plane (lm/service.py): KV-cache continuous
        # batching behind the SAME socket/stats/fleet protocol; generate
        # requests arrive as streaming ctrl frames
        from distribuuuu_tpu.lm import service as lm_service

        if args.batch_input is not None:
            raise SystemExit(
                "--batch-input is the image engine's one-shot mode; "
                "drive a gpt_* replica with generate ctrl frames "
                "(lm/service.generate_request) instead"
            )
        engine = lm_service.engine_from_cfg()
        logger.info(
            "generating with %s: %d tile executables compiled "
            "(decode tiles %s), %d slots, prompt<=%d, max_new=%d",
            cfg.MODEL.ARCH, engine.n_compiles,
            sorted(engine._decode_exec), engine.n_slots,
            engine.prompt_len, engine.max_new,
        )
    else:
        engine = engine_from_cfg()
        logger.info(
            "serving %s: buckets %s compiled (%d shapes), max_wait %.1f ms, "
            "queue bound %d",
            cfg.MODEL.ARCH, engine.buckets, engine.n_compiles,
            cfg.SERVE.MAX_WAIT_MS, cfg.SERVE.MAX_QUEUE,
        )
    engine.start()

    if args.batch_input is not None:
        n = protocol.run_batch(engine, args.batch_input, args.batch_output)
        engine.drain()
        logger.info("batch mode: served %d requests", n)
        return

    setup_metrics_log(cfg.OUT_DIR)  # serve metrics land in metrics.jsonl
    admission.install_drain()  # SIGTERM → graceful drain (preempt pattern)
    listener = protocol.open_listener(cfg.SERVE.HOST, cfg.SERVE.PORT)
    host, port = listener.getsockname()[:2]
    logger.info("listening on %s:%d (SIGTERM drains gracefully)", host, port)
    try:
        protocol.serve_forever(
            engine, listener, should_stop=admission.drain_requested,
            topk=trainer.effective_topk(),
        )
    except KeyboardInterrupt:
        listener.close()
        engine.drain()
    logger.info("drained; exiting")


def run_fleet(n: int):
    """The ``--fleet N`` entrypoint: this process is the router; replicas
    are child ``serve_net.py`` processes spawned from a dump of the merged
    config (so every CLI override reaches them), each with its own
    telemetry rank. SIGTERM drains the fleet end to end."""
    from distribuuuu_tpu import telemetry
    from distribuuuu_tpu.serve import admission, protocol
    from distribuuuu_tpu.serve.fleet import FleetService
    from distribuuuu_tpu.utils.jsonlog import setup_metrics_log
    from distribuuuu_tpu.utils.logger import get_logger, setup_logger

    setup_logger()
    logger = get_logger()
    telemetry.setup_from_cfg(cfg, rank=0)  # replicas take ranks 1..N
    setup_metrics_log(cfg.OUT_DIR)
    fleet_dir = os.path.join(cfg.OUT_DIR, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    cfg_path = os.path.join(fleet_dir, "replica_cfg.yaml")
    with open(cfg_path, "w") as f:
        f.write(cfg.dump())

    svc = FleetService(cfg, n, cfg_path=cfg_path)
    logger.info(
        "fleet: spawning %d replica(s) of %s (budget %d..%d, autoscale %s)",
        n, cfg.MODEL.ARCH, cfg.SERVE.FLEET.MIN_REPLICAS,
        cfg.SERVE.FLEET.MAX_REPLICAS, cfg.SERVE.FLEET.AUTOSCALE,
    )
    svc.start(wait=True)
    routable = svc.router.n_routable()
    if not routable:
        svc.shutdown()
        raise RuntimeError(
            "fleet: no replica survived warm-up — see "
            f"{fleet_dir}/replica*.log"
        )
    admission.install_drain()  # SIGTERM → drain the whole fleet
    listener = protocol.open_listener(cfg.SERVE.HOST, cfg.SERVE.PORT)
    host, port = listener.getsockname()[:2]
    logger.info(
        "fleet: router listening on %s:%d over %d routable replica(s) "
        "(SIGTERM drains gracefully)", host, port, routable,
    )
    try:
        svc.serve(listener, should_stop=admission.drain_requested)
    except KeyboardInterrupt:
        listener.close()
    svc.shutdown()
    logger.info("fleet drained; exiting")


if __name__ == "__main__":
    main()
