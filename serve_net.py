"""Serve a classification model online (the sibling of train_net.py /
test_net.py; no reference analogue — the reference stops at offline eval).

Loads any zoo arch from an orbax checkpoint or torch pickle
(``MODEL.WEIGHTS``) or the pretrained URL zoo (``MODEL.PRETRAINED``),
applies the val transform pipeline to incoming images, and serves
predictions through the dynamic micro-batching engine
(distribuuuu_tpu/serve/) over a length-prefixed socket. SIGTERM drains
gracefully: stop accepting, finish every in-flight request, exit.

Usage:
    # socket service (SERVE.* config node controls batching/port):
    python serve_net.py --cfg config/resnet50.yaml MODEL.WEIGHTS path/to/ckpt

    # one-shot batch mode (tests/CI): val-transformed .npy in, logits out
    python serve_net.py --cfg config/resnet50.yaml \\
        --batch-input imgs.npy --batch-output logits.npy
"""

import argparse
import sys

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Serve a classification model."
    )
    parser.add_argument(
        "--cfg", dest="cfg_file", required=True, type=str,
        help="Config file location",
    )
    parser.add_argument(
        "--batch-input", default=None,
        help="one-shot batch mode: .npy of val-transformed images "
             "('-' = stdin) instead of the socket server",
    )
    parser.add_argument(
        "--batch-output", default="-",
        help="batch-mode logits .npy destination ('-' = stdout)",
    )
    parser.add_argument(
        "opts", help="See distribuuuu_tpu/config.py for all options",
        default=None, nargs=argparse.REMAINDER,
    )
    args = parser.parse_args(sys.argv[1:] if argv is None else argv)
    config.merge_from_file(args.cfg_file)
    cfg.merge_from_list(args.opts)
    cfg.freeze()

    from distribuuuu_tpu import telemetry, trainer
    from distribuuuu_tpu.serve import admission, engine_from_cfg, protocol
    from distribuuuu_tpu.utils.jsonlog import setup_metrics_log
    from distribuuuu_tpu.utils.logger import get_logger, setup_logger

    setup_logger()
    logger = get_logger()
    # per-rank telemetry (TELEMETRY node): serving is single-process, so
    # rank 0 — bucket AOT compiles land as kind="compile" records
    telemetry.setup_from_cfg(cfg)
    engine = engine_from_cfg()
    logger.info(
        "serving %s: buckets %s compiled (%d shapes), max_wait %.1f ms, "
        "queue bound %d",
        cfg.MODEL.ARCH, engine.buckets, engine.n_compiles,
        cfg.SERVE.MAX_WAIT_MS, cfg.SERVE.MAX_QUEUE,
    )
    engine.start()

    if args.batch_input is not None:
        n = protocol.run_batch(engine, args.batch_input, args.batch_output)
        engine.drain()
        logger.info("batch mode: served %d requests", n)
        return

    setup_metrics_log(cfg.OUT_DIR)  # serve metrics land in metrics.jsonl
    admission.install_drain()  # SIGTERM → graceful drain (preempt pattern)
    listener = protocol.open_listener(cfg.SERVE.HOST, cfg.SERVE.PORT)
    host, port = listener.getsockname()[:2]
    logger.info("listening on %s:%d (SIGTERM drains gracefully)", host, port)
    try:
        protocol.serve_forever(
            engine, listener, should_stop=admission.drain_requested,
            topk=trainer.effective_topk(),
        )
    except KeyboardInterrupt:
        listener.close()
        engine.drain()
    logger.info("drained; exiting")


if __name__ == "__main__":
    main()
