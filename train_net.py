"""Train a classification model (≙ /root/reference/train_net.py).

Usage:
    python train_net.py --cfg config/resnet50.yaml [KEY VALUE ...]
"""

import distribuuuu_tpu.config as config
import distribuuuu_tpu.trainer as trainer
from distribuuuu_tpu.config import cfg


def main():
    config.load_cfg_fom_args("Train a classification model.")
    cfg.freeze()
    trainer.train_model()


if __name__ == "__main__":
    main()
