"""Evaluate a classification model (≙ /root/reference/test_net.py).

Usage:
    python test_net.py --cfg config/resnet50.yaml MODEL.WEIGHTS path/to/ckpt
"""

import distribuuuu_tpu.config as config
import distribuuuu_tpu.trainer as trainer
from distribuuuu_tpu.config import cfg


def main():
    config.load_cfg_fom_args("Evaluate a classification model.")
    cfg.freeze()
    trainer.test_model()


if __name__ == "__main__":
    main()
