"""One analyzed program per config stanza — lowered once, compiled once.

``build_bundle`` drives the EXISTING partition layer exactly the way
``train_net.py`` would — merge the stanza, validate through the topology
registry, ``lowering.lower()`` — then lowers/compiles the train step
against abstract declared-sharding arguments (``Lowered.abstract_args``)
and extracts every artifact the program passes need:

* the lowered StableHLO text with debug locations (dtype pass),
* the compiled post-GSPMD HLO text (collectives, donation),
* the compiled output shardings of the state tree (replication pass),
* ``memory_analysis()`` byte counts (donation footprint arithmetic),
* the spec-algebra collective expectations
  (``specs.collective_expectations``).

Each pass reads this one :class:`ProgramBundle`; nothing compiles twice.

Analysis geometry: the stanza's MESH axes, arch, class count, dtype and
ZeRO stage — everything placement-relevant — are analyzed VERBATIM.
Batch geometry (batch size, image size, LM sequence length) is shrunk to
keep CPU compile cost bounded: batch leaves ride the declared
``BATCH_TABLE`` specs whatever their size, so placement decisions do not
depend on it (the same downscaling the mesh-sweep dryrun uses). The
shrunken geometry is recorded per case in the report.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

# bounded analysis geometry (placement-neutral, see module docstring)
ANALYSIS_IM_SIZE = 32
ANALYSIS_SEQ_LEN = 32


@dataclass
class ProgramBundle:
    """Everything the program passes read for one stanza."""

    name: str
    arch: str
    topology: Any
    mesh: Any
    layout: dict
    lowered_text: str
    compiled_text: str
    state_in: Any            # abstract state args (SDS with shardings)
    state_out_shardings: Any  # compiled shardings of the output state
    n_flat_inputs: int
    memory: dict | None
    expectations: dict
    geometry: dict
    seconds: float = 0.0
    extras: dict = field(default_factory=dict)


def build_bundle(name: str, *, n_devices: int = 8,
                 batch_size: int | None = None) -> ProgramBundle:
    """Build the analyzed program for the LIVE cfg (caller merged the
    stanza). One lower, one compile; every extraction after that is
    text/metadata reads."""
    import jax

    from distribuuuu_tpu.analysis import hlo
    from distribuuuu_tpu.config import cfg
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib
    from distribuuuu_tpu.parallel.partition import lowering, specs
    from distribuuuu_tpu.telemetry import costmodel
    from distribuuuu_tpu.utils.optim import construct_optimizer

    t0 = time.perf_counter()
    # bounded geometry (placement-neutral — module docstring)
    cfg.TRAIN.IM_SIZE = min(int(cfg.TRAIN.IM_SIZE), ANALYSIS_IM_SIZE)
    cfg.LM.SEQ_LEN = min(int(cfg.LM.SEQ_LEN), ANALYSIS_SEQ_LEN)

    topo = trainer.check_trainer_mesh()
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg(topo)
    low = lowering.lower(
        model, construct_optimizer(), trainer.effective_topk(),
        mesh=mesh, topology=topo, im_size=cfg.TRAIN.IM_SIZE,
    )
    state_sds, batch_sds = low.abstract_args(batch_size)
    lowered = low.train_step.lower(state_sds, batch_sds)
    lowered_text = hlo.stablehlo_with_locs(lowered)
    compiled = lowered.compile()
    compiled_text = compiled.as_text()
    try:
        memory = costmodel.normalize_memory(compiled.memory_analysis())
    except Exception:
        memory = None
    state_out = compiled.output_shardings[0]
    flat_in = jax.tree.leaves((state_sds, batch_sds))
    return ProgramBundle(
        name=name,
        arch=str(cfg.MODEL.ARCH),
        topology=topo,
        mesh=mesh,
        layout=low.layout,
        lowered_text=lowered_text,
        compiled_text=compiled_text,
        state_in=state_sds,
        state_out_shardings=state_out,
        n_flat_inputs=len(flat_in),
        memory=memory,
        expectations=specs.collective_expectations(low.layout, topo),
        geometry={
            "im_size": int(cfg.TRAIN.IM_SIZE),
            "seq_len": int(cfg.LM.SEQ_LEN),
            "batch": int(
                jax.tree.leaves(batch_sds)[0].shape[0]
            ),
            "compute_dtype": str(cfg.DEVICE.COMPUTE_DTYPE),
            "n_devices": int(n_devices),
        },
        seconds=round(time.perf_counter() - t0, 1),
    )
