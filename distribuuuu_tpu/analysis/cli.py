"""``distribuuuu-staticcheck`` — the static analysis plane's CLI.

    distribuuuu-staticcheck [--ast-only | --program-only]
                            [--configs SUBSTR] [--no-sweep]
                            [--json-out REPORT.json]
                            [--baseline ANALYSIS_BASELINE.json]
                            [--devices N]

Exit 0 when every finding is waived (with a committed justification in
the baseline), 1 when any unwaived finding remains — the same gate
tier-1 pins. ``tools/staticcheck.py`` is the in-repo twin.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distribuuuu-staticcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--repo", default=None,
                    help="repo root (default: the checkout this package "
                         "lives in)")
    ap.add_argument("--ast-only", action="store_true",
                    help="only the AST passes (knobs/dispatch/telemetry) "
                         "— seconds, no compiles")
    ap.add_argument("--program-only", action="store_true",
                    help="only the program passes over the stanzas")
    ap.add_argument("--configs", default=None,
                    help="substring filter over program case names "
                         "(e.g. 'resnet18')")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the generated mesh-sweep core cases")
    ap.add_argument("--json-out", default=None,
                    help="write the full report JSON here")
    ap.add_argument("--baseline", default=None,
                    help="waiver file (default: {repo}/"
                         "ANALYSIS_BASELINE.json)")
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual device count for program lowering "
                         "(default 8 — the stanza-gate mesh)")
    ap.add_argument("--knob-index", action="store_true",
                    help="print the RUNBOOK config-knob index markdown "
                         "(generated from config.py) and exit")
    args = ap.parse_args(argv)

    if args.knob_index:
        from distribuuuu_tpu.analysis import runner as _runner
        from distribuuuu_tpu.analysis.passes import knobs as _knobs

        repo = args.repo or _runner.repo_root()
        print(_knobs.knob_index_markdown(
            os.path.join(repo, "distribuuuu_tpu", "config.py")
        ))
        return 0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if not args.ast_only:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.devices}"
            ).strip()

    from distribuuuu_tpu.analysis import runner
    from distribuuuu_tpu.analysis.findings import write_report

    def progress(record, findings):
        status = "ok " if record.get("ok") else "FAIL"
        n = len(findings)
        print(
            f"  {status} {record['name']:<44} "
            f"{record.get('seconds', 0):6.1f}s  "
            f"{n} finding(s)",
            flush=True,
        )

    report = runner.run_all(
        repo=args.repo,
        n_devices=args.devices,
        ast_only=args.ast_only,
        program_only=args.program_only,
        configs=args.configs,
        sweep=not args.no_sweep,
        baseline_path=args.baseline,
        progress=progress,
    )

    for f in report.findings:
        tag = "waived " if f.waived else f.severity.upper().ljust(7)
        print(f"{tag} [{f.pass_id}] {f.location}\n        {f.message}")
    unwaived = report.unwaived
    print(
        f"staticcheck: {len(report.findings)} finding(s), "
        f"{len(unwaived)} unwaived, {len(report.waived)} waived, "
        f"{len(report.cases)} program case(s), "
        f"passes: {', '.join(sorted(set(report.passes_run)))}"
    )
    if args.json_out:
        write_report(report, args.json_out)
        print(f"wrote {args.json_out}")
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main())
