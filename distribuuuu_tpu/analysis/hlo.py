"""Text-level parsers over lowered StableHLO and compiled (post-GSPMD)
HLO — the program passes' shared toolbox.

Everything here is pure string → dict: shapes and byte counts, the
``input_output_alias`` map, the collective census with replica-group →
mesh-axis attribution, and bf16→f32 upcast extraction with scope
attribution from the MLIR location table. No jax arrays are touched;
the analyzer hands in the texts it got from the one lowered/compiled
bundle per stanza (analysis/program.py).
"""

from __future__ import annotations

import re

import numpy as np

# HLO primitive byte widths (the types step programs actually contain)
DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")


def shape_bytes(shape_text: str) -> int:
    """Bytes of one HLO shape literal (``f32[2,8,64]{...}``); tuple
    shapes sum their elements."""
    total = 0
    for m in _SHAPE_RE.finditer(shape_text):
        dtype, dims = m.group(1), m.group(2)
        if dtype not in DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * DTYPE_BYTES[dtype]
    return total


# ------------------------------------------------------- replica groups

def decode_replica_groups(text: str) -> list[list[int]] | None:
    """Replica groups of one collective op line, both HLO spellings:

    * explicit: ``replica_groups={{0,2},{1,3}}``
    * iota v2:  ``replica_groups=[2,4]<=[4,2]T(1,0)`` — arange over the
      tile dims, transposed by the permutation, reshaped to the group
      dims (this is XLA's compact form for the mesh-regular groups GSPMD
      emits).
    """
    m = re.search(r"replica_groups=\{\{([\d,{} ]*)\}\}", text)
    if m:
        return [
            [int(x) for x in grp.split(",") if x.strip()]
            for grp in m.group(1).split("},{")
        ]
    m = re.search(
        r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?",
        text,
    )
    if m:
        group_dims = [int(x) for x in m.group(1).split(",")]
        tile_dims = [int(x) for x in m.group(2).split(",")]
        ids = np.arange(int(np.prod(tile_dims))).reshape(tile_dims)
        if m.group(3):
            perm = [int(x) for x in m.group(3).split(",")]
            ids = ids.transpose(perm)
        return [list(row) for row in ids.reshape(group_dims)]
    return None


def decode_source_target_pairs(text: str) -> list[tuple[int, int]] | None:
    m = re.search(r"source_target_pairs=\{([\d,{} ]*)\}", text)
    if not m:
        return None
    return [
        tuple(int(x) for x in pair.split(","))
        for pair in m.group(1).strip("{}").split("},{")
        if pair
    ]


def mesh_axis_groups(mesh) -> dict[tuple[str, ...], frozenset]:
    """Canonical device-id groups for every populated mesh-axis combo:
    ``{("data",): {{ids varying only along data}, …}, ("data","model"):
    …}`` — the lookup table replica groups are attributed against."""
    import itertools

    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    axis_names = list(mesh.axis_names)
    populated = [
        (i, name) for i, name in enumerate(axis_names)
        if ids.shape[i] > 1
    ]
    table: dict[tuple[str, ...], frozenset] = {}
    for r in range(1, len(populated) + 1):
        for combo in itertools.combinations(populated, r):
            axes = tuple(name for _, name in combo)
            dims = [i for i, _ in combo]
            other = [i for i in range(ids.ndim) if i not in dims]
            moved = np.transpose(ids, other + dims)
            flat = moved.reshape(-1, int(np.prod(
                [ids.shape[i] for i in dims], dtype=int)))
            table[axes] = frozenset(
                frozenset(int(x) for x in row) for row in flat
            )
    return table


def attribute_groups(groups, table) -> tuple[str, ...] | None:
    """The mesh-axis combo whose canonical groups exactly match
    ``groups`` (None = unattributable — an irregular grouping)."""
    got = frozenset(frozenset(g) for g in groups)
    for axes, canonical in table.items():
        if canonical == got:
            return axes
    return None


def attribute_pairs(pairs, table) -> tuple[str, ...] | None:
    """Smallest axis combo whose groups contain every (src, tgt) pair —
    collective-permute has no groups, only a neighbor relation."""
    best = None
    for axes, canonical in sorted(
        table.items(), key=lambda kv: sum(len(g) for g in kv[1])
    ):
        ok = all(
            any(s in g and t in g for g in canonical) for s, t in pairs
        )
        if ok:
            best = axes
            break
    return best


# ---------------------------------------------------- collective census

def collective_census(compiled_text: str, mesh) -> list[dict]:
    """Every collective op in the compiled HLO: kind, output bytes,
    attributed mesh axes, and the op_name scope (for the metric-op
    exemption). One dict per op instance."""
    table = mesh_axis_groups(mesh)
    out = []
    for line in compiled_text.splitlines():
        m = re.search(
            r"=\s+(\([^)]*\)|\S+)\s+(" + "|".join(COLLECTIVE_OPS)
            + r")(?:-start|-done)?\(",
            line,
        )
        if not m:
            continue
        kind = m.group(2)
        if "-done(" in line:
            continue  # the -start counted the op; -done is its completion
        shape = m.group(1)
        scope_m = re.search(r'op_name="([^"]*)"', line)
        src_m = re.search(r'source_file="([^"]*)"', line)
        axes = None
        pairs = decode_source_target_pairs(line)
        if pairs is not None:
            axes = attribute_pairs(pairs, table)
        else:
            groups = decode_replica_groups(line)
            if groups is not None:
                axes = attribute_groups(groups, table)
        out.append({
            "kind": kind,
            "bytes": shape_bytes(shape),
            "axes": axes,
            "scope": scope_m.group(1) if scope_m else "",
            "source_file": src_m.group(1) if src_m else "",
        })
    return out


# --------------------------------------------------------- alias parsing

def alias_map(compiled_text: str) -> dict[int, int] | None:
    """``{flat_output_index: flat_parameter_index}`` from the ENTRY
    computation's ``input_output_alias`` annotation; None when the
    program declares no aliasing at all."""
    m = re.search(r"input_output_alias=\{([^\n]*)\}", compiled_text)
    if not m:
        return None
    out = {}
    for pm in re.finditer(r"\{(\d*)\}:\s*\((\d+),", m.group(1)):
        out[int(pm.group(1) or 0)] = int(pm.group(2))
    return out


def entry_parameter_count(compiled_text: str) -> int | None:
    """Number of parameters of the ENTRY computation (the guard that the
    flat-arg → HLO-parameter mapping is positional and unpruned)."""
    pos = compiled_text.find("\nENTRY ")
    if pos < 0:
        return None
    body = compiled_text[pos:]
    end = body.find("\n}")
    body = body[: end if end > 0 else len(body)]
    return len(re.findall(r"=\s+\S+\s+parameter\(\d+\)", body))


# ------------------------------------------------------ upcast extraction

def stablehlo_with_locs(lowered) -> str:
    """The lowered StableHLO text WITH the MLIR debug-location table
    (``Lowered.as_text()`` strips it on this jax line)."""
    from jax.interpreters import mlir

    return mlir.module_to_string(
        lowered.compiler_ir("stablehlo"), enable_debug_info=True
    )


def _loc_table(text: str) -> dict[str, str]:
    return {
        m.group(1): m.group(2)
        for m in re.finditer(r"^#loc(\d+) = loc\((.*)\)\s*$", text, re.M)
    }


def resolve_loc(ref: str, table: dict[str, str], depth: int = 12) -> str:
    """Follow one ``#locN`` reference to a readable ``scope @ file:line``
    string (loc defs nest: ``"scope"(#locM)`` chains down to a callsite
    file location)."""
    scope = ""
    filename = ""
    seen = 0
    while ref in table and seen < depth:
        d = table[ref]
        seen += 1
        sm = re.match(r'"([^"]+)"', d)
        if sm and not scope and not sm.group(1).endswith(".py"):
            scope = sm.group(1)
        fm = re.search(r'"([^"]+\.py)":(\d+)', d)
        if fm and not filename:
            filename = f"{fm.group(1)}:{fm.group(2)}"
        nm = re.search(r"#loc(\d+)", d)
        if not nm:
            break
        ref = nm.group(1)
    return " @ ".join(x for x in (scope, filename) if x)


def upcast_census(stablehlo_text: str) -> list[dict]:
    """Every ``stablehlo.convert`` producing f32 from a bf16 operand in
    the lowered program — the trace-time promotions the dtype lint
    audits (compile-time converts XLA inserts for collectives are not
    the program author's doing and are excluded by construction)."""
    table = _loc_table(stablehlo_text)
    out = []
    for m in re.finditer(
        r"stablehlo\.convert\s+%\S+\s*:\s*\(tensor<([^>]*)xbf16>\)\s*->"
        r"\s*tensor<[^>]*xf32>(?:\s+loc\(#loc(\d+)\))?",
        stablehlo_text,
    ):
        dims = [int(d) for d in m.group(1).split("x") if d.isdigit()]
        n = 1
        for d in dims:
            n *= d
        loc = resolve_loc(m.group(2), table) if m.group(2) else ""
        out.append({
            "shape": "x".join(str(d) for d in dims) or "scalar",
            "elements": n,
            "scope": loc,
        })
    return out
