"""The one findings model every analysis pass reports through.

A :class:`Finding` is one diagnosed defect: which pass, how bad, where,
the message (carrying the arithmetic that proves it — counts, bytes,
``dim % axis`` remainders), and a *stable waiver key*. The key is the
contract with ``ANALYSIS_BASELINE.json``: it must survive line-number
drift and re-runs, so passes build it from semantic coordinates (pass id
+ stanza/file + leaf path/knob/op class), never from line numbers or
byte offsets.

Waivers are committed, justified, and dated. A finding whose key appears
in the baseline is *waived* (reported, but does not gate); everything
else is *unwaived* and fails the CLI/tier-1 gate. A waiver whose key no
match produces — a fixed or vanished finding — is *stale* and is itself
a finding (``baseline`` pass): the baseline is regeneration-pinned like
BENCH_INDEX, it cannot silently rot.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field

SCHEMA = 1

SEVERITIES = ("error", "warning")


@dataclass
class Finding:
    """One diagnosed defect from one pass."""

    pass_id: str       # "replication" | "donation" | "collectives" | ...
    severity: str      # "error" | "warning"
    location: str      # "config/resnet18.yaml::<leaf>" or "pkg/file.py:12"
    message: str       # human message WITH the arithmetic
    waiver_key: str    # stable key ANALYSIS_BASELINE.json waives by
    waived: bool = False

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"finding severity {self.severity!r} not in {SEVERITIES}"
            )

    def to_dict(self) -> dict:
        return asdict(self)


def finding_key(pass_id: str, *coords: str) -> str:
    """The canonical waiver key: ``pass::coord::coord…`` from semantic
    coordinates (stanza name, leaf path, knob, op class — never line
    numbers)."""
    return "::".join((pass_id,) + tuple(str(c) for c in coords))


@dataclass
class Report:
    """One analyzer run: findings + per-case ledgers + coverage."""

    findings: list = field(default_factory=list)
    cases: list = field(default_factory=list)      # program-case ledgers
    ast: dict = field(default_factory=dict)        # AST pass coverage
    n_devices: int = 0
    passes_run: list = field(default_factory=list)

    def extend(self, findings) -> None:
        self.findings.extend(findings)

    @property
    def unwaived(self) -> list:
        return [f for f in self.findings if not f.waived]

    @property
    def waived(self) -> list:
        return [f for f in self.findings if f.waived]

    def apply_baseline(self, baseline: dict,
                       check_stale: bool = True) -> None:
        """Mark findings waived per the baseline and append one
        ``baseline``-pass finding per STALE waiver (a key no finding
        produces any more — the fix landed, so the waiver must go).
        ``check_stale=False`` for partial runs (a filtered scope cannot
        judge waivers for passes it did not execute)."""
        waivers = {w["key"]: w for w in baseline.get("waivers", [])}
        produced = set()
        for f in self.findings:
            if f.waiver_key in waivers:
                f.waived = True
                produced.add(f.waiver_key)
        if not check_stale:
            return
        for key, w in waivers.items():
            if key in produced:
                continue
            self.findings.append(Finding(
                pass_id="baseline",
                severity="error",
                location="ANALYSIS_BASELINE.json",
                message=(
                    f"stale waiver {key!r} (justification: "
                    f"{w.get('justification', '?')!r}): no pass produces "
                    "this finding any more — the underlying issue was "
                    "fixed or renamed; remove the waiver (or re-key it) "
                    "so the baseline stays regeneration-exact"
                ),
                waiver_key=finding_key("baseline", "stale", key),
            ))

    def to_dict(self) -> dict:
        sev = {"error": 0, "warning": 0}
        for f in self.unwaived:
            sev[f.severity] += 1
        return {
            "schema": SCHEMA,
            "n_devices": self.n_devices,
            "passes_run": sorted(self.passes_run),
            "n_findings": len(self.findings),
            "n_unwaived": len(self.unwaived),
            "n_waived": len(self.waived),
            "unwaived_by_severity": sev,
            "findings": [f.to_dict() for f in sorted(
                self.findings,
                key=lambda f: (f.waived, f.severity != "error",
                               f.pass_id, f.location),
            )],
            "cases": self.cases,
            "ast": self.ast,
        }


# ------------------------------------------------------------- baseline

def load_baseline(path: str) -> dict:
    """Load + validate ANALYSIS_BASELINE.json. Every waiver must carry
    key + justification + date — an unjustified waiver is refused here,
    not discovered in review."""
    if not os.path.exists(path):
        return {"schema": SCHEMA, "waivers": []}
    with open(path) as f:
        doc = json.load(f)
    seen = set()
    for i, w in enumerate(doc.get("waivers", [])):
        for req in ("key", "justification", "date"):
            if not str(w.get(req, "")).strip():
                raise ValueError(
                    f"{path}: waiver #{i} missing {req!r} — every waiver "
                    "names its key, WHY the finding is load-bearing, and "
                    "the date it was taken"
                )
        if w["key"] in seen:
            raise ValueError(f"{path}: duplicate waiver key {w['key']!r}")
        seen.add(w["key"])
    return doc


def write_report(report: Report, path: str) -> None:
    with open(path, "w") as f:
        json.dump(report.to_dict(), f, indent=1, sort_keys=True)
        f.write("\n")
