"""Dtype-promotion lint: f32 ops fed by bf16 values outside the
known-safe scopes.

With ``DEVICE.COMPUTE_DTYPE=bfloat16`` the model body is meant to run in
bf16 end to end; every bf16→f32 convert in the LOWERED program is a
place where compute silently leaves the fast path (f32 doubles both the
MXU cost and the bytes of everything downstream of it). Some promotions
are *correct by design* and stay: BN/LayerNorm statistics (variance in
bf16 underflows), the loss/log-softmax (accuracy of the reduction),
optimizer counters and LR schedules (integers/fp32 master params), and
the metrics. Those are the safe scopes; anything else is a finding with
the tensor shape (= the cost) and the resolved scope in the message.

The pass reads the lowered StableHLO with debug locations — trace-time
promotions the program author wrote — NOT the compiled HLO, where XLA
legitimately inserts f32 converts for collective numerics and fusion
internals that are nobody's bug.
"""

from __future__ import annotations

import re

from distribuuuu_tpu.analysis import hlo
from distribuuuu_tpu.analysis.findings import Finding, finding_key

PASS_ID = "dtype"

# scope/source patterns that are correct-by-design promotions
SAFE_SCOPES = (
    r"BatchNorm",          # BN batch statistics (variance underflows bf16)
    r"LayerNorm|RMSNorm",  # LN/RMS statistics, same argument
    r"GroupNorm",
    r"utils/metrics\.py",  # loss + accuracy (log-softmax reduction)
    r"cross_entropy|log_softmax|softmax|logsumexp|top_k",
    r"optimizer_update",   # fp32 master params / counters
    r"utils/optim\.py|utils/schedules\.py|optax",
    r"resilience/supervisor\.py",  # non-finite guard reads the f32 loss
    r"normalize_in_graph|transforms\.py",  # device-side normalization
    r"moe\.py|router",     # MoE router runs its softmax in f32 by design
    # the self-declaration convention: a DELIBERATE f32 region wraps
    # itself in jax.named_scope("<name>_fp32") at the promotion site
    # (attn_softmax_fp32, se_squeeze_fp32, …) — the code states the
    # numerical argument where it lives, and the lint reads it
    r"_fp32\b",
    # model head helpers (ViT._head): GAP-mean's internal f32
    # accumulation + the documented f32 head/loss boundary
    r"\._head\b",
)


# the fwd head/loss boundary: every zoo model upcasts its pooled
# features and runs the classifier head + loss in f32 by design
# (models/layers.head_dtype — "the loss boundary"); the cast sits at
# the model ROOT scope (no submodule between the model class and the
# convert), in the forward and in its autodiff transpose
_HEAD_BOUNDARY = re.compile(
    r"(?:jvp\(fwd\)|fwd|eval_fwd|transpose\(jvp\(fwd\)\))"
    r"/[A-Za-z_0-9]+/convert_element_type"
)


def _safe(scope: str) -> bool:
    return any(re.search(pat, scope) for pat in SAFE_SCOPES)


def run(bundle) -> list:
    import jax

    if bundle.geometry.get("compute_dtype") != "bfloat16":
        return []  # nothing to audit: the program computes in f32
    findings = []
    census = hlo.upcast_census(bundle.lowered_text)
    # fp32 master params: the transpose of each param's compute-dtype
    # downcast materializes that param's GRADIENT in f32 — mandatory for
    # the f32 optimizer state, recognized by shape (a transpose-scope
    # upcast at exactly a param shape is the grad cast, not a leak)
    param_shapes = {
        tuple(int(d) for d in leaf.shape)
        for leaf in jax.tree.leaves(bundle.state_in.params)
    }
    bundle.extras["upcasts"] = {
        "total": len(census),
        "unsafe": 0,
    }
    # aggregate per scope so one miswritten module line is one finding,
    # not one per block instance
    unsafe: dict = {}
    for up in census:
        if _safe(up["scope"]):
            continue
        dims = tuple(
            int(d) for d in up["shape"].split("x") if d.isdigit()
        )
        if "transpose(" in up["scope"] and dims in param_shapes:
            continue  # master-param grad cast (see above)
        if _HEAD_BOUNDARY.search(up["scope"]):
            continue  # the f32 head/loss boundary
        key = up["scope"] or f"<unattributed {up['shape']}>"
        slot = unsafe.setdefault(key, {"count": 0, "elements": 0,
                                       "shape": up["shape"]})
        slot["count"] += 1
        slot["elements"] += up["elements"]
    bundle.extras["upcasts"]["unsafe"] = sum(
        s["count"] for s in unsafe.values()
    )
    for scope, slot in sorted(unsafe.items()):
        skey = re.sub(r"[:/ ]+", ".", scope)[:120] or "unattributed"
        findings.append(Finding(
            pass_id=PASS_ID, severity="warning",
            location=f"{bundle.name}::{scope[:140]}",
            message=(
                f"{slot['count']} bf16→f32 upcast(s) "
                f"({slot['elements']} elements, e.g. shape "
                f"{slot['shape']}) outside the known-safe scopes at "
                f"{scope or '<unattributed>'} — compute leaves the bf16 "
                "path here; cast back or add the scope to SAFE_SCOPES "
                "with the numerical argument"
            ),
            waiver_key=finding_key(PASS_ID, bundle.name, skey),
        ))
    return findings
