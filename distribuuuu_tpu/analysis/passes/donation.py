"""Donation lint: threaded state the compiled executable does not alias.

The train step donates its state (``donate_argnums=0``) and threads
every state leaf input → output, so XLA should alias each one — the
update then runs in place and the state exists in HBM ONCE. Donation is
silently droppable (a sharding mismatch between the rest layouts, a
layout change XLA refuses to alias across, a new un-donated wrapper),
and when it drops, the step's footprint grows by the full size of every
un-aliased leaf: params + optimizer state live twice. That number is
exactly what this pass reports, cross-checked against
``memory_analysis()``'s argument/alias byte counts.

Mechanics: the ``input_output_alias`` annotation on the compiled ENTRY
computation maps flat output indices to flat parameter indices. The
mapping is positional over the flattened ``(state, batch)`` /
``(state', metrics)`` trees; the pass guards that assumption against
parameter pruning via the ENTRY parameter count and degrades to an
aggregate finding when the guard fails (never a silently wrong per-leaf
attribution).
"""

from __future__ import annotations

import jax
import numpy as np

from distribuuuu_tpu.analysis import hlo
from distribuuuu_tpu.analysis.findings import Finding, finding_key
from distribuuuu_tpu.parallel.partition import specs

PASS_ID = "donation"


def leaf_nbytes(leaf) -> int:
    """Bytes of one abstract leaf (PRNG key dtypes count their base)."""
    try:
        itemsize = np.dtype(leaf.dtype).itemsize
    except TypeError:
        itemsize = 4  # extended dtype (PRNG key): uint32 base
    n = 1
    for d in leaf.shape:
        n *= int(d)
    return n * itemsize


def run(bundle) -> list:
    findings = []
    aliases = hlo.alias_map(bundle.compiled_text)
    state_flat = jax.tree_util.tree_flatten_with_path(bundle.state_in)[0]
    n_state = len(state_flat)
    total_state_bytes = sum(leaf_nbytes(l) for _, l in state_flat)
    mem_note = ""
    if bundle.memory:
        mem_note = (
            f" memory_analysis: arguments {bundle.memory['argument_bytes']}"
            f" B, aliased {bundle.memory['alias_bytes']} B."
        )

    if aliases is None:
        findings.append(Finding(
            pass_id=PASS_ID, severity="error", location=bundle.name,
            message=(
                f"the compiled train step declares NO input/output "
                f"aliasing at all — all {n_state} donatable state leaves "
                f"({total_state_bytes} B) are kept live across the "
                f"update: doubled footprint.{mem_note}"
            ),
            waiver_key=finding_key(PASS_ID, bundle.name, "no-aliasing"),
        ))
        return findings

    n_params = hlo.entry_parameter_count(bundle.compiled_text)
    if n_params is not None and n_params != bundle.n_flat_inputs:
        # parameter pruning broke positional mapping — aggregate check
        if len(aliases) < n_state:
            findings.append(Finding(
                pass_id=PASS_ID, severity="warning",
                location=bundle.name,
                message=(
                    f"compiled entry has {n_params} parameters for "
                    f"{bundle.n_flat_inputs} flat inputs (pruned) and "
                    f"only {len(aliases)}/{n_state} aliases — per-leaf "
                    "attribution unavailable; some donated state is "
                    "unaliased"
                ),
                waiver_key=finding_key(PASS_ID, bundle.name, "pruned"),
            ))
        return findings

    aliased_params = set(aliases.values())
    undonated = [
        (specs.leaf_path(path), leaf_nbytes(leaf))
        for i, (path, leaf) in enumerate(state_flat)
        if i not in aliased_params
    ]
    if undonated:
        bytes_lost = sum(b for _, b in undonated)
        worst = sorted(undonated, key=lambda x: -x[1])[:5]
        findings.append(Finding(
            pass_id=PASS_ID, severity="error",
            location=bundle.name,
            message=(
                f"{len(undonated)}/{n_state} donatable state leaves are "
                f"NOT aliased by the compiled executable — "
                f"{bytes_lost} B of state held twice across the update "
                f"(largest: "
                + ", ".join(f"{p} {b} B" for p, b in worst)
                + f").{mem_note}"
            ),
            waiver_key=finding_key(PASS_ID, bundle.name, "unaliased"),
        ))
    return findings
