"""Config-knob drift lint, both directions.

Forward: every ``cfg.X.Y`` *read* anywhere in the package / tools /
entry scripts must be DECLARED in ``config.py`` — an undeclared read is
a typo that AttributeErrors at runtime (or worse, a knob someone forgot
to add defaults for). Backward: every declared leaf knob must be read
somewhere (a dead knob is config surface that silently does nothing —
users set it and nothing changes) and must be mentioned in the
README/RUNBOOK corpus (an undocumented knob is invisible; the RUNBOOK
knob index exists so this direction stays cheap to satisfy). Doc
mentions are checked in reverse too: a dotted ``SECTION.KNOB`` token in
the docs whose section exists but whose leaf does not is a stale doc.

Resolution is deliberately conservative where static analysis cannot
see: a read of a bare section object (``cfg.MODEL.MOE`` passed as an
argument) marks the whole section *escaped* — its children are
reachable through the alias, so they are never reported dead. Dynamic
subscripts (``cfg.MESH[key]``) mark the section dynamically-read with
the same effect. Sound over noisy: this pass must never cry wolf on a
knob that IS read.
"""

from __future__ import annotations

import ast
import glob
import os
import re

from distribuuuu_tpu.analysis.findings import Finding, finding_key

PASS_ID = "knobs"

# files whose cfg reads count as "the program" (tests deliberately
# excluded: a knob only a test reads is still dead in production)
READ_GLOBS = (
    "distribuuuu_tpu/**/*.py",
    "tools/*.py",
    "train_net.py",
    "test_net.py",
    "serve_net.py",
    "bench.py",
    "__graft_entry__.py",
)

DOC_FILES = ("README.md", "docs/RUNBOOK.md", "docs/DESIGN.md",
             "docs/PARALLELISM.md")


# ------------------------------------------------------------ declared

def declared_knobs(config_path: str) -> tuple[set, set]:
    """(leaves, sections) of the config tree, from config.py's
    ``_C.<chain> = value`` assignments (a CfgNode() value declares a
    section; anything else a leaf knob)."""
    with open(config_path) as f:
        tree = ast.parse(f.read(), filename=config_path)
    leaves, sections = set(), set()

    def chain_of(node):
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name) and node.id == "_C":
            return ".".join(reversed(parts))
        return None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        dotted = None
        t = node.targets[0]
        if isinstance(t, ast.Attribute):
            dotted = chain_of(t)
        if not dotted:
            continue
        is_section = (
            isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Name)
            and node.value.func.id == "CfgNode"
        )
        (sections if is_section else leaves).add(dotted)
    return leaves, sections


# --------------------------------------------------------------- reads

class _ReadCollector(ast.NodeVisitor):
    """cfg.<chain> reads: dotted paths, section escapes, dynamic reads."""

    def __init__(self):
        self.reads: set[str] = set()
        self.dynamic: set[str] = set()   # sections subscripted dynamically

    def _root_chain(self, node):
        """Walk down Attribute/Subscript/.get() spine to the root Name;
        returns the dotted chain above ``cfg`` or None."""
        parts = []
        while True:
            if isinstance(node, ast.Attribute):
                parts.append(("attr", node.attr))
                node = node.value
            elif isinstance(node, ast.Subscript):
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    parts.append(("attr", sl.value))
                else:
                    parts.append(("dyn", None))
                node = node.value
            else:
                break
        if isinstance(node, ast.Name) and node.id in ("cfg", "_C"):
            return list(reversed(parts))
        return None

    def visit_Call(self, call):
        # cfg.SECTION.get("KNOB", default) reads SECTION.KNOB
        f = call.func
        if (
            isinstance(f, ast.Attribute) and f.attr == "get"
            and call.args and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            chain = self._root_chain(f.value)
            if chain is not None:
                self._record(chain + [("attr", call.args[0].value)])
                for a in call.args[1:]:
                    self.visit(a)
                return
        self.generic_visit(call)

    def visit_Attribute(self, node):
        self._maybe(node)

    def visit_Subscript(self, node):
        self._maybe(node)
        # still visit the slice (it may contain cfg reads)
        self.visit(node.slice)

    def _maybe(self, node):
        if not isinstance(getattr(node, "ctx", ast.Load()), ast.Load):
            # a WRITE target: setting a knob is not a read, and its
            # prefix chain is not a section-object read either
            return
        chain = self._root_chain(node)
        if chain is None:
            self.generic_visit(node)
            return
        self._record(chain)

    def _record(self, chain):
        path = []
        for kind, name in chain:
            if kind == "dyn":
                self.dynamic.add(".".join(path))
                return
            path.append(name)
        if path:
            self.reads.add(".".join(path))


def collect_reads(repo: str) -> _ReadCollector:
    col = _ReadCollector()
    for pattern in READ_GLOBS:
        for path in sorted(glob.glob(os.path.join(repo, pattern),
                                     recursive=True)):
            if "__pycache__" in path:
                continue
            # config.py itself participates: its declarations are Store
            # context (never counted), but dump_cfg & co genuinely READ
            # knobs like CFG_DEST/OUT_DIR
            try:
                with open(path) as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
            col.visit(tree)
    return col


# ---------------------------------------------------------------- docs

def doc_corpus(repo: str) -> str:
    texts = []
    for rel in DOC_FILES:
        path = os.path.join(repo, rel)
        if os.path.exists(path):
            with open(path) as f:
                texts.append(f.read())
    return "\n".join(texts)


def doc_mentions(corpus: str) -> set[str]:
    """Every dotted UPPER.CASE token in the docs (knob-shaped)."""
    return set(re.findall(
        r"\b[A-Z][A-Z0-9_]*(?:\.[A-Z][A-Z0-9_]*)+\b", corpus
    ))


# ----------------------------------------------------------- knob index

def knob_index_markdown(config_path: str) -> str:
    """Generate the RUNBOOK 'Config knob index' table from config.py:
    every leaf knob with its default and the first sentence of the
    comment block above its declaration. ``python tools/staticcheck.py
    --knob-index`` prints it; the docs-mention direction of this pass
    keeps it complete (a new knob missing from the index is a finding).
    """
    with open(config_path) as f:
        src = f.read()
    tree = ast.parse(src, filename=config_path)
    lines = src.splitlines()
    rows = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        parts = []
        while isinstance(t, ast.Attribute):
            parts.append(t.attr)
            t = t.value
        if not (isinstance(t, ast.Name) and t.id == "_C"):
            continue
        dotted = ".".join(reversed(parts))
        if (
            isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Name)
            and node.value.func.id == "CfgNode"
        ):
            continue  # sections head their own group implicitly
        try:
            default = repr(ast.literal_eval(node.value))
        except (ValueError, SyntaxError):
            default = "<computed>"
        # the comment block immediately above the assignment
        comment: list[str] = []
        i = node.lineno - 2
        while i >= 0 and lines[i].lstrip().startswith("#"):
            comment.append(lines[i].lstrip().lstrip("#").strip())
            i -= 1
        text = " ".join(reversed(comment))
        first = re.split(r"(?<=[.;])\s", text, maxsplit=1)[0] if text else ""
        if len(first) > 110:
            first = first[:107] + "…"
        rows.append((dotted, default, first))
    rows.sort()
    out = ["| Knob | Default | What it does |", "| --- | --- | --- |"]
    for dotted, default, first in rows:
        if len(default) > 24:
            default = default[:21] + "…"
        out.append(f"| `{dotted}` | `{default}` | {first} |")
    return "\n".join(out)


# ----------------------------------------------------------------- run

# CfgNode's own API surface — attribute reads on a section that are
# method calls, not knob reads
DICT_METHODS = {
    "get", "keys", "values", "items", "clone", "dump", "freeze",
    "defrost", "is_frozen", "merge_from_file", "merge_from_list",
    "merge_from_other_cfg", "to_dict", "update", "pop", "setdefault",
}


def run(repo: str) -> list:
    findings = []
    config_path = os.path.join(repo, "distribuuuu_tpu", "config.py")
    leaves, sections = declared_knobs(config_path)
    col = collect_reads(repo)

    # resolve raw chains: method/attr access on a declared leaf counts
    # as reading the leaf; anything below a declared SECTION that is not
    # declared (and not a dict method) is an undeclared read
    resolved: set[str] = set()
    undeclared: set[str] = set()
    for read in col.reads:
        if read in leaves or read in sections:
            resolved.add(read)
            continue
        parts = read.split(".")
        for i in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:i])
            if prefix in leaves:
                resolved.add(prefix)
                break
            if prefix in sections:
                if parts[i] in DICT_METHODS:
                    resolved.add(prefix)
                else:
                    undeclared.add(".".join(parts[: i + 1]))
                break
        # chains rooted at no declared name (cfg.items() etc.) are
        # CfgNode API reads, not knob reads — ignored

    # sections read as bare objects (aliased away) or dynamically
    escaped = {r for r in resolved if r in sections} | col.dynamic

    # (1) undeclared reads
    for read in sorted(undeclared):
        if any(read == e or read.startswith(e + ".") for e in col.dynamic):
            continue
        findings.append(Finding(
            pass_id=PASS_ID, severity="error",
            location=f"cfg.{read}",
            message=(
                f"cfg.{read} is read but never declared in config.py — "
                "an AttributeError waiting for that code path (declare "
                "the knob with a default and a comment, or fix the typo)"
            ),
            waiver_key=finding_key(PASS_ID, "undeclared", read),
        ))

    # (2) dead declared knobs
    for leaf in sorted(leaves):
        if leaf in resolved:
            continue
        if any(leaf == e or leaf.startswith(e + ".") for e in escaped):
            continue
        findings.append(Finding(
            pass_id=PASS_ID, severity="warning",
            location=f"config.py::{leaf}",
            message=(
                f"declared knob {leaf} is never read by the package, "
                "tools, or entry scripts — dead config surface: users "
                "can set it and nothing changes (remove it, or waive "
                "with the reason it must stay, e.g. reference-YAML "
                "schema compatibility)"
            ),
            waiver_key=finding_key(PASS_ID, "dead", leaf),
        ))

    # (3) docs: every leaf knob mentioned; stale doc mentions
    corpus = doc_corpus(repo)
    mentions = doc_mentions(corpus)
    top_sections = {s.split(".")[0] for s in sections} | {"OUT_DIR"}
    for leaf in sorted(leaves):
        dotted_forms = {leaf}
        if leaf.count(".") >= 2:
            # nested sections also accept the short form (FLEET.REPLICAS)
            dotted_forms.add(".".join(leaf.split(".")[-2:]))
        if "." not in leaf:
            continue  # top-level scalars (OUT_DIR etc.) documented freely
        if dotted_forms & mentions:
            continue
        findings.append(Finding(
            pass_id=PASS_ID, severity="warning",
            location=f"docs::{leaf}",
            message=(
                f"declared knob {leaf} appears nowhere in "
                f"{'/'.join(DOC_FILES)} — add it to the RUNBOOK knob "
                "index (docs/RUNBOOK.md 'Config knob index') so "
                "operators can find it"
            ),
            waiver_key=finding_key(PASS_ID, "undocumented", leaf),
        ))
    for token in sorted(mentions):
        root = token.split(".")[0]
        if root not in top_sections:
            continue
        if token.endswith("_"):
            continue  # docs wildcard convention (FAULTS.STALL_*)
        if token in leaves or token in sections:
            continue
        # accept short nested forms (FLEET.REPLICAS for SERVE.FLEET.…)
        if any(l.endswith("." + token) for l in leaves | sections):
            continue
        findings.append(Finding(
            pass_id=PASS_ID, severity="warning",
            location=f"docs::{token}",
            message=(
                f"docs mention {token} but config.py declares no such "
                "knob — stale documentation (renamed or removed knob)"
            ),
            waiver_key=finding_key(PASS_ID, "stale-doc", token),
        ))
    return findings
