"""Telemetry-kind discipline pass — ``tools/check_telemetry_schema.py``
absorbed into the analysis framework (ISSUE 14 satellite).

Same checks, same message text, new findings plumbing: every emit call
site in the package (``metrics_log`` / ``emit_event`` / ``mirror_event``
/ ``timeline_log`` / ``emit_span``) must use a literal kind that is
declared in ``telemetry/schema.py`` with its required fields statically
present (or splatted), and only the sink modules may forward a dynamic
kind. The old CLI remains as a thin wrapper over :func:`check_file` /
:func:`check_tree`, which keep their historical ``(violations, seen)``
string API — existing invocations and tests work unchanged.
"""

from __future__ import annotations

import ast
import os

from distribuuuu_tpu.analysis.findings import Finding, finding_key

PASS_ID = "telemetry"

# emit surface -> implicit kind (None = first positional arg is the kind)
EMIT_FUNCS = {
    "metrics_log": None,
    "emit_event": None,
    "mirror_event": None,
    "timeline_log": "timeline",
    "emit_span": "span",
}

# modules allowed to forward a caller's kind variable (the sinks themselves)
DYNAMIC_KIND_OK = ("utils/jsonlog.py", "telemetry/spans.py")


def _func_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _finding(where: str, kind_coord: str, message: str) -> Finding:
    return Finding(
        pass_id=PASS_ID, severity="error", location=where,
        message=message,
        waiver_key=finding_key(
            PASS_ID, where.split(":")[0], kind_coord
        ),
    )


def check_file(path: str, rel: str) -> tuple[list, set]:
    """(findings, kinds_seen) for one source file."""
    from distribuuuu_tpu.telemetry import schema

    with open(path) as f:
        tree = ast.parse(f.read(), filename=rel)
    findings, seen = [], set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _func_name(node)
        if name not in EMIT_FUNCS:
            continue
        where = f"{rel}:{node.lineno}"
        kind = EMIT_FUNCS[name]
        if kind is None:
            if not node.args:
                continue  # not an emit form we recognize
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                kind = first.value
            else:
                if not rel.replace(os.sep, "/").endswith(DYNAMIC_KIND_OK):
                    findings.append(_finding(
                        where, f"dynamic-{name}",
                        f"{name}() with a non-literal kind — only "
                        f"the sink modules {DYNAMIC_KIND_OK} may forward "
                        "a dynamic kind",
                    ))
                continue
        seen.add(kind)
        if kind not in schema.KINDS:
            findings.append(_finding(
                where, kind,
                f"undeclared kind {kind!r} — declare it (with "
                "required fields) in distribuuuu_tpu/telemetry/schema.py",
            ))
            continue
        if name in ("timeline_log", "emit_span"):
            continue  # those wrappers provide the required fields
        has_splat = any(kw.arg is None for kw in node.keywords)
        static = {kw.arg for kw in node.keywords if kw.arg is not None}
        missing = schema.KINDS[kind] - static
        if missing and not has_splat:
            findings.append(_finding(
                where, kind,
                f"kind {kind!r} drifted — call no longer provides "
                f"required fields {sorted(missing)} "
                "(telemetry/schema.py declares them)",
            ))
    return findings, seen


def check_tree(root: str) -> tuple[list, set]:
    """(findings, kinds_seen) for a package tree."""
    findings, seen = [], set()
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(root))
            v, s = check_file(path, rel)
            findings += v
            seen |= s
    return findings, seen


def run(repo: str) -> list:
    findings, _seen = check_tree(
        os.path.join(repo, "distribuuuu_tpu")
    )
    return findings
