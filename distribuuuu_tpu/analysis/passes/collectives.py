"""Collective lint: the compiled program's per-mesh-axis collective
census vs what the spec algebra predicts for the declared layout.

The census (``hlo.collective_census``) attributes every all-gather /
all-reduce / reduce-scatter / all-to-all / collective-permute to the
mesh axes its replica groups span; the prediction
(``specs.collective_expectations``) knows which op kinds each layout
legitimately produces and bounds the dangerous one — all-gathers over
the ``data`` axis:

* a gather over ``data`` in a program with NO ZeRO stage means
  something rests sharded that the declaration says is replicated (a
  silent re-gather — the layout and the program disagree);
* more data-gathers than the rest-layout re-gather bound is a gather
  storm (per-use gathering instead of gather-once);
* reduce-scatter / all-to-all / collective-permute over axes no feature
  predicts are redundant collectives.

Metric-scope ops are exempt (the ``top_k`` logits gather in
utils/metrics.py is a handful of KB and semantically a metric, not a
layout leak) — exempt from *findings*, still counted in the ledger.
The full per-axis count/bytes ledger lands in the report's case record
either way: it was the before/after referee the gather-once schedule
(ISSUE 15) was scored by — 195 → ~21 data-gathers on dp8·zero3 — and
the ``gather_bound`` now encodes the gather-once model, so a schedule
regression is a finding, not a waiver.
"""

from __future__ import annotations

from distribuuuu_tpu.analysis import hlo
from distribuuuu_tpu.analysis.findings import Finding, finding_key

PASS_ID = "collectives"

# op scopes that are metrics/loss bookkeeping, not layout traffic
METRIC_SCOPE = ("top_k", "metrics.py", "accuracy", "cross_entropy")


def _is_metric(op: dict) -> bool:
    hay = op["scope"] + " " + op["source_file"]
    return any(tok in hay for tok in METRIC_SCOPE)


def ledger_from_census(census) -> dict:
    """{axes-key: {kind: {count, bytes}}} — the report artifact."""
    out: dict = {}
    for op in census:
        axes = "+".join(op["axes"]) if op["axes"] else "unattributed"
        slot = out.setdefault(axes, {}).setdefault(
            op["kind"], {"count": 0, "bytes": 0, "metric_ops": 0}
        )
        slot["count"] += 1
        slot["bytes"] += op["bytes"]
        if _is_metric(op):
            slot["metric_ops"] += 1
    return out


def run(bundle) -> list:
    findings = []
    census = hlo.collective_census(bundle.compiled_text, bundle.mesh)
    bundle.extras["collective_ledger"] = ledger_from_census(census)
    exp = bundle.expectations
    allowed = exp["allowed"]

    # --- unexpected op kinds over axes the spec algebra predicts none of
    flagged: dict = {}
    for op in census:
        if op["axes"] is None or _is_metric(op):
            continue
        kinds_allowed = allowed.get(op["kind"])
        if kinds_allowed is None:
            continue  # unconstrained kind (all-reduce)
        if set(op["axes"]) <= kinds_allowed:
            continue
        key = (op["kind"], op["axes"])
        slot = flagged.setdefault(key, {"count": 0, "bytes": 0,
                                        "scope": op["scope"]})
        slot["count"] += 1
        slot["bytes"] += op["bytes"]
    for (kind, axes), slot in sorted(flagged.items()):
        axes_s = "+".join(axes)
        findings.append(Finding(
            pass_id=PASS_ID, severity="error",
            location=f"{bundle.name}::{kind}@{axes_s}",
            message=(
                f"{slot['count']} {kind} op(s) over mesh axes {axes_s} "
                f"({slot['bytes']} B) that the declared layout predicts "
                f"ZERO of (zero={bundle.topology.zero}, features="
                f"{sorted(bundle.topology.features())}): something rests "
                "sharded that the declaration says is replicated, or a "
                "redundant collective. First scope: "
                f"{slot['scope'][:120] or '<none>'}"
            ),
            waiver_key=finding_key(PASS_ID, bundle.name, kind, axes_s),
        ))

    # --- ring-attention permute census band over the seq axis
    ring = exp.get("ring")
    if ring:
        seq_permutes = [
            op for op in census
            if op["kind"] == "collective-permute"
            and op["axes"] == (ring["axis"],)
        ]
        n = len(seq_permutes)
        if n < ring["min_permutes"]:
            findings.append(Finding(
                pass_id=PASS_ID, severity="error",
                location=f"{bundle.name}::collective-permute@{ring['axis']}",
                message=(
                    f"missing ring hop: {n} collective-permute op(s) over "
                    f"the {ring['axis']} axis vs >= {ring['min_permutes']} "
                    f"expected ({ring['attn_layers']} seq-sharded attention "
                    "layers, each a ppermute ring over K/V blocks — "
                    "ops/ring_attention.py): an attention layer stopped "
                    "rotating K/V and each seq shard attends only its "
                    "local block — wrong math, not just a slow schedule"
                ),
                waiver_key=finding_key(
                    PASS_ID, bundle.name, "ring-missing", ring["axis"]
                ),
            ))
        elif n > ring["max_permutes"]:
            pbytes = sum(op["bytes"] for op in seq_permutes)
            findings.append(Finding(
                pass_id=PASS_ID, severity="warning",
                location=f"{bundle.name}::collective-permute@{ring['axis']}",
                message=(
                    f"extra ring traffic: {n} collective-permute op(s) over "
                    f"the {ring['axis']} axis ({pbytes} B) vs <= "
                    f"{ring['max_permutes']} expected (= 8 x "
                    f"{ring['attn_layers']} attention layers + 4 slack — "
                    "fwd k/v hops + their autodiff transposes, doubled "
                    "for XLA splitting): something beyond the attention "
                    "rings is bouncing over the seq axis"
                ),
                waiver_key=finding_key(
                    PASS_ID, bundle.name, "ring-extra", ring["axis"]
                ),
            ))

    # --- gather-storm bound over the data axis
    bound = exp["gather_bound"]
    if bound is not None:
        data_gathers = [
            op for op in census
            if op["kind"] == "all-gather" and op["axes"] == ("data",)
            and not _is_metric(op)
        ]
        if len(data_gathers) > bound:
            gbytes = sum(op["bytes"] for op in data_gathers)
            findings.append(Finding(
                pass_id=PASS_ID, severity="warning",
                location=f"{bundle.name}::all-gather@data",
                message=(
                    f"gather storm: {len(data_gathers)} non-metric "
                    f"all-gathers over data ({gbytes} B) vs the "
                    f"rest-layout re-gather bound {bound} "
                    f"(= f(zero={bundle.topology.zero}, "
                    f"{exp['zero_sharded']} sharded leaves)): the "
                    "program gathers more than the declared schedule — "
                    "gather-once hoists every FSDP leaf to ONE entry "
                    "gather (specs.gather_schedule); per-use gathering "
                    "is the ZERO.GATHER_AHEAD >= 0 escape hatch"
                ),
                waiver_key=finding_key(
                    PASS_ID, bundle.name, "gather-storm", "data"
                ),
            ))
    return findings
