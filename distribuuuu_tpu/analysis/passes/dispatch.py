"""Dispatch-discipline lint: device dispatch on threads outside the
sequencer token ring — the PR 11 deadlock class as a static check.

The pinned failure: two host threads dispatching SPMD programs onto one
multi-device mesh can enqueue in different per-device orders; the
collectives cross-wait at the XLA rendezvous and the backend wedges.
The fix (asyncplane/sequencer.py) is that every dispatch from a worker
thread goes through ``sequencer.dispatch`` — one token ring, one global
program order. This pass keeps that invariant: in the async plane and
the trainer, any *thread-entry* function (a function handed to
``threading.Thread(target=…)``, plus same-module functions it calls)
that directly calls ``jax.device_put`` / ``jax.block_until_ready`` /
``jax.jit`` dispatch is a finding, unless the call is lexically inside
a ``sequencer.dispatch(...)`` argument or lives in sequencer.py itself
(whose fences ARE the ring).

Main-thread dispatch sites are deliberately NOT flagged — the ring only
exists to order concurrent streams; the epoch loop's own dispatches
chain by construction. The lint is narrow and precise over the modules
where worker threads live rather than heuristic over the world.
"""

from __future__ import annotations

import ast
import glob
import os

from distribuuuu_tpu.analysis.findings import Finding, finding_key

PASS_ID = "dispatch"

# where worker threads that touch devices live
SCAN_GLOBS = (
    "distribuuuu_tpu/asyncplane/*.py",
    "distribuuuu_tpu/trainer.py",
)
EXEMPT_BASENAMES = ("sequencer.py",)  # the ring itself

# the dispatch surfaces (attribute names on the jax module)
DISPATCH_ATTRS = {"device_put", "block_until_ready"}


def _is_dispatch_call(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in DISPATCH_ATTRS:
        root = f.value
        if isinstance(root, ast.Name) and root.id == "jax":
            return f"jax.{f.attr}"
    return None


def _is_sequencer_dispatch(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute) and f.attr == "dispatch"
        and isinstance(f.value, ast.Name) and f.value.id == "sequencer"
    )


class _ModuleIndex(ast.NodeVisitor):
    """Function defs, thread targets, and call edges of one module."""

    def __init__(self):
        self.defs: dict[str, ast.AST] = {}
        self.thread_targets: set[str] = set()
        self._stack: list[str] = []
        self.calls: dict[str, set[str]] = {}

    def visit_FunctionDef(self, node):
        self.defs[node.name] = node
        self._stack.append(node.name)
        self.calls.setdefault(node.name, set())
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        # threading.Thread(target=X) / Thread(target=self.X)
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                t = kw.value
                if isinstance(t, ast.Name):
                    self.thread_targets.add(t.id)
                elif isinstance(t, ast.Attribute):
                    self.thread_targets.add(t.attr)
        if self._stack:
            callee = None
            if isinstance(f, ast.Name):
                callee = f.id
            elif isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ) and f.value.id == "self":
                callee = f.attr
            if callee:
                self.calls[self._stack[-1]].add(callee)
        self.generic_visit(node)


def _thread_reachable(index: _ModuleIndex) -> set[str]:
    """Thread targets plus same-module functions they call (fixpoint)."""
    reach = set(t for t in index.thread_targets if t in index.defs)
    frontier = list(reach)
    while frontier:
        fn = frontier.pop()
        for callee in index.calls.get(fn, ()):
            if callee in index.defs and callee not in reach:
                reach.add(callee)
                frontier.append(callee)
    return reach


def _violations_in(fn_node, rel: str, fn_name: str) -> list:
    """Dispatch calls inside one thread-reachable function that are not
    wrapped in sequencer.dispatch(...)."""
    # parent map for the lexical sequencer.dispatch ancestry check
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(fn_node):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    out = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        surface = _is_dispatch_call(node)
        if surface is None:
            continue
        cur = node
        wrapped = False
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, ast.Call) and _is_sequencer_dispatch(cur):
                wrapped = True
                break
        if wrapped:
            continue
        out.append(Finding(
            pass_id=PASS_ID, severity="error",
            location=f"{rel}:{node.lineno}",
            message=(
                f"{surface} on the worker-thread path "
                f"({fn_name}(), a threading.Thread target or called "
                "from one) outside the sequencer token ring — two "
                "free-running dispatch streams can invert per-device "
                "program order and deadlock the backend at the XLA "
                "rendezvous (the pinned PR 11 failure); route it "
                "through sequencer.dispatch(...)"
            ),
            waiver_key=finding_key(PASS_ID, rel, fn_name, surface),
        ))
    return out


def run(repo: str) -> list:
    findings = []
    for pattern in SCAN_GLOBS:
        for path in sorted(glob.glob(os.path.join(repo, pattern))):
            base = os.path.basename(path)
            if base in EXEMPT_BASENAMES or "__pycache__" in path:
                continue
            rel = os.path.relpath(path, repo)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=rel)
                except SyntaxError:
                    continue
            index = _ModuleIndex()
            index.visit(tree)
            for fn in sorted(_thread_reachable(index)):
                findings.extend(_violations_in(index.defs[fn], rel, fn))
    return findings
