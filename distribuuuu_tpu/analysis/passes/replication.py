"""Silent-replication lint: a leaf whose DECLARED spec is sharded but
whose COMPILED sharding rests replicated.

This generalizes the PR 12 drift gate (tests/test_mesh_stanzas.py, which
compares a handful of stanzas at runtime) into a static pass over the
whole config registry: the declared layout comes from the SpecTable /
annotations (``specs.state_layout``), the compiled verdict from the
train step's output shardings — the state the program actually leaves
at rest every step. The message carries the uneven-dim arithmetic that
explains the one way this legitimately happens (GSPMD demotes a spec it
cannot satisfy; a prime vocab dim on a model axis was PR 12's instance:
``257 % 2 = 1``).

Declared-replicated leaves that COMPILE sharded are flagged too (the
reverse drift): the declaration is the contract in both directions.
"""

from __future__ import annotations

import jax

from distribuuuu_tpu.analysis.findings import Finding, finding_key
from distribuuuu_tpu.parallel.partition import specs

PASS_ID = "replication"


def _axis_sizes(mesh) -> dict:
    return {k: int(v) for k, v in dict(mesh.shape).items()}


def _arith(shape, declared_spec, axis_sizes) -> str:
    """The per-dim divisibility arithmetic for the message."""
    bits = []
    entries = tuple(declared_spec) if declared_spec is not None else ()
    for dim, entry in enumerate(entries):
        names = (entry,) if isinstance(entry, str) else tuple(entry or ())
        for ax in names:
            size = axis_sizes.get(ax, 1)
            if size > 1 and dim < len(shape):
                rem = shape[dim] % size
                bits.append(
                    f"dim{dim}={shape[dim]} over {ax}({size}): "
                    f"{shape[dim]} % {size} = {rem}"
                    + ("" if rem == 0 else " — UNEVEN, GSPMD demotes")
                )
    return "; ".join(bits) or "no populated axis named"


def run(bundle) -> list:
    findings = []
    axis_sizes = _axis_sizes(bundle.mesh)
    declared_flat = jax.tree_util.tree_flatten_with_path(
        bundle.layout["params"]
    )[0]
    compiled_flat = jax.tree_util.tree_flatten_with_path(
        bundle.state_out_shardings.params
    )[0]
    shape_flat = jax.tree_util.tree_flatten_with_path(
        bundle.state_in.params
    )[0]
    if not (len(declared_flat) == len(compiled_flat) == len(shape_flat)):
        findings.append(Finding(
            pass_id=PASS_ID, severity="error", location=bundle.name,
            message=(
                f"declared/compiled/abstract param trees disagree on leaf "
                f"count ({len(declared_flat)}/{len(compiled_flat)}/"
                f"{len(shape_flat)}) — the pass cannot compare them"
            ),
            waiver_key=finding_key(PASS_ID, bundle.name, "tree-mismatch"),
        ))
        return findings

    for (path, decl), (_, comp), (_, leaf) in zip(
        declared_flat, compiled_flat, shape_flat
    ):
        leaf_path = specs.leaf_path(path)
        d = specs.canonicalize(decl.spec, axis_sizes)
        c = specs.canonicalize(comp.spec, axis_sizes)
        if d == c:
            continue
        shape = tuple(leaf.shape)
        if len(tuple(d)) and not len(tuple(c)):
            msg = (
                f"declared {decl.spec} but the compiled program rests this "
                f"leaf REPLICATED — every data rank holds all "
                f"{shape} elements. Arithmetic: "
                f"{_arith(shape, decl.spec, axis_sizes)}"
            )
        else:
            msg = (
                f"declared {decl.spec} but compiled {comp.spec} — the "
                f"declaration and GSPMD disagree "
                f"({_arith(shape, decl.spec, axis_sizes)})"
            )
        findings.append(Finding(
            pass_id=PASS_ID, severity="error",
            location=f"{bundle.name}::params/{leaf_path}",
            message=msg,
            waiver_key=finding_key(PASS_ID, bundle.name, leaf_path),
        ))
    return findings
