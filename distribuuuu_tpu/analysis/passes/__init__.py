"""Analysis passes. Program passes take a ``ProgramBundle`` and return
findings for one stanza; AST passes take the repo root and return
findings for the source tree. ``PROGRAM_PASSES`` / ``AST_PASSES`` are
the registries the runner and the CLI iterate."""

from distribuuuu_tpu.analysis.passes import (
    collectives,
    dispatch,
    donation,
    dtype,
    knobs,
    replication,
    telemetry,
)

PROGRAM_PASSES = {
    "replication": replication.run,
    "donation": donation.run,
    "collectives": collectives.run,
    "dtype": dtype.run,
}

AST_PASSES = {
    "knobs": knobs.run,
    "dispatch": dispatch.run,
    "telemetry": telemetry.run,
}
