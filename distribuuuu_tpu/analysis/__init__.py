"""The static analysis plane (ISSUE 14).

The repo's worst bugs were all *statically visible* and caught late: a
prime-vocab dim silently degraded to replication (the PR 12 drift gate
caught one instance at runtime), GSPMD mispartitioned the fused-update
custom call against sharded operands (PR 13, pinned by a hand-written
test), and collective programs dispatched out of token order deadlocked
the backend (PR 11). This package makes those bug classes fail CI before
a TPU ever sees them:

* **Program lints** run on the lowered StableHLO / compiled HLO of every
  shipped config stanza (plus the generated mesh-sweep core cases),
  built through the existing partition-layer ``lower()`` bundle against
  abstract, declared-sharding arguments (``Lowered.abstract_args`` — no
  state is materialized, each program compiles exactly once and every
  pass reads that one bundle):
  ``replication`` (declared-sharded leaf rests replicated, with the
  uneven-dim arithmetic), ``donation`` (threaded state the executable
  does not alias, with the doubled-footprint bytes), ``collectives``
  (per-mesh-axis census vs the spec-algebra prediction —
  ``specs.collective_expectations``), ``dtype`` (bf16→f32 upcasts
  outside the known-safe scopes).

* **AST lints** run on the package source: ``knobs`` (every ``cfg.X.Y``
  read declared in config.py and documented, dead declared knobs and
  stale doc mentions both directions), ``dispatch`` (device-dispatch
  calls on threads outside the sequencer token ring — the PR 11
  deadlock class as a lint), ``telemetry`` (the absorbed
  ``tools/check_telemetry_schema.py`` kind/field discipline).

One findings model (``findings.Finding``: pass id, severity, location,
message-with-the-arithmetic, stable waiver key), one committed waiver
file (``ANALYSIS_BASELINE.json`` — justification + date per waiver,
regeneration-pinned like BENCH_INDEX), one CLI
(``tools/staticcheck.py`` / ``distribuuuu-staticcheck``: ``--json-out``,
exit 1 on unwaived findings), and a tier-1 gate at 0 unwaived findings
with every pass proven live by a seeded-violation fixture
(tests/test_staticcheck.py).
"""

from distribuuuu_tpu.analysis.findings import (  # noqa: F401
    Finding,
    Report,
    load_baseline,
)
