"""The analyzer's case generator + orchestration.

Program cases are (a) every shipped model YAML in ``config/`` — the
stanza merged through the REAL config path, exactly as ``train_net.py``
would — and (b) the mesh-sweep CORE cases the topology registry
generates (``tools/mesh_sweep.generate_cases``), i.e. the same matrix
the MULTICHIP dryrun executes, analyzed statically instead. Each case
builds ONE ``ProgramBundle`` (one lower, one compile) and every program
pass reads it.

AST passes run once over the repo tree.

``run_all`` returns a :class:`findings.Report` with the baseline
applied. The CLI (tools/staticcheck.py) and the tier-1 gate
(tests/test_staticcheck.py) both drive this entry.
"""

from __future__ import annotations

import glob
import os
import sys
import traceback

from distribuuuu_tpu.analysis import program
from distribuuuu_tpu.analysis.findings import (
    Finding,
    Report,
    finding_key,
    load_baseline,
)
from distribuuuu_tpu.analysis.passes import AST_PASSES, PROGRAM_PASSES

BASELINE_FILE = "ANALYSIS_BASELINE.json"


def repo_root() -> str:
    """The repo checkout this package lives in (config/ + tools/)."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return here


def model_yaml_cases(repo: str) -> list[dict]:
    """One case per shipped model YAML (non-model YAMLs like
    monitor_rules are skipped the same way the stanza gate skips them)."""
    import yaml

    cases = []
    for path in sorted(glob.glob(os.path.join(repo, "config", "*.yaml"))):
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        if "MODEL" not in doc:
            continue
        cases.append({
            "name": f"config/{os.path.basename(path)}",
            "kind": "yaml",
            "path": path,
        })
    return cases


def sweep_core_cases(repo: str, n_devices: int) -> list[dict]:
    """The generated mesh-sweep core matrix as analysis cases."""
    tools = os.path.join(repo, "tools")
    sys.path.insert(0, tools)
    try:
        import mesh_sweep
    finally:
        sys.path.remove(tools)
    out = []
    for case in mesh_sweep.generate_cases(n_devices):
        if case["tier"] != "core" or case["degenerate_zero"]:
            continue
        out.append({
            "name": f"sweep/{case['name']}",
            "kind": "sweep",
            "arch": case["arch"],
            "stanza": case["stanza"],
        })
    return out


def _merge_case(case: dict) -> None:
    """Reset + merge the live cfg for one case (the same path the
    trainer takes; sweep cases mirror mesh_sweep's generated YAML)."""
    import distribuuuu_tpu.config as config
    from distribuuuu_tpu.config import cfg

    config.reset_cfg()
    if case["kind"] == "yaml":
        cfg.merge_from_file(case["path"])
    else:
        cfg.MODEL.ARCH = case["arch"]
        cfg.MODEL.NUM_CLASSES = 16
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        for key, val in case["stanza"].items():
            cfg.MESH[key] = val


def run_program_case(case: dict, n_devices: int = 8,
                     passes=None) -> tuple[list, dict]:
    """(findings, case_record) for one stanza. A case that fails to
    build is itself a finding (error) — the analyzer never silently
    skips coverage."""
    passes = passes or PROGRAM_PASSES
    findings: list = []
    record = {"name": case["name"], "kind": case["kind"], "ok": False}
    try:
        _merge_case(case)
        bundle = program.build_bundle(case["name"], n_devices=n_devices)
    except Exception as e:  # noqa: BLE001 — coverage loss is a finding
        findings.append(Finding(
            pass_id="build", severity="error", location=case["name"],
            message=(
                f"analysis bundle failed to build: "
                f"{type(e).__name__}: {e} — this stanza is NOT being "
                "analyzed; fix the build or the stanza"
            ),
            waiver_key=finding_key("build", case["name"]),
        ))
        record["error"] = f"{type(e).__name__}: {e}"
        record["traceback"] = traceback.format_exc(limit=4)
        return findings, record
    for pass_id, pass_fn in passes.items():
        try:
            findings.extend(pass_fn(bundle))
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                pass_id=pass_id, severity="error", location=case["name"],
                message=(
                    f"pass crashed: {type(e).__name__}: {e} — the "
                    "program was not checked by this pass"
                ),
                waiver_key=finding_key(pass_id, case["name"], "crash"),
            ))
    record.update({
        "ok": True,
        "arch": bundle.arch,
        "class": bundle.topology.class_name(),
        "zero": bundle.topology.zero,
        "geometry": bundle.geometry,
        "expectations": {
            k: (sorted(v) if isinstance(v, (set, frozenset)) else v)
            for k, v in bundle.expectations.items()
            if k != "allowed"
        },
        "collective_ledger": bundle.extras.get("collective_ledger", {}),
        "upcasts": bundle.extras.get("upcasts", {}),
        "seconds": bundle.seconds,
    })
    return findings, record


def run_ast(repo: str, passes=None) -> tuple[list, dict]:
    passes = passes or AST_PASSES
    findings: list = []
    for pass_id, pass_fn in passes.items():
        try:
            findings.extend(pass_fn(repo))
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                pass_id=pass_id, severity="error", location=repo,
                message=f"AST pass crashed: {type(e).__name__}: {e}",
                waiver_key=finding_key(pass_id, "crash"),
            ))
    return findings, {"root": repo, "passes": sorted(passes)}


def run_all(repo: str | None = None, *, n_devices: int = 8,
            ast_only: bool = False, program_only: bool = False,
            configs: str | None = None, sweep: bool = True,
            baseline_path: str | None = None,
            progress=None) -> Report:
    """The full analyzer. ``configs`` filters program cases by substring
    (CLI --configs); ``progress`` is an optional per-case callback."""
    import distribuuuu_tpu.config as config

    repo = repo or repo_root()
    report = Report(n_devices=n_devices)
    if not program_only:
        findings, ast_cov = run_ast(repo)
        report.extend(findings)
        report.ast = ast_cov
        report.passes_run += sorted(AST_PASSES)
    if not ast_only:
        cases = model_yaml_cases(repo)
        if sweep:
            cases += sweep_core_cases(repo, n_devices)
        if configs:
            cases = [c for c in cases if configs in c["name"]]
        try:
            for case in cases:
                findings, record = run_program_case(case, n_devices)
                report.extend(findings)
                report.cases.append(record)
                if progress:
                    progress(record, findings)
        finally:
            config.reset_cfg()
        report.passes_run += sorted(PROGRAM_PASSES)
    baseline = load_baseline(
        baseline_path or os.path.join(repo, BASELINE_FILE)
    )
    # a partial scope cannot judge staleness of waivers it never ran
    full = not ast_only and not program_only and not configs and sweep
    report.apply_baseline(baseline, check_stale=full)
    return report
