"""Resilience layer: verified checkpoints, elastic resume, failure supervision.

TPU fleets preempt slices, kill hosts mid-save, and resize pods; any run
longer than the fleet MTBF must treat recovery as a first-class path, not
an operator heroic. Three cooperating pieces (ISSUE 3):

  manifest.py    crash-consistent checkpoint verification: every save
                 commits a MANIFEST.json (tree spec + file digests + world
                 topology) atomically AFTER the orbax payload, so a
                 half-written checkpoint is detectable and auto-resume
                 walks back to the newest intact save
                 (utils/checkpoint.find_last_valid_checkpoint) instead of
                 crashing on a truncated payload. The recorded topology
                 also powers elastic cross-topology resume — a dp=N save
                 restored onto a dp=M mesh — by distinguishing
                 "re-shardable" from "incompatible".

  supervisor.py  in-run failure supervision: the in-graph non-finite loss
                 guard behind ``TRAIN.NONFINITE`` (raise / skip-step /
                 rollback-to-last-checkpoint) and the heartbeat watchdog
                 that flags stalled steps (``TRAIN.STALL_TIMEOUT``).

Fault injection lives in ``utils/faults.py`` (the ``FAULTS.*`` config
node); every recovery path here is exercised deterministically by
``tests/test_resilience*.py`` and ``tools/resilience_drill.py``.
"""

from distribuuuu_tpu.resilience import manifest, supervisor  # noqa: F401
