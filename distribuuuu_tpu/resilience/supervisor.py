"""In-run failure supervision: non-finite loss policy + stall watchdog.

Two failure classes the epoch loop previously could not survive:

* **Non-finite loss.** A NaN/Inf loss (bad sample, LR spike, hardware bit
  flip) silently poisons every subsequent step — the run keeps burning
  chips while training garbage. ``TRAIN.NONFINITE`` picks the policy:

    "raise"     fail fast at the next metric flush (the default — honest
                failure beats silent corruption);
    "skip"      the update is discarded IN-GRAPH (``guard_nonfinite``
                selects the pre-step state when the loss is non-finite,
                advancing only the step cursor) and the host logs/counts
                the skipped step — right for rare bad batches;
    "rollback"  the trainer reloads the last intact checkpoint and
                re-runs from there (``TRAIN.MAX_ROLLBACKS`` attempts) —
                right for transient corruption; a deterministic NaN will
                re-trip and surface after the budget is spent.

  The guard itself is compiled into the step (a scalar ``isfinite`` plus
  a select — no host sync, no dispatch stall); detection happens at the
  PRINT_FREQ metric flush the loop already performs, so the async
  dispatch pipeline keeps its depth.

* **Stalled steps.** A wedged collective, a dead remote host, or a hung
  storage layer leaves the loop blocked with no log line ever appearing.
  The ``Heartbeat`` watchdog (``TRAIN.STALL_TIMEOUT`` seconds, 0 = off)
  runs a daemon thread that flags — log line + ``kind="stall"`` metrics
  record — whenever no ``beat()`` lands inside the window. Flag, not
  kill: the operator (or the fleet scheduler's external watchdog) owns
  the restart decision; the log line is what makes the hang diagnosable.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

import jax
import jax.numpy as jnp

from distribuuuu_tpu.telemetry import registry as telemetry_registry
from distribuuuu_tpu.utils.jsonlog import metrics_log
from distribuuuu_tpu.utils.logger import get_logger

NONFINITE_POLICIES = ("raise", "skip", "rollback")


class NonFiniteLossError(RuntimeError):
    """Loss went NaN/Inf and the policy was not 'skip' (or the rollback
    budget ran out). Carries the position for the rollback handler."""

    def __init__(self, epoch: int, batch: int, value: float):
        super().__init__(
            f"non-finite loss ({value}) at epoch {epoch + 1}, batch ~{batch}. "
            "Policy TRAIN.NONFINITE: 'raise' (this), 'skip' (discard the "
            "step in-graph), 'rollback' (reload the last intact checkpoint); "
            "see docs/RUNBOOK.md 'Recovering a wedged run'."
        )
        self.epoch = epoch
        self.batch = batch
        self.value = value


def validate_policy(policy: str) -> str:
    if policy not in NONFINITE_POLICIES:
        raise ValueError(
            f"TRAIN.NONFINITE={policy!r}: must be one of {NONFINITE_POLICIES}"
        )
    return policy


def guard_nonfinite(old_state, new_state, metrics: dict, policy: str):
    """The in-graph half of the non-finite policy (call inside the jitted
    step body, AFTER the optimizer update).

    Always annotates ``metrics["nonfinite"]`` (1.0 when the loss is not
    finite) so the host loop can detect without an extra fetch. Under
    "skip" it additionally selects the PRE-step state leaf-by-leaf — the
    poisoned params/stats/optimizer update is discarded wholesale — while
    the step cursor still advances (so per-step RNG folding moves on and
    a deterministic bad batch is not re-drawn forever).
    """
    bad = jnp.logical_not(jnp.isfinite(metrics["loss"]))
    metrics = dict(metrics)
    metrics["nonfinite"] = bad.astype(jnp.float32)
    if policy != "skip":
        return new_state, metrics

    def _sel(n, o):
        if n is o:  # untouched leaves (e.g. the base PRNG key)
            return n
        try:
            if jnp.issubdtype(n.dtype, jax.dtypes.prng_key):
                return n  # the step never rewrites the base key
        except (AttributeError, TypeError):
            pass
        return jnp.where(bad, o, n)

    reverted = jax.tree.map(_sel, new_state, old_state)
    if hasattr(reverted, "replace") and hasattr(new_state, "step"):
        reverted = reverted.replace(step=new_state.step)
    return reverted, metrics


class NonFiniteMonitor:
    """Host-side half: consumes the fetched ``nonfinite`` flags at flush
    time and applies the policy — count+log for "skip", raise for
    "raise"/"rollback" (the trainer's epoch loop catches the latter)."""

    def __init__(self, policy: str, epoch: int, logger=None):
        self.policy = validate_policy(policy)
        self.epoch = epoch
        self.logger = logger or get_logger()
        self.skipped = 0

    def observe(self, loss: float, nonfinite: float, batch: int) -> bool:
        """True ⇒ this step was skipped in-graph (exclude it from meters)."""
        if not nonfinite:
            return False
        telemetry_registry.get_registry().counter("resilience.nonfinite").inc(1)
        if self.policy == "skip":
            self.skipped += 1
            self.logger.warning(
                "non-finite loss at epoch %d batch ~%d — update skipped "
                "in-graph (TRAIN.NONFINITE=skip; %d skipped so far)",
                self.epoch + 1, batch, self.skipped,
            )
            metrics_log(
                "nonfinite", epoch=self.epoch + 1, batch=batch,
                skipped=self.skipped, policy="skip",
            )
            return True
        metrics_log(
            "nonfinite", epoch=self.epoch + 1, batch=batch,
            policy=self.policy,
        )
        raise NonFiniteLossError(self.epoch, batch, loss)


@contextmanager
def watch_blocking(label: str, timeout: float, logger=None, on_flag=None):
    """Stall coverage for blocking host-side operations OUTSIDE the
    epoch loop, where no ``Heartbeat`` thread is running: the async
    checkpoint committer's join barrier, the cross-host commit barrier
    wait, a preemption drain, a restore, the dispatch sequencer's
    token/fence waits. Same signal contract as the heartbeat — a warning
    line, the ``resilience.stalls`` counter, and a ``kind="stall"``
    record — when the wrapped block exceeds ``timeout`` seconds (the
    operator's first clue that storage, not training, is what hung).
    ``timeout <= 0`` disables (zero overhead: no thread is started).
    Flag, not kill — the block keeps waiting; the restart decision stays
    external.

    ``on_flag(age_s)`` replaces the default emission: callers with their
    own record kind (the sequencer's ``dispatch.wedge``) reuse the
    watcher mechanics but speak their own schema — kinds stay literal at
    their emit sites for the static schema check."""
    timeout = float(timeout)
    if timeout <= 0:
        yield
        return
    logger = logger or get_logger()
    done = threading.Event()
    t0 = time.monotonic()

    def _watch():
        while not done.wait(min(timeout / 4.0, 1.0)):
            age = time.monotonic() - t0
            if age > timeout:
                if on_flag is not None:
                    on_flag(age)
                    return  # one flag per excursion
                logger.warning(
                    "blocked in %s for %.1fs (threshold %.1fs) — hung "
                    "storage or a wedged background commit; see "
                    "docs/RUNBOOK.md 'Async checkpointing and warm "
                    "restarts'", label, age, timeout,
                )
                telemetry_registry.get_registry().counter(
                    "resilience.stalls"
                ).inc(1)
                metrics_log(
                    "stall", age_s=round(age, 3), last=label, count=1
                )
                return  # one flag per excursion; the join itself persists

    watcher = threading.Thread(
        target=_watch, daemon=True, name="dtpu-block-watch"
    )
    watcher.start()
    try:
        yield
    finally:
        done.set()
        watcher.join(timeout=2.0)


class Heartbeat:
    """Stall watchdog: flags when no ``beat()`` arrives within ``timeout``
    seconds. ``timeout <= 0`` disables (no thread is started); ``beat``/
    ``stop`` are then no-ops, so call sites need no gating."""

    def __init__(self, timeout: float, logger=None):
        self.timeout = float(timeout)
        self.logger = logger or get_logger()
        self.stall_count = 0
        self._last = time.monotonic()
        self._label = "start"
        self._flagged_at = 0.0  # last beat time we already flagged for
        self._stop = threading.Event()
        self._thread = None
        if self.timeout > 0:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dtpu-heartbeat"
            )
            self._thread.start()

    def beat(self, label: str = "") -> None:
        self._last = time.monotonic()
        if label:
            self._label = label

    def _run(self) -> None:
        poll = max(min(self.timeout / 4.0, 1.0), 0.01)
        while not self._stop.wait(poll):
            last = self._last
            age = time.monotonic() - last
            if age > self.timeout and last != self._flagged_at:
                self._flagged_at = last
                self.stall_count += 1
                self.logger.warning(
                    "heartbeat: no step progress for %.1fs (last: %s; "
                    "TRAIN.STALL_TIMEOUT=%.1fs) — a wedged collective, dead "
                    "peer host, or hung storage; see docs/RUNBOOK.md "
                    "'Recovering a wedged run'",
                    age, self._label, self.timeout,
                )
                telemetry_registry.get_registry().counter(
                    "resilience.stalls"
                ).inc(1)
                metrics_log(
                    "stall", age_s=round(age, 3), last=self._label,
                    count=self.stall_count,
                )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
