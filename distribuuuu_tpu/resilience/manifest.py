"""Checkpoint manifests: crash consistency + topology for elastic resume.

Orbax's directory write is not atomic from the trainer's point of view: a
host dying mid-save leaves a ``ckpt_ep_*`` directory that LOOKS newest to
a lexicographic scan but cannot be restored — before this layer such a
dir was selected on the next start and killed the run inside tensorstore.
The fix is the classic commit-marker protocol: after the collective orbax
save returns on every process, the primary writes ``MANIFEST.json``
(tmp-file + ``os.replace``, atomic on POSIX) recording

  * the per-leaf tree spec (key path → shape/dtype) of the payload,
  * a size + sha256 digest of every file in the checkpoint directory,
  * the saving run's world topology (process/device counts, resolved
    mesh axis sizes, ZeRO stage) and an arch-identity fingerprint.

No manifest ⇒ the save never completed ⇒ the checkpoint is invalid.
Manifest present but any file missing/resized/redigested ⇒ corrupt.
``utils/checkpoint.find_last_valid_checkpoint`` uses ``verify_checkpoint``
to walk back to the newest intact save, quarantining broken dirs to
``*.corrupt``.

The topology record is what makes resume ELASTIC rather than exact-mesh:
``classify_topology`` compares the saved world against the live one and
answers "exact" (same mesh), "reshardable" (same model identity, different
mesh/process layout — restore proceeds, arrays are re-placed onto the live
layout by ``trainer._place_like`` / ``pack_opt_state`` reassembly), or
"incompatible" (different param tree — refuse with the reason, instead of
a cryptic shape error deep in device_put).
"""

from __future__ import annotations

import hashlib
import json
import os

import jax
import numpy as np

from distribuuuu_tpu.config import cfg

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_SCHEMA = 1


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    """Streaming file digest — shared by checkpoint manifests and the
    shard-dataset manifests (data/shards/format.py)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


_sha256_file = sha256_file  # internal call sites / tests predate the alias


def config_fingerprint() -> str:
    """Arch-identity digest: the config keys that determine the PARAM tree.

    Deliberately narrow — optimizer choice is excluded (an optimizer
    mismatch already degrades gracefully to weights-only restore), as are
    run knobs like WEIGHTS/PRETRAINED/OUT_DIR that don't shape the state."""
    ident = {
        "arch": cfg.MODEL.ARCH,
        "num_classes": cfg.MODEL.NUM_CLASSES,
        "moe": cfg.MODEL.MOE.to_dict(),
    }
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True, default=str).encode()
    ).hexdigest()


def _leaf_dtype(leaf) -> str:
    # lazy fallback only: np.asarray on a cross-host-sharded jax.Array
    # raises, and getattr's default argument would evaluate it EAGERLY
    dt = getattr(leaf, "dtype", None)
    return str(dt) if dt is not None else str(np.asarray(leaf).dtype)


def tree_spec(tree) -> dict:
    """Flattened leaf spec: jax key path → {"shape", "dtype"}. Works on
    host numpy and device arrays alike (only metadata is read — safe for
    multi-host arrays this process only partially addresses)."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {
        jax.tree_util.keystr(path): {
            "shape": list(np.shape(leaf)),
            "dtype": _leaf_dtype(leaf),
        }
        for path, leaf in leaves
    }


def _mesh_axes_of(tree) -> dict:
    """Resolved mesh axis sizes from the first device-array leaf (the
    topology the save actually ran on — cfg.MESH may hold -1 wildcards)."""
    for leaf in jax.tree.leaves(tree):
        sh = getattr(leaf, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and hasattr(mesh, "shape"):
            return {k: int(v) for k, v in dict(mesh.shape).items()}
    return {}


def _partition_record() -> dict | None:
    """The partition-layer layout record (Topology.describe): resolved
    axes, ZeRO stage, feature set, class name. Best-effort — a stanza
    that no longer validates (config drifted after the save) must not
    take the SAVE path down; classification handles absence."""
    try:
        from distribuuuu_tpu.parallel.partition import topology as topo_lib

        return topo_lib.from_cfg(cfg).describe()
    except Exception:
        return None


def world_topology(payload=None) -> dict:
    return {
        "processes": jax.process_count(),
        "devices": jax.device_count(),
        "mesh": _mesh_axes_of(payload) if payload is not None else {},
        "zero": int(cfg.MESH.ZERO),
        # r11: the partition-layer layout classification rides along so
        # elastic resume reports WHICH axes/stage moved, not just that
        # the world changed (parallel/partition/topology.py)
        "partition": _partition_record(),
    }


def manifest_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, MANIFEST_NAME)


def write_manifest(ckpt_dir: str, payload, kind: str = "full",
                   epoch: int | None = None,
                   fsync_payload: bool = False,
                   tree: dict | None = None,
                   topology: dict | None = None,
                   sharded: dict | None = None) -> str:
    """Commit marker for a completed save. Call AFTER the orbax write has
    returned on every process, from the primary only (a plain filesystem
    op, like ``prune_preempts``). Atomic: tmp file + ``os.replace``.

    ``fsync_payload`` (the async committer sets it — utils/checkpoint.py
    ``CHECKPOINT.ASYNC``) fsyncs every payload file and its directory
    BEFORE the manifest commits, so the commit-marker ordering holds
    through a power loss, not just a process death: a durable manifest
    can then never describe payload bytes the kernel still held. Off the
    critical path the fsync pass is free to the trainer; the synchronous
    protocol keeps the classic ordering (process-death-safe) by default.

    ``tree``/``topology`` override the live-payload reads for saves whose
    committer thread holds no full payload (the sharded multi-host
    protocol computes both eagerly on-path and passes them in; ``payload``
    may then be None). ``sharded`` records the shard layout summary
    (hosts + shard file names) so the manifest itself names the recorded
    sharding."""
    files = {}
    dirs = set()
    for dirpath, _, names in os.walk(ckpt_dir):
        for name in sorted(names):
            if name in (MANIFEST_NAME, MANIFEST_NAME + ".tmp"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, ckpt_dir)
            if fsync_payload:
                with open(full, "rb") as pf:
                    os.fsync(pf.fileno())
                dirs.add(dirpath)
            files[rel] = {
                "size": os.path.getsize(full),
                "sha256": _sha256_file(full),
            }
    for d in sorted(dirs):  # directory entries durable before the marker
        fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    man = {
        "schema": MANIFEST_SCHEMA,
        "kind": kind,
        "epoch": None if epoch is None else int(epoch),
        "fingerprint": config_fingerprint(),
        "topology": world_topology(payload) if topology is None
        else topology,
        "tree": tree_spec(payload) if tree is None else tree,
        "files": files,
    }
    if sharded is not None:
        man["sharded"] = sharded
    dest = manifest_path(ckpt_dir)
    tmp = dest + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dest)
    return dest


def read_manifest(ckpt_dir: str) -> dict | None:
    """The committed manifest, or None (pre-manifest / partial save)."""
    try:
        with open(manifest_path(ckpt_dir)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def verify_checkpoint(ckpt_dir: str) -> tuple[bool, str]:
    """Crash-consistency check: ``(ok, reason)``.

    A directory without a readable manifest is INVALID by definition under
    the commit protocol — the manifest is written last, so its absence
    means the save never completed (or predates the protocol; re-save or
    resume from an older intact checkpoint)."""
    if not os.path.isdir(ckpt_dir):
        return False, "not a directory"
    man = read_manifest(ckpt_dir)
    if man is None:
        return False, (
            "no committed manifest (save interrupted before commit, or a "
            "pre-manifest checkpoint)"
        )
    for rel, meta in man.get("files", {}).items():
        full = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(full):
            return False, f"payload file missing: {rel}"
        size = os.path.getsize(full)
        if size != meta["size"]:
            return False, (
                f"payload file truncated/resized: {rel} "
                f"({size} bytes, manifest says {meta['size']})"
            )
        if _sha256_file(full) != meta["sha256"]:
            return False, f"payload file digest mismatch: {rel}"
    return True, "ok"


def classify_topology(man: dict, live_spec: dict | None = None) -> tuple[str, str]:
    """Elastic-resume compatibility of a manifest against the LIVE world.

    Returns ``(kind, detail)`` with kind one of:
      "exact"        same mesh/process topology — plain resume;
      "reshardable"  same model identity, different world — restore
                     proceeds, every array is re-placed onto the live
                     layout (dp=N → dp=M, ZeRO shards reassembled);
      "incompatible" the saved param tree cannot feed this model —
                     refuse loudly with the first mismatch.

    ``live_spec`` (a ``tree_spec`` of the live params/batch_stats) enables
    the per-leaf shape check; without it only the fingerprint is compared.
    Optimizer-state leaves are deliberately NOT compared — an optimizer
    mismatch falls back to weights-only restore (utils/checkpoint.py).
    """
    if man.get("fingerprint") != config_fingerprint():
        return "incompatible", (
            "arch identity changed since the save (MODEL.ARCH / NUM_CLASSES "
            "/ MOE differ from the checkpoint's fingerprint)"
        )
    if live_spec is not None:
        saved = man.get("tree", {})
        for key, spec in live_spec.items():
            got = saved.get(key)
            if got is None:
                return "incompatible", f"checkpoint lacks leaf {key}"
            if list(got["shape"]) != list(spec["shape"]):
                return "incompatible", (
                    f"leaf {key} shape {got['shape']} != live {spec['shape']}"
                )
    saved_topo = man.get("topology", {})
    live_topo = world_topology()
    diffs = [
        f"{k} {saved_topo.get(k)}→{live_topo.get(k)}"
        for k in ("processes", "devices", "zero")
        if saved_topo.get(k) != live_topo.get(k)
    ]
    # partition-layer classification (r11): axis-by-axis layout
    # transition detail — every transition is reshardable (arrays
    # re-place leaf by leaf; ZeRO shards reassemble through canonical
    # leaf order), the classification's value is naming what moved
    if saved_topo.get("partition") and live_topo.get("partition"):
        from distribuuuu_tpu.parallel.partition import topology as topo_lib

        pkind, pdetail = topo_lib.classify_transition(
            saved_topo.get("partition"), live_topo.get("partition")
        )
        if pkind != "exact":
            diffs.append(pdetail)
    return ("reshardable", "; ".join(diffs)) if diffs else ("exact", "")


def classify_against_live(man: dict, live_state_tree, live_mesh=None) -> tuple[str, str]:
    """``classify_topology`` with the live side fully resolved: per-leaf
    shapes from ``live_state_tree`` (params + batch_stats only) and the
    live mesh axis sizes for the reshard detail message."""
    live_spec = tree_spec(
        {k: live_state_tree[k] for k in ("params", "batch_stats")
         if k in live_state_tree}
    )
    kind, detail = classify_topology(man, live_spec)
    if kind != "incompatible":
        saved_mesh = (man.get("topology") or {}).get("mesh") or {}
        live_axes = (
            {k: int(v) for k, v in dict(live_mesh.shape).items()}
            if live_mesh is not None
            else {}
        )
        if saved_mesh and live_axes and saved_mesh != live_axes:
            mesh_diff = ", ".join(
                f"{ax} {saved_mesh.get(ax)}→{live_axes.get(ax)}"
                for ax in sorted(set(saved_mesh) | set(live_axes))
                if saved_mesh.get(ax) != live_axes.get(ax)
            )
            detail = "; ".join(x for x in (detail, f"mesh {mesh_diff}") if x)
            kind = "reshardable"
    return kind, detail
