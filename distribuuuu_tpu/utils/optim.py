"""Optimizer construction (ref: /root/reference/distribuuuu/utils.py:187-196).

The reference builds torch SGD with momentum/dampening/nesterov and L2 weight
decay applied to **all** params including BN (utils.py:187-196,
config.py:43-56). The optax chain below reproduces torch-SGD update order
exactly: decay is added to the gradient *before* the momentum buffer update.

LR is epoch-granular (set once per epoch, ref: trainer.py:25-26), so the
learning rate rides through ``optax.inject_hyperparams`` and the trainer
mutates it between epochs without rebuilding state — jit sees it as a traced
scalar, so no recompilation.
"""

from __future__ import annotations

import os

import optax

from distribuuuu_tpu.config import cfg


def _momentum_dtype():
    """``OPTIM.MOMENTUM_DTYPE``: accumulator dtype for the SGD momentum
    buffer. ``float32`` (default) matches torch bit-for-bit; ``bfloat16``
    keeps fp32 master params but halves the momentum buffer's HBM
    footprint and read+write traffic (~200 MB/step on ResNet-50) — a
    mixed-precision-optimizer configuration the reference cannot express.
    ``DISTRIBUUUU_MOMENTUM_DTYPE`` overrides at trace time (ab_bench
    knob)."""
    mode = os.environ.get(
        "DISTRIBUUUU_MOMENTUM_DTYPE", cfg.OPTIM.MOMENTUM_DTYPE
    )
    if mode not in ("float32", "bfloat16"):
        raise ValueError(f"OPTIM.MOMENTUM_DTYPE={mode!r}")
    if mode == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return None  # optax default: momentum inherits the param dtype (fp32)


def construct_optimizer() -> optax.GradientTransformation:
    """Build the configured optimizer (``OPTIM.OPTIMIZER``).

    ``sgd`` (default, the reference's only choice): momentum + nesterov +
    uniform L2 decay, torch-ordered. ``adamw``: decoupled weight decay —
    the usual recipe for the ViT extension archs.
    """
    kind = cfg.OPTIM.OPTIMIZER
    if kind not in ("sgd", "adamw"):
        raise ValueError(
            f"OPTIM.OPTIMIZER must be 'sgd' or 'adamw'; got {kind!r}"
        )
    mom_dtype = _momentum_dtype()

    @optax.inject_hyperparams
    def _make(learning_rate):
        if kind == "sgd":
            return optax.chain(
                optax.add_decayed_weights(cfg.OPTIM.WEIGHT_DECAY),
                optax.sgd(
                    learning_rate=learning_rate,
                    momentum=cfg.OPTIM.MOMENTUM or None,
                    nesterov=cfg.OPTIM.NESTEROV,
                    accumulator_dtype=mom_dtype,
                ),
            )
        if kind == "adamw":
            return optax.adamw(
                learning_rate=learning_rate,
                b1=cfg.OPTIM.BETA1,
                b2=cfg.OPTIM.BETA2,
                weight_decay=cfg.OPTIM.WEIGHT_DECAY,
            )
        raise ValueError(
            f"OPTIM.OPTIMIZER must be 'sgd' or 'adamw'; got {kind!r}"
        )

    return _make(learning_rate=cfg.OPTIM.BASE_LR)


def set_lr(opt_state, lr: float):
    """Mutate the injected learning rate (≙ set_lr, ref: utils.py:313-316)."""
    opt_state.hyperparams["learning_rate"] = lr
    return opt_state
