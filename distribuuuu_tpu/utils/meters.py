"""Progress meters and ETA (ref: /root/reference/distribuuuu/utils.py:199-262)."""

from __future__ import annotations

import datetime


class AverageMeter:
    """Tracks current value, running average, sum, and count
    (ref: utils.py:199-221)."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val, n: int = 1):
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(**self.__dict__)


class ProgressMeter:
    """Formats a line of meters with an ETA extrapolated from avg batch time
    (ref: utils.py:224-252)."""

    def __init__(self, num_batches: int, meters, prefix: str = ""):
        self.num_batches = num_batches
        self.batch_fmtstr = self._get_batch_fmtstr(num_batches)
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int) -> str:
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(m) for m in self.meters]
        return "  ".join(entries)

    def get_eta(self, batch: int, total_remaining_iters: int | None = None) -> str:
        """Remaining wall-clock from the batch_time meter's average."""
        batch_time = next((m for m in self.meters if m.name == "Time"), None)
        if batch_time is None or batch_time.avg == 0:
            return "N/A"
        remaining = (
            self.num_batches - batch
            if total_remaining_iters is None
            else total_remaining_iters
        )
        eta_sec = batch_time.avg * remaining
        return str(datetime.timedelta(seconds=int(eta_sec)))

    @staticmethod
    def _get_batch_fmtstr(num_batches: int) -> str:
        num_digits = len(str(num_batches // 1))
        fmt = "{:" + str(num_digits) + "d}"
        return "[" + fmt + "/" + fmt.format(num_batches) + "]"


def construct_meters(num_batches: int, prefix: str, topk: int = 5):
    """The standard meter set (ref: utils.py:255-262): batch/data time,
    loss, top-1, top-k."""
    batch_time = AverageMeter("Time", ":6.3f")
    data_time = AverageMeter("Data", ":6.3f")
    losses = AverageMeter("Loss", ":.4e")
    top1 = AverageMeter("Acc@1", ":6.2f")
    topk_m = AverageMeter(f"Acc@{topk}", ":6.2f")
    progress = ProgressMeter(
        num_batches, [batch_time, data_time, losses, top1, topk_m], prefix=prefix
    )
    return batch_time, data_time, losses, top1, topk_m, progress
