"""Utilities: seeding, logging, meters, metrics, schedules, checkpointing."""

from distribuuuu_tpu.utils.seed import setup_env, setup_seed  # noqa: F401
from distribuuuu_tpu.utils.logger import get_logger, setup_logger  # noqa: F401
from distribuuuu_tpu.utils.meters import (  # noqa: F401
    AverageMeter,
    ProgressMeter,
    construct_meters,
)
from distribuuuu_tpu.utils.metrics import accuracy  # noqa: F401
from distribuuuu_tpu.utils.schedules import get_epoch_lr, lr_fun_cos, lr_fun_steps  # noqa: F401
