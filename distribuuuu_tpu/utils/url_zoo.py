"""Pretrained-weight URL zoo with an auto-download.

The reference resolves ``MODEL.PRETRAINED True`` to a torchvision URL per
arch and downloads through torch.hub (ref: /root/reference/distribuuuu/
models/resnet.py:23-33,309-311; models/utils.py:1-4; densenet key-remap
densenet.py:266-282). This module closes that parity gap for connected
environments while staying honest offline: ``fetch()`` attempts the
download directly and maps network-unreachable errors (DNS failure,
refused connection, timeout) to the actionable offline message the
trainer always gave — no up-front connectivity probe (ADVICE r5: the
old 3 s ``_online`` pre-flight added fixed latency to every cache miss
and could pass while the actual download still failed; the download
attempt itself is the probe). The build environment has zero egress, so
the refusal path is the one exercised there; the download path is
covered by tests with a mocked ``urlopen``.

Downloaded files are torch pickles; ingestion (DDP-prefix stripping,
densenet legacy-key remap, rel-pos/pos-embed params) is
``utils/torch_ingest.py`` — the same path a local weights file takes.
"""

from __future__ import annotations

import hashlib
import os
import re
import urllib.error
import urllib.request

# The torchvision v0.8-era zoo the reference links against
# (ref: resnet.py:23-33, densenet.py:300-365 model_urls).
MODEL_URLS = {
    "resnet18": "https://download.pytorch.org/models/resnet18-5c106cde.pth",
    "resnet34": "https://download.pytorch.org/models/resnet34-333f7ec4.pth",
    "resnet50": "https://download.pytorch.org/models/resnet50-19c8e357.pth",
    "resnet101": "https://download.pytorch.org/models/resnet101-5d3b4d8f.pth",
    "resnet152": "https://download.pytorch.org/models/resnet152-b121ed2d.pth",
    "resnext50_32x4d": "https://download.pytorch.org/models/resnext50_32x4d-7cdf4587.pth",
    "resnext101_32x8d": "https://download.pytorch.org/models/resnext101_32x8d-8ba56ff5.pth",
    "wide_resnet50_2": "https://download.pytorch.org/models/wide_resnet50_2-95faca4d.pth",
    "wide_resnet101_2": "https://download.pytorch.org/models/wide_resnet101_2-32ee1156.pth",
    "densenet121": "https://download.pytorch.org/models/densenet121-a639ec97.pth",
    "densenet161": "https://download.pytorch.org/models/densenet161-8d451a50.pth",
    "densenet169": "https://download.pytorch.org/models/densenet169-b2777c0a.pth",
    "densenet201": "https://download.pytorch.org/models/densenet201-c1103571.pth",
}

# Full sha256 pins, arch → 64-hex digest (ADVICE r5: the torchvision
# filename embeds only the FIRST 8 hex chars — a 32-bit check; the
# complete hash is the strong one). This table is AUTHORITATIVE when an
# arch has an entry: the downloaded/cached file must match it exactly.
# This build environment has zero egress, so the true digests cannot be
# computed here to ship as constants (inventing them would refuse every
# valid download); instead each verified download is pinned on first use:
# its full sha256 lands in a ``<file>.sha256`` sidecar next to the cache
# entry, and every later cache hit verifies the COMPLETE hash against the
# pin — truncation or tampering of a cached pickle is caught even when
# the 32-bit filename prefix still matches. Populate this table when a
# connected environment has verified the files.
MODEL_SHA256: dict[str, str] = {}

_DOWNLOAD_TIMEOUT_S = 60


def cache_dir() -> str:
    return os.environ.get(
        "DISTRIBUUUU_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "distribuuuu_tpu"),
    )


def fetch(arch: str) -> str:
    """Path to the cached pretrained torch pickle for ``arch``,
    downloading it when the zoo is reachable.

    Raises ValueError with the actionable offline message when the arch
    has no zoo URL or the network is unreachable — the caller's contract
    is unchanged from the always-refuse behavior.
    """
    url = MODEL_URLS.get(arch)
    if url is None:
        raise ValueError(
            f"MODEL.PRETRAINED True: no pretrained-URL zoo entry for "
            f"{arch!r} (the reference's zoo covers the torchvision archs "
            f"only); point MODEL.WEIGHTS at a local weights file instead"
        )
    dest = os.path.join(cache_dir(), os.path.basename(url))
    if os.path.exists(dest) and _digest_ok(dest, url, arch, _read_pin(dest)):
        return dest
    os.makedirs(cache_dir(), exist_ok=True)
    # per-process temp name: every process of a multi-host run may fetch
    # concurrently (trainer loads weights on all ranks); each writes its
    # own complete file and the atomic replace makes last-writer-wins
    # correct, never interleaved
    tmp = f"{dest}.part.{os.getpid()}"
    try:
        with urllib.request.urlopen(url, timeout=_DOWNLOAD_TIMEOUT_S) as r, \
                open(tmp, "wb") as f:
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
        if not _digest_ok(tmp, url, arch):
            raise ValueError(
                f"pretrained download {url} failed its sha256 checksum "
                "(the full MODEL_SHA256 pin when the arch has one, else "
                "the prefix the torchvision filename embeds); truncated "
                "or corrupted transfer"
            )
        os.replace(tmp, dest)  # atomic: no truncated cache on interrupt
        _write_pin(dest)  # full-hash pin for every later cache hit
    except ValueError:
        raise
    except urllib.error.HTTPError as e:
        # the server RESPONDED — network is fine, the download itself failed
        raise ValueError(
            f"MODEL.PRETRAINED True: downloading {url} failed ({e}); "
            "point MODEL.WEIGHTS at a local weights file instead"
        ) from e
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        # DNS failure / refused / timeout ⇒ unreachable: the actionable
        # offline message (the download attempt IS the connectivity probe)
        raise ValueError(
            "MODEL.PRETRAINED True needs MODEL.WEIGHTS pointing at a "
            "weights file (torch .pth or orbax dir): the pretrained-URL "
            f"zoo at {url} is unreachable from this environment ({e})"
        ) from e
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return dest


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _pin_path(dest: str) -> str:
    return dest + ".sha256"


def _read_pin(dest: str) -> str | None:
    """The sidecar full-hash pin recorded at download time, if any."""
    try:
        with open(_pin_path(dest)) as f:
            pin = f.read().strip()
        return pin if re.fullmatch(r"[0-9a-f]{64}", pin) else None
    except OSError:
        return None


def _write_pin(dest: str) -> None:
    # concurrent multi-process fetches may interleave file/sidecar writes;
    # both write identical content for one URL, and a genuine mismatch is
    # caught by the next fetch's full-hash check (→ re-download)
    with open(_pin_path(dest), "w") as f:
        f.write(_sha256(dest) + "\n")


def _digest_ok(path: str, url: str, arch: str | None = None,
               pin: str | None = None) -> bool:
    """Verify ``path`` against the strongest available expectation, in
    order: an explicit ``pin`` (the cache sidecar), the ``MODEL_SHA256``
    table — both compared as the COMPLETE 64-hex sha256 — else the 8-hex
    prefix the torchvision filename embeds (``resnet50-19c8e357.pth``,
    what torch.hub checks, ref: models/utils.py:1-4). A file that fails
    (truncated write, tampering) is re-downloaded rather than served."""
    digest = _sha256(path)
    full = pin or (MODEL_SHA256.get(arch) if arch else None)
    if full:
        return digest == full
    m = re.search(r"-([0-9a-f]{8})\.pth$", os.path.basename(url))
    if not m:
        return True  # no embedded digest to check against
    return digest.startswith(m.group(1))
