"""Logging (ref: /root/reference/distribuuuu/utils.py:71-82).

The reference uses loguru with a rank-0 file sink ``{OUT_DIR}/{time}.log``
plus an all-rank stderr sink. loguru is not in this environment, so this is
stdlib logging with the same shape: process-0 gets the file sink, every
process logs to stderr tagged with its process index.
"""

from __future__ import annotations

import logging
import os
import sys
import time

import jax

from distribuuuu_tpu.config import cfg

_LOGGER_NAME = "distribuuuu_tpu"
_configured = False


def setup_logger() -> logging.Logger:
    global _configured
    logger = logging.getLogger(_LOGGER_NAME)
    if _configured:
        return logger
    logger.setLevel(logging.INFO)
    logger.propagate = False
    rank = jax.process_index()
    fmt = logging.Formatter(
        fmt=f"%(asctime)s | %(levelname)s | p{rank} | %(message)s",
        datefmt="%Y-%m-%d %H:%M:%S",
    )
    stream = logging.StreamHandler(sys.stderr)
    stream.setFormatter(fmt)
    logger.addHandler(stream)
    if rank == 0:
        os.makedirs(cfg.OUT_DIR, exist_ok=True)
        fh = logging.FileHandler(os.path.join(cfg.OUT_DIR, f"{time.time()}.log"))
        fh.setFormatter(fmt)
        logger.addHandler(fh)
        logger.info("config:\n%s", cfg.dump())
    _configured = True
    return logger


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        # Usable before setup (e.g. in tests): stderr only, no file sink.
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(asctime)s | %(levelname)s | %(message)s"))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger
