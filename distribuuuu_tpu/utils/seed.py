"""Seeding and environment setup (ref: /root/reference/distribuuuu/utils.py:54-68).

The reference seeds numpy/torch/random with ``RNG_SEED + rank`` so each rank
draws distinct augmentations, and toggles cuDNN determinism. Here the
rank-offset seeding of the *global* numpy/``random`` streams is kept for
reference parity and incidental host randomness only — augmentation
deliberately does NOT draw from them (see ``setup_seed``), and the returned
``jax.random`` key is folded from the *base* seed only — in-graph randomness
under global-array jit must be identical on every process, XLA derives
per-shard streams itself.
"""

from __future__ import annotations

import os
import random

import jax
import numpy as np

from distribuuuu_tpu.config import cfg


def setup_seed() -> jax.Array:
    """Seed host RNGs rank-offset; return the in-graph base PRNG key.

    Mirrors setup_seed's semantics (utils.py:54-68): if ``cfg.RNG_SEED`` is
    None a random seed is drawn (and broadcast so all processes agree on the
    in-graph key).

    DATA-GROUP IDENTICAL-BATCH INVARIANT (ADVICE r5 — do not reintroduce
    rank-offset global-RNG augmentation): processes that share a data row
    of the mesh (model/pipe axes spanning hosts) load the SAME sampler
    shard and must assemble byte-identical batches — their devices hold
    the same shard of the global batch
    (parallel/mesh.data_process_groups; PARITY.md "DistributedSampler
    semantics"). Augmentation therefore draws from per-sample generators
    seeded by ``(RNG_SEED, epoch, sample_index)``
    (data/imagefolder.ImageFolderDataset._rng) — rank-independent by
    construction — and NEVER from the rank-offset ``np.random`` /
    ``random`` streams seeded here. Routing augmentation through these
    global streams would give same-data-row processes different pixels
    for the same sample: a silent cross-host batch divergence that TP/PP
    meshes turn into wrong math, not an error message.
    """
    seed = cfg.RNG_SEED
    if seed is None:
        seed = int.from_bytes(os.urandom(4), "little")
        if jax.process_count() > 1:
            from distribuuuu_tpu.parallel.collectives import broadcast_from_primary

            seed = int(broadcast_from_primary(np.int64(seed)))
    rank = jax.process_index()
    np.random.seed(seed + rank)
    random.seed(seed + rank)
    return jax.random.key(seed)


def setup_env() -> None:
    """Rank-0 output-dir creation + config dump (ref: utils.py:56-58).

    Determinism knobs (the cuDNN-toggle analogue, ref: utils.py:64-68) are
    applied by ``parallel.mesh.apply_backend_flags`` *before* backend init —
    by the time this runs the backend is live and XLA_FLAGS edits are moot.
    """
    if jax.process_index() == 0:
        os.makedirs(cfg.OUT_DIR, exist_ok=True)
        from distribuuuu_tpu import config

        config.dump_cfg()
