"""Learning-rate schedules (ref: /root/reference/distribuuuu/utils.py:280-316).

Semantics mirrored exactly: epoch-granular LR (set once per epoch,
ref: trainer.py:25-26), step policy ``LR_MULT ** idx`` over ``STEPS``,
half-period cosine with relative ``MIN_LR`` floor, linear warmup ramp from
``WARMUP_FACTOR`` to 1 over ``WARMUP_EPOCHS``, all scaled by ``BASE_LR``
(which configs set with the linear batch-size scaling rule, BASELINE.md).
"""

from __future__ import annotations

import numpy as np

from distribuuuu_tpu.config import cfg


def lr_fun_steps(cur_epoch: float) -> float:
    """Piecewise-constant decay: LR_MULT ** (index of current step band)."""
    steps = list(cfg.OPTIM.STEPS)
    if not steps or steps[0] != 0:
        steps = [0] + steps
    ind = [i for i, s in enumerate(steps) if cur_epoch >= s][-1]
    return float(cfg.OPTIM.LR_MULT) ** ind


def lr_fun_cos(cur_epoch: float) -> float:
    """Half-period cosine, floored at relative MIN_LR."""
    base = 0.5 * (1.0 + np.cos(np.pi * cur_epoch / cfg.OPTIM.MAX_EPOCH))
    return (1.0 - cfg.OPTIM.MIN_LR) * base + cfg.OPTIM.MIN_LR


def get_lr_fun():
    """Dispatch on OPTIM.LR_POLICY (ref: utils.py:292-298)."""
    name = "lr_fun_" + cfg.OPTIM.LR_POLICY
    if name not in globals():
        raise NotImplementedError(f"Unknown LR policy: {cfg.OPTIM.LR_POLICY}")
    return globals()[name]


def get_epoch_lr(cur_epoch: float) -> float:
    """Absolute LR for an epoch: policy × BASE_LR, with linear warmup
    (ref: utils.py:301-310)."""
    lr = get_lr_fun()(cur_epoch) * cfg.OPTIM.BASE_LR
    if cur_epoch < cfg.OPTIM.WARMUP_EPOCHS:
        alpha = cur_epoch / cfg.OPTIM.WARMUP_EPOCHS
        warmup_factor = cfg.OPTIM.WARMUP_FACTOR * (1.0 - alpha) + alpha
        lr *= warmup_factor
    return float(lr)
