"""Deterministic fault injection (the ``FAULTS.*`` config node).

A recovery path that is never exercised is a recovery path that does not
work. This module turns each failure class the resilience layer claims to
survive into a reproducible, config-driven event, so tests and
``tools/resilience_drill.py`` drive the REAL recovery code — not mocks:

  truncated checkpoint   ``FAULTS.CORRUPT_EPOCH`` — after ``ckpt_ep_e``
                         is saved+committed, truncate its largest payload
                         file ("truncate" mode: digest-mismatch path) or
                         delete its manifest ("partial" mode: the
                         crash-before-commit path);
  NaN at step k          ``FAULTS.NAN_STEP`` — the train step compiles in
                         ``loss × where(step==k, NaN, 1)`` so loss AND
                         grads go non-finite exactly once, in-graph;
  decode error           ``FAULTS.DECODE_ERROR_IDX`` — sample i's decode
                         raises ("once": the loader's first retry
                         succeeds; "always": the sample is skipped and
                         logged);
  killed rank            ``FAULTS.KILL_RANK/KILL_EPOCH/KILL_AT_BATCH`` —
                         SIGKILL this process at a batch boundary (no
                         handler can run: the hard-crash case);
  stalled step           ``FAULTS.STALL_EPOCH/STALL_AT_BATCH/STALL_S`` —
                         sleep mid-loop so the heartbeat watchdog flags;
  preemption             ``FAULTS.PREEMPT_EPOCH/PREEMPT_AT_BATCH`` —
                         self-deliver SIGTERM at a batch boundary through
                         the real handler chain (the scheduler-preemption
                         case: mid-epoch save with the shards data cursor);
  truncated shard        ``FAULTS.TRUNCATE_SHARD`` — cut a record shard
                         (DATA.FORMAT=shards) to 60% before the reader
                         opens it: index-footer recovery + record skips;
  killed mid-async-save  ``FAULTS.KILL_MID_ASYNC_SAVE`` — SIGKILL from
                         the async committer thread after ckpt_ep_e's
                         payload is written but before its manifest
                         commits (CHECKPOINT.ASYNC): the walk-back must
                         recover from the previous intact checkpoint;
  wedged dispatcher      ``FAULTS.WEDGE_DISPATCH/WEDGE_S`` — hold the
                         sequencer's dispatch token (asyncplane/
                         sequencer.py) for WEDGE_S seconds so the wedge
                         watchdog must flag a ``dispatch.wedge`` record;
  killed at barrier      ``FAULTS.KILL_AT_COMMIT_BARRIER`` — SIGKILL the
                         primary host between the cross-host commit
                         barrier (all payloads durable) and the manifest
                         commit (multi-host CHECKPOINT.ASYNC): the
                         restart walks back over the manifest-less dir;
  wedged ring slot       ``FAULTS.WEDGE_RING/WEDGE_RING_S`` — hold the
                         LEADER's cross-host ring slot before its order
                         publishes (asyncplane/ring.py): followers must
                         flag ``dispatch.wedge`` past their deadline and
                         the trainer must degrade that epoch's eval to
                         sync, never hang;
  killed at shard barrier ``FAULTS.KILL_AT_SHARD_BARRIER`` — SIGKILL the
                         primary inside the SHARDED commit window (every
                         host's shard file durable, manifest not): the
                         restart quarantines shards and all, walks back;
  dropped shard file     ``FAULTS.DROP_SHARD_FILE/DROP_SHARD_HOST`` —
                         delete one host's shards_host<r>.npz from a
                         COMMITTED sharded save: the restart's digest
                         walk must fail it, a direct load must refuse
                         naming the recorded sharding;
  recompile storm        ``FAULTS.RECOMPILE_AT_BATCH/RECOMPILE_N`` —
                         N real backend compiles mid-run (trivial jits
                         at distinct shapes; the shape-leak signature
                         tools/monitor.py's recompile-storm rule flags);
  sustained slowdown     ``FAULTS.SLOWDOWN_EPOCH/SLOWDOWN_MS`` — sleep
                         at every batch boundary of one epoch (the
                         throughput regression the monitor's
                         throughput-regression rule flags).

Every hook is a no-op (one attribute read) unless ``FAULTS.ENABLED`` —
zero overhead in production paths.
"""

from __future__ import annotations

import os
import signal
import time

from distribuuuu_tpu.config import cfg

__all__ = [
    "InjectedFault", "enabled", "nan_injection_step", "maybe_decode_error",
    "maybe_kill", "maybe_stall", "maybe_corrupt_checkpoint",
    "maybe_kill_mid_async_save", "maybe_kill_at_commit_barrier",
    "maybe_kill_at_shard_barrier", "maybe_drop_shard_file",
    "maybe_preempt", "maybe_truncate_shard",
    "maybe_recompile", "maybe_slowdown", "maybe_wedge_dispatch",
    "maybe_wedge_ring", "validate_cfg", "reset",
]


class InjectedFault(RuntimeError):
    """An injected failure — distinguishable from organic errors in logs."""


_state: dict = {"decode_raised": set(), "preempted": False,
                "truncated_shards": set(), "recompiled": False,
                "wedged": False, "ring_wedged": False,
                "dropped_shard": False}


def reset() -> None:
    """Clear once-mode bookkeeping (tests)."""
    _state["decode_raised"] = set()
    _state["preempted"] = False
    _state["truncated_shards"] = set()
    _state["recompiled"] = False
    _state["wedged"] = False
    _state["ring_wedged"] = False
    _state["dropped_shard"] = False


def enabled() -> bool:
    return bool(cfg.FAULTS.ENABLED)


def validate_cfg() -> None:
    """Arithmetic sanity for ARMED fault knobs, at startup rather than at
    the (possibly hours-later) injection point. Refusals name the knobs
    and the units so the fix is mechanical. No-op unless FAULTS.ENABLED."""
    if not enabled():
        return
    if cfg.FAULTS.WEDGE_RING >= 0:
        wedge_s = float(cfg.FAULTS.WEDGE_RING_S)
        deadline = float(cfg.ASYNC.RING_DEADLINE_S)
        if wedge_s <= 0:
            raise ValueError(
                "FAULTS.WEDGE_RING is armed but FAULTS.WEDGE_RING_S is "
                f"{wedge_s} — the ring hold must be a positive number of "
                "seconds for the wedge to exist at all"
            )
        if wedge_s <= deadline:
            raise ValueError(
                f"FAULTS.WEDGE_RING_S ({wedge_s} s) must exceed "
                f"ASYNC.RING_DEADLINE_S ({deadline} s): followers flag a "
                "ring wedge only after waiting a full deadline, so a hold "
                "shorter than the deadline is unobservable — the drill "
                "would 'pass' without exercising the degrade path"
            )
    if cfg.FAULTS.DROP_SHARD_FILE >= 0 and int(cfg.FAULTS.DROP_SHARD_HOST) < 0:
        raise ValueError(
            f"FAULTS.DROP_SHARD_HOST ({int(cfg.FAULTS.DROP_SHARD_HOST)}) "
            "must be a host rank >= 0 (it indexes shards_host<r>.npz; the "
            "upper bound is checked against the live world at the "
            "injection site)"
        )


def nan_injection_step() -> int | None:
    """Trace-time consult: the global step whose loss the train step body
    multiplies by NaN, or None (the common case — nothing is compiled in)."""
    if enabled() and cfg.FAULTS.NAN_STEP >= 0:
        return int(cfg.FAULTS.NAN_STEP)
    return None


def maybe_decode_error(idx: int) -> None:
    """Raise for the configured sample index. "once" mode raises only the
    first time the index is touched — the loader's retry-with-backoff
    succeeds (the transient-I/O case); "always" keeps raising — the
    loader's skip-and-log path engages (the corrupt-file case)."""
    if not enabled() or cfg.FAULTS.DECODE_ERROR_IDX < 0:
        return
    if int(idx) != int(cfg.FAULTS.DECODE_ERROR_IDX):
        return
    if cfg.FAULTS.DECODE_ERROR_MODE == "once":
        if idx in _state["decode_raised"]:
            return
        _state["decode_raised"].add(idx)
    raise InjectedFault(f"injected decode error on sample {idx}")


def maybe_kill(epoch: int, batch: int) -> None:
    """SIGKILL this process at the configured (rank, epoch, batch) — the
    uncatchable hard crash (OOM-killer / host death). Nothing below this
    line runs; recovery is entirely the next process's problem."""
    if not enabled() or cfg.FAULTS.KILL_RANK < 0:
        return
    import jax

    if (
        jax.process_index() == int(cfg.FAULTS.KILL_RANK)
        and epoch == int(cfg.FAULTS.KILL_EPOCH)
        and batch == int(cfg.FAULTS.KILL_AT_BATCH)
    ):
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_preempt(epoch: int, batch: int) -> None:
    """Self-deliver SIGTERM at the configured (epoch, batch) boundary —
    a deterministic scheduler preemption. Goes through the REAL installed
    handler chain (utils/preempt.py), so the epoch loop exits at the next
    boundary and writes the mid-epoch checkpoint exactly as it would for
    a fleet SIGTERM. One-shot per process."""
    if not enabled() or cfg.FAULTS.PREEMPT_AT_BATCH < 0 or _state["preempted"]:
        return
    if (
        epoch == int(cfg.FAULTS.PREEMPT_EPOCH)
        and batch == int(cfg.FAULTS.PREEMPT_AT_BATCH)
    ):
        _state["preempted"] = True
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_truncate_shard(split_dir: str) -> None:
    """Truncate shard file #``FAULTS.TRUNCATE_SHARD`` of the split to 60%
    of its manifest size — destroying its index footer and tail records —
    BEFORE the reader opens it. Exercises the reader's forward-scan index
    recovery plus the loader's DATA.SKIP_CORRUPT substitution for the
    physically lost records. Idempotent per (process, split)."""
    if not enabled() or cfg.FAULTS.TRUNCATE_SHARD < 0:
        return
    if split_dir in _state["truncated_shards"]:
        return
    _state["truncated_shards"].add(split_dir)
    import json

    from distribuuuu_tpu.data.shards.format import MANIFEST_NAME

    try:
        with open(os.path.join(split_dir, MANIFEST_NAME)) as f:
            man = json.load(f)
        meta = man["shards"][int(cfg.FAULTS.TRUNCATE_SHARD)]
    except (OSError, json.JSONDecodeError, IndexError, KeyError):
        return  # nothing to damage — the reader will complain on its own
    path = os.path.join(split_dir, meta["file"])
    if os.path.isfile(path) and os.path.getsize(path) == meta["size"]:
        with open(path, "r+b") as f:
            f.truncate(max(1, int(meta["size"]) * 6 // 10))


def maybe_recompile(epoch: int, batch: int) -> None:
    """Trigger ``FAULTS.RECOMPILE_N`` REAL backend compiles at the
    configured batch boundary: trivial jits at N distinct shapes, so the
    telemetry compile listener records genuine ``kind="compile"`` events
    — the mid-run recompile storm a shape leak causes — while training
    math is untouched (nothing here feeds the train step). One-shot per
    process."""
    if not enabled() or cfg.FAULTS.RECOMPILE_AT_BATCH < 0:
        return
    if _state["recompiled"]:
        return
    if (
        epoch != int(cfg.FAULTS.RECOMPILE_EPOCH)
        or batch != int(cfg.FAULTS.RECOMPILE_AT_BATCH)
    ):
        return
    _state["recompiled"] = True
    import jax
    import numpy as np

    for i in range(max(1, int(cfg.FAULTS.RECOMPILE_N))):
        # a fresh jit wrapper + a fresh shape per iteration: every call
        # is a cache miss, every miss is one real backend compile
        jax.jit(lambda x: x + 1.0)(
            np.zeros((i + 2,), np.float32)
        ).block_until_ready()


def maybe_slowdown(epoch: int, batch: int) -> None:
    """Sleep ``FAULTS.SLOWDOWN_MS`` at EVERY batch boundary of the
    configured epoch — a sustained throughput regression (vs the
    one-shot ``maybe_stall``, which must trip the watchdog instead).
    Keep it well under TRAIN.STALL_TIMEOUT."""
    if not enabled() or cfg.FAULTS.SLOWDOWN_MS <= 0:
        return
    if epoch == int(cfg.FAULTS.SLOWDOWN_EPOCH):
        time.sleep(float(cfg.FAULTS.SLOWDOWN_MS) / 1e3)


def maybe_stall(epoch: int, batch: int) -> None:
    """Sleep ``FAULTS.STALL_S`` at the configured batch boundary — long
    enough that the heartbeat watchdog (TRAIN.STALL_TIMEOUT) must flag."""
    if not enabled() or cfg.FAULTS.STALL_AT_BATCH < 0:
        return
    if (
        epoch == int(cfg.FAULTS.STALL_EPOCH)
        and batch == int(cfg.FAULTS.STALL_AT_BATCH)
        and cfg.FAULTS.STALL_S > 0
    ):
        time.sleep(float(cfg.FAULTS.STALL_S))


def maybe_wedge_dispatch(token: int) -> None:
    """Hold dispatch token #``FAULTS.WEDGE_DISPATCH`` for ``WEDGE_S``
    seconds before the dispatch proceeds (the sequencer calls this while
    HOLDING the token — asyncplane/sequencer.py): a wedged dispatcher
    thread. Every other stream's acquire blocks behind it, so the wedge
    watchdog must flag (``kind="dispatch.wedge"``) while the run itself
    survives and completes once the hold ends. One-shot per process."""
    if not enabled() or cfg.FAULTS.WEDGE_DISPATCH < 0 or _state["wedged"]:
        return
    if int(token) >= int(cfg.FAULTS.WEDGE_DISPATCH) and cfg.FAULTS.WEDGE_S > 0:
        _state["wedged"] = True
        time.sleep(float(cfg.FAULTS.WEDGE_S))


def maybe_wedge_ring(token: int) -> None:
    """Hold the LEADER's ring slot #``FAULTS.WEDGE_RING`` for
    ``WEDGE_RING_S`` seconds BEFORE the grant order publishes to the ring
    (sequencer.py calls this from the leader's acquire path, between
    taking the local token and ``ring.publish``). Followers waiting on
    the unpublished slot must flag ``dispatch.wedge`` once past
    ``ASYNC.RING_DEADLINE_S`` (hence ``validate_cfg``'s requirement that
    WEDGE_RING_S exceed the deadline) and the trainer must degrade that
    epoch's eval to synchronous — never hang. One-shot per process."""
    if not enabled() or cfg.FAULTS.WEDGE_RING < 0 or _state["ring_wedged"]:
        return
    if int(token) >= int(cfg.FAULTS.WEDGE_RING) and cfg.FAULTS.WEDGE_RING_S > 0:
        _state["ring_wedged"] = True
        time.sleep(float(cfg.FAULTS.WEDGE_RING_S))


def maybe_kill_at_commit_barrier(path: str, epoch: int) -> None:
    """SIGKILL the PRIMARY host inside the multi-host async-commit crash
    window: every host has arrived at the cross-host commit barrier (all
    payload bytes durable everywhere), ``MANIFEST.json`` has NOT been
    written (asyncplane/committer.py places this hook between the
    barrier completing and the manifest commit). The restart must
    quarantine the manifest-less directory and walk back to the previous
    intact save (tools/resilience_drill.py multihost_async_save_kill).
    Epoch checkpoints only, primary only."""
    if not enabled() or cfg.FAULTS.KILL_AT_COMMIT_BARRIER < 0:
        return
    if not os.path.basename(path).startswith("ckpt_ep_"):
        return
    import jax

    if jax.process_index() != 0:
        return
    if epoch == int(cfg.FAULTS.KILL_AT_COMMIT_BARRIER):
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_kill_at_shard_barrier(path: str, epoch: int) -> None:
    """SIGKILL the PRIMARY host inside the SHARDED commit crash window:
    every host's ``shards_host<r>.npz`` + layout are durable and the
    cross-host barrier has completed, but ``MANIFEST.json`` has NOT been
    written (asyncplane/committer.py places this hook there when
    ``sharded=True``). The restart must treat the manifest-less dir as
    never-committed — quarantine every shard file with it and walk back
    (tools/resilience_drill.py ``sharded_save_kill_at_barrier``). Epoch
    checkpoints only, primary only."""
    if not enabled() or cfg.FAULTS.KILL_AT_SHARD_BARRIER < 0:
        return
    if not os.path.basename(path).startswith("ckpt_ep_"):
        return
    import jax

    if jax.process_index() != 0:
        return
    if epoch == int(cfg.FAULTS.KILL_AT_SHARD_BARRIER):
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_drop_shard_file(path: str, epoch: int, world: int) -> None:
    """Delete host ``FAULTS.DROP_SHARD_HOST``'s ``shards_host<r>.npz``
    from a just-COMMITTED sharded checkpoint of the configured epoch —
    the lost-a-file restore case (a host's disk died between save and
    restart). The manifest's digest walk must fail the dir on the next
    start (quarantine + walk-back), and a direct ``load_checkpoint`` must
    refuse, naming the recorded sharding. Primary process only, one-shot;
    the host index is validated against the LIVE world here because the
    config layer cannot know it."""
    if not enabled() or cfg.FAULTS.DROP_SHARD_FILE < 0:
        return
    if _state["dropped_shard"] or epoch != int(cfg.FAULTS.DROP_SHARD_FILE):
        return
    import jax

    if jax.process_index() != 0:
        return
    victim = int(cfg.FAULTS.DROP_SHARD_HOST)
    if not 0 <= victim < int(world):
        raise ValueError(
            f"FAULTS.DROP_SHARD_HOST ({victim}) must satisfy "
            f"0 <= host < world ({int(world)}): the sharded save wrote "
            f"shards_host0.npz .. shards_host{int(world) - 1}.npz, so "
            "there is no such shard file to drop"
        )
    _state["dropped_shard"] = True
    shard = os.path.join(path, f"shards_host{victim}.npz")
    if os.path.isfile(shard):
        os.unlink(shard)


def maybe_kill_mid_async_save(path: str, epoch: int) -> None:
    """SIGKILL this process inside the async-save crash window: the
    checkpoint's orbax payload is fully on disk, its ``MANIFEST.json``
    is NOT — exactly where a host dying mid-background-commit leaves the
    directory (``CHECKPOINT.ASYNC``). The restart must quarantine the
    manifest-less dir ("no committed manifest") and walk back to the
    previous intact save (tools/resilience_drill.py
    ``killed_mid_async_save``). Epoch checkpoints only — a preempt
    save's number is its interrupted epoch, not a save cursor."""
    if not enabled() or cfg.FAULTS.KILL_MID_ASYNC_SAVE < 0:
        return
    if not os.path.basename(path).startswith("ckpt_ep_"):
        return
    if epoch == int(cfg.FAULTS.KILL_MID_ASYNC_SAVE):
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_corrupt_checkpoint(path: str, epoch: int) -> None:
    """Damage a just-committed checkpoint of the configured epoch:
    "truncate" halves the largest payload file (manifest digests then
    mismatch — the bit-rot/partial-write path); "partial" deletes the
    manifest (the crash-before-commit path). Primary process only —
    the same process that owns the manifest commit."""
    if not enabled() or cfg.FAULTS.CORRUPT_EPOCH < 0:
        return
    if epoch != int(cfg.FAULTS.CORRUPT_EPOCH):
        return
    import jax

    if jax.process_index() != 0:
        return
    from distribuuuu_tpu.resilience.manifest import MANIFEST_NAME

    if cfg.FAULTS.CORRUPT_MODE == "partial":
        man = os.path.join(path, MANIFEST_NAME)
        if os.path.isfile(man):
            os.unlink(man)
        return
    largest, largest_size = None, -1
    for dirpath, _, names in os.walk(path):
        for name in names:
            if name == MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, name)
            size = os.path.getsize(full)
            if size > largest_size:
                largest, largest_size = full, size
    if largest is not None:
        with open(largest, "r+b") as f:
            f.truncate(max(1, largest_size // 2))
