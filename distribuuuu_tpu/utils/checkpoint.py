"""Checkpoint/auto-resume on orbax (ref: /root/reference/distribuuuu/utils.py:319-410).

Semantics mirrored: epoch-granular saves named ``ckpt_ep_{epoch:03d}`` under
``{OUT_DIR}/checkpoints`` (ref: utils.py:320-334), auto-resume picks the
lexicographically-last epoch dir (ref: utils.py:337-342), keep-all policy
plus a weights-only ``best`` checkpoint on a new best metric (ref:
utils.py:385-387), optimizer-state restore optional with graceful fallback
(ref: utils.py:399-405), and weights-only checkpoints load cleanly
(ref: utils.py:406-407).

Formats differ by design: orbax OCDBT directories instead of torch pickles —
multi-host-safe (every process participates; array shards are written by
their owners) and framework-portable.
"""

from __future__ import annotations

import os
import re

import jax
import numpy as np
import orbax.checkpoint as ocp

from distribuuuu_tpu.config import cfg

_NAME_PREFIX = "ckpt_ep_"
_BEST_NAME = "best"


def get_checkpoint_dir() -> str:
    # Absolute: orbax/tensorstore rejects relative paths.
    return os.path.abspath(os.path.join(cfg.OUT_DIR, "checkpoints"))


def get_checkpoint(epoch: int) -> str:
    """Path for an epoch's checkpoint (ref naming: utils.py:320-334)."""
    return os.path.join(get_checkpoint_dir(), f"{_NAME_PREFIX}{epoch:03d}")


def get_best_checkpoint() -> str:
    return os.path.join(get_checkpoint_dir(), _BEST_NAME)


def get_last_checkpoint() -> str:
    """Latest epoch checkpoint by numeric order (ref: utils.py:337-342)."""
    d = get_checkpoint_dir()
    names = [
        f
        for f in os.listdir(d)
        if re.fullmatch(_NAME_PREFIX + r"\d+", f)
        and os.path.isdir(os.path.join(d, f))
    ]
    if not names:
        raise FileNotFoundError(f"No checkpoints in {d}")
    return os.path.join(d, sorted(names)[-1])


def has_checkpoint() -> bool:
    """Any checkpoint to resume from? (ref: utils.py:345-350)"""
    d = get_checkpoint_dir()
    if not os.path.isdir(d):
        return False
    return any(re.fullmatch(_NAME_PREFIX + r"\d+", f) for f in os.listdir(d))


def save_checkpoint(state_tree: dict, epoch: int, best_acc1: float, is_best: bool):
    """Save a full training checkpoint; side-write weights-only ``best``.

    The payload mirrors the reference dict {epoch, state_dict, optimizer,
    best_acc1} (ref: utils.py:375-380). All processes must call this
    (collective); orbax writes each array shard from its owning host.
    """
    os.makedirs(get_checkpoint_dir(), exist_ok=True)
    payload = dict(state_tree)
    payload["epoch"] = np.int32(epoch)
    payload["best_acc1"] = np.float32(best_acc1)
    ckptr = ocp.PyTreeCheckpointer()
    path = get_checkpoint(epoch)
    ckptr.save(path, payload, force=True)
    if is_best:
        best = {"params": state_tree["params"], "batch_stats": state_tree["batch_stats"]}
        ckptr.save(get_best_checkpoint(), best, force=True)
    return path


def load_checkpoint(path: str):
    """Restore a checkpoint as a numpy pytree (host-side; the trainer
    re-places arrays onto the mesh). Weights-only checkpoints return without
    ``opt_state``/``epoch`` keys and the caller falls back gracefully
    (ref semantics: utils.py:391-410)."""
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(os.path.abspath(path))
    return restored
