"""Checkpoint/auto-resume on orbax (ref: /root/reference/distribuuuu/utils.py:319-410).

Semantics mirrored: epoch-granular saves named ``ckpt_ep_{epoch:03d}`` under
``{OUT_DIR}/checkpoints`` (ref: utils.py:320-334), auto-resume picks the
lexicographically-last epoch dir (ref: utils.py:337-342), keep-all policy
plus a weights-only ``best`` checkpoint on a new best metric (ref:
utils.py:385-387), optimizer-state restore optional with graceful fallback
(ref: utils.py:399-405), and weights-only checkpoints load cleanly
(ref: utils.py:406-407).

Formats differ by design: orbax OCDBT directories instead of torch pickles —
multi-host-safe (every process participates; array shards are written by
their owners) and framework-portable.

Crash consistency (resilience/manifest.py): every save commits a
``MANIFEST.json`` (tree spec + file digests + world topology) atomically
AFTER the orbax payload. ``find_last_valid_checkpoint`` — the trainer's
resume entry — verifies candidates newest-first, quarantines corrupt or
partial directories to ``*.corrupt``, and walks back to the newest intact
save; the raw lexicographic pick (``get_last_checkpoint``) previously
selected a half-written dir and killed the resume inside tensorstore.

Async commit (``CHECKPOINT.ASYNC`` — asyncplane/committer.py): the
trainer blocks only for the device→host snapshot of the payload
(``ckpt_snapshot`` span); the orbax write, digests, and manifest commit
run on a background thread (``ckpt_commit`` span), manifest still
strictly LAST — the crash-consistency story above is byte-for-byte the
same, just off the critical path. Multi-host runs commit async too:
each host's committer thread runs the cross-host commit barrier
(asyncplane/committer.py ``multihost_commit`` — payload durable on
every host BEFORE the primary's manifest). A tree sharded ACROSS hosts
commits through the SHARDED variant (``_save_sharded``, ISSUE 18):
each host writes its own addressable shards under the barrier and the
manifest records the sharding. Degrades to the synchronous collective
protocol with one logged warning remain only for ``ASYNC.SEQUENCER``
off (the escape hatch) and trees a host snapshot cannot represent at
all (non-dict containers, object-dtype leaves). Preempt saves
always drain the committer first and commit synchronously — the
process is about to exit, and the grace window must end with a durable
manifest.
"""

from __future__ import annotations

import os
import re

import jax
import numpy as np
import orbax.checkpoint as ocp

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.resilience import manifest as manifest_lib
from distribuuuu_tpu.telemetry import spans as telemetry_spans

_NAME_PREFIX = "ckpt_ep_"
_BEST_NAME = "best"
# mid-epoch checkpoint written on preemption (utils/preempt.py); the number
# is the INTERRUPTED epoch, so preempt_ep_e outranks ckpt_ep_{e-1} (it holds
# strictly newer optimizer progress) and is superseded by ckpt_ep_e.
_PREEMPT_PREFIX = "preempt_ep_"


class CheckpointError(RuntimeError):
    """Base for checkpoint failures this module can diagnose."""


class NoValidCheckpointError(CheckpointError, FileNotFoundError):
    """Checkpoint dirs exist (or don't) but none verifies intact."""


class CheckpointLoadError(CheckpointError):
    """An orbax restore failed; the message names the path, the quarantine
    action taken, and the resume-from-previous command."""


def get_checkpoint_dir() -> str:
    # Absolute: orbax/tensorstore rejects relative paths.
    return os.path.abspath(os.path.join(cfg.OUT_DIR, "checkpoints"))


def get_checkpoint(epoch: int) -> str:
    """Path for an epoch's checkpoint (ref naming: utils.py:320-334)."""
    return os.path.join(get_checkpoint_dir(), f"{_NAME_PREFIX}{epoch:03d}")


def get_best_checkpoint() -> str:
    return os.path.join(get_checkpoint_dir(), _BEST_NAME)


def _scan(prefix: str) -> dict[int, str]:
    d = get_checkpoint_dir()
    if not os.path.isdir(d):
        return {}
    out = {}
    for f in os.listdir(d):
        if re.fullmatch(prefix + r"\d+", f) and os.path.isdir(
            os.path.join(d, f)
        ):
            out[int(f[len(prefix):])] = os.path.join(d, f)
    return out


def _ordered_candidates() -> list[str]:
    """Every resumable checkpoint, newest state first. Recency rank:
    ``preempt_ep_e`` (mid-epoch state of interrupted epoch e) sits between
    ``ckpt_ep_{e-1}`` and ``ckpt_ep_e`` — it holds strictly newer progress
    than the former and is superseded by the latter."""
    ranked = [(2 * e + 2, p) for e, p in _scan(_NAME_PREFIX).items()]
    ranked += [(2 * e + 1, p) for e, p in _scan(_PREEMPT_PREFIX).items()]
    return [p for _, p in sorted(ranked, reverse=True)]


def get_last_checkpoint() -> str:
    """Newest checkpoint by the recency ordering — UNVERIFIED (the raw
    reference semantics, ref numeric-order pick: utils.py:337-342). The
    trainer resumes through ``find_last_valid_checkpoint`` instead, which
    skips/quarantines saves that fail manifest verification."""
    cands = _ordered_candidates()
    if not cands:
        raise FileNotFoundError(f"No checkpoints in {get_checkpoint_dir()}")
    return cands[0]


def quarantine_checkpoint(path: str, reason: str) -> str | None:
    """Move a broken checkpoint dir aside as ``<name>.corrupt[.N]`` so it
    never outranks intact saves again (and stays inspectable). Primary
    process only — a plain filesystem op on shared storage, like
    ``prune_preempts``; other ranks just log the skip."""
    from distribuuuu_tpu.utils.logger import get_logger

    if jax.process_index() != 0:
        get_logger().warning(
            "checkpoint %s failed verification (%s) — skipping "
            "(primary quarantines)", path, reason,
        )
        return None
    dest = path + ".corrupt"
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}.corrupt.{n}"
    try:
        os.replace(path, dest)
    except OSError as e:  # already moved by a concurrent restart, etc.
        get_logger().warning(
            "could not quarantine %s (%s); skipping it", path, e
        )
        return None
    get_logger().warning(
        "quarantined corrupt checkpoint %s -> %s (%s)", path, dest, reason
    )
    return dest


def find_last_valid_checkpoint() -> str:
    """The newest checkpoint that passes manifest verification
    (resilience/manifest.verify_checkpoint), walking back over — and
    quarantining — corrupt or partial saves instead of crashing the
    resume on them. Raises ``NoValidCheckpointError`` when nothing
    survives.

    Joins any in-flight async commit first: a mid-run resume (the
    non-finite rollback path) must not race the committer for the very
    directory it is about to verify."""
    from distribuuuu_tpu.asyncplane import committer
    from distribuuuu_tpu.utils.logger import get_logger

    committer.join_commits()
    cands = _ordered_candidates()
    if not cands:
        raise NoValidCheckpointError(
            f"No checkpoints in {get_checkpoint_dir()}"
        )
    for i, path in enumerate(cands):
        ok, reason = manifest_lib.verify_checkpoint(path)
        if ok:
            if i:
                get_logger().warning(
                    "walked back over %d broken checkpoint(s) to %s", i, path
                )
            return path
        quarantine_checkpoint(path, reason)
    raise NoValidCheckpointError(
        f"{len(cands)} checkpoint(s) under {get_checkpoint_dir()} but none "
        "verified intact (all quarantined to *.corrupt); inspect the "
        "quarantined dirs or restart training from scratch"
    )


def has_checkpoint() -> bool:
    """Any checkpoint to resume from? (ref: utils.py:345-350)"""
    return bool(_scan(_NAME_PREFIX) or _scan(_PREEMPT_PREFIX))


def pack_opt_state(opt_state):
    """Optax state → a serialization-stable numbered-leaf dict.

    Orbax restores optax's namedtuple containers as plain dicts, which do
    NOT unflatten back into the namedtuple structure (and matching leaves
    by alphabetical-key order only works when every namedtuple's field
    order happens to be alphabetical — a silent-swap hazard for
    same-shaped leaves like Adam's mu/nu). Stored form: leaves numbered
    in the template's canonical jax flatten order, so the restore side
    rebuilds the exact structure from the LIVE optimizer's treedef with
    no dependence on container serialization at all."""
    leaves = jax.tree.leaves(opt_state)
    return {
        "format": "optax_leaves_v1",
        "leaves": {f"{i:05d}": leaf for i, leaf in enumerate(leaves)},
    }


def unpack_opt_state(template, stored):
    """Rebuild an optax state from ``pack_opt_state`` output (or a legacy
    structured save) against the live ``template``. Raises ValueError on
    any leaf-count/shape mismatch — the caller's graceful weights-only
    fallback (ref: utils.py:399-405) handles that."""
    if (
        isinstance(stored, dict)
        and stored.get("format") == "optax_leaves_v1"
    ):
        leaves = [stored["leaves"][k] for k in sorted(stored["leaves"])]
    else:
        # legacy structured form: flatten order matched the template only
        # when namedtuple field order was alphabetical. Only leaf COUNT and
        # SHAPES are verified below — same-shaped leaves from a
        # non-alphabetical namedtuple (none among current optax states)
        # would pass the check swapped; the v1 keyed format above is why
        # this path is legacy-only (ADVICE r4).
        leaves = jax.tree.leaves(stored)
    tmpl_leaves, tdef = jax.tree.flatten(template)
    if len(leaves) != len(tmpl_leaves):
        raise ValueError(
            f"optimizer state leaf count {len(leaves)} != live optimizer's "
            f"{len(tmpl_leaves)} (different OPTIM settings?)"
        )
    for i, (t, s) in enumerate(zip(tmpl_leaves, leaves)):
        t_shape = tuple(getattr(t, "shape", ()))
        if t_shape != tuple(np.shape(s)):
            raise ValueError(
                f"optimizer state leaf {i} shape {tuple(np.shape(s))} != "
                f"live {t_shape}"
            )
    return jax.tree.unflatten(tdef, leaves)


_state: dict = {"async_warned": False, "snapshot_warned": False,
                "solo": False}


def async_enabled() -> bool:
    """CHECKPOINT.ASYNC. Multi-host runs commit async too, behind the
    cross-host commit barrier (asyncplane/committer.py): per-host
    background committer threads rendezvous on payload durability and
    the manifest commits strictly last — unless ``ASYNC.SEQUENCER`` is
    off (the explicit escape hatch restoring the PR 10 single-host
    gate, warned once). A state tree sharded ACROSS hosts commits
    through the SHARDED protocol (``_save_sharded``, ISSUE 18) — each
    host writes its own shards under the barrier."""
    if not cfg.CHECKPOINT.ASYNC:
        return False
    if jax.process_count() > 1 and not cfg.ASYNC.SEQUENCER:
        if not _state.get("async_warned"):
            _state["async_warned"] = True
            from distribuuuu_tpu.utils.logger import get_logger

            get_logger().warning(
                "CHECKPOINT.ASYNC requested with ASYNC.SEQUENCER=False "
                "and process_count=%d — the cross-host commit barrier "
                "is part of the sequencer plane; falling back to "
                "synchronous collective checkpointing",
                jax.process_count(),
            )
        return False
    return True


def _solo_checkpointer():
    """An orbax checkpointer whose internal barriers span only THIS
    process. The multihost async commit writes the primary's
    host-snapshot payload SOLO (the peers attest durability through the
    cross-host commit barrier instead) — the default ``Checkpointer``
    would block at its own all-process sync, which the peers never
    reach."""
    return ocp.Checkpointer(
        ocp.PyTreeCheckpointHandler(),
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=jax.process_index(),
            active_processes={jax.process_index()},
        ),
    )


def _commit(path: str, payload: dict, epoch_cursor: int,
            post_commit=None, fsync_payload: bool = False) -> None:
    """The durable half of one save: orbax payload write, then the
    atomic manifest commit STRICTLY last, then any post-commit work
    (best side-write, preempt pruning, fault hooks). Runs on the caller
    thread (sync protocol) or the committer thread (async — which also
    fsyncs the payload before the marker: power-loss-safe ordering,
    free off the critical path)."""
    from distribuuuu_tpu.utils import faults

    ocp.PyTreeCheckpointer().save(path, payload, force=True)
    # the async-save crash window, injectable: SIGKILL lands here — after
    # every payload byte, before the commit marker (no-op unless FAULTS.*)
    faults.maybe_kill_mid_async_save(path, epoch_cursor)
    if jax.process_index() == 0:
        manifest_lib.write_manifest(path, payload, kind="full",
                                    epoch=epoch_cursor,
                                    fsync_payload=fsync_payload)
    if post_commit is not None:
        post_commit(payload)


def _save_full(
    path: str, state_tree: dict, epoch_cursor: int, best_acc1: float,
    extra: dict | None = None, post_commit=None, force_sync: bool = False,
):
    """The one save protocol: reference-shaped payload {epoch, state,
    best_acc1} (ref: utils.py:375-380), collective orbax write (every
    process participates; array shards written by their owners), then the
    manifest commit marker (primary only, atomic, AFTER the payload — a
    crash at any earlier point leaves a dir that verification rejects).

    With ``CHECKPOINT.ASYNC`` (and not ``force_sync``) the caller blocks
    only for the device→host snapshot; the commit runs on the background
    committer (asyncplane/committer.py), same protocol, same ordering —
    the manifest is still the last byte written."""
    import time as _time

    os.makedirs(get_checkpoint_dir(), exist_ok=True)
    payload = dict(state_tree)
    if "opt_state" in payload:
        payload["opt_state"] = pack_opt_state(payload["opt_state"])
    payload["epoch"] = np.int32(epoch_cursor)
    payload["best_acc1"] = np.float32(best_acc1)
    if extra:
        payload.update(extra)
    name = os.path.basename(path)
    if async_enabled() and not force_sync:
        from distribuuuu_tpu.asyncplane import committer

        # on-path cost: ONLY the host snapshot (donation-safe copy); the
        # span is what run_report attributes as trainer-blocked time.
        # Non-primary hosts of a multi-host run snapshot nothing — the
        # primary's host snapshot materializes the full tree; their
        # committer thread only runs the barrier protocol.
        multihost = jax.process_count() > 1
        snapshot_s = 0.0
        if multihost and committer.tree_is_cross_host_sharded(payload):
            # state sharded ACROSS hosts (ZeRO over a cross-host axis):
            # the SHARDED protocol (ISSUE 18) — each host snapshots the
            # shards it owns on-path and its committer thread writes
            # them under the cross-host barrier. This replaces the PR 11
            # degrade-to-sync; MultiHostSnapshotError remains the safety
            # valve for trees the shard layout cannot record.
            try:
                return _save_sharded(
                    path, payload, epoch_cursor, name, post_commit
                )
            except committer.MultiHostSnapshotError as e:
                if not _state.get("snapshot_warned"):
                    _state["snapshot_warned"] = True
                    from distribuuuu_tpu.utils.logger import get_logger

                    get_logger().warning(
                        "CHECKPOINT.ASYNC: the sharded save protocol "
                        "cannot record this tree (%s) — committing "
                        "synchronously (collective)", e,
                    )
                # the synchronous collective save, verbatim
                with telemetry_spans.span(
                    "ckpt_save", track="ckpt", ckpt=name,
                    epoch=int(epoch_cursor),
                ):
                    _commit(path, payload, epoch_cursor, post_commit)
                return path
        try:
            if not multihost or jax.process_index() == 0:
                t0 = _time.perf_counter()
                with telemetry_spans.span(
                    "ckpt_snapshot", track="ckpt", ckpt=name,
                    epoch=int(epoch_cursor),
                ):
                    payload = committer.snapshot_tree(payload)
                snapshot_s = _time.perf_counter() - t0
        except committer.MultiHostSnapshotError as e:
            # a host-local snapshot cannot represent this tree — the
            # save stays on the synchronous collective protocol
            if not _state.get("snapshot_warned"):
                _state["snapshot_warned"] = True
                from distribuuuu_tpu.utils.logger import get_logger

                get_logger().warning(
                    "CHECKPOINT.ASYNC: state is sharded across hosts "
                    "(%s) — committing synchronously (collective)", e,
                )
        else:
            if multihost:
                # only the primary's closures touch the payload — a
                # non-primary host must not pin references to device
                # buffers the next epoch's steps are about to donate
                bg_payload = payload if jax.process_index() == 0 else None

                def _post_solo(p):
                    # post-commit work (the best side-write) must use
                    # the solo checkpointer too — the peers are not in
                    # this code path to meet a collective barrier
                    if post_commit is None:
                        return
                    _state["solo"] = True
                    try:
                        post_commit(p)
                    finally:
                        _state["solo"] = False

                def _bg_multihost():
                    c0 = _time.perf_counter()
                    with telemetry_spans.span(
                        "ckpt_commit", track="ckpt", ckpt=name,
                        epoch=int(epoch_cursor),
                    ):
                        committer.multihost_commit(
                            path, bg_payload, epoch_cursor,
                            write_payload=lambda: _solo_checkpointer()
                            .save(path, bg_payload, force=True),
                            write_manifest=lambda: manifest_lib
                            .write_manifest(path, bg_payload, kind="full",
                                            epoch=epoch_cursor),
                            post_commit=_post_solo,
                        )
                    committer.emit_commit_record(
                        name, snapshot_s, _time.perf_counter() - c0
                    )

                committer.submit_commit(name, _bg_multihost)
                return path

            def _bg_commit():
                c0 = _time.perf_counter()
                with telemetry_spans.span(
                    "ckpt_commit", track="ckpt", ckpt=name,
                    epoch=int(epoch_cursor),
                ):
                    _commit(path, payload, epoch_cursor, post_commit,
                            fsync_payload=True)
                committer.emit_commit_record(
                    name, snapshot_s, _time.perf_counter() - c0
                )

            committer.submit_commit(name, _bg_commit)
            return path
    # span covers payload + manifest commit: the save duration an operator
    # budgets the preemption grace window against (tools/run_report.py
    # reports count/mean/max per rank from these)
    with telemetry_spans.span(
        "ckpt_save", track="ckpt", ckpt=name, epoch=int(epoch_cursor),
    ):
        _commit(path, payload, epoch_cursor, post_commit)
    return path


def _save_sharded(path: str, payload: dict, epoch_cursor: int, name: str,
                  post_commit=None) -> str:
    """The cross-host SHARDED async commit (ISSUE 18): generalizes the
    solo-checkpointer trick so every host's committer thread writes its
    OWN addressable shards under the existing barrier.

    On-path (this call): each host snapshots only the shards it owns
    (``replica_id == 0`` — donation-safe host copies; the union over
    hosts covers every element exactly once) and computes the manifest's
    tree/topology eagerly (metadata-only reads, safe on partially-
    addressed arrays — the committer thread never holds a full payload).
    Off-path: peers write ``shards_host<r>.npz`` + ``SHARDS_host<r>.json``
    between the barrier's OPEN wait and their arrival, the primary writes
    its own as the payload and commits MANIFEST.json strictly last — its
    digest walk covers every host's shard files, so a lost shard file
    fails verification and quarantines + walks back like any torn save.
    Bounded by ``ASYNC.BARRIER_TIMEOUT_S``; failures surface as
    ``AsyncCommitError`` at the next join, never silently."""
    import time as _time

    from distribuuuu_tpu.asyncplane import committer

    rank, world = jax.process_index(), jax.process_count()
    t0 = _time.perf_counter()
    with telemetry_spans.span(
        "ckpt_snapshot", track="ckpt", ckpt=name, epoch=int(epoch_cursor),
    ):
        owned, layout = committer.snapshot_host_shards(payload, rank)
        tree = manifest_lib.tree_spec(payload)
        topology = manifest_lib.world_topology(payload)
    snapshot_s = _time.perf_counter() - t0
    sharded_rec = {
        "hosts": world,
        "files": [f"shards_host{r}.npz" for r in range(world)],
    }

    def _write_mine():
        w0 = _time.perf_counter()
        nbytes = committer.write_host_shards(path, rank, world, owned,
                                             layout)
        committer.emit_shard_record(
            name, rank, world, len(owned), nbytes,
            _time.perf_counter() - w0,
        )

    def _post(p):
        # the sharded commit holds no full payload: post-commit work
        # (preempt pruning, fault hooks) runs with None; the best
        # side-write was handled up front (save_checkpoint)
        from distribuuuu_tpu.utils import faults

        faults.maybe_drop_shard_file(path, epoch_cursor, world)
        if post_commit is not None:
            post_commit(None)

    def _bg_sharded():
        c0 = _time.perf_counter()
        with telemetry_spans.span(
            "ckpt_commit", track="ckpt", ckpt=name, epoch=int(epoch_cursor),
        ):
            committer.multihost_commit(
                path, None, epoch_cursor,
                write_payload=_write_mine,
                write_manifest=lambda: manifest_lib.write_manifest(
                    path, None, kind="full", epoch=epoch_cursor,
                    tree=tree, topology=topology, sharded=sharded_rec,
                ),
                post_commit=_post,
                write_local=_write_mine,
                sharded=True,
            )
        committer.emit_commit_record(
            name, snapshot_s, _time.perf_counter() - c0
        )

    committer.submit_commit(name, _bg_sharded)
    return path


def prune_preempts(upto: int):
    """Delete preempt checkpoints with number ≤ ``upto`` — full
    params+optimizer snapshots would otherwise accumulate across
    preemptions (and a stale one would outrank the real checkpoints on
    every restart). Primary process only (plain filesystem op)."""
    if jax.process_index() != 0:
        return
    import shutil

    for e, p in _scan(_PREEMPT_PREFIX).items():
        if e <= upto:
            shutil.rmtree(p, ignore_errors=True)


def _write_best(params, batch_stats, epoch: int) -> str:
    """The weights-only ``best`` side-write: payload then manifest, same
    commit ordering as a full save. Accepts device OR host arrays. Runs
    solo (process-local orbax barriers) when invoked from the multihost
    async commit's post-commit hook — the peers are at the cross-host
    barrier, not inside orbax."""
    best = {"params": params, "batch_stats": batch_stats}
    ckptr = _solo_checkpointer() if _state.get("solo") else \
        ocp.PyTreeCheckpointer()
    ckptr.save(get_best_checkpoint(), best, force=True)
    if jax.process_index() == 0:
        manifest_lib.write_manifest(
            get_best_checkpoint(), best, kind="weights", epoch=epoch
        )
    return get_best_checkpoint()


def save_best_checkpoint(params, batch_stats, epoch: int) -> str:
    """Standalone best side-write for the concurrent-eval join path
    (the epoch checkpoint was already committed at the boundary; the
    is_best verdict arrives one epoch later). Async mode rides the
    committer — off the critical path, ordered after any in-flight full
    commit; ``params``/``batch_stats`` must then be snapshot copies the
    train loop will not donate (asyncplane/evalloop.device_snapshot)."""
    path = get_best_checkpoint()
    # the standalone async side-write rides the single-process committer
    # only (its caller, the concurrent-eval join, is single-process); a
    # multi-host best write goes through the collective path below
    if async_enabled() and jax.process_count() == 1:
        from distribuuuu_tpu.asyncplane import committer

        committer.submit_commit(
            _BEST_NAME, lambda: _write_best(params, batch_stats, epoch)
        )
        return path
    return _write_best(params, batch_stats, epoch)


def save_checkpoint(state_tree: dict, epoch: int, best_acc1: float, is_best: bool):
    """Save a full training checkpoint; side-write weights-only ``best``.

    The best side-write, preempt pruning, and the corrupt-checkpoint
    fault hook all run post-commit — after the manifest is durable, on
    the committer thread when ``CHECKPOINT.ASYNC`` (the payload handed
    to the closure is then the host snapshot, safe to re-save). Under
    CROSS-HOST sharding (the sharded async protocol) the commit holds no
    full payload: the weights-only best side-write then stays on the
    synchronous collective path, written up front — small and rare; the
    FULL state commit is what moved off-path (ISSUE 18)."""
    path = get_checkpoint(epoch)
    from distribuuuu_tpu.utils import faults

    best_up_front = False
    if is_best and jax.process_count() > 1 and async_enabled():
        from distribuuuu_tpu.asyncplane import committer

        if committer.tree_is_cross_host_sharded(state_tree):
            # collective on every host (is_best and the predicate are
            # host-invariant, so all hosts reach this together)
            best_up_front = True
            _write_best(state_tree["params"], state_tree["batch_stats"],
                        epoch)

    def _post(payload):
        if is_best and not best_up_front:
            _write_best(payload["params"], payload["batch_stats"], epoch)
        prune_preempts(epoch)
        faults.maybe_corrupt_checkpoint(path, epoch)  # no-op unless injected

    return _save_full(path, state_tree, epoch, best_acc1, post_commit=_post)


def encode_data_state(data_state: dict) -> np.ndarray:
    """Loader iterator state (``data/loader.Loader.state_dict`` — a
    JSON-able dict: epoch, global sample cursor, shuffle-order identity)
    as a uint8 array, so it rides the orbax pytree payload like any other
    leaf. The big-int shuffle-RNG state rules out a numeric pytree."""
    import json

    return np.frombuffer(
        json.dumps(data_state, sort_keys=True).encode(), np.uint8
    ).copy()


def decode_data_state(arr) -> dict | None:
    """Inverse of ``encode_data_state``; None on anything unreadable (a
    damaged cursor only costs the mid-epoch exactness, never the resume)."""
    import json

    try:
        return json.loads(np.asarray(arr, np.uint8).tobytes().decode())
    except (ValueError, UnicodeDecodeError):
        return None


def save_preempt_checkpoint(
    state_tree: dict, epoch: int, best_acc1: float,
    pending_eval: int | None = None,
    data_state: dict | None = None,
):
    """Mid-epoch checkpoint on preemption (utils/preempt.py).

    ``epoch`` is the epoch being interrupted; the stored cursor is
    ``epoch - 1`` so the normal resume path re-runs the interrupted epoch
    from this (strictly newer) params/optimizer state. ``pending_eval``
    marks a COMPLETED epoch whose validation was preempted — the resume
    path validates it and writes its real epoch checkpoint before
    continuing. ``data_state`` (shards pipeline, ``Loader.state_dict``)
    embeds the exact global sample cursor: the resumed epoch then
    CONTINUES at the next batch instead of re-running from batch 0 —
    trajectory-equivalent to the uninterrupted run. Same collective save
    protocol as ``save_checkpoint``.

    Always synchronous: the process exits right after, so there is
    nothing to overlap with — and the grace window must end with a
    durable manifest. Any in-flight async commit (the previous epoch
    boundary's) is drained FIRST, so the preempt save can never race it.
    """
    from distribuuuu_tpu.asyncplane import committer

    committer.join_commits(reason="preemption")
    extra = {}
    if pending_eval is not None:
        extra["pending_eval"] = np.int32(pending_eval)
    if data_state is not None:
        extra["data_state"] = encode_data_state(data_state)
    return _save_full(
        os.path.join(get_checkpoint_dir(), f"{_PREEMPT_PREFIX}{epoch:03d}"),
        state_tree, epoch - 1, best_acc1, extra or None, force_sync=True,
    )


def _is_managed_checkpoint(path: str) -> bool:
    """True for dirs this module owns (under the run's checkpoint dir with
    a recognized name) — the only ones quarantine may rename. A user-given
    MODEL.WEIGHTS path pointing anywhere else is never touched."""
    name = os.path.basename(os.path.normpath(path))
    return os.path.dirname(os.path.normpath(path)) == get_checkpoint_dir() and (
        bool(re.fullmatch(f"({_NAME_PREFIX}|{_PREEMPT_PREFIX})\\d+", name))
        or name == _BEST_NAME
    )


def load_checkpoint(path: str):
    """Restore a checkpoint as a numpy pytree (host-side; the trainer
    re-places arrays onto the mesh). Weights-only checkpoints return without
    ``opt_state``/``epoch`` keys and the caller falls back gracefully
    (ref semantics: utils.py:391-410).

    A failed restore raises ``CheckpointLoadError`` naming the path, the
    quarantine action taken, and how to resume from the previous intact
    save — instead of a raw tensorstore traceback.

    Sharded saves (the cross-host async protocol, ISSUE 18) restore
    through their recorded layout: every ``shards_host<r>.npz`` the
    ``SHARDS_host0.json`` manifest names reassembles into the full tree
    — elastically, since the result is plain host arrays the trainer
    re-places onto whatever mesh is live. A shard-count mismatch REFUSES
    (the error names the recorded sharding) rather than restoring a
    partial tree."""
    path = os.path.abspath(path)
    from distribuuuu_tpu.asyncplane import committer

    try:
        with telemetry_spans.span(
            "ckpt_restore", track="ckpt", ckpt=os.path.basename(path)
        ):
            if committer.sharded_layout_present(path):
                return committer.read_sharded_checkpoint(path)
            return ocp.PyTreeCheckpointer().restore(path)
    except Exception as e:  # orbax/tensorstore raise many concrete types
        if _is_managed_checkpoint(path):
            dest = quarantine_checkpoint(path, f"restore failed: {e}")
            action = (
                f"quarantined to {dest}" if dest
                else "quarantine skipped (non-primary process or rename failed)"
            )
        else:
            action = "no quarantine (path is outside this run's checkpoint dir)"
        raise CheckpointLoadError(
            f"failed to restore checkpoint {path} "
            f"({type(e).__name__}: {e}). Action taken: {action}. "
            "To resume from the previous intact save, rerun with "
            "TRAIN.AUTO_RESUME True (auto-resume walks back via "
            "find_last_valid_checkpoint), or point at it explicitly: "
            "python train_net.py --cfg <your.yaml> MODEL.WEIGHTS "
            f"{get_checkpoint_dir()}/ckpt_ep_NNN"
        ) from e
