"""Checkpoint/auto-resume on orbax (ref: /root/reference/distribuuuu/utils.py:319-410).

Semantics mirrored: epoch-granular saves named ``ckpt_ep_{epoch:03d}`` under
``{OUT_DIR}/checkpoints`` (ref: utils.py:320-334), auto-resume picks the
lexicographically-last epoch dir (ref: utils.py:337-342), keep-all policy
plus a weights-only ``best`` checkpoint on a new best metric (ref:
utils.py:385-387), optimizer-state restore optional with graceful fallback
(ref: utils.py:399-405), and weights-only checkpoints load cleanly
(ref: utils.py:406-407).

Formats differ by design: orbax OCDBT directories instead of torch pickles —
multi-host-safe (every process participates; array shards are written by
their owners) and framework-portable.
"""

from __future__ import annotations

import os
import re

import jax
import numpy as np
import orbax.checkpoint as ocp

from distribuuuu_tpu.config import cfg

_NAME_PREFIX = "ckpt_ep_"
_BEST_NAME = "best"
# mid-epoch checkpoint written on preemption (utils/preempt.py); the number
# is the INTERRUPTED epoch, so preempt_ep_e outranks ckpt_ep_{e-1} (it holds
# strictly newer optimizer progress) and is superseded by ckpt_ep_e.
_PREEMPT_PREFIX = "preempt_ep_"


def get_checkpoint_dir() -> str:
    # Absolute: orbax/tensorstore rejects relative paths.
    return os.path.abspath(os.path.join(cfg.OUT_DIR, "checkpoints"))


def get_checkpoint(epoch: int) -> str:
    """Path for an epoch's checkpoint (ref naming: utils.py:320-334)."""
    return os.path.join(get_checkpoint_dir(), f"{_NAME_PREFIX}{epoch:03d}")


def get_best_checkpoint() -> str:
    return os.path.join(get_checkpoint_dir(), _BEST_NAME)


def _scan(prefix: str) -> dict[int, str]:
    d = get_checkpoint_dir()
    if not os.path.isdir(d):
        return {}
    out = {}
    for f in os.listdir(d):
        if re.fullmatch(prefix + r"\d+", f) and os.path.isdir(
            os.path.join(d, f)
        ):
            out[int(f[len(prefix):])] = os.path.join(d, f)
    return out


def get_last_checkpoint() -> str:
    """Latest resumable checkpoint (ref numeric-order pick: utils.py:337-342),
    extended for preemption: ``preempt_ep_e`` (mid-epoch state of an
    interrupted epoch e) is preferred over ``ckpt_ep_{e-1}`` and ignored as
    stale once ``ckpt_ep_e`` exists."""
    epochs = _scan(_NAME_PREFIX)
    preempts = _scan(_PREEMPT_PREFIX)
    last_epoch = max(epochs) if epochs else -1
    live_preempts = {e: p for e, p in preempts.items() if e > last_epoch}
    if live_preempts:
        return live_preempts[max(live_preempts)]
    if epochs:
        return epochs[last_epoch]
    raise FileNotFoundError(f"No checkpoints in {get_checkpoint_dir()}")


def has_checkpoint() -> bool:
    """Any checkpoint to resume from? (ref: utils.py:345-350)"""
    return bool(_scan(_NAME_PREFIX) or _scan(_PREEMPT_PREFIX))


def pack_opt_state(opt_state):
    """Optax state → a serialization-stable numbered-leaf dict.

    Orbax restores optax's namedtuple containers as plain dicts, which do
    NOT unflatten back into the namedtuple structure (and matching leaves
    by alphabetical-key order only works when every namedtuple's field
    order happens to be alphabetical — a silent-swap hazard for
    same-shaped leaves like Adam's mu/nu). Stored form: leaves numbered
    in the template's canonical jax flatten order, so the restore side
    rebuilds the exact structure from the LIVE optimizer's treedef with
    no dependence on container serialization at all."""
    leaves = jax.tree.leaves(opt_state)
    return {
        "format": "optax_leaves_v1",
        "leaves": {f"{i:05d}": leaf for i, leaf in enumerate(leaves)},
    }


def unpack_opt_state(template, stored):
    """Rebuild an optax state from ``pack_opt_state`` output (or a legacy
    structured save) against the live ``template``. Raises ValueError on
    any leaf-count/shape mismatch — the caller's graceful weights-only
    fallback (ref: utils.py:399-405) handles that."""
    if (
        isinstance(stored, dict)
        and stored.get("format") == "optax_leaves_v1"
    ):
        leaves = [stored["leaves"][k] for k in sorted(stored["leaves"])]
    else:
        # legacy structured form: flatten order matched the template only
        # when namedtuple field order was alphabetical. Only leaf COUNT and
        # SHAPES are verified below — same-shaped leaves from a
        # non-alphabetical namedtuple (none among current optax states)
        # would pass the check swapped; the v1 keyed format above is why
        # this path is legacy-only (ADVICE r4).
        leaves = jax.tree.leaves(stored)
    tmpl_leaves, tdef = jax.tree.flatten(template)
    if len(leaves) != len(tmpl_leaves):
        raise ValueError(
            f"optimizer state leaf count {len(leaves)} != live optimizer's "
            f"{len(tmpl_leaves)} (different OPTIM settings?)"
        )
    for i, (t, s) in enumerate(zip(tmpl_leaves, leaves)):
        t_shape = tuple(getattr(t, "shape", ()))
        if t_shape != tuple(np.shape(s)):
            raise ValueError(
                f"optimizer state leaf {i} shape {tuple(np.shape(s))} != "
                f"live {t_shape}"
            )
    return jax.tree.unflatten(tdef, leaves)


def _save_full(
    path: str, state_tree: dict, epoch_cursor: int, best_acc1: float,
    extra: dict | None = None,
):
    """The one save protocol: reference-shaped payload {epoch, state,
    best_acc1} (ref: utils.py:375-380), collective orbax write (every
    process participates; array shards written by their owners)."""
    os.makedirs(get_checkpoint_dir(), exist_ok=True)
    payload = dict(state_tree)
    if "opt_state" in payload:
        payload["opt_state"] = pack_opt_state(payload["opt_state"])
    payload["epoch"] = np.int32(epoch_cursor)
    payload["best_acc1"] = np.float32(best_acc1)
    if extra:
        payload.update(extra)
    ocp.PyTreeCheckpointer().save(path, payload, force=True)
    return path


def prune_preempts(upto: int):
    """Delete preempt checkpoints with number ≤ ``upto`` — full
    params+optimizer snapshots would otherwise accumulate across
    preemptions (and a stale one would outrank the real checkpoints on
    every restart). Primary process only (plain filesystem op)."""
    if jax.process_index() != 0:
        return
    import shutil

    for e, p in _scan(_PREEMPT_PREFIX).items():
        if e <= upto:
            shutil.rmtree(p, ignore_errors=True)


def save_checkpoint(state_tree: dict, epoch: int, best_acc1: float, is_best: bool):
    """Save a full training checkpoint; side-write weights-only ``best``."""
    path = _save_full(get_checkpoint(epoch), state_tree, epoch, best_acc1)
    if is_best:
        best = {"params": state_tree["params"], "batch_stats": state_tree["batch_stats"]}
        ocp.PyTreeCheckpointer().save(get_best_checkpoint(), best, force=True)
    prune_preempts(epoch)
    return path


def save_preempt_checkpoint(
    state_tree: dict, epoch: int, best_acc1: float,
    pending_eval: int | None = None,
):
    """Mid-epoch checkpoint on preemption (utils/preempt.py).

    ``epoch`` is the epoch being interrupted; the stored cursor is
    ``epoch - 1`` so the normal resume path re-runs the interrupted epoch
    from this (strictly newer) params/optimizer state. ``pending_eval``
    marks a COMPLETED epoch whose validation was preempted — the resume
    path validates it and writes its real epoch checkpoint before
    continuing. Same collective save protocol as ``save_checkpoint``.
    """
    extra = (
        {"pending_eval": np.int32(pending_eval)}
        if pending_eval is not None
        else None
    )
    return _save_full(
        os.path.join(get_checkpoint_dir(), f"{_PREEMPT_PREFIX}{epoch:03d}"),
        state_tree, epoch - 1, best_acc1, extra,
    )


def load_checkpoint(path: str):
    """Restore a checkpoint as a numpy pytree (host-side; the trainer
    re-places arrays onto the mesh). Weights-only checkpoints return without
    ``opt_state``/``epoch`` keys and the caller falls back gracefully
    (ref semantics: utils.py:391-410)."""
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(os.path.abspath(path))
    return restored
