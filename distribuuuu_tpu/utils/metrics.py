"""Classification metrics, computed in-graph.

The reference computes top-k accuracy on device then immediately ``.item()``s
and all-reduces every step (ref: /root/reference/distribuuuu/trainer.py:50-55,
utils.py:265-277) — a per-step host sync. Here ``accuracy`` is a pure jax
function meant to be called *inside* the jitted step over the global batch,
so cross-replica reduction is free (the batch is already global) and the
host only fetches at PRINT_FREQ.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def accuracy(logits, targets, topk=(1,)):
    """Top-k accuracy percentages over the (global) batch
    (semantics: utils.py:265-277).

    Args:
        logits: [..., classes] float array — ``[batch, classes]`` for the
            image zoo, ``[batch, seq, vocab]`` for the LM (every leading
            dim is an example dim; the mean runs over all of them, so the
            LM reading is next-token accuracy per token).
        targets: [...] int class labels, matching the leading dims.
        topk: tuple of k values, each ≤ the class count (the trainer clamps
            once via ``effective_topk``; see trainer.py).
    Returns:
        list of scalar percentages, one per k.
    """
    maxk = max(topk)
    assert maxk <= logits.shape[-1], (
        f"top-{maxk} needs ≥{maxk} classes, got {logits.shape[-1]}"
    )
    _, pred = jax.lax.top_k(logits, maxk)  # [..., maxk], ordered
    hits = pred == targets[..., None]
    return [
        hits[..., :k].any(axis=-1).mean(dtype=jnp.float32) * 100.0
        for k in topk
    ]


def cross_entropy(logits, targets):
    """Mean softmax cross-entropy with integer labels (≙ nn.CrossEntropyLoss,
    ref: trainer.py:139). Loss math in fp32 regardless of a low-precision
    compute dtype — promoted, not hard-cast, so f64 logits (the x64
    equivalence tests) are not re-rounded at the loss boundary.

    Leading dims are generic: ``[B, C]`` image logits and ``[B, S, V]``
    per-token LM logits both reduce to ONE mean over every example dim —
    the next-token CE task head is this same function, no LM-specific
    loss path exists (ISSUE 12)."""
    from distribuuuu_tpu.models.layers import head_dtype

    logp = jax.nn.log_softmax(logits.astype(head_dtype(logits.dtype)), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def count_parameters(params):
    """(params in millions, fp32 megabytes) — ref: utils.py:353-357."""
    n = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(params))
    return n / 1e6, n * 4 / 2**20
