"""Preemption-safe training (TPU-native extension; no reference analogue).

TPU slices are routinely preempted — the scheduler delivers SIGTERM with a
grace window. The reference's whole recovery story is restart + epoch
auto-resume (ref: /root/reference/distribuuuu/trainer.py:143-149), which
loses every step of the interrupted epoch. Here the trainer installs a
signal handler; when preemption is signaled, the epoch loop stops at the
next dispatch boundary and writes a mid-epoch checkpoint
(``utils/checkpoint.py::save_preempt_checkpoint``) that auto-resume
prefers — the interrupted epoch is re-run, but from the preserved
params/optimizer state rather than the last epoch boundary.

Multi-host: each host may receive the signal at a different moment, and
the checkpoint save is a collective — so the loop consults
``requested_global()``, an OR of the per-host flags via
``process_allgather``, guaranteeing every process leaves the epoch at the
same boundary. At world size 1 this is a local bool check (free).
"""

from __future__ import annotations

import signal

import jax

_state = {"requested": False, "installed": False}


def install(signals=(signal.SIGTERM,)) -> None:
    """Install the preemption handler (idempotent). Call from the main
    thread before the epoch loop (the trainer does this when
    ``TRAIN.PREEMPT_SAVE`` is on).

    CHAINS to any previously installed handler instead of clobbering it:
    multiple subsystems legitimately watch SIGTERM in one process (the
    serve drain in ``serve/admission.py`` registers it too), and before
    this fix whichever installed last silently disabled the other. A
    re-install is detected by the marker attribute and left alone — the
    chain never loops back into itself."""

    def _make(prev):
        def handler(signum, frame):
            _state["requested"] = True
            if callable(prev):
                prev(signum, frame)

        handler._dtpu_preempt = True
        return handler

    for s in signals:
        prev = signal.getsignal(s)
        if getattr(prev, "_dtpu_preempt", False):
            continue  # already ours (with its chain) — idempotent
        if prev in (signal.SIG_DFL, signal.SIG_IGN, None):
            prev = None  # nothing meaningful to chain to
        signal.signal(s, _make(prev))
    _state["installed"] = True


def requested_local() -> bool:
    return _state["requested"]


def requested_global() -> bool:
    """True iff ANY process has seen the signal — all processes agree on
    the answer, so the collective checkpoint save lines up."""
    if jax.process_count() == 1:
        return _state["requested"]
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.int32(1 if _state["requested"] else 0)
    )
    return bool(np.asarray(flags).sum() > 0)


def reset() -> None:
    """Clear the flag (tests; also after a handled preemption save)."""
    _state["requested"] = False
