"""Ingest PyTorch checkpoints (reference-trained weights) into flax trees.

The reference ships a pretrained-weight path: a URL zoo for ResNet
(ref: /root/reference/distribuuuu/models/resnet.py:23-33,309-311) and
DenseNet with a legacy-key remap (ref: densenet.py:266-282), plus
``MODEL.WEIGHTS`` checkpoint loading (ref: trainer.py:204-205). This module
is the TPU-native equivalent: it converts a torch ``state_dict`` (torchvision
naming, or the reference's training checkpoints ``{state_dict: ...}``) into
this framework's ``{"params": ..., "batch_stats": ...}`` pytrees, so users
can bring reference-trained weights to TPU.

Strategy: align by *kind and definition order*, not by name. Both frameworks
enumerate modules in definition order (torch ``state_dict`` insertion order;
flax init-dict insertion order). Convs, BatchNorms and Linears are each
matched in that order per kind, which is invariant to naming schemes and to
conv/BN interleaving differences. Every pairing is shape-checked after
layout transposition, so any misalignment fails loudly:

  - conv weight  [O, I/g, kh, kw]  →  kernel [kh, kw, I/g, O]
  - linear weight [O, I]           →  kernel [I, O]
  - bn {weight, bias, running_mean, running_var}
        → params {scale, bias} + batch_stats {mean, var}
  - embed (everything else: learned position/relative embeddings — botnet's
    rel_height/rel_width, ViT's pos_embed) → copied 1:1 by order, exact
    shape match required (embeddings share layout across frameworks)

Torch is only needed when reading ``.pth`` pickles; a pre-extracted numpy
``state_dict``-style mapping works without torch installed.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

__all__ = [
    "load_torch_state_dict",
    "convert_state_dict",
    "is_torch_checkpoint",
    "ordered_variables",
]

_TORCH_SUFFIXES = (".pth", ".pt", ".pth.tar", ".pt.tar", ".bin")


def is_torch_checkpoint(path: str) -> bool:
    return any(path.endswith(s) for s in _TORCH_SUFFIXES)


def ordered_variables(model, im_size: int = 64):
    """Init ``model`` eagerly to recover *definition-ordered* variable dicts.

    Conversion aligns modules by definition order, which plain ``init``
    preserves via dict insertion order — but anything that round-trips
    through a jax transform (jit, eval_shape) canonicalizes pytree dict keys
    to sorted order and loses it. Always feed conversion from here.
    """
    import jax
    import jax.numpy as jnp

    return model.init(
        jax.random.key(0), jnp.ones((1, im_size, im_size, 3)), train=False
    )


def load_torch_state_dict(path: str) -> dict[str, np.ndarray]:
    """Read a torch checkpoint file → {name: numpy array}, insertion-ordered.

    Accepts either a bare ``state_dict`` or the reference trainer's
    checkpoint dict ``{"state_dict": ..., ...}`` (ref: utils.py:375-380);
    DDP ``module.`` prefixes are stripped (ref: utils.py:360-363).
    """
    import torch  # CPU build is sufficient; only used as a pickle reader

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    out: dict[str, np.ndarray] = {}
    for k, v in obj.items():
        if k.startswith("module."):
            k = k[len("module."):]
        out[k] = np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)
    return out


# ---------------------------------------------------------------------------
# torch side: group the flat state_dict into per-module slots, in order
# ---------------------------------------------------------------------------


def _torch_slots(state_dict: Mapping[str, np.ndarray]):
    """Yield ('conv'|'linear'|'bn'|'embed', dict) per module, in definition
    order. 'embed' entries are per-LEAF (one tensor each) because the flax
    walk sees loose embedding params individually."""
    groups: dict[str, dict[str, np.ndarray]] = {}
    order: list[str] = []
    for key, val in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        prefix, _, leaf = key.rpartition(".")
        if prefix not in groups:
            groups[prefix] = {}
            order.append(prefix)
        groups[prefix][leaf] = np.asarray(val)
    for prefix in order:
        g = groups[prefix]
        if "running_mean" in g:
            yield "bn", prefix, g
        elif "weight" in g and g["weight"].ndim == 4:
            yield "conv", prefix, g
        elif "weight" in g and g["weight"].ndim == 2:
            yield "linear", prefix, g
        elif "weight" in g and g["weight"].ndim == 1:
            # 1D weight without running stats: an affine norm layer saved
            # without stats — treat as bn with zero/one stats
            yield "bn", prefix, g
        else:
            # loose learned tensors (position / relative embeddings):
            # one slot per leaf, in insertion order
            for leaf, val in g.items():
                yield "embed", f"{prefix}.{leaf}" if prefix else leaf, {
                    leaf: val
                }


# ---------------------------------------------------------------------------
# flax side: walk params/batch_stats in insertion (definition) order
# ---------------------------------------------------------------------------


def _is_leaf_dict(d) -> bool:
    return isinstance(d, Mapping) and all(
        not isinstance(v, Mapping) for v in d.values()
    )


def _unwrap(v):
    """Strip flax AxisMetadata boxes (nn.with_partitioning wraps kernels in
    Partitioned, whose array lives in ``.value``)."""
    return v.value if hasattr(v, "value") and not isinstance(v, np.ndarray) else v


def _flax_slots(params: Mapping, batch_stats: Mapping):
    """Yield ('conv'|'linear'|'bn', path, leaves) in definition order.

    ``leaves`` maps leaf name → array for shape reference. Walks the params
    dict in insertion order (flax init preserves module-definition order);
    batch_stats are joined by path for BN modules.
    """

    def stats_at(path):
        node = batch_stats
        for p in path:
            if not isinstance(node, Mapping) or p not in node:
                return None
            node = node[p]
        return node

    def walk(node, path):
        if _is_leaf_dict(node):
            node = {k: _unwrap(v) for k, v in node.items()}
            names = set(node.keys())
            if "scale" in names or (names == {"bias"} and stats_at(path)):
                st = stats_at(path) or {}
                yield "bn", path, {**node, **{k: _unwrap(v) for k, v in st.items()}}
                return
            if "kernel" in names:
                kind = "conv" if np.ndim(node["kernel"]) == 4 else "linear"
                yield kind, path, dict(node)
                return
            # learned embeddings saved as a leaf dict: one slot per leaf
            for key, v in node.items():
                yield "embed", path + (key,), {key: v}
            return
        for key, child in node.items():
            if isinstance(child, Mapping):
                yield from walk(child, path + (key,))
            else:
                # loose param directly on a module (pos_embed, rel_height…)
                yield "embed", path + (key,), {key: _unwrap(child)}

    yield from walk(params, ())


# ---------------------------------------------------------------------------
# conversion
# ---------------------------------------------------------------------------


def _set_in(tree: dict, path: tuple, leaf: str, value: np.ndarray):
    node = tree
    for p in path:
        node = node.setdefault(p, {})
    node[leaf] = value


def convert_state_dict(
    state_dict: Mapping[str, np.ndarray],
    variables: Mapping[str, Any],
) -> dict[str, Any]:
    """Convert a torch ``state_dict`` to ``{"params", "batch_stats"}`` trees
    shaped like ``variables`` (a flax ``model.init`` result or its
    ``eval_shape``). Raises ``ValueError`` on any kind/shape mismatch.
    """
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    queues: dict[str, list] = {"conv": [], "linear": [], "bn": [], "embed": []}
    for kind, prefix, group in _torch_slots(state_dict):
        queues[kind].append((prefix, group))

    counts = {k: 0 for k in queues}
    new_params: dict = {}
    new_stats: dict = {}

    for kind, path, leaves in _flax_slots(params, batch_stats):
        if counts[kind] >= len(queues[kind]):
            raise ValueError(
                f"torch checkpoint ran out of {kind} modules at flax path "
                f"{'/'.join(path)} (needed >{counts[kind]})"
            )
        prefix, group = queues[kind][counts[kind]]
        counts[kind] += 1

        def check(name, got, want_shape):
            if tuple(got.shape) != tuple(want_shape):
                raise ValueError(
                    f"shape mismatch at flax {'/'.join(path)} ↔ torch "
                    f"'{prefix}' [{name}]: torch {tuple(got.shape)} vs flax "
                    f"{tuple(want_shape)} — architecture/order mismatch"
                )

        if kind == "embed":
            # path ends with the leaf name; embeddings copy 1:1 (no layout
            # transpose — both frameworks store them identically). The
            # trailing names must MATCH: same-shape embeddings (botnet's
            # rel_height/rel_width on a square grid) would otherwise swap
            # silently, and this module's contract is to fail loudly.
            (leaf_name, want) = next(iter(leaves.items()))
            (t_leaf, got) = next(iter(group.items()))
            if t_leaf != leaf_name:
                raise ValueError(
                    f"embedding name mismatch at flax {'/'.join(path)} ↔ "
                    f"torch '{prefix}': '{leaf_name}' vs '{t_leaf}' — if the "
                    "source checkpoint uses different names, rename its "
                    "keys to match before ingesting"
                )
            check(t_leaf, got, np.shape(want))
            _set_in(new_params, path[:-1], path[-1], np.asarray(got))
        elif kind == "conv":
            w = np.transpose(group["weight"], (2, 3, 1, 0))  # OIHW → HWIO
            check("weight", w, np.shape(leaves["kernel"]))
            _set_in(new_params, path, "kernel", np.ascontiguousarray(w))
            if "bias" in leaves:
                check("bias", group["bias"], np.shape(leaves["bias"]))
                _set_in(new_params, path, "bias", group["bias"])
        elif kind == "linear":
            w = np.transpose(group["weight"], (1, 0))  # OI → IO
            check("weight", w, np.shape(leaves["kernel"]))
            _set_in(new_params, path, "kernel", np.ascontiguousarray(w))
            if "bias" in leaves:
                check("bias", group["bias"], np.shape(leaves["bias"]))
                _set_in(new_params, path, "bias", group["bias"])
        else:  # bn
            n = group.get("weight", group.get("scale"))
            if "scale" in leaves:
                check("weight", n, np.shape(leaves["scale"]))
                _set_in(new_params, path, "scale", n)
            check("bias", group["bias"], np.shape(leaves["bias"]))
            _set_in(new_params, path, "bias", group["bias"])
            if "mean" in leaves:
                mean = group.get("running_mean", np.zeros_like(group["bias"]))
                var = group.get("running_var", np.ones_like(group["bias"]))
                check("running_mean", mean, np.shape(leaves["mean"]))
                check("running_var", var, np.shape(leaves["var"]))
                _set_in(new_stats, path, "mean", mean)
                _set_in(new_stats, path, "var", var)

    leftovers = {k: len(q) - counts[k] for k, q in queues.items() if len(q) > counts[k]}
    if leftovers:
        detail = {
            k: [p for p, _ in queues[k][counts[k] : counts[k] + 3]]
            for k in leftovers
        }
        raise ValueError(
            f"torch checkpoint has unconsumed modules {leftovers} "
            f"(first unmatched: {detail}) — architecture mismatch"
        )
    return {"params": new_params, "batch_stats": new_stats}
