"""Structured metrics sink: one JSON object per line in
``{OUT_DIR}/metrics.jsonl``.

The reference's observability is text logs only (loguru file + stderr,
ref: /root/reference/distribuuuu/utils.py:71-82; SURVEY.md §5.5). This adds
the machine-readable channel: every train print-window, eval summary, and
epoch boundary lands as a JSON record — plot, diff, or regression-track a
run with ``jq``/pandas, no tensorboard dependency.

Module-level singleton like ``utils/logger.py`` (``setup`` in
``train_model``, then ``log()`` from anywhere; a no-op until set up and on
non-primary processes), so call sites need no signature changes.
"""

from __future__ import annotations

import json
import os
import time

from distribuuuu_tpu.telemetry import spans

_sink = {"f": None}


def setup_metrics_log(out_dir: str, primary: bool = True) -> None:
    """Open (append) the sink on the primary process; close any previous."""
    close_metrics_log()
    if not primary:
        return
    os.makedirs(out_dir, exist_ok=True)
    _sink["f"] = open(
        os.path.join(out_dir, "metrics.jsonl"), "a", buffering=1
    )


# Stage-boundary timestamp fields of one kind="timeline" record, in
# pipeline order (all values are time.perf_counter() seconds — one
# monotonic clock per process, so records are differenced, never read as
# wall-clock dates):
#   submit        batch assembly submitted to the loader worker pool
#   dec0 / dec1   decode+augment interval (worker thread; dataset access)
#   asm1          host batch assembled (stack/pad/dict done)
#   get0 / get1   consumer blocked waiting on the host batch
#   put0 / put1   H2D dispatch (shard_batch/device_put) interval
#   step0 / step1 compiled step dispatch interval
# Worker-side intervals (submit..asm1) overlap each other and the
# consumer; consumer-side intervals (get/put/step) are disjoint, so their
# sums — plus the residual — partition the epoch wall time exactly
# (tools/overlap_report.py does that attribution).
TIMELINE_STAGES = (
    "submit", "dec0", "dec1", "asm1",
    "get0", "get1", "put0", "put1", "step0", "step1",
)
TIMELINE_SCHEMA = 1


def timeline_log(phase: str, epoch: int, batch: int, n: int, **stamps) -> None:
    """One per-batch timeline record: ``phase`` ("train"/"eval"), 1-based
    ``epoch``, 0-based ``batch`` index, ``n`` images in the batch, and the
    TIMELINE_STAGES timestamps present in ``stamps`` (µs-rounded). No-op
    when the sink is not set up — non-primary processes and library use."""
    if _sink["f"] is None:
        return
    rec = {k: round(float(stamps[k]), 6) for k in TIMELINE_STAGES if k in stamps}
    metrics_log(
        "timeline", v=TIMELINE_SCHEMA, phase=phase, epoch=epoch, batch=batch,
        n=n, **rec,
    )


def metrics_log(kind: str, **fields) -> None:
    """Append one record: {"t": unix_time, "kind": kind, **fields}.
    No-op when the sink is not set up (non-primary, tests, library use).

    Every record is additionally mirrored to the per-rank telemetry sink
    when one is open (telemetry/spans.py) — BEFORE the primary gate, so
    rank-local kinds (stall, data_error, nonfinite) survive on ranks > 0
    instead of being silently dropped; before the telemetry layer the
    supervisor's records simply vanished on every non-primary process.
    ``timeline`` records are not mirrored (they stay primary-only here;
    the trace exporter reads them from metrics.jsonl directly)."""
    spans.mirror_event(kind, fields)
    f = _sink["f"]
    if f is None:
        return
    rec = {"t": round(time.time(), 3), "kind": kind}
    rec.update(fields)
    f.write(json.dumps(rec) + "\n")


def close_metrics_log() -> None:
    if _sink["f"] is not None:
        _sink["f"].close()
        _sink["f"] = None
