"""Structured metrics sink: one JSON object per line in
``{OUT_DIR}/metrics.jsonl``.

The reference's observability is text logs only (loguru file + stderr,
ref: /root/reference/distribuuuu/utils.py:71-82; SURVEY.md §5.5). This adds
the machine-readable channel: every train print-window, eval summary, and
epoch boundary lands as a JSON record — plot, diff, or regression-track a
run with ``jq``/pandas, no tensorboard dependency.

Module-level singleton like ``utils/logger.py`` (``setup`` in
``train_model``, then ``log()`` from anywhere; a no-op until set up and on
non-primary processes), so call sites need no signature changes.
"""

from __future__ import annotations

import json
import os
import time

_sink = {"f": None}


def setup_metrics_log(out_dir: str, primary: bool = True) -> None:
    """Open (append) the sink on the primary process; close any previous."""
    close_metrics_log()
    if not primary:
        return
    os.makedirs(out_dir, exist_ok=True)
    _sink["f"] = open(
        os.path.join(out_dir, "metrics.jsonl"), "a", buffering=1
    )


def metrics_log(kind: str, **fields) -> None:
    """Append one record: {"t": unix_time, "kind": kind, **fields}.
    No-op when the sink is not set up (non-primary, tests, library use)."""
    f = _sink["f"]
    if f is None:
        return
    rec = {"t": round(time.time(), 3), "kind": kind}
    rec.update(fields)
    f.write(json.dumps(rec) + "\n")


def close_metrics_log() -> None:
    if _sink["f"] is not None:
        _sink["f"].close()
        _sink["f"] = None
