"""Configuration system.

A from-scratch, yacs-compatible ``CfgNode`` built on pyyaml, providing the
same public surface the reference uses (ref: /root/reference/distribuuuu/
config.py:7-100): an attribute-access config tree with ``freeze``/``defrost``,
``merge_from_file`` (YAML), ``merge_from_list`` (dotted-key CLI overrides),
``dump``, and type-checked merges — so every shipped ``config/*.yaml`` parses
unchanged.

TPU-specific additions live under new top-level keys (``DEVICE``, ``MESH``,
``DATA``) which default sensibly and never collide with the reference schema.
"""

from __future__ import annotations

import argparse
import copy
import os
import sys

import yaml

__all__ = ["CfgNode", "cfg", "load_cfg_fom_args", "merge_from_file", "dump_cfg", "reset_cfg"]


_VALID_TYPES = (tuple, list, str, int, float, bool, type(None))


class CfgNode(dict):
    """A dict subclass with attribute access, freezing, and typed merges.

    API-compatible with ``yacs.config.CfgNode`` for the subset the reference
    framework exercises (ref: config.py usage + train_net.py:8 freeze).
    """

    _FROZEN = "__frozen__"

    def __init__(self, init_dict=None):
        init_dict = {} if init_dict is None else init_dict
        super().__init__()
        object.__setattr__(self, CfgNode._FROZEN, False)
        for k, v in init_dict.items():
            if isinstance(v, dict) and not isinstance(v, CfgNode):
                v = CfgNode(v)
            dict.__setitem__(self, k, v)

    # -- attribute access ---------------------------------------------------
    def __getattr__(self, name):
        if name in self:
            return self[name]
        raise AttributeError(f"Config key not found: {name}")

    def __setattr__(self, name, value):
        if self.is_frozen():
            raise AttributeError(
                f"Attempted to set {name} to {value}, but CfgNode is frozen"
            )
        dict.__setitem__(self, name, value)

    def __setitem__(self, name, value):
        if self.is_frozen():
            raise AttributeError(
                f"Attempted to set {name} to {value}, but CfgNode is frozen"
            )
        dict.__setitem__(self, name, value)

    # -- freezing -----------------------------------------------------------
    def is_frozen(self):
        return object.__getattribute__(self, CfgNode._FROZEN)

    def freeze(self):
        self._set_frozen(True)

    def defrost(self):
        self._set_frozen(False)

    def _set_frozen(self, frozen):
        object.__setattr__(self, CfgNode._FROZEN, frozen)
        for v in self.values():
            if isinstance(v, CfgNode):
                v._set_frozen(frozen)

    # -- merging ------------------------------------------------------------
    def clone(self):
        return copy.deepcopy(self)

    def merge_from_file(self, cfg_filename):
        with open(cfg_filename, "r") as f:
            loaded = yaml.safe_load(f)
        if loaded is None:
            return
        self._merge_dict(CfgNode(loaded), [])

    def merge_from_other_cfg(self, other):
        self._merge_dict(other, [])

    def merge_from_list(self, cfg_list):
        if len(cfg_list) % 2 != 0:
            raise ValueError(
                f"Override list has odd length: {cfg_list}; it must be (key, value) pairs"
            )
        for full_key, v in zip(cfg_list[0::2], cfg_list[1::2]):
            d = self
            key_parts = full_key.split(".")
            for sub in key_parts[:-1]:
                if sub not in d:
                    raise KeyError(f"Non-existent key: {full_key}")
                d = d[sub]
            sub = key_parts[-1]
            if sub not in d:
                raise KeyError(f"Non-existent key: {full_key}")
            value = _decode_value(v)
            value = _check_and_coerce(value, d[sub], full_key)
            dict.__setitem__(d, sub, value)

    def _merge_dict(self, other, key_path):
        for k, v in other.items():
            full_key = ".".join(key_path + [str(k)])
            if k not in self:
                raise KeyError(f"Non-existent config key: {full_key}")
            old = self[k]
            if isinstance(old, CfgNode):
                if not isinstance(v, (dict, CfgNode)):
                    raise ValueError(
                        f"Cannot merge non-dict value into config section {full_key}"
                    )
                old._merge_dict(CfgNode(v) if not isinstance(v, CfgNode) else v, key_path + [str(k)])
            else:
                value = _check_and_coerce(copy.deepcopy(v), old, full_key)
                dict.__setitem__(self, k, value)

    # -- serialization ------------------------------------------------------
    def to_dict(self):
        out = {}
        for k, v in self.items():
            out[k] = v.to_dict() if isinstance(v, CfgNode) else v
        return out

    def dump(self, **kwargs):
        kwargs.setdefault("default_flow_style", None)
        return yaml.safe_dump(self.to_dict(), **kwargs)

    def __repr__(self):
        return f"CfgNode({dict.__repr__(self)})"

    def __str__(self):
        return self.dump()


def _decode_value(v):
    """Parse a CLI string into a Python literal (yaml rules, like yacs)."""
    if not isinstance(v, str):
        return v
    try:
        return yaml.safe_load(v)
    except yaml.YAMLError:
        return v


def _check_and_coerce(new, old, full_key):
    """Type-check a replacement value, with yacs-style coercions."""
    old_type, new_type = type(old), type(new)
    if old_type is new_type or old is None or new is None:
        return new
    # yacs-sanctioned casts
    if isinstance(old, (tuple, list)) and isinstance(new, (tuple, list)):
        return old_type(new)
    if isinstance(old, float) and isinstance(new, int) and not isinstance(new, bool):
        return float(new)
    if isinstance(old, int) and isinstance(new, float):
        # allow e.g. WEIGHT_DECAY-style float into int slot only if integral
        if float(new).is_integer():
            return int(new)
    raise ValueError(
        f"Type mismatch ({old_type} vs {new_type}) for config key {full_key}: "
        f"cannot replace {old!r} with {new!r}"
    )


# ---------------------------------------------------------------------------
# Default config tree. Mirrors the reference defaults (ref: config.py:10-63)
# with TPU-native additions under DEVICE / MESH / DATA.
# ---------------------------------------------------------------------------

_C = CfgNode()
cfg = _C

# ------------------------------- model -------------------------------------
_C.MODEL = CfgNode()
_C.MODEL.ARCH = "resnet18"
_C.MODEL.NUM_CLASSES = 1000
_C.MODEL.PRETRAINED = False
# BatchNorm statistic regime. SYNCBN True ⇒ stats over the GLOBAL batch
# (cross-replica, ≙ torch SyncBatchNorm, ref: trainer.py:131). False (the
# reference default — every published baseline) ⇒ "ghost" BN: stats over
# independent BN_GROUP-sample groups, reproducing the reference's per-GPU
# statistics on any chip count.
_C.MODEL.SYNCBN = False
# Ghost-BN group size when SYNCBN is False. 0 ⇒ TRAIN.BATCH_SIZE (the
# per-chip batch — exactly the reference's per-GPU BN batch). Must divide
# the (micro-)batch each training forward sees.
# (Running-stats decay is per-module — torch-parity 0.9; the trace-time
# env knob DISTRIBUUUU_BN_MOMENTUM overrides it globally for eval-
# stability experiments, PERF.md r5 "stabilizing the convergence
# artifact".)
_C.MODEL.BN_GROUP = 0
_C.MODEL.WEIGHTS = None
# Use randomly generated fake data (no dataset on disk needed).
_C.MODEL.DUMMY_INPUT = False
# Mixture-of-experts knobs for the *_moe archs (ops/moe.py expert
# parallelism over the ``model`` mesh axis).
_C.MODEL.MOE = CfgNode()
_C.MODEL.MOE.NUM_EXPERTS = 8
_C.MODEL.MOE.TOP_K = 2
# Every Nth block gets the MoE FFN (2 = the GShard/ViT-MoE placement).
_C.MODEL.MOE.EVERY = 2
# λ for the switch-transformer load-balancing aux loss added to the task
# loss (0 disables; without it top-k routing collapses onto few experts).
_C.MODEL.MOE.AUX_WEIGHT = 0.01
# Execution strategy: "partial" = local experts on all tokens + one psum
# (exact, O(E/n) compute/token — right for small E); "dispatch" =
# switch-style all_to_all routing at fixed capacity (O(top_k)
# compute/token — the scalable path for large E; over-capacity
# assignments drop, logged as the ``moe_dropped`` train metric).
_C.MODEL.MOE.IMPL = "partial"
# Dispatch capacity: each expert takes ceil(T_shard·top_k/E × this) slots
# per source rank. Raise toward E/top_k for exactness, lower for speed.
_C.MODEL.MOE.CAPACITY_FACTOR = 2.0

# ------------------------------- training ----------------------------------
_C.TRAIN = CfgNode()
_C.TRAIN.DATASET = "./data/ILSVRC/"
_C.TRAIN.SPLIT = "train"
_C.TRAIN.IM_SIZE = 224
# Per-process (per-host) batch size, matching the reference's per-GPU meaning.
_C.TRAIN.BATCH_SIZE = 32
_C.TRAIN.AUTO_RESUME = True
_C.TRAIN.LOAD_OPT = True
# Preemption-safe training (utils/preempt.py): on SIGTERM the epoch loop
# stops at the next dispatch boundary and writes a mid-epoch checkpoint
# that AUTO_RESUME prefers — the interrupted epoch re-runs from the
# preserved params/optimizer state instead of the last epoch boundary.
_C.TRAIN.PREEMPT_SAVE = True
_C.TRAIN.WORKERS = 4
_C.TRAIN.PIN_MEMORY = True
_C.TRAIN.PRINT_FREQ = 30
_C.TRAIN.TOPK = 5
# Fold this many optimizer steps into ONE compiled call (lax.scan over the
# step body). >1 removes the per-step host dispatch from the critical path —
# worth ~4 ms/step on tunneled transports (PERF.md) — at the cost of
# metric/profiler granularity rounding up to the fold size. 1 = the
# reference's one-dispatch-per-step behavior.
_C.TRAIN.STEPS_PER_CALL = 1
# Device-side prefetch ring depth (data/loader.device_prefetch): the H2D
# transfer of batches k+1..k+PREFETCH_DEVICE is dispatched while the
# compiled step still works on batch k, so transfers never serialize
# behind steps. Applies to the per-step dispatch path (STEPS_PER_CALL 1)
# of train_epoch AND validate; the folded path has its own ping-pong
# double buffering. 0 = the unoverlapped put-then-step order. Results are
# bit-identical at every depth (same device_put order, same step order —
# tests/test_overlap.py); only dispatch timing moves. HBM cost: depth
# extra device batches resident.
_C.TRAIN.PREFETCH_DEVICE = 2
# Per-batch stage-boundary timeline records (kind="timeline" in
# {OUT_DIR}/metrics.jsonl — utils/jsonlog.timeline_log): decode/augment,
# host assembly, H2D dispatch, and step dispatch monotonic timestamps for
# every batch on the per-step dispatch path (train + eval). Feed them to
# tools/overlap_report.py for exact wall-time attribution. Primary
# process only; one small JSON line per batch (folded dispatch emits
# none — set STEPS_PER_CALL 1 to diagnose an input-bound run).
_C.TRAIN.TIMELINE = True
# Rematerialize (jax.checkpoint via nn.remat) ResNet stages 1-2 — the
# largest-activation stages: their block activations are not stored for
# the backward but recomputed, trading cheap MXU flops for HBM traffic on
# a 93%-bus-bound step (PERF.md "Where the time goes"; the one untried
# roofline lever, VERDICT r5 #3). Exact same math (step-equivalence:
# tests/test_remat.py). resnet/resnext/wide_resnet family only (densenet
# always remats its dense layers; other archs refuse the knob loudly).
# A/B on hardware: python tools/ab_bench.py --preset remat
_C.TRAIN.REMAT = False
# Split each optimizer step's batch into this many sequential micro-batches,
# summing gradients in-graph before the (single) update. Runs the
# reference's large-global-batch recipes (README.md:210-211 — 8192/16384
# over 64 GPUs) on far fewer chips: BATCH_SIZE stays the *optimizer* batch
# per chip; HBM holds only BATCH_SIZE/GRAD_ACCUM_STEPS activations at once.
# Gradient math is exact (mean-CE grads average over equal micro-batches);
# BN batch stats are per-micro-batch — the same semantics torch DDP +
# gradient accumulation has (stats over what the device sees per forward).
_C.TRAIN.GRAD_ACCUM_STEPS = 1
# Non-finite loss policy (resilience/supervisor.py). "raise" fails fast at
# the next metric flush (honest failure beats silently training garbage);
# "skip" discards the poisoned update IN-GRAPH (pre-step state selected,
# step cursor still advances) and logs the skipped step — for rare bad
# batches; "rollback" reloads the last intact checkpoint and re-runs
# (TRAIN.MAX_ROLLBACKS attempts) — for transient corruption.
_C.TRAIN.NONFINITE = "raise"
_C.TRAIN.MAX_ROLLBACKS = 2
# Heartbeat watchdog (resilience/supervisor.Heartbeat): warn + emit a
# kind="stall" metrics record when no train-loop progress lands within
# this many seconds — a wedged collective, dead peer host, or hung
# storage would otherwise hang silently forever. 0 disables (default:
# first-step compiles legitimately take minutes on some backends; set
# ~2-5× your steady-state fold wall in production).
_C.TRAIN.STALL_TIMEOUT = 0.0

# ------------------------------- testing -----------------------------------
_C.TEST = CfgNode()
_C.TEST.DATASET = "./data/ILSVRC/"
_C.TEST.SPLIT = "val"
_C.TEST.IM_SIZE = 256
_C.TEST.BATCH_SIZE = 200
_C.TEST.PRINT_FREQ = 10

# ------------------------------- cudnn (compat) -----------------------------
# Accepted for YAML compatibility (ref: config.py:38-40); on TPU these map to
# XLA autotune/determinism behavior (see runtime.apply_backend_flags).
_C.CUDNN = CfgNode()
_C.CUDNN.BENCHMARK = True
_C.CUDNN.DETERMINISTIC = False

# ------------------------------- optimizer ----------------------------------
_C.OPTIM = CfgNode()
# "sgd" (the reference's recipe) or "adamw" (typical for the ViT archs).
_C.OPTIM.OPTIMIZER = "sgd"
_C.OPTIM.BETA1 = 0.9
_C.OPTIM.BETA2 = 0.999
_C.OPTIM.BASE_LR = 0.1
_C.OPTIM.LR_POLICY = "cos"
_C.OPTIM.LR_MULT = 0.1
_C.OPTIM.MAX_EPOCH = 100
_C.OPTIM.MOMENTUM = 0.9
_C.OPTIM.DAMPENING = 0.0
_C.OPTIM.NESTEROV = True
_C.OPTIM.WEIGHT_DECAY = 5e-5
_C.OPTIM.WARMUP_FACTOR = 0.1
_C.OPTIM.WARMUP_EPOCHS = 0
_C.OPTIM.STEPS = []
_C.OPTIM.MIN_LR = 0.0

# SGD momentum-buffer dtype: "float32" (torch-exact) or "bfloat16"
# (fp32 master params + half-traffic momentum; utils/optim.py)
_C.OPTIM.MOMENTUM_DTYPE = "float32"

# ------------------------------- language model -----------------------------
# Decoder-only LM workload plane (distribuuuu_tpu/lm/, models/gpt.py —
# ISSUE 12). The gpt_* archs train through the SAME trainer/partition
# lowering the image zoo uses: batches are {"image": tokens [B, S] int32,
# "label": next-tokens [B, S] int32, "mask": [B]} from token shards
# (DATA.FORMAT=tokens), the loss is the same cross-entropy — computed per
# token — and placement comes from the LM SpecTable rules
# (parallel/partition/specs.LM_TABLE).
_C.LM = CfgNode()
# Trained context length. Token shards must be packed with
# ``--pack-len SEQ_LEN`` (each record holds SEQ_LEN+1 tokens: input =
# [:-1], next-token targets = [1:]); a mismatch is refused at loader
# construction with the repack command. Also the learned-position table
# size, so generation prompts + new tokens must fit under it.
_C.LM.SEQ_LEN = 256
# -------------------------------- generation --------------------------------
# Autoregressive serving (lm/generate.py): paged per-request KV cache,
# prefill/decode split, continuous batching. The serve engine's AOT-bucket
# idea generalizes to (batch, cache-len) TILES: decode is compiled once
# per (batch_tile, cache_tile) pair and a step runs the smallest tile
# covering the live slots / longest sequence, so steady-state decoding
# never recompiles.
_C.GENERATE = CfgNode()
# Hard cap on generated tokens per request (requests may ask for fewer).
_C.GENERATE.MAX_NEW_TOKENS = 64
# Batch tiles: concurrent-sequence capacities decode is compiled for.
# The largest is the continuous-batching slot count. [] ⇒ powers of two
# up to 4.
_C.GENERATE.BATCH_TILES = []
# KV-cache length tiles. The largest must cover PROMPT_LEN + MAX_NEW_TOKENS
# (validated with the exact arithmetic at engine build) and every tile
# must be ≤ LM.SEQ_LEN (positions beyond the learned table don't exist).
# [] ⇒ [LM.SEQ_LEN].
_C.GENERATE.CACHE_TILES = []
# Longest admissible prompt (tokens). Prefill pads to this length.
_C.GENERATE.PROMPT_LEN = 64
# Chunked paged prefill (lm/generate.py, ISSUE 19): > 0 streams each
# prompt into its KV-cache page in fixed CHUNK_PREFILL-token
# prefill-shaped calls — a long prompt needs no wide prefill bucket, and
# the admissible prompt length grows from PROMPT_LEN to whatever the
# largest cache tile can hold next to the request's max_new (+ SPECULATE.K).
# Every cache tile >= the chunk must be a chunk multiple (the final padded
# chunk writes ceil(plen/chunk)*chunk page positions — validated with the
# arithmetic at engine build). 0 = classic whole-prompt prefill.
_C.GENERATE.CHUNK_PREFILL = 0
# Token id that terminates a sequence early (the byte tokenizer's EOS
# document-boundary token). -1 = generate exactly max_new_tokens.
_C.GENERATE.EOS_ID = 256
# Scheduler admission poll (seconds) while decode slots are free.
_C.GENERATE.POLL_S = 0.002

# ------------------------------- sampling -----------------------------------
# Decode-time token selection (lm/generate.sample_token). The default is
# greedy (TEMPERATURE=0.0 ⇒ argmax, the pre-ISSUE-17 behaviour, and what
# the speculative greedy-identity pin runs against). Any sampled stream
# is REPLAYABLE: selection uses counter-based uniforms keyed on
# (SEED, stream, decision-index), never a stateful RNG, so the same seed
# in the ctrl frame reproduces the same token stream bit-for-bit on any
# replica regardless of batching — the serving-side twin of the
# (seed, epoch, idx) augmentation invariant.
_C.GENERATE.SAMPLE = CfgNode()
# 0.0 = greedy argmax (deterministic, ignores TOP_K/TOP_P/SEED).
# > 0 scales logits by 1/T before the softmax.
_C.GENERATE.SAMPLE.TEMPERATURE = 0.0
# Keep only the k highest-probability tokens (0 = off).
_C.GENERATE.SAMPLE.TOP_K = 0
# Nucleus sampling: keep the minimal prefix of the probability-sorted
# vocab with cumulative mass >= TOP_P (1.0 = off).
_C.GENERATE.SAMPLE.TOP_P = 1.0
# Default replay seed when a request carries none.
_C.GENERATE.SAMPLE.SEED = 0

# ----------------------------- speculative decode ---------------------------
# Draft-model speculation (lm/generate.py, ISSUE 17): a small draft
# model proposes SPECULATE.K tokens per round; the target verifies all K
# in ONE prefill-shaped call through the existing cache tiles (the
# roofline-native fix — decode is memory-bound, so K verify positions
# cost barely more than 1). Standard accept/reject + bonus-token rule:
# the emitted distribution is IDENTICAL to target-only decoding (greedy:
# exact token match for any draft; sampled: same seed ⇒ same stream).
_C.GENERATE.SPECULATE = CfgNode()
_C.GENERATE.SPECULATE.ENABLED = False
# Draft arch (a gpt_* zoo name, e.g. gpt_nano drafting for gpt_nano_moe).
# Must share the target's tokenizer identity + vocab (validated with the
# exact values in-message at engine build).
_C.GENERATE.SPECULATE.DRAFT_ARCH = ""
# Optional draft checkpoint (same restore path as MODEL.WEIGHTS).
_C.GENERATE.SPECULATE.DRAFT_WEIGHTS = ""
# Tokens proposed per round. Each round may append up to K+1 tokens, so
# the largest cache tile must hold PROMPT_LEN + MAX_NEW_TOKENS + K
# (validated with the sum named in-message).
_C.GENERATE.SPECULATE.K = 4

# ------------------------------- kernel tier ---------------------------------
# The Pallas kernel tier (ops/pallas/, ISSUE 13): hand-fused kernels for
# the memory-bound regions the cost ledger pinned, each behind its own
# impl knob. Values: "auto" (pallas on the TPU backend for supported
# shapes, XLA elsewhere — interpret mode is the CPU *test* path, never
# the auto choice), "pallas" (force; interpret mode off-TPU, falls back
# loudly on unsupported shapes), "xla" (the always-available escape
# hatch). Every resolution emits a kernel.select record; every
# forced-but-unsupported site a kernel.fallback record + one warning
# (run_report's `kernels` section shows what actually ran).
_C.KERNELS = CfgNode()
# Fused optimizer update (ops/pallas/opt_update.py): ONE HBM pass over
# params+grads+moments for SGD-momentum and AdamW, replacing the optax
# chain's re-read-per-transform traffic in the trainer's
# optimizer_update scope. Bit-exact vs the optax reference (pinned).
_C.KERNELS.OPT_UPDATE = "auto"
# Fused pointwise conv + BN-affine + activation for the eval/inference
# path (ops/pallas/conv_epilogue.py): 1x1/s1 ungrouped convs with a
# known activation (ResNet/RegNet bottleneck 1x1s, EfficientNet
# expand/project/head). Other shapes fall back per call site.
_C.KERNELS.CONV_EPILOGUE = "auto"
# Fused decode attention over the paged KV cache
# (ops/pallas/decode_attn.py): the T=1 decode step of lm/generate's
# CachedAttention — online softmax per (row, head), ragged block-skip,
# no fp32 cache copy, no [B,H,1,C] logits round-trip.
_C.KERNELS.DECODE_ATTN = "auto"
# Key-block height of the decode kernel (sublane dim; multiple of 8).
# Each GENERATE.CACHE_TILES entry must be a multiple of it (or fit in
# one block) — validated with the arithmetic at engine build.
_C.KERNELS.DECODE_BLOCK = 128

# ------------------------------- device / mesh (TPU-native additions) -------
_C.DEVICE = CfgNode()
# "tpu" | "cpu" | "auto" — jax platform selection.
_C.DEVICE.PLATFORM = "auto"
# Compute dtype for the model ("bfloat16" keeps the MXU fed; params stay fp32).
_C.DEVICE.COMPUTE_DTYPE = "bfloat16"
# Deterministic XLA ops (maps CUDNN.DETERMINISTIC intent onto TPU).
_C.DEVICE.DETERMINISTIC = False
# Attention implementation for attention archs. BoTNet: "auto" | "xla"
# (the fused Pallas path for the 196-token grid was retired r5 at 0.854×
# XLA e2e — PERF.md "BoTNet attention").
# ViT: "auto" picks the Pallas flash kernel (ops/flash_attention.py) for
# sequences ≥1024 tokens WHEN dropout is 0 (the kernel has no
# probability-dropout; with dropout>0 auto stays on dense XLA — at long
# sequences that materializes O(L²) logits, so prefer dropout 0 there),
# and dense XLA below; "flash" forces the kernel (blockwise-scan fallback
# off-TPU); "blockwise" is the lax.scan O(L·chunk) exact path; MESH.SEQ>1
# overrides with ring attention.
_C.DEVICE.ATTN_IMPL = "auto"
# Space-to-depth stem for the 7x7/s2-stem archs (resnet/resnext/wide_resnet/
# botnet): compute the stem as a 4x4/s1 conv over 2x2-block-folded input
# (models/layers.StemConv7x7). Exact same math and the SAME params/
# checkpoints either way. Measured NEUTRAL on v5e (XLA already lays the stem
# out well there — PERF.md); kept as a knob for TPU generations where the
# classic MLPerf gain applies.
_C.DEVICE.S2D_STEM = False

_C.MESH = CfgNode()
# Logical mesh axis sizes; -1 means "all remaining devices" on that axis.
# Axes: data (DP), model (TP), seq (SP/CP), pipe (PP — parallel/pp.py),
# expert (EP — a dedicated MoE dispatch axis, so expert parallelism can
# compose with tensor parallelism on a 3-axis dp×tp×ep mesh instead of
# riding the model axis). Any stanza is validated/classified up front by
# the partition-layer topology registry (parallel/partition/topology.py).
_C.MESH.DATA = -1
_C.MESH.MODEL = 1
_C.MESH.SEQ = 1
_C.MESH.PIPE = 1
# Expert-parallel axis for the *_moe archs. 1 (default) keeps the legacy
# behavior where expert tensors ride the ``model`` axis; >1 dedicates
# this axis to MoE dispatch (must divide MODEL.MOE.NUM_EXPERTS).
_C.MESH.EXPERT = 1
# GPipe microbatches per step when PIPE > 1 (parallel/pp.py schedule);
# 0 → 2 × PIPE. The per-data-shard batch must divide by it.
_C.MESH.MICROBATCH = 0
# ZeRO / FSDP redundancy elimination over the data axis (parallel/zero.py).
# 0 = off (DDP layout: params + optimizer state replicated per data rank,
# the reference's topology). 1 = optimizer state sharded over data, grads
# reduce-scattered into the sharded update (ZeRO-1). 3 = params also
# sharded at rest (FSDP; weights all-gathered at use). Same math in every
# stage — only per-rank memory and the compiled collective schedule change.
# Stage 2 is subsumed: in-graph gradients are transient, the stage-1
# constraint already materializes them sharded.
_C.MESH.ZERO = 0

# ------------------------------- ZeRO collective scheduling -----------------
# Latency-hiding controls for the ZeRO/FSDP collective schedule the
# partition layer derives (parallel/partition/specs.gather_schedule +
# lowering.train_step_body). The MESH.ZERO stage declares WHERE state
# rests; this node declares WHEN the spec-induced collectives run.
_C.ZERO = CfgNode()
# Collective/compute overlap. True (default): the step's ZeRO collectives
# (gather-once entry all-gathers, backward reduce-scatters, rest-layout
# re-gathers) are emitted as independent per-leaf ops with no serializing
# joins, so XLA's latency-hiding scheduler can run them concurrently with
# compute (proof artifact: trace_report's overlap-fraction rollup over
# the zero_*@data named scopes). False: an optimization_barrier joins
# each collective class before the consuming compute — the synchronous
# control arm of the A/B (tools/collective_bench.py --zero-ab); values
# are bit-identical either way (pinned: tests/test_zero_overlap.py).
_C.ZERO.OVERLAP = True
# ZeRO-3 gather-once prefetch depth, in parameter block-groups (the
# path-pattern groups specs.gather_groups derives — one group per
# numbered model block). -1 (default): the WHOLE FSDP param tree is
# all-gathered once at step entry (~1 gather/leaf instead of the per-use
# gather storm the PR 14 census priced at ~9.3/leaf; full-model gathered
# footprint lives through the step). N >= 1: only the first N groups are
# hoisted to step entry, later groups keep per-use gathering (bounds the
# gathered-live footprint on memory-tight configs at the cost of extra
# collectives). 0: no hoisting at all — the legacy per-use schedule, the
# escape hatch the census A/B compares against.
_C.ZERO.GATHER_AHEAD = -1

# ------------------------------- data pipeline -------------------------------
_C.DATA = CfgNode()
# Dataset storage format. "imagefolder" reads root/split/class/*.jpg one
# file per sample (the reference layout). "shards" streams indexed record
# shards packed by tools/make_shards.py (data/shards/): sequential IO from
# a few large files, a (seed, epoch)-only topology-independent sample
# order, and exact mid-epoch resume — the preemption checkpoint embeds the
# loader's global cursor, so a restart continues at the exact next batch
# instead of re-running the epoch. TRAIN/TEST.DATASET point at the shards
# root (the directory holding <split>/MANIFEST.json). "tokens" streams
# packed-sequence TOKEN shards (data/shards/tokens.py, packed by
# tools/make_token_shards.py) for the gpt_* LM archs: same record
# container, same window-shuffled order, same exact mid-epoch resume —
# batches become {"image": tokens [B,S] int32, "label": next-tokens}
# (LM.SEQ_LEN must match the pack length; refused with the repack
# command otherwise).
_C.DATA.FORMAT = "imagefolder"
# Shard-streaming order knobs (data/shards/order.py): storage order is cut
# into SHARDS_BLOCK-record sequential runs, the runs are permuted, and a
# SHARDS_WINDOW-sample shuffle buffer decorrelates neighbors. Bigger block
# = more sequential IO, less mixing; bigger window = better mixing, more
# read scatter. block=1 + window≥dataset restores the exact uniform
# shuffle of the imagefolder sampler.
_C.DATA.SHARDS_BLOCK = 64
_C.DATA.SHARDS_WINDOW = 1024
# Decode backend: "auto" uses the C++ kernel (native/decode.cc) when it
# builds, else PIL; "native" requires it; "pil" forces pure Python.
_C.DATA.BACKEND = "auto"
# Ship uint8 pixels and run (x/255 - mean)/std in-graph on device instead
# of on the host: 4× fewer host→device bytes per batch (PCIe / tunnel)
# and less host CPU, numerically equivalent (pixels are uint8 after
# resampling either way — transforms.normalize_in_graph). Default ON
# since r4 (VERDICT r3 #6): measured strictly better (2.7× faster fenced
# H2D), eval metrics bit-identical on both decode backends
# (tests/test_device_normalize.py); False restores the reference's
# host-normalized float pipeline byte-for-byte.
_C.DATA.DEVICE_NORMALIZE = True
# Loader-level resilience (data/loader.py): a failed sample/batch decode
# is retried RETRIES times with exponential backoff starting at
# RETRY_BACKOFF_S (transient filesystem/network hiccups), then — with
# SKIP_CORRUPT — the corrupt sample is replaced by a good sample from the
# same batch and logged (logger warning + kind="data_error" metrics
# record) instead of aborting the whole epoch. False restores fail-stop.
_C.DATA.RETRIES = 2
_C.DATA.RETRY_BACKOFF_S = 0.05
_C.DATA.SKIP_CORRUPT = True

# ------------------------------- fault injection -----------------------------
# Deterministic failure injection (utils/faults.py) — every resilience
# recovery path is exercised by tests and tools/resilience_drill.py
# through these knobs. All hooks are no-ops unless ENABLED.
_C.FAULTS = CfgNode()
_C.FAULTS.ENABLED = False
# Compile `loss × where(step==NAN_STEP, NaN, 1)` into the train step:
# loss AND grads go non-finite at exactly that global step. -1 = off.
_C.FAULTS.NAN_STEP = -1
# Decode of this dataset sample index raises. "once": the first retry
# succeeds (transient I/O); "always": the loader's skip-and-log path
# engages (corrupt file). -1 = off.
_C.FAULTS.DECODE_ERROR_IDX = -1
_C.FAULTS.DECODE_ERROR_MODE = "once"
# SIGKILL process KILL_RANK at (KILL_EPOCH, KILL_AT_BATCH) — the
# uncatchable hard crash. -1 = off.
_C.FAULTS.KILL_RANK = -1
_C.FAULTS.KILL_EPOCH = 0
_C.FAULTS.KILL_AT_BATCH = -1
# Sleep STALL_S seconds at (STALL_EPOCH, STALL_AT_BATCH) so the heartbeat
# watchdog must flag. -1 = off.
_C.FAULTS.STALL_EPOCH = 0
_C.FAULTS.STALL_AT_BATCH = -1
_C.FAULTS.STALL_S = 0.0
# Deliver SIGTERM to this process at (PREEMPT_EPOCH, PREEMPT_AT_BATCH) —
# a deterministic scheduler preemption through the REAL signal handler
# (utils/preempt.py): the epoch loop exits at the next boundary and the
# mid-epoch checkpoint (with the shards data cursor) is written. -1 = off.
_C.FAULTS.PREEMPT_EPOCH = 0
_C.FAULTS.PREEMPT_AT_BATCH = -1
# Trigger RECOMPILE_N real backend compiles (trivial jits at distinct
# shapes — genuine kind="compile" events, nothing feeds the train step)
# at (RECOMPILE_EPOCH, RECOMPILE_AT_BATCH): the mid-run recompile storm
# a shape leak or bad bucket config causes, injectable so the monitor's
# recompile-storm alert is provable (tools/soak.py). -1 = off.
_C.FAULTS.RECOMPILE_EPOCH = 0
_C.FAULTS.RECOMPILE_AT_BATCH = -1
_C.FAULTS.RECOMPILE_N = 8
# Sleep SLOWDOWN_MS at EVERY batch boundary of SLOWDOWN_EPOCH — a
# sustained host-side throughput regression (thermal throttle, noisy
# neighbor, degraded storage) that must trip the monitor's
# throughput-regression rule without tripping the stall watchdog
# (keep SLOWDOWN_MS well under TRAIN.STALL_TIMEOUT). 0 = off.
_C.FAULTS.SLOWDOWN_EPOCH = 0
_C.FAULTS.SLOWDOWN_MS = 0.0
# SIGKILL the process from the async checkpoint committer thread AFTER
# ckpt_ep_{KILL_MID_ASYNC_SAVE}'s orbax payload is fully written but
# BEFORE its MANIFEST.json commits (CHECKPOINT.ASYNC) — the async-save
# crash window. The restart must quarantine the manifest-less directory
# and walk back to the previous intact checkpoint
# (tools/resilience_drill.py killed_mid_async_save). -1 = off.
_C.FAULTS.KILL_MID_ASYNC_SAVE = -1
# Truncate shard file #TRUNCATE_SHARD of the dataset split to 60% of its
# manifest size before the reader opens it (DATA.FORMAT=shards): kills the
# index footer and the tail records — the reader must recover the index by
# forward scan and the lost records must flow through DATA.SKIP_CORRUPT.
# -1 = off.
_C.FAULTS.TRUNCATE_SHARD = -1
# After ckpt_ep_{CORRUPT_EPOCH} commits: "truncate" halves its largest
# payload file (digest-mismatch path); "partial" deletes its manifest
# (crash-before-commit path). -1 = off.
_C.FAULTS.CORRUPT_EPOCH = -1
_C.FAULTS.CORRUPT_MODE = "truncate"
# Hold dispatch token #WEDGE_DISPATCH (the sequencer's global grant
# counter — asyncplane/sequencer.py) for WEDGE_S seconds before the
# dispatch proceeds: a wedged dispatcher thread. The sequencer's wedge
# watchdog (wired through supervisor.watch_blocking) must flag it as a
# kind="dispatch.wedge" record instead of the run hanging silently
# (tools/resilience_drill.py dispatch_wedge_recovery). -1 = off.
_C.FAULTS.WEDGE_DISPATCH = -1
_C.FAULTS.WEDGE_S = 0.0
# SIGKILL the PRIMARY host from its committer thread inside the
# multi-host async-commit crash window: AFTER every host arrived at the
# cross-host commit barrier (payload durable everywhere) but BEFORE
# MANIFEST.json commits (asyncplane/committer.py). The restart must
# quarantine the manifest-less dir and walk back
# (tools/resilience_drill.py multihost_async_save_kill). -1 = off.
_C.FAULTS.KILL_AT_COMMIT_BARRIER = -1
# Hold the LEADER's cross-host ring slot #WEDGE_RING for WEDGE_RING_S
# seconds BEFORE its order publishes (asyncplane/ring.py): followers
# starve at that slot past ASYNC.RING_DEADLINE_S, must flag
# kind="dispatch.wedge", and the trainer must run that epoch's eval
# synchronously — degraded, never hung (tools/resilience_drill.py
# ring_wedge_degrade). WEDGE_RING_S must exceed ASYNC.RING_DEADLINE_S or
# the wedge is unobservable (validated, utils/faults.validate_cfg).
# -1 = off.
_C.FAULTS.WEDGE_RING = -1
_C.FAULTS.WEDGE_RING_S = 0.0
# SIGKILL the PRIMARY inside the SHARDED async-commit crash window:
# every host's shard file durable + all barrier arrivals in, but
# MANIFEST.json not committed (the sharded protocol's analogue of
# KILL_AT_COMMIT_BARRIER). The restart must quarantine the manifest-less
# dir — shard files and all — and walk back
# (tools/resilience_drill.py sharded_save_kill_at_barrier). -1 = off.
_C.FAULTS.KILL_AT_SHARD_BARRIER = -1
# After ckpt_ep_{DROP_SHARD_FILE} fully commits: delete host
# DROP_SHARD_HOST's shards_host<r>.npz from it (primary's post-commit
# hook). The next restart's manifest verification must fail the digest
# walk, quarantine, and walk back; a DIRECT load must refuse with the
# recorded sharding named (tools/resilience_drill.py
# sharded_restore_fewer_shards). DROP_SHARD_HOST must be a valid host
# rank — validated against the live world at the hook site. -1 = off.
_C.FAULTS.DROP_SHARD_FILE = -1
_C.FAULTS.DROP_SHARD_HOST = 1

# ------------------------------- async dispatch plane ------------------------
# The dispatch sequencer (asyncplane/sequencer.py): the primitive that
# makes overlapped execution safe on multi-DEVICE processes. Two host
# threads dispatching SPMD programs concurrently can enqueue in
# different per-device orders; their collectives then cross-wait at the
# XLA rendezvous and the backend deadlocks (pinned: PR 10, reproduced
# deterministically on the 8-virtual-device CPU mesh). With SEQUENCER on
# (the default), every step dispatch from the trainer / concurrent-eval
# / snapshot threads first acquires a dispatch token — tokens are
# granted in ONE global order, and switching dispatch streams fences on
# the previous stream's completion — so every device observes one
# program sequence and the deadlock precondition is structurally
# removed. SEQUENCER False is the explicit escape hatch: it restores the
# PR 10 degrade-to-sync gates (concurrent eval single-device only, async
# commit single-host only) with a logged warning.
_C.ASYNC = CfgNode()
_C.ASYNC.SEQUENCER = True
# Cross-host commit barrier (multi-host CHECKPOINT.ASYNC): how long a
# host waits for its peers' barrier arrivals / the manifest commit
# before the background commit fails (surfaced as AsyncCommitError at
# the next join barrier — never silent, never a hang).
_C.ASYNC.BARRIER_TIMEOUT_S = 600.0
# Cross-host dispatch ring (multi-host concurrent eval, ISSUE 18): how
# long a FOLLOWER waits for the leader's published dispatch order before
# flagging kind="dispatch.wedge" and degrading that epoch's eval to
# synchronous (asyncplane/ring.py). The run keeps going either way; past
# BARRIER_TIMEOUT_S of zero leader progress the follower detaches to
# host-local order with an error log (a leader silent that long is a
# dead host — the group scheduler's restart to make). Seconds, > 0.
_C.ASYNC.RING_DEADLINE_S = 30.0

# ------------------------------- checkpointing ------------------------------
# Async execution plane (distribuuuu_tpu/asyncplane/): checkpoint commit off
# the trainer's critical path. With ASYNC on, a save blocks the epoch loop
# only for the device→host snapshot of the state tree (donation-safe copy);
# the orbax payload write, file digests, and the atomic MANIFEST.json commit
# run on a background committer thread. The PR 3 crash-consistency protocol
# is preserved exactly — the manifest is still written strictly LAST, so a
# process killed mid-async-save leaves a manifest-less directory that
# find_last_valid_checkpoint quarantines and walks back over. A join
# barrier runs before the next save (at most one commit in flight), at
# preemption (the committer drains inside the SIGTERM grace window before
# the preempt save), and at exit. Telemetry splits the cost:
# "ckpt_snapshot" spans are the on-path time, "ckpt_commit" spans the
# off-path time (tools/run_report.py reports both). Multi-host runs
# commit async too (ASYNC.SEQUENCER on, the default): hosts rendezvous
# on a cross-host commit barrier — per-host background threads, payload
# durable on every host, MANIFEST.json strictly last behind the
# all-hosts-durable barrier (asyncplane/committer.py; a host killed
# between barrier and manifest is recovered by the walk-back). A state
# tree sharded ACROSS hosts (e.g. ZeRO over a cross-host axis) commits
# through the SHARDED variant of the same protocol: each host writes its
# own shards_host<r>.npz + layout under the barrier, the manifest
# records the sharding, restore reassembles elastically (ISSUE 18).
# Only trees a host snapshot cannot represent at all (non-dict
# containers, object-dtype leaves) still degrade to the synchronous
# collective save, with a warning.
_C.CHECKPOINT = CfgNode()
_C.CHECKPOINT.ASYNC = False

# Run validate() concurrently with the NEXT train epoch (asyncplane/
# evalloop.py): at each epoch boundary the trainer takes an on-device copy
# of params/batch_stats and hands it to an eval worker thread; the result
# joins — with best-acc/is_best bookkeeping and the "eval"/"epoch" log
# records — at the following boundary. Trajectory-neutral by contract
# (eval reads a snapshot; training math never sees it —
# tests/test_asyncplane.py pins async-everything ≡ sync bit-identically).
# Epoch checkpoints record best_acc1 as of one eval earlier (the in-flight
# eval hasn't joined when the boundary save happens); the weights-only
# "best" checkpoint itself is always written when a new best joins.
# Multi-device processes run it under the dispatch sequencer
# (ASYNC.SEQUENCER, asyncplane/sequencer.py): train/eval/snapshot
# dispatches are token-ordered into one global program sequence, which
# removes the cross-thread collective deadlock PR 10 pinned on the
# 8-virtual-device mesh. Multi-host processes attach the cross-host
# dispatch ring (asyncplane/ring.py, ISSUE 18): the leader publishes
# its grant order through the run directory and followers grant only
# in that order, so eval overlaps train ACROSS hosts too; a host
# starving past ASYNC.RING_DEADLINE_S flags dispatch.wedge and that
# epoch's eval collectively degrades to sync (never a hang).
# ASYNC.SEQUENCER=False on multi-device remains the explicit escape
# hatch, degrading to synchronous eval with a logged warning.
_C.TRAIN.CONCURRENT_EVAL = False

# ------------------------------- compilation cache ---------------------------
# JAX persistent compilation cache (asyncplane/compile_cache.py): compiled
# step programs are serialized to DIR, so a restart — crash recovery,
# preemption resume, elastic resume at the same topology — skips the
# compile storm PR 5's jit.compiles counter measures. Cache hits/misses
# are counted (jit.cache_hits / jit.cache_misses registry counters +
# kind="compile.cache" telemetry records); a compile served from the
# cache is NOT counted as a jit.compile (it is a deserialization, not a
# compilation), so a warm restart shows jit.compiles at/near zero for
# previously-compiled programs (tools/asyncplane_bench.py proves it into
# BENCH_r06.json). While the cache is active the cost-model HBM ledger
# (TELEMETRY.COSTMODEL_MEMORY) runs its extra AOT compile in an ISOLATED
# child process (telemetry/costmodel.py subprocess probe) — the in-process
# compile corrupted the CPU backend heap when combined with the cache's
# executable (de)serialization and a checkpoint restore (PERF.md "Async
# execution plane"); the probe keeps cache and ledger coexisting.
_C.COMPILE_CACHE = CfgNode()
_C.COMPILE_CACHE.ENABLED = False
# Cache directory; "" = {OUT_DIR}/compile_cache (restarts of the same run
# share it). Point several runs at one absolute path to share compiles
# across output dirs (the cache key covers program + flags + backend).
_C.COMPILE_CACHE.DIR = ""
# Only compiles at least this long are persisted (0 caches everything —
# jax's own default of 1s would skip most CPU-test-sized programs).
_C.COMPILE_CACHE.MIN_COMPILE_TIME_S = 0.0
# Evict least-recently-used entries past this size. 0 = unbounded.
_C.COMPILE_CACHE.MAX_SIZE_MB = 0

# ------------------------------- serving ------------------------------------
# Online inference (serve/, serve_net.py) — the request-level engine that
# turns the eval step into a service. No reference analogue (the reference
# stops at offline test_net.py).
_C.SERVE = CfgNode()
# Dynamic micro-batch assembly: flush when MAX_BATCH requests are waiting
# or MAX_WAIT_MS after the oldest request arrived, whichever comes first.
_C.SERVE.MAX_BATCH = 8
_C.SERVE.MAX_WAIT_MS = 5.0
# Batch-shape buckets compiled ONCE at startup (jax.jit AOT lowering);
# a batch of n pads to the smallest bucket ≥ n, so steady-state serving
# never recompiles. [] ⇒ powers of two up to MAX_BATCH.
_C.SERVE.BUCKET_SIZES = []
# Bounded-queue backpressure: submissions beyond this depth are rejected
# with a retry-after hint instead of growing latency without bound.
_C.SERVE.MAX_QUEUE = 64
# Length-aware serving (the long-context plane): prompts of at least
# LONG_PROMPT_THRESHOLD tokens form the "long" admission/routing class;
# 0 disables classification (every request is "short").
_C.SERVE.LONG_PROMPT_THRESHOLD = 0
# At most this many of the MAX_QUEUE slots may hold long-class requests
# at once, so a burst of long prompts backpressures while short decode
# traffic keeps admitting — one chunked 4k prefill cannot starve the
# decode batch. Must stay below MAX_QUEUE (the short-class headroom IS
# the reservation); 0 = no reservation.
_C.SERVE.LONG_MAX_QUEUE = 0
# Optional per-length-class windowed p99 SLO targets (ms; 0 = no
# target). The fleet router surfaces `length:short` / `length:long`
# rows next to its per-model SLO rows, so the slo-breach alert rule
# referees them unchanged (telemetry/live.py).
_C.SERVE.SHORT_P99_SLO_MS = 0.0
_C.SERVE.LONG_P99_SLO_MS = 0.0
# Local device index the serving replica pins to (latency-optimal
# small-batch serving is one single-chip replica per chip; run one
# serve_net process per chip for throughput).
_C.SERVE.DEVICE = 0
# Socket frontend (length-prefixed frames; serve_net.py). PORT 0 picks an
# ephemeral port (logged at startup).
_C.SERVE.HOST = "127.0.0.1"
_C.SERVE.PORT = 8765

# Weight-only serving quantization (serve/quantize.py): "" (full
# precision), "bf16", or "int8". Repacks the weights before the AOT
# bucket compiles — buckets, protocol, and batching are unchanged; int8
# weights dequantize in-graph. Accuracy deltas are pinned by
# `zoo_check.py --quantize` against per-mode tolerances.
_C.SERVE.QUANTIZE = ""

# Request-scoped distributed tracing (telemetry/tracectx.py): the
# fraction of requests the client/bench edge opens a trace context for
# (head-based deterministic sampling — the decision is a pure function
# of the minted trace id, made once at the edge; downstream hops only
# honor presence). Traced requests carry the context in every protocol
# frame and accumulate a `trace.span` tree across router and replica
# sinks (queue wait, prefill chunks, decode steps, speculation rounds);
# the router's latency ring keeps trace ids so p99-breach alerts name
# their worst exemplars. 0.0 (default) keeps every frame byte-identical
# to the untraced wire format — server math is bit-identical either way
# (the trajectory-neutrality pin, tests/test_trace.py).
_C.SERVE.TRACE_SAMPLE = 0.0

# Serving fleet (serve/fleet/, `serve_net.py --fleet N`): a shared-nothing
# replica pool behind a router process. The router owns SERVE.HOST:PORT;
# each replica is a full serve_net engine in its own process on an
# ephemeral port, dispatched to by least-loaded policy (router in-flight
# depth + replica queue depth + occupancy + EWMA latency), with idempotent
# retry on replica failure and verbatim backpressure passthrough when the
# whole fleet is saturated.
_C.SERVE.FLEET = CfgNode()
# Initial replica count (`--fleet N` overrides). The autoscaler moves the
# target inside [MIN_REPLICAS, MAX_REPLICAS]; the pool keeps the target
# met (dead replicas are replaced automatically).
_C.SERVE.FLEET.REPLICAS = 2
_C.SERVE.FLEET.MIN_REPLICAS = 1
_C.SERVE.FLEET.MAX_REPLICAS = 4
# Autoscale-from-telemetry policy loop (fleet/autoscale.py): add a replica
# after BREACH_N consecutive windows with fleet p99 over P99_TARGET_MS or
# total queued work over QUEUE_HIGH; remove one after BREACH_N consecutive
# calm windows (p99 under SCALE_DOWN_FRAC x target AND queue under
# QUEUE_LOW); COOLDOWN_S of hysteresis after every action. False pins the
# fleet at its launch size (the pool still replaces dead replicas).
_C.SERVE.FLEET.AUTOSCALE = True
_C.SERVE.FLEET.P99_TARGET_MS = 250.0
_C.SERVE.FLEET.QUEUE_HIGH = 32
_C.SERVE.FLEET.QUEUE_LOW = 2
_C.SERVE.FLEET.SCALE_DOWN_FRAC = 0.5
_C.SERVE.FLEET.BREACH_N = 3
_C.SERVE.FLEET.EVAL_PERIOD_S = 2.0
_C.SERVE.FLEET.COOLDOWN_S = 10.0
# Replica health-checking (fleet/pool.py): a stats probe every
# HEALTH_PERIOD_S; HEALTH_FAILS consecutive failures (or process exit)
# marks the replica dead, removes it from routing, and spawns its
# replacement. WARMUP_TIMEOUT_S bounds how long a fresh replica may take
# to AOT-compile its bucket shapes before it is abandoned — a replica is
# never routable before its warm-up probe reports every bucket compiled.
_C.SERVE.FLEET.HEALTH_PERIOD_S = 1.0
_C.SERVE.FLEET.HEALTH_FAILS = 3
_C.SERVE.FLEET.WARMUP_TIMEOUT_S = 180.0
# Per-request router->replica socket timeout; a replica that sits on one
# request longer than this is treated as failed (the request reroutes).
_C.SERVE.FLEET.REQUEST_TIMEOUT_S = 60.0
# Fleet telemetry cadence: kind="fleet.stats"/"fleet.replica" records
# into the router's per-rank telemetry sink every EMIT_INTERVAL_S.
_C.SERVE.FLEET.EMIT_INTERVAL_S = 10.0

# ------------------------------- telemetry -----------------------------------
# Unified telemetry layer (distribuuuu_tpu/telemetry/): per-rank JSONL
# event files ({OUT_DIR}/telemetry/rank*.jsonl — spans, compile events,
# registry snapshots, mirrored resilience events), merged by
# tools/run_report.py into a run health report and a Perfetto trace.
# Trajectory-neutral by contract: ENABLED True vs False produces
# bit-identical training states (tests/test_telemetry.py); overhead is a
# few JSON lines per batch per rank, off the measured intervals.
_C.TELEMETRY = CfgNode()
_C.TELEMETRY.ENABLED = True
# Per-rank sink directory; "" = {OUT_DIR}/telemetry.
_C.TELEMETRY.DIR = ""
# Per-batch wait/h2d/step spans on EVERY rank (the per-rank half of the
# TRAIN.TIMELINE records, which stay primary-only): cross-rank step-time
# percentiles and straggler skew come from these. False keeps only
# epoch-level records (registry snapshots, memstats) and event mirrors.
_C.TELEMETRY.STEP_SPANS = True
# Count jit compiles + wall time via the jax.monitoring bus (kind=
# "compile" records + jit.compiles/jit.compile_s registry counters).
_C.TELEMETRY.COMPILE_EVENTS = True
# Sample device.memory_stats() per epoch (kind="memstats"; TPU/GPU
# backends — the CPU backend reports none and is skipped).
_C.TELEMETRY.MEMSTATS = True
# XLA cost-model ledger (telemetry/costmodel.py): once per step program,
# lower the jitted step and emit kind="cost.step"/"cost.roofline"
# records (flops, bytes accessed, roofline position) from XLA's own
# cost_analysis — the source run_report's MFU section and the monitor's
# mfu-regression rule read. Lowering only re-traces; no extra compile.
_C.TELEMETRY.COSTMODEL = True
# Additionally AOT-compile the lowered step for memory_analysis()
# (kind="cost.memory": executable HBM footprint vs capacity → headroom %
# and the hbm-headroom-low rule). Costs ONE extra backend compile per
# distinct step program at startup — disable for compile-latency-
# sensitive runs; the serving engine's bucket ledger is unaffected (it
# reads executables it already built).
_C.TELEMETRY.COSTMODEL_MEMORY = True

# ------------------------------- profiler ------------------------------------
# jax.profiler trace capture (TensorBoard/XProf format). When enabled, the
# primary process traces NUM_STEPS train steps starting at START_STEP of
# epoch 0 into {OUT_DIR}/profile (or DIR when set). The reference offers
# wall-clock meters only (SURVEY.md §5.1); this is the TPU-idiomatic upgrade.
_C.PROF = CfgNode()
_C.PROF.ENABLED = False
_C.PROF.DIR = ""
_C.PROF.START_STEP = 10
_C.PROF.NUM_STEPS = 5

# ------------------------------- misc ---------------------------------------
_C.OUT_DIR = "./output"
_C.CFG_DEST = "config.yaml"
_C.RNG_SEED = None
_C.LOG_DEST = "stdout"

# Snapshot of defaults for reset_cfg (ref: config.py:65-66).
_CFG_DEFAULT = _C.clone()
_CFG_DEFAULT.freeze()


def merge_from_file(cfg_file):
    """Merge a YAML file into the global cfg (ref: config.py:69-72)."""
    _C.merge_from_file(cfg_file)


def dump_cfg(out_dir=None):
    """Dump the merged config to OUT_DIR/CFG_DEST (ref: config.py:75-79)."""
    out_dir = _C.OUT_DIR if out_dir is None else out_dir
    cfg_file = os.path.join(out_dir, _C.CFG_DEST)
    os.makedirs(out_dir, exist_ok=True)
    with open(cfg_file, "w") as f:
        f.write(_C.dump())
    return cfg_file


def reset_cfg():
    """Reset the global cfg back to defaults (ref: config.py:82-84)."""
    _C.defrost()
    _C.merge_from_other_cfg(_CFG_DEFAULT)


def load_cfg_fom_args(description="Config file options.", argv=None):
    """Load config from command line args and a --cfg file (ref: config.py:87-100).

    Supports ``--cfg path.yaml`` plus a remainder of dotted ``KEY VALUE``
    overrides; absorbs ``--local_rank`` for launcher compatibility.
    """
    parser = argparse.ArgumentParser(description=description)
    help_s = "Config file location"
    parser.add_argument("--cfg", dest="cfg_file", help=help_s, required=True, type=str)
    # Accepted and ignored: process placement comes from the TPU runtime env.
    parser.add_argument("--local_rank", default=0, type=int)
    help_s = "See distribuuuu_tpu/config.py for all options"
    parser.add_argument("opts", help=help_s, default=None, nargs=argparse.REMAINDER)
    args_list = sys.argv[1:] if argv is None else argv
    if not args_list:
        parser.print_help()
        sys.exit(1)
    args = parser.parse_args(args_list)
    merge_from_file(args.cfg_file)
    _C.merge_from_list(args.opts)
    return _C
