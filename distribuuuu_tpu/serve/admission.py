"""Admission control and graceful drain for the serving engine.

Two serving-specific failure modes the training stack never sees:

* **Overload.** An open-loop client population does not slow down when the
  server does; an unbounded queue turns overload into unbounded latency
  for *every* request. The ``AdmissionController`` bounds the queue at
  ``SERVE.MAX_QUEUE`` and rejects beyond it with a ``retry_after_ms``
  hint (the HTTP-429/Retry-After shape) so clients back off while
  in-queue requests keep their latency budget. Length-aware engines
  (the LM plane) additionally cap the queue share long prompts may hold
  (``SERVE.LONG_MAX_QUEUE``): one burst of chunked 4k prefills
  backpressures the long class while short decode traffic keeps
  admitting.

* **Preemption.** TPU serving replicas are preempted exactly like
  training slices — SIGTERM plus a grace window. This reuses the
  ``utils/preempt.py`` signal pattern (handler sets a flag; the serving
  loop polls it at a safe boundary): on signal the frontend stops
  accepting, the engine finishes every queued/in-flight request, and the
  process exits inside the grace window. Training's analogue writes a
  mid-epoch checkpoint; serving's "state" is the in-flight requests, so
  draining them IS the checkpoint.
"""

from __future__ import annotations

import signal


class QueueFullError(RuntimeError):
    """Request rejected: the admission queue is at ``SERVE.MAX_QUEUE``.

    ``retry_after_ms`` estimates when capacity frees up (queue depth ×
    recent per-batch service time / batch size) — the client-visible
    backpressure signal.
    """

    def __init__(self, depth: int, max_queue: int, retry_after_ms: float):
        super().__init__(
            f"serve queue full ({depth}/{max_queue}); "
            f"retry after ~{retry_after_ms:.0f} ms"
        )
        self.depth = depth
        self.max_queue = max_queue
        self.retry_after_ms = retry_after_ms


class LongQueueFullError(QueueFullError):
    """Long-class rejection: the long-prompt reservation
    (``SERVE.LONG_MAX_QUEUE``) is exhausted while short-class capacity
    may remain — the client-visible half of decode-batch protection.
    Subclasses :class:`QueueFullError`, so every service layer that
    catches the base class keeps the queue_full/retry-after frame shape
    byte-for-byte; only the message (and ``length_class``) differ."""

    def __init__(self, class_depth: int, long_max_queue: int,
                 max_queue: int, retry_after_ms: float):
        RuntimeError.__init__(
            self,
            f"serve queue full for long prompts ({class_depth}/"
            f"{long_max_queue} long-class slots; SERVE.MAX_QUEUE="
            f"{max_queue}); retry after ~{retry_after_ms:.0f} ms"
        )
        self.depth = class_depth
        self.max_queue = long_max_queue
        self.retry_after_ms = retry_after_ms
        self.length_class = "long"


class EngineClosedError(RuntimeError):
    """Submitted after drain began — the engine no longer accepts work."""


class AdmissionController:
    """Bounded-queue admission: ``admit`` raises rather than letting the
    pending queue grow past ``max_queue``; ``close`` flips to
    reject-everything (drain mode).

    ``long_max_queue`` (the long-context plane) additionally caps how
    many queue slots long-class requests may hold: a long request needs
    BOTH a free slot and a free long-class slot, while short requests
    see only the total bound — so at least ``max_queue -
    long_max_queue`` slots always stay reachable for short traffic."""

    def __init__(self, max_queue: int, long_max_queue: int = 0):
        if max_queue < 1:
            raise ValueError(f"SERVE.MAX_QUEUE must be ≥ 1, got {max_queue}")
        long_max_queue = int(long_max_queue or 0)
        if long_max_queue < 0:
            raise ValueError(
                f"SERVE.LONG_MAX_QUEUE must be ≥ 0, got {long_max_queue}"
            )
        if long_max_queue >= max_queue and long_max_queue:
            raise ValueError(
                f"SERVE.LONG_MAX_QUEUE={long_max_queue} must leave "
                f"short-class headroom below SERVE.MAX_QUEUE={max_queue} "
                f"({long_max_queue} >= {max_queue}) — lower LONG_MAX_QUEUE "
                "or raise MAX_QUEUE"
            )
        self.max_queue = int(max_queue)
        self.long_max_queue = long_max_queue
        self._open = True

    @property
    def is_open(self) -> bool:
        return self._open

    def admit(self, depth: int, retry_after_ms: float, *,
              length_class: str = "short", class_depth: int = 0) -> None:
        """Raise unless a request may join a queue currently ``depth``
        deep. Long-class callers (``length_class="long"``) also pass
        ``class_depth`` — how many queued requests are long — checked
        against the reservation. The two-positional-arg call is the
        unchanged image-engine contract."""
        if not self._open:
            raise EngineClosedError("engine is draining; not accepting requests")
        if depth >= self.max_queue:
            raise QueueFullError(depth, self.max_queue, retry_after_ms)
        if (
            self.long_max_queue
            and length_class == "long"
            and class_depth >= self.long_max_queue
        ):
            raise LongQueueFullError(
                class_depth, self.long_max_queue, self.max_queue,
                retry_after_ms,
            )

    def close(self) -> None:
        self._open = False


# -- SIGTERM → graceful drain (the utils/preempt.py pattern) -----------------

_drain = {"requested": False}


def install_drain(signals=(signal.SIGTERM,)) -> None:
    """Install the drain handler (idempotent; main thread only — the same
    contract as ``preempt.install``). The handler only sets a flag; the
    serving accept loop polls ``drain_requested()`` and performs the
    actual drain at its next safe boundary.

    Chains to any previously installed handler (same fix as
    ``preempt.install``): co-resident SIGTERM watchers — e.g. training's
    preemption save in the same process — keep working."""

    def _make(prev):
        def handler(signum, frame):
            _drain["requested"] = True
            if callable(prev):
                prev(signum, frame)

        handler._dtpu_drain = True
        return handler

    for s in signals:
        prev = signal.getsignal(s)
        if getattr(prev, "_dtpu_drain", False):
            continue  # already ours (with its chain) — idempotent
        if prev in (signal.SIG_DFL, signal.SIG_IGN, None):
            prev = None
        signal.signal(s, _make(prev))


def drain_requested() -> bool:
    return _drain["requested"]


def reset_drain() -> None:
    """Clear the flag (tests)."""
    _drain["requested"] = False
