"""Serving observability: latency histogram, batch occupancy, throughput.

Per-request latency is measured enqueue → response-demux (the engine-level
number a load balancer would see, excluding client transport). Snapshots
report p50/p90/p99 from a bounded reservoir, batch occupancy (requests
per dispatched bucket slot — the padding-waste gauge), and throughput
over the observation window; ``emit()`` lands a snapshot in the existing
``utils/jsonlog.py`` JSONL sink (kind="serve"), the same machine-readable
channel train/eval metrics use.

Since the telemetry layer (ISSUE 5) the meters are the SHARED registry
instruments (telemetry/registry.py) — the same Counter/Histogram
machinery, reservoir, and nearest-rank percentile math train-side
telemetry reports through, so serve and train speak one schema. Each
``ServeMetrics`` owns a fresh ``Registry`` instance because it is a
bounded observation WINDOW (benches install a new one per load point);
pass ``registry=`` to aggregate into an external one instead. The
serve_bench JSON fields are unchanged — snapshot() is field-for-field
what it was before the migration.
"""

from __future__ import annotations

import time

from distribuuuu_tpu.telemetry.registry import Registry, percentile
from distribuuuu_tpu.utils.jsonlog import metrics_log


class ServeMetrics:
    """Thread-safe accumulator; one instance per observation window (the
    engine's is swappable — benches install a fresh one per load point)."""

    def __init__(self, max_samples: int = 65536, registry: Registry | None = None):
        self.max_samples = max_samples
        self.registry = registry or Registry()
        self._lat = self.registry.histogram("serve.latency_s", max_samples)
        self._t0 = time.perf_counter()

    def record_batch(
        self, n: int, bucket: int, batch_s: float, latencies_s: list[float]
    ) -> None:
        reg = self.registry
        reg.counter("serve.requests").inc(n)
        reg.counter("serve.batches").inc(1)
        reg.counter("serve.occ_filled").inc(n)
        reg.counter("serve.occ_slots").inc(bucket)
        reg.counter("serve.batch_s").inc(batch_s)
        for lat in latencies_s:
            self._lat.observe(lat)

    def record_rejection(self) -> None:
        self.registry.counter("serve.rejected").inc(1)

    def _count(self, name: str) -> float:
        return self.registry.counter(name).value

    def mean_batch_ms(self) -> float:
        """Recent per-batch service time — drives retry-after estimates."""
        n_b = self._count("serve.batches")
        if not n_b:
            return 0.0
        return self._count("serve.batch_s") / n_b * 1e3

    def snapshot(self) -> dict:
        lat = self._lat.values()  # sorted reservoir
        n_req = self._count("serve.requests")
        n_rej = self._count("serve.rejected")
        n_b = self._count("serve.batches")
        filled = self._count("serve.occ_filled")
        slots = self._count("serve.occ_slots")
        batch_s = self._count("serve.batch_s")
        window = max(time.perf_counter() - self._t0, 1e-9)
        return {
            "requests": int(n_req),
            "rejected": int(n_rej),
            "batches": int(n_b),
            "throughput_rps": round(n_req / window, 2),
            "p50_ms": round(percentile(lat, 0.50) * 1e3, 3),
            "p90_ms": round(percentile(lat, 0.90) * 1e3, 3),
            "p99_ms": round(percentile(lat, 0.99) * 1e3, 3),
            "mean_ms": round(sum(lat) / len(lat) * 1e3, 3) if lat else 0.0,
            "batch_occupancy": round(filled / slots, 4) if slots else 0.0,
            "mean_batch_ms": round(batch_s / n_b * 1e3, 3) if n_b else 0.0,
            "window_s": round(window, 3),
        }

    def emit(self, **extra) -> None:
        """One JSONL record via the shared sink (no-op until
        ``setup_metrics_log`` ran — same contract as train metrics; the
        record also mirrors into the per-rank telemetry sink)."""
        metrics_log("serve", **self.snapshot(), **extra)
