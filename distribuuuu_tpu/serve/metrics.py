"""Serving observability: latency histogram, batch occupancy, throughput.

Per-request latency is measured enqueue → response-demux (the engine-level
number a load balancer would see, excluding client transport). Snapshots
report p50/p90/p99 from a bounded reservoir, batch occupancy (requests
per dispatched bucket slot — the padding-waste gauge), and throughput
over the observation window; ``emit()`` lands a snapshot in the existing
``utils/jsonlog.py`` JSONL sink (kind="serve"), the same machine-readable
channel train/eval metrics use.
"""

from __future__ import annotations

import random
import threading
import time

from distribuuuu_tpu.utils.jsonlog import metrics_log


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (0 < q ≤ 1)."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1, int(q * len(sorted_vals) + 0.5) - 1))
    return sorted_vals[idx]


class ServeMetrics:
    """Thread-safe accumulator; one instance per observation window (the
    engine's is swappable — benches install a fresh one per load point)."""

    def __init__(self, max_samples: int = 65536):
        self.max_samples = max_samples
        self._lock = threading.Lock()
        self._lat: list[float] = []  # seconds; reservoir-capped
        self._seen = 0  # latencies offered to the reservoir
        self._n_requests = 0
        self._n_rejected = 0
        self._n_batches = 0
        self._occ_filled = 0
        self._occ_slots = 0
        self._batch_s = 0.0
        self._t0 = time.perf_counter()

    def record_batch(
        self, n: int, bucket: int, batch_s: float, latencies_s: list[float]
    ) -> None:
        with self._lock:
            self._n_requests += n
            self._n_batches += 1
            self._occ_filled += n
            self._occ_slots += bucket
            self._batch_s += batch_s
            for lat in latencies_s:
                self._seen += 1
                if len(self._lat) < self.max_samples:
                    self._lat.append(lat)
                else:  # reservoir sampling keeps percentiles unbiased
                    j = random.randrange(self._seen)
                    if j < self.max_samples:
                        self._lat[j] = lat

    def record_rejection(self) -> None:
        with self._lock:
            self._n_rejected += 1

    def mean_batch_ms(self) -> float:
        """Recent per-batch service time — drives retry-after estimates."""
        with self._lock:
            if not self._n_batches:
                return 0.0
            return self._batch_s / self._n_batches * 1e3

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._lat)
            n_req, n_rej = self._n_requests, self._n_rejected
            n_b = self._n_batches
            filled, slots = self._occ_filled, self._occ_slots
            batch_s = self._batch_s
        window = max(time.perf_counter() - self._t0, 1e-9)
        return {
            "requests": n_req,
            "rejected": n_rej,
            "batches": n_b,
            "throughput_rps": round(n_req / window, 2),
            "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "p90_ms": round(_percentile(lat, 0.90) * 1e3, 3),
            "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
            "mean_ms": round(sum(lat) / len(lat) * 1e3, 3) if lat else 0.0,
            "batch_occupancy": round(filled / slots, 4) if slots else 0.0,
            "mean_batch_ms": round(batch_s / n_b * 1e3, 3) if n_b else 0.0,
            "window_s": round(window, 3),
        }

    def emit(self, **extra) -> None:
        """One JSONL record via the shared sink (no-op until
        ``setup_metrics_log`` ran — same contract as train metrics)."""
        metrics_log("serve", **self.snapshot(), **extra)
