"""Campaign DSL: seeded, trace-driven serving traffic as data (ISSUE 16c).

A campaign YAML declares WHAT traffic a fleet must survive — not code:

.. code-block:: yaml

    campaign: 1
    name: flash_crowd
    seed: 23
    interval_s: 1.0
    models:
      - {name: resnet18, slo_class: standard, p99_slo_ms: 400}
    rules:
      - {kind: p99-breach, threshold: 350.0, window_s: 2, min_steps: 4}
    phases:
      - {name: control, kind: steady, duration_s: 6, rate_rps: 3,
         expect: []}
      - {name: crowd, kind: flash, duration_s: 10, rate_rps: 3,
         burst_x: 40, burst_window: [0.3, 0.7],
         expect: [p99-breach, backpressure]}

``build_schedule(spec)`` turns the spec into an explicit request
schedule — a list of ``(t_seconds, model, size)`` tuples — via an
inhomogeneous-Poisson thinning sampler over a per-phase rate curve,
driven ONLY by ``numpy.random.default_rng(seed)``. Same YAML + same
seed ⇒ byte-identical schedule (``schedule_hash`` pins this in tier-1
and in the committed SERVE_CAMPAIGN_r*.json artifact); the runner
replays it open-loop against a real fleet, so a campaign is a
reproducible experiment, not a load-test vibe.

Phase kinds (rate curves over phase-relative u ∈ [0, 1)):

* ``steady``         — constant ``rate_rps`` (control phases).
* ``diurnal``        — raised-cosine trough→peak→trough between
                       ``rate_rps`` and ``peak_rps`` (one "day").
* ``flash``          — ``rate_rps`` with a ``burst_x`` multiplier
                       inside ``burst_window`` (flash crowd).
* ``heavy_tail``     — steady rate, Pareto(``size_alpha``) request
                       sizes clamped to ``size_max`` (a "request" of
                       size k is k back-to-back dispatches: the
                       heavy-tail work-size mix).
* ``rolling_update`` — steady rate; the runner triggers
                       ``update`` (model weight swap via draining
                       restarts) at ``at_frac`` of the phase.

Each phase carries ``expect`` — the exact alert-kind set the rule
engine must raise during that phase (empty for control). The runner
scores raised == expected per phase; exact match is the verdict.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math

import numpy as np

PHASE_KINDS = ("steady", "diurnal", "flash", "heavy_tail", "rolling_update")

# rule kinds a campaign may arm: the runner builds serve-shaped
# snapshots (no training plane), so only serve-evaluable kinds make
# sense here. Validated at load so a typo fails the spec, not the run.
CAMPAIGN_RULE_KINDS = (
    "p99-breach",
    "backpressure",
    "slo-breach",
    "degrade-spill",
    "recompile-storm",
)

_PHASE_KEYS = {
    "name", "kind", "duration_s", "rate_rps", "expect", "mix",
    "peak_rps", "burst_x", "burst_window", "size_alpha", "size_max",
    "update", "at_frac",
}
_MODEL_KEYS = {"name", "slo_class", "p99_slo_ms", "overflow_to"}
_SPEC_KEYS = {"campaign", "name", "seed", "interval_s", "models",
              "rules", "phases"}


@dataclasses.dataclass(frozen=True)
class PhaseSpec:
    name: str
    kind: str
    duration_s: float
    rate_rps: float
    expect: tuple
    mix: tuple  # ((model, weight), ...) — normalized at load
    peak_rps: float = 0.0
    burst_x: float = 1.0
    burst_window: tuple = (0.0, 0.0)
    size_alpha: float = 1.5
    size_max: int = 8
    update: dict | None = None
    at_frac: float = 0.25


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    name: str
    seed: int
    interval_s: float
    models: tuple   # (dict(name, slo_class, p99_slo_ms, overflow_to), ...)
    rules: tuple    # raw AlertRule spec dicts (fed to live.AlertRule)
    phases: tuple   # (PhaseSpec, ...)

    @property
    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)


def _mix_for(raw_mix, models) -> tuple:
    names = [m["name"] for m in models]
    if raw_mix is None:
        raw_mix = {names[0]: 1.0}
    unknown = sorted(set(raw_mix) - set(names))
    if unknown:
        raise ValueError(
            f"phase mix references unknown models {unknown}; declared: {names}"
        )
    total = float(sum(raw_mix.values()))
    if total <= 0:
        raise ValueError("phase mix weights must sum > 0")
    return tuple((m, float(w) / total) for m, w in sorted(raw_mix.items()))


def parse_campaign(doc: dict) -> CampaignSpec:
    """Validate a parsed campaign YAML document into a CampaignSpec.

    Strict like telemetry's AlertRule: unknown keys, unknown phase
    kinds, and unknown expect/rule kinds are errors — a campaign that
    silently ignores a typoed gate is worse than no campaign.
    """
    if not isinstance(doc, dict) or doc.get("campaign") != 1:
        raise ValueError("campaign YAML must set 'campaign: 1'")
    unknown = sorted(set(doc) - _SPEC_KEYS)
    if unknown:
        raise ValueError(f"unknown campaign keys: {unknown}")
    models = []
    for m in doc.get("models") or []:
        bad = sorted(set(m) - _MODEL_KEYS)
        if bad:
            raise ValueError(f"unknown model keys: {bad}")
        if not m.get("name"):
            raise ValueError("each campaign model needs a name")
        models.append({
            "name": str(m["name"]),
            "slo_class": str(m.get("slo_class", "standard")),
            "p99_slo_ms": (None if m.get("p99_slo_ms") is None
                           else float(m["p99_slo_ms"])),
            "overflow_to": m.get("overflow_to"),
        })
    if not models:
        raise ValueError("campaign needs at least one model")
    names = {m["name"] for m in models}
    for m in models:
        if m["overflow_to"] is not None and m["overflow_to"] not in names:
            raise ValueError(
                f"model {m['name']!r} overflows to undeclared "
                f"{m['overflow_to']!r}"
            )

    rules = tuple(dict(r) for r in doc.get("rules") or [])
    for r in rules:
        if r.get("kind") not in CAMPAIGN_RULE_KINDS:
            raise ValueError(
                f"campaign rule kind {r.get('kind')!r} not in "
                f"{CAMPAIGN_RULE_KINDS}"
            )

    phases = []
    for p in doc.get("phases") or []:
        bad = sorted(set(p) - _PHASE_KEYS)
        if bad:
            raise ValueError(f"unknown phase keys: {bad}")
        kind = p.get("kind")
        if kind not in PHASE_KINDS:
            raise ValueError(f"unknown phase kind {kind!r}; one of {PHASE_KINDS}")
        expect = tuple(p.get("expect") or ())
        bad_expect = sorted(set(expect) - set(CAMPAIGN_RULE_KINDS))
        if bad_expect:
            raise ValueError(
                f"phase {p.get('name')!r} expects un-armable kinds {bad_expect}"
            )
        armed = {r["kind"] for r in rules}
        missing = sorted(set(expect) - armed)
        if missing:
            raise ValueError(
                f"phase {p.get('name')!r} expects {missing} but the "
                f"campaign arms only {sorted(armed)}"
            )
        if kind == "rolling_update":
            upd = p.get("update") or {}
            if upd.get("model") not in names:
                raise ValueError(
                    "rolling_update phase needs update.model ∈ declared models"
                )
        bw = p.get("burst_window", (0.3, 0.7))
        phases.append(PhaseSpec(
            name=str(p.get("name", kind)),
            kind=kind,
            duration_s=float(p["duration_s"]),
            rate_rps=float(p["rate_rps"]),
            expect=expect,
            mix=_mix_for(p.get("mix"), models),
            peak_rps=float(p.get("peak_rps", 0.0)),
            burst_x=float(p.get("burst_x", 1.0)),
            burst_window=(float(bw[0]), float(bw[1])),
            size_alpha=float(p.get("size_alpha", 1.5)),
            size_max=int(p.get("size_max", 8)),
            update=p.get("update"),
            at_frac=float(p.get("at_frac", 0.25)),
        ))
    if not phases:
        raise ValueError("campaign needs at least one phase")

    return CampaignSpec(
        name=str(doc.get("name", "campaign")),
        seed=int(doc.get("seed", 0)),
        interval_s=float(doc.get("interval_s", 1.0)),
        models=tuple(models),
        rules=rules,
        phases=tuple(phases),
    )


def load_campaign(path: str) -> CampaignSpec:
    import yaml

    with open(path) as f:
        return parse_campaign(yaml.safe_load(f))


def _rate(phase: PhaseSpec, u: float) -> float:
    """Instantaneous arrival rate (rps) at phase-relative u ∈ [0, 1)."""
    if phase.kind == "diurnal":
        peak = max(phase.peak_rps, phase.rate_rps)
        return phase.rate_rps + (peak - phase.rate_rps) * 0.5 * (
            1.0 - math.cos(2.0 * math.pi * u)
        )
    if phase.kind == "flash":
        lo, hi = phase.burst_window
        if lo <= u < hi:
            return phase.rate_rps * phase.burst_x
        return phase.rate_rps
    # steady / heavy_tail / rolling_update: constant
    return phase.rate_rps


def _rate_max(phase: PhaseSpec) -> float:
    if phase.kind == "diurnal":
        return max(phase.peak_rps, phase.rate_rps)
    if phase.kind == "flash":
        return phase.rate_rps * max(phase.burst_x, 1.0)
    return phase.rate_rps


def _pick_model(mix: tuple, r: float) -> str:
    acc = 0.0
    for name, w in mix:
        acc += w
        if r < acc:
            return name
    return mix[-1][0]


def build_schedule(spec: CampaignSpec) -> list:
    """Expand the spec into ``[(t, model, size), ...]`` sorted by t.

    Inhomogeneous Poisson via thinning: draw candidate arrivals at the
    phase's max rate, accept with probability rate(u)/rate_max. All
    randomness flows from ``default_rng(spec.seed)`` in a fixed draw
    order, so the schedule is a pure function of (YAML, seed) — the
    determinism pin hashes exactly this output.
    """
    rng = np.random.default_rng(spec.seed)
    out = []
    t_base = 0.0
    for phase in spec.phases:
        rmax = _rate_max(phase)
        if rmax > 0:
            t = 0.0
            while True:
                t += float(rng.exponential(1.0 / rmax))
                if t >= phase.duration_s:
                    break
                u = t / phase.duration_s
                if float(rng.random()) * rmax > _rate(phase, u):
                    continue
                model = _pick_model(phase.mix, float(rng.random()))
                size = 1
                if phase.kind == "heavy_tail":
                    draw = float(rng.pareto(phase.size_alpha))
                    size = 1 + min(phase.size_max - 1, int(draw))
                out.append((round(t_base + t, 6), model, size))
        t_base += phase.duration_s
    out.sort(key=lambda r: r[0])
    return out


def schedule_hash(schedule: list) -> str:
    """sha256 over the canonical JSON of the schedule — the determinism
    pin recorded in SERVE_CAMPAIGN_r*.json and asserted in tier-1."""
    blob = json.dumps(
        [[f"{t:.6f}", m, s] for t, m, s in schedule], separators=(",", ":")
    ).encode()
    return hashlib.sha256(blob).hexdigest()


def phase_window(spec: CampaignSpec, index: int) -> tuple:
    """Absolute (t_start, t_end) seconds of phase ``index``."""
    start = sum(p.duration_s for p in spec.phases[:index])
    return start, start + spec.phases[index].duration_s
