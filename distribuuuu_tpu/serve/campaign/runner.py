"""Campaign runner: replay a seeded schedule against a real fleet and
let the alert-rule engine referee every phase (ISSUE 16c).

The runner is the serving-side analogue of soak.py's interval matrix:
it OPEN-LOOP replays the schedule ``build_schedule`` produced (arrival
times are absolute, not feedback-coupled — a saturated fleet faces the
same offered load a healthy one does, which is what makes backpressure
observable), samples router-derived snapshots every ``interval_s``, and
feeds them to a FRESH ``RuleEngine`` per phase armed with the
campaign's rules. A phase passes iff the raised alert-kind set equals
its ``expect`` list EXACTLY — control phases must stay silent, so a
rule that false-positives fails the campaign just as loudly as one
that misses.

The snapshots are serve-shaped (``totals.steps`` counts served
requests; the training-plane fields are zeroed), so campaigns may arm
only the serve-evaluable kinds in ``dsl.CAMPAIGN_RULE_KINDS``:
p99-breach, backpressure, slo-breach, degrade-spill, recompile-storm.

``rolling_update`` phases trigger ``MultiModelFleet.rolling_update``
mid-phase (at ``at_frac`` of the phase) while the schedule keeps
arriving; the phase record pins ``logits_changed`` (a fixed probe's
logits differ across the swap) and ``failed_during`` (the drain
ordering promises zero).
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from distribuuuu_tpu.serve import protocol
from distribuuuu_tpu.serve.campaign import dsl
from distribuuuu_tpu.telemetry import tracectx
from distribuuuu_tpu.telemetry.live import SNAPSHOT_SCHEMA, AlertRule, RuleEngine
from distribuuuu_tpu.utils.logger import get_logger

_BACKOFF = ("queue_full", "draining", "no_routable_replicas")


class CampaignRunner:
    """Replays one ``CampaignSpec`` against a router (in-process; the
    router→replica hops are the real framed sockets).

    ``payload_for(model)`` returns one raw request payload for that
    model (the runner wraps it in the model envelope itself). ``fleet``
    (a MultiModelFleet) is only needed for rolling_update phases.
    """

    def __init__(self, spec: dsl.CampaignSpec, router, *, payload_for,
                 fleet=None, max_workers: int = 32,
                 trace_sample: float = 0.0):
        self.spec = spec
        self.router = router
        self.fleet = fleet
        self._payload_for = payload_for
        # ISSUE 20: fraction of generate requests that open a trace at
        # the campaign edge (head-based deterministic sampling); 0.0
        # keeps every frame byte-identical to an untraced campaign
        self._trace_sample = float(trace_sample)
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="campaign"
        )
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._bounds = [
            dsl.phase_window(spec, i) for i in range(len(spec.phases))
        ]
        self._starts = [b[0] for b in self._bounds]
        self._counts = [
            {"sent": 0, "ok": 0, "busy": 0, "failed": 0, "unknown_model": 0}
            for _ in spec.phases
        ]
        self._t0 = 0.0
        self.logger = get_logger()

    # -- load generation ---------------------------------------------------
    def _phase_index(self, t: float) -> int:
        return max(0, bisect.bisect_right(self._starts, t) - 1)

    def _job(self, t: float, model: str, size: int) -> None:
        pi = self._phase_index(t)
        payload = self._payload_for(model)
        # LM campaigns (config/campaigns/lm_decode.yaml): payload_for
        # returns an ``op="generate"`` ctrl frame — the model rides IN
        # the ctrl frame (dispatch_stream contract), not the envelope,
        # and the final streamed frame is what classifies the request
        generate = payload.startswith(protocol.CTRL_MAGIC)
        frame = payload if generate else protocol.model_envelope(
            model, payload
        )
        for _ in range(size):
            if self._stop.is_set():
                return
            cls = "failed"
            try:
                if generate:
                    req, ctx, esid = frame, None, ""
                    if self._trace_sample > 0.0:
                        # open a per-request trace at the campaign edge
                        # (ISSUE 20): the edge span is the tree's root;
                        # the router re-points the parent at its own
                        # dispatch span, so exemplar-named traces render
                        # as connected waterfalls
                        ctx = tracectx.open_trace(self._trace_sample)
                        if ctx is not None:
                            esid = tracectx.new_span_id()
                            ctrl = protocol.parse_ctrl(frame) or {}
                            ctrl.update(
                                tracectx.to_fields(ctx.child(esid))
                            )
                            req = protocol.CTRL_MAGIC + json.dumps(
                                ctrl
                            ).encode("utf-8")
                    # final frame of the stream: a clean done frame has
                    # no "error" key; a mid-stream failure rides the done
                    # frame itself, so classify on the parsed record
                    t_req = time.perf_counter()
                    rec = json.loads(self.router.dispatch_generate(
                        req, model=model
                    ))
                    err = rec.get("error")
                    if err is None and rec.get("stream") == "done":
                        cls = "ok"
                    elif err in _BACKOFF:
                        cls = "busy"
                    elif err == "unknown_model":
                        cls = "unknown_model"
                    tracectx.emit_trace_span(
                        ctx, "client.request", t_req,
                        time.perf_counter() - t_req, parent="",
                        span_id=esid, ok=(err is None),
                    )
                    with self._lock:
                        self._counts[pi]["sent"] += 1
                        self._counts[pi][cls] += 1
                    continue
                resp = self.router.dispatch(frame)
                if not resp.startswith(b'{"error"'):
                    cls = "ok"
                else:
                    err = json.loads(resp).get("error")
                    if err in _BACKOFF:
                        cls = "busy"
                    elif err == "unknown_model":
                        cls = "unknown_model"
            except Exception:  # noqa: BLE001 — load-gen must not die
                cls = "failed"
            with self._lock:
                self._counts[pi]["sent"] += 1
                self._counts[pi][cls] += 1

    def _replay(self, schedule: list) -> None:
        for t, model, size in schedule:
            delay = self._t0 + t - time.perf_counter()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            self._pool.submit(self._job, t, model, size)

    # -- refereeing --------------------------------------------------------
    def _snapshot(self) -> dict:
        win = self.router.window_stats(max(2.0 * self.spec.interval_s, 1.0))
        st = self.router.stats()
        return {
            "schema": SNAPSHOT_SCHEMA,
            "steps": 0,
            "totals": {"steps": int(st.get("requests", 0))},
            "compiles": {"count": 0},
            "events": {"stall": 0, "nonfinite": 0},
            "serve": {
                "p50_ms": float(win.get("p50_ms", 0.0)),
                "p99_ms": float(win.get("p99_ms", 0.0)),
                "window_samples": int(win.get("samples", 0)),
                "queue_depth": int(win.get("queue_depth", 0)),
                "rejected": int(st.get("rejected", 0)),
                "degraded": int(st.get("degraded", 0)),
                # worst traced samples of the window (ISSUE 20): the
                # rule engine copies these ids onto p99-breach /
                # backpressure alerts as exemplar_trace_ids
                "exemplars": win.get("exemplars", []),
                "models": win.get("models", {}),
            },
        }

    def _probe_logits(self, model: str):
        frame = protocol.model_envelope(model, self._payload_for(model))
        resp = self.router.dispatch(frame)
        if resp.startswith(b'{"error"'):
            return None
        return json.loads(resp).get("logits")

    def _run_update(self, phase: dsl.PhaseSpec, rec: dict) -> None:
        upd = dict(phase.update or {})
        model = upd.get("model")
        overrides = upd.get("overrides") or {}
        before = self._probe_logits(model)
        failed_before = self._counts_total("failed")
        try:
            self.fleet.rolling_update(model, overrides, wait=True)
            rec["ok"] = self.router.n_routable() >= 1
        except Exception as e:  # noqa: BLE001 — scored, not fatal
            rec["ok"] = False
            rec["error"] = f"{type(e).__name__}: {e}"
        after = self._probe_logits(model)
        rec.update(
            model=model,
            overrides=overrides,
            logits_changed=(
                before is not None and after is not None and before != after
            ),
            failed_during=self._counts_total("failed") - failed_before,
        )

    def _counts_total(self, key: str) -> int:
        with self._lock:
            return sum(c[key] for c in self._counts)

    # -- the campaign ------------------------------------------------------
    def run(self) -> dict:
        """Replay every phase; returns the campaign verdict dict that
        SERVE_CAMPAIGN_r*.json archives."""
        from distribuuuu_tpu.telemetry import spans

        spec = self.spec
        schedule = dsl.build_schedule(spec)
        sched_hash = dsl.schedule_hash(schedule)
        self.logger.info(
            "campaign %s: %d requests over %.0fs (seed %d, hash %s)",
            spec.name, len(schedule), spec.duration_s, spec.seed,
            sched_hash[:12],
        )
        self._t0 = time.perf_counter()
        replayer = threading.Thread(
            target=self._replay, args=(schedule,), daemon=True,
            name="campaign-replay",
        )
        replayer.start()

        phases = []
        for pi, phase in enumerate(spec.phases):
            engine = RuleEngine(
                [AlertRule(dict(r)) for r in spec.rules], spec.interval_s
            )
            raised: set = set()
            alerts: list = []
            degraded_at_start = int(
                self.router.stats().get("degraded", 0)
            )
            update_rec: dict | None = None
            update_thread = None
            if phase.kind == "rolling_update":
                update_rec = {}
                delay = phase.at_frac * phase.duration_s

                def trigger(rec=update_rec, delay=delay, ph=phase):
                    if not self._stop.wait(delay):
                        self._run_update(ph, rec)

                update_thread = threading.Thread(
                    target=trigger, daemon=True, name="campaign-update"
                )
                update_thread.start()

            t_end = self._t0 + self._bounds[pi][1]
            while not self._stop.is_set():
                remaining = t_end - time.perf_counter()
                if remaining <= 0:
                    # a rolling update may outlive its phase clock (warm-up
                    # gated respawn); keep refereeing until it lands
                    if update_thread is None or not update_thread.is_alive():
                        break
                self._stop.wait(min(spec.interval_s, max(remaining, 0.05)))
                snap = self._snapshot()
                for alert in engine.evaluate(snap):
                    raised.add(alert["rule"])
                    alerts.append(alert)
            if update_thread is not None:
                update_thread.join(timeout=120)

            snap = self._snapshot()
            with self._lock:
                counts = dict(self._counts[pi])
            ok = raised == set(phase.expect)
            if update_rec is not None:
                ok = ok and bool(update_rec.get("ok")) and bool(
                    update_rec.get("logits_changed")
                )
            rec = {
                "name": phase.name,
                "kind": phase.kind,
                "duration_s": phase.duration_s,
                "expected": sorted(phase.expect),
                "raised": sorted(raised),
                "ok": ok,
                "counts": counts,
                "degraded_delta": int(
                    snap["serve"]["degraded"] - degraded_at_start
                ),
                "p99_ms_end": snap["serve"]["p99_ms"],
                "alerts": alerts,
            }
            if update_rec is not None:
                rec["update"] = update_rec
            phases.append(rec)
            spans.emit_event(
                "campaign.phase",
                campaign=spec.name,
                phase=phase.name,
                expected_alerts=rec["expected"],
                raised_alerts=rec["raised"],
                ok=rec["ok"],
            )
            self.logger.info(
                "campaign %s phase %s: expected=%s raised=%s ok=%s %s",
                spec.name, phase.name, rec["expected"], rec["raised"],
                rec["ok"], counts,
            )

        self._stop.set()
        replayer.join(timeout=10)
        self._pool.shutdown(wait=True)

        alerts_exact = all(p["ok"] for p in phases)
        control_clean = all(
            not p["raised"] for p in phases if not p["expected"]
        )
        st = self.router.stats()
        verdict = {
            "campaign": spec.name,
            "seed": spec.seed,
            "interval_s": spec.interval_s,
            "schedule_hash": sched_hash,
            "requests_scheduled": len(schedule),
            "phases": phases,
            "models": st.get("models", {}),
            "alerts_exact": alerts_exact,
            "control_clean": control_clean,
            "ok": alerts_exact and control_clean,
        }
        if st.get("length_classes"):
            # length-aware fleet (ISSUE 19c): the per-class admission and
            # latency ledger is the artifact's starvation evidence
            verdict["length_classes"] = st["length_classes"]
            verdict["long_prompt_threshold"] = st.get(
                "long_prompt_threshold"
            )
        spans.emit_event(
            "campaign.verdict",
            campaign=spec.name,
            phases=len(phases),
            alerts_exact=alerts_exact,
            control_clean=control_clean,
            ok=verdict["ok"],
        )
        return verdict

    def stop(self) -> None:
        self._stop.set()
