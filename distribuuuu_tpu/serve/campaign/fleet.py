"""Multi-model fleet: one router, N model variants, per-model pools.

ISSUE 16a — the multiplexing layer. A ``MultiModelFleet`` composes the
existing single-model building blocks instead of replacing them:

* ONE ``Router`` fronts the whole fleet. Replicas are tagged with the
  model id they serve (``Router.add_replica(..., model=...)``), model
  ids are registered with their SLO class
  (``Router.register_model``), and the model-envelope frames
  (``protocol.model_envelope``) steer each request to its model's
  replicas — with overflow to the configured cheap model when the
  expensive model saturates (the degrade-under-pressure path the
  campaign referee scores).
* One ``PoolManager`` PER MODEL owns that model's replica lifecycle.
  Each pool spawns the unchanged ``serve_net.py`` single-engine
  replica from its own dumped config (its own arch, its own
  ``SERVE.QUANTIZE`` dtype variant, its own AOT bucket set, its own
  telemetry subdir), so every replica stays shared-nothing and the
  serving protocol is untouched end to end.

Weight paging is the checkpoint story the repo already has: a model's
replicas restore ``MODEL.WEIGHTS`` (or seeded init) at spawn, and
``rolling_update`` pages new weights in mid-traffic by rewriting the
model's dumped config and draining-restarting its replicas one at a
time — zero failed requests by the PR 9 drain ordering, while OTHER
models' traffic never even reroutes.
"""

from __future__ import annotations

import os
import threading

from distribuuuu_tpu.serve.fleet.pool import PoolManager, spawn_serve_net
from distribuuuu_tpu.serve.fleet.router import Router
from distribuuuu_tpu.utils.logger import get_logger

# per-model override keys a fleet spec may set on top of the base cfg
_SPEC_KEYS = {"name", "arch", "replicas", "quantize", "overrides",
              "slo_class", "p99_slo_ms", "overflow_to"}


class MultiModelFleet:
    """N model variants behind one router.

    ``model_specs`` rows::

        {"name": "resnet50", "arch": "resnet50", "replicas": 1,
         "quantize": "", "overrides": {...merge_from_list pairs...},
         "slo_class": "premium", "p99_slo_ms": 300.0,
         "overflow_to": "resnet18"}

    ``name`` is the routing id (what request envelopes carry); ``arch``
    defaults to it. ``overrides`` is a flat {cfg_key: value} dict merged
    into that model's replica config.
    """

    def __init__(self, cfg, model_specs, *, out_dir: str | None = None):
        fl = cfg.SERVE.FLEET
        self.cfg = cfg
        self.out_dir = out_dir or cfg.OUT_DIR
        self.router = Router(
            request_timeout_s=fl.REQUEST_TIMEOUT_S,
            long_prompt_threshold=cfg.SERVE.LONG_PROMPT_THRESHOLD,
            short_p99_slo_ms=cfg.SERVE.SHORT_P99_SLO_MS,
            long_p99_slo_ms=cfg.SERVE.LONG_P99_SLO_MS,
        )
        self.pools: dict[str, PoolManager] = {}
        self._targets: dict[str, int] = {}
        self._cfg_paths: dict[str, str] = {}
        self.logger = get_logger()
        for spec in model_specs:
            bad = sorted(set(spec) - _SPEC_KEYS)
            if bad:
                raise ValueError(f"unknown fleet model-spec keys: {bad}")
            name = spec["name"]
            if name in self.pools:
                raise ValueError(f"duplicate fleet model id {name!r}")
            self.router.register_model(
                name,
                slo_class=spec.get("slo_class", "standard"),
                p99_slo_ms=spec.get("p99_slo_ms"),
                overflow_to=spec.get("overflow_to"),
            )
            model_dir = os.path.join(self.out_dir, f"model_{name}")
            cfg_path = self._dump_model_cfg(model_dir, spec)
            self._cfg_paths[name] = cfg_path
            self.pools[name] = PoolManager(
                self.router,
                spawn_serve_net(
                    cfg_path, host=cfg.SERVE.HOST,
                    out_dir=os.path.join(model_dir, "fleet"),
                ),
                model=name,
                host=cfg.SERVE.HOST,
                min_replicas=0,
                max_replicas=fl.MAX_REPLICAS,
                warmup_timeout_s=fl.WARMUP_TIMEOUT_S,
                health_period_s=fl.HEALTH_PERIOD_S,
                health_fails=fl.HEALTH_FAILS,
            )
            self._targets[name] = int(spec.get("replicas", 1))

    def _dump_model_cfg(self, model_dir: str, spec: dict) -> str:
        """Materialize this model's replica config: base cfg + arch +
        dtype variant + overrides, each model in its own telemetry
        subdir so replica sink files never collide across models."""
        os.makedirs(model_dir, exist_ok=True)
        mcfg = self.cfg.clone()
        mcfg.defrost()
        mcfg.MODEL.ARCH = spec.get("arch") or spec["name"]
        mcfg.SERVE.QUANTIZE = spec.get("quantize", "")
        mcfg.OUT_DIR = model_dir
        flat = []
        for key, val in (spec.get("overrides") or {}).items():
            flat += [key, val]
        if flat:
            mcfg.merge_from_list(flat)
        mcfg.freeze()
        cfg_path = os.path.join(model_dir, "replica_cfg.yaml")
        with open(cfg_path, "w") as f:
            f.write(mcfg.dump())
        return cfg_path

    # -- lifecycle ---------------------------------------------------------
    def start(self, *, wait: bool = True) -> "MultiModelFleet":
        """Spawn every model's replicas concurrently (warm-up gated per
        replica as always); with ``wait``, block until the whole fleet
        is routable, then start per-pool supervision."""
        for name, pool in self.pools.items():
            pool.set_target(self._targets[name])
            pool._spawn_toward_target()
        if wait:
            # per pool: each pool only sees (and only waits on) its own
            # model's replicas — warm-ups still overlap, this loop just
            # joins them
            for name, pool in self.pools.items():
                pool._wait_routable(self._targets[name])
        for pool in self.pools.values():
            pool.start_supervisor()
        return self

    def rolling_update(self, model: str, overrides: dict,
                       *, wait: bool = True) -> dict:
        """Page new weights/config into ONE model mid-traffic: rewrite
        that model's dumped replica config with ``overrides``
        ({cfg_key: value}), then draining-restart its replicas one at a
        time. Other models' pools are untouched."""
        pool = self.pools[model]
        cfg_path = self._cfg_paths[model]
        mcfg = self.cfg.clone()
        mcfg.defrost()
        mcfg.merge_from_file(cfg_path)
        flat = []
        for key, val in overrides.items():
            flat += [key, val]
        if flat:
            mcfg.merge_from_list(flat)
        mcfg.freeze()
        with open(cfg_path, "w") as f:
            f.write(mcfg.dump())
        rids = [r.id for r in self.router.replicas() if r.model == model]
        self.logger.info(
            "fleet: rolling update of %s over replicas %s (%s)",
            model, rids, overrides,
        )
        for rid in rids:
            pool.restart_replica(rid, wait=wait)
        return {"model": model, "replicas": rids, "overrides": overrides}

    def serve(self, listener, should_stop, poll_s: float = 0.25) -> None:
        self.router.serve(
            listener, should_stop, poll_s=poll_s,
            emit_interval_s=self.cfg.SERVE.FLEET.EMIT_INTERVAL_S,
        )

    def shutdown(self) -> None:
        threads = [
            threading.Thread(target=p.shutdown, daemon=True)
            for p in self.pools.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        self.router.emit_telemetry()
