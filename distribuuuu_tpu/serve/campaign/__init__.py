"""Traffic-campaign plane (ISSUE 16): multi-model fleet multiplexing,
quantized bucket variants, and trace-driven serving campaigns.

``dsl``    — campaign YAML → seeded deterministic request schedule.
``fleet``  — MultiModelFleet: one router, per-model replica pools.
``runner`` — replay a schedule against a real fleet, alert-rule referee.

``tools/serve_campaign.py`` composes all three into the committed
SERVE_CAMPAIGN_r*.json artifact; docs/RUNBOOK.md "Running a traffic
campaign" is the operator recipe.
"""

from distribuuuu_tpu.serve.campaign.dsl import (  # noqa: F401
    CampaignSpec,
    build_schedule,
    load_campaign,
    parse_campaign,
    schedule_hash,
)
from distribuuuu_tpu.serve.campaign.runner import CampaignRunner  # noqa: F401
