"""Length-prefixed socket frontend + one-shot batch mode for serve_net.

Wire format: every frame is a 4-byte big-endian payload length followed by
the payload. Request payloads, auto-detected:

* ``.npy`` bytes (numpy magic ``\\x93NUMPY``) holding an (H, W, 3) uint8
  image — decoded without a PIL round-trip;
* a ``(TRAIN.IM_SIZE, TRAIN.IM_SIZE, 3)`` float32 ``.npy`` — treated as
  ALREADY val-transformed (the engine's float input path) and submitted
  as-is;
* anything else — an encoded image file (JPEG/PNG/…, PIL-decodable).

Raw images get the SAME val transform pipeline evaluation uses (shorter
side to ``TEST.IM_SIZE``, center-crop ``TRAIN.IM_SIZE``, normalization
placement per ``DATA.DEVICE_NORMALIZE`` — data/transforms.py), so a
served prediction is bit-for-bit the offline ``test_net.py`` prediction
for the same file.

Response payload: JSON — ``{"pred", "topk", "logits"}`` on success;
``{"error": ..., "retry_after_ms"?}`` on rejection/failure (backpressure
maps to ``"queue_full"`` + retry hint, drain to ``"draining"``).

Batch mode (``run_batch``) bypasses the socket: a ``.npy`` of N
val-transformed images in (file or stdin), an ``(N, num_classes)`` float32
logits ``.npy`` out (file or stdout) — the CI-testable path.
"""

from __future__ import annotations

import io
import json
import os
import socket
import struct
import sys
import threading
import time

import numpy as np

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.serve.admission import EngineClosedError, QueueFullError
from distribuuuu_tpu.telemetry import tracectx

_NPY_MAGIC = b"\x93NUMPY"
MAX_FRAME = 64 << 20  # refuse absurd frames before allocating for them

# Control frames: a payload starting with this magic is a JSON control
# request, not an image. The fleet layer (serve/fleet/) uses op="stats" as
# the replica health/load endpoint — the pool's warm-up gate and health
# probes, and the router's queue-depth/occupancy reads, all ride the same
# length-prefixed connection clients use. The leading NUL byte cannot
# occur in any image or .npy payload, so detection is unambiguous.
CTRL_MAGIC = b"\x00DTPUCTL1"

# Model-id envelope (serve/campaign, multi-model fleets): magic, a 1-byte
# model-id length, the utf-8 model id, then the ORIGINAL request payload
# unchanged. Shares the NUL lead byte with control frames (unambiguous vs
# image payloads) but differs from CTRL_MAGIC at byte 5, so parse_ctrl
# rejects it and bare payloads keep their existing single-model meaning.
# The router strips the envelope before forwarding — replicas serve the
# same bytes they always did.
MODEL_MAGIC = b"\x00DTPUMDL1"

# Request-trace envelope (ISSUE 20): binary data payloads of TRACED
# requests ride ``tracectx.TRACE_MAGIC + u16 len + ctx JSON + payload``,
# OUTERMOST (a traced multi-model request is TRACE(MODEL(payload))).
# Same NUL-lead disambiguation as the other two magics; untraced
# payloads are byte-identical to the pre-tracing wire format. Traced
# ``op="generate"`` ctrl frames instead embed ``"trace": {...}`` in the
# ctrl JSON — peers that predate tracing ignore the extra key.


def ctrl_request(op: str, **fields) -> bytes:
    """Encode a control request payload (send it with ``send_frame``)."""
    return CTRL_MAGIC + json.dumps({"op": op, **fields}).encode()


def parse_ctrl(payload: bytes) -> dict | None:
    """The decoded control request, or None for a data (image) payload."""
    if not payload.startswith(CTRL_MAGIC):
        return None
    return json.loads(payload[len(CTRL_MAGIC):])


def model_envelope(model: str, payload: bytes) -> bytes:
    """Wrap a request payload with the model id it must route to."""
    mid = model.encode("utf-8")
    if not 0 < len(mid) < 256:
        raise ValueError(f"model id must be 1..255 utf-8 bytes, got {model!r}")
    return MODEL_MAGIC + bytes([len(mid)]) + mid + payload


def split_model_envelope(payload: bytes) -> tuple[str | None, bytes]:
    """(model_id, inner_payload) for an enveloped payload; (None, payload)
    for a bare one — single-model clients never change."""
    if not payload.startswith(MODEL_MAGIC):
        return None, payload
    n = payload[len(MODEL_MAGIC)]
    start = len(MODEL_MAGIC) + 1
    mid = payload[start:start + n]
    if len(mid) != n:
        raise ValueError("truncated model envelope")
    return mid.decode("utf-8"), payload[start + n:]


def replica_stats(engine) -> dict:
    """The replica-side stats snapshot a ``ctrl_request("stats")`` returns:
    the engine's metrics/queue view plus the process-global ``jit.compiles``
    counter (telemetry/runtime.py's compile listener) — how the fleet
    asserts zero steady-state recompiles across every replica."""
    from distribuuuu_tpu.telemetry import registry as telemetry_registry

    reg = telemetry_registry.get_registry()
    out = engine.stats()
    out.update(
        pid=os.getpid(),
        accepting=engine._admission.is_open,
        jit_compiles=int(reg.counter("jit.compiles").value),
        aot_compiles=int(reg.counter("serve.aot_compiles").value),
    )
    return out


# -- framing ----------------------------------------------------------------

def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None  # peer closed
        buf += chunk
    return buf


def recv_frame(sock: socket.socket) -> bytes | None:
    """One frame's payload, or None on clean EOF."""
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack(">I", hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds MAX_FRAME={MAX_FRAME}")
    return _recv_exact(sock, n)


# -- request decoding -------------------------------------------------------

def make_transform():
    """The val pipeline as a payload→engine-input function, captured from
    the global cfg (same geometry/normalization the val loader uses)."""
    from PIL import Image

    from distribuuuu_tpu.data.transforms import val_transform

    resize, crop = cfg.TEST.IM_SIZE, cfg.TRAIN.IM_SIZE
    normalize = not cfg.DATA.DEVICE_NORMALIZE

    def transform(payload: bytes) -> np.ndarray:
        if payload[: len(_NPY_MAGIC)] == _NPY_MAGIC:
            arr = np.load(io.BytesIO(payload), allow_pickle=False)
            if (
                arr.dtype == np.float32
                and arr.shape == (crop, crop, 3)
            ):
                return arr  # pre-transformed: the engine's float input path
            if arr.dtype != np.uint8 or arr.ndim != 3 or arr.shape[-1] != 3:
                raise ValueError(
                    f"npy request must be (H, W, 3) uint8 raw or "
                    f"({crop}, {crop}, 3) float32 pre-transformed, got "
                    f"{arr.shape} {arr.dtype}"
                )
            img = Image.fromarray(arr)
        else:
            img = Image.open(io.BytesIO(payload)).convert("RGB")
        return val_transform(img, resize, crop, normalize=normalize)

    return transform


# -- socket server ----------------------------------------------------------

def open_listener(host: str, port: int) -> socket.socket:
    """Bound+listening socket (port 0 ⇒ ephemeral; read
    ``sock.getsockname()[1]`` for the real port)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock


def _handle_conn(engine, conn: socket.socket, transform, topk: int) -> None:
    with conn:
        while True:
            try:
                payload = recv_frame(conn)
            except (OSError, ValueError):
                return
            if payload is None:
                return
            trace = None
            if payload.startswith(tracectx.TRACE_MAGIC):
                # traced binary payload: strip the context so the inner
                # bytes the engine sees are exactly the untraced bytes; a
                # torn envelope gets a clean refusal, never a half-parse
                try:
                    trace, payload = tracectx.split_payload(payload)
                except ValueError:
                    try:
                        send_frame(conn, json.dumps(
                            {"error": "bad_trace_envelope"}
                        ).encode())
                    except OSError:
                        return
                    continue
            if payload.startswith(MODEL_MAGIC):
                # a fleet router already routed this here; a direct client
                # may also send enveloped requests — either way the replica
                # serves the inner payload (it IS the model)
                try:
                    _model, payload = split_model_envelope(payload)
                except (ValueError, IndexError):
                    try:
                        send_frame(conn, json.dumps(
                            {"error": "bad_model_envelope"}
                        ).encode())
                    except OSError:
                        return
                    continue
            ctrl = parse_ctrl(payload) if payload.startswith(CTRL_MAGIC[:1]) else None
            if ctrl is not None:
                if ctrl.get("op") == "stats":
                    resp = replica_stats(engine)
                elif ctrl.get("op") == "generate":
                    # the LM generation plane's STREAMING ctrl frame
                    # (lm/service.py): one token frame per decode step on
                    # this same connection, a done frame last — the fleet
                    # router relays the whole sequence
                    if not hasattr(engine, "submit") or not hasattr(
                        engine, "prompt_len"
                    ):
                        resp = {
                            "error": "not_a_generation_replica",
                            "detail": "this replica serves an image arch; "
                                      "generate needs a gpt_* MODEL.ARCH",
                        }
                    else:
                        from distribuuuu_tpu.lm import service as lm_service

                        try:
                            lm_service.handle_generate(
                                engine, ctrl,
                                lambda p: send_frame(conn, p),
                            )
                        except OSError:
                            return
                        continue
                else:
                    resp = {"error": f"unknown control op {ctrl.get('op')!r}"}
                try:
                    send_frame(conn, json.dumps(resp).encode())
                except OSError:
                    return
                continue
            t_req = time.perf_counter()
            try:
                fut = engine.submit(transform(payload))
                logits = fut.result()
                order = np.argsort(logits)[::-1][: max(1, topk)]
                resp = {
                    "pred": int(order[0]),
                    "topk": [int(i) for i in order],
                    "logits": [float(v) for v in logits],
                }
            except QueueFullError as e:
                resp = {
                    "error": "queue_full",
                    "retry_after_ms": round(e.retry_after_ms, 1),
                }
            except EngineClosedError:
                resp = {"error": "draining"}
            except Exception as e:  # noqa: BLE001 — per-request fault isolation
                resp = {"error": f"{type(e).__name__}: {e}"}
            tracectx.emit_trace_span(
                trace, "replica.handle", t_req,
                time.perf_counter() - t_req,
                ok=("error" not in resp),
            )
            try:
                send_frame(conn, json.dumps(resp).encode())
            except OSError:
                return


def serve_forever(
    engine,
    listener: socket.socket,
    should_stop,
    topk: int = 5,
    poll_s: float = 0.25,
) -> None:
    """Accept loop: one handler thread per connection, requests multiplexed
    through the shared engine. Polls ``should_stop()`` (the SIGTERM drain
    flag, admission.drain_requested) between accepts; on stop it closes the
    listener, drains the engine (every accepted request completes), and
    joins the handlers — the graceful-exit half of preemption handling."""
    transform = make_transform()
    listener.settimeout(poll_s)
    handlers: list[threading.Thread] = []
    try:
        while not should_stop():
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                continue
            t = threading.Thread(
                target=_handle_conn,
                args=(engine, conn, transform, topk),
                daemon=True,
            )
            t.start()
            handlers.append(t)
    finally:
        listener.close()
        engine.drain()
        for t in handlers:
            t.join(timeout=5.0)


# -- batch mode -------------------------------------------------------------

def run_batch(engine, in_path: str, out_path: str) -> int:
    """One-shot batch mode: ``.npy`` images in, ``.npy`` logits out
    ('-' = stdin/stdout). Input must be (N, IM, IM, 3) in the engine's
    input dtype (val-transformed). Submits through the normal admission/
    batching path — backpressure is honored by waiting out the retry
    hint, so N may exceed SERVE.MAX_QUEUE. Returns N."""
    src = sys.stdin.buffer if in_path == "-" else in_path
    images = np.load(src, allow_pickle=False)
    if images.ndim != 4:
        raise ValueError(f"batch input must be (N, H, W, 3), got {images.shape}")
    futs = []
    for row in images:
        while True:
            try:
                futs.append(engine.submit(row))
                break
            except QueueFullError as e:  # back off as a client would
                time.sleep(e.retry_after_ms / 1e3)
    logits = np.stack([f.result() for f in futs]).astype(np.float32)
    if out_path == "-":
        np.save(sys.stdout.buffer, logits)
        sys.stdout.buffer.flush()
    else:
        np.save(out_path, logits)
    return len(images)
