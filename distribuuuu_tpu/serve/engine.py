"""Dynamic micro-batching inference engine (the serving hot path).

Turns the trainer's eval forward into an online service. Design, in the
order requests experience it:

1. **Admission** (``admission.AdmissionController``): ``submit`` rejects
   beyond ``SERVE.MAX_QUEUE`` pending requests with a retry-after hint —
   bounded queues keep overload from becoming unbounded latency.
2. **Dynamic micro-batching**: a batcher thread assembles up to
   ``SERVE.MAX_BATCH`` requests, or flushes ``SERVE.MAX_WAIT_MS`` after
   the oldest waiting request arrived — the batching-delay/occupancy
   trade the Gemma-on-TPU serving study (PAPERS.md, 2605.25645) puts at
   the center of TPU serving economics.
3. **Bucketed shapes, compiled exactly once**: a batch of n pads (zero
   rows) to the smallest bucket ≥ n; every bucket shape is AOT-compiled
   at startup via ``jax.jit`` lowering (``.lower(...).compile()``), so
   steady-state serving NEVER hits the jit cache or recompiles — the
   dispatch-pipelining regime the TPU concurrency study (2011.03641)
   shows bounds small-batch latency. ``n_compiles``/``COMPILE_EVENTS``
   are the compilation-count hook tests assert on.
4. **Double-buffered dispatch**: XLA dispatch is async — the batcher
   hands the in-flight device computation to a completion thread through
   a depth-2 queue and immediately assembles batch k+1 while the device
   executes batch k. The depth bound is the backpressure that stops the
   host from racing arbitrarily far ahead of the device.
5. **Per-request futures**: the completion thread blocks on the device
   result, slices off the padding rows, and demuxes row i to request i's
   ``Future`` — padded logits never leave the engine.

The forward is the eval step's: ``model.apply(..., train=False)`` on
val-transformed input, with the trainer's dtype-gated in-graph
normalization (uint8 input ⇒ ``(x/255 − mean)/std`` on device — the
``DATA.DEVICE_NORMALIZE`` pipeline; float input arrives pre-normalized).
Served logits are numerically identical to ``test_model``'s
(tests/test_serve.py proves it, padding included).

Throughput beyond one chip: serving is latency-optimal at one single-chip
replica per chip (no cross-chip collective on the critical path) — run
one engine per local device (``SERVE.DEVICE``) behind any request-level
balancer, rather than sharding a tiny batch over the mesh.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from queue import Queue

import jax
import numpy as np

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.serve.admission import AdmissionController
from distribuuuu_tpu.serve.metrics import ServeMetrics
from distribuuuu_tpu.telemetry import registry as telemetry_registry
from distribuuuu_tpu.telemetry import spans

# Compilation-count hook: every AOT bucket compile appends its batch size.
# Steady-state serving must not grow this list (tests/test_serve.py).
COMPILE_EVENTS: list[int] = []


def default_buckets(max_batch: int) -> list[int]:
    """Powers of two up to ``max_batch``, plus ``max_batch`` itself —
    ≤ 2× padding waste at any occupancy with O(log) compiled shapes."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be ≥ 1, got {max_batch}")
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


class _Request:
    __slots__ = ("image", "future", "t_enq")

    def __init__(self, image: np.ndarray, t_enq: float):
        self.image = image
        self.future: Future = Future()
        self.t_enq = t_enq


class Engine:
    """Request-level serving engine over one device.

    ``variables`` is the eval-state dict ``{"params", "batch_stats"}``
    (what ``test_model`` feeds its eval step). Parameters default from
    ``cfg.SERVE``; pass explicit values for library/test use. ``submit``
    before ``start`` is allowed — requests queue until the threads run.
    """

    def __init__(
        self,
        model,
        variables: dict,
        im_size: int,
        *,
        max_batch: int | None = None,
        max_wait_ms: float | None = None,
        bucket_sizes: list[int] | None = None,
        max_queue: int | None = None,
        input_dtype=np.uint8,
        metrics: ServeMetrics | None = None,
        emit_interval_s: float = 10.0,
        quantize: str | None = None,
    ):
        self.model = model
        self._variables = variables
        self.im_size = int(im_size)
        self.max_batch = int(max_batch if max_batch is not None else cfg.SERVE.MAX_BATCH)
        wait = max_wait_ms if max_wait_ms is not None else cfg.SERVE.MAX_WAIT_MS
        self._max_wait_s = float(wait) / 1e3
        buckets = bucket_sizes or list(cfg.SERVE.BUCKET_SIZES) or default_buckets(
            self.max_batch
        )
        self.buckets = sorted(set(int(b) for b in buckets))
        if self.buckets[0] < 1 or self.buckets[-1] != self.max_batch:
            raise ValueError(
                f"SERVE.BUCKET_SIZES {self.buckets} must lie in [1, MAX_BATCH] "
                f"and include MAX_BATCH={self.max_batch} (a batch of n pads "
                "to the smallest bucket ≥ n; larger buckets would be dead "
                "compiled shapes)"
            )
        self.input_dtype = np.dtype(input_dtype)
        self.metrics = metrics or ServeMetrics()
        self._emit_interval_s = emit_interval_s
        self._admission = AdmissionController(
            max_queue if max_queue is not None else cfg.SERVE.MAX_QUEUE
        )

        # -- weight-only quantized variant (serve/quantize.py) ------------
        # "" = full precision; "bf16"/"int8" repack the weights BEFORE the
        # AOT compiles below, so every bucket executable bakes in the
        # variant — int8 weights dequantize in-graph per forward, trading
        # a cheap elementwise op for halved/quartered HBM weight traffic.
        mode = quantize if quantize is not None else str(cfg.SERVE.QUANTIZE)
        self.quantize_mode = mode
        self.quantize_meta = None
        if mode:
            from distribuuuu_tpu.serve import quantize as quantize_lib

            self._variables, self.quantize_meta = (
                quantize_lib.quantize_variables(variables, mode)
            )
            spans.emit_event(
                "serve.quantized",
                arch=cfg.MODEL.ARCH,
                mode=mode,
                bytes_before=self.quantize_meta["bytes_before"],
                bytes_after=self.quantize_meta["bytes_after"],
                leaves=self.quantize_meta["leaves"],
            )

        # -- AOT compile every bucket shape, exactly once, at startup -----
        self.n_compiles = 0
        self._compiled = {}
        jit_fwd = jax.jit(self._forward)
        for b in self.buckets:
            sds = jax.ShapeDtypeStruct(
                (b, self.im_size, self.im_size, 3), self.input_dtype
            )
            self._compiled[b] = jit_fwd.lower(self._variables, sds).compile()
            self.n_compiles += 1
            COMPILE_EVENTS.append(b)
        # AOT startup compiles in the shared registry (telemetry/): a
        # run_report over a serve run separates these expected compiles
        # from steady-state recompile storms (which bump jit.compiles
        # via the monitoring listener without bumping this)
        telemetry_registry.get_registry().counter(
            "serve.aot_compiles"
        ).inc(self.n_compiles)
        # cost-model ledger per bucket (telemetry/costmodel.py): flops /
        # bytes / HBM footprint of each serving shape, read straight off
        # the executables compiled above — no extra compile. The serve
        # half of run_report's MFU/headroom section.
        if cfg.TELEMETRY.COSTMODEL:
            from distribuuuu_tpu.telemetry import costmodel

            for b in self.buckets:
                label = (
                    f"serve_bucket_{b}_{mode}" if mode
                    else f"serve_bucket_{b}"
                )
                costmodel.capture_compiled(
                    self._compiled[b], label=label,
                    phase="serve", images=b, arch=cfg.MODEL.ARCH,
                )

        self._cond = threading.Condition()
        self._pending: deque[_Request] = deque()
        # depth-2 in-flight queue = the double buffer: batch k executing on
        # device, batch k+1 dispatched, batcher assembling k+2 blocks here
        self._inflight: Queue = Queue(maxsize=2)
        self._draining = False
        self._started = False
        self._batcher_t = threading.Thread(
            target=self._batcher, name="serve-batcher", daemon=True
        )
        self._completer_t = threading.Thread(
            target=self._completer, name="serve-completer", daemon=True
        )

    # -- model forward (traced once per bucket at startup) -----------------
    def _forward(self, variables, images):
        if self.quantize_mode == "int8":
            # in-graph dequant: int8 weights + per-channel scales expand to
            # f32 inside the traced forward — XLA fuses the expansion into
            # the consuming matmul/conv, so HBM reads stay int8-sized
            from distribuuuu_tpu.serve import quantize as quantize_lib

            variables = quantize_lib.dequantize_in_graph(variables)
        if images.dtype == np.uint8:
            # the DATA.DEVICE_NORMALIZE eval pipeline: host ships raw uint8,
            # normalization runs in-graph (identical formula/order to the
            # host path — data/transforms.py)
            from distribuuuu_tpu.data.transforms import normalize_in_graph

            images = normalize_in_graph(images)
        return self.model.apply(variables, images, train=False)

    # -- client surface ----------------------------------------------------
    def start(self) -> "Engine":
        self._batcher_t.start()
        self._completer_t.start()
        self._started = True
        return self

    def __enter__(self) -> "Engine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.drain()

    def submit(self, image: np.ndarray) -> Future:
        """Enqueue one request; returns a Future resolving to its logits
        row. Raises ``QueueFullError`` (backpressure) or
        ``EngineClosedError`` (draining) instead of queueing unboundedly."""
        image = np.asarray(image)
        want = (self.im_size, self.im_size, 3)
        if image.shape != want or image.dtype != self.input_dtype:
            raise ValueError(
                f"request image must be {want} {self.input_dtype.name} "
                f"(the engine's compiled input), got {image.shape} "
                f"{image.dtype.name}"
            )
        with self._cond:
            self._admission.admit(len(self._pending), self._retry_after_ms())
            req = _Request(image, time.perf_counter())
            self._pending.append(req)
            self._cond.notify()
        return req.future

    def drain(self, timeout: float | None = 60.0) -> None:
        """Graceful shutdown: stop accepting, finish every queued and
        in-flight request, stop the threads. Idempotent."""
        with self._cond:
            self._draining = True
            self._admission.close()
            self._cond.notify_all()
        if self._started:
            self._batcher_t.join(timeout)
            self._completer_t.join(timeout)
        else:
            # never started: nothing will ever serve the queue — fail
            # pending futures rather than hanging their owners
            from distribuuuu_tpu.serve.admission import EngineClosedError

            with self._cond:
                while self._pending:
                    req = self._pending.popleft()
                    req.future.set_exception(
                        EngineClosedError("engine drained before start()")
                    )

    def stats(self) -> dict:
        with self._cond:
            depth = len(self._pending)
        out = self.metrics.snapshot()
        out.update(
            queue_depth=depth,
            n_compiles=self.n_compiles,
            buckets=list(self.buckets),
            max_batch=self.max_batch,
            quantize=self.quantize_mode,
        )
        return out

    def _retry_after_ms(self) -> float:
        """Queue depth × recent service time per slot, floored at the
        batching window — a client honoring it lands when capacity frees."""
        per_slot = self.metrics.mean_batch_ms() / self.max_batch
        with_depth = self._admission.max_queue * per_slot / 2
        return max(self._max_wait_s * 1e3, with_depth)

    # -- batcher thread ----------------------------------------------------
    def _collect(self) -> list[_Request] | None:
        """Block until a flush condition: MAX_BATCH waiting, or MAX_WAIT_MS
        since the oldest request arrived, or draining. None = drained dry."""
        with self._cond:
            while not self._pending and not self._draining:
                self._cond.wait(timeout=0.1)
            if not self._pending:
                return None  # draining and nothing left
            deadline = self._pending[0].t_enq + self._max_wait_s
            while len(self._pending) < self.max_batch and not self._draining:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            take = min(len(self._pending), self.max_batch)
            return [self._pending.popleft() for _ in range(take)]

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise AssertionError(f"no bucket for batch {n}")  # unreachable

    def _batcher(self) -> None:
        while True:
            reqs = self._collect()
            if reqs is None:
                break
            bucket = self._bucket_for(len(reqs))
            batch = np.zeros(
                (bucket, self.im_size, self.im_size, 3), self.input_dtype
            )
            for i, r in enumerate(reqs):
                batch[i] = r.image
            try:
                # async dispatch: returns immediately; the device executes
                # while we loop back and assemble the next batch
                out = self._compiled[bucket](self._variables, batch)
            except Exception as e:  # noqa: BLE001 — fail THIS batch only
                for r in reqs:
                    r.future.set_exception(e)
                continue
            self._inflight.put((out, reqs, bucket, time.perf_counter()))
        self._inflight.put(None)  # completer shutdown sentinel

    # -- completion thread -------------------------------------------------
    def _completer(self) -> None:
        last_emit = time.perf_counter()
        while True:
            item = self._inflight.get()
            if item is None:
                break
            out, reqs, bucket, t_disp = item
            logits = np.asarray(out)  # blocks until the device finishes
            t_done = time.perf_counter()
            lats = []
            for i, r in enumerate(reqs):
                r.future.set_result(np.array(logits[i]))
                lats.append(t_done - r.t_enq)
            self.metrics.record_batch(len(reqs), bucket, t_done - t_disp, lats)
            if t_done - last_emit >= self._emit_interval_s:
                self.metrics.emit()  # no-op without a jsonlog sink
                last_emit = t_done


def engine_from_cfg() -> Engine:
    """Build a serving Engine from the global cfg: the configured arch on a
    single-device mesh (``SERVE.DEVICE``), weights from ``MODEL.WEIGHTS``
    (orbax dir or torch pickle) or the pretrained URL zoo
    (``MODEL.PRETRAINED``), input dtype per ``DATA.DEVICE_NORMALIZE``.

    Single-process by construction — serving does not call
    ``setup_distributed``; multi-chip hosts run one engine per chip.
    """
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib

    mesh_lib.apply_backend_flags(
        cfg.DEVICE.DETERMINISTIC or cfg.CUDNN.DETERMINISTIC
    )
    mesh_lib.apply_platform(cfg.DEVICE.PLATFORM)
    devices = jax.local_devices()
    idx = cfg.SERVE.DEVICE
    if not 0 <= idx < len(devices):
        raise ValueError(
            f"SERVE.DEVICE={idx} out of range: {len(devices)} local devices"
        )
    mesh = mesh_lib.build_mesh(data=1, model=1, seq=1, pipe=1,
                               devices=[devices[idx]])
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(
        model, jax.random.key(cfg.RNG_SEED or 0), mesh, cfg.TRAIN.IM_SIZE
    )
    if cfg.MODEL.WEIGHTS:
        state = trainer._with_restored_weights(state, cfg.MODEL.WEIGHTS, model)
    elif cfg.MODEL.PRETRAINED:
        from distribuuuu_tpu.utils import url_zoo

        state = trainer._with_restored_weights(
            state, url_zoo.fetch(cfg.MODEL.ARCH), model
        )
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    return Engine(
        model,
        variables,
        cfg.TRAIN.IM_SIZE,
        input_dtype=np.uint8 if cfg.DATA.DEVICE_NORMALIZE else np.float32,
        quantize=str(cfg.SERVE.QUANTIZE),
    )
