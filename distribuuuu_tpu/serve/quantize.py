"""Weight-only quantized serving variants (ISSUE 16, tentpole part b).

Serving on TPU/CPU is memory-bound at small batch (the decode-attention
roofline argument of ISSUE 13 applies to the image engine too: bucket-1
latency is dominated by streaming weights, not FLOPs), so the cheapest
latency/capacity lever is shrinking the weights the executable streams:

* ``bf16`` — every float weight leaf is cast to ``bfloat16`` at rest
  (half the bytes). JAX's type promotion runs the matmuls against the
  f32 activations in f32, so this is WEIGHT-ONLY quantization: the
  compute dtype and the engine protocol are unchanged.
* ``int8`` — 2D+ float leaves (conv kernels HWIO, dense ``(in, out)``)
  are stored as symmetric per-output-channel int8 with an f32 scale
  (4x smaller at rest) and dequantized IN-GRAPH
  (``dequantize_in_graph``), inside the same AOT-compiled bucket
  executable. Small leaves (biases, BN stats/params) stay f32 — they
  are noise in the byte budget and poison accuracy cheaply.

The accuracy referee is ``tools/zoo_check.py --quantize MODE``: served
logits of the quantized variant must stay within ``TOLERANCE[mode]``
relative error of the f32 forward (tests/test_campaign.py pins the same
bound in the fast tier on toy shapes). The serving engine
(serve/engine.py ``quantize=``) emits one ``kind="serve.quantized"``
record with the measured byte shrink; the per-(model, dtype) latency
frontier lands in SERVE_CAMPAIGN_r*.json and PERF.md.
"""

from __future__ import annotations

from typing import Mapping

import jax.numpy as jnp
import numpy as np

MODES = ("bf16", "int8")

# relative logits tolerance per mode: max|logits_q - logits_f32| over
# max|logits_f32| (the zoo_check --quantize gate and the test-tier pin).
# bf16 keeps ~8 mantissa bits (~0.4% per op, accumulating over depth);
# int8 per-channel weight-only lands low-single-digit percent on the zoo.
TOLERANCE = {"bf16": 0.02, "int8": 0.08}

# leaves smaller than this stay f32 under int8 (biases, BN) — they don't
# pay for their scale metadata and BN stats are accuracy-critical
MIN_INT8_SIZE = 256

_Q = "q8"            # quantized payload key
_SCALE = "q8_scale"  # per-output-channel scale key


def _is_q8(node) -> bool:
    return isinstance(node, Mapping) and set(node.keys()) == {_Q, _SCALE}


def _quantize_leaf_int8(x: np.ndarray) -> dict:
    """Symmetric per-output-channel (last axis) int8: conv kernels are
    HWIO and dense kernels (in, out), so the last axis is the output
    channel for every weight shape the zoo ships."""
    absmax = np.max(np.abs(x), axis=tuple(range(x.ndim - 1)), keepdims=True)
    scale = (absmax / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, np.float32(1.0), scale)
    q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
    return {_Q: q, _SCALE: scale}


def quantize_variables(variables, mode: str) -> tuple[dict, dict]:
    """Return ``(packed, meta)``: the variables tree with weight leaves
    replaced by their quantized form, plus the byte-accounting meta dict
    ``{mode, bytes_before, bytes_after, leaves, quantized_leaves}``.

    ``packed`` feeds ``model.apply`` only after
    ``dequantize_in_graph`` (int8) — bf16 leaves apply directly (JAX
    promotion computes in f32 against f32 activations).
    """
    if mode not in MODES:
        raise ValueError(
            f"SERVE.QUANTIZE must be one of {MODES} (or empty), got {mode!r}"
        )
    meta = {"mode": mode, "bytes_before": 0, "bytes_after": 0,
            "leaves": 0, "quantized_leaves": 0}

    def walk(node):
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        x = np.asarray(node)
        meta["leaves"] += 1
        meta["bytes_before"] += x.nbytes
        if not np.issubdtype(x.dtype, np.floating):
            meta["bytes_after"] += x.nbytes
            return x
        if mode == "bf16":
            meta["quantized_leaves"] += 1
            out = jnp.asarray(x).astype(jnp.bfloat16)
            meta["bytes_after"] += x.nbytes // 2
            return out
        if x.ndim >= 2 and x.size >= MIN_INT8_SIZE:
            packed = _quantize_leaf_int8(x.astype(np.float32))
            meta["quantized_leaves"] += 1
            meta["bytes_after"] += (
                packed[_Q].nbytes + packed[_SCALE].nbytes
            )
            return packed
        meta["bytes_after"] += x.nbytes
        return x

    return walk(variables), meta


def dequantize_in_graph(packed):
    """Rebuild an apply-able variables tree from ``quantize_variables``
    output. Traceable — the serving engine calls this INSIDE its jitted
    forward, so the AOT bucket executables take int8 weights as inputs
    and pay the dequant once per batch on-device."""

    def walk(node):
        if _is_q8(node):
            return node[_Q].astype(jnp.float32) * node[_SCALE]
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(packed)


def quantized_delta(model, variables, images, mode: str) -> dict:
    """The accuracy referee's measurement (zoo_check --quantize and the
    test-tier pins share it): forward ``images`` through the f32
    variables and the ``mode`` variant, return the relative logits delta
    and top-1 agreement against ``TOLERANCE[mode]``."""
    ref = np.asarray(model.apply(variables, images, train=False))
    packed, meta = quantize_variables(variables, mode)
    got = np.asarray(
        model.apply(dequantize_in_graph(packed), images, train=False)
    )
    denom = max(float(np.max(np.abs(ref))), 1e-9)
    rel = float(np.max(np.abs(got - ref))) / denom
    agree = float(np.mean(np.argmax(got, -1) == np.argmax(ref, -1)))
    return {
        "mode": mode,
        "rel_logits_delta": round(rel, 6),
        "tolerance": TOLERANCE[mode],
        "top1_agree": round(agree, 4),
        "ok": rel <= TOLERANCE[mode],
        "bytes_before": meta["bytes_before"],
        "bytes_after": meta["bytes_after"],
        "quantized_leaves": meta["quantized_leaves"],
    }
