"""Online inference serving (no reference analogue — the reference stops
at offline ``test_net.py``).

The request-level layer the ROADMAP's "heavy traffic from millions of
users" goal needs: ``engine.py`` (dynamic micro-batching over AOT-compiled
bucket shapes, double-buffered dispatch, per-request futures),
``admission.py`` (bounded-queue backpressure + SIGTERM graceful drain),
``metrics.py`` (latency histograms / occupancy / throughput into the
jsonlog sink), ``protocol.py`` (length-prefixed socket frontend + batch
mode + stats control frames), ``fleet/`` (the multi-replica serving
fleet: least-loaded router, warm-up-gated replica pool, autoscaler —
``serve_net.py --fleet N``). Entry points: ``serve_net.py`` (the CLI
sibling of ``train_net.py``/``test_net.py``) and
``tools/serve_bench.py`` (the closed/open-loop load generator, fleet
scaling bench via ``--fleet``).
"""

from distribuuuu_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    EngineClosedError,
    QueueFullError,
    drain_requested,
    install_drain,
    reset_drain,
)
from distribuuuu_tpu.serve.engine import (  # noqa: F401
    COMPILE_EVENTS,
    Engine,
    default_buckets,
    engine_from_cfg,
)
from distribuuuu_tpu.serve.metrics import ServeMetrics  # noqa: F401
