"""Autoscale-from-telemetry: the fleet-sizing policy loop.

The policy consumes exactly what the telemetry layer already measures —
the router's windowed latency percentiles (the same reservoir +
nearest-rank math every Registry histogram reports) and total queued work
— and moves the pool's target size against a p99 objective with
queue-depth watermarks. The MLPerf TPU-pod lesson (PAPERS.md,
1909.09756) applies: the scaling signal is end-to-end run health (client
p99, queued work), never per-kernel speed.

Hysteresis, because a serving fleet must not flap:

* **Consecutive-breach gating** — one bad window never scales; it takes
  ``BREACH_N`` consecutive over-target windows (p99 > target OR queue >
  high watermark) to add a replica, and ``BREACH_N`` consecutive calm
  windows (p99 < SCALE_DOWN_FRAC x target AND queue <= low watermark) to
  remove one. Any in-between window resets both streaks.
* **Cooldown** — after any action the policy holds for ``COOLDOWN_S``
  (a new replica needs its warm-up before its effect is measurable;
  scaling again on the same evidence double-counts it).
* **Budget clamp** — the target never leaves
  [MIN_REPLICAS, MAX_REPLICAS].

``AutoscalePolicy.decide`` is a pure function of (time, observation) —
the fast test tier drives the hysteresis math directly, no processes.
``Autoscaler`` is the thread that feeds it router observations every
``EVAL_PERIOD_S`` and applies decisions through ``pool.scale_to``,
emitting a ``kind="fleet.scale"`` telemetry record per action.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from distribuuuu_tpu.utils.logger import get_logger


@dataclass
class Observation:
    """One autoscaler input window (from ``Router.window_stats``)."""

    p99_ms: float
    queue_depth: int
    n_replicas: int
    samples: int = 0


class AutoscalePolicy:
    """The pure hysteresis math. ``decide(now_s, obs)`` returns +1
    (add a replica), -1 (remove one), or 0."""

    def __init__(
        self,
        *,
        p99_target_ms: float,
        queue_high: int,
        queue_low: int,
        scale_down_frac: float = 0.5,
        breach_n: int = 3,
        cooldown_s: float = 10.0,
        min_replicas: int = 1,
        max_replicas: int = 4,
    ):
        if not 0.0 < scale_down_frac < 1.0:
            raise ValueError(
                f"SCALE_DOWN_FRAC must be in (0, 1), got {scale_down_frac} "
                "(>= 1 would scale down while still breaching the target)"
            )
        if min_replicas > max_replicas:
            raise ValueError(
                f"MIN_REPLICAS {min_replicas} > MAX_REPLICAS {max_replicas}"
            )
        self.p99_target_ms = float(p99_target_ms)
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        self.scale_down_frac = float(scale_down_frac)
        self.breach_n = int(breach_n)
        self.cooldown_s = float(cooldown_s)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: float | None = None
        self.last_reason = ""

    def _overloaded(self, obs: Observation) -> bool:
        return (
            obs.p99_ms > self.p99_target_ms
            or obs.queue_depth > self.queue_high
        )

    def _calm(self, obs: Observation) -> bool:
        # an idle window (no samples) is calm by definition — idle fleets
        # shrink to the minimum budget
        return (
            obs.p99_ms < self.scale_down_frac * self.p99_target_ms
            and obs.queue_depth <= self.queue_low
        )

    def decide(self, now_s: float, obs: Observation) -> int:
        in_cooldown = (
            self._last_action_t is not None
            and now_s - self._last_action_t < self.cooldown_s
        )
        # streaks accumulate through cooldown (the evidence is real), but
        # no ACTION fires until the cooldown expires
        if self._overloaded(obs):
            self._up_streak += 1
            self._down_streak = 0
        elif self._calm(obs):
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if in_cooldown:
            return 0
        if (
            self._up_streak >= self.breach_n
            and obs.n_replicas < self.max_replicas
        ):
            self.last_reason = (
                f"p99 {obs.p99_ms:.0f} ms / queue {obs.queue_depth} over "
                f"target for {self._up_streak} windows"
            )
            self._acted(now_s)
            return +1
        if (
            self._down_streak >= self.breach_n
            and obs.n_replicas > self.min_replicas
        ):
            self.last_reason = (
                f"p99 {obs.p99_ms:.0f} ms / queue {obs.queue_depth} calm "
                f"for {self._down_streak} windows"
            )
            self._acted(now_s)
            return -1
        return 0

    def _acted(self, now_s: float) -> None:
        self._last_action_t = now_s
        self._up_streak = self._down_streak = 0


class Autoscaler:
    """The policy loop thread: observe the router, decide, act through
    the pool, record the action in telemetry."""

    def __init__(self, router, pool, policy: AutoscalePolicy,
                 *, eval_period_s: float = 2.0):
        self.router = router
        self.pool = pool
        self.policy = policy
        self.eval_period_s = float(eval_period_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.logger = get_logger()

    def observe(self) -> Observation:
        w = self.router.window_stats(2 * self.eval_period_s)
        return Observation(
            p99_ms=w["p99_ms"],
            queue_depth=w["queue_depth"],
            n_replicas=self.pool.target_size,
            samples=w["samples"],
        )

    def step(self, now_s: float | None = None) -> int:
        """One observe->decide->act iteration (public for tests/drills)."""
        from distribuuuu_tpu.telemetry import spans

        now_s = time.perf_counter() if now_s is None else now_s
        obs = self.observe()
        d = self.policy.decide(now_s, obs)
        if d:
            n_before = self.pool.target_size
            n_after = self.pool.scale_to(n_before + d, wait=False)
            action = "scale_up" if d > 0 else "scale_down"
            self.logger.info(
                "fleet: autoscale %s %d -> %d (%s)",
                action, n_before, n_after, self.policy.last_reason,
            )
            spans.emit_event(
                "fleet.scale", action=action, reason=self.policy.last_reason,
                n_before=n_before, n_after=n_after,
            )
        return d

    def _loop(self) -> None:
        while not self._stop.wait(self.eval_period_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must not die
                self.logger.exception("fleet: autoscaler iteration failed")

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="fleet-autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.eval_period_s + 5)
