"""Serving fleet (ISSUE 6): a shared-nothing replica pool behind a
router process — the subsystem that takes PR 1's single-replica engine to
"millions of users" scale by composing three prior tentpoles:

* **serving** (PR 1) — each replica IS the existing serve_net engine
  (dynamic micro-batching over AOT bucket shapes) in its own process;
* **resilience** (PR 3) — draining restarts chain through the SIGTERM
  drain protocol, so deploys and scale-downs lose zero requests;
* **telemetry** (PR 5) — the least-loaded policy and the autoscaler read
  the Registry instruments serve/metrics.py already reports through.

    router.py     least-loaded dispatch, idempotent retry, verbatim
                  backpressure passthrough, fleet-wide latency telemetry
    pool.py       replica lifecycle: spawn, warm-up-gated routability,
                  health probes, draining restarts, target maintenance;
                  FleetService composes router+pool+autoscaler
    autoscale.py  p99-target/queue-watermark policy loop with hysteresis

Entry points: ``serve_net.py --fleet N`` (the operator CLI),
``tools/serve_bench.py --fleet N`` (saturation scaling bench), and
``tools/resilience_drill.py`` drill 10 (SIGKILL-a-replica-under-load).
"""

from distribuuuu_tpu.serve.fleet.autoscale import (  # noqa: F401
    AutoscalePolicy,
    Autoscaler,
    Observation,
)
from distribuuuu_tpu.serve.fleet.pool import (  # noqa: F401
    FleetService,
    PoolManager,
    free_port,
    probe_stats,
    spawn_serve_net,
    warmed_up,
)
from distribuuuu_tpu.serve.fleet.router import (  # noqa: F401
    LoadSnapshot,
    Replica,
    Router,
    load_score,
    pick_replica,
)
