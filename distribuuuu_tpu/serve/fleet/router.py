"""Fleet router: least-loaded dispatch over a shared-nothing replica pool.

The router is the process clients connect to (it owns ``SERVE.HOST:PORT``
in ``serve_net.py --fleet``); replicas are full single-engine serve_net
processes on ephemeral ports. Requests ride the existing length-prefixed
framing (serve/protocol.py) end to end — the router forwards the raw
payload bytes and the raw response bytes, so the val transform and the
engine dtype contract run at the replica and the router stays thin (no
jax, no PIL on the dispatch path).

Dispatch policy, per request:

1. **Least-loaded pick** — every routable replica carries a
   ``LoadSnapshot``: router-tracked in-flight depth, plus the replica's
   own queue depth / batch occupancy (from its Registry instruments,
   polled by the pool's health probes over the stats control frame), plus
   an EWMA of latencies the router itself observed. ``pick_replica`` is a
   pure function over those snapshots (tests drive it with synthetic
   ones).
2. **Idempotent retry** — serving requests are read-only, so a transport
   failure (replica died mid-request, connection refused) reroutes the
   SAME payload to the next-best replica and marks the failed one
   unroutable until a health probe clears it. ``fleet.rerouted`` counts
   these.
3. **Backpressure passthrough** — a replica's ``queue_full`` rejection is
   not the router's cue to queue: it tries the remaining replicas, and
   when EVERY routable replica rejects, the client receives the LAST
   replica's retry-after rejection payload verbatim (byte-for-byte the
   serve/admission.py shape). The router never holds a request queue of
   its own — fleet-wide overload stays client-visible, bounded, and
   honest, exactly like the single-replica engine's admission contract.

Telemetry: the router owns a Registry (fleet.* counters + the fleet-wide
latency histogram, plus one histogram per replica) and a recent-latency
window for the autoscaler's p99 reads; ``emit_telemetry`` lands
``kind="fleet.stats"`` / ``"fleet.replica"`` records in the per-rank sink
(declared in telemetry/schema.py).
"""

from __future__ import annotations

import json
import socket
import threading
import time
from dataclasses import dataclass, field

from distribuuuu_tpu.serve import protocol
from distribuuuu_tpu.telemetry import tracectx
from distribuuuu_tpu.telemetry.registry import Registry, percentile

_ERROR_PREFIX = b'{"error"'
# replica rejections the router may retry elsewhere (read-only requests):
_BUSY_ERRORS = ("queue_full", "draining")


# -- the least-loaded policy (pure; tests feed synthetic snapshots) ----------

@dataclass
class LoadSnapshot:
    """One replica's load as the router sees it at pick time."""

    inflight: int = 0        # router-tracked: dispatched minus answered
    queue_depth: int = 0     # replica-reported (stats probe)
    occupancy: float = 0.0   # replica-reported batch occupancy (0..1)
    ewma_ms: float = 0.0     # router-observed EWMA request latency


def load_score(snap: LoadSnapshot) -> float:
    """Expected-wait proxy: queued work ahead of a new request (router
    in-flight + replica queue) x the replica's recent per-request latency,
    weighted up when its batches are running full (a saturated replica
    drains slower than its EWMA suggests). Lower is better."""
    depth = max(0, snap.inflight) + max(0, snap.queue_depth)
    busy = 1.0 + max(0.0, min(1.0, snap.occupancy))
    return (1.0 + depth) * busy * max(snap.ewma_ms, 0.1)


def pick_replica(snaps: list[LoadSnapshot | None], rr: int = 0) -> int | None:
    """Index of the least-loaded replica (None entries are unroutable).
    Ties break round-robin via ``rr`` so equally-idle replicas share cold
    traffic instead of replica 0 taking it all."""
    best, best_score = None, None
    n = len(snaps)
    for k in range(n):
        i = (rr + k) % n
        if snaps[i] is None:
            continue
        s = load_score(snaps[i])
        if best_score is None or s < best_score:
            best, best_score = i, s
    return best


# -- one replica, as the router tracks it ------------------------------------

@dataclass
class Replica:
    id: int
    host: str
    port: int
    proc: object = None            # pool-owned process handle (or None)
    model: str = ""                # model id this replica serves ("": sole model)
    routable: bool = False
    warmed: bool = False           # warm-up completed at least once
    warm_jit_compiles: int = 0     # jit.compiles baseline at warm-up
    draining: bool = False
    inflight: int = 0
    ewma_ms: float = 0.0
    requests: int = 0
    stats: dict = field(default_factory=dict)  # last health-probe snapshot
    fails: int = 0
    _conns: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    def snapshot(self) -> LoadSnapshot | None:
        if not self.routable or self.draining:
            return None
        return LoadSnapshot(
            inflight=self.inflight,
            queue_depth=int(self.stats.get("queue_depth", 0)),
            occupancy=float(self.stats.get("batch_occupancy", 0.0)),
            ewma_ms=self.ewma_ms,
        )

    def _get_conn(self, timeout: float) -> socket.socket:
        with self._lock:
            if self._conns:
                return self._conns.pop()
        conn = socket.create_connection(self.addr, timeout=timeout)
        conn.settimeout(timeout)
        return conn

    def _put_conn(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.append(conn)

    def close_conns(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def roundtrip(self, payload: bytes, timeout: float) -> bytes:
        """One request/response over a pooled connection. Raises OSError
        on any transport failure (the caller reroutes)."""
        conn = self._get_conn(timeout)
        try:
            protocol.send_frame(conn, payload)
            resp = protocol.recv_frame(conn)
        except (OSError, ValueError):
            conn.close()
            raise
        if resp is None:  # replica closed mid-request
            conn.close()
            raise ConnectionResetError(f"replica {self.id} closed connection")
        self._put_conn(conn)
        return resp


class NoRoutableReplicaError(RuntimeError):
    """Every replica is dead, draining, or not yet warm."""


class Router:
    """Request dispatcher + fleet-wide observability. The pool
    (fleet/pool.py) owns replica lifecycle and calls
    ``add_replica``/``mark_routable``/``mark_draining``/``remove_replica``;
    the router only routes."""

    EWMA_ALPHA = 0.2

    def __init__(self, *, request_timeout_s: float = 60.0,
                 recent_window: int = 4096,
                 long_prompt_threshold: int = 0,
                 short_p99_slo_ms: float | None = None,
                 long_p99_slo_ms: float | None = None):
        self._replicas: dict[int, Replica] = {}
        self._lock = threading.Lock()
        self._rr = 0
        self._next_id = 0
        self.request_timeout_s = float(request_timeout_s)
        self.registry = Registry()
        self._lat = self.registry.histogram("fleet.latency_s")
        # (t_done, latency_s, trace_id|None) ring: the autoscaler's
        # windowed p99 source AND the exemplar store — traced samples
        # keep their trace id so a p99 breach can name its worst
        # offenders (window_stats "exemplars", ISSUE 20)
        self._recent: list[tuple[float, float, str | None]] = []
        self._recent_cap = recent_window
        self._t0 = time.perf_counter()
        # multi-model multiplexing (serve/campaign): model id -> SLO class
        # record, and per-model routing stats. Empty for single-model
        # fleets — bare (non-enveloped) payloads never consult either.
        self._models: dict[str, dict] = {}
        self._mstats: dict[str, dict] = {}
        # length-aware routing stats (the long-context plane): generate
        # ctrl frames with >= long_prompt_threshold prompt tokens are the
        # "long" class; per-class windowed latencies surface next to the
        # per-model SLO rows (window_stats "length:short"/"length:long")
        # so the slo-breach rule referees short-class p99 against long-
        # prompt interference unchanged. 0 disables classification.
        self.long_prompt_threshold = int(long_prompt_threshold)
        self._lslo = {
            "short": float(short_p99_slo_ms) if short_p99_slo_ms else None,
            "long": float(long_p99_slo_ms) if long_p99_slo_ms else None,
        }
        self._lstats: dict[str, dict] = {}

    # -- model registry (multi-model fleets) -------------------------------
    @staticmethod
    def _fresh_mstat() -> dict:
        return {"requests": 0, "rejected": 0, "degraded_out": 0,
                "degraded_in": 0, "recent": []}

    def register_model(self, name: str, *, slo_class: str = "standard",
                       p99_slo_ms: float | None = None,
                       overflow_to: str | None = None) -> None:
        """Declare a model id and its SLO class. ``overflow_to`` names the
        cheaper model that absorbs this model's traffic when every one of
        its replicas is saturated — the degrade-under-pressure path
        (counted, never silent)."""
        with self._lock:
            self._models[name] = {
                "slo_class": str(slo_class),
                "p99_slo_ms": None if p99_slo_ms is None else float(p99_slo_ms),
                "overflow_to": overflow_to,
            }
            self._mstats.setdefault(name, self._fresh_mstat())

    def registered_models(self) -> list[str]:
        """Every routable model id: registered ones plus any a replica was
        tagged with (the wrong-model-id error lists these)."""
        with self._lock:
            names = set(self._models)
            names.update(
                r.model for r in self._replicas.values() if r.model
            )
            return sorted(names)

    # -- replica membership (pool-driven) ---------------------------------
    def add_replica(self, host: str, port: int, *, proc=None,
                    replica_id: int | None = None,
                    model: str = "") -> Replica:
        """Register a replica in the NOT-routable (warming) state — the
        pool flips it routable only after the warm-up probe confirms every
        bucket shape is compiled. ``model`` tags the replica for model-id
        routing (multi-model fleets); untagged replicas serve bare
        payloads exactly as before."""
        with self._lock:
            rid = self._next_id if replica_id is None else int(replica_id)
            self._next_id = max(self._next_id, rid + 1)
            rep = Replica(
                id=rid, host=host, port=int(port), proc=proc, model=model
            )
            self._replicas[rid] = rep
            if model:
                self._mstats.setdefault(model, self._fresh_mstat())
            return rep

    def mark_routable(self, rid: int) -> None:
        with self._lock:
            self._replicas[rid].routable = True

    def mark_draining(self, rid: int) -> None:
        """Stop routing NEW requests to a replica; in-flight ones finish
        (the drain-before-exit half of a draining restart)."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.draining = True

    def remove_replica(self, rid: int) -> Replica | None:
        with self._lock:
            rep = self._replicas.pop(rid, None)
        if rep is not None:
            rep.close_conns()
        return rep

    def replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def get_replica(self, rid: int) -> Replica | None:
        with self._lock:
            return self._replicas.get(rid)

    def n_routable(self) -> int:
        with self._lock:
            return sum(
                1 for r in self._replicas.values()
                if r.routable and not r.draining
            )

    # -- length classes (long-context serving) -----------------------------
    @staticmethod
    def _fresh_lstat() -> dict:
        return {"requests": 0, "rejected": 0, "recent": []}

    def _classify_payload(self, payload: bytes) -> str | None:
        """"short" / "long" for a generate ctrl frame when length
        classification is on (by prompt token count — "text" prompts
        count utf-8 bytes, the byte tokenizer's 1:1 identity); None for
        everything else. The router classifies from the frame alone, so
        per-class accounting needs no replica cooperation."""
        if not self.long_prompt_threshold:
            return None
        if not payload.startswith(protocol.CTRL_MAGIC[:1]):
            return None
        try:
            ctrl = protocol.parse_ctrl(payload)
        except (ValueError, UnicodeDecodeError):
            return None
        if not ctrl or ctrl.get("op") != "generate":
            return None
        if "tokens" in ctrl:
            n = len(ctrl["tokens"])
        else:
            n = len(str(ctrl.get("text", "")).encode("utf-8"))
        return "long" if n >= self.long_prompt_threshold else "short"

    # -- dispatch ----------------------------------------------------------
    def _pick(self, exclude: set[int],
              model: str | None = None) -> Replica | None:
        """Least-loaded routable replica outside ``exclude``; with
        ``model``, only replicas tagged with that model id count."""
        with self._lock:
            reps = list(self._replicas.values())
            snaps = [
                (r.snapshot()
                 if r.id not in exclude
                 and (model is None or r.model == model) else None)
                for r in reps
            ]
            self._rr += 1
            idx = pick_replica(snaps, rr=self._rr)
            return None if idx is None else reps[idx]

    def _note_failure(self, rep: Replica) -> None:
        """Transport failure: stop routing to it now; the pool's health
        probe decides dead-vs-transient and restores or replaces it."""
        with self._lock:
            rep.routable = False
        rep.close_conns()
        self.registry.counter("fleet.replica_failures").inc(1)

    def _observe(self, rep: Replica, lat_s: float,
                 model: str | None = None,
                 length_class: str | None = None,
                 trace: str | None = None) -> None:
        now = time.perf_counter()
        with self._lock:
            rep.requests += 1
            rep.ewma_ms = (
                lat_s * 1e3 if rep.ewma_ms == 0.0
                else (1 - self.EWMA_ALPHA) * rep.ewma_ms
                + self.EWMA_ALPHA * lat_s * 1e3
            )
            self._recent.append((now, lat_s, trace))
            if len(self._recent) > self._recent_cap:
                del self._recent[: self._recent_cap // 4]
            if model:
                ms = self._mstats.setdefault(model, self._fresh_mstat())
                ms["requests"] += 1
                ms["recent"].append((now, lat_s, trace))
                if len(ms["recent"]) > self._recent_cap:
                    del ms["recent"][: self._recent_cap // 4]
            if length_class:
                ls = self._lstats.setdefault(
                    length_class, self._fresh_lstat()
                )
                ls["requests"] += 1
                ls["recent"].append((now, lat_s, trace))
                if len(ls["recent"]) > self._recent_cap:
                    del ls["recent"][: self._recent_cap // 4]
        self._lat.observe(lat_s)
        self.registry.histogram(f"fleet.replica{rep.id}.latency_s").observe(
            lat_s
        )
        self.registry.counter("fleet.requests").inc(1)

    def _try_dispatch(
        self, payload: bytes, model: str | None, t0: float,
        trace: tracectx.TraceContext | None = None, parent: str = "",
    ) -> tuple[bytes | None, bytes | None]:
        """The retry loop over one model's (or, with None, every)
        replica set: ``(response, last_busy)``. ``response`` is None when
        every candidate was busy, failed, or unroutable — the caller
        decides between overflow, verbatim rejection, and the router
        error. A traced request (``trace``) is re-enveloped per attempt
        with ``parent`` (the router's dispatch span) so the replica's
        spans attach under it, and every failed attempt lands a
        ``router.reroute`` span in the tree."""
        tried: set[int] = set()
        last_busy: bytes | None = None
        wire = payload if trace is None else tracectx.wrap_payload(
            trace.child(parent), payload
        )
        while True:
            rep = self._pick(tried, model=model)
            if rep is None:
                return None, last_busy
            with self._lock:
                rep.inflight += 1
            t_at = time.perf_counter()
            try:
                resp = rep.roundtrip(wire, self.request_timeout_s)
            except (OSError, ValueError):
                self._note_failure(rep)
                self.registry.counter("fleet.rerouted").inc(1)
                tried.add(rep.id)
                tracectx.emit_trace_span(
                    trace, "router.reroute", t_at,
                    time.perf_counter() - t_at, parent=parent,
                    replica=rep.id,
                )
                continue
            finally:
                with self._lock:
                    rep.inflight -= 1
            if resp.startswith(_ERROR_PREFIX):
                try:
                    err = json.loads(resp).get("error")
                except (ValueError, AttributeError):
                    err = None
                if err in _BUSY_ERRORS:
                    # this replica is saturated/draining — try the rest,
                    # and keep its rejection for verbatim passthrough
                    last_busy = resp
                    tried.add(rep.id)
                    continue
            self._observe(
                rep, time.perf_counter() - t0, model=model,
                trace=None if trace is None else trace.trace_id,
            )
            return resp, last_busy

    def _count_rejected(self, model: str | None,
                        length_class: str | None = None) -> None:
        self.registry.counter("fleet.rejected").inc(1)
        with self._lock:
            if model:
                self._mstats.setdefault(
                    model, self._fresh_mstat()
                )["rejected"] += 1
            if length_class:
                self._lstats.setdefault(
                    length_class, self._fresh_lstat()
                )["rejected"] += 1

    def dispatch(self, payload: bytes) -> bytes:
        """Route one request payload; returns the response payload.

        Model-enveloped payloads (protocol.model_envelope) route only to
        replicas tagged with that model id — an unknown id is refused
        with the registered-model list; when EVERY replica of a model
        with a configured ``overflow_to`` is saturated, the stripped
        payload spills to the cheap model instead of being rejected
        (counted as degraded, per model). Bare payloads keep the
        single-model semantics exactly.

        Transport failures reroute (idempotent requests); fleet-wide
        saturation returns the last replica's retry-after rejection
        VERBATIM; a fleet with nothing routable returns a router-level
        error record in the same JSON shape.

        Traced payloads (tracectx.TRACE_MAGIC, outermost) are stripped
        here; the routed attempt re-envelopes with the router's dispatch
        span as the new parent, and one ``router.dispatch`` span (plus a
        ``router.reroute`` per failed attempt) lands in this rank's
        sink. Untraced payloads take the exact pre-tracing path."""
        t0 = time.perf_counter()
        try:
            trace, payload = tracectx.split_payload(payload)
        except ValueError:
            return json.dumps({"error": "bad_trace_envelope"}).encode()
        dsid = "" if trace is None else tracectx.new_span_id()
        resp = self._dispatch_routed(payload, t0, trace, dsid)
        if trace is not None:
            err = None
            if resp.startswith(_ERROR_PREFIX):
                try:
                    err = json.loads(resp).get("error")
                except (ValueError, AttributeError):
                    err = "unparseable_error"
            tracectx.emit_trace_span(
                trace, "router.dispatch", t0, time.perf_counter() - t0,
                span_id=dsid, ok=(err is None),
                **({} if err is None else {"error": err}),
            )
        return resp

    def _dispatch_routed(self, payload: bytes, t0: float,
                         trace: tracectx.TraceContext | None,
                         dsid: str) -> bytes:
        model, inner = protocol.split_model_envelope(payload)
        if model is not None:
            known = self.registered_models()
            if model not in known:
                self.registry.counter("fleet.unknown_model").inc(1)
                return json.dumps({
                    "error": "unknown_model",
                    "model": model,
                    "models": known,
                }).encode()
        resp, last_busy = self._try_dispatch(
            inner, model, t0, trace=trace, parent=dsid
        )
        if resp is not None:
            return resp
        if model is not None:
            with self._lock:
                mrec = self._models.get(model)
                spill = mrec.get("overflow_to") if mrec else None
            if spill:
                resp, spill_busy = self._try_dispatch(
                    inner, spill, t0, trace=trace, parent=dsid
                )
                if resp is not None:
                    # the cheap model absorbed the overflow: a degraded
                    # answer beats a rejected one, and both sides count it
                    self.registry.counter("fleet.degraded").inc(1)
                    with self._lock:
                        self._mstats.setdefault(
                            model, self._fresh_mstat()
                        )["degraded_out"] += 1
                        self._mstats.setdefault(
                            spill, self._fresh_mstat()
                        )["degraded_in"] += 1
                    return resp
                last_busy = spill_busy or last_busy
        if last_busy is not None:
            self._count_rejected(model)
            return last_busy
        self.registry.counter("fleet.unroutable").inc(1)
        if model is not None:
            with self._lock:
                self._mstats.setdefault(
                    model, self._fresh_mstat()
                )["rejected"] += 1
        return json.dumps(
            {"error": "no_routable_replicas", "retry_after_ms": 1000.0}
        ).encode()

    def dispatch_stream(self, payload: bytes, client: socket.socket,
                        model: str | None = None) -> None:
        """Route one STREAMING request (the LM ``op="generate"`` ctrl
        frame, lm/service.py): pick a replica exactly like ``dispatch``,
        then relay its whole frame sequence — token frames as they decode,
        the done frame last — straight to the client. Tokens stream
        through the router; nothing buffers. A generate ctrl frame may
        carry ``"model"``: the stream then routes only to that model's
        replicas (unknown ids are refused with the registered list; no
        overflow — a stream is not idempotently spillable once committed
        to a model's weights).

        Retry semantics are necessarily narrower than ``dispatch``'s: a
        transport failure BEFORE the first frame reroutes (nothing
        reached the client — still idempotent); after a partial stream
        the client gets a done frame carrying the error (re-running the
        prefix would emit duplicate tokens). Busy rejections pass through
        verbatim when every replica rejects, the admission contract.

        A traced generate frame (``"trace"`` in the ctrl JSON) has its
        context re-pointed at the router's dispatch span before
        forwarding, so the replica engine's spans attach under this hop;
        the router lands ``router.pick`` per attempt, ``router.reroute``
        per transport failure, and one ``router.dispatch`` covering the
        whole relay. Untraced frames forward byte-identically."""
        t0 = time.perf_counter()
        trace = None
        if payload.startswith(protocol.CTRL_MAGIC):
            try:
                ctrl = protocol.parse_ctrl(payload)
                trace = tracectx.from_fields((ctrl or {}).get("trace"))
            except (ValueError, UnicodeDecodeError):
                trace = None
        dsid = "" if trace is None else tracectx.new_span_id()
        if trace is not None:
            # downstream spans parent onto the router's dispatch span —
            # only TRACED frames are re-encoded; untraced bytes forward
            # exactly as received
            ctrl["trace"] = {"id": trace.trace_id, "parent": dsid,
                             "origin": trace.origin}
            payload = protocol.CTRL_MAGIC + json.dumps(ctrl).encode()
        if model is not None and model not in self.registered_models():
            self.registry.counter("fleet.unknown_model").inc(1)
            protocol.send_frame(client, json.dumps({
                "error": "unknown_model",
                "model": model,
                "models": self.registered_models(),
            }).encode())
            return
        length_class = self._classify_payload(payload)
        tried: set[int] = set()
        last_busy: bytes | None = None
        while True:
            t_pick = time.perf_counter()
            rep = self._pick(tried, model=model)
            if rep is None:
                break
            tracectx.emit_trace_span(
                trace, "router.pick", t_pick,
                time.perf_counter() - t_pick, parent=dsid,
                replica=rep.id,
            )
            with self._lock:
                rep.inflight += 1
            conn = None
            streamed = 0
            try:
                conn = socket.create_connection(
                    rep.addr, timeout=self.request_timeout_s
                )
                conn.settimeout(self.request_timeout_s)
                protocol.send_frame(conn, payload)
                busy = False
                while True:
                    frame = protocol.recv_frame(conn)
                    if frame is None:
                        raise ConnectionResetError(
                            f"replica {rep.id} closed mid-stream"
                        )
                    if streamed == 0 and frame.startswith(_ERROR_PREFIX):
                        try:
                            err = json.loads(frame).get("error")
                        except (ValueError, AttributeError):
                            err = None
                        if err in _BUSY_ERRORS:
                            last_busy = frame
                            tried.add(rep.id)
                            busy = True
                            break  # try the next replica
                    done = (
                        b'"stream": "done"' in frame[:64]
                        or frame.startswith(_ERROR_PREFIX)
                    )
                    if done:
                        # account the stream BEFORE forwarding its final
                        # frame: the client unblocks the moment it reads
                        # "done", and an after-the-send increment races
                        # anything that checks the counters then
                        self._observe(
                            rep, time.perf_counter() - t0, model=model,
                            length_class=length_class,
                            trace=None if trace is None
                            else trace.trace_id,
                        )
                        self.registry.counter("fleet.streams").inc(1)
                        tracectx.emit_trace_span(
                            trace, "router.dispatch", t0,
                            time.perf_counter() - t0, span_id=dsid,
                            replica=rep.id, frames=streamed + 1,
                            ok=not frame.startswith(_ERROR_PREFIX),
                        )
                    protocol.send_frame(client, frame)
                    streamed += 1
                    if done:
                        return
                if busy:
                    continue  # busy rejection: next replica
            except (OSError, ValueError) as e:
                self._note_failure(rep)
                self.registry.counter("fleet.rerouted").inc(1)
                tried.add(rep.id)
                tracectx.emit_trace_span(
                    trace, "router.reroute", t_pick,
                    time.perf_counter() - t_pick, parent=dsid,
                    replica=rep.id, streamed=streamed,
                )
                if streamed:
                    # tokens already reached the client — re-running the
                    # request would duplicate them; fail THIS stream
                    tracectx.emit_trace_span(
                        trace, "router.dispatch", t0,
                        time.perf_counter() - t0, span_id=dsid,
                        replica=rep.id, frames=streamed, ok=False,
                        error="replica_failed_mid_stream",
                    )
                    try:
                        protocol.send_frame(client, json.dumps({
                            "stream": "done",
                            "error": f"replica failed mid-stream: "
                                     f"{type(e).__name__}: {e}",
                            "n": streamed - 1,
                        }).encode())
                    except OSError:
                        pass
                    return
                continue
            finally:
                with self._lock:
                    rep.inflight -= 1
                if conn is not None:
                    conn.close()
        if last_busy is not None:
            self._count_rejected(model, length_class=length_class)
            tracectx.emit_trace_span(
                trace, "router.dispatch", t0, time.perf_counter() - t0,
                span_id=dsid, ok=False, error="busy",
            )
            protocol.send_frame(client, last_busy)
            return
        self.registry.counter("fleet.unroutable").inc(1)
        tracectx.emit_trace_span(
            trace, "router.dispatch", t0, time.perf_counter() - t0,
            span_id=dsid, ok=False, error="no_routable_replicas",
        )
        protocol.send_frame(client, json.dumps(
            {"error": "no_routable_replicas", "retry_after_ms": 1000.0}
        ).encode())

    def dispatch_generate(self, payload: bytes,
                          model: str | None = None) -> bytes:
        """In-process façade over ``dispatch_stream`` for callers that
        want one classified outcome per generate request rather than a
        client socket to relay into — the campaign runner's LM path
        (config/campaigns/lm_decode.yaml). Relays the stream into a
        local socketpair, drains the token frames, and returns the FINAL
        frame (done / busy / error) — the same bytes ``dispatch``-style
        callers classify on."""
        ours, theirs = socket.socketpair()
        frames: list[bytes] = []

        def _drain() -> None:
            try:
                ours.settimeout(self.request_timeout_s)
                while True:
                    frame = protocol.recv_frame(ours)
                    if frame is None:
                        return
                    frames.append(frame)
            except (OSError, ValueError):
                return

        reader = threading.Thread(target=_drain, daemon=True)
        reader.start()
        try:
            self.dispatch_stream(payload, theirs, model=model)
        finally:
            try:
                theirs.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            reader.join(self.request_timeout_s)
            theirs.close()
            ours.close()
        if not frames:
            return json.dumps(
                {"error": "no_routable_replicas", "retry_after_ms": 1000.0}
            ).encode()
        return frames[-1]

    # -- observability -----------------------------------------------------
    def window_stats(self, window_s: float) -> dict:
        """Latency percentiles over the trailing ``window_s`` plus total
        queued work — the autoscaler's observation."""
        cut = time.perf_counter() - window_s
        with self._lock:
            lats = sorted(
                lat for (t, lat, _tr) in self._recent if t >= cut
            )
            # exemplar attribution (ISSUE 20): the worst <= 3 TRACED
            # samples in the window, so a p99 breach names concrete
            # trace ids instead of a bare percentile
            exemplars = sorted(
                ((lat, tr) for (t, lat, tr) in self._recent
                 if t >= cut and tr),
                reverse=True,
            )[:3]
            queue_depth = sum(
                r.inflight + int(r.stats.get("queue_depth", 0))
                for r in self._replicas.values()
                if r.routable and not r.draining
            )
            models = {}
            for name, ms in self._mstats.items():
                mlats = sorted(
                    lat for (t, lat, _tr) in ms["recent"] if t >= cut
                )
                mrec = self._models.get(name) or {}
                models[name] = {
                    "samples": len(mlats),
                    "p99_ms": round(percentile(mlats, 0.99) * 1e3, 3),
                    "target_ms": mrec.get("p99_slo_ms"),
                }
            # length classes ride the same models dict as "length:short"
            # / "length:long" rows (same {samples, p99_ms, target_ms}
            # shape), so the slo-breach rule — which scans serve.models
            # for targeted rows — referees per-class p99 unchanged
            for name, ls in self._lstats.items():
                llats = sorted(
                    lat for (t, lat, _tr) in ls["recent"] if t >= cut
                )
                models[f"length:{name}"] = {
                    "samples": len(llats),
                    "p99_ms": round(percentile(llats, 0.99) * 1e3, 3),
                    "target_ms": self._lslo.get(name),
                }
        out = {
            "samples": len(lats),
            "p50_ms": round(percentile(lats, 0.50) * 1e3, 3),
            "p90_ms": round(percentile(lats, 0.90) * 1e3, 3),
            "p99_ms": round(percentile(lats, 0.99) * 1e3, 3),
            "queue_depth": queue_depth,
        }
        if exemplars:
            out["exemplars"] = [
                {"trace": tr, "latency_ms": round(lat * 1e3, 3)}
                for (lat, tr) in exemplars
            ]
        if models:
            # per-model windowed p99 against its SLO target — what the
            # slo-breach rule reads (telemetry/live.py)
            out["models"] = models
        return out

    def _counter(self, name: str) -> int:
        return int(self.registry.counter(name).value)

    def stats(self) -> dict:
        """Fleet-wide + per-replica snapshot (the router's own stats
        control-frame response, and what the fleet bench reads)."""
        lat = self._lat.values()
        with self._lock:
            reps = list(self._replicas.values())
        per_replica = [
            {
                "replica": r.id,
                "port": r.port,
                "routable": bool(r.routable and not r.draining),
                "draining": r.draining,
                "inflight": r.inflight,
                "queue_depth": int(r.stats.get("queue_depth", 0)),
                "occupancy": float(r.stats.get("batch_occupancy", 0.0)),
                "ewma_ms": round(r.ewma_ms, 3),
                "requests": r.requests,
                "jit_compiles": int(r.stats.get("jit_compiles", 0)),
                "warm_jit_compiles": r.warm_jit_compiles,
                "aot_compiles": int(r.stats.get("aot_compiles", 0)),
                "model": r.model,
            }
            for r in reps
        ]
        with self._lock:
            names = set(self._models)
            names.update(r.model for r in reps if r.model)
            models = {}
            for name in sorted(names):
                mrec = self._models.get(name) or {}
                ms = self._mstats.get(name) or self._fresh_mstat()
                mlats = [lat for (_t, lat, _tr) in ms["recent"]]
                models[name] = {
                    "slo_class": mrec.get("slo_class", "standard"),
                    "p99_slo_ms": mrec.get("p99_slo_ms"),
                    "overflow_to": mrec.get("overflow_to"),
                    "replicas": sum(1 for r in reps if r.model == name),
                    "requests": ms["requests"],
                    "rejected": ms["rejected"],
                    "degraded_out": ms["degraded_out"],
                    "degraded_in": ms["degraded_in"],
                    "p99_ms": round(percentile(mlats, 0.99) * 1e3, 3),
                }
        with self._lock:
            length_classes = {
                name: {
                    "p99_slo_ms": self._lslo.get(name),
                    "requests": ls["requests"],
                    "rejected": ls["rejected"],
                    "p99_ms": round(
                        percentile(
                            [lat for (_t, lat, _tr) in ls["recent"]], 0.99
                        ) * 1e3, 3,
                    ),
                }
                for name, ls in sorted(self._lstats.items())
            }
        window = max(time.perf_counter() - self._t0, 1e-9)
        out = {
            "replicas": len(reps),
            "routable": sum(1 for p in per_replica if p["routable"]),
            "requests": self._counter("fleet.requests"),
            "rejected": self._counter("fleet.rejected"),
            "rerouted": self._counter("fleet.rerouted"),
            "unroutable": self._counter("fleet.unroutable"),
            "degraded": self._counter("fleet.degraded"),
            "unknown_model": self._counter("fleet.unknown_model"),
            "replica_failures": self._counter("fleet.replica_failures"),
            "throughput_rps": round(
                self._counter("fleet.requests") / window, 2
            ),
            "p50_ms": round(percentile(lat, 0.50) * 1e3, 3),
            "p90_ms": round(percentile(lat, 0.90) * 1e3, 3),
            "p99_ms": round(percentile(lat, 0.99) * 1e3, 3),
            "per_replica": per_replica,
        }
        if models:
            out["models"] = models
        if length_classes:
            out["length_classes"] = length_classes
            out["long_prompt_threshold"] = self.long_prompt_threshold
        return out

    def emit_telemetry(self) -> None:
        """One ``fleet.stats`` + one ``fleet.replica`` per replica (plus
        one ``fleet.model_route`` per registered model on multi-model
        fleets, and one ``fleet.length_class`` per observed length class
        on length-aware fleets) into the per-rank telemetry sink (no-op
        until setup_telemetry ran)."""
        from distribuuuu_tpu.telemetry import spans

        snap = self.stats()
        per_replica = snap.pop("per_replica")
        models = snap.pop("models", {})
        length_classes = snap.pop("length_classes", {})
        snap.pop("long_prompt_threshold", None)
        spans.emit_event("fleet.stats", **snap)
        for p in per_replica:
            spans.emit_event("fleet.replica", **p)
        for name, m in models.items():
            spans.emit_event(
                "fleet.model_route",
                model=name,
                requests=m["requests"],
                rejected=m["rejected"],
                degraded_in=m["degraded_in"],
                degraded_out=m["degraded_out"],
                p99_ms=m["p99_ms"],
            )
        for name, lc in length_classes.items():
            spans.emit_event(
                "fleet.length_class",
                length_class=name,
                threshold=self.long_prompt_threshold,
                requests=lc["requests"],
                rejected=lc["rejected"],
                p99_ms=lc["p99_ms"],
            )

    # -- the client-facing accept loop ------------------------------------
    def _handle_conn(self, conn: socket.socket) -> None:
        with conn:
            while True:
                try:
                    payload = protocol.recv_frame(conn)
                except (OSError, ValueError):
                    return
                if payload is None:
                    return
                ctrl = (
                    protocol.parse_ctrl(payload)
                    if payload.startswith(protocol.CTRL_MAGIC[:1]) else None
                )
                if ctrl is not None:
                    if ctrl.get("op") == "generate":
                        # streaming passthrough: the replica's whole frame
                        # sequence relays on this client connection
                        try:
                            self.dispatch_stream(
                                payload, conn, model=ctrl.get("model")
                            )
                        except OSError:
                            return
                        continue
                    if ctrl.get("op") == "stats":
                        snap = self.stats()
                        # a stats request carrying window_s also gets the
                        # trailing-window latency view (the autoscaler's
                        # observation) — the live monitor's p99 source
                        if ctrl.get("window_s"):
                            snap["window"] = self.window_stats(
                                float(ctrl["window_s"])
                            )
                        resp = json.dumps(snap).encode()
                    else:
                        resp = json.dumps(
                            {"error": f"unknown control op {ctrl.get('op')!r}"}
                        ).encode()
                else:
                    resp = self.dispatch(payload)
                try:
                    protocol.send_frame(conn, resp)
                except OSError:
                    return

    def serve(self, listener: socket.socket, should_stop,
              poll_s: float = 0.25, emit_interval_s: float = 0.0) -> None:
        """Accept loop: one handler thread per client connection (each
        multiplexes that client's requests over the fleet). Polls
        ``should_stop()`` between accepts — the SIGTERM drain flag in
        ``serve_net.py --fleet``."""
        listener.settimeout(poll_s)
        handlers: list[threading.Thread] = []
        last_emit = time.perf_counter()
        try:
            while not should_stop():
                if (
                    emit_interval_s
                    and time.perf_counter() - last_emit >= emit_interval_s
                ):
                    self.emit_telemetry()
                    last_emit = time.perf_counter()
                try:
                    conn, _addr = listener.accept()
                except socket.timeout:
                    continue
                t = threading.Thread(
                    target=self._handle_conn, args=(conn,), daemon=True
                )
                t.start()
                handlers.append(t)
        finally:
            listener.close()
            for t in handlers:
                t.join(timeout=5.0)
