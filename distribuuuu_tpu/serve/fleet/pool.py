"""Replica pool manager: spawn, warm-up gating, health, draining restarts.

The pool owns replica *lifecycle*; the router (fleet/router.py) only
routes. Each replica is the existing single-engine ``serve_net.py``
process on its own ephemeral port (shared-nothing: its own engine, its
own AOT-compiled bucket executables, its own admission queue).

Lifecycle invariants:

* **Warm-up gates routability.** A spawned replica is registered with the
  router in the NOT-routable state; the pool polls its stats control
  frame (serve/protocol.py) until the replica reports every configured
  bucket shape AOT-compiled (``n_compiles == len(buckets)``), and only
  then marks it routable. The warm-up probe also records the replica's
  post-warm-up ``jit.compiles`` baseline, so "zero steady-state
  recompiles fleet-wide" is assertable from any later probe.
* **The target size is kept met.** ``target_size`` is the pool's one
  scaling input (the autoscaler moves it; ``--fleet N`` seeds it). The
  supervision loop replaces dead replicas and spawns toward the target;
  scale-down drains the victim first.
* **Draining restarts drain BEFORE exiting.** ``drain_stop`` marks the
  replica draining at the router (no new requests), THEN delivers
  SIGTERM, which chains through the replica's ``admission.install_drain``
  handler (the PR 3 SIGTERM protocol): the replica stops accepting,
  completes every in-flight request, and exits. Only after exit is it
  removed from the router. ``restart_replica`` is that plus a
  replacement spawn — a zero-failed-request deploy.

Everything process-shaped is injectable (``spawn``/``probe``) so the fast
test tier exercises warm-up gating, drain ordering, and replacement logic
with fakes — no real processes, no jax.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from distribuuuu_tpu.serve import protocol
from distribuuuu_tpu.serve.fleet.router import Router
from distribuuuu_tpu.utils.logger import get_logger


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-and-release)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def probe_stats(addr: tuple[str, int], timeout: float = 2.0) -> dict:
    """One stats control-frame roundtrip to a replica (raises OSError /
    ValueError when the replica is down or not yet listening)."""
    with socket.create_connection(addr, timeout=timeout) as conn:
        conn.settimeout(timeout)
        protocol.send_frame(conn, protocol.ctrl_request("stats"))
        payload = protocol.recv_frame(conn)
        if payload is None:
            raise ConnectionResetError(f"replica at {addr} closed during probe")
        return json.loads(payload)


def warmed_up(stats: dict) -> bool:
    """A replica is warm when every configured bucket shape is compiled —
    the gate between 'process is up' and 'safe to route to'."""
    buckets = stats.get("buckets") or []
    return bool(buckets) and int(stats.get("n_compiles", 0)) >= len(buckets)


class _ReplicaProc:
    """A spawned serve_net replica process (the default ``spawn``)."""

    def __init__(self, proc: subprocess.Popen, log_path: str):
        self._proc = proc
        self.log_path = log_path
        self.pid = proc.pid

    def poll(self):
        return self._proc.poll()

    def terminate(self) -> None:  # SIGTERM -> the replica's drain chain
        self._proc.terminate()

    def kill(self) -> None:
        self._proc.kill()

    def wait(self, timeout: float | None = None):
        return self._proc.wait(timeout=timeout)


def spawn_serve_net(cfg_path: str, *, host: str, out_dir: str):
    """Build the default ``spawn(replica_id, port)``: launch
    ``serve_net.py --cfg <dumped cfg> SERVE.PORT <port>`` with the
    replica's telemetry rank in ``DTPU_REPLICA_RANK`` and its stdout in
    ``{out_dir}/replica{id}.log``."""
    serve_net = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))), "serve_net.py"
    )

    def spawn(replica_id: int, port: int) -> _ReplicaProc:
        os.makedirs(out_dir, exist_ok=True)
        log_path = os.path.join(out_dir, f"replica{replica_id}.log")
        env = dict(os.environ)
        # telemetry rank: 0 is the router; replicas are 1.. (replacement
        # replicas get fresh ids, hence fresh per-rank sink files)
        env["DTPU_REPLICA_RANK"] = str(replica_id + 1)
        log = open(log_path, "a", buffering=1)
        proc = subprocess.Popen(
            [
                sys.executable, serve_net, "--cfg", cfg_path,
                "SERVE.PORT", str(port), "SERVE.HOST", host,
            ],
            env=env, stdout=log, stderr=subprocess.STDOUT, text=True,
        )
        log.close()  # the child holds the fd
        return _ReplicaProc(proc, log_path)

    return spawn


class PoolManager:
    """Replica lifecycle around a Router. ``spawn(replica_id, port)``
    returns a process handle (``poll``/``terminate``/``kill``/``wait``);
    ``probe(addr)`` returns a replica stats dict or raises. Both are
    injectable for the no-process test tier."""

    def __init__(
        self,
        router: Router,
        spawn,
        *,
        probe=probe_stats,
        host: str = "127.0.0.1",
        min_replicas: int = 1,
        max_replicas: int = 4,
        warmup_timeout_s: float = 180.0,
        warmup_poll_s: float = 0.25,
        health_period_s: float = 1.0,
        health_fails: int = 3,
        probe_timeout_s: float = 5.0,
        model: str = "",
    ):
        self.router = router
        self._spawn = spawn
        # model id this pool's replicas serve ("" = single-model fleet);
        # tags every add_replica so the router can model-filter _pick
        self.model = str(model)
        if probe is probe_stats:
            # the default probe gets the pool's timeout (a loaded 1-core
            # replica can sit on the GIL past a short probe window —
            # that is "busy", not "dead")
            probe = lambda addr: probe_stats(addr, timeout=probe_timeout_s)  # noqa: E731
        self._probe = probe
        self.host = host
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.warmup_timeout_s = float(warmup_timeout_s)
        self.warmup_poll_s = float(warmup_poll_s)
        self.health_period_s = float(health_period_s)
        self.health_fails = int(health_fails)
        self.target_size = 0
        self._lock = threading.Lock()
        self._scale_lock = threading.Lock()  # one spawn-decision at a time
        self._stop = threading.Event()
        self._supervisor: threading.Thread | None = None
        self._draining: dict[int, object] = {}  # rid -> handle (exiting)
        self.logger = get_logger()

    # -- spawn + warm-up ---------------------------------------------------
    def add_replica(self, *, wait: bool = True):
        """Spawn one replica and (optionally) block until it is warm and
        routable. Returns the router's Replica record."""
        port = free_port(self.host)
        rep = self.router.add_replica(self.host, port, model=self.model)
        handle = self._spawn(rep.id, port)
        rep.proc = handle
        self.logger.info(
            "fleet: replica %d spawning on %s:%d (pid %s)",
            rep.id, self.host, port, getattr(handle, "pid", "?"),
        )
        if wait:
            self._wait_warm(rep)
        else:
            threading.Thread(
                target=self._wait_warm, args=(rep,), daemon=True
            ).start()
        return rep

    def _wait_warm(self, rep) -> bool:
        """Poll the replica's stats endpoint until every bucket shape is
        compiled, then mark it routable. A replica that dies or exceeds
        the warm-up budget is removed (and the supervisor loop respawns
        toward the target)."""
        deadline = time.perf_counter() + self.warmup_timeout_s
        while time.perf_counter() < deadline and not self._stop.is_set():
            if rep.proc is not None and rep.proc.poll() is not None:
                break  # died during warm-up
            try:
                stats = self._probe(rep.addr)
            except (OSError, ValueError):
                time.sleep(self.warmup_poll_s)
                continue
            if warmed_up(stats):
                rep.stats = stats
                rep.warmed = True
                # the zero-steady-state-recompile baseline: any later
                # probe reporting jit.compiles above this is a recompile
                rep.warm_jit_compiles = int(stats.get("jit_compiles", 0))
                self.router.mark_routable(rep.id)
                self.logger.info(
                    "fleet: replica %d routable (%d bucket shapes compiled, "
                    "jit.compiles baseline %d)",
                    rep.id, int(stats.get("n_compiles", 0)),
                    int(stats.get("jit_compiles", 0)),
                )
                return True
            time.sleep(self.warmup_poll_s)
        self.logger.warning(
            "fleet: replica %d failed warm-up — removing", rep.id
        )
        self._destroy(rep, reason="warmup_failed")
        return False

    # -- scaling -----------------------------------------------------------
    def set_target(self, n: int) -> int:
        """Set the target size without acting on it now (the supervision
        loop spawns toward it); returns the clamped value."""
        n = max(self.min_replicas, min(self.max_replicas, int(n)))
        with self._lock:
            self.target_size = n
        return n

    def scale_to(self, n: int, *, wait: bool = True) -> int:
        """Move the target size to ``n`` (clamped to the configured
        min/max budget) and act on the delta now: spawn up, or drain the
        newest replicas down. Returns the clamped target."""
        n = self.set_target(n)
        current = self._members()
        if n > len(current):
            self._spawn_toward_target()
            if wait:
                self._wait_routable(n)
        elif n < len(current):
            # drain the newest first (oldest replicas keep their warm caches)
            for rep in sorted(current, key=lambda r: -r.id)[: len(current) - n]:
                self.drain_stop(rep.id, wait=wait)
        return n

    def _spawn_toward_target(self) -> list:
        """Spawn however many replicas the target is missing. Registration
        happens under the scale lock, so a concurrent supervision pass and
        an explicit scale/restart cannot double-spawn; warm-up proceeds in
        background threads either way."""
        with self._scale_lock:
            missing = self.target_size - len(self._members())
            return [self.add_replica(wait=False) for _ in range(missing)]

    def _wait_routable(self, n: int) -> bool:
        deadline = time.perf_counter() + self.warmup_timeout_s
        while time.perf_counter() < deadline and not self._stop.is_set():
            if self._n_routable() >= n:
                return True
            time.sleep(0.1)
        return self._n_routable() >= n

    def _n_routable(self) -> int:
        return sum(1 for r in self._own() if r.routable)

    def _own(self) -> list:
        """THIS pool's replicas. The router is shared across pools in a
        multi-model fleet (fleet/campaign), so every lifecycle decision —
        target counting, warm-up waits, health, shutdown — must filter
        by the pool's model tag or pools start managing (and refusing to
        spawn against) each other's replicas."""
        return [r for r in self.router.replicas() if r.model == self.model]

    def _members(self) -> list:
        """Replicas that count toward the target: routable or warming —
        not the ones already draining out."""
        return [
            r for r in self._own()
            if not r.draining and r.id not in self._draining
        ]

    # -- draining restarts -------------------------------------------------
    def drain_stop(self, rid: int, *, wait: bool = True,
                   timeout: float = 60.0) -> bool:
        """Stop one replica with zero failed requests, in this order:
        1) router stops routing to it (mark_draining), 2) SIGTERM chains
        through its drain handler (in-flight requests complete), 3) wait
        for exit, 4) remove from the router."""
        rep = self.router.get_replica(rid)
        if rep is None:
            return False
        self.router.mark_draining(rid)
        with self._lock:
            self._draining[rid] = rep.proc
        if rep.proc is not None:
            try:
                rep.proc.terminate()
            except (OSError, ProcessLookupError):
                pass

        def reap():
            deadline = time.perf_counter() + timeout
            while time.perf_counter() < deadline:
                if rep.proc is None or rep.proc.poll() is not None:
                    break
                time.sleep(0.05)
            else:
                if rep.proc is not None:  # drain hung past the grace window
                    try:
                        rep.proc.kill()
                    except (OSError, ProcessLookupError):
                        pass
            self.router.remove_replica(rid)
            with self._lock:
                self._draining.pop(rid, None)
            self.logger.info("fleet: replica %d drained and exited", rid)

        if wait:
            reap()
        else:
            threading.Thread(target=reap, daemon=True).start()
        return True

    def restart_replica(self, rid: int, *, wait: bool = True) -> bool:
        """Draining restart: drain-stop ``rid``, then spawn toward the
        target (warm-up gated as always; the scale lock keeps a racing
        supervision pass from double-replacing). Zero failed requests by
        construction — the router never routes to a draining replica."""
        self._emit_scale("restart", f"draining restart of replica {rid}")
        if not self.drain_stop(rid, wait=wait):
            return False
        self._spawn_toward_target()
        if wait:
            return self._wait_routable(self.target_size)
        return True

    # -- supervision (health + target maintenance) -------------------------
    def start_supervisor(self) -> None:
        if self._supervisor is not None:
            return
        self._supervisor = threading.Thread(
            target=self._supervise, name="fleet-pool-supervisor", daemon=True
        )
        self._supervisor.start()

    def _supervise(self) -> None:
        while not self._stop.wait(self.health_period_s):
            try:
                self.health_check()
                self._maintain_target()
            except Exception:  # noqa: BLE001 — supervision must not die
                self.logger.exception("fleet: supervisor iteration failed")

    def health_check(self) -> None:
        """One probe pass: refresh every routable replica's load snapshot
        (queue depth, occupancy, jit.compiles) for the router's
        least-loaded policy; HEALTH_FAILS consecutive probe failures or a
        dead process marks the replica dead and removes it. Replicas
        still WARMING are ``_wait_warm``'s to judge (it has the generous
        compile-time budget) — probing them here would kill every fresh
        replica before its first bucket compiles."""
        for rep in self._own():
            if rep.draining or rep.id in self._draining or not rep.warmed:
                continue
            if rep.proc is not None and rep.proc.poll() is not None:
                self._destroy(rep, reason="process_exited")
                continue
            try:
                stats = self._probe(rep.addr)
            except (OSError, ValueError):
                rep.fails += 1
                if rep.fails >= self.health_fails:
                    self._destroy(rep, reason="health_probe_failed")
                continue
            rep.fails = 0
            rep.stats = stats
            if not rep.routable and warmed_up(stats):
                # a transient transport failure knocked it out of routing;
                # the probe just proved it healthy again
                self.router.mark_routable(rep.id)

    def _maintain_target(self) -> None:
        for rep in self._spawn_toward_target():
            self.logger.info(
                "fleet: below target (%d), spawned replacement replica %d",
                self.target_size, rep.id,
            )
            self._emit_scale("replace", "replacing dead replica")

    def _destroy(self, rep, *, reason: str) -> None:
        self.logger.warning("fleet: replica %d dead (%s)", rep.id, reason)
        self.router.remove_replica(rep.id)
        if rep.proc is not None:
            try:
                rep.proc.kill()
            except (OSError, ProcessLookupError):
                pass

    def _emit_scale(self, action: str, reason: str) -> None:
        from distribuuuu_tpu.telemetry import spans

        n = len(self._members())
        spans.emit_event(
            "fleet.scale", action=action, reason=reason,
            n_before=n, n_after=self.target_size,
        )

    # -- shutdown ----------------------------------------------------------
    def shutdown(self, timeout: float = 60.0) -> None:
        """Drain every replica of THIS pool (SIGTERM chain) and stop
        supervision; other pools' replicas on the shared router are
        theirs to drain."""
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=self.health_period_s + 5)
        for rep in self._own():
            self.drain_stop(rep.id, wait=False, timeout=timeout)
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline and self._own():
            time.sleep(0.05)
        for rep in self._own():  # anything that refused to die
            if rep.proc is not None:
                try:
                    rep.proc.kill()
                except (OSError, ProcessLookupError):
                    pass
            self.router.remove_replica(rep.id)


class FleetService:
    """The composed fleet: Router + PoolManager + (optional) Autoscaler,
    configured from the ``SERVE.FLEET`` node. This is what
    ``serve_net.py --fleet N``, the fleet bench, and the fleet fault
    drill all run."""

    def __init__(self, cfg, n_replicas: int, *, cfg_path: str,
                 out_dir: str | None = None, autoscale: bool | None = None):
        fl = cfg.SERVE.FLEET
        self.cfg = cfg
        self.n_initial = int(n_replicas)
        self.router = Router(
            request_timeout_s=fl.REQUEST_TIMEOUT_S,
            long_prompt_threshold=cfg.SERVE.LONG_PROMPT_THRESHOLD,
            short_p99_slo_ms=cfg.SERVE.SHORT_P99_SLO_MS,
            long_p99_slo_ms=cfg.SERVE.LONG_P99_SLO_MS,
        )
        fleet_dir = os.path.join(out_dir or cfg.OUT_DIR, "fleet")
        self.pool = PoolManager(
            self.router,
            spawn_serve_net(cfg_path, host=cfg.SERVE.HOST, out_dir=fleet_dir),
            host=cfg.SERVE.HOST,
            min_replicas=fl.MIN_REPLICAS,
            max_replicas=fl.MAX_REPLICAS,
            warmup_timeout_s=fl.WARMUP_TIMEOUT_S,
            health_period_s=fl.HEALTH_PERIOD_S,
            health_fails=fl.HEALTH_FAILS,
        )
        self.autoscaler = None
        if fl.AUTOSCALE if autoscale is None else autoscale:
            from distribuuuu_tpu.serve.fleet.autoscale import (
                Autoscaler,
                AutoscalePolicy,
            )

            self.autoscaler = Autoscaler(
                self.router, self.pool,
                AutoscalePolicy(
                    p99_target_ms=fl.P99_TARGET_MS,
                    queue_high=fl.QUEUE_HIGH,
                    queue_low=fl.QUEUE_LOW,
                    scale_down_frac=fl.SCALE_DOWN_FRAC,
                    breach_n=fl.BREACH_N,
                    cooldown_s=fl.COOLDOWN_S,
                    min_replicas=fl.MIN_REPLICAS,
                    max_replicas=fl.MAX_REPLICAS,
                ),
                eval_period_s=fl.EVAL_PERIOD_S,
            )
        self.emit_interval_s = fl.EMIT_INTERVAL_S

    def start(self, *, wait: bool = True) -> "FleetService":
        """Spawn the initial replicas concurrently (each warm-up gated);
        with ``wait`` block until all are routable (or the warm-up budget
        lapses), then start supervision and the autoscaler loop."""
        n = self.pool.set_target(self.n_initial)
        self.pool._spawn_toward_target()
        if wait:
            self.pool._wait_routable(n)
        self.pool.start_supervisor()
        if self.autoscaler is not None:
            self.autoscaler.start()
        return self

    def serve(self, listener, should_stop, poll_s: float = 0.25) -> None:
        self.router.serve(
            listener, should_stop, poll_s=poll_s,
            emit_interval_s=self.emit_interval_s,
        )

    def shutdown(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.pool.shutdown()
        self.router.emit_telemetry()
