"""The Pallas kernel tier (ISSUE 13): fused kernels for the memory-bound
programs the cost ledger pinned, as ONE subsystem instead of one-offs.

Three kernels, one discipline:

* ``opt_update``     — fused optimizer update (opt_update.py): ONE HBM
                       pass over params+grads+moments for SGD-momentum
                       and AdamW, replacing the optax chain's re-read-
                       per-transform traffic (measured 5.4×/8× the
                       one-pass bytes on the lowered XLA programs).
* ``conv_epilogue``  — fused 1×1-conv(matmul)+BN-affine+activation for
                       the eval/inference path (conv_epilogue.py): the
                       epilogue rides the matmul tile, the conv output
                       never round-trips HBM unactivated.
* ``decode_attn``    — fused decode attention over the paged KV cache
                       (decode_attn.py): one kernel per (batch, head)
                       program, online softmax over cache blocks, no
                       [B,H,T,C] logits materialization and no fp32
                       cache copy.

Tier discipline (every kernel, no exceptions):

* selection rides a ``KERNELS.*`` config knob — ``auto`` | ``pallas`` |
  ``xla`` — resolved HERE (:func:`select`) so policy lives in one place:
  ``auto`` engages the kernel on the TPU backend for supported shapes
  and stays on XLA elsewhere; ``pallas`` forces it (interpret mode
  off-TPU — the exact-but-slow CPU test path); ``xla`` is the
  always-available escape hatch.
* every resolution emits a ``kernel.select`` telemetry record and every
  forced-but-unsupported resolution a ``kernel.fallback`` record with
  the reason (run_report's ``kernels`` section reads both), with a
  warn-once log so a silently-ignored knob cannot happen.
* every kernel has an interpret-mode CPU path (this repo's tier-1 story
  — the same ``pallas_call`` with ``interpret=True``) and a pinned
  bit-exactness or tolerance A/B test against the XLA reference
  (tests/test_pallas_kernels.py).

This tier supersedes the repo's earlier one-off Pallas work: the retired
r5 BoTNet attention kernel (deleted at 0.854× XLA e2e — PERF.md) and the
r2 flash-attention kernel (ops/flash_attention.py, which stays: the
decode kernel reuses its block machinery and its lesson — fuse the whole
memory-bound region or lose to XLA's epilogue fusion at the custom-call
boundary).
"""

from __future__ import annotations

VALID_IMPLS = ("auto", "pallas", "xla")

# op name -> KERNELS knob
KNOBS = {
    "opt_update": "OPT_UPDATE",
    "conv_epilogue": "CONV_EPILOGUE",
    "decode_attn": "DECODE_ATTN",
}

# process-lifetime emission/warn dedup: one kernel.select per (op, impl,
# requested) resolution, one kernel.fallback + warning per (op, reason)
_emitted: set = set()
_warned: set = set()


def reset_selection() -> None:
    """Forget emitted selections/fallbacks (tests)."""
    _emitted.clear()
    _warned.clear()


def validate_kernels_cfg(kcfg=None) -> None:
    """The KERNELS config refusals. An unknown impl name lists the valid
    set; a bad decode block names the lane constraint it violates."""
    if kcfg is None:
        from distribuuuu_tpu.config import cfg

        kcfg = cfg.KERNELS
    for op, knob in KNOBS.items():
        v = kcfg[knob]
        if v not in VALID_IMPLS:
            raise ValueError(
                f"KERNELS.{knob}={v!r} is not a known impl for the "
                f"{op} kernel — valid: {list(VALID_IMPLS)} (auto = pallas "
                "on TPU for supported shapes, xla elsewhere; xla = the "
                "always-available escape hatch)"
            )
    blk = int(kcfg.DECODE_BLOCK)
    if blk < 8 or blk % 8:
        raise ValueError(
            f"KERNELS.DECODE_BLOCK={blk} must be a positive multiple of "
            f"8 (the TPU sublane width): {blk} % 8 = {blk % 8} — the "
            "decode kernel tiles the KV cache into (DECODE_BLOCK, "
            "head_dim) VMEM blocks, with head_dim on the 128-lane axis "
            "and the key blocks on the sublane axis"
        )


def requested(op: str) -> str:
    """The validated KERNELS.* knob value for one op."""
    from distribuuuu_tpu.config import cfg

    validate_kernels_cfg(cfg.KERNELS)
    return str(cfg.KERNELS[KNOBS[op]])


def interpret_mode() -> bool:
    """Whether pallas kernels run the interpreter (any non-TPU backend —
    the tier-1 CPU story; TPU lowers the same call with interpret=False)."""
    import jax

    return jax.default_backend() != "tpu"


def _emit_once(key, kind: str, **fields) -> None:
    if key in _emitted:
        return
    _emitted.add(key)
    from distribuuuu_tpu.telemetry import spans

    if kind == "kernel.select":
        spans.emit_event("kernel.select", op=fields["op"],
                         impl=fields["impl"], requested=fields["requested"])
    else:
        spans.emit_event("kernel.fallback", op=fields["op"],
                         requested=fields["requested"],
                         reason=fields["reason"])


def select(op: str, *, supported: bool = True, reason: str = "") -> str:
    """Resolve which impl runs for ``op`` right now: ``"pallas"`` or
    ``"xla"``. The ONE policy point of the tier:

    * ``xla`` requested → xla.
    * ``pallas`` requested → pallas when ``supported``; otherwise xla
      with a ``kernel.fallback`` record + ONE warning naming ``reason``
      (forced-but-impossible must be loud, never silent).
    * ``auto`` → pallas only on the TPU backend AND ``supported``; the
      CPU/test backends stay on XLA (interpret mode is exact but orders
      of magnitude slower — it is the *test* path, not the auto path).

    Every resolution emits ``kernel.select`` once per process (the
    run_report ``kernels`` section's source).
    """
    if op not in KNOBS:
        raise ValueError(f"unknown kernel op {op!r} — one of {list(KNOBS)}")
    req = requested(op)
    if req == "xla":
        impl = "xla"
    elif req == "pallas":
        impl = "pallas" if supported else "xla"
        if not supported:
            _emit_once(("fb", op, reason), "kernel.fallback", op=op,
                       requested=req, reason=reason or "unsupported")
            wkey = (op, reason)
            if wkey not in _warned:
                _warned.add(wkey)
                from distribuuuu_tpu.utils.logger import get_logger

                get_logger().warning(
                    "KERNELS.%s=pallas requested but unsupported here "
                    "(%s): falling back to the XLA reference path",
                    KNOBS[op], reason or "unsupported shape",
                )
    else:  # auto
        impl = "pallas" if (supported and not interpret_mode()) else "xla"
    _emit_once(("sel", op, impl, req), "kernel.select", op=op, impl=impl,
               requested=req)
    return impl
