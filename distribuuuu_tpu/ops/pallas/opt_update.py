"""Fused optimizer update: ONE HBM pass over params+grads+moments.

The cost ledger's motivation, measured on the lowered XLA programs (the
numbers tools/kernel_bench.py re-derives into BENCH_r09.json): the optax
chain re-reads its operands per transform — ``add_decayed_weights`` →
``trace``/``scale_by_adam`` → ``scale`` each materialize an
intermediate, so the SGD-momentum update accesses ~5.4× and AdamW ~8×
the one-pass byte count. At ResNet-50 scale (25.6M params) that is
~500 MB of avoidable HBM traffic per step on a path with near-zero
arithmetic intensity — pure roofline loss. These kernels read each of
p/g/m(/v) exactly once and write p/m(/v) exactly once per leaf: the
per-shard fused weight update of arXiv:2004.13336, which is also the
fusion point ROADMAP #1's overlapped ZeRO update will reuse.

Numerics are optax's EXACTLY — same op order, same promotion points
(``mom * trace`` in the trace's own dtype for the bf16 momentum
configuration, f32 elsewhere), same ``safe_int32_increment`` counters —
so the jit-vs-jit A/B against the reference chain is BIT-EXACT on the
CPU tier-1 backend (pinned: tests/test_pallas_kernels.py; on TPU
hardware Mosaic's FMA contraction may differ in the last ulp, covered by
the same test's documented tolerance).

Sharding: the update is elementwise per leaf, so it commutes with any
shard slicing — updating a ZeRO shard equals slicing the unsharded
update (pinned by test). Under a ZeRO layout the kernel lowers
PER-SHARD via :func:`per_shard_update` (shard_map over the rest
layout): each rank runs the one-pass kernel on its own 1/N slice, no
gather and no re-scatter — the fusion point of the gather-once schedule
(ISSUE 15, delivered ROADMAP #1). Plain-replicated layouts run the
whole-leaf call unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Tile geometry: leaves are flattened and viewed as (rows, 128) lanes;
# one grid step updates _BLK_ROWS rows (_BLK_ROWS·128·4B·~5 tensors
# ≈ 1.3 MiB VMEM-resident — well under budget with double buffering).
_LANES = 128
_BLK_ROWS = 512


def _pad_rows(n: int) -> tuple[int, int]:
    """(rows, block_rows) for an n-element leaf: rows is the padded
    (rows, 128) view's height — a multiple of 8 sublanes, and of the
    block height when the leaf spans multiple blocks."""
    rows = -(-n // _LANES)
    rows = -(-rows // 8) * 8
    if rows > _BLK_ROWS:
        rows = -(-rows // _BLK_ROWS) * _BLK_ROWS
        return rows, _BLK_ROWS
    return rows, rows


def _tiled(x, rows: int):
    flat = x.reshape(-1)
    pad = rows * _LANES - flat.shape[0]
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES)


def _untiled(t, shape, n: int):
    return t.reshape(-1)[:n].reshape(shape)


def _call(kernel, scalars, tensors, out_dtypes, rows, blk, interpret):
    spec = pl.BlockSpec((blk, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec(scalars.shape, lambda i: (0, 0))
    return pl.pallas_call(
        kernel,
        out_shape=tuple(
            jax.ShapeDtypeStruct((rows, _LANES), d) for d in out_dtypes
        ),
        grid=(rows // blk,),
        in_specs=[sspec] + [spec] * len(tensors),
        out_specs=tuple(spec for _ in out_dtypes),
        interpret=interpret,
    )(scalars, *tensors)


# ------------------------------------------------------------- the kernels


def _sgd_kernel(sc_ref, p_ref, g_ref, t_ref, po_ref, to_ref,
                *, wd, mom, nesterov):
    """torch-ordered SGD-momentum: decay into the grad, trace, (nesterov)
    lookahead, scale — optax's exact op order, one pass."""
    p = p_ref[...]
    g = g_ref[...]
    t = t_ref[...]
    lr = sc_ref[0, 0]
    u = g + wd * p
    # optax.trace computes decay*t in the TRACE dtype (bf16 momentum
    # rounds here) before the f32 add — mirrored for bit-exactness
    tn = u + (mom * t).astype(jnp.float32)
    upd = u + mom * tn if nesterov else tn
    po_ref[...] = (p + upd * (-lr)).astype(po_ref.dtype)
    to_ref[...] = tn.astype(to_ref.dtype)


def _sgd_plain_kernel(sc_ref, p_ref, g_ref, po_ref, *, wd):
    p = p_ref[...]
    g = g_ref[...]
    lr = sc_ref[0, 0]
    u = g + wd * p
    po_ref[...] = (p + u * (-lr)).astype(po_ref.dtype)


def _adamw_kernel(sc_ref, p_ref, g_ref, mu_ref, nu_ref,
                  po_ref, muo_ref, nuo_ref, *, b1, b2, eps, wd):
    """AdamW: moments, bias correction (the 1−βᵗ factors arrive
    precomputed as scalars — optax computes them once per tree, not per
    element), decoupled decay, scale — one pass over p/g/mu/nu."""
    p = p_ref[...]
    g = g_ref[...]
    mu = mu_ref[...]
    nu = nu_ref[...]
    lr = sc_ref[0, 0]
    c1 = sc_ref[0, 1]
    c2 = sc_ref[0, 2]
    mu_n = (1.0 - b1) * g + b1 * mu
    nu_n = (1.0 - b2) * (g * g) + b2 * nu
    u = (mu_n / c1) / (jnp.sqrt(nu_n / c2) + eps)
    u = u + wd * p
    po_ref[...] = (p + u * (-lr)).astype(po_ref.dtype)
    muo_ref[...] = mu_n.astype(muo_ref.dtype)
    nuo_ref[...] = nu_n.astype(nuo_ref.dtype)


# ------------------------------------------------------------ per-leaf ops


def sgd_leaf(p, g, t, lr, *, wd, mom, nesterov, interpret):
    """Fused SGD-momentum for ONE leaf → (p_new, trace_new). ``t=None``
    is the momentum-less configuration (no trace tensor at all)."""
    n = p.size
    rows, blk = _pad_rows(n)
    sc = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    if t is None:
        (po,) = _call(
            functools.partial(_sgd_plain_kernel, wd=wd),
            sc, (_tiled(p, rows), _tiled(g, rows)), (p.dtype,),
            rows, blk, interpret,
        )
        return _untiled(po, p.shape, n), None
    po, to = _call(
        functools.partial(_sgd_kernel, wd=wd, mom=mom, nesterov=nesterov),
        sc, (_tiled(p, rows), _tiled(g, rows), _tiled(t, rows)),
        (p.dtype, t.dtype),
        rows, blk, interpret,
    )
    return _untiled(po, p.shape, n), _untiled(to, t.shape, n)


def adamw_leaf(p, g, mu, nu, lr, c1, c2, *, b1, b2, eps, wd, interpret):
    """Fused AdamW for ONE leaf → (p_new, mu_new, nu_new). ``c1``/``c2``
    are the 1−β₁ᵗ / 1−β₂ᵗ bias corrections (traced scalars)."""
    n = p.size
    rows, blk = _pad_rows(n)
    sc = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(c1, jnp.float32),
        jnp.asarray(c2, jnp.float32),
    ]).reshape(1, 3)
    po, muo, nuo = _call(
        functools.partial(_adamw_kernel, b1=b1, b2=b2, eps=eps, wd=wd),
        sc, (_tiled(p, rows), _tiled(g, rows), _tiled(mu, rows),
             _tiled(nu, rows)),
        (p.dtype, mu.dtype, nu.dtype),
        rows, blk, interpret,
    )
    return (_untiled(po, p.shape, n), _untiled(muo, mu.shape, n),
            _untiled(nuo, nu.shape, n))


# ------------------------------------------------- the optax-shaped update


def _find_state(inner, field: str):
    """Locate the one namedtuple in the (possibly nested-tuple) inner
    chain state that carries ``field`` (TraceState.trace /
    ScaleByAdamState.mu). Returns (state, rebuild) where rebuild maps a
    replacement state back into the same nesting."""
    if hasattr(inner, "_fields") and field in inner._fields:
        return inner, lambda new: new
    if isinstance(inner, tuple):
        for i, sub in enumerate(inner):
            found = _find_state(sub, field)
            if found is not None:
                state, rebuild = found

                def wrap(new, i=i, rebuild=rebuild, outer=inner):
                    return tuple(
                        rebuild(new) if j == i else s
                        for j, s in enumerate(outer)
                    )

                return state, wrap
    return None


def fused_optimizer_update(params, grads, opt_state, *, kind: str,
                           wd: float, mom: float, nesterov: bool,
                           b1: float, b2: float, eps: float,
                           interpret: bool):
    """Drop-in replacement for ``optimizer.update`` + ``apply_updates``
    for the two shipped optimizers (utils/optim.construct_optimizer):
    reads the injected learning rate and the moment trees out of the
    live optax state, runs the fused kernel per leaf, and rebuilds the
    state structure exactly (counters via ``safe_int32_increment``, the
    same dict/namedtuple shapes — ``set_lr`` and checkpoint restore see
    no difference). Returns ``(new_params, new_opt_state)``."""
    import optax

    lr = opt_state.hyperparams["learning_rate"]
    inner = opt_state.inner_state
    if kind == "sgd":
        found = _find_state(inner, "trace") if mom else None
        if found is not None:
            trace_state, rebuild = found
            out = jax.tree.map(
                lambda p, g, t: sgd_leaf(
                    p, g, t, lr, wd=wd, mom=mom, nesterov=nesterov,
                    interpret=interpret,
                ),
                params, grads, trace_state.trace,
            )
            new_params = jax.tree.map(
                lambda _, o: o[0], params, out,
            )
            new_trace = jax.tree.map(lambda _, o: o[1], params, out)
            new_inner = rebuild(trace_state._replace(trace=new_trace))
        else:
            new_params = jax.tree.map(
                lambda p, g: sgd_leaf(
                    p, g, None, lr, wd=wd, mom=0.0, nesterov=False,
                    interpret=interpret,
                )[0],
                params, grads,
            )
            new_inner = inner
    elif kind == "adamw":
        adam_state, rebuild = _find_state(inner, "mu")
        count_inc = optax.safe_int32_increment(adam_state.count)
        c1 = 1 - b1 ** count_inc  # optax.tree_bias_correction's exact expr
        c2 = 1 - b2 ** count_inc
        out = jax.tree.map(
            lambda p, g, m, v: adamw_leaf(
                p, g, m, v, lr, c1, c2, b1=b1, b2=b2, eps=eps, wd=wd,
                interpret=interpret,
            ),
            params, grads, adam_state.mu, adam_state.nu,
        )
        new_params = jax.tree.map(lambda _, o: o[0], params, out)
        new_mu = jax.tree.map(lambda _, o: o[1], params, out)
        new_nu = jax.tree.map(lambda _, o: o[2], params, out)
        new_inner = rebuild(adam_state._replace(
            count=count_inc, mu=new_mu, nu=new_nu,
        ))
    else:
        raise ValueError(f"fused optimizer update: unknown kind {kind!r}")
    new_state = opt_state._replace(
        count=optax.safe_int32_increment(opt_state.count),
        inner_state=new_inner,
    )
    return new_params, new_state


def fused_update_for(optimizer_kind: str | None = None):
    """The trainer hook (partition/lowering.py): resolve KERNELS.OPT_UPDATE
    for the configured optimizer and return the fused update callable, or
    ``None`` when the XLA reference path should run. Captures the OPTIM
    hyperparams at step-build time, like the optax chain itself does."""
    from distribuuuu_tpu.config import cfg
    from distribuuuu_tpu.ops import pallas as tier

    kind = optimizer_kind or str(cfg.OPTIM.OPTIMIZER)
    supported = kind in ("sgd", "adamw")
    impl = tier.select(
        "opt_update", supported=supported,
        reason="" if supported else f"optimizer {kind!r} has no fused kernel",
    )
    if impl != "pallas":
        return None
    interpret = tier.interpret_mode()
    kwargs = dict(
        kind=kind,
        wd=float(cfg.OPTIM.WEIGHT_DECAY),
        mom=float(cfg.OPTIM.MOMENTUM),
        nesterov=bool(cfg.OPTIM.NESTEROV),
        b1=float(cfg.OPTIM.BETA1),
        b2=float(cfg.OPTIM.BETA2),
        eps=1e-8,  # optax.adamw's default — construct_optimizer passes none
        interpret=interpret,
    )

    def update(params, grads, opt_state):
        return fused_optimizer_update(params, grads, opt_state, **kwargs)

    return update


def per_shard_update(update, layout):
    """Lower a fused update PER-SHARD through shard_map over the ZeRO
    layout (ISSUE 15 — the per-shard fused weight update of
    arXiv:2004.13336, replacing the r14 whole-leaf replicated-pin that
    gathered params+grads+moments before every update).

    ``update`` is the whole-leaf callable from :func:`fused_update_for`;
    ``layout`` the ``specs.state_layout`` dict whose ``grads`` tree
    carries the per-leaf shard specs (``data`` added where divisible).
    The returned callable runs the kernel on each rank's LOCAL 1/N slice
    of params/grads/moments — no gather, no re-scatter; the update IS
    shard-local because it is elementwise per leaf (the shard-commute
    contract pinned in tests/test_pallas_kernels.py). Inputs resting in
    a different layout (stage-1 params rest replicated) are sliced by
    the shard_map in_specs — a local view, not a collective; the outer
    rest-layout constraints re-gather stage-1 params once after the
    update, exactly the declared schedule. Scalar state (counters, the
    injected learning rate) rides in replicated and is recomputed
    identically per rank."""
    mesh = jax.tree.leaves(layout["grads"])[0].mesh
    shard_specs = jax.tree.map(lambda sh: sh.spec, layout["grads"])

    def call(params, grads, opt_state):
        from distribuuuu_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        tdef = jax.tree.structure(params)

        def is_param_shaped(node):
            try:
                return jax.tree.structure(node) == tdef
            except (TypeError, ValueError):
                return False

        def place(node):
            if is_param_shaped(node):
                return shard_specs
            return jax.tree.map(lambda _: P(), node)

        # the abstract twin of lowering.abstract_args' place_opt: moment
        # trees (param-structured) ride the shard specs, everything else
        # (counters, hyperparams) is replicated
        ospecs = jax.tree.map(place, opt_state, is_leaf=is_param_shaped)
        fn = shard_map(
            update, mesh=mesh,
            in_specs=(shard_specs, shard_specs, ospecs),
            out_specs=(shard_specs, ospecs),
        )
        return fn(params, grads, opt_state)

    return call


def leaf_pass_bytes(tree, kind: str = "sgd") -> int:
    """The kernel's DMA model: exact bytes one fused pass moves for a
    param tree (reads p+g+moments, writes p+moments) — what pallas_call
    transfers on TPU per its BlockSpecs, used by tools/kernel_bench.py
    as the pallas arm of the roofline A/B (XLA cost_analysis cannot see
    inside the custom call — the recorded caveat)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        pb = leaf.size * leaf.dtype.itemsize
        if kind == "adamw":
            total += 7 * pb  # read p,g,mu,nu; write p,mu,nu
        else:
            total += 5 * pb  # read p,g,trace; write p,trace
    return total
