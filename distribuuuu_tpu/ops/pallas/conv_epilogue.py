"""Fused pointwise conv + BN-affine + activation (the eval epilogue).

A 1×1/s1 conv is a matmul ``[B·H·W, Cin] × [Cin, Cout]``, and eval-mode
BatchNorm is a per-channel affine: ``y = act(x·W·a + c)`` with
``a = rsqrt(var+eps)·scale`` and ``c = bias − mean·a``. XLA computes the
chain as conv → elementwise — the conv output round-trips HBM (bf16)
before the affine re-reads it; this kernel rides the affine+activation
on the matmul tile while the fp32 accumulator is still VMEM-resident:
one HBM read of the activations, one write of the activated output,
nothing in between.

Scope is deliberately the shape where Pallas WINS: the PERF.md r5 conv
campaign measured a Pallas conv chain 34% behind XLA's conv emitter on
spatial convs, and the retired group-conv kernel lost e2e to forfeited
epilogue fusion at the custom-call boundary — so this kernel only takes
matmul-shaped convs (1×1, stride 1, ungrouped: ResNet/RegNet bottleneck
1×1s via layers.ConvBN, EfficientNet's expand/project/head convs) and
carries its epilogue INSIDE the call. Everything else falls back to the
XLA reference path with a ``kernel.fallback`` record.

Numerics vs the reference chain: the conv accumulator stays fp32 into
the affine (the unfused path rounds the conv output to the compute
dtype first), so outputs agree to compute-dtype rounding — the pinned
tolerance in tests/test_pallas_kernels.py, not bit-exactness.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Tile defaults: [blk_m, K]·[K, blk_n] with the fp32 accumulator and the
# per-channel affine vectors resident — ≈(blk_m+blk_n)·K·2B + blk_m·blk_n·4B,
# ~1.3 MiB at K=2048. Both snap down to the array bounds for small shapes.
BLK_M = 256
BLK_N = 128

# activation registry: code -> in-kernel fp32 implementation. Callables
# are matched by identity in act_code() — an activation outside this
# table is a fallback reason, never a silent misfusion.
_ACTS = {
    "id": lambda y: y,
    "relu": lambda y: jnp.maximum(y, 0.0),
    "silu": lambda y: y * jax.nn.sigmoid(y),
}


def act_code(fn) -> str | None:
    """Map a module-level activation callable to its kernel code, or
    None when the kernel has no implementation for it."""
    import flax.linen as nn

    if fn is None:
        return "id"
    if fn in (nn.relu, jax.nn.relu):
        return "relu"
    if fn in (nn.silu, jax.nn.silu, nn.swish, jax.nn.swish):
        return "silu"
    return None


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _mm_epilogue_kernel(x_ref, w_ref, a_ref, c_ref, o_ref, *, act):
    x = x_ref[...]
    w = w_ref[...]
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    y = acc * a_ref[0] + c_ref[0]
    o_ref[...] = _ACTS[act](y).astype(o_ref.dtype)


def conv1x1_bn_act(x, kernel, a, c, act: str = "id", *,
                   out_dtype=None, interpret: bool = False,
                   blk_m: int = BLK_M, blk_n: int = BLK_N):
    """``act((x ⊛ kernel) · a + c)`` for a pointwise conv, one fused pass.

    x: [..., Cin] (any leading dims — NHWC batches flatten to rows);
    kernel: [1, 1, Cin, Cout] (the nn.Conv param layout) or [Cin, Cout];
    a, c: [Cout] fp32 affine (BN folded by the caller);
    act: a key of the in-kernel activation registry.
    Returns [..., Cout] in ``out_dtype`` (default: x.dtype).
    """
    if act not in _ACTS:
        raise ValueError(f"conv epilogue: unknown act {act!r} ({list(_ACTS)})")
    if kernel.ndim == 4:
        kernel = kernel.reshape(kernel.shape[-2], kernel.shape[-1])
    cin, cout = kernel.shape
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    out_dtype = out_dtype or x.dtype

    x2 = x.reshape(m, cin)
    # pad every dim to its tile multiple (K to the 128-lane boundary);
    # zero K-padding is exact (0·w contributes nothing), M/N padding is
    # sliced back off
    if m >= blk_m:
        mp = _round_up(m, blk_m)
    else:
        blk_m = _round_up(m, 8)  # small inputs: one sublane-aligned block
        mp = blk_m
    kp = _round_up(cin, 128)
    if cout >= blk_n:
        np_ = _round_up(cout, blk_n)
    else:
        blk_n = _round_up(cout, 128)  # lane-aligned single block
        np_ = blk_n
    x2 = jnp.pad(x2, ((0, mp - m), (0, kp - cin)))
    w2 = jnp.pad(kernel, ((0, kp - cin), (0, np_ - cout)))
    a2 = jnp.pad(a.astype(jnp.float32), (0, np_ - cout)).reshape(1, np_)
    c2 = jnp.pad(c.astype(jnp.float32), (0, np_ - cout)).reshape(1, np_)

    out = pl.pallas_call(
        functools.partial(_mm_epilogue_kernel, act=act),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        grid=(mp // blk_m, np_ // blk_n),
        in_specs=[
            pl.BlockSpec((blk_m, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, blk_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, blk_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, blk_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((blk_m, blk_n), lambda i, j: (i, j)),
        interpret=interpret,
    )(x2, w2, a2, c2)
    return out[:m, :cout].reshape(*lead, cout)


def qualifies(kernel_size, strides, padding, groups, act_fn,
              train: bool) -> tuple[bool, str]:
    """(supported, reason) for one conv+BN+act site. The reason string
    names the disqualifier — it becomes the kernel.fallback record."""
    if train:
        return False, "training forward (BN batch stats need the raw conv output)"
    k = tuple(kernel_size)
    if k != (1, 1):
        return False, f"kernel {k} is not pointwise (1, 1)"
    s = strides if isinstance(strides, (tuple, list)) else (strides, strides)
    if tuple(s) != (1, 1):
        return False, f"stride {tuple(s)} != (1, 1)"
    if padding is not None and any(p != (0, 0) for p in map(tuple, padding)):
        return False, f"padding {padding} != zero"
    if groups != 1:
        return False, f"grouped conv (groups={groups})"
    if act_code(act_fn) is None:
        return False, f"activation {getattr(act_fn, '__name__', act_fn)!r} has no kernel"
    return True, ""


def pass_bytes(m: int, cin: int, cout: int, in_dtype, out_dtype) -> int:
    """DMA model of one fused pass: activations + weights read once,
    output written once, affine vectors negligible — the pallas arm of
    kernel_bench's roofline A/B (cost_analysis cannot price the fused
    TPU call; this is what its BlockSpecs transfer)."""
    isz = jnp.dtype(in_dtype).itemsize
    osz = jnp.dtype(out_dtype).itemsize
    return m * cin * isz + cin * cout * isz + 2 * cout * 4 + m * cout * osz
