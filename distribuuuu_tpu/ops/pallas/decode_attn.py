"""Fused decode attention over the paged KV cache.

The ledger's worst offender: ``gen_decode_*`` programs measured at
arithmetic intensity 0.56 vs the 3.9 ridge (PERF.md "LM decode
roofline") — per token, tiny flops against a full read of the cache.
The dense reference (lm/generate.CachedAttention's T=1 step) makes it
worse than it has to be: it CASTS the whole bf16 cache to fp32
(materializing a 2× copy), materializes the ``[B, H, 1, C]`` fp32
logits, and runs softmax as separate max/exp/sum/div passes over them —
tools/kernel_bench.py measures ~5× the unavoidable byte count on the
lowered program.

This kernel is that region fused: one program per (batch row, head)
reads its cache page block-by-block, runs the two matmuls and the
online softmax on VMEM-resident tiles (fp32 compute, exactly the
reference's precision), masks ``kpos > length`` in-register, and skips
key blocks entirely past the row's length — the flash block machinery
(ops/flash_attention.py) re-tiled for the T=1 ragged-lengths cache
shape. HBM sees one read of the live cache blocks and one [B, H, D]
write. Same math as the dense softmax up to fp32 summation order
(pinned tolerance: tests/test_pallas_kernels.py against real GPT
checkpoint logits).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distribuuuu_tpu.ops.flash_attention import _NEG_BIG

# default cache-block height (sublane dim; the lane dim is head_dim).
# KERNELS.DECODE_BLOCK overrides per run.
BLK_K = 128


def resolve_block(cache_len: int, blk: int) -> int | None:
    """The key-block height actually used for a cache tile: ``blk`` when
    it divides the tile, the whole tile when it fits inside one block,
    else None (unsupported — the caller's fallback/refusal carries both
    numbers)."""
    if cache_len <= blk:
        return cache_len
    if cache_len % blk == 0:
        return blk
    return None


def supported(t: int, cache_len: int, head_dim: int,
              blk: int) -> tuple[bool, str]:
    """(supported, reason) for one CachedAttention call site."""
    if t != 1:
        return False, f"T={t} new tokens (the kernel is the T=1 decode step)"
    if head_dim > 128:
        return False, f"head_dim {head_dim} > 128 (lane tiling)"
    if resolve_block(cache_len, blk) is None:
        return False, (
            f"KERNELS.DECODE_BLOCK={blk} does not divide the cache tile "
            f"{cache_len} ({cache_len} % {blk} = {cache_len % blk})"
        )
    return True, ""


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, scale, blk_k):
    q = q_ref[0, 0].reshape(1, -1).astype(jnp.float32)  # [1, D]
    d = q.shape[1]
    c = k_ref.shape[2]
    nk = c // blk_k
    length = len_ref[0, 0]

    def body(t, carry):
        m, l, acc = carry
        kb = k_ref[0, 0, pl.ds(t * blk_k, blk_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(t * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [1, blk_k]
        kpos = t * blk_k + jax.lax.broadcasted_iota(jnp.int32, (1, blk_k), 1)
        # the new token sits at absolute position ``length``: keys
        # 0..length inclusive are visible, stale tail positions masked
        s = jnp.where(kpos <= length, s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = corr * l + p.sum(axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, vb, preferred_element_type=jnp.float32)
        return m_new, l, acc

    # ragged block-skip: blocks starting past this row's length are fully
    # masked — never read them (the continuous-batching win: a short row
    # in a long tile reads only its own live blocks)
    nk_hi = jnp.minimum(nk, length // blk_k + 1)
    m0 = jnp.full((1, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((1, 1), jnp.float32)
    a0 = jnp.zeros((1, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk_hi, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).reshape(d)


def decode_attention(q, cache_k, cache_v, lengths, *, scale: float,
                     blk_k: int = BLK_K, interpret: bool = False):
    """One fused decode-attention step.

    q: [B, H, D] (the single new token's queries); cache_k/cache_v:
    [B, H, C, D] paged KV (row b's positions 0..lengths[b] live, the new
    token's K/V already written at index lengths[b]); lengths: [B] int32.
    Returns fp32 [B, H, D] — identical contract to the dense reference's
    pre-projection output.
    """
    b, h, c, d = cache_k.shape
    blk = resolve_block(c, blk_k)
    if blk is None:
        raise ValueError(
            f"decode_attention: block {blk_k} does not divide cache {c}"
        )
    lens = lengths.astype(jnp.int32).reshape(b, 1)
    return pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, blk_k=blk),
        out_shape=jax.ShapeDtypeStruct((b, h, d), jnp.float32),
        grid=(b, h),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, 1, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, c, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, c, d), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(lens, q, cache_k, cache_v)


def pass_bytes(b: int, h: int, c: int, d: int, cache_dtype) -> int:
    """DMA model of one fused decode step: K+V cache pages read once in
    their STORED dtype (no fp32 copy), q read and out written once —
    kernel_bench's pallas arm for the gen_decode roofline A/B."""
    csz = jnp.dtype(cache_dtype).itemsize
    return 2 * b * h * c * d * csz + b * h * d * csz + b * h * d * 4 + b * 4
