"""Hand-tiled Pallas TPU flash attention for long sequences.

The long-sequence path the framework's lax.scan blockwise attention
(ops/ring_attention.blockwise_attention) opened up — re-tiled as real TPU
kernels. Where the scan path materializes one [L, chunk] logits block per
scan step from HBM-resident tensors, these kernels keep K/V and the logits
tile VMEM-resident per (batch·head) program, run both matmuls on the MXU
(bf16 in, fp32 accumulate), and never write the O(L²) probabilities
anywhere. Forward saves only the log-sum-exp [B, H, L]; the backward is
the standard flash recompute: one kernel accumulates dQ over key blocks,
one accumulates dK/dV over query blocks.

Scope: non-causal (the ViT workload this exists for) AND causal (r4 —
in-kernel mask with block-skip loop bounds; ring attention's block updates
route here), head_dim ≤ 128, L padded to the block size internally with
masked keys/rows. Because whole-sequence K/V
(forward, dQ) and q/dO (dK/dV) stay VMEM-resident per (batch·head)
program, the practical length bound is ≈10·L·D bytes against the ~16 MiB
VMEM budget — ~19k tokens at D=64, ~9k at D=128. Lengths beyond it (and
any off-TPU call) route to ``blockwise_attention`` — same exact-softmax
math from HBM-resident tensors — so call sites work unchanged at any L
and on the CPU test mesh.

Reference shape (VERDICT r1 item 4): ViT-Ti at 1024px ⇒ [B, 3, 4096, 64].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)

# VMEM headroom for the whole-sequence-resident tensors (see module
# docstring): ≈10·lp·D bytes across the binding kernel's resident set with
# Mosaic double-buffering, kept under 12 MiB of the ~16 MiB/core budget.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_VMEM_BYTES_PER_TOKEN_DIM = 10

# Defaults re-tuned r3 on a v5e at the reference shape [4, 3, 4096, 64]
# (ViT-Ti/1024px) with the interleaved paired-rounds harness
# (tools/flash_bench.py): 512² beats the old 1024² on the paired
# flash-vs-scan ratio both directions (fwd 1.09x vs 1.01x; fwd+bwd 1.43x
# vs 1.19x — the smaller q-block speeds the dK/dV kernel's inner loop).
BLK_Q = 512
BLK_K = 512


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _k_loop(n, body, carry, lo=0):
    # NOTE (r3): statically unrolling this loop (Python for over range(n))
    # was tried and REVERTED — Mosaic keeps every unrolled iteration's
    # [blk_q, blk_k] fp32 logits tile live simultaneously, blowing the
    # 16 MiB VMEM stack at the tuned 1024² blocks (measured: 16.14M).
    # ``lo``/``n`` may be traced (the causal block-skip bounds).
    return jax.lax.fori_loop(lo, n, body, carry)


def fits_vmem(L: int, d: int) -> bool:
    """Whether an L-token, d-dim shard fits the kernels' whole-sequence
    VMEM residency bound (module docstring). The single source of truth
    for both flash_attention's fallback gate and ring_attention's
    ``auto`` routing."""
    return _round_up(L, 128) * d * _VMEM_BYTES_PER_TOKEN_DIM <= _VMEM_BUDGET_BYTES


def _resolve_blocks(L: int, blk_q: int, blk_k: int):
    """Pad the sequence to the 128-lane boundary and snap each requested
    block size down to the largest 128-multiple divisor of the padded
    length. Both invariants the kernels rely on hold by construction
    (lp % blk == 0 for q AND k — a floor-divided remainder would silently
    drop keys / leave output rows unwritten), and the padding overhead is
    ≤127 rows for ANY length — e.g. a cls-token sequence L=4097 resolves
    to lp=4224 with blk 384 (+3% work) where lcm-based padding would have
    cost a whole extra block (+25%). Power-of-two lengths keep the full
    requested blocks (L=4096 → blk 1024, the tuned default)."""
    lp = _round_up(L, 128)

    def pick(req):
        best = 128
        for m in range(1, lp // 128 + 1):
            cand = 128 * m
            if cand <= min(req, lp) and lp % cand == 0:
                best = cand
        return best

    return pick(blk_q), pick(blk_k), lp


# ---------------------------------------------------------------------------
# forward: grid (B·H, nq); K/V whole-sequence VMEM blocks reused across the
# inner q-block dimension (index map constant in j ⇒ no re-fetch)
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, length, blk_k, causal
):
    q = q_ref[0]  # [blk_q, D]
    blk_q, d = q.shape
    lp = k_ref.shape[1]
    nk = lp // blk_k
    pad = lp != length
    j = pl.program_id(1)

    def body(t, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(t * blk_k, blk_k), :]
        vb = v_ref[0, pl.ds(t * blk_k, blk_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [blk_q, blk_k]
        if pad or causal:
            kpos = t * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, blk_k), 1
            )
            keep = kpos < length
            if causal:
                qpos = j * blk_q + jax.lax.broadcasted_iota(
                    jnp.int32, (blk_q, 1), 0
                )
                keep = keep & (kpos <= qpos)
            s = jnp.where(keep, s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = corr * l + p.sum(axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(
            p.astype(vb.dtype), vb, preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    # causal block-skip: key blocks starting past this q block's last row
    # are fully masked — never visit them (that is the flash-causal win:
    # ~half the blocks at large nk). Every q row still sees key 0, so m/l
    # are always finite after the first block.
    nk_hi = (
        jnp.minimum(nk, ((j + 1) * blk_q + blk_k - 1) // blk_k)
        if causal
        else nk
    )
    m0 = jnp.full((blk_q, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    a0 = jnp.zeros((blk_q, d), jnp.float32)
    m, l, acc = _k_loop(nk_hi, body, (m0, l0, a0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)  # [blk_q, 1]


# ---------------------------------------------------------------------------
# backward: dQ over key blocks (grid nq), dK/dV over query blocks (grid nk)
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, scale, length, blk_k, causal,
):
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]    # [blk_q, 1]
    delta = delta_ref[0]  # [blk_q, 1]
    blk_q, d = q.shape
    lp = k_ref.shape[1]
    nk = lp // blk_k
    pad = lp != length
    j = pl.program_id(1)

    def body(t, dq):
        kb = k_ref[0, pl.ds(t * blk_k, blk_k), :]
        vb = v_ref[0, pl.ds(t * blk_k, blk_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if pad or causal:
            kpos = t * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, blk_k), 1
            )
            keep = kpos < length
            if causal:
                qpos = j * blk_q + jax.lax.broadcasted_iota(
                    jnp.int32, (blk_q, 1), 0
                )
                keep = keep & (kpos <= qpos)
            s = jnp.where(keep, s, _NEG_BIG)
        p = jnp.exp(s - lse)  # [blk_q, blk_k]
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(
            ds.astype(kb.dtype), kb, preferred_element_type=jnp.float32
        )

    # same causal block-skip as the forward
    nk_hi = (
        jnp.minimum(nk, ((j + 1) * blk_q + blk_k - 1) // blk_k)
        if causal
        else nk
    )
    dq = _k_loop(nk_hi, body, jnp.zeros((blk_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, length, blk_q, causal,
):
    """Everything is computed in TRANSPOSED orientation (sᵀ = k·qᵀ directly)
    so all four matmuls are plain last-dim/first-dim contractions — no
    pᵀ/dsᵀ transpose contractions for Mosaic to materialize."""
    kb = k_ref[0]  # [blk_k, D]
    vb = v_ref[0]
    blk_k, d = kb.shape
    lp = q_ref.shape[1]
    nq = lp // blk_q
    pad = lp != length
    j = pl.program_id(1)
    kpos = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_k, 1), 0)

    def body(t, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(t * blk_q, blk_q), :]
        dob = do_ref[0, pl.ds(t * blk_q, blk_q), :]
        lse_t = lse_ref[0, pl.ds(t * blk_q, blk_q), :]    # [blk_q, 1]
        delta_t = delta_ref[0, pl.ds(t * blk_q, blk_q), :]
        s_t = jax.lax.dot_general(
            kb, qb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [blk_k, blk_q]
        if pad or causal:
            # mask padded keys AND padded query rows (their lse is garbage)
            qpos = t * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (1, blk_q), 1
            )
            keep = (kpos < length) & (qpos < length)
            if causal:
                keep = keep & (qpos >= kpos)
            s_t = jnp.where(keep, s_t, _NEG_BIG)
        # padded q rows: s_t is _NEG_BIG there, so exp(_NEG_BIG - lse)
        # underflows to exactly 0 — no second mask needed
        p_t = jnp.exp(s_t - lse_t[:, 0][None, :])  # [blk_k, blk_q]
        dv = dv + jnp.dot(
            p_t.astype(dob.dtype), dob, preferred_element_type=jnp.float32
        )  # [blk_k, D]
        dp_t = jax.lax.dot_general(
            vb, dob, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [blk_k, blk_q]
        ds_t = (p_t * (dp_t - delta_t[:, 0][None, :]) * scale).astype(qb.dtype)
        dk = dk + jnp.dot(ds_t, qb, preferred_element_type=jnp.float32)
        return dk, dv

    # causal block-skip: q blocks ending before this key block's first row
    # are fully masked — start at the first intersecting q block
    t_lo = (j * blk_k) // blk_q if causal else 0
    z = jnp.zeros((blk_k, d), jnp.float32)
    dk, dv = _k_loop(nq, body, (z, z), lo=t_lo)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _specs(lp, d, blk):
    """BlockSpec helpers for [BH, Lp, D] tensors over a (BH, L-blocks) grid."""

    def blocked():
        return pl.BlockSpec(
            (1, blk, d), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM
        )

    def whole():
        return pl.BlockSpec(
            (1, lp, d), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM
        )

    def vec_blocked():
        return pl.BlockSpec(
            (1, blk, 1), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM
        )

    def vec_whole():
        return pl.BlockSpec(
            (1, lp, 1), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM
        )

    return blocked, whole, vec_blocked, vec_whole


def _pad_lhd(t, lp):
    pad = lp - t.shape[1]
    return jnp.pad(t, ((0, 0), (0, pad), (0, 0))) if pad else t


def _flash_forward(q, k, v, scale, interpret, blk_q, blk_k, causal):
    b, h, L, d = q.shape
    blk_q, blk_k, lp = _resolve_blocks(L, blk_q, blk_k)
    bh = b * h

    qf = _pad_lhd(q.reshape(bh, L, d), lp)
    kf = _pad_lhd(k.reshape(bh, L, d), lp)
    vf = _pad_lhd(v.reshape(bh, L, d), lp)

    blocked, whole, vec_blocked, vec_whole = _specs(lp, d, blk_q)
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, length=L, blk_k=blk_k, causal=causal
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lp, d), v.dtype),
            jax.ShapeDtypeStruct((bh, lp, 1), jnp.float32),
        ),
        grid=(bh, lp // blk_q),
        in_specs=[blocked(), whole(), whole()],
        out_specs=(blocked(), vec_blocked()),
        interpret=interpret,
    )(qf, kf, vf)
    return (
        o[:, :L].reshape(b, h, L, d),
        lse,  # [bh, lp, 1] — padded, kept for backward
        (qf, kf, vf),
    )


def _flash_backward(res, g, scale, interpret, blk_q, blk_k, causal,
                    g_lse=None):
    """dQ/dK/dV from the saved residuals. ``g_lse`` (padded [bh, lp, 1]) is
    the cotangent of the lse output when the caller exposed it
    (``flash_attention_with_lse``): dL/ds_ij gains the softmax term
    ``p_ij·g_lse_i`` on top of the standard ``p_ij·(dp_ij − delta_i)`` —
    algebraically identical to replacing delta with (delta − g_lse), so
    BOTH backward kernels absorb it through their delta input unchanged."""
    (qf, kf, vf, lse, o, q_shape) = res
    b, h, L, d = q_shape
    bh, lp, _ = qf.shape
    # same resolution as the forward (lp is already a multiple of both)
    blk_q, blk_k, _ = _resolve_blocks(L, blk_q, blk_k)

    gf = _pad_lhd(g.reshape(bh, L, d), lp)
    of = _pad_lhd(o.reshape(bh, L, d), lp)
    # delta_i = Σ_d dO_i · O_i  (padded rows give garbage — masked in-kernel)
    delta = (gf.astype(jnp.float32) * of.astype(jnp.float32)).sum(
        -1, keepdims=True
    )
    if g_lse is not None:
        delta = delta - g_lse

    blocked_q, whole, vec_blocked_q, vec_whole = _specs(lp, d, blk_q)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, length=L, blk_k=blk_k, causal=causal
        ),
        out_shape=jax.ShapeDtypeStruct((bh, lp, d), qf.dtype),
        grid=(bh, lp // blk_q),
        in_specs=[blocked_q(), whole(), whole(), blocked_q(),
                  vec_blocked_q(), vec_blocked_q()],
        out_specs=blocked_q(),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    blocked_k, _, vec_blocked_k, _ = _specs(lp, d, blk_k)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkdv_kernel, scale=scale, length=L, blk_q=blk_q, causal=causal
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lp, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, lp, d), vf.dtype),
        ),
        grid=(bh, lp // blk_k),
        in_specs=[whole(), blocked_k(), blocked_k(), whole(),
                  vec_whole(), vec_whole()],
        out_specs=(blocked_k(), blocked_k()),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    def unpad(t):
        return t[:, :L].reshape(b, h, L, d)

    return unpad(dq), unpad(dk), unpad(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, scale, interpret, blk_q, blk_k, causal):
    o, _, _ = _flash_forward(q, k, v, scale, interpret, blk_q, blk_k, causal)
    return o


def _fa_fwd(q, k, v, scale, interpret, blk_q, blk_k, causal):
    o, lse, (qf, kf, vf) = _flash_forward(
        q, k, v, scale, interpret, blk_q, blk_k, causal
    )
    return o, (qf, kf, vf, lse, o, q.shape)


def _fa_bwd(scale, interpret, blk_q, blk_k, causal, res, g):
    return _flash_backward(res, g, scale, interpret, blk_q, blk_k, causal)


_flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_lse(q, k, v, scale, interpret, blk_q, blk_k, causal):
    o, lse, _ = _flash_forward(q, k, v, scale, interpret, blk_q, blk_k, causal)
    b, h, L, _ = q.shape
    return o, lse[:, :L, 0].reshape(b, h, L)


def _fal_fwd(q, k, v, scale, interpret, blk_q, blk_k, causal):
    o, lse, (qf, kf, vf) = _flash_forward(
        q, k, v, scale, interpret, blk_q, blk_k, causal
    )
    b, h, L, _ = q.shape
    out = (o, lse[:, :L, 0].reshape(b, h, L))
    return out, (qf, kf, vf, lse, o, q.shape)


def _fal_bwd(scale, interpret, blk_q, blk_k, causal, res, g):
    g_o, g_lse = g
    b, h, L, _ = res[5]
    lp = res[0].shape[1]
    g_lse_p = jnp.pad(
        g_lse.astype(jnp.float32).reshape(b * h, L, 1),
        ((0, 0), (0, lp - L), (0, 0)),
    )
    return _flash_backward(
        res, g_o, scale, interpret, blk_q, blk_k, causal, g_lse=g_lse_p
    )


_flash_attention_lse.defvjp(_fal_fwd, _fal_bwd)


def flash_attention(
    q, k, v, *, scale: float | None = None, causal: bool = False,
    interpret: bool | None = None, blk_q: int = BLK_Q, blk_k: int = BLK_K,
):
    """Exact softmax attention, flash-tiled in Pallas.

    q, k, v: [B, H, L, D]. Returns [B, H, L, D] in v.dtype. Differentiable
    (flash backward: recompute from K/V blocks + saved log-sum-exp).

    ``causal=True`` (r4, VERDICT r3 #4) applies the autoregressive mask
    in-kernel: fully-masked key/query blocks are never visited (the loop
    bounds shrink with the program id — ~2× fewer blocks at large L) and
    the diagonal blocks mask elementwise.

    Off-TPU (and when ``interpret`` is not forced), and for sequences past
    the VMEM-residency bound (~19k tokens at D=64 — module docstring),
    this falls back to ``blockwise_attention`` — the same exact-softmax
    math as a lax.scan — so call sites run unchanged at any length and on
    CPU meshes.
    """
    d = q.shape[-1]
    if d > 128:
        raise ValueError(f"head_dim {d} > 128: lane tiling not supported")
    scale = d ** -0.5 if scale is None else scale

    def _scan_fallback():
        from distribuuuu_tpu.ops.ring_attention import blockwise_attention

        return blockwise_attention(q, k, v, causal=causal, scale=scale)

    L = q.shape[2]
    if (
        interpret is not True  # the interpreter has no VMEM budget
        and not fits_vmem(L, d)
    ):
        # past the whole-sequence VMEM residency bound: stream from HBM
        # via the scan path instead of failing at Mosaic compile time
        return _scan_fallback()
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _scan_fallback()
        interpret = False
    return _flash_attention(q, k, v, scale, interpret, blk_q, blk_k, causal)


def flash_attention_with_lse(
    q, k, v, *, scale: float | None = None, causal: bool = False,
    interpret: bool | None = None, blk_q: int = BLK_Q, blk_k: int = BLK_K,
):
    """:func:`flash_attention` that ALSO returns the log-sum-exp [B, H, L].

    ``(o, lse)`` fully characterizes a block's softmax state — the online
    combination ``(m=lse, l=1, o_unnorm=o)`` merges exactly with any other
    block's state — which is what lets ring attention run its per-rotation
    block updates through this kernel (ops/ring_attention, r4).
    Differentiable in BOTH outputs: an lse cotangent folds into the
    backward kernels' delta input (see ``_flash_backward``).

    No silent fallback: the caller owns the routing decision (ring's
    ``impl='auto'`` checks backend + VMEM bound before choosing this
    path); off-TPU with ``interpret=None`` runs the Pallas interpreter.
    """
    d = q.shape[-1]
    if d > 128:
        raise ValueError(f"head_dim {d} > 128: lane tiling not supported")
    scale = d ** -0.5 if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention_lse(q, k, v, scale, interpret, blk_q, blk_k, causal)
