"""2D multi-head self-attention with relative position logits.

Semantics mirror the reference's BoTNet MHSA (ref: /root/reference/
distribuuuu/models/botnet.py:25-98,163-215 — the Shaw/Ramachandran
relative-position scheme of arXiv:1803.02155 / 1904.09925), re-derived in
jit-friendly jax: static shapes, no device-specific allocations (the
reference hardcodes ``.cuda()`` pads, botnet.py:33,36), and a layout that
XLA fuses cleanly on TPU. (A fused Pallas kernel under this signature was
tried r1-r4 and retired r5 at 0.854× XLA e2e on the 196-token grid —
PERF.md "BoTNet attention".)
"""

from __future__ import annotations

import jax.numpy as jnp


def rel_to_abs(x):
    """Relative→absolute index shift via the pad-reshape trick.

    x: [B, N, L, 2L-1] relative logits → [B, N, L, L] absolute logits
    (ref math: botnet.py:25-40).
    """
    b, n, l, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, 1)))  # [., L, 2L]
    x = x.reshape(b, n, l * 2 * l)
    x = jnp.pad(x, ((0, 0), (0, 0), (0, l - 1)))  # [., 2L² + L - 1]
    x = x.reshape(b, n, l + 1, 2 * l - 1)
    return x[:, :, :l, l - 1 :]


def relative_logits_1d(q, rel_k):
    """Relative logits along the last spatial dim.

    q: [B, N, H, W, d]; rel_k: [2W-1, d] → [B, N, H, W, H, W] with the
    H-expansion broadcast (ref math: botnet.py:43-57).
    """
    b, n, h, w, _ = q.shape
    logits = jnp.einsum("bnhwd,md->bnhwm", q, rel_k)
    logits = logits.reshape(b, n * h, w, 2 * w - 1)
    logits = rel_to_abs(logits)
    logits = logits.reshape(b, n, h, 1, w, w)
    return jnp.broadcast_to(logits, (b, n, h, h, w, w))


def rel_pos_logits(q, rel_height, rel_width, height: int, width: int):
    """Full 2D relative-position logits (ref: RelPosEmb, botnet.py:77-98).

    q: [B, N, HW, d] → [B, N, HW, HW]
    """
    b, n, _, d = q.shape
    q2 = q.reshape(b, n, height, width, d)
    # width (last-dim) logits: [B,N,x,i(H-expd... ) ...] → (x y) (i j)
    lw = relative_logits_1d(q2, rel_width)  # [B,N,x,X,y,j] broadcast over X
    lw = lw.transpose(0, 1, 2, 4, 3, 5)  # b n x y X j
    lw = lw.reshape(b, n, height * width, height * width)
    # height logits: transpose spatial dims, same 1d op
    qt = q2.transpose(0, 1, 3, 2, 4)  # b n y x d
    lh = relative_logits_1d(qt, rel_height)  # [B,N,y,Y,x,i]
    lh = lh.transpose(0, 1, 4, 2, 5, 3)  # b n x y i Y -> matches (y x)(j i) swap
    lh = lh.reshape(b, n, height * width, height * width)
    return lw + lh


def abs_pos_logits(q, emb_height, emb_width):
    """Absolute position logits (ref: AbsPosEmb, botnet.py:60-75).

    q: [B, N, HW, d]; emb_height: [H, d]; emb_width: [W, d].
    """
    emb = emb_height[:, None, :] + emb_width[None, :, :]
    emb = emb.reshape(-1, q.shape[-1])
    return jnp.einsum("bnid,jd->bnij", q, emb)


def mhsa_2d(q, k, v, pos_logits, scale: float):
    """Core attention: softmax(q·kᵀ·scale + pos) · v.

    q,k,v: [B, N, L, d]; pos_logits: [B, N, L, L] (any float dtype — kept
    as-is into the fp32 softmax). Output in v.dtype
    (ref math: botnet.py:193-214).
    """
    import jax.nn

    logits = jnp.einsum("bnxd,bnyd->bnxy", q * scale, k)
    # fp32 softmax is the documented numerical choice here (weights cast
    # straight back to v.dtype); the *_fp32 scope declares it to the
    # static analyzer's dtype lint
    with jax.named_scope("attn_softmax_fp32"):
        logits = logits.astype(jnp.float32) + pos_logits.astype(jnp.float32)
        weights = jax.nn.softmax(logits, axis=-1)
        # exit the region in v.dtype HERE so the cast (and its autodiff
        # transpose) carries the scope
        weights = weights.astype(v.dtype)
    return jnp.einsum("bnxy,bnyd->bnxd", weights, v)
