"""Custom ops: attention kernels (dense, Pallas-fused, sequence-parallel),
mixture-of-experts, and their pure-jax references."""

from distribuuuu_tpu.ops.attention import (  # noqa: F401
    mhsa_2d,
    rel_to_abs,
    relative_logits_1d,
)
from distribuuuu_tpu.ops.moe import (  # noqa: F401
    moe_ffn_dispatch,
    moe_ffn_partial,
    moe_ffn_reference,
)

# NOTE: the sequence-parallel entry points live in the ring_attention
# SUBMODULE (ops.ring_attention.ring_attention / .ulysses_attention /
# .reference_attention). They are deliberately NOT re-exported here: the
# function names collide with the submodule name, and a package-level
# `ring_attention` function would shadow the module for every
# `from distribuuuu_tpu.ops import ring_attention as ra` call site.
from distribuuuu_tpu.ops import ring_attention  # noqa: F401  (the module)
