"""Custom ops: attention kernels and their pure-jax references."""

from distribuuuu_tpu.ops.attention import (  # noqa: F401
    mhsa_2d,
    rel_to_abs,
    relative_logits_1d,
)
