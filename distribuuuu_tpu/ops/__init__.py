"""Custom ops: attention kernels (dense, Pallas-fused, sequence-parallel),
mixture-of-experts, and their pure-jax references."""

from distribuuuu_tpu.ops.attention import (  # noqa: F401
    mhsa_2d,
    rel_to_abs,
    relative_logits_1d,
)
from distribuuuu_tpu.ops.moe import (  # noqa: F401
    moe_ffn_dispatch,
    moe_ffn_partial,
    moe_ffn_reference,
)
from distribuuuu_tpu.ops.ring_attention import (  # noqa: F401
    reference_attention,
    ring_attention,
    ulysses_attention,
)
