"""Expert parallelism: mixture-of-experts FFN over a mesh axis.

Beyond the reference's capability set (DDP-only, SURVEY.md §2.3) — expert
parallelism completes the framework's parallelism matrix (DP/TP/SP/PP/EP)
because distributed scale is a first-class goal here.

Two execution strategies over the same parameters:

- ``moe_ffn_partial``: every rank runs its LOCAL experts over all tokens and
  the gate-weighted partial outputs are summed with one ``psum`` over the
  expert axis. Exact (no token dropping, no capacity), communication = one
  allreduce of the output — the right choice when tokens-per-expert is dense
  (small expert counts, top-k close to E).
- ``moe_ffn_dispatch``: classic switch-style routing. Tokens are dispatched
  to their top-k experts' ranks with ``all_to_all``, processed by the local
  experts at a fixed capacity, and combined back. Communication = 2
  all_to_alls of the routed tokens — the scalable path when E is large and
  top-k small. Over-capacity tokens are dropped (standard switch semantics),
  so it matches the exact path only when capacity is ample.

Gating is top-k softmax (renormalized over the selected experts), the
standard switch/mixtral formulation.

Parameters (functional, like ops/ring_attention.py):
  gate  [d, E]              (replicated)
  w_in  [E, d, f], b_in  [E, f]   (sharded over the expert axis, dim 0)
  w_out [E, f, d], b_out [E, d]   (sharded over the expert axis, dim 0)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distribuuuu_tpu.parallel.compat import axis_size, shard_map


def init_moe_params(key, d_model: int, d_ff: int, num_experts: int):
    """Reference initializer: returns the param dict described above."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(d_model)
    scale_out = 1.0 / np.sqrt(d_ff)
    return {
        "gate": jax.random.normal(k1, (d_model, num_experts), jnp.float32)
        * scale_in,
        "w_in": jax.random.normal(k2, (num_experts, d_model, d_ff), jnp.float32)
        * scale_in,
        "b_in": jnp.zeros((num_experts, d_ff), jnp.float32),
        "w_out": jax.random.normal(k3, (num_experts, d_ff, d_model), jnp.float32)
        * scale_out,
        "b_out": jnp.zeros((num_experts, d_model), jnp.float32),
    }


def moe_params_sharding(mesh, params, axis: str = "model"):
    """Expert-dim-0 sharding for the expert tensors; gate replicated."""

    def spec(path_leaf, x):
        if path_leaf == "gate":
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(axis, *([None] * (np.ndim(x) - 1))))

    return {k: spec(k, v) for k, v in params.items()}


def gating_probs(x, gate_w):
    """Router probabilities: softmax(x @ gate) in fp32, [T, E]. The single
    source of routing — compute once, feed both the expert paths and the
    load-balancing aux."""
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    return jax.nn.softmax(logits, axis=-1)


def top_k_from_probs(probs, top_k: int):
    """Softmax-renormalized top-k gate from precomputed probabilities.

    Returns (weights [T, k] f32, indices [T, k] i32).
    """
    weights, indices = jax.lax.top_k(probs, top_k)
    weights = weights / jnp.maximum(
        weights.sum(axis=-1, keepdims=True), 1e-9
    )
    return weights, indices.astype(jnp.int32)


def top_k_gating(x, gate_w, top_k: int):
    """Softmax-renormalized top-k gate (gating_probs ∘ top_k_from_probs)."""
    return top_k_from_probs(gating_probs(x, gate_w), top_k)


def balance_stats(probs, top_k: int):
    """The two token-mean vectors the balancing aux is bilinear in:
    ``f`` [E] — fraction of (token, k) assignments per expert (Σf = 1),
    ``p`` [E] — mean router probability per expert.

    Exposed separately because both are MEANS over tokens: stats computed
    over disjoint equal-size token subsets (pipeline microbatches, data
    shards) AVERAGE to the full-batch stats exactly — so the full-batch
    aux can be reconstructed exactly from accumulated (f, p), which a
    mean of per-subset aux scalars cannot (f·p is nonlinear). This is how
    parallel/pp.py collects the aux under PP (VERDICT r3 #2).
    """
    E = probs.shape[-1]
    _, indices = jax.lax.top_k(probs, top_k)
    assigned = jax.nn.one_hot(indices, E).sum(axis=1)          # [T, E] 0/1
    f = assigned.mean(axis=0) / top_k                          # Σf = 1
    p = probs.mean(axis=0)
    return f, p


def aux_from_balance_stats(f, p):
    """``E · Σ_e f_e · P_e`` from :func:`balance_stats` vectors."""
    return f.shape[-1] * jnp.sum(f * p)


def load_balancing_loss_from_probs(probs, top_k: int):
    """Switch-transformer auxiliary loss (arXiv:2101.03961 eq. 4-6).

    ``E · Σ_e f_e · P_e`` where ``f_e`` is the fraction of tokens whose
    top-k includes expert e and ``P_e`` the mean router probability of e.
    Minimized (=1.0) at a uniform assignment; add ``λ·aux`` (λ≈0.01) to the
    task loss to keep routed experts balanced — without it top-k routing
    collapses onto a few experts and the dispatch path drops tokens.
    """
    return aux_from_balance_stats(*balance_stats(probs, top_k))


def load_balancing_loss(x, gate_w, top_k: int):
    """`load_balancing_loss_from_probs` with the router computed here."""
    return load_balancing_loss_from_probs(gating_probs(x, gate_w), top_k)


def _expert_ffn(w_in, b_in, w_out, b_out, x):
    """One expert's FFN on [T, d] tokens: gelu(x@w_in+b)@w_out+b."""
    h = jax.nn.gelu(x @ w_in.astype(x.dtype) + b_in.astype(x.dtype))
    return h @ w_out.astype(x.dtype) + b_out.astype(x.dtype)


def moe_ffn_reference(params, x, top_k: int = 2):
    """Dense single-device reference: loop over ALL experts, weighted sum.
    The oracle the parallel paths are tested against."""
    T = x.shape[0]
    weights, indices = top_k_gating(x, params["gate"], top_k)
    E = params["gate"].shape[-1]
    out = jnp.zeros_like(x)
    for e in range(E):
        y = _expert_ffn(
            params["w_in"][e], params["b_in"][e],
            params["w_out"][e], params["b_out"][e], x,
        )
        # weight of expert e for each token (0 when not in its top-k)
        w_e = (weights * (indices == e)).sum(axis=-1)  # [T]
        out = out + y * w_e[:, None].astype(x.dtype)
    return out


def _rank_partials(params, tokens, axis: str, top_k: int):
    """The shared per-rank body of the partial strategy: route the [T, d]
    tokens, run the LOCAL experts, psum the partials over ``axis``. Call
    inside shard_map with ``axis`` bound."""
    r = jax.lax.axis_index(axis)
    local_E = params["w_in"].shape[0]  # E / n
    weights, indices = top_k_from_probs(
        gating_probs(tokens, params["gate"]), top_k
    )
    out = jnp.zeros_like(tokens)
    for le in range(local_E):
        ge = r * local_E + le  # global expert id
        y = _expert_ffn(
            params["w_in"][le], params["b_in"][le],
            params["w_out"][le], params["b_out"][le], tokens,
        )
        w_e = (weights * (indices == ge)).sum(axis=-1)
        out = out + y * w_e[:, None].astype(tokens.dtype)
    return jax.lax.psum(out, axis)


def _moe_param_specs(axis: str):
    """shard_map specs shared by ALL strategies: expert tensors on ``axis``
    dim 0, gate replicated."""
    return {
        "gate": P(),
        "w_in": P(axis), "b_in": P(axis),
        "w_out": P(axis), "b_out": P(axis),
    }


def moe_ffn_partial(params, x, *, mesh, axis: str = "model", top_k: int = 2):
    """Exact expert-parallel MoE: local experts over all tokens + one psum.

    ``x``: [T, d] tokens (replicated over ``axis``; shard T over ``data``
    outside if desired). Expert params sharded over ``axis`` dim 0.
    """
    n = mesh.shape[axis]
    E = params["gate"].shape[-1]
    assert E % n == 0, f"expert-axis size {n} must divide num_experts {E}"

    def per_rank(params, x):
        return _rank_partials(params, x, axis, top_k)

    return shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(_moe_param_specs(axis), P()),
        out_specs=P(),
    )(params, x)


def moe_ffn_partial_batched(
    params,
    x,
    *,
    mesh,
    axis: str = "model",
    data_axis: str | None = "data",
    top_k: int = 2,
):
    """`moe_ffn_partial` for batched activations inside a larger SPMD program.

    ``x``: [B, S, d] with B sharded over ``data_axis`` (the trainer's layout).
    Tokens stay on their data shard — each data rank routes and combines its
    own B_local·S tokens; the only communication is the expert-partials psum
    over ``axis``. This is the trainer-facing EP entry point (DP × EP
    composition); ``moe_ffn_partial`` is the flat-token primitive.
    """
    n = mesh.shape[axis]
    E = params["gate"].shape[-1]
    if E % n:
        raise ValueError(f"expert-axis size {n} must divide num_experts {E}")

    def per_rank(params, x):
        b, s, d = x.shape
        out = _rank_partials(params, x.reshape(b * s, d), axis, top_k)
        return out.reshape(b, s, d)

    data_sharded = bool(data_axis) and mesh.shape.get(data_axis, 1) > 1
    x_spec = P(data_axis) if data_sharded else P()
    return shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(_moe_param_specs(axis), x_spec),
        out_specs=x_spec,
    )(params, x)


def _rank_dispatch(params, x, *, axis: str, top_k: int, C: int, valid=None):
    """The per-rank switch-dispatch body (call inside shard_map, ``axis``
    bound; tokens sharded over ``axis``). ``x``: [T_local, d] — this rank's
    token shard; ``valid``: optional [T_local] bool marking real (non-pad)
    tokens. Returns ``(out [T_local, d], kept, total)`` where kept/total
    count this rank's surviving vs valid (token, k) assignments — psum and
    divide for the global dropped fraction.
    """
    E = params["gate"].shape[-1]
    n = jax.lax.psum(1, axis)
    local_E = E // n
    T_local, d = x.shape
    weights, indices = top_k_gating(x, params["gate"], top_k)  # [Tl,k]
    flat_e = indices.reshape(-1)          # [Tl*k] global expert ids
    flat_w = weights.reshape(-1)          # [Tl*k]
    flat_tok = jnp.repeat(jnp.arange(T_local), top_k)
    if valid is None:
        flat_valid = jnp.ones((T_local * top_k,), bool)
    else:
        flat_valid = jnp.repeat(valid, top_k)

    # slot of each assignment within its expert's per-source capacity
    # (pad tokens take no slot: their one_hot row is zeroed)
    one_hot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)      # [Tl*k, E]
    one_hot = one_hot * flat_valid[:, None].astype(jnp.int32)
    pos_in_e = jnp.cumsum(one_hot, axis=0) * one_hot - 1      # [Tl*k, E]
    pos = pos_in_e.max(axis=-1)                               # [Tl*k]
    keep = (pos >= 0) & (pos < C)

    # dispatch buffer [E, C, d]: my tokens, slotted per target expert
    disp = jnp.zeros((E, C, d), x.dtype)
    disp = disp.at[
        jnp.where(keep, flat_e, 0),
        jnp.where(keep, pos, 0),
    ].add(jnp.where(keep[:, None], x[flat_tok], 0), mode="drop")

    # all_to_all #1: chunk p (= experts owned by rank p) goes to rank p;
    # I receive, from every source rank s, the slots for MY experts.
    disp = disp.reshape(n, local_E, C, d)
    recv = jax.lax.all_to_all(disp, axis, split_axis=0, concat_axis=0)
    # recv: [n, local_E, C, d], recv[s, le] = rank s's tokens for my
    # local expert le → flatten source into the slot dim per expert
    recv = jnp.moveaxis(recv, 0, 1).reshape(local_E, n * C, d)

    # local expert compute
    y = jnp.stack(
        [
            _expert_ffn(
                params["w_in"][le], params["b_in"][le],
                params["w_out"][le], params["b_out"][le], recv[le],
            )
            for le in range(local_E)
        ]
    )  # [local_E, n*C, d]

    # all_to_all #2 (return trip): chunk s goes back to source rank s
    y = jnp.moveaxis(y.reshape(local_E, n, C, d), 1, 0)  # [n, local_E, C, d]
    back = jax.lax.all_to_all(y, axis, split_axis=0, concat_axis=0)
    # back: [n, local_E, C, d], back[p, le] = output of global expert
    # (p*local_E + le) for MY tokens' slots → [E, C, d]
    back = back.reshape(E, C, d)

    # combine: weighted gather of each kept assignment's output
    gathered = back[
        jnp.where(keep, flat_e, 0), jnp.where(keep, pos, 0)
    ]  # [Tl*k, d]
    contrib = gathered * jnp.where(keep, flat_w, 0.0)[:, None].astype(x.dtype)
    out = jnp.zeros_like(x).at[flat_tok].add(contrib)
    kept = keep.sum().astype(jnp.float32)
    total = flat_valid.sum().astype(jnp.float32)
    return out, kept, total


def moe_ffn_dispatch(
    params,
    x,
    *,
    mesh,
    axis: str = "model",
    top_k: int = 2,
    capacity_factor: float = 2.0,
):
    """Switch-style routed MoE: all_to_all dispatch → local experts → return.

    Tokens are SHARDED over ``axis`` (each rank routes its own T/n tokens),
    experts are sharded over the same axis — the DeepSpeed-MoE layout where
    the expert group doubles as the token group. Per (token, k) assignment
    the token rides an ``all_to_all`` to the rank owning that expert; each
    expert processes at most C = ceil(T_local·k/E × capacity_factor) slots
    per source rank (assignments beyond C are dropped — standard switch
    semantics). Matches ``moe_ffn_partial`` exactly when nothing drops.
    """
    n = mesh.shape[axis]
    E = params["gate"].shape[-1]
    assert E % n == 0, f"expert-axis size {n} must divide num_experts {E}"
    T = x.shape[0]
    assert T % n == 0, f"expert-axis size {n} must divide token count {T}"
    C = max(1, int(np.ceil(T // n * top_k / E * capacity_factor)))

    def per_rank(params, x):
        out, _, _ = _rank_dispatch(
            params, x, axis=axis, top_k=top_k, C=C
        )
        return out

    return shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(_moe_param_specs(axis), P(axis)),
        out_specs=P(axis),
    )(params, x)


def moe_ffn_dispatch_batched(
    params,
    x,
    *,
    mesh,
    axis: str = "model",
    data_axis: str | None = "data",
    top_k: int = 2,
    capacity_factor: float = 2.0,
):
    """`moe_ffn_dispatch` for batched activations inside a larger SPMD
    program — the trainer-facing scalable-EP entry point (DP × EP).

    ``x``: [B, S, d] with B sharded over ``data_axis`` and the activations
    replicated over ``axis`` (the trainer's layout between blocks). Each
    data shard's B_local·S tokens are split across the ``axis`` ranks
    (padded up to a multiple — pad tokens take no capacity slots), routed
    through the two all_to_alls, then all_gathered back to the replicated
    layout. Returns ``(out [B, S, d], dropped)`` where ``dropped`` is the
    global fraction of (token, k) assignments lost to the capacity bound —
    0.0 when capacity is ample, at which point the result matches
    ``moe_ffn_partial_batched`` exactly.
    """
    n = mesh.shape[axis]
    E = params["gate"].shape[-1]
    if E % n:
        raise ValueError(f"expert-axis size {n} must divide num_experts {E}")
    B, S, d = x.shape
    data_sharded = bool(data_axis) and mesh.shape.get(data_axis, 1) > 1
    data_size = mesh.shape.get(data_axis, 1) if data_sharded else 1
    if B % data_size:
        raise ValueError(
            f"batch {B} does not shard over data axis of size {data_size}"
        )
    reduce_axes = (axis, data_axis) if data_sharded else (axis,)

    def per_rank(params, xl):
        return dispatch_inline(
            params, xl, axis=axis, top_k=top_k,
            capacity_factor=capacity_factor, reduce_axes=reduce_axes,
        )

    x_spec = P(data_axis) if data_sharded else P()
    return shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(_moe_param_specs(axis), x_spec),
        out_specs=(x_spec, P()),
    )(params, x)


def dispatch_inline(
    params_local,
    xl,
    *,
    axis: str = "model",
    top_k: int = 2,
    capacity_factor: float = 2.0,
    reduce_axes=None,
):
    """The per-device switch-dispatch body — call with ``axis`` BOUND (inside
    any enclosing shard_map: the trainer's, or a pipeline stage's).

    ``params_local``: this rank's expert shard (``w_in`` [E/n, d, f], ...;
    gate full). ``xl``: [B_local, S, d] activations replicated over ``axis``
    (the layout between transformer blocks). Splits the B_local·S tokens
    across the ``axis`` ranks (padding up to a multiple; pad tokens take no
    capacity slots), routes through the two all_to_alls of
    :func:`_rank_dispatch`, and all_gathers back to the replicated layout.
    Returns ``(out [B_local, S, d], dropped)`` — the dropped fraction is
    psummed over ``reduce_axes`` (default: ``(axis,)``).

    This is the shared body of ``moe_ffn_dispatch_batched`` (which wraps it
    in its own shard_map) and the PP×EP dispatch path (models/vit.MoeMlp
    ``axes_bound`` — a nested shard_map would be illegal, but the
    collectives compose fine on the already-bound axes; VERDICT r3 #3).
    """
    n = axis_size(axis)
    E = params_local["gate"].shape[-1]
    B_l, S, d = xl.shape
    T = B_l * S
    ss = -(-T // n)  # per-axis-rank token shard (ceil)
    Tp = ss * n
    C = max(1, int(np.ceil(ss * top_k / E * capacity_factor)))
    if reduce_axes is None:
        reduce_axes = (axis,)

    flat = xl.reshape(T, d)
    r = jax.lax.axis_index(axis)
    flatp = jnp.pad(flat, ((0, Tp - T), (0, 0)))
    mine = jax.lax.dynamic_slice_in_dim(flatp, r * ss, ss, 0)
    valid = (r * ss + jnp.arange(ss)) < T
    out_l, kept, total = _rank_dispatch(
        params_local, mine, axis=axis, top_k=top_k, C=C, valid=valid
    )
    outp = jax.lax.all_gather(out_l, axis).reshape(Tp, d)
    out = outp[:T].reshape(xl.shape)
    kept = jax.lax.psum(kept, reduce_axes)
    total = jax.lax.psum(total, reduce_axes)
    dropped = 1.0 - kept / jnp.maximum(total, 1.0)
    return out, dropped
