"""Pallas grouped 3×3 convolution — the RegNet/ResNeXt hot op, hand-tiled.

Why this kernel exists (PERF.md r5, VERDICT r4 #2): XLA:TPU's
``feature_group_count`` lowering retiles channels physically (the r1
finding), and the r1 workaround — G per-group convs over slices of one
canonical kernel (``models/layers.UnrolledGroupConv``) — leaves the chip
at ~20% MFU on regnety_160: G small convs cannot pipeline their HBM
prefetches, and marginal-cost measurement on the chip puts the stage-3
grouped conv at 0.42-0.51 ms while this kernel's core does the same math
in 0.33 ms (≈48% MXU).

Design (TPU-first):
  * NO layout change at the HBM boundary. The kernel consumes the
    canonical NHWC activation viewed as ``[B, Hp, Wp, G, cg]`` — a free
    minor-dim split — and writes ``[B, Ho, Wo, G, fg]`` (minor-dim merge
    back). The group index is a GRID dimension resolved INSIDE the kernel
    by a sublane-axis dynamic slice; the earlier G-major design needed a
    physical transpose each way that cost more than XLA's whole conv
    (0.53 ms/conv measured).
  * Grid ``(B/BB,)`` — one program per batch tile, with a STATIC
    in-kernel loop over all G groups (Mosaic cannot prove a dynamic
    second-minor index respects bf16 (2,1) sublane packing, so g must be
    a compile-time constant). Each input block is fetched once and every
    group's output lane-concatenated into one 4D store, so HBM traffic
    stays at one read of x + one write of out.
  * taps are 2D slices of the padded block: for tap ``(dy, dx)`` the
    group's [BB, Hp, Wp, cg] view is sliced ``[:, dy:dy+Ho, dx:dx+Wo, :]``
    and contracted against ``w[dy, dx, g]`` — 9 aligned [·, cg] @ [cg, fg]
    MXU contractions accumulated in fp32. (An earlier design flattened
    padded rows to make each tap one contiguous sublane slice
    ``x_flat[m + dy·Wp + dx]``; it was abandoned — the 2D slices lower
    directly in Mosaic with no flatten reshape and identical traffic.)
    stride 2 uses 2D *strided* tap slices, which Mosaic only accepts in
    interpret mode (VMEM slice strides are confined to [1, 2)); compiled
    stride-2 convs (3 per net) fall back to the unrolled XLA path.
  * backward: dx is the SAME kernel run on the padded cotangent with the
    spatially-flipped, transposed kernel (a grouped conv identity);
    dW falls back to XLA's per-group correlation (measured cheap —
    its contraction over B·H·W rows is a well-tiled matmul already).

Exactness: identical math to the unrolled/fused paths (same canonical
``(3, 3, cg, C)`` parameter; fp32 accumulation inside the kernel), tested
in interpret mode on CPU and against the chip (tests/test_group_conv.py).

Reference analogue: none — the reference outsources grouped convs to
cuDNN via timm models (ref: /root/reference/requirements.txt:9,
README.md:215-217 baselines).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# per-program VMEM budget for block sizing (bytes); leaves headroom in
# the ~16 MB/core VMEM for double buffering
_VMEM_BUDGET = 9 * 2 ** 20


def _pick_bb(batch: int, hp: int, wp: int, c_all: int, ho: int, wo: int,
             cg: int, fg: int, groups: int, itemsize: int) -> int:
    """Largest batch tile whose blocks fit the VMEM budget.

    Accumulator accounting (ADVICE r5): ``_kernel_s1`` keeps ALL G group
    accumulators live until the final lane-concatenate — the per-group
    results are collected in ``outs`` and merged in one 4D store — so the
    live fp32 accumulator footprint is bb·ho·wo·G·fg, not one group's,
    plus the concatenated output temp that exists before the store. The
    earlier one-group model could admit a batch tile whose real peak
    overflowed VMEM on compiled TPU runs (loud Mosaic failure)."""
    for bb in (32, 16, 8, 4, 2, 1):
        if batch % bb:
            continue
        x_block = bb * hp * wp * c_all * itemsize     # input tile
        o_block = bb * ho * wo * groups * fg * itemsize
        acc = bb * ho * wo * groups * fg * 4          # ALL G fp32 accums live
        concat = bb * ho * wo * groups * fg * itemsize  # lane-merged temp
        scratch = bb * hp * wp * cg * itemsize * 2    # group gather + taps
        if x_block + o_block + acc + concat + scratch <= _VMEM_BUDGET:
            return bb
    return 1


def _kernel_s1(x_ref, w_ref, o_ref, *, ho, wo, wp, cg, fg, groups):
    """stride-1 3×3 tap-accumulation via 2D slices of the padded block.

    x_ref: [BB, Hp, Wp, G, cg]  w_ref: [3, 3, G, cg, fg]
    o_ref: [BB, Ho, Wo, G, fg]   (program: one batch tile, ALL groups —
    the group loop is static because Mosaic cannot prove a *dynamic*
    second-minor index respects bf16 (2,1) sublane packing; static odd
    indices lower fine, probed on-chip)
    """
    outs = []
    for g in range(groups):
        # this group's channels: static sublane-axis slice (the 5D view
        # makes this a sublane slice, not a misaligned lane slice)
        xg = x_ref[:, :, :, g, :]                   # [BB, Hp, Wp, cg]
        acc = None
        for dy in range(3):
            for dx in range(3):
                xs = xg[:, dy:dy + ho, dx:dx + wo, :]
                t = jax.lax.dot_general(
                    xs, w_ref[dy, dx, g],
                    (((3,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                acc = t if acc is None else acc + t
        outs.append(acc.astype(o_ref.dtype))
    # one 4D store of the lane-merged result — a 5D per-group store would
    # need a reshape Mosaic cannot lower ("unsupported shape cast")
    o_ref[...] = jnp.concatenate(outs, axis=-1)


def _kernel_s2(x_ref, w_ref, o_ref, *, ho, wo, cg, fg, groups):
    """stride-2 variant: 2D strided tap slices. Interpret-mode only —
    Mosaic rejects stride-2 VMEM slices (compiled stride-2 falls back to
    the XLA unrolled path in _conv_core)."""
    outs = []
    for g in range(groups):
        xg = x_ref[:, :, :, g, :]                   # [BB, Hp, Wp, cg]
        acc = None
        for dy in range(3):
            for dx in range(3):
                xs = jax.lax.slice(
                    xg,
                    (0, dy, dx, 0),
                    (xg.shape[0], dy + 2 * (ho - 1) + 1,
                     dx + 2 * (wo - 1) + 1, cg),
                    (1, 2, 2, 1),
                )
                t = jax.lax.dot_general(
                    xs, w_ref[dy, dx, g],
                    (((3,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                acc = t if acc is None else acc + t
        outs.append(acc.astype(o_ref.dtype))
    o_ref[...] = jnp.concatenate(outs, axis=-1)


def _conv_core(x, kernel, stride: int, groups: int, interpret: bool):
    """x: [B, H, W, C] (NHWC), kernel: [3, 3, cg, C] canonical HWIO."""
    b, h, w, c_all = x.shape
    cg = c_all // groups
    fg = kernel.shape[-1] // groups
    ho, wo = -(-h // stride), -(-w // stride)
    if stride != 1 and not interpret:
        # Mosaic rejects stride-2 strided slices in VMEM ("strides
        # confined to [1,2)"); the 2D-strided-tap kernel compiles only in
        # interpret mode. Compiled stride-2 (one conv per stage
        # transition) takes the unrolled XLA path.
        return _xla_unrolled(x, kernel, stride, groups)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    hp, wp = h + 2, w + 2
    x5 = xp.reshape(b, hp, wp, groups, cg)          # free minor split
    # canonical kernel → [3, 3, G, cg, fg] (tiny; traffic-irrelevant)
    w5 = kernel.reshape(3, 3, cg, groups, fg).transpose(0, 1, 3, 2, 4)
    bb = _pick_bb(b, hp, wp, c_all, ho, wo, cg, fg, groups,
                  jnp.dtype(x.dtype).itemsize)
    if stride == 1:
        body = functools.partial(
            _kernel_s1, ho=ho, wo=wo, wp=wp, cg=cg, fg=fg, groups=groups)
    else:
        body = functools.partial(
            _kernel_s2, ho=ho, wo=wo, cg=cg, fg=fg, groups=groups)
    return pl.pallas_call(
        body,
        grid=(b // bb,),
        in_specs=[
            pl.BlockSpec(
                (bb, hp, wp, groups, cg), lambda bt: (bt, 0, 0, 0, 0)),
            pl.BlockSpec(
                (3, 3, groups, cg, fg), lambda bt: (0, 0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (bb, ho, wo, groups * fg), lambda bt: (bt, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (b, ho, wo, groups * fg), x.dtype),
        interpret=interpret,
    )(x5, w5)


def _xla_unrolled(x, kernel, stride: int, groups: int):
    """Reference formulation (the UnrolledGroupConv math) — used for the
    dW transpose and as the exactness oracle."""
    cg = x.shape[-1] // groups
    fg = kernel.shape[-1] // groups
    outs = [
        jax.lax.conv_general_dilated(
            x[..., g * cg:(g + 1) * cg],
            kernel[..., g * fg:(g + 1) * fg],
            (stride, stride), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        for g in range(groups)
    ]
    return jnp.concatenate(outs, axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def group_conv3x3(x, kernel, stride: int = 1, groups: int = 1,
                  interpret: bool = False):
    """Grouped 3×3 conv, 'same' padding, via the Pallas kernel.

    ``x``: [B, H, W, C] NHWC; ``kernel``: [3, 3, C/G, C_out] — the same
    canonical parameter every other grouped-conv path uses, so
    checkpoints are compute-path-independent.
    """
    return _conv_core(x, kernel, stride, groups, interpret)


def _fwd(x, kernel, stride, groups, interpret):
    return _conv_core(x, kernel, stride, groups, interpret), (x, kernel)


def _bwd(stride, groups, interpret, res, dy):
    x, kernel = res
    cg = x.shape[-1] // groups
    fg = kernel.shape[-1] // groups
    if stride == 1:
        # dx = grouped conv of dy with the flipped, in/out-transposed
        # kernel — same kernel, same speed as the forward
        w5 = kernel.reshape(3, 3, cg, groups, fg)
        w_t = (
            w5[::-1, ::-1]                      # spatial flip
            .transpose(0, 1, 4, 3, 2)           # [3,3,fg,G,cg]
            .reshape(3, 3, fg, groups * cg)
        )
        dx = _conv_core(dy, w_t, 1, groups, interpret)
    else:
        # stride-2 dx is a dilated transpose conv (3 per net): XLA path
        dx = jax.vjp(
            lambda xx: _xla_unrolled(xx, kernel, stride, groups), x
        )[1](dy)[0]
    # dW: per-group correlation over B·H·W — a well-tiled XLA matmul
    dw = jax.vjp(
        lambda kk: _xla_unrolled(x, kk, stride, groups), kernel
    )[1](dy)[0]
    return dx, dw


group_conv3x3.defvjp(_fwd, _bwd)
