"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence-scaling mechanism at all — its only attention
runs on a fixed 196-token grid (ref: /root/reference/distribuuuu/models/
botnet.py:270-281, hard-asserted shape; SURVEY.md §5.7). This module is the
TPU-native capability the reference lacks: attention over sequences sharded
across the ``seq`` mesh axis, so context length scales with chips.

Two strategies, both built on XLA collectives riding ICI:

- **Ring attention** (Liu et al., arXiv:2310.01889): each device holds one
  query block and rotates K/V blocks around the ring with ``ppermute``,
  accumulating exact softmax attention with the online (flash) update. The
  K/V transfer for step ``i+1`` overlaps the block computation of step ``i``
  under XLA's latency-hiding scheduler. Exact — not an approximation.
- **Ulysses all-to-all** (arXiv:2309.14509): ``all_to_all`` re-shards
  sequence→heads, computes full attention locally on a head subset, and
  re-shards back. Cheaper at moderate sequence lengths; requires
  ``heads % seq_axis_size == 0``.

Both are pure functions of ``[B, H, S_shard, D]`` blocks designed to be
called inside ``shard_map`` (the mesh-axis name bound); ``ring_attention`` /
``ulysses_attention`` are the host-level wrappers that bind a mesh.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distribuuuu_tpu.parallel.compat import axis_size, shard_map

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)  # safe additive -inf


def _block_update(q, k, v, m, l, o, scale, mask):
    """One online-softmax accumulation step over a K/V block.

    q: [B,H,Sq,D]; k,v: [B,H,Sk,D]; m,l: [B,H,Sq] running max / normalizer;
    o: [B,H,Sq,Dv] unnormalized accumulator; mask: [Sq,Sk] bool or None.

    The whole update is a deliberate f32 region (the ``_fp32`` scope is
    the dtype lint's self-declaration convention): the running
    (m, l, o) logsumexp state must accumulate in f32 across up to n
    rotations — bf16 would round the correction products once per hop.
    """
    with jax.named_scope("ring_softmax_fp32"):
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * scale
        if mask is not None:
            s = jnp.where(mask[None, None], s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # exp of masked-out logits underflows to 0 via the _NEG_BIG shift
        p = jnp.exp(s - m_new[..., None])
        if mask is not None:
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, o_new


def _flash_state_update(q, kb, vb, m, l, o, scale, causal, interpret):
    """One online-softmax accumulation step computed by the Pallas flash
    kernel (r4). ``(o_b, lse_b)`` fully characterizes the block's softmax
    state as ``(m=lse_b, l=1, o_unnorm=o_b)``, which merges exactly with
    the running (m, l, o) — so ring attention's per-rotation updates get
    the kernel's VMEM tiling (no [Sq, Sk] logits materialized in HBM) and
    its fwd+bwd win. Gradients are exact: flash_attention_with_lse carries
    a vjp for BOTH outputs."""
    from distribuuuu_tpu.ops import flash_attention as fa

    # v upcast: the kernel writes o in v.dtype — bf16 v would round the
    # block output once per rotation before the f32 merge, a numerics
    # regression vs the all-f32 einsum path. f32 v keeps the accumulator
    # chain f32 end-to-end (scores still take the bf16-input MXU path);
    # the einsum ring pays full-f32 everywhere, so this still wins.
    o_b, lse_b = fa.flash_attention_with_lse(
        q, kb, vb.astype(jnp.float32), scale=scale, causal=causal,
        interpret=interpret,
    )
    m_new = jnp.maximum(m, lse_b)
    corr = jnp.exp(m - m_new)
    corr_b = jnp.exp(lse_b - m_new)
    l_new = corr * l + corr_b
    o_new = o * corr[..., None] + o_b * corr_b[..., None]
    return m_new, l_new, o_new


def _ring_flash_fits(q, k):
    """Whether the per-device shard can run the flash block path: head dim
    within lane tiling, equal q/k shards, and the whole-shard VMEM
    residency bound of the kernel (ops/flash_attention docstring)."""
    from distribuuuu_tpu.ops import flash_attention as fa

    d = q.shape[-1]
    L = q.shape[2]
    return d <= 128 and k.shape[2] == L and fa.fits_vmem(L, d)


def ring_self_attention(
    q, k, v, *, axis_name: str = "seq", causal: bool = False,
    scale: float | None = None, impl: str = "auto",
):
    """Exact attention over a ring-sharded sequence. Call inside shard_map.

    q, k, v: [B, H, S_shard, D] — this device's sequence block; the global
    sequence is the concatenation of blocks in mesh-axis order. Returns
    [B, H, S_shard, Dv] in v.dtype.

    ``impl``: ``"einsum"`` — the original whole-block einsum update;
    ``"flash"`` — per-rotation block updates through the Pallas flash
    kernel (``_flash_state_update``; Pallas interpreter off-TPU — tests);
    ``"auto"`` — flash on TPU when the shard fits the kernel's bounds,
    einsum otherwise. In causal mode the flash path also SKIPS
    fully-masked source blocks via ``lax.cond`` (the einsum path computes
    and masks them), and the local block runs the kernel's causal
    block-skip — ring + causal flash composition (VERDICT r3 #4).
    """
    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = d ** -0.5 if scale is None else scale
    with jax.named_scope("ring_softmax_fp32"):
        qf = q.astype(jnp.float32)

    if impl not in ("auto", "einsum", "flash"):
        raise ValueError(f"ring impl must be auto|einsum|flash, got {impl!r}")
    use_flash = impl == "flash" or (
        impl == "auto"
        and jax.default_backend() == "tpu"
        and v.shape[-1] == d
        and _ring_flash_fits(q, k)
    )
    if use_flash and (v.shape[-1] != d or sk != sq):
        raise ValueError(
            f"ring flash path needs Dv == D and equal q/k shards, got "
            f"D={d} Dv={v.shape[-1]} Sq={sq} Sk={sk}"
        )

    m0 = jnp.full((b, h, sq), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, h, sq, v.shape[-1]), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]
    q_pos = my_idx * sq + jnp.arange(sq)

    def block_mask(src):
        if not causal:
            return None
        k_pos = src * sk + jnp.arange(sk)
        return q_pos[:, None] >= k_pos[None, :]

    # local block first (no rotation needed), then n-1 rotate-and-update
    # steps. The local block is the (only) diagonal one: under flash it is
    # the statically-causal kernel call.
    if use_flash:
        m, l, o = _flash_state_update(
            q, k, v, m0, l0, o0, scale, causal, None
        )
    else:
        with jax.named_scope("ring_softmax_fp32"):
            m, l, o = _block_update(qf, k.astype(jnp.float32), v, m0, l0,
                                    o0, scale, block_mask(my_idx))

    def step(carry, step_idx):
        m, l, o, kb, vb = carry
        # rotate K/V from the previous device; XLA's latency-hiding scheduler
        # overlaps the transfer with the previous iteration's compute
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        # after `step_idx` rotations this device holds block (my_idx - step_idx)
        src = (my_idx - step_idx) % n
        if use_flash:
            # rotated blocks are never diagonal (step_idx ∈ [1, n-1]):
            # under causal they are fully visible (src < my_idx) or fully
            # masked (src > my_idx) — skip the latter outright
            def upd(args):
                m, l, o = args
                return _flash_state_update(
                    q, kb, vb, m, l, o, scale, False, None
                )

            if causal:
                m, l, o = jax.lax.cond(
                    src < my_idx, upd, lambda args: args, (m, l, o)
                )
            else:
                m, l, o = upd((m, l, o))
        else:
            with jax.named_scope("ring_softmax_fp32"):
                m, l, o = _block_update(qf, kb.astype(jnp.float32), vb, m,
                                        l, o, scale, block_mask(src))
        return (m, l, o, kb, vb), None

    if n > 1:
        (m, l, o, _, _), _ = jax.lax.scan(
            step, (m, l, o, k, v), jnp.arange(1, n)
        )
    with jax.named_scope("ring_softmax_fp32"):
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.astype(v.dtype)


def ulysses_self_attention(
    q, k, v, *, axis_name: str = "seq", causal: bool = False,
    scale: float | None = None,
):
    """All-to-all sequence parallelism. Call inside shard_map.

    Re-shards [B, H, S_shard, D] → [B, H/n, S_full, D] with one all_to_all,
    runs full (flash-style fp32-softmax) attention on the local head subset,
    and re-shards back. heads must divide by the axis size.
    """
    n = axis_size(axis_name)
    assert q.shape[1] % n == 0, (
        f"heads {q.shape[1]} not divisible by seq axis {n}"
    )
    # seq-sharded → head-sharded (gather full sequence, scatter heads)
    q, k, v = (
        jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
        for t in (q, k, v)
    )
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        sl = s.shape[-1]
        mask = jnp.tril(jnp.ones((sl, sl), bool))
        s = jnp.where(mask[None, None], s, _NEG_BIG)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", w, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)
    # head-sharded → seq-sharded
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def _spec(mesh: Mesh, data_axis: str | None, seq_axis: str):
    data = data_axis if data_axis and data_axis in mesh.axis_names else None
    return P(data, None, seq_axis, None)


def ring_attention(
    q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
    data_axis: str | None = "data", causal: bool = False,
    scale: float | None = None, impl: str = "auto",
):
    """Host-level ring attention: q,k,v are global [B, H, S, D] arrays with S
    sharded over ``seq_axis`` (and B optionally over ``data_axis``).
    ``impl`` routes the per-rotation block updates (see
    :func:`ring_self_attention`): flash kernel on TPU by default."""
    spec = _spec(mesh, data_axis, seq_axis)
    fn = functools.partial(
        ring_self_attention, axis_name=seq_axis, causal=causal, scale=scale,
        impl=impl,
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def ulysses_attention(
    q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
    data_axis: str | None = "data", causal: bool = False,
    scale: float | None = None,
):
    """Host-level Ulysses attention over a ``seq``-sharded sequence."""
    spec = _spec(mesh, data_axis, seq_axis)
    fn = functools.partial(
        ulysses_self_attention, axis_name=seq_axis, causal=causal, scale=scale
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def blockwise_attention(q, k, v, *, chunk: int = 256, causal: bool = False,
                        scale: float | None = None, remat: bool = True):
    """Single-device flash-style attention: exact softmax in O(L·chunk)
    memory instead of the dense path's O(L²) logits (Rabe & Staats,
    arXiv:2112.05682; the single-chip sibling of ring attention — same
    ``_block_update`` online-softmax math, ``lax.scan`` over local K/V
    chunks instead of ``ppermute`` hops around a mesh ring).

    This is what makes high-resolution ViT trainable on one chip: at
    L=4096 the dense attention materializes ~L²·H·B bf16 logits per layer
    (hundreds of MB) while this keeps only the running (m, l, o) state plus
    one [L, chunk] block. ``remat=True`` recomputes each chunk's block in
    the backward pass, so autodiff never stores the probabilities either.

    q, k: [B, H, L, D]; v: [B, H, L, Dv]. Returns [B, H, L, Dv] in v.dtype.
    """
    b, h, L, d = q.shape
    dv = v.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    chunk = min(chunk, L)
    nc = -(-L // chunk)
    pad = nc * chunk - L
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # [nc, B, H, chunk, D] so scan slices one K/V chunk per step
    ks = jnp.moveaxis(kp.reshape(b, h, nc, chunk, d), 2, 0)
    vs = jnp.moveaxis(vp.reshape(b, h, nc, chunk, dv), 2, 0)

    qf = q.astype(jnp.float32)
    q_pos = jnp.arange(L)
    m0 = jnp.full((b, h, L), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, L), jnp.float32)
    o0 = jnp.zeros((b, h, L, dv), jnp.float32)
    need_pad_mask = pad > 0

    def step(carry, inp):
        m, l, o = carry
        idx, kb, vb = inp
        k_pos = idx * chunk + jnp.arange(chunk)
        mask = None
        if causal or need_pad_mask:
            mask = jnp.broadcast_to((k_pos < L)[None, :], (L, chunk))
            if causal:
                mask = mask & (q_pos[:, None] >= k_pos[None, :])
        m, l, o = _block_update(
            qf, kb.astype(jnp.float32), vb, m, l, o, scale, mask
        )
        return (m, l, o), None

    step_fn = jax.checkpoint(step) if remat else step
    (m, l, o), _ = jax.lax.scan(
        step_fn, (m0, l0, o0), (jnp.arange(nc), ks, vs)
    )
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(v.dtype)


def reference_attention(q, k, v, *, causal: bool = False,
                        scale: float | None = None):
    """Single-device exact attention — the numerics oracle for the tests."""
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        sl = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((sl, sl), bool))[None, None], s,
                      _NEG_BIG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(
        v.dtype
    )
