"""Fused Pallas TPU kernel for BoTNet's 2D relative-position attention.

The showcase native-performance component (SURVEY.md §7.6): the reference
computes MHSA over the 14×14=196-token grid as separate einsum/softmax ops
(ref: /root/reference/distribuuuu/models/botnet.py:193-214), each of which
round-trips the [B, N, 196, 196] logits through HBM. This kernel fuses
``softmax(q·kᵀ + pos) · v`` into one VMEM-resident program per (batch, head):
the logits tile never leaves on-chip memory, both matmuls hit the MXU, and
the softmax runs on the VPU between them.

The sequence axis is padded to a multiple of 128 lanes (196 → 256) with
``-inf`` position logits on the padded keys so the softmax ignores them;
padded query rows are sliced off on the way out.

Backward: ``jax.custom_vjp`` recomputes the (cheap, 196-token) attention with
plain XLA ops — the forward fusion is where the HBM traffic is.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _attention_kernel(q_ref, k_ref, v_ref, pos_ref, o_ref, *, length: int):
    # q/k/v blocks are [1, Lp, D] (padded); pos is [1, L, L] unpadded — it is
    # padded here in VMEM with -inf keys, which keeps the (4-byte × L²) pos
    # tensor from being re-written padded in HBM by the host wrapper.
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
    lp = s.shape[-1]
    pad = lp - length
    pos = pos_ref[0]
    if pad:
        pos = jnp.pad(pos, ((0, pad), (0, pad)), constant_values=_NEG_BIG)
    s = s + pos
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o_ref[0] = jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32).astype(
        o_ref.dtype
    )


def _fused_forward(q, k, v, pos, scale: float, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, length, d = q.shape
    dv = v.shape[-1]
    lp = _round_up(length, 128)
    pad = lp - length

    def flat(t, dd):
        return t.reshape(b * n, length, dd)

    qf = flat(q * scale, d)
    kf, vf = flat(k, d), flat(v, dv)
    posf = pos.astype(jnp.float32).reshape(b * n, length, length)
    if pad:
        qf = jnp.pad(qf, ((0, 0), (0, pad), (0, 0)))
        kf = jnp.pad(kf, ((0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad), (0, 0)))

    def spec3(a, c):
        return pl.BlockSpec(
            (1, a, c), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        )

    out = pl.pallas_call(
        functools.partial(_attention_kernel, length=length),
        out_shape=jax.ShapeDtypeStruct((b * n, lp, dv), v.dtype),
        grid=(b * n,),
        in_specs=[spec3(lp, d), spec3(lp, d), spec3(lp, dv),
                  spec3(length, length)],
        out_specs=spec3(lp, dv),
        interpret=interpret,
    )(qf, kf, vf, posf)
    return out[:, :length].reshape(b, n, length, dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_attention(q, k, v, pos, scale: float, interpret: bool = False):
    """softmax(q·kᵀ·scale + pos) · v, fused on TPU.

    q, k: [B, N, L, D]; v: [B, N, L, Dv]; pos: [B, N, L, L] float logits.
    Matches ops.attention.mhsa_2d numerics (fp32 softmax, output v.dtype).
    """
    return _fused_forward(q, k, v, pos, scale, interpret)


def _fwd(q, k, v, pos, scale, interpret):
    return _fused_forward(q, k, v, pos, scale, interpret), (q, k, v, pos)


def _bwd(scale, interpret, res, g):
    # Recompute in plain XLA: at 196 tokens the bwd matmuls dominate anyway
    # and XLA fuses the elementwise chain.
    q, k, v, pos = res
    s = jnp.einsum(
        "bnxd,bnyd->bnxy", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale + pos.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    gf = g.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dv = jnp.einsum("bnxy,bnxd->bnyd", p, gf)
    dp = jnp.einsum("bnxd,bnyd->bnxy", gf, vf)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bnxy,bnyd->bnxd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bnxy,bnxd->bnyd", ds, q.astype(jnp.float32)) * scale
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
        ds.astype(pos.dtype),
    )


fused_attention.defvjp(_fwd, _bwd)


def use_pallas(impl: str) -> bool:
    """Resolve an attention-impl knob: 'pallas' | 'xla' | 'auto'.

    'auto' currently resolves to the XLA path: measured on a v5e chip at the
    BoTNet shape (B=32, N=4, L=196, D=128), XLA's own fusion runs the
    attention in ~53µs vs ~115µs for this kernel — the 196-token grid is too
    small for a per-(batch, head) Pallas grid to keep the MXU busy
    (grid programs execute sequentially per core), and XLA's batched-matmul
    layout wins. The kernel stays as a forceable alternative and the
    foundation for shapes where fusion *does* pay (long-sequence attention
    uses ops/ring_attention.py instead).
    """
    if impl == "pallas":
        return True
    if impl == "xla":
        return False
    if impl != "auto":
        raise ValueError(
            f"attn_impl must be 'auto', 'xla', or 'pallas'; got {impl!r}"
        )
    return False


def mhsa_2d_fused(q, k, v, pos_logits, scale: float):
    """Drop-in for ops.attention.mhsa_2d using the fused kernel.

    Compiled on TPU; interpreter mode elsewhere (CPU tests), so the same
    call site works on the fake mesh and real chips.
    """
    interpret = jax.default_backend() != "tpu"
    return fused_attention(q, k, v, pos_logits, scale, interpret)
