"""Train+serve soak referee (ROADMAP open item #5; ISSUE 7's proof).

Composes the five subsystems into the production scenario they exist
for: shards-backed training runs with deterministic ``FAULTS.*``
injection, a serving fleet answering Poisson background traffic and
hot-reloading checkpoints as epochs complete, and the LIVE monitor
(telemetry/live.py) watching every interval — then referees the whole
thing into one machine-readable verdict (``SOAK_r01.json``):

* every injected fault class must raise EXACTLY its expected
  ``kind="alert"`` record (and nothing else);
* the clean control interval must raise ZERO alerts;
* run_report-style regression gates are evaluated per interval against
  the control interval's report (intervals that inject a regression are
  EXPECTED to fail their gate — the gate catching them is the proof);
* the monitored control run must be bit-identical to an unmonitored
  rerun of the same config (trajectory-neutrality, checked leaf by leaf
  in a fresh interpreter).

Interval matrix (``--smoke`` keeps the first two; fault batch indices
scale with the corpus so every injection lands inside the epoch):

    control           no faults            expects no alert, gate n/a
    nonfinite         FAULTS.NAN_STEP      expects {nonfinite}, gate PASS
    stall             FAULTS.STALL_*       expects {stall}, gate PASS
    recompile_storm   FAULTS.RECOMPILE_*   expects {recompile-storm},
                                           gate FAIL (recompiles count)
    slowdown          FAULTS.SLOWDOWN_*    expects {throughput-regression},
                                           gate FAIL (img/s)
    p99_burst         open-loop overload   expects {p99-breach} (serve
                                           plane only, no train)

Straggler-skew is deliberately NOT injected here: on a lockstep data-
parallel CPU run every rank's step span includes the collective wait, so
a host-side sleep on one rank slows every rank's measured step equally —
the skew rule is exercised from synthetic multi-rank sinks in
tests/test_monitor.py instead.

Thresholds that depend on the host are calibrated, not guessed: the
throughput baseline is the control interval's own live rate, and the
serve p99 threshold comes from background-traffic latency observed while
training runs (the contended case), so the soak is meaningful on a
laptop and on a pod. Each train interval is a fresh interpreter (the
resilience-drill pattern — injected faults must not share JAX state).

    python tools/soak.py --out SOAK_r01.json       # the full matrix
    python tools/soak.py --smoke                   # control + nonfinite
    python tools/soak.py --dry                     # validate, run nothing
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

from distribuuuu_tpu.telemetry.live import (
    AlertRule,
    Monitor,
    MonitorSink,
    RuleEngine,
    load_rules,
)

SOAK_SCHEMA = 1
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
# hermetic single-device run: a parent test harness may export
# xla_force_host_platform_device_count=8 (the virtual test mesh), which
# would silently turn each interval into dp=8 and shift every
# batch-indexed fault injection off its target step
os.environ["XLA_FLAGS"] = ""
import jax
jax.config.update("jax_platforms", "cpu")

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out_dir = sys.argv[1]
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 4
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.TRAIN.BATCH_SIZE = 4
cfg.TRAIN.IM_SIZE = 32
cfg.TRAIN.PRINT_FREQ = 16
cfg.TEST.BATCH_SIZE = 8
cfg.TEST.IM_SIZE = 32
cfg.DATA.FORMAT = "shards"
cfg.DATA.SHARDS_BLOCK = 4
cfg.DATA.SHARDS_WINDOW = 16
cfg.OPTIM.MAX_EPOCH = 1
cfg.RNG_SEED = 0
cfg.OUT_DIR = out_dir
if len(sys.argv) > 2:
    cfg.merge_from_list(sys.argv[2:])
best = trainer.train_model()
print(f"SOAK_RUN_DONE best={best:.3f}", flush=True)
"""

# fresh-interpreter checkpoint comparison: argv = ckpt_a ckpt_b; exits 0
# iff every leaf of both trees is BIT-identical
COMPARE = """
import sys
import numpy as np
import jax
from distribuuuu_tpu.utils import checkpoint as ckpt

a = ckpt.load_checkpoint(sys.argv[1])
b = ckpt.load_checkpoint(sys.argv[2])
la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
assert len(la) == len(lb), f"leaf count {len(la)} != {len(lb)}"
diff = sum(
    0 if np.array_equal(np.asarray(x), np.asarray(y)) else 1
    for x, y in zip(la, lb)
)
print(f"COMPARE leaves={len(la)} diff={diff}", flush=True)
sys.exit(0 if diff == 0 else 1)
"""


def _run_report_module():
    """tools/run_report.py as an importable module (the per-interval
    gates reuse its build_report/compare verbatim — the soak gate IS the
    post-mortem gate, evaluated early)."""
    import importlib

    tools = os.path.join(_ROOT, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    return importlib.import_module("run_report")


def make_corpus(work: str, per_class: int) -> tuple[str, int]:
    """Synthetic 4-class imagefolder packed into REAL record shards (the
    resilience-drill recipe); returns (shards_root, train_batches)."""
    import numpy as np
    from PIL import Image

    from distribuuuu_tpu.data.shards.format import pack_imagefolder

    src = os.path.join(work, "imagefolder")
    rng = np.random.default_rng(0)
    for split, n in (("train", per_class), ("val", max(4, per_class // 8))):
        for c in range(4):
            d = os.path.join(src, split, f"class{c}")
            os.makedirs(d, exist_ok=True)
            for i in range(n):
                arr = rng.integers(0, 256, size=(48, 56, 3), dtype=np.uint8)
                arr[:, :, c % 3] |= 0x80
                Image.fromarray(arr).save(
                    os.path.join(d, f"img{i}.jpg"), "JPEG", quality=90
                )
    out = os.path.join(work, "shards")
    pack_imagefolder(src, out, target_bytes=64 * 1024)
    return out, per_class * 4 // 4  # batch size 4, 4 classes


def interval_matrix(n_batches: int) -> list[dict]:
    """The train intervals; fault batch indices scale with the corpus so
    injections land mid-epoch at any ``--per-class``."""
    nan_at = max(2, int(n_batches * 0.30))
    stall_at = max(3, int(n_batches * 0.60))
    recompile_at = max(3, int(n_batches * 0.45))
    return [
        {"name": "control", "overrides": (), "expected": [],
         "expected_gate": None},
        {"name": "nonfinite", "expected": ["nonfinite"],
         "expected_gate": "pass",
         "overrides": ("TRAIN.NONFINITE", "skip", "FAULTS.ENABLED", "True",
                       "FAULTS.NAN_STEP", nan_at)},
        {"name": "stall", "expected": ["stall"], "expected_gate": "pass",
         "overrides": ("TRAIN.STALL_TIMEOUT", 0.6, "FAULTS.ENABLED", "True",
                       "FAULTS.STALL_EPOCH", 0,
                       "FAULTS.STALL_AT_BATCH", stall_at,
                       "FAULTS.STALL_S", 2.0)},
        {"name": "recompile_storm", "expected": ["recompile-storm"],
         "expected_gate": "fail",
         "overrides": ("FAULTS.ENABLED", "True",
                       "FAULTS.RECOMPILE_AT_BATCH", recompile_at,
                       "FAULTS.RECOMPILE_N", 12)},
        {"name": "slowdown", "expected": ["throughput-regression"],
         "expected_gate": "fail",
         "overrides": ("FAULTS.ENABLED", "True", "FAULTS.SLOWDOWN_EPOCH", 0,
                       "FAULTS.SLOWDOWN_MS", 250.0)},
    ]


def build_rules(*, baseline: float | None = None,
                p99_ms: float | None = None) -> list[AlertRule]:
    """The soak's rule set — the same kinds config/monitor_rules.yaml
    ships, with the host-dependent thresholds filled by calibration
    (throughput baseline from the control interval, p99 from observed
    contended background latency). Dormant rules stay DECLARED so a
    false positive from them would still fail the exact-match check."""
    specs = [
        {"kind": "recompile-storm", "threshold": 8, "window_s": 10},
        {"kind": "stall", "threshold": 1},
        {"kind": "nonfinite", "threshold": 1},
        {"kind": "straggler-skew", "threshold": 1.5, "breach_windows": 2,
         "min_steps": 8},
        # breach_windows 3: a one-off pause (the ~2s recompile-storm
        # burst, a single stall) can dip at most two consecutive windows;
        # only a SUSTAINED regression breaches three
        {"kind": "throughput-regression", "threshold": 40.0,
         "breach_windows": 3, "min_steps": 4,
         **({"baseline": baseline} if baseline else {})},
    ]
    if p99_ms is not None:
        specs.append({"kind": "p99-breach", "threshold": p99_ms,
                      "breach_windows": 2, "min_steps": 4})
    return [AlertRule(s) for s in specs]


# ------------------------------------------------------------- train side
def spawn_train(work: str, out_dir: str, shards_root: str,
                overrides=(), tag: str = "run"):
    """One fresh-interpreter training run (non-blocking); returns
    (Popen, log_path)."""
    os.makedirs(work, exist_ok=True)
    script = os.path.join(work, "soak_worker.py")
    with open(script, "w") as f:
        f.write(WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    log_path = os.path.join(work, f"{tag}.log")
    data_over = ("TRAIN.DATASET", shards_root, "TEST.DATASET", shards_root)
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, script, out_dir,
         *map(str, data_over + tuple(overrides))],
        env=env, cwd=_ROOT, stdout=log, stderr=subprocess.STDOUT, text=True,
    )
    log.close()  # the child holds the fd
    return proc, log_path


def newest_checkpoint(out_dir: str) -> str | None:
    d = os.path.join(out_dir, "checkpoints")
    if not os.path.isdir(d):
        return None
    cands = sorted(
        n for n in os.listdir(d)
        if n.startswith("ckpt_ep_") and not n.endswith(".corrupt")
    )
    return os.path.join(d, cands[-1]) if cands else None


def check_divergence(work: str, shards_root: str, monitored_out: str) -> dict:
    """Re-run the control config WITHOUT a monitor attached and compare
    the final checkpoints bit-for-bit in a fresh interpreter."""
    out2 = os.path.join(work, "unmonitored")
    proc, log_path = spawn_train(work, out2, shards_root, tag="unmonitored")
    proc.wait(timeout=1800)
    a, b = newest_checkpoint(monitored_out), newest_checkpoint(out2)
    result = {"checked": True, "bit_identical": False,
              "monitored_ckpt": a, "unmonitored_ckpt": b}
    if proc.returncode != 0 or a is None or b is None:
        result["error"] = f"unmonitored rerun rc={proc.returncode}"
        return result
    script = os.path.join(work, "soak_compare.py")
    with open(script, "w") as f:
        f.write(COMPARE)
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    cmp = subprocess.run(
        [sys.executable, script, a, b], env=env, cwd=_ROOT,
        capture_output=True, text=True, timeout=600,
    )
    result["bit_identical"] = cmp.returncode == 0
    lines = (cmp.stdout + cmp.stderr).strip().splitlines()
    marker = [ln for ln in lines if ln.startswith("COMPARE ")]
    result["detail"] = marker[-1] if marker else "\n".join(lines)[-200:]
    return result


# ------------------------------------------------------------- serve side
class ServePlane:
    """The co-located serving side: a FleetService (replicas are real
    serve_net.py processes), a router listener the monitor probes over
    the stats control frame, a Poisson background client, checkpoint
    hot-reload, and the overload burst."""

    def __init__(self, work: str, weights: str, *, rate_rps: float = 2.0):
        import distribuuuu_tpu.config as config
        from distribuuuu_tpu.config import cfg

        self.work = work
        self.rate_rps = float(rate_rps)
        config.reset_cfg()
        cfg.MODEL.ARCH = "resnet18"
        cfg.MODEL.NUM_CLASSES = 4
        cfg.MODEL.BN_GROUP = 8
        cfg.MODEL.WEIGHTS = weights
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        cfg.DEVICE.PLATFORM = "cpu"
        cfg.TRAIN.IM_SIZE = 16
        cfg.TEST.IM_SIZE = 16
        cfg.RNG_SEED = 0
        cfg.DATA.DEVICE_NORMALIZE = False  # float payloads, no PIL
        cfg.OUT_DIR = os.path.join(work, "serve_out")
        cfg.SERVE.MAX_BATCH = 4
        cfg.SERVE.MAX_WAIT_MS = 5.0
        cfg.SERVE.MAX_QUEUE = 64
        cfg.SERVE.FLEET.AUTOSCALE = False  # the soak pins fleet size 1
        cfg.SERVE.FLEET.MIN_REPLICAS = 1
        cfg.SERVE.FLEET.HEALTH_PERIOD_S = 0.5
        self.cfg = cfg
        self.cfg_path = os.path.join(work, "serve_cfg.yaml")
        self._dump_cfg()

        import numpy as np

        from distribuuuu_tpu.serve.fleet import FleetService

        rng = np.random.default_rng(0)
        self.payloads = []
        import io

        for _ in range(8):
            buf = io.BytesIO()
            np.save(buf, rng.standard_normal((16, 16, 3)).astype(np.float32))
            self.payloads.append(buf.getvalue())

        self.svc = FleetService(cfg, 1, cfg_path=self.cfg_path, out_dir=work)
        self.tallies = {"ok": 0, "failed": 0, "backoff": 0}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener = None
        self.addr = None
        self.reloads: list[dict] = []

    def _dump_cfg(self) -> None:
        with open(self.cfg_path, "w") as f:
            f.write(self.cfg.dump())

    def start(self) -> "ServePlane":
        from distribuuuu_tpu.serve import protocol

        self.svc.start(wait=True)
        self._listener = protocol.open_listener("127.0.0.1", 0)
        self.addr = self._listener.getsockname()[:2]
        threading.Thread(
            target=self.svc.serve,
            args=(self._listener, self._stop.is_set),
            daemon=True, name="soak-router",
        ).start()
        threading.Thread(
            target=self._background_client, daemon=True, name="soak-loadgen"
        ).start()
        return self

    def _dispatch(self, payload: bytes) -> str:
        """One request through the router; "ok" / "backoff" / "failed".
        Backpressure (queue_full / draining / no_routable_replicas) is
        the admission contract working — the caller backs off and
        retries the idempotent request; only a hard error counts
        failed."""
        resp = self.svc.router.dispatch(payload)
        if resp.startswith(b'{"error"'):
            err = json.loads(resp).get("error")
            if err in ("queue_full", "draining", "no_routable_replicas"):
                with self._lock:
                    self.tallies["backoff"] += 1
                return "backoff"
            with self._lock:
                self.tallies["failed"] += 1
            return "failed"
        with self._lock:
            self.tallies["ok"] += 1
        return "ok"

    def _background_client(self) -> None:
        """Poisson arrivals at ``rate_rps`` for the whole soak — the
        'millions of users' stand-in that must survive every train
        interval and every hot-reload with zero failures."""
        import random

        i = 0
        while not self._stop.is_set():
            time.sleep(random.expovariate(self.rate_rps))
            if self._stop.is_set():
                break
            self._dispatch(self.payloads[i % len(self.payloads)])
            i += 1

    def observed_p99_ms(self, window_s: float = 30.0) -> float:
        return float(
            self.svc.router.window_stats(window_s).get("p99_ms", 0.0)
        )

    def hot_reload(self, ckpt_path: str) -> dict:
        """Roll the fleet onto a new checkpoint with zero dropped
        requests: rewrite the replica config's MODEL.WEIGHTS, then
        draining-restart every replica (mark_draining → SIGTERM drain →
        replacement spawn, warm-up gated). Records whether the served
        function actually changed (a fixed probe's logits differ)."""
        before = self._probe_logits()
        failed_before = self.tallies["failed"]
        self.cfg.defrost()
        self.cfg.MODEL.WEIGHTS = ckpt_path
        self._dump_cfg()
        ok = all(
            self.svc.pool.restart_replica(rep.id, wait=True)
            for rep in list(self.svc.router.replicas())
        )
        after = self._probe_logits()
        rec = {
            "ckpt": ckpt_path,
            "ok": bool(ok and self.svc.router.n_routable() >= 1),
            "failed_during_reload": self.tallies["failed"] - failed_before,
            "logits_changed": (
                before is not None and after is not None and before != after
            ),
        }
        self.reloads.append(rec)
        return rec

    def _probe_logits(self):
        resp = self.svc.router.dispatch(self.payloads[0])
        if resp.startswith(b'{"error"'):
            return None
        return json.loads(resp).get("logits")

    def measure_capacity_rps(self, seconds: float = 3.0,
                             clients: int = 4) -> float:
        """Short closed-loop probe of fleet capacity (the burst offers a
        multiple of this)."""
        done = {"n": 0}
        stop = time.perf_counter() + seconds

        def worker(ci):
            i = ci
            while time.perf_counter() < stop:
                self._dispatch(self.payloads[i % len(self.payloads)])
                done["n"] += 1
                i += 1

        threads = [threading.Thread(target=worker, args=(c,), daemon=True)
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return max(1.0, done["n"] / seconds)

    def overload_burst(self, clients: int, duration_s: float) -> dict:
        """Deeply oversubscribed closed-loop hammer (the serve_bench
        saturation pattern): ``clients`` threads each keep one request
        outstanding, so admitted requests queue behind dozens of peers
        and latency climbs well past steady state — the p99-breach
        injection. queue_full rejections are expected and counted (the
        backpressure design working, not a failure)."""
        stop_at = time.perf_counter() + duration_s
        sent = {"n": 0}
        lock = threading.Lock()

        def worker(ci):
            i = ci
            while time.perf_counter() < stop_at:
                res = self._dispatch(self.payloads[i % len(self.payloads)])
                with lock:
                    sent["n"] += 1
                if res != "ok":
                    time.sleep(0.02)  # back off, keep the pressure on
                i += 1

        threads = [threading.Thread(target=worker, args=(c,), daemon=True)
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {"clients": clients, "sent": sent["n"],
                "duration_s": duration_s}

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self.svc.shutdown()
        finally:
            if self._listener is not None:
                try:
                    self._listener.close()
                except OSError:
                    pass


# ----------------------------------------------------------------- referee
def _median(vals: list[float]) -> float | None:
    vals = sorted(vals)
    return vals[len(vals) // 2] if vals else None


def run_train_interval(spec: dict, *, work: str, shards_root: str,
                       rules: list[AlertRule], interval_s: float,
                       serve_addr, log) -> dict:
    """One train interval: spawn the worker, monitor it live until exit,
    return {raised, snapshots, report, out_dir, rc}."""
    out_dir = os.path.join(work, "intervals", spec["name"])
    engine = RuleEngine(rules, interval_s=interval_s)
    mon = Monitor(out_dir, engine, serve_addr=serve_addr,
                  sink_path=os.path.join(work, f"MONITOR_{spec['name']}.jsonl"))
    proc, log_path = spawn_train(
        os.path.join(work, "intervals"), out_dir, shards_root,
        overrides=spec["overrides"], tag=spec["name"],
    )
    rates: list[float] = []

    def on_tick(out):
        snap = out["snapshot"]
        if snap["img_per_sec"] is not None and snap["steps"] >= 4:
            rates.append(snap["img_per_sec"])
        for a in out["alerts"]:
            log(f"    ALERT {a['rule']}: {a['message']}")

    t0 = time.time()
    mon.run(interval_s, should_stop=lambda: proc.poll() is not None,
            on_tick=on_tick)
    proc.wait(timeout=60)
    mon.close()
    return {
        "out_dir": out_dir, "rc": proc.returncode,
        "raised": sorted({a["rule"] for a in mon.alerts}),
        "alerts": mon.alerts, "median_img_per_sec": _median(rates),
        "duration_s": round(time.time() - t0, 1),
        "monitor_sink": mon.sink.path, "log": log_path,
    }


def run_soak(args) -> dict:
    log = lambda msg: print(msg, flush=True)  # noqa: E731
    work = args.work_dir or tempfile.mkdtemp(prefix="soak_")
    os.makedirs(work, exist_ok=True)
    run_report = _run_report_module()
    sink = MonitorSink(os.path.join(work, "soak_events.jsonl"))
    # per-metric gate tolerances: tail percentiles and IO-shaped metrics
    # are high-variance on short intervals sharing one core with the
    # monitor and the serve plane; p50 and throughput stay at the strict
    # default — they are what the regression injections must move
    gate_tols = {"data_wait_frac": 400.0, "straggler_skew": 25.0,
                 "ckpt_save_max_s": 300.0, "step_ms_p90": 120.0,
                 "step_ms_p99": 250.0}

    log(f"soak: work dir {work}")
    shards_root, n_batches = make_corpus(work, args.per_class)
    log(f"soak: shard corpus ready ({args.per_class * 4} train samples, "
        f"{n_batches} batches/epoch)")
    matrix = interval_matrix(n_batches)
    if args.intervals:
        keep = set(args.intervals.split(","))
        matrix = [m for m in matrix if m["name"] in keep]
    if args.smoke:
        matrix = matrix[:2]  # control + nonfinite
    if not matrix or matrix[0]["name"] != "control":
        raise SystemExit("soak: the interval matrix must start with "
                         "'control' (it is the gate baseline)")

    serve: ServePlane | None = None
    intervals: list[dict] = []
    control_report = None
    baseline_rate = None
    p99_threshold = None
    ok_all = True
    try:
        for idx, spec in enumerate(matrix):
            # p99 rule arms once contended background latency is known
            # (observed while a train interval ran with traffic flowing)
            rules = build_rules(baseline=baseline_rate,
                                p99_ms=p99_threshold)
            armed = sorted(r.kind for r in rules
                           if not (r.kind == "throughput-regression"
                                   and r.baseline is None))
            log(f"[{idx}] {spec['name']}: rules armed: {', '.join(armed)}")
            res = run_train_interval(
                spec, work=work, shards_root=shards_root,
                rules=rules, interval_s=args.interval_s,
                serve_addr=serve.addr if serve else None, log=log,
            )
            raised, expected = res["raised"], sorted(spec["expected"])
            entry = {
                "interval": idx, "name": spec["name"],
                "kind": "train", "rc": res["rc"],
                "expected_alerts": expected, "raised_alerts": raised,
                "alerts_exact": raised == expected,
                "duration_s": res["duration_s"],
                "median_img_per_sec": res["median_img_per_sec"],
            }
            # the per-interval run_report gate, evaluated NOW — not hours
            # later: control is the baseline; regression-injecting
            # intervals are expected to FAIL it
            report = run_report.build_report(res["out_dir"])
            if spec["name"] == "control":
                control_report = report
                baseline_rate = res["median_img_per_sec"]
                entry["gate"] = None
            else:
                cmp = run_report.compare(report, control_report,
                                         args.gate_tol_pct, gate_tols)
                want_fail = spec["expected_gate"] == "fail"
                entry["gate"] = {
                    "ok": cmp["ok"], "checked": cmp["checked"],
                    "expected": spec["expected_gate"],
                    "as_expected": cmp["ok"] != want_fail,
                    "failed_metrics": [r["metric"] for r in cmp["rows"]
                                       if not r["ok"]],
                    "rows": cmp["rows"],
                }
            entry["ok"] = (
                res["rc"] == 0 and entry["alerts_exact"]
                and (entry["gate"] is None or entry["gate"]["as_expected"])
            )
            ok_all &= entry["ok"]
            log(f"[{idx}] {spec['name']}: "
                f"{'ok' if entry['ok'] else 'FAIL'} — raised "
                f"{raised or '[]'} (expected {expected or '[]'})"
                + (f", gate {'PASS' if entry['gate']['ok'] else 'FAIL'} "
                   f"(expected {spec['expected_gate']})"
                   if entry["gate"] else ""))
            sink.emit_event("soak.interval", **{
                k: v for k, v in entry.items() if k != "kind"
            })
            intervals.append(entry)

            if spec["name"] == "control" and not args.no_serve:
                ckpt = newest_checkpoint(res["out_dir"])
                log(f"soak: starting serve fleet on {ckpt}")
                serve = ServePlane(work, ckpt, rate_rps=args.rate_rps)
                serve.start()
                log(f"soak: fleet routable, router stats at "
                    f"{serve.addr[0]}:{serve.addr[1]}, background "
                    f"Poisson at {args.rate_rps} rps")
            elif serve is not None:
                # contended-background p99 calibration after the first
                # train interval that ran WITH traffic flowing
                if p99_threshold is None:
                    obs = serve.observed_p99_ms(window_s=res["duration_s"])
                    # 4x the worst contended background p99, floored (an
                    # idle fleet's p99 is single-digit ms — 4x that is
                    # not a meaningful SLO) and capped (the burst must
                    # remain provably above the threshold)
                    p99_threshold = round(
                        min(max(4.0 * obs, 150.0), 600.0), 1
                    )
                    log(f"soak: p99-breach armed at {p99_threshold}ms "
                        f"(4x contended background p99 {obs}ms)")
                ckpt = newest_checkpoint(res["out_dir"])
                if ckpt:
                    rec = serve.hot_reload(ckpt)
                    log(f"soak: hot-reload -> {os.path.basename(ckpt)} "
                        f"ok={rec['ok']} failed={rec['failed_during_reload']}"
                        f" logits_changed={rec['logits_changed']}")

        # ---- the serve-plane burst interval (p99-breach) ----------------
        if serve is not None and p99_threshold is not None:
            idx = len(intervals)
            cap = serve.measure_capacity_rps()
            burst_clients = 96
            log(f"[{idx}] p99_burst: fleet capacity ~{cap:.0f} rps; "
                f"hammering with {burst_clients} closed-loop clients")
            burst_dir = os.path.join(work, "intervals", "p99_burst")
            os.makedirs(burst_dir, exist_ok=True)
            engine = RuleEngine(build_rules(baseline=None,
                                            p99_ms=p99_threshold),
                                interval_s=args.interval_s)
            mon = Monitor(burst_dir, engine, serve_addr=serve.addr,
                          sink_path=os.path.join(work,
                                                 "MONITOR_p99_burst.jsonl"))
            burst_s = max(6 * args.interval_s, 12.0)
            burster = threading.Thread(
                target=serve.overload_burst, args=(burst_clients, burst_s),
                daemon=True,
            )
            t0 = time.time()
            burster.start()
            mon.run(args.interval_s,
                    should_stop=lambda: not burster.is_alive())
            burster.join()
            mon.close()
            raised = sorted({a["rule"] for a in mon.alerts})
            entry = {
                "interval": idx, "name": "p99_burst", "kind": "serve",
                "rc": 0, "expected_alerts": ["p99-breach"],
                "raised_alerts": raised,
                "alerts_exact": raised == ["p99-breach"],
                "duration_s": round(time.time() - t0, 1),
                "p99_threshold_ms": p99_threshold,
                "gate": None, "ok": raised == ["p99-breach"],
            }
            ok_all &= entry["ok"]
            log(f"[{idx}] p99_burst: {'ok' if entry['ok'] else 'FAIL'} — "
                f"raised {raised or '[]'}")
            sink.emit_event("soak.interval", **{
                k: v for k, v in entry.items() if k != "kind"
            })
            intervals.append(entry)
    finally:
        serve_summary = None
        if serve is not None:
            serve_summary = {
                "background_rate_rps": args.rate_rps,
                "requests_ok": serve.tallies["ok"],
                "requests_failed": serve.tallies["failed"],
                "backpressure_backoffs": serve.tallies["backoff"],
                "hot_reloads": serve.reloads,
                "p99_threshold_ms": p99_threshold,
            }
            serve.shutdown()

    # ---- trajectory divergence: monitored control vs unmonitored rerun --
    divergence = {"checked": False}
    if not args.no_divergence:
        log("soak: divergence check — re-running control unmonitored...")
        divergence = check_divergence(
            work, shards_root, os.path.join(work, "intervals", "control")
        )
        log(f"soak: divergence checked — bit_identical="
            f"{divergence.get('bit_identical')}")
        ok_all &= bool(divergence.get("bit_identical"))
    if serve_summary is not None:
        ok_all &= serve_summary["requests_failed"] == 0

    control = next((i for i in intervals if i["name"] == "control"), None)
    verdict = {
        "schema": SOAK_SCHEMA,
        "generated_by": "tools/soak.py",
        "platform": "cpu",
        "cpu_count": os.cpu_count(),
        "interval_s": args.interval_s,
        "train_batches_per_interval": n_batches,
        "intervals": intervals,
        "alerts_exact": all(i["alerts_exact"] for i in intervals),
        "control_clean": bool(control and not control["raised_alerts"]),
        "gates_evaluated": all(
            i["gate"] is not None and i["gate"]["checked"] > 0
            for i in intervals if i["name"] not in ("control", "p99_burst")
        ),
        "straggler_note": (
            "straggler-skew is not injectable on a 1-core lockstep DP run "
            "(collective wait equalizes every rank's measured step); the "
            "rule is exercised from synthetic multi-rank sinks in "
            "tests/test_monitor.py"
        ),
        "serve": serve_summary,
        "divergence": divergence,
        "work_dir": work,
        "ok": bool(ok_all),
    }
    sink.emit_event(
        "soak.verdict", ok=verdict["ok"],
        intervals=[i["name"] for i in intervals],
        alerts_exact=verdict["alerts_exact"],
        control_clean=verdict["control_clean"],
        gates_evaluated=verdict["gates_evaluated"],
    )
    sink.close()
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Train+serve soak referee: fault-injected train "
                    "intervals + a serving fleet under Poisson traffic, "
                    "monitored live; emits a SOAK verdict JSON.",
    )
    ap.add_argument("--out", default="SOAK_r01.json")
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--per-class", type=int, default=64,
                    help="train images per class (4 classes; batch 4 — "
                         "64 ⇒ 64 batches/interval)")
    ap.add_argument("--interval-s", type=float, default=2.5,
                    help="monitor evaluation interval (default 2.5s)")
    ap.add_argument("--rate-rps", type=float, default=2.0,
                    help="background Poisson request rate (default 2)")
    ap.add_argument("--gate-tol-pct", type=float, default=35.0,
                    help="per-interval regression-gate tolerance")
    ap.add_argument("--intervals", default=None,
                    help="comma-separated interval names to run "
                         "(control is always required first)")
    ap.add_argument("--smoke", action="store_true",
                    help="short referee: control + nonfinite, no serve "
                         "plane (tests/test_monitor.py slow tier)")
    ap.add_argument("--no-serve", action="store_true",
                    help="skip the serve fleet / burst interval")
    ap.add_argument("--no-divergence", action="store_true",
                    help="skip the unmonitored-rerun bit-identity check")
    ap.add_argument("--dry", action="store_true",
                    help="validate the interval matrix, the soak rule "
                         "set, and config/monitor_rules.yaml; run nothing")
    args = ap.parse_args(argv)
    if args.smoke:
        args.no_serve = True
        args.per_class = min(args.per_class, 24)

    if args.dry:
        matrix = interval_matrix(args.per_class * 4 // 4)
        rules = build_rules(baseline=100.0, p99_ms=250.0)
        shipped = load_rules(os.path.join(_ROOT, "config",
                                          "monitor_rules.yaml"))
        for spec in matrix:  # overrides must be well-formed pairs
            if len(spec["overrides"]) % 2 != 0:
                raise SystemExit(
                    f"soak --dry: interval {spec['name']} has odd-length "
                    "overrides"
                )
            unknown = [a for a in spec["expected"]
                       if a not in {r.kind for r in rules}]
            if unknown:
                raise SystemExit(
                    f"soak --dry: interval {spec['name']} expects alerts "
                    f"no rule can raise: {unknown}"
                )
        print(f"soak --dry: {len(matrix)} intervals "
              f"({', '.join(s['name'] for s in matrix)} + p99_burst), "
              f"{len(rules)} soak rules, "
              f"{len(shipped)} shipped rules OK")
        return 0

    verdict = run_soak(args)
    with open(args.out, "w") as f:
        json.dump(verdict, f, indent=1)
    print(f"soak verdict -> {args.out}: ok={verdict['ok']} "
          f"(alerts_exact={verdict['alerts_exact']}, "
          f"control_clean={verdict['control_clean']}, "
          f"gates_evaluated={verdict['gates_evaluated']}, "
          f"divergence={verdict['divergence']})")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
