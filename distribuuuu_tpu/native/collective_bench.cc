// Native collective micro-benchmark against the TPU runtime's PJRT C API —
// the nccl-tests analogue for ICI/DCN (the reference's NCCL role is described
// in SURVEY.md §2.2; this tool measures what those collectives cost here).
//
// Talks to the accelerator runtime with no Python in the path: dlopens a
// PJRT plugin (libtpu.so by default), compiles a StableHLO all-reduce across
// every addressable device, then times chained executions per buffer size and
// reports latency + algorithm bandwidth, nccl-tests style.
//
//   g++ -O2 -std=c++17 collective_bench.cc -o collective_bench -ldl
//   ./collective_bench --plugin /path/to/libtpu.so --max-mb 64 --iters 50
//
// (Build via CMakeLists.txt in this directory. On machines without a TPU the
// tool reports the plugin error and exits 2 — exercised by tests as the
// graceful-failure path.)

#include <dlfcn.h>
#include <getopt.h>

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

const PJRT_Api* g_api = nullptr;

// Abort with the PJRT error message (frees the error).
void CheckPjrt(PJRT_Error* err, const char* what) {
  if (err == nullptr) return;
  PJRT_Error_Message_Args msg{};
  msg.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  msg.error = err;
  g_api->PJRT_Error_Message(&msg);
  std::fprintf(stderr, "PJRT error in %s: %.*s\n", what,
               static_cast<int>(msg.message_size), msg.message);
  PJRT_Error_Destroy_Args d{};
  d.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  d.error = err;
  g_api->PJRT_Error_Destroy(&d);
  std::exit(1);
}

void AwaitEvent(PJRT_Event* ev, const char* what) {
  PJRT_Event_Await_Args aw{};
  aw.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aw.event = ev;
  CheckPjrt(g_api->PJRT_Event_Await(&aw), what);
  PJRT_Event_Destroy_Args ed{};
  ed.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  ed.event = ev;
  g_api->PJRT_Event_Destroy(&ed);
}

void DestroyBuffer(PJRT_Buffer* b) {
  PJRT_Buffer_Destroy_Args d{};
  d.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
  d.buffer = b;
  CheckPjrt(g_api->PJRT_Buffer_Destroy(&d), "Buffer_Destroy");
}

// ---------------------------------------------------------------------------
// Minimal protobuf wire-format encoding of xla's CompileOptionsProto:
//   CompileOptionsProto.executable_build_options = 3 (message)
//   ExecutableBuildOptionsProto.device_ordinal   = 1 (int64, -1)
//   ExecutableBuildOptionsProto.num_replicas     = 4 (int64)
//   ExecutableBuildOptionsProto.num_partitions   = 5 (int64)
// Field numbers from xla/pjrt/proto/compile_options.pb.h; the wire format is
// stable by protobuf's compatibility rules, so hand-encoding avoids linking
// a protobuf runtime into this tool.
// ---------------------------------------------------------------------------

void PutVarint(std::string* out, uint64_t v) {
  while (v >= 0x80) {
    out->push_back(static_cast<char>((v & 0x7f) | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

std::string EncodeCompileOptions(int64_t num_replicas) {
  std::string build;  // ExecutableBuildOptionsProto
  build.push_back(0x08);  // field 1, varint (device_ordinal)
  PutVarint(&build, static_cast<uint64_t>(int64_t{-1}));
  build.push_back(0x20);  // field 4, varint (num_replicas)
  PutVarint(&build, static_cast<uint64_t>(num_replicas));
  build.push_back(0x28);  // field 5, varint (num_partitions)
  PutVarint(&build, 1);

  std::string opts;  // CompileOptionsProto
  opts.push_back(0x1a);  // field 3, length-delimited
  PutVarint(&opts, build.size());
  opts += build;
  return opts;
}

// StableHLO all-reduce (sum ÷ n, i.e. the framework's pmean) over one
// replica group [0..n), cross-replica semantics (no channel_handle) —
// exactly what XLA emits for a mean-allreduce over a mesh axis. The ÷n keeps
// a ones input at 1.0 through any number of chained iterations, making the
// end-of-run correctness check exact.
std::string AllReduceModule(int64_t n, int64_t elems) {
  std::string groups = "[[";
  for (int64_t i = 0; i < n; ++i) {
    groups += std::to_string(i);
    if (i + 1 < n) groups += ", ";
  }
  groups += "]]";
  const std::string T = "tensor<" + std::to_string(elems) + "xf32>";
  std::string m;
  m += "module @allreduce attributes {mhlo.num_replicas = " +
       std::to_string(n) + " : i32, mhlo.num_partitions = 1 : i32} {\n";
  m += "  func.func public @main(%arg0: " + T + ") -> " + T + " {\n";
  m += "    %0 = \"stablehlo.all_reduce\"(%arg0) ({\n";
  m += "    ^bb0(%a: tensor<f32>, %b: tensor<f32>):\n";
  m += "      %s = stablehlo.add %a, %b : tensor<f32>\n";
  m += "      stablehlo.return %s : tensor<f32>\n";
  m += "    }) {replica_groups = dense<" + groups + "> : tensor<1x" +
       std::to_string(n) + "xi64>} : (" + T + ") -> " + T + "\n";
  m += "    %c = stablehlo.constant dense<" + std::to_string(n) +
       ".0> : " + T + "\n";
  m += "    %1 = stablehlo.divide %0, %c : " + T + "\n";
  m += "    func.return %1 : " + T + "\n";
  m += "  }\n}\n";
  return m;
}

struct Options {
  const char* plugin = "libtpu.so";
  double min_kb = 4.0;
  double max_mb = 64.0;
  int iters = 50;
};

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  static option longopts[] = {
      {"plugin", required_argument, nullptr, 'p'},
      {"min-kb", required_argument, nullptr, 'k'},
      {"max-mb", required_argument, nullptr, 'm'},
      {"iters", required_argument, nullptr, 'i'},
      {nullptr, 0, nullptr, 0},
  };
  int c;
  while ((c = getopt_long(argc, argv, "p:k:m:i:", longopts, nullptr)) != -1) {
    switch (c) {
      case 'p': opt.plugin = optarg; break;
      case 'k': opt.min_kb = std::atof(optarg); break;
      case 'm': opt.max_mb = std::atof(optarg); break;
      case 'i': opt.iters = std::atoi(optarg); break;
      default:
        std::fprintf(stderr,
                     "usage: %s [--plugin lib] [--min-kb N] [--max-mb N] "
                     "[--iters N]\n",
                     argv[0]);
        return 64;  // EX_USAGE — distinct from the no-TPU exit code 2
    }
  }

  void* lib = dlopen(opt.plugin, RTLD_NOW | RTLD_GLOBAL);
  if (lib == nullptr) {
    std::fprintf(stderr, "cannot dlopen PJRT plugin '%s': %s\n", opt.plugin,
                 dlerror());
    return 2;
  }
  using GetPjrtApiFn = const PJRT_Api* (*)();
  auto get_api =
      reinterpret_cast<GetPjrtApiFn>(dlsym(lib, "GetPjrtApi"));
  if (get_api == nullptr) {
    std::fprintf(stderr, "plugin '%s' exports no GetPjrtApi\n", opt.plugin);
    return 2;
  }
  g_api = get_api();

  PJRT_Plugin_Initialize_Args init{};
  init.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  CheckPjrt(g_api->PJRT_Plugin_Initialize(&init), "Plugin_Initialize");

  PJRT_Client_Create_Args cc{};
  cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  if (PJRT_Error* err = g_api->PJRT_Client_Create(&cc)) {
    PJRT_Error_Message_Args msg{};
    msg.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
    msg.error = err;
    g_api->PJRT_Error_Message(&msg);
    std::fprintf(stderr,
                 "no usable accelerator behind plugin '%s': %.*s\n",
                 opt.plugin, static_cast<int>(msg.message_size), msg.message);
    return 2;  // graceful: machine has no TPU attached
  }
  PJRT_Client* client = cc.client;

  PJRT_Client_AddressableDevices_Args ad{};
  ad.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  ad.client = client;
  CheckPjrt(g_api->PJRT_Client_AddressableDevices(&ad), "AddressableDevices");
  const int64_t n = static_cast<int64_t>(ad.num_addressable_devices);
  std::printf("# PJRT plugin %s: %lld addressable device(s)\n", opt.plugin,
              static_cast<long long>(n));
  std::printf("# %-12s%14s%14s%14s\n", "op", "size", "time/iter", "algbw GB/s");

  std::string copts = EncodeCompileOptions(n);

  for (double kb = opt.min_kb; kb * 1024 <= opt.max_mb * 1024 * 1024;
       kb *= 8) {
    const int64_t elems = std::max<int64_t>(1, static_cast<int64_t>(kb * 1024 / 4));
    std::string mlir = AllReduceModule(n, elems);

    PJRT_Program prog{};
    prog.struct_size = PJRT_Program_STRUCT_SIZE;
    prog.code = mlir.data();
    prog.code_size = mlir.size();
    prog.format = "mlir";
    prog.format_size = 4;

    PJRT_Client_Compile_Args comp{};
    comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
    comp.client = client;
    comp.program = &prog;
    comp.compile_options = copts.data();
    comp.compile_options_size = copts.size();
    CheckPjrt(g_api->PJRT_Client_Compile(&comp), "Compile");
    PJRT_LoadedExecutable* exec = comp.executable;

    // one input buffer per device, value 1.0 everywhere
    std::vector<float> host(static_cast<size_t>(elems), 1.0f);
    int64_t dims[1] = {elems};
    std::vector<PJRT_Buffer*> inputs(n);
    for (int64_t d = 0; d < n; ++d) {
      PJRT_Client_BufferFromHostBuffer_Args bh{};
      bh.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
      bh.client = client;
      bh.data = host.data();
      bh.type = PJRT_Buffer_Type_F32;
      bh.dims = dims;
      bh.num_dims = 1;
      bh.host_buffer_semantics =
          PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
      bh.device = ad.addressable_devices[d];
      CheckPjrt(g_api->PJRT_Client_BufferFromHostBuffer(&bh),
                "BufferFromHostBuffer");
      AwaitEvent(bh.done_with_host_buffer, "host transfer");
      inputs[d] = bh.buffer;
    }

    PJRT_ExecuteOptions eopts{};
    eopts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

    auto run_once = [&](std::vector<PJRT_Buffer*>& bufs, bool fence) {
      std::vector<PJRT_Buffer*> out(n, nullptr);
      std::vector<PJRT_Buffer**> out_lists(n);
      std::vector<PJRT_Buffer* const*> arg_lists(n);
      for (int64_t d = 0; d < n; ++d) {
        out_lists[d] = &out[d];
        arg_lists[d] = &bufs[d];
      }
      std::vector<PJRT_Event*> done(fence ? n : 0, nullptr);
      PJRT_LoadedExecutable_Execute_Args ex{};
      ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
      ex.executable = exec;
      ex.options = &eopts;
      ex.argument_lists = arg_lists.data();
      ex.num_devices = static_cast<size_t>(n);
      ex.num_args = 1;
      ex.output_lists = out_lists.data();
      ex.device_complete_events = fence ? done.data() : nullptr;
      CheckPjrt(g_api->PJRT_LoadedExecutable_Execute(&ex), "Execute");
      for (int64_t d = 0; d < n; ++d) {
        DestroyBuffer(bufs[d]);
        bufs[d] = out[d];
      }
      for (PJRT_Event* ev : done) AwaitEvent(ev, "execute fence");
    };

    run_once(inputs, /*fence=*/true);  // warmup + compile-cache touch
    auto t0 = std::chrono::steady_clock::now();
    for (int i = 0; i < opt.iters; ++i) {
      run_once(inputs, /*fence=*/i + 1 == opt.iters);
    }
    double dt = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0)
                    .count() /
                opt.iters;

    // correctness: the kernel is mean(allreduce of ones) == 1.0 at every
    // element after any number of chained iterations
    PJRT_Buffer_ToHostBuffer_Args th{};
    th.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    th.src = inputs[0];
    std::vector<float> back(static_cast<size_t>(elems));
    th.dst = back.data();
    th.dst_size = back.size() * sizeof(float);
    CheckPjrt(g_api->PJRT_Buffer_ToHostBuffer(&th), "ToHostBuffer");
    AwaitEvent(th.event, "readback");
    for (int64_t i = 0; i < elems; ++i) {
      if (back[static_cast<size_t>(i)] < 0.999f ||
          back[static_cast<size_t>(i)] > 1.001f) {
        std::fprintf(stderr,
                     "CORRECTNESS FAILURE: element %lld = %f (want 1.0) — "
                     "all-reduce result is wrong\n",
                     static_cast<long long>(i),
                     back[static_cast<size_t>(i)]);
        return 1;
      }
    }

    double bytes = static_cast<double>(elems) * 4;
    char label[32];
    std::snprintf(label, sizeof(label), "%.3fMB", bytes / (1 << 20));
    std::printf("  %-12s%14s%12.1fus%14.2f\n", "all_reduce", label,
                dt * 1e6, bytes / dt / 1e9);

    for (int64_t d = 0; d < n; ++d) DestroyBuffer(inputs[d]);
    PJRT_LoadedExecutable_Destroy_Args xd{};
    xd.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
    xd.executable = exec;
    CheckPjrt(g_api->PJRT_LoadedExecutable_Destroy(&xd), "Executable_Destroy");
  }

  PJRT_Client_Destroy_Args cd{};
  cd.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
  cd.client = client;
  CheckPjrt(g_api->PJRT_Client_Destroy(&cd), "Client_Destroy");
  std::printf("# done\n");
  return 0;
}
