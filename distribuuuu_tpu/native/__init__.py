"""Native (C++) input-pipeline kernel: build + ctypes bindings.

The reference reaches native decode through torchvision/PIL and parallelizes
it with the DataLoader worker pool (ref: /root/reference/distribuuuu/
utils.py:127,147). Here the equivalent is first-party C++ (decode.cc):
libjpeg/libpng decode, a PIL-compatible resampler, normalization, and an
internal std::thread pool — one GIL-free call per batch.

The library is built lazily with g++ on first use and cached next to the
source; everything degrades gracefully to the pure-PIL path when a toolchain
or libjpeg headers are missing (``available()`` → False).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "decode.cc")
_LIB = os.path.join(os.path.dirname(__file__), "_libdtpu_decode.so")
_ABI_VERSION = 4

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


class Geom(ctypes.Structure):
    """Mirror of decode.cc's Geom: one resample geometry per image."""

    _fields_ = [
        ("box_x", ctypes.c_double),
        ("box_y", ctypes.c_double),
        ("scale_x", ctypes.c_double),
        ("scale_y", ctypes.c_double),
        ("out_x0", ctypes.c_int32),
        ("out_y0", ctypes.c_int32),
        ("flip", ctypes.c_int32),
        ("_pad", ctypes.c_int32),
    ]


def _build() -> str | None:
    """Compile decode.cc → shared lib. Returns error string or None."""
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return None
    # Per-pid temp target: concurrent first-use builds (multi-process JAX on
    # one host, shared package dir) must not interleave writes; os.replace of
    # a fully-written file is atomic either way.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", tmp, "-ljpeg", "-lpng",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as exc:  # no g++ etc.
        return f"native build failed to launch: {exc}"
    if proc.returncode != 0:
        return f"native build failed:\n{proc.stderr[-2000:]}"
    os.replace(tmp, _LIB)
    return None


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as exc:
            _build_error = f"native lib load failed: {exc}"
            return None
        if lib.dtpu_abi_version() != _ABI_VERSION:
            _build_error = "native ABI mismatch (stale _libdtpu_decode.so?)"
            return None
        lib.dtpu_file_dims.restype = ctypes.c_int
        lib.dtpu_file_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dtpu_load_batch.restype = None
        lib.dtpu_load_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dtpu_load_batch_u8.restype = None
        lib.dtpu_load_batch_u8.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
        ]
        # memory-buffer entry points (shard records) — ABI 4
        lib.dtpu_mem_dims.restype = ctypes.c_int
        lib.dtpu_mem_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dtpu_load_batch_mem.restype = None
        lib.dtpu_load_batch_mem.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dtpu_load_batch_u8_mem.restype = None
        lib.dtpu_load_batch_u8_mem.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native kernel built/loaded (builds on first call)."""
    return _load() is not None


def build_error() -> str | None:
    _load()
    return _build_error


def file_dims(path: str) -> tuple[int, int] | None:
    """(width, height) from the image header, or None if unsupported."""
    lib = _load()
    if lib is None:
        return None
    w, h = ctypes.c_int32(), ctypes.c_int32()
    if lib.dtpu_file_dims(path.encode(), ctypes.byref(w), ctypes.byref(h)):
        return None
    return w.value, h.value


def load_batch(
    paths: list[str],
    geoms: np.ndarray,  # structured array matching Geom, len n
    out_size: tuple[int, int],  # (h, w)
    mean: np.ndarray,
    std: np.ndarray,
    n_threads: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Decode+transform a batch. Returns (images [n,h,w,3] f32, statuses [n]).

    Nonzero status marks an image the native path could not handle (exotic
    format/CMYK/corrupt); the caller re-does those via PIL.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decode unavailable: {_build_error}")
    n = len(paths)
    out_h, out_w = out_size
    images = np.empty((n, out_h, out_w, 3), np.float32)
    statuses = np.empty((n,), np.int32)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    mean32 = np.ascontiguousarray(mean, np.float32)
    std32 = np.ascontiguousarray(std, np.float32)
    geoms = np.ascontiguousarray(geoms)
    assert geoms.nbytes == n * ctypes.sizeof(Geom), "geom layout mismatch"
    lib.dtpu_load_batch(
        c_paths,
        geoms.ctypes.data_as(ctypes.c_void_p),
        n,
        out_w,
        out_h,
        mean32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_threads,
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return images, statuses


def load_batch_u8(
    paths: list[str],
    geoms: np.ndarray,  # structured array matching Geom, len n
    out_size: tuple[int, int],  # (h, w)
    n_threads: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Raw-u8 batch (``DATA.DEVICE_NORMALIZE``): decode+resample+flip, no
    normalize. Returns (images [n,h,w,3] uint8, statuses [n])."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decode unavailable: {_build_error}")
    n = len(paths)
    out_h, out_w = out_size
    images = np.empty((n, out_h, out_w, 3), np.uint8)
    statuses = np.empty((n,), np.int32)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    geoms = np.ascontiguousarray(geoms)
    assert geoms.nbytes == n * ctypes.sizeof(Geom), "geom layout mismatch"
    lib.dtpu_load_batch_u8(
        c_paths,
        geoms.ctypes.data_as(ctypes.c_void_p),
        n,
        out_w,
        out_h,
        n_threads,
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return images, statuses


def has_mem_api() -> bool:
    """True when the loaded kernel speaks the memory-buffer entry points
    (ABI ≥ 4 — the version gate in ``_load`` already enforces it, so this
    is equivalent to ``available()``; kept separate for call-site intent)."""
    return available()


def mem_dims(data: bytes) -> tuple[int, int] | None:
    """(width, height) from an in-memory encoded image, or None."""
    lib = _load()
    if lib is None or not data:
        return None
    w, h = ctypes.c_int32(), ctypes.c_int32()
    if lib.dtpu_mem_dims(data, len(data), ctypes.byref(w), ctypes.byref(h)):
        return None
    return w.value, h.value


def _mem_args(bufs: list[bytes]):
    n = len(bufs)
    c_bufs = (ctypes.c_char_p * n)(*bufs)
    c_lens = (ctypes.c_int64 * n)(*[len(b) for b in bufs])
    return c_bufs, c_lens


def load_batch_mem(
    bufs: list[bytes],
    geoms: np.ndarray,  # structured array matching Geom, len n
    out_size: tuple[int, int],  # (h, w)
    mean: np.ndarray,
    std: np.ndarray,
    n_threads: int,
) -> tuple[np.ndarray, np.ndarray]:
    """``load_batch`` over in-memory encoded buffers (shard records): one
    GIL-free call, internal thread pool. An empty buffer is the caller's
    fallback sentinel — it fails instantly with nonzero status."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decode unavailable: {_build_error}")
    n = len(bufs)
    out_h, out_w = out_size
    images = np.empty((n, out_h, out_w, 3), np.float32)
    statuses = np.empty((n,), np.int32)
    c_bufs, c_lens = _mem_args(bufs)
    mean32 = np.ascontiguousarray(mean, np.float32)
    std32 = np.ascontiguousarray(std, np.float32)
    geoms = np.ascontiguousarray(geoms)
    assert geoms.nbytes == n * ctypes.sizeof(Geom), "geom layout mismatch"
    lib.dtpu_load_batch_mem(
        c_bufs,
        c_lens,
        geoms.ctypes.data_as(ctypes.c_void_p),
        n,
        out_w,
        out_h,
        mean32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_threads,
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return images, statuses


def load_batch_u8_mem(
    bufs: list[bytes],
    geoms: np.ndarray,  # structured array matching Geom, len n
    out_size: tuple[int, int],  # (h, w)
    n_threads: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Raw-u8 variant of ``load_batch_mem`` (``DATA.DEVICE_NORMALIZE``)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native decode unavailable: {_build_error}")
    n = len(bufs)
    out_h, out_w = out_size
    images = np.empty((n, out_h, out_w, 3), np.uint8)
    statuses = np.empty((n,), np.int32)
    c_bufs, c_lens = _mem_args(bufs)
    geoms = np.ascontiguousarray(geoms)
    assert geoms.nbytes == n * ctypes.sizeof(Geom), "geom layout mismatch"
    lib.dtpu_load_batch_u8_mem(
        c_bufs,
        c_lens,
        geoms.ctypes.data_as(ctypes.c_void_p),
        n,
        out_w,
        out_h,
        n_threads,
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return images, statuses


GEOM_DTYPE = np.dtype(
    [
        ("box_x", np.float64),
        ("box_y", np.float64),
        ("scale_x", np.float64),
        ("scale_y", np.float64),
        ("out_x0", np.int32),
        ("out_y0", np.int32),
        ("flip", np.int32),
        ("_pad", np.int32),
    ]
)
