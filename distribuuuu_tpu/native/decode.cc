// Native input-pipeline kernel: JPEG/PNG decode + resample + normalize.
//
// Role: the TPU-native equivalent of the reference's DataLoader worker pool +
// libjpeg/PIL decode path (ref: /root/reference/distribuuuu/utils.py:127,147 —
// ImageFolder + num_workers). Host-side JPEG decode feeding a TPU slice is the
// classic input bottleneck (SURVEY.md §7 "hard parts" #2); this moves the
// whole decode→augment→normalize chain into one GIL-free C++ call per batch,
// fanned out over an internal std::thread pool.
//
// Augmentation *geometry* (RandomResizedCrop box, flip coin) is sampled in
// Python with the same numpy RNG stream as the pure-PIL path, so switching
// backends does not change the augmentation sequence; C++ only executes the
// resample. The resampler reimplements PIL's convolution algorithm (triangle
// filter, window renormalization at edges, uint8 intermediate between the
// horizontal and vertical passes) so outputs match the PIL path to ±2/255.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this environment).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------------------
// Image buffer
// ---------------------------------------------------------------------------

struct ImageU8 {
  int w = 0, h = 0;           // pixels
  std::vector<uint8_t> rgb;   // h*w*3, row-major
};

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg, error-trampoline via setjmp)
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

bool decode_jpeg(const uint8_t* data, size_t len, ImageU8* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  // Grayscale→RGB and YCbCr→RGB both handled by libjpeg itself, matching
  // PIL's convert("RGB") for those spaces. CMYK/YCCK are left to the Python
  // fallback (rare, and PIL applies an inverted-Adobe heuristic).
  if (cinfo.jpeg_color_space == JCS_CMYK ||
      cinfo.jpeg_color_space == JCS_YCCK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out->w = static_cast<int>(cinfo.output_width);
  out->h = static_cast<int>(cinfo.output_height);
  out->rgb.resize(static_cast<size_t>(out->w) * out->h * 3);
  const size_t stride = static_cast<size_t>(out->w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->rgb.data() + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool jpeg_dims(const uint8_t* data, size_t len, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  *w = static_cast<int>(cinfo.image_width);
  *h = static_cast<int>(cinfo.image_height);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ---------------------------------------------------------------------------
// PNG decode (libpng simplified API; palette/gray/alpha → RGB)
// ---------------------------------------------------------------------------

bool decode_png(const uint8_t* data, size_t len, ImageU8* out) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, data, len)) return false;
  // Alpha (incl. palette tRNS): libpng would COMPOSITE it away, while the
  // PIL path's convert("RGB") drops the band — different pixels. Punt those
  // to the PIL fallback so both backends agree (same treatment as CMYK JPEG).
  if (image.format & PNG_FORMAT_FLAG_ALPHA) {
    png_image_free(&image);
    return false;
  }
  image.format = PNG_FORMAT_RGB;
  out->w = static_cast<int>(image.width);
  out->h = static_cast<int>(image.height);
  out->rgb.resize(PNG_IMAGE_SIZE(image));
  if (!png_image_finish_read(&image, nullptr, out->rgb.data(), 0, nullptr)) {
    png_image_free(&image);
    return false;
  }
  return true;
}

bool png_dims(const uint8_t* data, size_t len, int* w, int* h) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, data, len)) return false;
  *w = static_cast<int>(image.width);
  *h = static_cast<int>(image.height);
  png_image_free(&image);
  return true;
}

bool is_png(const uint8_t* d, size_t n) {
  static const uint8_t sig[8] = {0x89, 'P', 'N', 'G', '\r', '\n', 0x1a, '\n'};
  return n >= 8 && std::memcmp(d, sig, 8) == 0;
}

bool is_jpeg(const uint8_t* d, size_t n) {
  return n >= 3 && d[0] == 0xFF && d[1] == 0xD8 && d[2] == 0xFF;
}

bool read_file(const char* path, std::vector<uint8_t>* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  if (n <= 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(n));
  size_t got = std::fread(out->data(), 1, static_cast<size_t>(n), f);
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

// Bounded prefix read for header probes — dims live in the first few KB, so
// the dims pass must not read whole files (the batch decode reads them once).
bool read_prefix(const char* path, size_t cap, std::vector<uint8_t>* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  out->resize(cap);
  size_t got = std::fread(out->data(), 1, cap, f);
  std::fclose(f);
  if (got == 0) return false;
  out->resize(got);
  return true;
}

// ---------------------------------------------------------------------------
// PIL-compatible separable resampler (triangle/bilinear filter)
// ---------------------------------------------------------------------------
//
// For each output index xx along an axis, PIL computes
//   center = box0 + (xx + 0.5) * scale
//   support = filterscale,  filterscale = max(scale, 1)
//   window  = [floor(center - support + 0.5), floor(center + support + 0.5))
//             clipped to [0, in_size)
//   weight(x) = triangle((x + 0.5 - center) / filterscale), renormalized over
//               the clipped window (this is the edge behavior — renormalize,
//               not zero-pad).
// The uint8 pipeline rounds to uint8 between the horizontal and vertical
// passes; we do the same so outputs track PIL within quantization error.

struct AxisCoeffs {
  std::vector<int> xmin;       // per-out-pixel window start
  std::vector<int> xlen;       // per-out-pixel window length
  std::vector<double> weights; // flattened, ksize per out pixel
  int ksize = 0;
};

AxisCoeffs precompute_coeffs(int in_size, double box0, double scale,
                             int out0, int out_n) {
  AxisCoeffs c;
  const double filterscale = std::max(scale, 1.0);
  const double support = filterscale;  // bilinear filter support = 1.0
  c.ksize = static_cast<int>(std::ceil(support)) * 2 + 1;
  c.xmin.resize(out_n);
  c.xlen.resize(out_n);
  c.weights.assign(static_cast<size_t>(out_n) * c.ksize, 0.0);
  for (int xx = 0; xx < out_n; ++xx) {
    const double center = box0 + (out0 + xx + 0.5) * scale;
    int xmin = static_cast<int>(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = static_cast<int>(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    double* k = &c.weights[static_cast<size_t>(xx) * c.ksize];
    double total = 0.0;
    for (int x = xmin; x < xmax; ++x) {
      double arg = std::abs((x + 0.5 - center) / filterscale);
      double w = arg < 1.0 ? 1.0 - arg : 0.0;  // triangle filter
      k[x - xmin] = w;
      total += w;
    }
    if (total > 0.0)
      for (int x = 0; x < xmax - xmin; ++x) k[x] /= total;
    c.xmin[xx] = xmin;
    c.xlen[xx] = xmax - xmin;
  }
  return c;
}

inline uint8_t clip_round_u8(double v) {
  v = std::round(v);
  if (v < 0.0) return 0;
  if (v > 255.0) return 255;
  return static_cast<uint8_t>(v);
}

// Resample src into a (out_h, out_w) RGB uint8 image. Output pixel (x, y)
// corresponds to position (box_x + (out_x0+x+0.5)*scale_x,
//                          box_y + (out_y0+y+0.5)*scale_y) in src — this one
// geometry expresses both train (crop-box resize: box≠0, out0=0) and val
// (full resize then center-crop: box=0, out0=crop offset) paths.
void resample(const ImageU8& src, double box_x, double box_y, double scale_x,
              double scale_y, int out_x0, int out_y0, int out_w, int out_h,
              std::vector<uint8_t>* out) {
  AxisCoeffs cx = precompute_coeffs(src.w, box_x, scale_x, out_x0, out_w);
  AxisCoeffs cy = precompute_coeffs(src.h, box_y, scale_y, out_y0, out_h);

  // Horizontal pass over only the source rows the vertical pass will touch.
  int row_lo = src.h, row_hi = 0;
  for (int yy = 0; yy < out_h; ++yy) {
    row_lo = std::min(row_lo, cy.xmin[yy]);
    row_hi = std::max(row_hi, cy.xmin[yy] + cy.xlen[yy]);
  }
  if (row_lo >= row_hi) {
    out->assign(static_cast<size_t>(out_h) * out_w * 3, 0);
    return;
  }
  const int n_rows = row_hi - row_lo;
  std::vector<uint8_t> mid(static_cast<size_t>(n_rows) * out_w * 3);
  for (int y = 0; y < n_rows; ++y) {
    const uint8_t* srow =
        src.rgb.data() + static_cast<size_t>(row_lo + y) * src.w * 3;
    uint8_t* drow = mid.data() + static_cast<size_t>(y) * out_w * 3;
    for (int xx = 0; xx < out_w; ++xx) {
      const double* k = &cx.weights[static_cast<size_t>(xx) * cx.ksize];
      const int xmin = cx.xmin[xx], xlen = cx.xlen[xx];
      double r = 0, g = 0, b = 0;
      for (int x = 0; x < xlen; ++x) {
        const uint8_t* p = srow + static_cast<size_t>(xmin + x) * 3;
        r += p[0] * k[x];
        g += p[1] * k[x];
        b += p[2] * k[x];
      }
      drow[xx * 3 + 0] = clip_round_u8(r);
      drow[xx * 3 + 1] = clip_round_u8(g);
      drow[xx * 3 + 2] = clip_round_u8(b);
    }
  }

  // Vertical pass.
  out->resize(static_cast<size_t>(out_h) * out_w * 3);
  for (int yy = 0; yy < out_h; ++yy) {
    const double* k = &cy.weights[static_cast<size_t>(yy) * cy.ksize];
    const int ymin = cy.xmin[yy] - row_lo, ylen = cy.xlen[yy];
    uint8_t* drow = out->data() + static_cast<size_t>(yy) * out_w * 3;
    for (int xx = 0; xx < out_w * 3; ++xx) {
      double acc = 0;
      for (int y = 0; y < ylen; ++y)
        acc += mid[static_cast<size_t>(ymin + y) * out_w * 3 + xx] * k[y];
      drow[xx] = clip_round_u8(acc);
    }
  }
}

// ---------------------------------------------------------------------------
// Batch task plumbing
// ---------------------------------------------------------------------------

struct Geom {
  double box_x, box_y;     // crop-box origin in source pixels
  double scale_x, scale_y; // source pixels per output pixel
  int32_t out_x0, out_y0;  // crop offset within the virtual resized image
  int32_t flip;            // horizontal flip after resample
};

bool decode_any(const std::vector<uint8_t>& bytes, ImageU8* img) {
  if (is_jpeg(bytes.data(), bytes.size()))
    return decode_jpeg(bytes.data(), bytes.size(), img);
  if (is_png(bytes.data(), bytes.size()))
    return decode_png(bytes.data(), bytes.size(), img);
  return false;  // other formats → Python fallback
}

// Shared front half: path → decode → resample. Fills `res` (out_h rows
// of out_w RGB u8, pre-flip).
bool load_resampled(const char* path, const Geom& g, int out_w, int out_h,
                    std::vector<uint8_t>* res) {
  std::vector<uint8_t> bytes;
  if (!read_file(path, &bytes)) return false;
  ImageU8 img;
  if (!decode_any(bytes, &img)) return false;
  resample(img, g.box_x, g.box_y, g.scale_x, g.scale_y, g.out_x0, g.out_y0,
           out_w, out_h, res);
  return true;
}

// Memory-buffer front half (shard records hand encoded bytes directly —
// no filesystem round-trip): buffer → decode → resample.
bool load_resampled_mem(const uint8_t* data, int64_t len, const Geom& g,
                        int out_w, int out_h, std::vector<uint8_t>* res) {
  if (data == nullptr || len <= 0) return false;
  std::vector<uint8_t> bytes(data, data + len);
  ImageU8 img;
  if (!decode_any(bytes, &img)) return false;
  resample(img, g.box_x, g.box_y, g.scale_x, g.scale_y, g.out_x0, g.out_y0,
           out_w, out_h, res);
  return true;
}

// Post-resample back halves, shared by the path and memory entry points.
void finish_one(const std::vector<uint8_t>& res, const Geom& g, int out_w,
                int out_h, const float* mean, const float* stdv, float* out) {
  const float inv255 = 1.0f / 255.0f;
  float inv_std[3] = {1.0f / stdv[0], 1.0f / stdv[1], 1.0f / stdv[2]};
  for (int y = 0; y < out_h; ++y) {
    const uint8_t* srow = res.data() + static_cast<size_t>(y) * out_w * 3;
    float* drow = out + static_cast<size_t>(y) * out_w * 3;
    for (int x = 0; x < out_w; ++x) {
      const int sx = g.flip ? (out_w - 1 - x) : x;
      const uint8_t* p = srow + sx * 3;
      float* q = drow + x * 3;
      for (int c = 0; c < 3; ++c)
        q[c] = (p[c] * inv255 - mean[c]) * inv_std[c];
    }
  }
}

void finish_one_u8(const std::vector<uint8_t>& res, const Geom& g, int out_w,
                   int out_h, uint8_t* out) {
  for (int y = 0; y < out_h; ++y) {
    const uint8_t* srow = res.data() + static_cast<size_t>(y) * out_w * 3;
    uint8_t* drow = out + static_cast<size_t>(y) * out_w * 3;
    if (!g.flip) {
      std::memcpy(drow, srow, static_cast<size_t>(out_w) * 3);
      continue;
    }
    for (int x = 0; x < out_w; ++x) {
      const uint8_t* p = srow + (out_w - 1 - x) * 3;
      uint8_t* q = drow + x * 3;
      q[0] = p[0];
      q[1] = p[1];
      q[2] = p[2];
    }
  }
}

// Load path → decode → resample → (flip) → normalize into out[HWC].
bool load_one(const char* path, const Geom& g, int out_w, int out_h,
              const float* mean, const float* stdv, float* out) {
  std::vector<uint8_t> res;
  if (!load_resampled(path, g, out_w, out_h, &res)) return false;
  finish_one(res, g, out_w, out_h, mean, stdv, out);
  return true;
}

// Raw-u8 variant (DATA.DEVICE_NORMALIZE): same decode/resample/flip, no
// normalize — the trainer does (x/255 - mean)/std in-graph on device, so
// the host ships 4× fewer bytes (uint8 vs float32 over PCIe/tunnel).
bool load_one_u8(const char* path, const Geom& g, int out_w, int out_h,
                 uint8_t* out) {
  std::vector<uint8_t> res;
  if (!load_resampled(path, g, out_w, out_h, &res)) return false;
  finish_one_u8(res, g, out_w, out_h, out);
  return true;
}

// Memory-buffer variants (shard records).
bool load_one_mem(const uint8_t* data, int64_t len, const Geom& g, int out_w,
                  int out_h, const float* mean, const float* stdv,
                  float* out) {
  std::vector<uint8_t> res;
  if (!load_resampled_mem(data, len, g, out_w, out_h, &res)) return false;
  finish_one(res, g, out_w, out_h, mean, stdv, out);
  return true;
}

bool load_one_u8_mem(const uint8_t* data, int64_t len, const Geom& g,
                     int out_w, int out_h, uint8_t* out) {
  std::vector<uint8_t> res;
  if (!load_resampled_mem(data, len, g, out_w, out_h, &res)) return false;
  finish_one_u8(res, g, out_w, out_h, out);
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// ABI version — bump when struct layouts change; Python checks it.
int dtpu_abi_version() { return 4; }

// Header-only dims probe. Returns 0 on success. Reads a bounded prefix
// (enough for any realistic SOF/IHDR placement); retries with the full file
// only if the prefix parse fails (e.g. giant EXIF before SOF).
int dtpu_file_dims(const char* path, int32_t* w, int32_t* h) {
  std::vector<uint8_t> bytes;
  if (!read_prefix(path, 256 * 1024, &bytes)) return 1;
  for (int attempt = 0; attempt < 2; ++attempt) {
    int iw = 0, ih = 0;
    bool ok = false;
    if (is_jpeg(bytes.data(), bytes.size()))
      ok = jpeg_dims(bytes.data(), bytes.size(), &iw, &ih);
    else if (is_png(bytes.data(), bytes.size()))
      ok = png_dims(bytes.data(), bytes.size(), &iw, &ih);
    else
      return 2;  // unknown magic — no point re-reading
    if (ok) {
      *w = iw;
      *h = ih;
      return 0;
    }
    if (attempt == 0 && !read_file(path, &bytes)) return 1;
  }
  return 2;
}

// Decode+transform a whole batch with an internal thread pool.
//   paths:    n file paths
//   geoms:    n Geom records (see struct — layout mirrored in ctypes)
//   out:      n * out_h * out_w * 3 float32, NHWC
//   statuses: n int32, 0 = ok, nonzero = fall back to Python for that image
void dtpu_load_batch(const char** paths, const void* geoms, int32_t n,
                     int32_t out_w, int32_t out_h, const float* mean,
                     const float* stdv, int32_t n_threads, float* out,
                     int32_t* statuses) {
  const Geom* gs = static_cast<const Geom*>(geoms);
  const size_t img_elems = static_cast<size_t>(out_h) * out_w * 3;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      const int32_t i = next.fetch_add(1);
      if (i >= n) return;
      bool ok = load_one(paths[i], gs[i], out_w, out_h, mean, stdv,
                         out + img_elems * i);
      statuses[i] = ok ? 0 : 1;
    }
  };
  int nt = std::max(1, std::min<int>(n_threads, n));
  if (nt == 1) {
    worker();
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

// Raw-u8 batch (DATA.DEVICE_NORMALIZE): out is n*out_h*out_w*3 uint8 RGB,
// resampled+flipped but NOT normalized (done in-graph on device).
void dtpu_load_batch_u8(const char** paths, const void* geoms, int32_t n,
                        int32_t out_w, int32_t out_h, int32_t n_threads,
                        uint8_t* out, int32_t* statuses) {
  const Geom* gs = static_cast<const Geom*>(geoms);
  const size_t img_elems = static_cast<size_t>(out_h) * out_w * 3;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      const int32_t i = next.fetch_add(1);
      if (i >= n) return;
      bool ok = load_one_u8(paths[i], gs[i], out_w, out_h,
                            out + img_elems * i);
      statuses[i] = ok ? 0 : 1;
    }
  };
  int nt = std::max(1, std::min<int>(n_threads, n));
  if (nt == 1) {
    worker();
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

// Header-only dims probe over an in-memory buffer (shard records).
int dtpu_mem_dims(const uint8_t* data, int64_t len, int32_t* w, int32_t* h) {
  if (data == nullptr || len <= 0) return 1;
  int iw = 0, ih = 0;
  bool ok = false;
  const size_t n = static_cast<size_t>(len);
  if (is_jpeg(data, n))
    ok = jpeg_dims(data, n, &iw, &ih);
  else if (is_png(data, n))
    ok = png_dims(data, n, &iw, &ih);
  else
    return 2;  // unknown magic
  if (!ok) return 2;
  *w = iw;
  *h = ih;
  return 0;
}

// Batch decode+transform from in-memory encoded buffers (shard records):
// same contract as dtpu_load_batch, but inputs are (pointer, length) pairs
// instead of paths — no per-image filesystem round-trip.
void dtpu_load_batch_mem(const uint8_t** bufs, const int64_t* lens,
                         const void* geoms, int32_t n, int32_t out_w,
                         int32_t out_h, const float* mean, const float* stdv,
                         int32_t n_threads, float* out, int32_t* statuses) {
  const Geom* gs = static_cast<const Geom*>(geoms);
  const size_t img_elems = static_cast<size_t>(out_h) * out_w * 3;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      const int32_t i = next.fetch_add(1);
      if (i >= n) return;
      bool ok = load_one_mem(bufs[i], lens[i], gs[i], out_w, out_h, mean,
                             stdv, out + img_elems * i);
      statuses[i] = ok ? 0 : 1;
    }
  };
  int nt = std::max(1, std::min<int>(n_threads, n));
  if (nt == 1) {
    worker();
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

void dtpu_load_batch_u8_mem(const uint8_t** bufs, const int64_t* lens,
                            const void* geoms, int32_t n, int32_t out_w,
                            int32_t out_h, int32_t n_threads, uint8_t* out,
                            int32_t* statuses) {
  const Geom* gs = static_cast<const Geom*>(geoms);
  const size_t img_elems = static_cast<size_t>(out_h) * out_w * 3;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      const int32_t i = next.fetch_add(1);
      if (i >= n) return;
      bool ok = load_one_u8_mem(bufs[i], lens[i], gs[i], out_w, out_h,
                                out + img_elems * i);
      statuses[i] = ok ? 0 : 1;
    }
  };
  int nt = std::max(1, std::min<int>(n_threads, n));
  if (nt == 1) {
    worker();
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
}

}  // extern "C"
