"""Image transforms with torchvision-matching semantics, on PIL + numpy.

Train: RandomResizedCrop(IM_SIZE) + RandomHorizontalFlip + Normalize
(ref: /root/reference/distribuuuu/utils.py:127-139).
Val: Resize(shorter side = TEST.IM_SIZE) + CenterCrop(model input size =
TRAIN.IM_SIZE; 224 in the shipped configs) + Normalize (ref: utils.py:163-172).
Mean/std are the standard ImageNet constants.

Output is NHWC float32 (TPU-native layout); normalization can be delegated
to the optional C++ kernel (native/) when built.
"""

from __future__ import annotations

import math

import numpy as np
from PIL import Image

IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


def sample_rrc_box(
    width: int,
    height: int,
    rng: np.random.Generator,
    scale=(0.08, 1.0),
    ratio=(3 / 4, 4 / 3),
) -> tuple[int, int, int, int]:
    """torchvision RandomResizedCrop box sampling: 10 attempts at area/ratio
    jitter, then a center-crop fallback. Returns ``(j, i, w, h)`` — left, top,
    width, height of the crop box in source pixels.

    This is the *only* place train-augmentation randomness is drawn, shared by
    the PIL and native (C++) decode backends so both see the same stream.
    """
    area = width * height
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        w = int(round(math.sqrt(target_area * aspect)))
        h = int(round(math.sqrt(target_area / aspect)))
        if 0 < w <= width and 0 < h <= height:
            i = int(rng.integers(0, height - h + 1))
            j = int(rng.integers(0, width - w + 1))
            return j, i, w, h
    # fallback: center crop at the closest valid ratio
    in_ratio = width / height
    if in_ratio < ratio[0]:
        w, h = width, int(round(width / ratio[0]))
    elif in_ratio > ratio[1]:
        h, w = height, int(round(height * ratio[1]))
    else:
        w, h = width, height
    i, j = (height - h) // 2, (width - w) // 2
    return j, i, w, h


def random_resized_crop(
    img: Image.Image,
    size: int,
    rng: np.random.Generator,
    scale=(0.08, 1.0),
    ratio=(3 / 4, 4 / 3),
) -> Image.Image:
    j, i, w, h = sample_rrc_box(img.size[0], img.size[1], rng, scale, ratio)
    return img.resize((size, size), Image.BILINEAR, box=(j, i, j + w, i + h))


def compute_resize_dims(width: int, height: int, size: int) -> tuple[int, int]:
    """torchvision Resize(int) target dims: shorter side to ``size``, keep
    aspect. Shared by the PIL and native val pipelines — they must agree."""
    if width <= height:
        return size, int(round(size * height / width))
    return int(round(size * width / height)), size


def resize_shorter(img: Image.Image, size: int) -> Image.Image:
    """torchvision Resize(int): shorter side to ``size``, keep aspect."""
    new_w, new_h = compute_resize_dims(img.size[0], img.size[1], size)
    return img.resize((new_w, new_h), Image.BILINEAR)


def center_crop(img: Image.Image, size: int) -> Image.Image:
    width, height = img.size
    left = (width - size) // 2
    top = (height - size) // 2
    return img.crop((left, top, left + size, top + size))


def to_normalized_array(img: Image.Image) -> np.ndarray:
    """ToTensor + Normalize, NHWC float32."""
    arr = np.asarray(img, np.float32) / 255.0
    if arr.ndim == 2:  # grayscale
        arr = np.stack([arr] * 3, axis=-1)
    return (arr - IMAGENET_MEAN) / IMAGENET_STD


def to_u8_array(img: Image.Image) -> np.ndarray:
    """Raw uint8 NHWC — the ``DATA.DEVICE_NORMALIZE`` host output. Lossless
    vs ``to_normalized_array``: PIL ops keep pixels uint8 anyway, so the
    only change is WHERE (x/255 − mean)/std runs (in-graph, fp32)."""
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:  # grayscale
        arr = np.stack([arr] * 3, axis=-1)
    return arr


def normalize_in_graph(images, mean=None, std=None):
    """The device-side half of ``DATA.DEVICE_NORMALIZE``: uint8 NHWC →
    normalized float32, same formula/order as ``to_normalized_array``.
    Works on jax or numpy arrays (pure jnp ops; call inside jit)."""
    import jax.numpy as jnp

    mean = IMAGENET_MEAN if mean is None else mean
    std = IMAGENET_STD if std is None else std
    x = images.astype(jnp.float32) / 255.0
    return (x - jnp.asarray(mean)) / jnp.asarray(std)


def train_transform(
    img: Image.Image, im_size: int, rng: np.random.Generator,
    normalize: bool = True,
):
    img = random_resized_crop(img, im_size, rng)
    if rng.random() < 0.5:
        img = img.transpose(Image.FLIP_LEFT_RIGHT)
    return to_normalized_array(img) if normalize else to_u8_array(img)


def val_transform(
    img: Image.Image, resize_size: int, crop_size: int,
    normalize: bool = True,
):
    img = resize_shorter(img, resize_size)
    img = center_crop(img, crop_size)
    return to_normalized_array(img) if normalize else to_u8_array(img)


# ---------------------------------------------------------------------------
# Resample geometries for the native (C++) decode backend. Both transform
# pipelines reduce to one resample whose output pixel (x, y) samples source
# position (box + (out0 + x + 0.5) * scale):
#   train — crop-box resize: box = RRC corner, out0 = 0
#   val   — shorter-side resize then center-crop: box = 0, out0 = crop offset
# The draws in train_geom are EXACTLY those of train_transform (same rng
# stream), so PIL and native backends produce the same augmentations.
# ---------------------------------------------------------------------------


def train_geom(width: int, height: int, im_size: int, rng: np.random.Generator):
    """(box_x, box_y, scale_x, scale_y, out_x0, out_y0, flip) for train."""
    j, i, w, h = sample_rrc_box(width, height, rng)
    flip = 1 if rng.random() < 0.5 else 0
    return (
        float(j), float(i), w / im_size, h / im_size, 0, 0, flip,
    )


def val_geom(width: int, height: int, resize_size: int, crop_size: int):
    """Geometry for val: Resize(shorter=resize_size) + CenterCrop(crop_size).

    Computing only the cropped window of the virtual resized image is exact:
    each output pixel of a convolution resample depends only on its own
    source window, so resize-then-crop == crop-of-resize.
    """
    new_w, new_h = compute_resize_dims(width, height, resize_size)
    left = (new_w - crop_size) // 2
    top = (new_h - crop_size) // 2
    return (
        0.0, 0.0, width / new_w, height / new_h, left, top, 0,
    )
