"""Distributed sampling with torch-DistributedSampler semantics.

The reference shards every dataset across ranks with ``DistributedSampler``
(ref: /root/reference/distribuuuu/utils.py:141-143,174): per-epoch seeded
global shuffle, round-robin rank assignment, padding (repeating head samples)
so every rank sees the same number of items, and ``set_epoch`` to reshuffle
(ref: trainer.py:33). Reproduced here at *host process* granularity — each
host feeds all of its local chips.
"""

from __future__ import annotations

import numpy as np


class DistributedSampler:
    def __init__(
        self,
        dataset_len: int,
        num_replicas: int,
        rank: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_len % num_replicas != 0:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = -(-dataset_len // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle for a new epoch (≙ sampler.set_epoch, trainer.py:33)."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        if not self.drop_last and len(order) < self.total_size:
            # pad by wrapping (torch repeats the head of the permutation)
            pad = self.total_size - len(order)
            order = np.concatenate([order, order[:pad]])
        else:
            order = order[: self.total_size]
        # interleaved rank assignment: rank r takes order[r::num_replicas]
        return order[self.rank :: self.num_replicas]

    def __len__(self):
        return self.num_samples
