"""Fake-data backend (ref: /root/reference/distribuuuu/utils.py:109-118).

Random images with label 0, behind ``cfg.MODEL.DUMMY_INPUT`` — the mechanism
that lets the full training path run with no dataset on disk. Samples are
generated on the fly from a per-epoch seed so the pipeline shape (including
per-epoch reshuffling effects) matches the real one.
"""

from __future__ import annotations

import numpy as np


class DummyDataset:
    """length random NHWC images of ``size``×``size``, label 0.

    ``raw_u8`` mirrors ``DATA.DEVICE_NORMALIZE``: uint8 samples so the
    dummy pipeline ships the same dtype the real one would."""

    def __init__(self, length: int = 6400, size: int = 224,
                 raw_u8: bool = False):
        self.length = length
        self.size = size
        self.raw_u8 = raw_u8

    def __len__(self):
        return self.length

    def __getitem__(self, idx: int):
        rng = np.random.default_rng(idx)
        if self.raw_u8:
            return rng.integers(0, 256, (self.size, self.size, 3),
                                dtype=np.uint8), 0
        img = rng.standard_normal((self.size, self.size, 3), dtype=np.float32)
        return img, 0
