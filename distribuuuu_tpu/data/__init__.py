"""Data layer: input pipelines feeding the device mesh.

Reference surface (ref: /root/reference/distribuuuu/utils.py:109-184):
``construct_train_loader`` / ``construct_val_loader`` building
ImageFolder-or-dummy pipelines with DistributedSampler sharding. Here each
*host process* loads only its shard (images/sec scale with hosts) and the
trainer assembles global sharded arrays on the data mesh axis.

``DATA.FORMAT = shards`` swaps the storage layer for indexed record
shards (data/shards/): sequential IO from a few large files, a
(seed, epoch)-only topology-independent sample order, and an exact
mid-epoch resume cursor embedded in preemption checkpoints.
"""

from distribuuuu_tpu.data.dummy import DummyDataset  # noqa: F401
from distribuuuu_tpu.data.loader import (  # noqa: F401
    Loader,
    construct_train_loader,
    construct_val_loader,
    device_prefetch,
)
