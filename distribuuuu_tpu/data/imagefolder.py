"""ImageFolder dataset: ``root/split/class_name/*.jpg`` directory layout.

Semantics mirror torchvision.datasets.ImageFolder as the reference uses it
(ref: /root/reference/distribuuuu/utils.py:127,166): classes are the sorted
subdirectory names, labels their indices; every file with an image extension
counts. Decode is PIL; transforms are data/transforms.py.
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

from distribuuuu_tpu.data.transforms import train_transform, val_transform

IMG_EXTENSIONS = (
    ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp",
)


def scan_image_folder(root: str):
    """Return (samples, classes): samples = [(path, class_idx)], classes sorted."""
    if not os.path.isdir(root):
        raise FileNotFoundError(
            f"Dataset directory not found: {root} "
            f"(expected ImageFolder layout root/class_name/*.jpg; "
            f"set MODEL.DUMMY_INPUT True to train without data)"
        )
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    if not classes:
        raise FileNotFoundError(f"No class subdirectories under {root}")
    samples = []
    for idx, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for dirpath, _, filenames in sorted(os.walk(cdir)):
            for fname in sorted(filenames):
                if fname.lower().endswith(IMG_EXTENSIONS):
                    samples.append((os.path.join(dirpath, fname), idx))
    if not samples:
        raise FileNotFoundError(f"No images found under {root}")
    return samples, classes


class ImageFolderDataset:
    def __init__(
        self,
        root: str,
        split: str,
        im_size: int,
        train: bool,
        base_seed: int = 0,
        crop_size: int | None = None,
        backend: str = "auto",
        raw_u8: bool = False,
    ):
        self.dir = os.path.join(root, split)
        self.samples, self.classes = scan_image_folder(self.dir)
        self.im_size = im_size
        # val: shorter-side resize to im_size, then center-crop to the model
        # input size (ref: utils.py:169-170 — Resize(256) + CenterCrop(224))
        self.crop_size = im_size if crop_size is None else crop_size
        self.train = train
        self.base_seed = base_seed
        self._epoch_seed = 0
        if backend not in ("auto", "native", "pil"):
            raise ValueError(f"DATA.BACKEND must be auto|native|pil, got {backend}")
        self.backend = backend
        # DATA.DEVICE_NORMALIZE: emit resampled uint8 RGB; normalization
        # runs in-graph on device (transforms.normalize_in_graph) — 4×
        # fewer host→device bytes, numerics unchanged (pixels are uint8
        # after PIL/native resampling either way)
        self.raw_u8 = raw_u8

    def _use_native(self) -> bool:
        if self.backend == "pil":
            return False
        from distribuuuu_tpu import native

        if native.available():
            return True
        if self.backend == "native":
            raise RuntimeError(
                f"DATA.BACKEND=native but the C++ kernel is unavailable: "
                f"{native.build_error()}"
            )
        return False

    def _rng(self, idx: int) -> np.random.Generator:
        # RNG_SEED participates so different seeds draw different augmentation
        # streams (≙ rank-offset host seeding intent, ref: utils.py:61-63).
        # One generator per (seed, epoch, sample): backend-independent.
        return np.random.default_rng(
            np.random.SeedSequence([self.base_seed, self._epoch_seed, idx])
        )

    def load_batch(self, idxs, n_threads: int = 4):
        """Decode+transform a batch of samples, via the C++ kernel when
        available (one GIL-free call, internal thread pool) with per-image
        PIL fallback; otherwise plain per-item PIL.

        Returns ``(images [n,H,W,3] float32, labels [n] int32)``.
        """
        out_size = self.im_size if self.train else self.crop_size
        labels = np.asarray(
            [self.samples[int(i)][1] for i in idxs], np.int32
        )
        out_dtype = np.uint8 if self.raw_u8 else np.float32
        if not self._use_native():
            images = np.stack([self[int(i)][0] for i in idxs])
            return images.astype(out_dtype), labels

        from distribuuuu_tpu import native
        from distribuuuu_tpu.data import transforms as T

        n = len(idxs)
        geoms = np.zeros((n,), native.GEOM_DTYPE)
        paths: list[str] = []
        fallback: list[int] = []  # positions the native path can't handle
        for pos, idx in enumerate(int(i) for i in idxs):
            path, _ = self.samples[idx]
            dims = native.file_dims(path)
            if dims is None:  # exotic format → PIL for this image
                paths.append("")  # sentinel: C++ fails it instantly, no IO
                fallback.append(pos)
                continue
            paths.append(path)
            w, h = dims
            if self.train:
                g = T.train_geom(w, h, self.im_size, self._rng(idx))
            else:
                g = T.val_geom(w, h, self.im_size, self.crop_size)
            geoms[pos] = g + (0,)  # trailing struct padding field
        if self.raw_u8:
            images, statuses = native.load_batch_u8(
                paths, geoms, (out_size, out_size), n_threads,
            )
        else:
            images, statuses = native.load_batch(
                paths, geoms, (out_size, out_size),
                T.IMAGENET_MEAN, T.IMAGENET_STD, n_threads,
            )
        for pos in set(fallback) | set(np.nonzero(statuses)[0].tolist()):
            images[pos] = self[int(idxs[pos])][0]
        return images, labels

    def set_epoch_seed(self, seed: int) -> None:
        """Augmentation randomness folds in the epoch (reference semantics:
        worker RNG reseeded per epoch via the sampler reshuffle)."""
        self._epoch_seed = seed

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx: int):
        path, label = self.samples[idx]
        with Image.open(path) as img:
            img = img.convert("RGB")
            if self.train:
                arr = train_transform(
                    img, self.im_size, self._rng(idx),
                    normalize=not self.raw_u8,
                )
            else:
                arr = val_transform(
                    img, self.im_size, self.crop_size,
                    normalize=not self.raw_u8,
                )
        return arr, label
