"""ImageFolder dataset: ``root/split/class_name/*.jpg`` directory layout.

Semantics mirror torchvision.datasets.ImageFolder as the reference uses it
(ref: /root/reference/distribuuuu/utils.py:127,166): classes are the sorted
subdirectory names, labels their indices; every file with an image extension
counts. Decode is PIL; transforms are data/transforms.py.
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

from distribuuuu_tpu.data.transforms import train_transform, val_transform

IMG_EXTENSIONS = (
    ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp",
)


def scan_image_folder(root: str):
    """Return (samples, classes): samples = [(path, class_idx)], classes sorted."""
    if not os.path.isdir(root):
        raise FileNotFoundError(
            f"Dataset directory not found: {root} "
            f"(expected ImageFolder layout root/class_name/*.jpg; "
            f"set MODEL.DUMMY_INPUT True to train without data)"
        )
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    if not classes:
        raise FileNotFoundError(f"No class subdirectories under {root}")
    samples = []
    for idx, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for dirpath, _, filenames in sorted(os.walk(cdir)):
            for fname in sorted(filenames):
                if fname.lower().endswith(IMG_EXTENSIONS):
                    samples.append((os.path.join(dirpath, fname), idx))
    if not samples:
        raise FileNotFoundError(f"No images found under {root}")
    return samples, classes


class ImageFolderDataset:
    def __init__(
        self,
        root: str,
        split: str,
        im_size: int,
        train: bool,
        base_seed: int = 0,
        crop_size: int | None = None,
    ):
        self.dir = os.path.join(root, split)
        self.samples, self.classes = scan_image_folder(self.dir)
        self.im_size = im_size
        # val: shorter-side resize to im_size, then center-crop to the model
        # input size (ref: utils.py:169-170 — Resize(256) + CenterCrop(224))
        self.crop_size = im_size if crop_size is None else crop_size
        self.train = train
        self.base_seed = base_seed
        self._epoch_seed = 0

    def set_epoch_seed(self, seed: int) -> None:
        """Augmentation randomness folds in the epoch (reference semantics:
        worker RNG reseeded per epoch via the sampler reshuffle)."""
        self._epoch_seed = seed

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx: int):
        path, label = self.samples[idx]
        with Image.open(path) as img:
            img = img.convert("RGB")
            if self.train:
                # RNG_SEED participates so different seeds draw different
                # augmentation streams (≙ rank-offset host seeding intent,
                # ref: utils.py:61-63)
                rng = np.random.default_rng(
                    np.random.SeedSequence([self.base_seed, self._epoch_seed, idx])
                )
                arr = train_transform(img, self.im_size, rng)
            else:
                arr = val_transform(img, self.im_size, self.crop_size)
        return arr, label
