"""ShardDataset: streaming reader over a packed shard split.

Same dataset surface the loader already speaks for imagefolder —
``__len__``/``__getitem__``/``load_batch``/``set_epoch_seed``/``classes`` —
so the whole downstream stack (thread-pool assembly, retry/skip
resilience, device prefetch ring, device-normalize) is reused unchanged.
What differs is underneath: samples come from a handful of large shard
files via positioned reads (``os.pread`` — lockless under the loader's
worker threads) instead of one ``open()`` per JPEG, and the train-time
sample order is the window-shuffled sequential order of ``order.py``
(:meth:`make_sampler`), so reads track a sequential sweep.

Decode parity: records hold the source files' encoded bytes verbatim, and
augmentation randomness is the same ``(base_seed, epoch, idx)``-derived
stream the imagefolder dataset draws — sample i of a packed split decodes
byte-identically to sample i of the source tree (packing preserves scan
order). The native C++ kernel decodes straight from the record buffers
(``native.load_batch_mem``); PIL covers fallback and exotic formats.

Failure containment: a damaged record (CRC mismatch, truncation-lost
tail) raises ``ShardReadError`` from exactly one sample; the loader's
``DATA.RETRIES``/``DATA.SKIP_CORRUPT`` machinery substitutes and logs it.
A shard whose index footer is gone is re-indexed by forward scan at open
(warned, with the recovered/lost record counts) — the
``FAULTS.TRUNCATE_SHARD`` injection drills exactly this path.
"""

from __future__ import annotations

import io
import os
import threading

import numpy as np

from distribuuuu_tpu.data.shards.format import (
    ShardReadError,
    read_record_at,
    read_shard_index,
    read_shard_manifest,
)
from distribuuuu_tpu.data.transforms import train_transform, val_transform
from distribuuuu_tpu.telemetry import registry as telemetry_registry


class RecordShards:
    """The species-independent half of a shard reader: manifest load,
    global-index→(shard, record) mapping, lazy per-shard fd + index, and
    the lockless positioned record read. :class:`ShardDataset` (images)
    and the token species (data/shards/tokens.TokenShardDataset) both
    stream through exactly this core, so footer recovery, the
    ``ShardReadError``→``DATA.SKIP_CORRUPT`` containment path, and the
    shard-IO telemetry tallies are one implementation."""

    FORMAT = "shards"
    # the manifest species this reader decodes (absence in an old image
    # manifest reads as "images")
    KIND = "images"

    def _open_split(self, root: str, split: str) -> None:
        from distribuuuu_tpu.data.shards.format import ShardFormatError
        from distribuuuu_tpu.utils import faults

        self.dir = os.path.join(root, split)
        faults.maybe_truncate_shard(self.dir)  # injection no-op (FAULTS.*)
        self.manifest = read_shard_manifest(self.dir)
        kind = self.manifest.get("kind", "images")
        if kind != self.KIND:
            raise ShardFormatError(
                f"{self.dir} holds {kind!r} shards but DATA.FORMAT selects "
                f"the {self.KIND!r} reader — point TRAIN/TEST.DATASET at a "
                f"{self.KIND} pack ("
                + ("tools/make_shards.py" if self.KIND == "images"
                   else "tools/make_token_shards.py")
                + " writes one) or switch DATA.FORMAT"
            )
        self._shards = self.manifest["shards"]
        # global index i → shard s where cum[s] <= i < cum[s+1]
        counts = [int(s["records"]) for s in self._shards]
        self._cum = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        self._n = int(self.manifest["num_records"])
        # per-shard fd + offsets, opened/indexed lazily under a lock (the
        # pread calls themselves are lockless and thread-safe)
        self._open_lock = threading.Lock()
        self._fds: dict[int, int] = {}
        self._offsets: dict[int, list[int]] = {}

    # ------------------------------------------------------------- plumbing
    def _shard_of(self, idx: int) -> tuple[int, int]:
        if not 0 <= idx < self._n:
            raise IndexError(f"sample {idx} out of range [0, {self._n})")
        s = int(np.searchsorted(self._cum, idx, side="right")) - 1
        return s, idx - int(self._cum[s])

    def _ensure_open(self, s: int) -> tuple[int, list[int]]:
        with self._open_lock:
            if s not in self._fds:
                from distribuuuu_tpu.utils.logger import get_logger

                path = os.path.join(self.dir, self._shards[s]["file"])
                offsets, recovered = read_shard_index(path)
                expect = int(self._shards[s]["records"])
                if recovered or len(offsets) != expect:
                    get_logger().warning(
                        "shard %s: index footer unreadable — recovered %d of "
                        "%d records by forward scan; lost records will raise "
                        "and flow through the DATA.SKIP_CORRUPT path",
                        path, len(offsets), expect,
                    )
                self._fds[s] = os.open(path, os.O_RDONLY)
                self._offsets[s] = offsets
            return self._fds[s], self._offsets[s]

    def record(self, idx: int) -> tuple[bytes, int, str]:
        """Raw record ``(image_bytes, label, key)`` — the byte-identical
        round-trip surface (tests) and the decode input."""
        s, r = self._shard_of(int(idx))
        fd, offsets = self._ensure_open(s)
        if r >= len(offsets):
            raise ShardReadError(
                f"sample {idx}: record {r} of {self._shards[s]['file']} lost "
                f"to truncation (shard has {len(offsets)} readable records, "
                f"manifest says {self._shards[s]['records']})"
            )
        rec = read_record_at(fd, offsets[r], self._shards[s]["file"])
        # shard-IO tallies in the shared registry (telemetry/registry.py):
        # run_report's per-rank IO line comes from the epoch snapshots
        reg = telemetry_registry.get_registry()
        reg.counter("shards.records").inc(1)
        reg.counter("shards.bytes").inc(len(rec[0]))
        return rec

    def close(self) -> None:
        with self._open_lock:
            for fd in self._fds.values():
                os.close(fd)
            self._fds.clear()
            self._offsets.clear()

    # ------------------------------------------- shared loader surface
    def __len__(self):
        return self._n

    def set_epoch_seed(self, seed: int) -> None:
        self._epoch_seed = seed

    def make_sampler(self, num_replicas: int, rank: int, shuffle: bool,
                     seed: int, drop_last: bool = False):
        """The loader's sampler hook: train (shuffle) gets the
        window-shuffled sequential order; val returns None → the plain
        DistributedSampler (storage order — already sequential). Shared by
        both species — which is what carries exact mid-epoch resume to the
        token pipeline for free (the cursor protocol only needs
        ``order_state``)."""
        if not shuffle:
            return None
        from distribuuuu_tpu.config import cfg
        from distribuuuu_tpu.data.shards.order import WindowShuffleSampler

        return WindowShuffleSampler(
            self._n, num_replicas, rank, seed=seed,
            block=int(cfg.DATA.SHARDS_BLOCK),
            window=int(cfg.DATA.SHARDS_WINDOW),
            drop_last=drop_last,
        )


class ShardDataset(RecordShards):
    """The IMAGE shard species: encoded image bytes per record, decoded
    through PIL or the C++ kernel's memory-buffer API (module docstring)."""

    def __init__(
        self,
        root: str,
        split: str,
        im_size: int,
        train: bool,
        base_seed: int = 0,
        crop_size: int | None = None,
        backend: str = "auto",
        raw_u8: bool = False,
    ):
        self._open_split(root, split)
        self.classes = list(self.manifest["classes"])
        self.im_size = im_size
        self.crop_size = im_size if crop_size is None else crop_size
        self.train = train
        self.base_seed = base_seed
        self._epoch_seed = 0
        if backend not in ("auto", "native", "pil"):
            raise ValueError(f"DATA.BACKEND must be auto|native|pil, got {backend}")
        self.backend = backend
        self.raw_u8 = raw_u8

    def _rng(self, idx: int) -> np.random.Generator:
        # identical stream to ImageFolderDataset._rng — same (seed, epoch,
        # sample) triple, so a packed corpus augments byte-identically
        return np.random.default_rng(
            np.random.SeedSequence([self.base_seed, self._epoch_seed, idx])
        )

    def _use_native(self) -> bool:
        if self.backend == "pil":
            return False
        from distribuuuu_tpu import native

        if native.available() and native.has_mem_api():
            return True
        if self.backend == "native":
            raise RuntimeError(
                "DATA.BACKEND=native but the C++ kernel (with the memory-"
                f"buffer API shards need) is unavailable: {native.build_error()}"
            )
        return False

    def _decode_pil(self, image_bytes: bytes, idx: int) -> np.ndarray:
        from PIL import Image

        with Image.open(io.BytesIO(image_bytes)) as img:
            img = img.convert("RGB")
            if self.train:
                return train_transform(
                    img, self.im_size, self._rng(idx), normalize=not self.raw_u8
                )
            return val_transform(
                img, self.im_size, self.crop_size, normalize=not self.raw_u8
            )

    def __getitem__(self, idx: int):
        image_bytes, label, _ = self.record(int(idx))
        return self._decode_pil(image_bytes, int(idx)), label

    def load_batch(self, idxs, n_threads: int = 4):
        """Batch decode from record buffers — the C++ kernel path
        (``native.load_batch_mem``: one GIL-free call, internal thread
        pool) with per-image PIL fallback, mirroring the imagefolder
        dataset's contract. Returns ``(images, labels)``."""
        out_size = self.im_size if self.train else self.crop_size
        recs = [self.record(int(i)) for i in idxs]
        labels = np.asarray([r[1] for r in recs], np.int32)
        out_dtype = np.uint8 if self.raw_u8 else np.float32
        if not self._use_native():
            images = np.stack([
                self._decode_pil(rec[0], int(i)) for rec, i in zip(recs, idxs)
            ])
            return images.astype(out_dtype), labels

        from distribuuuu_tpu import native
        from distribuuuu_tpu.data import transforms as T

        n = len(recs)
        geoms = np.zeros((n,), native.GEOM_DTYPE)
        bufs: list[bytes] = []
        fallback: list[int] = []
        for pos, (rec, idx) in enumerate(zip(recs, (int(i) for i in idxs))):
            dims = native.mem_dims(rec[0])
            if dims is None:  # exotic format → PIL for this image
                bufs.append(b"")  # sentinel: C++ fails it instantly
                fallback.append(pos)
                continue
            bufs.append(rec[0])
            w, h = dims
            if self.train:
                g = T.train_geom(w, h, self.im_size, self._rng(idx))
            else:
                g = T.val_geom(w, h, self.im_size, self.crop_size)
            geoms[pos] = g + (0,)  # trailing struct padding field
        if self.raw_u8:
            images, statuses = native.load_batch_u8_mem(
                bufs, geoms, (out_size, out_size), n_threads,
            )
        else:
            images, statuses = native.load_batch_mem(
                bufs, geoms, (out_size, out_size),
                T.IMAGENET_MEAN, T.IMAGENET_STD, n_threads,
            )
        for pos in set(fallback) | set(np.nonzero(statuses)[0].tolist()):
            images[pos] = self._decode_pil(recs[pos][0], int(idxs[pos]))
        return images, labels
