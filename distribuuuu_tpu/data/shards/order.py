"""Topology-independent, IO-friendly sample order for shard streaming.

The global order for an epoch is a function of ``(seed, epoch)`` ALONE —
never of world size, rank, or mesh shape. Every data rank strides the same
global permutation (rank r takes ``order[r::world]``, the
DistributedSampler convention), so after k global batches the consumed set
is exactly ``order[:k × global_batch]`` on ANY topology: a dp=4 → dp=2
elastic resume (resilience layer) continues the identical stream, and the
saved global cursor means the same thing on both sides.

Unlike the full uniform permutation the imagefolder sampler draws, this
order is built for sequential shard IO: storage order is cut into
``block``-record runs, the RUNS are permuted, and a ``window``-sample
shuffle buffer decorrelates neighbors — every read lands within ~window
records of a sequential sweep position (page-cache/readahead friendly),
while any two samples can still meet in a batch across epochs. This is the
tf.data ``shuffle(buffer)`` regime the MLPerf TPU input pipelines use; at
``block=1, window=n`` it degenerates to the exact uniform shuffle.
"""

from __future__ import annotations

import numpy as np


def shuffle_rng(seed: int, epoch: int) -> np.random.Generator:
    """The epoch's shuffle generator. (seed, epoch)-derived, nothing else."""
    return np.random.default_rng(
        np.random.SeedSequence([int(seed) & 0xFFFFFFFF, int(epoch)])
    )


def global_order(n: int, seed: int, epoch: int, block: int = 64,
                 window: int = 1024) -> np.ndarray:
    """The epoch's global sample permutation of ``[0, n)`` (int64).

    Two stages, both drawn from :func:`shuffle_rng`:
      1. block shuffle — storage order is split into ``block``-record runs
         and the runs are permuted (sequential IO within each run);
      2. window shuffle — a ``window``-slot buffer over that stream emits a
         uniformly-chosen slot per step (refilled from the stream), then
         drains fully shuffled.
    """
    n, block, window = int(n), max(1, int(block)), max(1, int(window))
    if n <= 0:
        return np.empty((0,), np.int64)
    rng = shuffle_rng(seed, epoch)
    n_blocks = -(-n // block)
    stream = np.concatenate([
        np.arange(b * block, min((b + 1) * block, n), dtype=np.int64)
        for b in rng.permutation(n_blocks)
    ])
    w = min(window, n)
    if w <= 1:
        return stream
    buf = stream[:w].copy()
    out = np.empty((n,), np.int64)
    draws = rng.integers(0, w, size=n - w)
    for k in range(n - w):
        j = draws[k]
        out[k] = buf[j]
        buf[j] = stream[w + k]
    rng.shuffle(buf)
    out[n - w:] = buf
    return out


class WindowShuffleSampler:
    """Drop-in for ``data/sampler.DistributedSampler`` whose per-epoch
    permutation is :func:`global_order` — the shard-streaming order. Same
    padding/striding contract (pad by wrapping to a world multiple, rank r
    takes ``order[r::world]``), plus ``order_state()`` — the saveable
    identity of the epoch's shuffle that ``Loader.state_dict`` embeds in
    preemption checkpoints (exact mid-epoch resume verifies it before
    trusting a restored cursor)."""

    def __init__(self, dataset_len: int, num_replicas: int, rank: int,
                 seed: int = 0, block: int = 64, window: int = 1024,
                 drop_last: bool = False):
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.dataset_len = int(dataset_len)
        self.num_replicas = num_replicas
        self.rank = rank
        self.seed = int(seed)
        self.block = int(block)
        self.window = int(window)
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_len % num_replicas != 0:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = -(-dataset_len // num_replicas)
        self.total_size = self.num_samples * num_replicas
        self._cache: tuple[int, np.ndarray] | None = None  # (epoch, order)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def epoch_order(self) -> np.ndarray:
        """The epoch's GLOBAL order (shared by all ranks), cached."""
        if self._cache is None or self._cache[0] != self.epoch:
            self._cache = (
                self.epoch,
                global_order(self.dataset_len, self.seed, self.epoch,
                             self.block, self.window),
            )
        return self._cache[1]

    def indices(self) -> np.ndarray:
        order = self.epoch_order()
        if not self.drop_last and len(order) < self.total_size:
            pad = self.total_size - len(order)
            order = np.concatenate([order, order[:pad]])
        else:
            order = order[: self.total_size]
        return order[self.rank :: self.num_replicas]

    def order_state(self) -> dict:
        """The shuffle identity for this epoch: the knobs that determine
        the order plus the initial shuffle-RNG state (bit-generator state
        dict — plain ints, JSON-able). A restored cursor is only honored
        when the live sampler regenerates the SAME state; anything else
        (changed RNG_SEED / block / window / corpus) means the cursor
        would point into a different permutation."""
        return {
            "kind": "window_shuffle",
            "seed": self.seed,
            "epoch": int(self.epoch),
            "block": self.block,
            "window": self.window,
            "num_records": self.dataset_len,
            "rng_state": shuffle_rng(self.seed, self.epoch).bit_generator.state,
        }

    def __len__(self):
        return self.num_samples
