"""Sharded dataset subsystem (``DATA.FORMAT = shards``).

Indexed record shards + topology-independent streaming + exact mid-epoch
resume: ``format.py`` is the on-disk contract (length-prefixed CRC'd
records, per-shard index footer, atomically-committed MANIFEST.json),
``order.py`` the (seed, epoch)-only window-shuffled sample order, and
``reader.py`` the dataset the existing loader stack consumes. Pack a tree
with ``tools/make_shards.py``; certify it with ``--verify``.
"""

from distribuuuu_tpu.data.shards.format import (  # noqa: F401
    MANIFEST_NAME,
    ShardFormatError,
    ShardReadError,
    ShardWriter,
    pack_imagefolder,
    read_shard_index,
    read_shard_manifest,
    verify_split,
    write_shard_manifest,
)
from distribuuuu_tpu.data.shards.order import (  # noqa: F401
    WindowShuffleSampler,
    global_order,
)
from distribuuuu_tpu.data.shards.reader import ShardDataset  # noqa: F401
