"""Token shard species: packed LM sequences over the existing shard format.

Documents → byte tokens → one ``EOS`` per document boundary → the
concatenated stream cut into fixed ``pack_len + 1``-token records (the +1
is the next-token shift: input = ``[:-1]``, targets = ``[1:]``, so one
record feeds one training example with NO cross-record dependency — any
shuffle order is valid). The container is ``data/shards/format.py``
verbatim — length-prefixed CRC'd records, index footer, atomically-
committed manifest — so footer recovery, ``--verify``, the
``FAULTS.TRUNCATE_SHARD`` drill, and the loader's
``DATA.RETRIES``/``SKIP_CORRUPT`` containment all apply unchanged.

Record body reuse: the image record's ``label`` field counts the document
boundaries inside the sequence (free observability), ``key`` is the
global sequence id, and the payload bytes are the little-endian uint16
token array instead of encoded image bytes.

Manifest extras (``format.write_shard_manifest(extra=...)``):
``kind="tokens"`` (the species guard — the image reader refuses these),
``pack_len``, ``total_tokens``, and the tokenizer identity
(lm/tokenizer.ByteTokenizer.identity) — which :class:`TokenShardDataset`
checks against the live config so a seq-len or vocab/tokenizer mismatch
refuses at loader construction with the repack command, not as a garbage
loss curve three hours in (ISSUE 12 satellite).

Exact mid-epoch resume is inherited, not reimplemented: the dataset is a
``reader.RecordShards`` (``FORMAT="shards"`` + the shared window-shuffle
sampler), so ``Loader.state_dict``'s global-cursor protocol applies
verbatim; :meth:`TokenShardDataset.identity` additionally rides the
cursor so a tokenizer/pack change invalidates it loudly.
"""

from __future__ import annotations

import numpy as np

from distribuuuu_tpu.data.shards.format import (
    ShardFormatError,
    ShardReadError,
    ShardWriter,
    write_shard_manifest,
)
from distribuuuu_tpu.data.shards.reader import RecordShards
from distribuuuu_tpu.lm.tokenizer import ByteTokenizer

TOKEN_DTYPE = np.dtype("<u2")  # little-endian uint16 payload on disk


# ------------------------------------------------------------------ packing


def pack_token_stream(docs, pack_len: int, tokenizer: ByteTokenizer | None = None):
    """Documents → fixed-length packed sequences.

    Yields ``(seq, n_docs)``: ``seq`` a ``pack_len + 1`` uint16 array from
    the EOS-joined document stream, ``n_docs`` the number of document
    boundaries (EOS tokens) inside it. The trailing partial window is
    DROPPED (a short record would break the fixed-shape batch contract);
    the packer reports how many tokens that cost.
    """
    tok = tokenizer or ByteTokenizer()
    if pack_len < 1:
        raise ValueError(f"pack_len must be >= 1, got {pack_len}")
    width = pack_len + 1
    buf = np.empty((0,), np.uint16)
    for doc in docs:
        ids = tok.encode(doc) if not isinstance(doc, np.ndarray) else doc
        buf = np.concatenate(
            [buf, ids.astype(np.uint16), np.array([tok.eos_id], np.uint16)]
        )
        while len(buf) >= width:
            seq, buf = buf[:width].copy(), buf[width:]
            yield seq, int((seq == tok.eos_id).sum())


def write_token_shards(
    out_dir: str,
    seqs,
    pack_len: int,
    *,
    tokenizer: ByteTokenizer | None = None,
    target_bytes: int = 4 * 1024 * 1024,
    source: str = "",
) -> str:
    """Write packed sequences into ``out_dir`` (one split directory) and
    commit the token manifest. Returns the manifest path."""
    tok = tokenizer or ByteTokenizer()
    writer = ShardWriter(out_dir, target_bytes=target_bytes)
    n = 0
    for seq, n_docs in seqs:
        seq = np.asarray(seq, TOKEN_DTYPE)
        if len(seq) != pack_len + 1:
            raise ValueError(
                f"sequence {n} has {len(seq)} tokens, want pack_len+1="
                f"{pack_len + 1}"
            )
        writer.add(seq.tobytes(), int(n_docs), f"seq-{n:08d}")
        n += 1
    if n == 0:
        # the long-context footgun (ISSUE 19): repacking a small corpus at
        # --pack-len 4096 silently drops the trailing partial window — the
        # ONLY window — and commits an empty split the loader then refuses
        # hours later. Refuse here, at pack time, with the arithmetic (no
        # shard was opened — zero adds — so there is nothing to clean up,
        # and no MANIFEST.json is committed).
        raise ValueError(
            f"{out_dir}: 0 complete records at pack_len={pack_len} — every "
            f"record needs pack_len+1={pack_len + 1} tokens and the "
            "EOS-joined corpus stream is shorter than one record (the "
            "trailing partial window is dropped by contract); lower "
            "--pack-len or grow the corpus"
        )
    shards = writer.close()
    return write_shard_manifest(
        out_dir, shards, classes=[], target_bytes=target_bytes, source=source,
        extra={
            "kind": "tokens",
            "pack_len": int(pack_len),
            "total_tokens": n * (pack_len + 1),
            **tok.identity(),
        },
    )


# ------------------------------------------------------------------ reading


class TokenShardDataset(RecordShards):
    """Loader-facing token shard reader: ``dataset[i]`` returns
    ``(input_tokens [S] int32, next_tokens [S] int32)`` — the loader's
    generic ``(image, label)`` contract, so batches arrive as
    ``{"image": [B, S] int32, "label": [B, S] int32, "mask": [B]}``
    through the unchanged assembly/prefetch/sharding stack.

    ``BATCH_DTYPE`` tells the loader to keep the stacked payload int32
    (the embedding lookup input) instead of the image float/uint8 cast.
    """

    KIND = "tokens"
    BATCH_DTYPE = np.int32

    def __init__(self, root: str, split: str, seq_len: int,
                 num_classes: int | None = None):
        self._open_split(root, split)
        self.seq_len = int(seq_len)
        pack = int(self.manifest.get("pack_len", -1))
        if pack != self.seq_len:
            raise ShardFormatError(
                f"{self.dir}: token shards are packed at pack_len={pack} "
                f"but LM.SEQ_LEN={self.seq_len} — set LM.SEQ_LEN {pack} or "
                f"repack: python tools/make_token_shards.py --src <corpus> "
                f"--out <root> --pack-len {self.seq_len}"
            )
        self.tokenizer = ByteTokenizer()
        live = self.tokenizer.identity()
        packed = {k: self.manifest.get(k) for k in live}
        if packed != live:
            raise ShardFormatError(
                f"{self.dir}: tokenizer identity drift — pack says "
                f"{packed}, live tokenizer is {live}; a cursor/weights "
                "trained on one cannot continue on the other (repack with "
                "tools/make_token_shards.py)"
            )
        if num_classes is not None and int(num_classes) < live["vocab_size"]:
            raise ShardFormatError(
                f"MODEL.NUM_CLASSES={num_classes} is smaller than the "
                f"pack's tokenizer vocab {live['vocab_size']} — the head "
                "could never emit every token id; set MODEL.NUM_CLASSES "
                f"{live['vocab_size']} (the gpt_*.yaml default)"
            )

    def identity(self) -> dict:
        """Rides the Loader's exact-resume cursor: a restored cursor is
        honored only when the live pack/tokenizer identity matches."""
        return {
            "kind": "tokens",
            "pack_len": self.seq_len,
            **self.tokenizer.identity(),
        }

    def seq_tokens(self, idx: int) -> np.ndarray:
        """The full packed ``[pack_len + 1]`` uint16 sequence of record
        ``idx`` (round-trip surface for tests and the bench)."""
        payload, _, _ = self.record(int(idx))
        seq = np.frombuffer(payload, TOKEN_DTYPE)
        if len(seq) != self.seq_len + 1:
            raise ShardReadError(
                f"record {idx}: {len(seq)} tokens, manifest pack_len says "
                f"{self.seq_len + 1}"
            )
        return seq

    def __getitem__(self, idx: int):
        seq = self.seq_tokens(int(idx)).astype(np.int32)
        return seq[:-1], seq[1:]
