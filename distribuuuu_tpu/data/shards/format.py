"""Sharded record format: fixed-target-size shard files of length-prefixed
records, a per-shard index footer, and a dataset-level ``MANIFEST.json``.

Why shards at all: the imagefolder path pays one ``open``+``read`` per
JPEG — at ImageNet scale that is ~1.3M metadata round-trips per epoch, the
access pattern network filesystems and disaggregated storage are worst at.
Production TPU input pipelines instead stream a few thousand large files
sequentially (the tf.data/ArrayRecord pattern of the MLPerf TPU-pod runs);
this module is the first-party equivalent. ``tools/make_shards.py`` packs
any imagefolder tree; ``reader.ShardDataset`` streams it back.

On-disk layout (``<out>/<split>/``):

  shard-00000.drec … shard-NNNNN.drec   record shards (SHARD_PATTERN)
  MANIFEST.json                         dataset manifest (committed LAST)

Shard file = records, then an index footer::

  record  := <u32 body_len> <u32 crc32(body)> body
  body    := <i32 label> <u16 key_len> key-utf8 image-bytes
  index   := n_records × <u64 record_offset>
  trailer := <u64 index_offset> <u32 n_records> <u32 crc32(index)> 8s magic

The image bytes are the source file's ENCODED bytes verbatim (no
re-encode): pack→read round-trips are byte-identical and the decode cost
is unchanged — only the IO pattern improves. Every record carries its own
CRC, so a flipped bit or a truncated tail is detected at read time and
surfaced as :class:`ShardReadError` — which the loader's existing
``DATA.SKIP_CORRUPT`` path turns into a logged substitution instead of a
dead epoch. A shard whose footer is damaged (tail truncation) is
re-indexed by a forward scan over the length-prefixed records; only the
records physically lost stay unreadable.

``MANIFEST.json`` follows the atomic-commit pattern of
``resilience/manifest.py`` (tmp file + fsync + ``os.replace``, written
AFTER every shard is durable): its absence means the pack never
completed. It records per-shard record counts, sizes and sha256 digests
(``tools/make_shards.py --verify`` re-reads everything against them), and
the class map, so the reader needs no directory scan at all.
"""

from __future__ import annotations

import json
import os
import struct
import zlib

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_SCHEMA = 1
RECORD_FORMAT = "dtpu-rec-v1"
SHARD_PATTERN = "shard-{:05d}.drec"
TRAILER_MAGIC = b"DTPUSHD1"

_HEADER = struct.Struct("<II")       # body_len, crc32(body)
_BODY_FIXED = struct.Struct("<iH")   # label, key_len
_TRAILER = struct.Struct("<QII8s")   # index_offset, n_records, crc32, magic
_OFFSET = struct.Struct("<Q")

DEFAULT_SHARD_BYTES = 64 * 1024 * 1024


class ShardFormatError(RuntimeError):
    """The shard directory/manifest itself is unusable (missing, partial
    pack, schema mismatch) — a configuration/corpus problem, not a
    per-record one."""


class ShardReadError(RuntimeError):
    """One record could not be read (CRC mismatch, truncation-lost record).
    The loader's retry/skip path handles these per sample."""


# ------------------------------------------------------------------ writing


def encode_record(image_bytes: bytes, label: int, key: str) -> bytes:
    kb = key.encode("utf-8")
    if len(kb) > 0xFFFF:
        raise ValueError(f"record key too long ({len(kb)} bytes): {key[:80]}…")
    body = _BODY_FIXED.pack(int(label), len(kb)) + kb + image_bytes
    return _HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF) + body


def decode_record(body: bytes) -> tuple[bytes, int, str]:
    """Body bytes (CRC already checked) → (image_bytes, label, key)."""
    label, key_len = _BODY_FIXED.unpack_from(body, 0)
    off = _BODY_FIXED.size
    key = body[off : off + key_len].decode("utf-8")
    return body[off + key_len :], int(label), key


class ShardWriter:
    """Append records, rolling to a new shard once the current one crosses
    ``target_bytes`` (records are never split across shards). ``close()``
    fsyncs every shard and returns the per-shard metadata list for the
    manifest."""

    def __init__(self, out_dir: str, target_bytes: int = DEFAULT_SHARD_BYTES):
        if target_bytes <= 0:
            raise ValueError(f"target_bytes must be positive, got {target_bytes}")
        self.out_dir = out_dir
        self.target_bytes = int(target_bytes)
        os.makedirs(out_dir, exist_ok=True)
        self.shards: list[dict] = []
        self._f = None
        self._offsets: list[int] = []

    def _open_next(self):
        name = SHARD_PATTERN.format(len(self.shards))
        self.shards.append({"file": name, "records": 0})
        self._offsets = []
        self._f = open(os.path.join(self.out_dir, name), "wb")

    def _finish_shard(self):
        if self._f is None:
            return
        index = b"".join(_OFFSET.pack(o) for o in self._offsets)
        index_offset = self._f.tell()
        self._f.write(index)
        self._f.write(_TRAILER.pack(
            index_offset, len(self._offsets),
            zlib.crc32(index) & 0xFFFFFFFF, TRAILER_MAGIC,
        ))
        self._f.flush()
        os.fsync(self._f.fileno())
        size = self._f.tell()
        self._f.close()
        self.shards[-1]["records"] = len(self._offsets)
        self.shards[-1]["size"] = size
        self._f = None

    def add(self, image_bytes: bytes, label: int, key: str) -> None:
        if self._f is None:
            self._open_next()
        self._offsets.append(self._f.tell())
        self._f.write(encode_record(image_bytes, label, key))
        if self._f.tell() >= self.target_bytes:
            self._finish_shard()

    def close(self) -> list[dict]:
        self._finish_shard()
        return self.shards


def write_shard_manifest(split_dir: str, shards: list[dict], classes: list[str],
                         target_bytes: int, source: str = "",
                         extra: dict | None = None) -> str:
    """Commit marker for a completed pack — written AFTER every shard is
    durable (same tmp+fsync+``os.replace`` discipline as
    ``resilience/manifest.py``). Digests are computed here so ``--verify``
    and the truncated-shard fault injection have ground truth.

    ``extra`` merges species-specific fields into the manifest — the token
    species (data/shards/tokens.py) declares ``kind="tokens"`` plus its
    pack length and tokenizer identity there, so a reader opening the
    wrong species refuses with the reason instead of mis-decoding records.
    Image packs carry no ``kind`` (readers treat its absence as
    ``"images"`` — every pre-r13 manifest stays valid)."""
    from distribuuuu_tpu.resilience.manifest import sha256_file

    for s in shards:
        s["sha256"] = sha256_file(os.path.join(split_dir, s["file"]))
    man = {
        "schema": MANIFEST_SCHEMA,
        "record_format": RECORD_FORMAT,
        "num_records": sum(s["records"] for s in shards),
        "classes": list(classes),
        "target_shard_bytes": int(target_bytes),
        "shards": shards,
        "source": source,
        **(extra or {}),
    }
    dest = os.path.join(split_dir, MANIFEST_NAME)
    tmp = dest + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dest)
    return dest


def read_shard_manifest(split_dir: str) -> dict:
    path = os.path.join(split_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            man = json.load(f)
    except FileNotFoundError:
        raise ShardFormatError(
            f"no {MANIFEST_NAME} under {split_dir} — not a packed shard "
            "split (or the pack was interrupted before commit). Pack with: "
            "python tools/make_shards.py --src <imagefolder-root> --out "
            f"{os.path.dirname(split_dir) or '<shards-root>'}"
        ) from None
    except (OSError, json.JSONDecodeError) as e:
        raise ShardFormatError(f"unreadable {path}: {e}") from e
    if man.get("schema") != MANIFEST_SCHEMA or man.get("record_format") != RECORD_FORMAT:
        raise ShardFormatError(
            f"{path}: schema/format {man.get('schema')}/{man.get('record_format')} "
            f"not supported (want {MANIFEST_SCHEMA}/{RECORD_FORMAT})"
        )
    return man


def pack_imagefolder(src_root: str, out_root: str, splits=("train", "val"),
                     target_bytes: int = DEFAULT_SHARD_BYTES,
                     progress=None) -> dict:
    """Pack an imagefolder tree (``src_root/split/class/*.jpg``) into record
    shards under ``out_root/split/``. Record order IS the imagefolder scan
    order (``scan_image_folder``): global index i in the shard split equals
    index i of ``ImageFolderDataset`` over the same tree, so round-trip
    tests and mixed-format pipelines agree sample-for-sample.

    Returns ``{split: manifest_path}``.
    """
    from distribuuuu_tpu.data.imagefolder import scan_image_folder

    out = {}
    for split in splits:
        samples, classes = scan_image_folder(os.path.join(src_root, split))
        split_dir = os.path.join(out_root, split)
        writer = ShardWriter(split_dir, target_bytes=target_bytes)
        for i, (path, label) in enumerate(samples):
            with open(path, "rb") as f:
                image_bytes = f.read()
            key = os.path.relpath(path, os.path.join(src_root, split))
            writer.add(image_bytes, label, key)
            if progress is not None and (i + 1) % 1000 == 0:
                progress(split, i + 1, len(samples))
        shards = writer.close()
        out[split] = write_shard_manifest(
            split_dir, shards, classes, target_bytes,
            source=os.path.abspath(src_root),
        )
    return out


# ------------------------------------------------------------------ reading


def read_shard_index(path: str) -> tuple[list[int], bool]:
    """Record offsets of one shard: ``(offsets, recovered)``.

    Fast path reads the trailer+index footer. When the footer is damaged
    (tail truncation, bit rot) the index is RECOVERED by walking the
    length-prefixed records forward from offset 0, keeping every record
    that is complete and CRC-clean — so a truncated shard still serves
    everything before the cut (``recovered=True`` tells the caller to log
    it). Raises :class:`ShardFormatError` only when the file is unopenable.
    """
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size >= _TRAILER.size:
                f.seek(size - _TRAILER.size)
                index_offset, n, crc, magic = _TRAILER.unpack(f.read(_TRAILER.size))
                if (
                    magic == TRAILER_MAGIC
                    and index_offset + n * _OFFSET.size + _TRAILER.size == size
                ):
                    f.seek(index_offset)
                    index = f.read(n * _OFFSET.size)
                    if zlib.crc32(index) & 0xFFFFFFFF == crc:
                        return [
                            _OFFSET.unpack_from(index, i * _OFFSET.size)[0]
                            for i in range(n)
                        ], False
            # footer damaged → forward scan over length-prefixed records
            f.seek(0)
            offsets, pos = [], 0
            while pos + _HEADER.size <= size:
                f.seek(pos)
                body_len, crc = _HEADER.unpack(f.read(_HEADER.size))
                end = pos + _HEADER.size + body_len
                if end > size:
                    break  # record extends past EOF — the truncation point
                body = f.read(body_len)
                if zlib.crc32(body) & 0xFFFFFFFF != crc:
                    # either a damaged record or we walked into the index
                    # footer of an intact-but-weird file; stop either way
                    break
                offsets.append(pos)
                pos = end
            return offsets, True
    except OSError as e:
        raise ShardFormatError(f"cannot read shard {path}: {e}") from e


def read_record_at(fd: int, offset: int, path: str = "?") -> tuple[bytes, int, str]:
    """One record via ``os.pread`` (thread-safe positioned read; no shared
    file-position state, so reader threads need no locking). Raises
    :class:`ShardReadError` on truncation or CRC mismatch."""
    header = os.pread(fd, _HEADER.size, offset)
    if len(header) < _HEADER.size:
        raise ShardReadError(
            f"{path}@{offset}: record header truncated "
            f"({len(header)}/{_HEADER.size} bytes)"
        )
    body_len, crc = _HEADER.unpack(header)
    body = os.pread(fd, body_len, offset + _HEADER.size)
    if len(body) < body_len:
        raise ShardReadError(
            f"{path}@{offset}: record body truncated ({len(body)}/{body_len} bytes)"
        )
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise ShardReadError(f"{path}@{offset}: record CRC mismatch")
    return decode_record(body)


def verify_split(split_dir: str) -> tuple[bool, list[str]]:
    """Certify a packed split against its manifest: per-shard size + sha256
    (the resilience digest helpers), per-shard index integrity, per-record
    CRC walk, and total record count. Returns ``(ok, problems)`` — the
    ``tools/make_shards.py --verify`` engine."""
    from distribuuuu_tpu.resilience.manifest import sha256_file

    problems: list[str] = []
    try:
        man = read_shard_manifest(split_dir)
    except ShardFormatError as e:
        return False, [str(e)]
    total = 0
    for meta in man["shards"]:
        path = os.path.join(split_dir, meta["file"])
        if not os.path.isfile(path):
            problems.append(f"{meta['file']}: missing")
            continue
        size = os.path.getsize(path)
        if size != meta["size"]:
            problems.append(
                f"{meta['file']}: size {size} != manifest {meta['size']}"
            )
            continue
        if sha256_file(path) != meta["sha256"]:
            problems.append(f"{meta['file']}: sha256 mismatch")
            continue
        offsets, recovered = read_shard_index(path)
        if recovered:
            problems.append(f"{meta['file']}: index footer unreadable")
            continue
        if len(offsets) != meta["records"]:
            problems.append(
                f"{meta['file']}: {len(offsets)} records != manifest "
                f"{meta['records']}"
            )
            continue
        fd = os.open(path, os.O_RDONLY)
        try:
            for off in offsets:
                read_record_at(fd, off, path)
        except ShardReadError as e:
            problems.append(str(e))
        finally:
            os.close(fd)
        total += meta["records"]
    if not problems and total != man["num_records"]:
        problems.append(
            f"total records {total} != manifest num_records {man['num_records']}"
        )
    return not problems, problems
