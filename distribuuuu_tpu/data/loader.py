"""Batch loader: per-host sharded iteration with background prefetch.

Mirror of the reference's DataLoader construction (ref:
/root/reference/distribuuuu/utils.py:121-184): train = shuffled sampler +
``drop_last=True``; val = unshuffled + ``drop_last=False``. The torch worker
pool becomes a thread pool assembling numpy batches ahead of the consumer;
device placement (the ``pin_memory``/``non_blocking`` analogue) happens in
the trainer via ``shard_batch`` with double-buffered async dispatch.

Each batch is a dict: ``image`` [B,H,W,C] float32 (NHWC — TPU-native),
``label`` [B] int32, ``mask`` [B] float32 (0 marks padding in the final
ragged eval batch, so metrics can ignore it in-graph; the reference instead
silently double-counts DistributedSampler's padded duplicates).
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.data.dummy import DummyDataset
from distribuuuu_tpu.data.sampler import DistributedSampler
from distribuuuu_tpu.parallel import mesh as mesh_lib
from distribuuuu_tpu.telemetry import (
    registry as telemetry_registry,
    spans as telemetry_spans,
)
from distribuuuu_tpu.utils import faults
from distribuuuu_tpu.utils.jsonlog import metrics_log
from distribuuuu_tpu.utils.logger import get_logger


class Loader:
    """Iterates a dataset as per-host batches using sampler shards."""

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool,
        drop_last: bool,
        workers: int = 4,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.workers = max(1, workers)
        self._last_timing = None
        # Prefetch depth (batches assembled ahead of the consumer). When the
        # native backend is active each _assemble call already fans out over
        # `workers` C++ threads, so deep Python-side prefetch would multiply
        # to workers² decode threads; two in-flight batches suffice to
        # overlap. The PIL path decodes one image per Python thread, so there
        # the prefetch depth IS the parallelism.
        native_batch = False
        if hasattr(dataset, "_use_native"):
            try:
                native_batch = dataset._use_native()
            except RuntimeError:
                pass  # surfaces with a clear error at iteration time
        self.prefetch_depth = 2 if native_batch else self.workers
        # Decode resilience (DATA.RETRIES / RETRY_BACKOFF_S / SKIP_CORRUPT):
        # a failed decode retries with exponential backoff (transient
        # filesystem/network hiccups), then the corrupt sample is replaced
        # by a good one from the same batch and logged — one bad JPEG must
        # not abort a million-image epoch. SKIP_CORRUPT False = fail-stop.
        self.retries = max(0, int(cfg.DATA.RETRIES))
        self.retry_backoff = float(cfg.DATA.RETRY_BACKOFF_S)
        self.skip_corrupt = bool(cfg.DATA.SKIP_CORRUPT)
        # shard by DATA GROUP, not by process: processes sharing a data
        # row (model/pipe axes spanning hosts) must load identical data
        # (parallel/mesh.data_process_groups; ≡ (rank, world) in pure DP)
        data_rank, data_world = mesh_lib.data_process_groups()
        # Datasets may supply their own sampler (the shard reader's
        # window-shuffled sequential order, data/shards/order.py); the
        # torch-semantics DistributedSampler is the default. Both draw the
        # GLOBAL per-epoch order from (seed, epoch) alone and stride it by
        # rank, so k consumed global batches ≡ the order's first
        # k × global_batch entries on any topology — the invariant the
        # exact mid-epoch resume cursor (state_dict) rests on.
        self.sampler = None
        mk = getattr(dataset, "make_sampler", None)
        if mk is not None:
            self.sampler = mk(
                num_replicas=data_world, rank=data_rank, shuffle=shuffle,
                seed=seed, drop_last=False,
            )
        if self.sampler is None:
            self.sampler = DistributedSampler(
                len(dataset),
                num_replicas=data_world,
                rank=data_rank,
                shuffle=shuffle,
                seed=seed,
                drop_last=False,  # torch pads in the sampler; drop per-batch
            )
        self._epoch = 0
        self._resume: dict | None = None  # {"epoch", "skip"} — one-shot

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self.sampler.set_epoch(epoch)
        if hasattr(self.dataset, "set_epoch_seed"):
            self.dataset.set_epoch_seed(epoch)

    # ------------------------------------------------- exact mid-epoch resume
    def can_save_state(self) -> bool:
        """True when this loader's position is exactly resumable: the
        shard-format dataset plus an order whose identity is saveable
        (WindowShuffleSampler.order_state). The imagefolder path keeps the
        coarser epoch-granular resume."""
        return (
            getattr(self.dataset, "FORMAT", "") == "shards"
            and hasattr(self.sampler, "order_state")
        )

    def state_dict(self, batches_consumed: int) -> dict:
        """Saveable iterator state after ``batches_consumed`` batches of
        the current epoch: the epoch, the GLOBAL sample cursor (world-size
        independent — k global batches consume the order's first
        k × global_batch entries on any topology), and the shuffle-order
        identity incl. the shuffle-RNG state. JSON-able by construction;
        ``utils/checkpoint.save_preempt_checkpoint`` embeds it."""
        sd = {
            "v": 1,
            "format": getattr(self.dataset, "FORMAT", "imagefolder"),
            "epoch": int(self._epoch),
            "cursor": int(batches_consumed)
            * self.batch_size
            * self.sampler.num_replicas,
            "num_records": len(self.dataset),
        }
        if hasattr(self.sampler, "order_state"):
            sd["order"] = self.sampler.order_state()
        # dataset-species identity (the token pipeline's tokenizer/pack
        # fingerprint): a cursor must not survive a tokenizer or pack-len
        # change — the same byte stream would mean different tokens
        if hasattr(self.dataset, "identity"):
            sd["dataset_identity"] = self.dataset.identity()
        return sd

    def load_state_dict(self, sd: dict) -> int:
        """Arm the one-shot mid-epoch skip from a saved ``state_dict``.
        Returns the number of per-rank batches that will be skipped when
        the matching epoch is iterated. Raises ``ValueError`` when the
        cursor cannot be trusted (format/corpus/shuffle-identity changed)
        — the caller falls back to re-running the epoch from batch 0."""
        live_fmt = getattr(self.dataset, "FORMAT", "imagefolder")
        if sd.get("format") != live_fmt:
            raise ValueError(
                f"saved data state is {sd.get('format')!r}, live pipeline "
                f"is {live_fmt!r}"
            )
        if int(sd.get("num_records", -1)) != len(self.dataset):
            raise ValueError(
                f"corpus changed: saved {sd.get('num_records')} records, "
                f"live dataset has {len(self.dataset)}"
            )
        saved_order = sd.get("order")
        if saved_order is not None:
            if not hasattr(self.sampler, "order_state"):
                raise ValueError("live sampler has no saveable order")
            epoch = int(sd["epoch"])
            cur_epoch = self.sampler.epoch
            self.sampler.set_epoch(epoch)
            live_order = self.sampler.order_state()
            self.sampler.set_epoch(cur_epoch)
            if live_order != saved_order:
                diff = [
                    k for k in sorted(set(live_order) | set(saved_order))
                    if live_order.get(k) != saved_order.get(k)
                ]
                raise ValueError(
                    "shuffle order identity changed since the save "
                    f"(fields: {', '.join(diff)}) — the cursor would point "
                    "into a different permutation"
                )
        saved_ident = sd.get("dataset_identity")
        if saved_ident is not None:
            live_ident = (
                self.dataset.identity()
                if hasattr(self.dataset, "identity") else None
            )
            if live_ident != saved_ident:
                raise ValueError(
                    f"dataset identity changed since the save (saved "
                    f"{saved_ident}, live {live_ident}) — a tokenizer/"
                    "pack-len drift makes the cursor meaningless"
                )
        cursor = int(sd["cursor"])
        global_batch = self.batch_size * self.sampler.num_replicas
        skip, rem = divmod(cursor, global_batch)
        if rem:
            # topology grew (global batch no longer divides the cursor):
            # round DOWN — re-trains at most one partial batch, exactness
            # degrades to at-least-once for those samples (logged)
            get_logger().warning(
                "restored cursor %d is not a multiple of the live global "
                "batch %d — resuming at batch %d (up to %d samples re-run)",
                cursor, global_batch, skip, rem,
            )
        self._resume = {"epoch": int(sd["epoch"]), "skip": int(skip)}
        return int(skip)

    def resume_skip(self, epoch: int) -> int:
        """Batches the NEXT iteration of ``epoch`` will skip (armed by
        ``load_state_dict``; consumed one-shot by ``__iter__``)."""
        if self._resume is not None and self._resume["epoch"] == int(epoch):
            return self._resume["skip"]
        return 0

    def __len__(self):
        n = self.sampler.num_samples
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _assemble(self, idxs: np.ndarray, submit: float = 0.0) -> tuple:
        """Returns ``(batch, timing)``: the batch dict plus the stage
        timestamps of its assembly (utils/jsonlog.TIMELINE_STAGES subset:
        submit/dec0/dec1/asm1 — all ``time.perf_counter`` values)."""
        dec0 = time.perf_counter()
        images, labels = self._decode(idxs)
        dec1 = time.perf_counter()
        n = len(images)
        images = np.asarray(images)
        # DATA.DEVICE_NORMALIZE ships uint8 (4× fewer H2D bytes; the
        # trainer normalizes in-graph); otherwise float32 as before. A
        # dataset may pin the payload dtype instead (BATCH_DTYPE — the
        # token species ships int32 ids that must NOT be float-cast or
        # in-graph-normalized, data/shards/tokens.py).
        img_dtype = getattr(self.dataset, "BATCH_DTYPE", None) or (
            np.uint8 if images.dtype == np.uint8 else np.float32
        )
        batch = {
            "image": images.astype(img_dtype, copy=False),
            "label": labels.astype(np.int32),
            "mask": np.ones((n,), np.float32),
        }
        if n < self.batch_size:  # pad ragged final eval batch, mask it out
            pad = self.batch_size - n
            batch["image"] = np.concatenate(
                [batch["image"],
                 np.zeros((pad,) + batch["image"].shape[1:], img_dtype)]
            )
            # label shape is [B] for classification, [B, S] for the LM —
            # pad shape-generically
            batch["label"] = np.concatenate(
                [batch["label"],
                 np.zeros((pad,) + batch["label"].shape[1:], np.int32)]
            )
            batch["mask"] = np.concatenate([batch["mask"], np.zeros(pad, np.float32)])
        asm1 = time.perf_counter()
        if telemetry_spans.enabled() and cfg.TELEMETRY.STEP_SPANS:
            # worker-side halves of the batch timeline, per rank (the
            # primary-only kind="timeline" records carry the same stamps
            # for rank 0; these make a rank-3 decode stall visible)
            telemetry_spans.emit_span("decode", dec0, dec1, track="loader", n=n)
            telemetry_spans.emit_span("assemble", dec1, asm1, track="loader", n=n)
        reg = telemetry_registry.get_registry()
        reg.counter("data.batches").inc(1)
        reg.counter("data.samples").inc(n)
        reg.counter("data.decode_s").inc(dec1 - dec0)
        return batch, {"submit": submit, "dec0": dec0, "dec1": dec1,
                       "asm1": asm1}

    def _fetch_sample(self, i: int):
        """One sample with retry-with-backoff; ``None`` marks a
        persistently corrupt sample (logged, skipped — DATA.SKIP_CORRUPT)
        for the caller to substitute."""
        delay = self.retry_backoff
        err = None
        for attempt in range(self.retries + 1):
            try:
                faults.maybe_decode_error(int(i))  # injection hook (tests)
                return self.dataset[int(i)]
            except Exception as e:
                err = e
                if attempt < self.retries:
                    time.sleep(delay)
                    delay *= 2
        if not self.skip_corrupt:
            raise RuntimeError(
                f"sample {int(i)} failed decode after {self.retries + 1} "
                "attempts (DATA.SKIP_CORRUPT False — fail-stop)"
            ) from err
        get_logger().warning(
            "corrupt sample %d skipped after %d attempts (%s: %s) — "
            "substituting a good sample from the same batch",
            int(i), self.retries + 1, type(err).__name__, err,
        )
        telemetry_registry.get_registry().counter("data.errors").inc(1)
        metrics_log(
            "data_error", index=int(i), attempts=self.retries + 1,
            error=f"{type(err).__name__}: {err}",
        )
        return None

    def _decode(self, idxs) -> tuple:
        """(images, labels) via the batch kernel when available, else
        per-sample — both behind retry-with-backoff. A batch-level decode
        that keeps failing falls back to the per-sample path, which
        isolates and substitutes the corrupt sample(s) instead of
        aborting the epoch."""
        if hasattr(self.dataset, "load_batch"):
            delay = self.retry_backoff
            err = None
            for attempt in range(self.retries + 1):
                try:
                    for i in idxs:
                        faults.maybe_decode_error(int(i))
                    return self.dataset.load_batch(
                        idxs, n_threads=self.workers
                    )
                except Exception as e:
                    err = e
                    if attempt < self.retries:
                        time.sleep(delay)
                        delay *= 2
            if not self.skip_corrupt:
                raise RuntimeError(
                    f"batch decode failed after {self.retries + 1} attempts "
                    "(DATA.SKIP_CORRUPT False — fail-stop)"
                ) from err
            get_logger().warning(
                "batch decode failed after %d attempts (%s: %s) — "
                "isolating per-sample", self.retries + 1,
                type(err).__name__, err,
            )
        samples = [self._fetch_sample(i) for i in idxs]
        good = [s for s in samples if s is not None]
        if not good:
            raise RuntimeError(
                f"all {len(list(idxs))} samples in the batch failed decode — "
                "not a stray corrupt file; check the dataset/storage "
                "(first indices: " + ", ".join(str(int(i)) for i in list(idxs)[:4]) + ")"
            )
        samples = [s if s is not None else good[0] for s in samples]
        images = np.stack([p[0] for p in samples])
        labels = np.asarray([p[1] for p in samples], np.int32)
        return images, labels

    def last_timing(self) -> dict | None:
        """Stage timestamps (submit/dec0/dec1/asm1) of the most recently
        yielded batch — the loader half of the per-batch timeline
        (single-consumer iteration, so "last yielded" is unambiguous)."""
        return self._last_timing

    def __iter__(self):
        self._last_timing = None
        idxs = self.sampler.indices()
        n_batches = len(self)
        chunks = [
            idxs[b * self.batch_size : (b + 1) * self.batch_size]
            for b in range(n_batches)
        ]
        if self._resume is not None and self._resume["epoch"] == self._epoch:
            # exact mid-epoch resume: the skipped batches were already
            # consumed (and trained) by the preempted run — jump the
            # cursor, don't decode them (one-shot; later epochs are whole)
            chunks = chunks[self._resume["skip"] :]
            self._resume = None
        # Parallel background assembly (the torch worker-pool analogue):
        # `workers` batches decode/augment concurrently ahead of the consumer.
        # PIL decode and numpy transforms release the GIL, so threads give
        # real decode parallelism; batch order is preserved.
        with ThreadPoolExecutor(max_workers=self.prefetch_depth) as pool:
            in_flight: deque = deque()
            chunk_iter = iter(chunks)
            for chunk in chunks[: self.prefetch_depth]:
                in_flight.append(
                    pool.submit(self._assemble, chunk, time.perf_counter())
                )
                next(chunk_iter)
            while in_flight:
                batch, timing = in_flight.popleft().result()
                nxt = next(chunk_iter, None)
                if nxt is not None:
                    in_flight.append(
                        pool.submit(self._assemble, nxt, time.perf_counter())
                    )
                self._last_timing = timing
                yield batch


def device_prefetch(loader, put_fn, depth: int):
    """Device-side prefetch ring over a host-batch iterable.

    Yields ``(it, device_batch, timing)`` in loader order. With
    ``depth > 0`` the ring keeps the NEXT ``depth`` batches already put
    (``put_fn`` = the sharded ``jax.device_put``, an async dispatch), so
    the H2D transfers of batches k+1..k+depth overlap the consumer's step
    on batch k instead of serializing behind it. ``depth 0`` reproduces
    the unoverlapped put-then-step order exactly. Any depth is
    value-bit-identical: the put order, step order, and batch contents
    never change — only when each transfer is dispatched.

    ``timing`` carries the loader's assembly stamps (when the iterable is
    a ``Loader``) plus ``get0/get1`` (consumer blocked on the host batch)
    and ``put0/put1`` (H2D dispatch) — the consumer-side half of the
    utils/jsonlog timeline schema; the caller adds ``step0/step1``.
    """
    get_timing = getattr(loader, "last_timing", lambda: None)
    src = iter(loader)

    def pull():
        get0 = time.perf_counter()
        try:
            hb = next(src)
        except StopIteration:
            return None
        get1 = time.perf_counter()
        tl = dict(get_timing() or {})
        tl["get0"], tl["get1"] = get0, get1
        tl["n"] = int(np.shape(hb["image"])[0]) if "image" in hb else 0
        tl["put0"] = time.perf_counter()
        db = put_fn(hb)
        tl["put1"] = time.perf_counter()
        return db, tl

    ring: deque = deque()
    exhausted = False
    it = 0
    while True:
        while not exhausted and len(ring) < max(0, depth) + 1:
            item = pull()
            if item is None:
                exhausted = True
            else:
                ring.append(item)
        if not ring:
            return
        db, tl = ring.popleft()
        yield it, db, tl
        it += 1


def _build_dataset(split: str, train: bool):
    raw_u8 = bool(cfg.DATA.DEVICE_NORMALIZE)
    if cfg.MODEL.DUMMY_INPUT:
        # dummy images are model-input-sized for both splits (the reference
        # likewise uses 224² dummies everywhere, utils.py:125,159)
        return DummyDataset(
            length=cfg.TRAIN.BATCH_SIZE * 64, size=cfg.TRAIN.IM_SIZE,
            raw_u8=raw_u8,
        )
    root = cfg.TRAIN.DATASET if train else cfg.TEST.DATASET
    # train: RandomResizedCrop target; val: shorter-side resize to
    # TEST.IM_SIZE, center-crop to the model input size TRAIN.IM_SIZE
    # (ref: utils.py:131,169-170 — Resize(256) + CenterCrop(224))
    im_size = cfg.TRAIN.IM_SIZE if train else cfg.TEST.IM_SIZE
    common = dict(
        im_size=im_size, train=train,
        base_seed=cfg.RNG_SEED or 0,
        crop_size=None if train else cfg.TRAIN.IM_SIZE,
        backend=cfg.DATA.BACKEND,
        raw_u8=raw_u8,
    )
    if cfg.DATA.FORMAT == "tokens":
        # packed-sequence token shards (data/shards/tokens.py, packed by
        # tools/make_token_shards.py) — the LM pipeline. Same container,
        # same window-shuffled order, same exact mid-epoch resume; the
        # image-specific transform knobs don't apply. Pack/seq-len and
        # tokenizer/vocab identity are refused here, before any compile.
        from distribuuuu_tpu.data.shards.tokens import TokenShardDataset

        return TokenShardDataset(
            root, split, seq_len=int(cfg.LM.SEQ_LEN),
            num_classes=int(cfg.MODEL.NUM_CLASSES),
        )
    if cfg.DATA.FORMAT == "shards":
        # indexed record shards (data/shards/) — DATASET points at the
        # packed root (tools/make_shards.py); sequential IO + exact
        # mid-epoch resume
        from distribuuuu_tpu.data.shards.reader import ShardDataset

        return ShardDataset(root, split, **common)
    if cfg.DATA.FORMAT != "imagefolder":
        raise ValueError(
            f"DATA.FORMAT must be imagefolder|shards|tokens, got "
            f"{cfg.DATA.FORMAT!r}"
        )
    from distribuuuu_tpu.data.imagefolder import ImageFolderDataset

    return ImageFolderDataset(root, split, **common)


def construct_train_loader() -> Loader:
    """Train pipeline (ref: utils.py:121-152): shuffled, drop_last."""
    dataset = _build_dataset(cfg.TRAIN.SPLIT, train=True)
    return Loader(
        dataset,
        batch_size=_per_host_batch(cfg.TRAIN.BATCH_SIZE),
        shuffle=True,
        drop_last=True,
        workers=cfg.TRAIN.WORKERS,
        seed=cfg.RNG_SEED or 0,
    )


def construct_val_loader() -> Loader:
    """Val pipeline (ref: utils.py:155-184): unshuffled, keep ragged tail."""
    dataset = _build_dataset(cfg.TEST.SPLIT, train=False)
    return Loader(
        dataset,
        batch_size=_per_host_batch(cfg.TEST.BATCH_SIZE),
        shuffle=False,
        drop_last=False,
        workers=cfg.TRAIN.WORKERS,
        seed=cfg.RNG_SEED or 0,
    )


def _per_host_batch(per_chip_batch: int) -> int:
    """BATCH_SIZE is per-chip (the reference's per-GPU meaning,
    README.md:197); each host feeds all its local chips."""
    n_local = jax.local_device_count()
    return per_chip_batch * n_local
