"""Trainer: mesh-data-parallel training and evaluation.

Capability mirror of the reference trainer (ref: /root/reference/distribuuuu/
trainer.py): ``train_model`` / ``test_model`` orchestration, per-epoch LR,
cross-replica metrics, best-tracking, epoch checkpoints with auto-resume.

TPU-first redesign of the hot loop (ref call stack: SURVEY.md §3.1):
  - One jitted ``train_step`` holds forward, loss, backward, optimizer
    update, and metric computation. The global batch is sharded over the
    ``data`` mesh axis and params are replicated, so XLA compiles the
    gradient allreduce into the step (the DDP-bucket/NCCL path,
    ref: trainer.py:134, disappears into the compiled program and rides ICI).
  - BN stats are computed over the global batch in-graph — SyncBatchNorm
    (ref: trainer.py:131) by construction.
  - Metrics are global means computed in-graph; the host fetches them at
    PRINT_FREQ instead of the reference's `.item()` + extra allreduce every
    step (ref perf hazard: trainer.py:51-55), so steps dispatch
    asynchronously back-to-back.
  - The ragged final eval batch is masked in-graph instead of silently
    double-counting DistributedSampler padding.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from distribuuuu_tpu import models
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.data import (
    construct_train_loader,
    construct_val_loader,
    device_prefetch,
)
from distribuuuu_tpu.models.layers import head_dtype, resolve_dtype
from distribuuuu_tpu.parallel import (
    mesh as mesh_lib,
    sharding as sharding_lib,
    tp,
)
from distribuuuu_tpu.parallel.partition import (
    lowering as partition_lowering,
    specs as partition_specs,
    topology as partition_topology,
)
# The step builders and TrainState live in the partition lowering
# (parallel/partition/lowering.py) — ONE step body for every topology;
# re-exported here so the long-standing call sites (tests, tools, serve)
# keep their spelling.
from distribuuuu_tpu.parallel.partition.lowering import (  # noqa: F401
    TrainState,
    make_eval_step,
    make_scan_train_step,
    make_train_step,
)
from distribuuuu_tpu import asyncplane
from distribuuuu_tpu.asyncplane import compile_cache, sequencer
from distribuuuu_tpu.resilience import manifest as manifest_lib, supervisor
from distribuuuu_tpu import telemetry
from distribuuuu_tpu.telemetry import (
    costmodel,
    runtime as telemetry_runtime,
    spans as telemetry_spans,
)
from distribuuuu_tpu.utils import checkpoint as ckpt
from distribuuuu_tpu.utils import faults
from distribuuuu_tpu.utils import preempt
from distribuuuu_tpu.utils.jsonlog import (
    metrics_log,
    setup_metrics_log,
    timeline_log,
)
from distribuuuu_tpu.utils.logger import get_logger, setup_logger
from distribuuuu_tpu.utils.meters import AverageMeter, construct_meters
from distribuuuu_tpu.utils.metrics import count_parameters
from distribuuuu_tpu.utils.optim import construct_optimizer, set_lr
from distribuuuu_tpu.utils.schedules import get_epoch_lr
from distribuuuu_tpu.utils.seed import setup_env, setup_seed


def check_trainer_mesh():
    """Validate the configured MESH stanza BEFORE any expensive
    init/compile.

    Delegates to the partition-layer topology registry
    (parallel/partition/topology.py): one capability table serves the
    trainer, the dryrun sweep, and the YAML stanza gate, and its errors
    are capability-derived — a stanza is refused because a named rule is
    broken, never because a code path happens to be missing. Compositions
    the old scattered refusals blocked without cause (ZeRO-3 under PP; a
    dp×tp×ep mesh) now validate and lower.
    """
    supervisor.validate_policy(cfg.TRAIN.NONFINITE)
    return partition_topology.from_cfg(cfg)


def bn_group_from_cfg() -> int:
    """BN statistic regime from the config (honors ``MODEL.SYNCBN``,
    ref: trainer.py:131 + config.py:14). ``SYNCBN True`` ⇒ 0 = global-batch
    stats (SyncBatchNorm). ``False`` (the reference default for every
    published baseline) ⇒ ghost groups of ``MODEL.BN_GROUP`` samples,
    defaulting to ``TRAIN.BATCH_SIZE`` — the reference's per-GPU BN batch."""
    if cfg.MODEL.SYNCBN:
        return 0
    return cfg.MODEL.BN_GROUP or cfg.TRAIN.BATCH_SIZE


def build_model_from_cfg(topology=None):
    """Build the configured arch (≙ models.build_model + timm fallback,
    ref: trainer.py:117-128 — the zoo here is closed, no fallback needed).

    Mesh-dependent construction (ring attention, pipeline stages, MoE
    axis/mesh threading) reads the RESOLVED topology
    (parallel/partition/topology.py) rather than raw ``cfg.MESH``
    integers, so ``-1`` wildcards and the dedicated ``expert`` axis
    resolve identically here and in the lowering."""
    if topology is None:
        topology = partition_topology.from_cfg(cfg)
    kwargs = dict(
        num_classes=cfg.MODEL.NUM_CLASSES,
        dtype=resolve_dtype(cfg.DEVICE.COMPUTE_DTYPE),
    )
    if not cfg.MODEL.ARCH.startswith(("vit", "gpt")):
        # every CNN arch in the zoo normalizes with BN (the transformer
        # families — ViT, GPT — are LayerNorm-only)
        kwargs["bn_group"] = bn_group_from_cfg()
    if cfg.MODEL.ARCH.startswith(
        ("resnet", "resnext", "wide_resnet", "botnet", "densenet")
    ):
        kwargs["s2d_stem"] = cfg.DEVICE.S2D_STEM
    if cfg.MODEL.ARCH.startswith(("resnet", "resnext", "wide_resnet")):
        # remat-for-traffic on the bus-bound step (PERF.md roofline):
        # recompute stage 1-2 block activations in the backward instead of
        # storing them (models/resnet.py). Exact same math.
        kwargs["remat"] = bool(cfg.TRAIN.REMAT)
    elif cfg.TRAIN.REMAT:
        raise ValueError(
            f"TRAIN.REMAT targets the resnet/resnext/wide_resnet family "
            f"(stages 1-2 rematerialization); {cfg.MODEL.ARCH!r} does not "
            "take the knob (densenet always remats its dense layers) — "
            "refusing rather than silently measuring an unchanged step"
        )
    if cfg.MODEL.ARCH == "botnet50":
        # the attention grid follows the input size; each stride-2 op maps
        # n → ceil(n/2), so the stride-16 backbone gives ceil(IM_SIZE/16).
        # The reference instead hard-asserts 224 inputs (ref: botnet.py:270-271)
        fmap = max(1, -(-cfg.TRAIN.IM_SIZE // 16))
        kwargs["fmap_size"] = (fmap, fmap)
        kwargs["attn_impl"] = cfg.DEVICE.ATTN_IMPL
    if cfg.MODEL.ARCH.startswith("gpt"):
        # decoder-only LM (models/gpt.py): token batches, causal attention,
        # context length from LM.SEQ_LEN, vocab = MODEL.NUM_CLASSES (the
        # tokenizer's size — token-shard manifests are checked against it).
        # Same MoE knob plumbing as the ViT family; the partition layer
        # places everything from the LM spec-table rules + annotations.
        kwargs["seq_len"] = int(cfg.LM.SEQ_LEN)
        if topology.seq > 1:
            # sequence-sharded causal LM (ISSUE 19): causal ring attention
            # over the seq axis — the exact ViT wiring (the blocks are
            # shared modules), with the token dim of every batch leaf
            # declared over ``seq`` (specs.TOKEN_BATCH_TABLE). The ring
            # shard_map splits the token dim into EQUAL blocks; an uneven
            # dim would silently rest replicated on this jax line, so the
            # divisibility refusals carry the arithmetic.
            if int(cfg.LM.SEQ_LEN) % topology.seq:
                raise ValueError(
                    f"MESH.SEQ={topology.seq} does not divide LM.SEQ_LEN="
                    f"{int(cfg.LM.SEQ_LEN)} ({int(cfg.LM.SEQ_LEN)} % "
                    f"{topology.seq} = "
                    f"{int(cfg.LM.SEQ_LEN) % topology.seq}) — the causal "
                    "ring rotates equal K/V blocks per seq rank; use an "
                    "LM.SEQ_LEN that is a multiple of MESH.SEQ (e.g. "
                    f"{-(-int(cfg.LM.SEQ_LEN) // topology.seq) * topology.seq}"
                    ") or a smaller seq axis"
                )
            impl = (
                "ulysses" if cfg.DEVICE.ATTN_IMPL == "ulysses" else "ring"
            )
            kwargs["attn_impl"] = impl
            kwargs["mesh"] = mesh_lib.mesh_from_cfg(cfg)
        elif cfg.DEVICE.ATTN_IMPL in ("flash", "blockwise"):
            kwargs["attn_impl"] = cfg.DEVICE.ATTN_IMPL
        elif cfg.DEVICE.ATTN_IMPL in ("ring", "ulysses"):
            raise ValueError(
                f"DEVICE.ATTN_IMPL={cfg.DEVICE.ATTN_IMPL!r} needs a "
                "sequence-sharded mesh: set MESH.SEQ > 1"
            )
        elif cfg.DEVICE.ATTN_IMPL not in ("auto", "xla"):
            raise ValueError(
                f"DEVICE.ATTN_IMPL={cfg.DEVICE.ATTN_IMPL!r}: gpt archs "
                "accept 'auto'/'xla' (dense causal), 'flash', "
                "'blockwise', or MESH.SEQ>1 for ring/ulysses "
                "sequence-sharded attention"
            )
        if cfg.MODEL.ARCH.endswith("_moe"):
            kwargs["moe_experts"] = cfg.MODEL.MOE.NUM_EXPERTS
            kwargs["moe_top_k"] = cfg.MODEL.MOE.TOP_K
            kwargs["moe_every"] = cfg.MODEL.MOE.EVERY
            kwargs["moe_impl"] = cfg.MODEL.MOE.IMPL
            kwargs["moe_capacity_factor"] = cfg.MODEL.MOE.CAPACITY_FACTOR
            kwargs["moe_axis"] = topology.moe_axis()
            if topology.expert > 1 or topology.model > 1:
                kwargs["mesh"] = mesh_lib.mesh_from_cfg(cfg)
    if cfg.MODEL.ARCH.startswith("vit"):
        # seq axis populated means sequence-sharded attention: route
        # through ring attention over the seq axis. On a single chip,
        # DEVICE.ATTN_IMPL=blockwise selects O(L·chunk)-memory exact
        # attention (ops.ring_attention.blockwise_attention) for
        # high-resolution inputs. Dense XLA attention otherwise.
        if topology.seq > 1:
            kwargs["attn_impl"] = "ring"
            kwargs["mesh"] = mesh_lib.mesh_from_cfg(cfg)
        elif cfg.DEVICE.ATTN_IMPL in ("blockwise", "flash"):
            kwargs["attn_impl"] = cfg.DEVICE.ATTN_IMPL
        elif cfg.DEVICE.ATTN_IMPL == "auto":
            # per-shape resolution at trace time (models/vit.Attention):
            # Pallas flash kernel for long sequences on TPU, dense XLA below
            kwargs["attn_impl"] = "auto"
        elif cfg.DEVICE.ATTN_IMPL in ("ring", "ulysses"):
            raise ValueError(
                f"DEVICE.ATTN_IMPL={cfg.DEVICE.ATTN_IMPL!r} needs a "
                "sequence-sharded mesh: set MESH.SEQ > 1"
            )
        elif cfg.DEVICE.ATTN_IMPL != "xla":
            raise ValueError(
                f"DEVICE.ATTN_IMPL={cfg.DEVICE.ATTN_IMPL!r}: ViT archs "
                "accept 'auto', 'xla' (dense), 'flash' (Pallas kernel), "
                "'blockwise', or MESH.SEQ>1 for ring attention"
            )
        if topology.pipe > 1:
            # GPipe pipeline over the pipe axis (models/vit.PipelinedViT)
            kwargs["pipe_stages"] = topology.pipe
            kwargs["pipe_microbatches"] = cfg.MESH.MICROBATCH
            kwargs["mesh"] = mesh_lib.mesh_from_cfg(cfg)
        if cfg.MODEL.ARCH.endswith("_moe"):
            # expert parallelism: tensors/dispatch ride the dedicated
            # ``expert`` axis when MESH.EXPERT > 1 (composes with TP on a
            # 3-axis dp×tp×ep mesh), the ``model`` axis otherwise (the
            # legacy layout — EP and TP time-share one axis)
            kwargs["moe_experts"] = cfg.MODEL.MOE.NUM_EXPERTS
            kwargs["moe_top_k"] = cfg.MODEL.MOE.TOP_K
            kwargs["moe_every"] = cfg.MODEL.MOE.EVERY
            kwargs["moe_impl"] = cfg.MODEL.MOE.IMPL
            kwargs["moe_capacity_factor"] = cfg.MODEL.MOE.CAPACITY_FACTOR
            kwargs["moe_axis"] = topology.moe_axis()
            if topology.expert > 1 or topology.model > 1:
                kwargs["mesh"] = mesh_lib.mesh_from_cfg(cfg)
    model = models.build_model(cfg.MODEL.ARCH, **kwargs)
    if (
        topology.seq > 1
        and kwargs.get("attn_impl") == "ulysses"
        and int(getattr(model, "num_heads", 0)) % topology.seq
    ):
        heads = int(model.num_heads)
        raise ValueError(
            f"MESH.SEQ={topology.seq} does not divide num_heads={heads} "
            f"({heads} % {topology.seq} = {heads % topology.seq}) for "
            "DEVICE.ATTN_IMPL='ulysses' — the all-to-all re-shards "
            "sequence to heads, so each seq rank needs an equal head "
            "slice; use ring attention (the sp default) or an arch whose "
            "head count MESH.SEQ divides"
        )
    return model


def create_train_state(model, key, mesh, im_size: int, layout=None) -> TrainState:
    """Initialize params/stats/optimizer laid out over the mesh.

    Params are placed by their ``nn.with_partitioning`` metadata: replicated
    by default (≙ DDP's init broadcast, ref: trainer.py:134) and sharded over
    the ``model`` axis where a kernel is annotated (tensor parallelism —
    collapses to replication at MESH.MODEL=1). The optimizer's momentum
    buffers inherit the param layout through GSPMD propagation. With
    ``MESH.ZERO`` on, optimizer state (and at stage 3 the params) rest in
    the ZeRO layout instead. ``layout`` accepts a precomputed
    ``_state_layout`` result so callers that also need it for the train
    step don't trace the abstract init twice.
    """
    shardings = layout or _state_layout(model, mesh, im_size)
    optimizer = construct_optimizer()
    repl = sharding_lib.replicate(mesh)
    # the model's declared init dummy (token models declare their own —
    # models/gpt.py dummy_input; image models get the standard image dummy)
    dummy = partition_specs.model_dummy_input(model, im_size)

    def init_all(key):
        variables = flax.linen.meta.unbox(model.init(key, dummy, train=False))
        params = jax.lax.with_sharding_constraint(
            variables["params"], shardings["params"]
        )
        # stats-free models (e.g. ViT — LayerNorm only) have no batch_stats
        bs = variables.get("batch_stats", {})
        stats = jax.lax.with_sharding_constraint(
            bs, jax.tree.map(lambda _: repl, bs)
        )
        opt_state = tp.constrain_like(
            optimizer.init(params), params, shardings["opt"]
        )
        return TrainState(
            params=params,
            batch_stats=stats,
            opt_state=opt_state,
            step=jnp.int32(0),
            key=key,
        )

    return jax.jit(init_all)(key)


def _state_layout(model, mesh, im_size: int) -> dict:
    """Resolved NamedSharding trees for the configured layout regime:
    ``{"params", "opt", "grads"}`` — param-shaped trees, from the
    partition spec layer (parallel/partition/specs.state_layout: base
    declarations + the ZeRO transform per ``cfg.MESH.ZERO``, every
    derived leaf spec validated before GSPMD sees it)."""
    return partition_specs.state_layout(model, mesh, im_size, cfg.MESH.ZERO)


def effective_topk() -> int:
    """TOPK clamped to the class count, so 'Acc@k' labels match the math."""
    return min(cfg.TRAIN.TOPK, cfg.MODEL.NUM_CLASSES)


class _ProfilerWindow:
    """jax.profiler capture over steps [START, START+NUM) of the first
    *executed* epoch (auto-resumed runs profile their first epoch too)."""

    def __init__(self, epoch: int, first_epoch: int):
        self.active = False
        self.started = False
        self.enabled = (
            cfg.PROF.ENABLED and epoch == first_epoch and mesh_lib.is_primary()
        )
        if self.enabled and cfg.PROF.NUM_STEPS < 1:
            get_logger().warning(
                "PROF.NUM_STEPS=%d < 1; profiling disabled", cfg.PROF.NUM_STEPS
            )
            self.enabled = False
        if self.enabled:
            import os

            self.trace_dir = cfg.PROF.DIR or os.path.join(cfg.OUT_DIR, "profile")
            self.first = cfg.PROF.START_STEP
            self.last = cfg.PROF.START_STEP + cfg.PROF.NUM_STEPS

    def begin(self, it):
        # >= not ==: in folded mode ``it`` advances in fold-sized jumps, so
        # the window opens at the first call boundary at/after START_STEP
        if self.enabled and not self.started and it >= self.first:
            jax.profiler.start_trace(self.trace_dir)
            self.active = self.started = True

    def _stop(self, state):
        # drain the async dispatch queue so the trace holds real device work
        jax.block_until_ready(state.params)
        jax.profiler.stop_trace()
        self.active = False
        get_logger().info("profiler trace written to %s", self.trace_dir)

    def end(self, it, state):
        # >= not ==: close at the first call boundary covering the window end
        if self.active and it + 1 >= self.last:
            self._stop(state)

    def finish(self, state):
        """Epoch ended before the window did — close the trace anyway, and
        diagnose a window that never started (START_STEP past the epoch)."""
        if self.active:
            get_logger().warning(
                "profiler window truncated by epoch end (wanted steps "
                "[%d, %d))", self.first, self.last,
            )
            self._stop(state)
        elif self.enabled and not self.started:
            get_logger().warning(
                "profiler never started: PROF.START_STEP=%d not reached "
                "(epoch has fewer batches?) — no trace written", self.first,
            )


def _emit_batch_spans(phase: str, epoch: int, batch: int, tl: dict) -> None:
    """Per-rank wait/h2d/step spans for one dispatched batch, from the
    stage stamps the loop already measured (telemetry/spans.py — the
    write happens AFTER every measured interval closed, so telemetry
    never sits inside its own numbers). Unlike the primary-only
    ``kind="timeline"`` records, these land in EVERY rank's sink: the
    cross-rank step percentiles and straggler skew in
    tools/run_report.py come from exactly these spans."""
    attrs = {"phase": phase, "epoch": epoch, "batch": batch}
    if "get0" in tl and "get1" in tl:
        telemetry_spans.emit_span(
            "wait", tl["get0"], tl["get1"], track="pipeline", **attrs
        )
    if "put0" in tl and "put1" in tl:
        telemetry_spans.emit_span(
            "h2d", tl["put0"], tl["put1"], track="pipeline", **attrs
        )
    if "step0" in tl and "step1" in tl:
        telemetry_spans.emit_span(
            "step", tl["step0"], tl["step1"], track="pipeline",
            n=tl.get("n", 0), **attrs,
        )


def _step_spans_on() -> bool:
    return telemetry_spans.enabled() and cfg.TELEMETRY.STEP_SPANS


def _capture_step_cost(step_fn, state, batch, *, label: str, phase: str,
                       steps_per_call: int = 1, with_memory: bool | None = None,
                       memory_only: bool = False) -> None:
    """XLA cost-model ledger for one step program (telemetry/costmodel.py):
    at the FIRST dispatch — state not yet donated, the live (state, batch)
    supply exact shapes/shardings — lower the jitted step and emit
    cost.step / cost.memory / cost.roofline records. Once per label per
    process (costmodel dedups); never raises."""
    if not (telemetry_spans.enabled() and cfg.TELEMETRY.COSTMODEL):
        return
    # every leading dim of the image leaf is batch-like: (batch,...) /
    # (fold, batch, ...) / (fold, accum, micro, ...) — their product is
    # the examples per compiled call. Token batches (the LM — integer
    # [..., seq]) have ONE trailing payload dim instead of the image's
    # three; "images" then counts sequences (run_report's lm section
    # multiplies by seq len for tokens/s).
    img = batch["image"]
    lead = (
        img.shape[:-1]
        if jnp.issubdtype(img.dtype, jnp.integer)
        else img.shape[:-3]
    )
    images_per_call = 1
    for d in lead:
        images_per_call *= int(d)
    if with_memory is None:
        with_memory = cfg.TELEMETRY.COSTMODEL_MEMORY
    costmodel.capture_step(
        step_fn, (state, batch), label=label, phase=phase,
        images=max(1, images_per_call // max(1, steps_per_call)),
        steps_per_call=steps_per_call, arch=cfg.MODEL.ARCH,
        with_memory=with_memory, memory_only=memory_only,
    )


def train_epoch(loader, mesh, state, train_step, epoch: int, logger,
                first_epoch: int = 0, scan_step=None):
    """One epoch of the hot loop (ref: trainer.py:14-64).

    With ``TRAIN.STEPS_PER_CALL > 1`` (``scan_step`` provided) full groups of
    batches dispatch as one compiled ``lax.scan`` call; the ragged tail falls
    back to ``train_step``. Metric fetch still happens at PRINT_FREQ batch
    granularity (rounded up to the fold size); the profiler window rounds to
    call boundaries.

    Returns ``(state, interrupted, batches_done)``: with
    ``TRAIN.PREEMPT_SAVE`` on, a SIGTERM (utils/preempt.py) ends the epoch
    at the next dispatch boundary with ``interrupted=True`` so the caller
    can write the mid-epoch checkpoint; ``batches_done`` is the absolute
    batch cursor (counting any resume-skipped prefix), which the shards
    pipeline persists for exact mid-epoch resume.

    When the loader was armed by ``load_state_dict`` (a restored shards
    cursor for THIS epoch), iteration skips the already-trained prefix —
    the epoch continues at the exact next batch instead of re-running.
    """
    lr = get_epoch_lr(epoch)
    set_lr(state.opt_state, lr)  # epoch-granular LR (ref: trainer.py:25-26)
    loader.set_epoch(epoch)  # reshuffle shards (ref: trainer.py:33)
    num_batches = len(loader)
    # exact mid-epoch resume (DATA.FORMAT=shards): batches [0, start) were
    # consumed and trained by the preempted run — continue, don't re-run
    start_batch = getattr(loader, "resume_skip", lambda e: 0)(epoch)
    if start_batch and mesh_lib.is_primary():
        logger.info(
            "exact mid-epoch resume: continuing epoch %d at batch %d/%d "
            "(restored global cursor)",
            epoch + 1, start_batch + 1, num_batches,
        )
    watch_preemption = cfg.TRAIN.PREEMPT_SAVE
    interrupted = False
    # multi-host: the cross-host flag agreement is a blocking collective,
    # so run it only every Nth window (deterministic sites — every process
    # reaches the same ones, exit stays agreed). Single-process reads the
    # local bool — free, so check every window.
    preempt_check_every = 1 if jax.process_count() == 1 else 8
    windows_seen = 0
    fold = max(1, cfg.TRAIN.STEPS_PER_CALL) if scan_step is not None else 1
    accum = max(1, cfg.TRAIN.GRAD_ACCUM_STEPS)

    def put_batch(hb):
        if accum > 1:
            return sharding_lib.shard_micro_batch(mesh, hb, accum)
        return sharding_lib.shard_batch(mesh, hb)

    def put_stacked(hb):
        if accum > 1:
            return sharding_lib.shard_stacked_micro_batch(mesh, hb, accum)
        return sharding_lib.shard_stacked_batch(mesh, hb)
    batch_time, data_time, losses, top1, topk_m, progress = construct_meters(
        num_batches, f"Epoch[{epoch + 1}/{cfg.OPTIM.MAX_EPOCH}]", effective_topk()
    )
    prof = _ProfilerWindow(epoch, first_epoch)
    pending = []  # (n_steps, device metrics) awaiting async fetch
    n_buffered = 0  # fold slots filled since the last dispatch
    done = start_batch  # absolute batches dispatched (incl. skipped prefix)

    # dispatch-MoE only: fraction of routed assignments lost to capacity
    moe_dropped = AverageMeter("MoEDrop", ":.4f")

    # non-finite policy enforcement at flush granularity (the guard inside
    # the step already annotated/skipped in-graph; this is the host half —
    # count+log for "skip", raise for "raise"/"rollback")
    nf_mon = supervisor.NonFiniteMonitor(
        str(cfg.TRAIN.NONFINITE), epoch, logger
    )
    # stall watchdog: a wedged collective or hung storage flags instead of
    # hanging silently (TRAIN.STALL_TIMEOUT seconds; 0 = no thread)
    heartbeat = supervisor.Heartbeat(cfg.TRAIN.STALL_TIMEOUT, logger)

    def flush_pending():
        for n, m in pending:
            if n == 1:
                if nf_mon.observe(
                    float(m["loss"]), float(m.get("nonfinite", 0.0)), done
                ):
                    continue  # skipped in-graph — keep it out of the meters
                losses.update(float(m["loss"]))
                top1.update(float(m["top1"]))
                topk_m.update(float(m["topk"]))
                if "moe_dropped" in m:
                    moe_dropped.update(float(m["moe_dropped"]))
            else:  # stacked (fold,) metrics from a scan call
                nfs = np.asarray(
                    m.get("nonfinite", np.zeros(n))
                ).reshape(-1)
                for j, (ls, t1, tk) in enumerate(zip(
                    np.asarray(m["loss"]), np.asarray(m["top1"]),
                    np.asarray(m["topk"]),
                )):
                    if nf_mon.observe(float(ls), float(nfs[j]), done):
                        continue
                    losses.update(float(ls))
                    top1.update(float(t1))
                    topk_m.update(float(tk))
                if "moe_dropped" in m:
                    for dv in np.asarray(m["moe_dropped"]).reshape(-1):
                        moe_dropped.update(float(dv))
        pending.clear()

    def maybe_print():
        if done % cfg.TRAIN.PRINT_FREQ < fold or done == num_batches:
            flush_pending()
            if mesh_lib.is_primary():
                eta = progress.get_eta(
                    done,
                    (num_batches - done)
                    + (cfg.OPTIM.MAX_EPOCH - epoch - 1) * num_batches,
                )
                logger.info("%s  LR %.5f  ETA %s", progress.display(done), lr, eta)
                extra = (
                    {"moe_dropped": moe_dropped.avg} if moe_dropped.count else {}
                )
                metrics_log(
                    "train", epoch=epoch + 1, batch=done, loss=losses.avg,
                    top1=top1.avg, topk=topk_m.avg, lr=lr,
                    batch_time=batch_time.avg, data_time=data_time.avg,
                    **extra,
                )

    def preempt_break(batches_done: int) -> bool:
        """Preemption check at window granularity: requested_global() makes
        every process agree on the exit boundary (the save is collective).
        A COMPLETED epoch never reports interrupted — it falls through to
        the normal validate/save path (re-running a fully-trained epoch
        from its own end state would double-train it)."""
        nonlocal windows_seen, interrupted
        windows_seen += 1
        if (
            watch_preemption
            and batches_done < num_batches
            and windows_seen % preempt_check_every == 0
            and preempt.requested_global()
        ):
            flush_pending()
            if mesh_lib.is_primary():
                logger.warning(
                    "preemption signaled — leaving epoch %d at batch %d/%d",
                    epoch + 1, batches_done, num_batches,
                )
            interrupted = True
            return True
        return False

    emit_timeline = cfg.TRAIN.TIMELINE and mesh_lib.is_primary()
    emit_spans = _step_spans_on()
    try:
        if fold > 1:
            # Two preallocated (fold, batch, ...) host buffers, ping-ponged per
            # dispatch: device_put may still be reading buffer A asynchronously
            # while the next fold fills buffer B. Before REFILLING a buffer,
            # fence on the device batch previously created from it — readiness
            # implies the H2D transfer has consumed the host memory (near-zero
            # cost in steady state; without it a deep dispatch backlog could
            # overwrite a buffer a pending transfer is still reading, silently
            # corrupting a batch). No per-batch timeline records in this mode
            # (stage boundaries are fold-granular); STEPS_PER_CALL 1 is the
            # attribution mode.
            stack_bufs, buf_idx = None, 0
            inflight = [None, None]  # device batch last created from each buffer
            end = time.perf_counter()
            win_start = end  # start of the current fold window (incl. buffering)
            for it, host_batch in enumerate(loader):
                abs_it = start_batch + it  # loader skipped the resumed prefix
                heartbeat.beat(f"epoch {epoch + 1} batch {abs_it}")
                faults.maybe_stall(epoch, abs_it)  # injection no-ops (FAULTS.*)
                faults.maybe_kill(epoch, abs_it)
                faults.maybe_preempt(epoch, abs_it)
                faults.maybe_recompile(epoch, abs_it)
                faults.maybe_slowdown(epoch, abs_it)
                data_time.update(time.perf_counter() - end)
                is_last = abs_it + 1 == num_batches
                # copy into the preallocated fold slot NOW (spreads the host
                # memcpy across the fold window, overlapped with the device
                # executing the previous call) instead of np.stack-ing the
                # whole fold on the dispatch iteration
                if stack_bufs is None:
                    stack_bufs = [
                        jax.tree.map(
                            lambda x: np.empty(
                                (fold,) + np.shape(x), np.asarray(x).dtype
                            ),
                            host_batch,
                        )
                        for _ in range(2)
                    ]
                stack_buf = stack_bufs[buf_idx]
                if n_buffered == 0 and inflight[buf_idx] is not None:
                    jax.block_until_ready(inflight[buf_idx])
                    inflight[buf_idx] = None
                jax.tree.map(
                    lambda buf, x: buf.__setitem__(n_buffered, x),
                    stack_buf, host_batch,
                )
                n_buffered += 1
                if n_buffered < fold and not is_last:
                    end = time.perf_counter()
                    continue
                n = n_buffered
                if n == fold:
                    batch = put_stacked(stack_buf)
                    inflight[buf_idx] = batch
                    if "train_step" not in costmodel._seen_labels:
                        # flops from the PER-STEP program (XLA cost
                        # analysis counts a lax.scan body once regardless
                        # of trip count — the folded program cannot
                        # source per-step flops); lower-only, no compile
                        _capture_step_cost(
                            train_step, state,
                            put_batch(jax.tree.map(
                                lambda buf: buf[0], stack_buf
                            )),
                            label="train_step", phase="train",
                            with_memory=False,
                        )
                    # HBM footprint of the folded program actually
                    # running (memory_analysis is per-executable — real)
                    _capture_step_cost(
                        scan_step, state, batch, label="train_fold",
                        phase="train", steps_per_call=fold,
                        memory_only=True,
                    )
                    prof.begin(done)
                    # token-ordered when a second dispatch stream is
                    # active (asyncplane/sequencer.py); pass-through with
                    # one attribute read otherwise
                    state, metrics = sequencer.dispatch(
                        sequencer.TRAIN_STREAM, scan_step, state, batch
                    )
                    prof.end(done + fold - 1, state)
                    pending.append((fold, metrics))
                else:  # ragged tail: per-step dispatch
                    for i in range(n):
                        hb = jax.tree.map(lambda buf: buf[i], stack_buf)
                        b = put_batch(hb)
                        _capture_step_cost(
                            train_step, state, b, label="train_step",
                            phase="train",
                        )
                        prof.begin(done + i)
                        state, metrics = sequencer.dispatch(
                            sequencer.TRAIN_STREAM, train_step, state, b
                        )
                        prof.end(done + i, state)
                        pending.append((1, metrics))
                done += n
                n_buffered = 0
                buf_idx ^= 1
                # per-BATCH time over the whole window (incl. the buffering
                # iterations) so display/ETA keep their per-batch meaning
                now = time.perf_counter()
                if emit_spans:
                    # folded dispatch has no per-step stamps; one span per
                    # window (n steps) — run_report derives per-step time
                    # as dur/n when a run has only fold_window spans
                    telemetry_spans.emit_span(
                        "fold_window", win_start, now, track="pipeline",
                        phase="train", epoch=epoch + 1,
                        batch=done - n, n=n,
                    )
                batch_time.update((now - win_start) / n, n=n)
                win_start = now
                end = time.perf_counter()
                maybe_print()
                if preempt_break(done):
                    break
        else:
            # Per-step dispatch through the device-side prefetch ring
            # (data/loader.device_prefetch): the H2D transfer of batches
            # it+1..it+depth is dispatched while the step for batch `it` runs,
            # so transfer never serializes behind the step; depth 0 restores
            # the serial put-then-step order. Results are value-bit-identical
            # at every depth (same put/step order — tests/test_overlap.py).
            # Each dispatched batch leaves one kind="timeline" record with its
            # stage-boundary timestamps (tools/overlap_report.py attributes
            # the epoch wall from them).
            depth = max(0, cfg.TRAIN.PREFETCH_DEVICE)
            end = time.perf_counter()
            for it, batch, tl in device_prefetch(loader, put_batch, depth):
                abs_it = start_batch + it  # loader skipped the resumed prefix
                heartbeat.beat(f"epoch {epoch + 1} batch {abs_it}")
                faults.maybe_stall(epoch, abs_it)  # injection no-ops (FAULTS.*)
                faults.maybe_kill(epoch, abs_it)
                faults.maybe_preempt(epoch, abs_it)
                faults.maybe_recompile(epoch, abs_it)
                faults.maybe_slowdown(epoch, abs_it)
                data_time.update(tl["get1"] - tl["get0"])
                _capture_step_cost(
                    train_step, state, batch, label="train_step",
                    phase="train",
                )
                prof.begin(abs_it)
                tl["step0"] = time.perf_counter()
                state, metrics = sequencer.dispatch(
                    sequencer.TRAIN_STREAM, train_step, state, batch
                )
                tl["step1"] = time.perf_counter()
                prof.end(abs_it, state)
                pending.append((1, metrics))
                done += 1
                batch_time.update(time.perf_counter() - end)
                end = time.perf_counter()
                if emit_spans:
                    _emit_batch_spans("train", epoch + 1, abs_it, tl)
                if emit_timeline:
                    timeline_log(
                        "train", epoch + 1, abs_it, tl.pop("n", 0), **tl
                    )
                maybe_print()
                if preempt_break(done):
                    break
        prof.finish(state)
    finally:
        heartbeat.stop()
    return state, interrupted, done


def validate(loader, mesh, state, eval_step, epoch: int, logger,
             quiet: bool = False, watch_preemption: bool | None = None):
    """Full evaluation pass; returns ``(top1, topk, loss, samples)``
    (ref: trainer.py:67-103), or ``None`` if preemption was signaled
    mid-eval (``TRAIN.PREEMPT_SAVE`` — the caller persists state and
    exits inside the grace window rather than finishing a long eval).
    Per-batch progress at TEST.PRINT_FREQ (≙ ref validate's meter display,
    trainer.py:91-95) — totals stay on device between prints so batches
    dispatch asynchronously.

    ``quiet`` suppresses every log line and the ``kind="eval"`` record —
    the concurrent-eval worker (asyncplane/evalloop.py) runs this body
    off-thread and the MAIN thread logs the summary at join time, so the
    record order matches a synchronous run. ``watch_preemption`` False
    disables the mid-eval abandon (the concurrent path must complete:
    its result is joined before any preemption exit)."""
    if watch_preemption is None:
        watch_preemption = cfg.TRAIN.PREEMPT_SAVE
    # same collective-throttle as train_epoch: cross-host agreement only at
    # every Nth deterministic site; free local check at world size 1
    preempt_check_every = 1 if jax.process_count() == 1 else 8
    checks_seen = 0
    totals = None
    pending_print = None  # previous window's (batch_idx, totals) — async copy
    num_batches = len(loader)
    # same overlap machinery as train_epoch's per-step path (VERDICT r5
    # item 5 leftover: eval had none): the device prefetch ring dispatches
    # the H2D transfer of batches it+1..it+depth while eval_step(it) runs,
    # and each batch leaves a phase="eval" timeline record. Metric totals
    # are a pure sum — overlap order cannot change them (equivalence:
    # tests/test_overlap.py).
    emit_timeline = cfg.TRAIN.TIMELINE and mesh_lib.is_primary()
    emit_spans = _step_spans_on()
    depth = max(0, cfg.TRAIN.PREFETCH_DEVICE)
    end = time.perf_counter()
    for it, batch, tl in device_prefetch(
        loader, functools.partial(sharding_lib.shard_batch, mesh), depth
    ):
        _capture_step_cost(
            eval_step, state, batch, label="eval_step", phase="eval"
        )
        tl["step0"] = time.perf_counter()
        # eval steps do not chain through data dependencies, so under
        # the sequencer each one is dispatched fenced (outputs ready
        # before the token releases) — the eval thread absorbs the wait,
        # the train stream never fences on eval (asyncplane/sequencer.py
        # has the dispatch-ordering story); pass-through when inactive
        m = sequencer.dispatch(
            sequencer.EVAL_STREAM, eval_step, state, batch, fence=True
        )
        totals = (
            m
            if totals is None
            else jax.tree.map(jnp.add, totals, m)
        )
        tl["step1"] = time.perf_counter()
        if emit_spans:
            _emit_batch_spans("eval", epoch + 1, it, tl)
        if emit_timeline:
            timeline_log("eval", epoch + 1, it, tl.pop("n", 0), **tl)
        at_check_site = (
            watch_preemption
            and (it + 1) % cfg.TEST.PRINT_FREQ == 0
            and it + 1 < num_batches
        )
        if at_check_site:
            checks_seen += 1
        if (
            at_check_site
            and checks_seen % preempt_check_every == 0
            and preempt.requested_global()
        ):
            # deterministic check sites (same batch indices on every
            # process) — abandon the eval; the caller saves and exits
            if mesh_lib.is_primary():
                logger.warning(
                    "preemption signaled — abandoning eval at batch %d/%d",
                    it + 1, num_batches,
                )
            return None
        if (it + 1) % cfg.TEST.PRINT_FREQ == 0 and mesh_lib.is_primary() \
                and not quiet:
            # async metric fetch (same treatment the train loop gives its
            # metrics): start the host copy of THIS window's totals and log
            # the PREVIOUS window's — already landed, so reading it costs
            # nothing and eval batches keep dispatching back-to-back
            # (the blocking fetch here was the last per-N-batches host sync)
            for leaf in jax.tree.leaves(totals):
                leaf.copy_to_host_async()
            if pending_print is not None:
                pit, ptot = pending_print
                acc1_so_far = (
                    float(ptot["correct1"]) / max(float(ptot["count"]), 1.0) * 100.0
                )
                window = time.perf_counter() - end
                logger.info(
                    "Eval[%d][%d/%d]  Time %6.3f (%.3f/batch)  "
                    "Acc@1 %.3f (through batch %d)",
                    epoch + 1, it + 1, num_batches,
                    window, window / cfg.TEST.PRINT_FREQ, acc1_so_far, pit,
                )
            end = time.perf_counter()
            pending_print = (it + 1, totals)
    totals = jax.tree.map(float, totals)
    n = max(totals["count"], 1.0)
    top1 = totals["correct1"] / n * 100.0
    topk = totals["correctk"] / n * 100.0
    loss = totals["loss_sum"] / n
    if not quiet:
        log_eval_result(logger, epoch, top1, topk, loss, int(n))
    return top1, topk, loss, int(n)


def log_eval_result(logger, epoch: int, top1: float, topk: float,
                    loss: float, samples: int) -> None:
    """The eval summary line + ``kind="eval"`` record — split out so the
    concurrent-eval join path emits them from the main thread in the same
    order a synchronous run would."""
    if mesh_lib.is_primary():
        logger.info(
            "Eval[%d]  Loss %.4f  Acc@1 %.3f  Acc@%d %.3f  (%d samples)",
            epoch + 1, loss, top1, effective_topk(), topk, samples,
        )
        metrics_log(
            "eval", epoch=epoch + 1, loss=loss, top1=top1, topk=topk,
            samples=samples,
        )


def _place_like(tmpl, new):
    """Place restored arrays with the live template's dtype + layout
    (replicated, TP- or ZeRO-sharded), leaf by leaf.

    Host (numpy) leaves go through a plain sharded device_put on a
    single-process run; on MULTI-HOST they place collective-free through
    ``jax.make_array_from_callback`` (each process feeds its addressable
    shards from its own host copy) — a cross-process ``device_put``
    dispatches per-leaf gloo/ICI collectives whose enqueue order is not
    agreed across hosts, and two hosts mid-restore can interleave them
    (observed: gloo "op.preamble.length <= op.nbytes" aborts restoring a
    multi-host async save; the same dispatch-ordering hazard the
    sequencer removes from the train loop). Restored ``jax.Array``
    leaves that SPAN processes (multi-host ZeRO resume: orbax hands back
    arrays in their saved sharding, of which this process addresses only
    its slice) cannot be fetched to host at all — those reshard
    on-device through a jitted identity with the template's sharding as
    out_shardings (compiles to the minimal collective)."""

    def _place(t, n):
        dtype = getattr(t, "dtype", None)
        if isinstance(n, jax.Array) and not n.is_fully_addressable:
            return _reshard_fn(dtype, t.sharding)(n)
        sharding = getattr(t, "sharding", None)
        if sharding is None:
            # non-array template leaf — e.g. the python-float LR that
            # set_lr injects in place (a mid-run rollback resumes against
            # a live, already-mutated state): keep it host-side
            return np.asarray(n, dtype=dtype) if dtype is not None else n
        host = np.asarray(n, dtype=dtype)
        if not sharding.is_fully_addressable:
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx]
            )
        return jax.device_put(host, sharding)

    return jax.tree.map(_place, tmpl, new)


@functools.lru_cache(maxsize=None)
def _reshard_fn(dtype, sharding):
    """Jitted identity-cast keyed on (dtype, target sharding) — one
    compiled reshard program per distinct layout instead of one per leaf."""
    return jax.jit(
        lambda a: a.astype(dtype) if dtype is not None else a,
        out_shardings=sharding,
    )


def _state_tree(state: TrainState) -> dict:
    # key is intentionally excluded: it is re-derived from RNG_SEED at startup
    return {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "step": state.step,
    }


def _restore_weights(path: str, model):
    """Weights from an orbax checkpoint dir OR a torch ``.pth`` pickle
    (reference-trained weights / URL-zoo files, ref: resnet.py:23-33,
    trainer.py:204-205). Returns {"params", "batch_stats"} numpy/jax trees."""
    from distribuuuu_tpu.utils import torch_ingest

    if torch_ingest.is_torch_checkpoint(path):
        sd = torch_ingest.load_torch_state_dict(path)
        return torch_ingest.convert_state_dict(
            sd, torch_ingest.ordered_variables(model, im_size=cfg.TRAIN.IM_SIZE)
        )
    return ckpt.load_checkpoint(path)


def _with_restored_weights(state: TrainState, path: str, model) -> TrainState:
    """State with params/batch_stats replaced from ``path`` (orbax or torch),
    placed with the live layout; optimizer state and step untouched."""
    restored = _restore_weights(path, model)
    return TrainState(
        params=_place_like(state.params, restored["params"]),
        batch_stats=_place_like(state.batch_stats, restored["batch_stats"]),
        opt_state=state.opt_state,
        step=state.step,
        key=state.key,
    )


def _resume(
    state: TrainState, mesh
) -> tuple[TrainState, int, float, int | None, dict | None]:
    """Auto-resume from the last INTACT checkpoint (ref: trainer.py:143-149,
    hardened): candidates are manifest-verified newest-first, corrupt or
    partial saves are quarantined to ``*.corrupt`` and walked past
    (utils/checkpoint.find_last_valid_checkpoint), and the recorded world
    topology is compared against the live mesh — a dp=N save restores onto
    a dp=M mesh ("elastic resume": every array is re-placed onto the live
    layout by ``_place_like``; ZeRO opt-state shards reassemble through
    ``pack_opt_state``'s canonical leaf order), while a save whose param
    tree cannot feed this model is refused with the first mismatch."""
    logger = get_logger()
    path = ckpt.find_last_valid_checkpoint()
    man = manifest_lib.read_manifest(path)
    if man is not None:
        kind, detail = manifest_lib.classify_against_live(
            man, _state_tree(state), mesh
        )
        if kind == "incompatible":
            raise ckpt.CheckpointError(
                f"checkpoint {path} cannot feed the configured model: "
                f"{detail}. Match the config to the save (MODEL.ARCH / "
                "NUM_CLASSES / MOE), or start a fresh OUT_DIR."
            )
        if kind == "reshardable":
            logger.info(
                "elastic resume: saved world differs from the live one "
                "(%s) — re-placing restored arrays onto the live layout",
                detail,
            )
    restored = ckpt.load_checkpoint(path)

    params = _place_like(state.params, restored["params"])
    stats = _place_like(state.batch_stats, restored["batch_stats"])
    opt_state = state.opt_state
    if cfg.TRAIN.LOAD_OPT and "opt_state" in restored:
        try:
            # rebuild the optax structure against the LIVE optimizer first —
            # orbax restores namedtuple containers as plain dicts
            # (utils/checkpoint.pack_opt_state has the full story; before
            # r4 this mismatch made every auto-resume silently fall through
            # to a fresh optimizer)
            opt_state = _place_like(
                state.opt_state,
                ckpt.unpack_opt_state(state.opt_state, restored["opt_state"]),
            )
        except ValueError as e:  # structural mismatch from unpack_opt_state →
            # graceful weights-only fallback (utils.py:399-405). Deliberately
            # narrow: placement errors (device_put/OOM) must propagate rather
            # than silently degrade to a fresh optimizer (ADVICE r4).
            logger.warning("optimizer state not restored (%s); fresh optimizer", e)
    start_epoch = int(restored.get("epoch", -1)) + 1
    best_acc1 = float(restored.get("best_acc1", 0.0))
    pending = restored.get("pending_eval")
    pending_eval = None if pending is None else int(pending)
    # shards exact-resume cursor (save_preempt_checkpoint embedded the
    # loader's state_dict); None on epoch-boundary saves / older formats
    ds_arr = restored.get("data_state")
    data_state = None if ds_arr is None else ckpt.decode_data_state(ds_arr)
    logger.info("resumed from %s (epoch %d)", path, start_epoch)
    return (
        TrainState(
            params=params,
            batch_stats=stats,
            opt_state=opt_state,
            step=jnp.int32(int(restored.get("step", 0))),
            key=state.key,
        ),
        start_epoch,
        best_acc1,
        pending_eval,
        data_state,
    )


def check_batch_geometry(mesh, eval_only: bool = False):
    """Validate every batch-divisibility constraint before the expensive
    state init/compile, in the user's config units: grad-accum split, data
    axis sharding, GPipe microbatching (TRAIN **and** the padded eval
    batch — the val loader pads each batch to the full TEST.BATCH_SIZE, so
    an indivisible eval batch would otherwise train a whole epoch and then
    crash inside validate(), ADVICE r2), and ghost BN grouping.

    ``eval_only`` (ADVICE r3 #2): test_model() never trains, so it runs
    only the eval-batch checks — a train-invalid but eval-valid config
    (e.g. an accum setting left in a YAML) must not block evaluation.
    Returns the per-optimizer-step forward batch (None when eval_only).
    """
    data_size = dict(mesh.shape).get("data", 1)
    pipe_size = dict(mesh.shape).get("pipe", 1)
    pipe_mb = cfg.MESH.MICROBATCH or 2 * pipe_size
    # global batch = per-host × DATA GROUPS (≡ process_count in pure DP;
    # smaller when model/pipe axes span hosts — those hosts feed copies)
    _, n_groups = mesh_lib.data_process_groups(mesh)

    if not eval_only:
        accum = max(1, cfg.TRAIN.GRAD_ACCUM_STEPS)
        per_host_batch = cfg.TRAIN.BATCH_SIZE * jax.local_device_count()
        if per_host_batch % accum:
            raise ValueError(
                f"TRAIN.BATCH_SIZE={cfg.TRAIN.BATCH_SIZE} × "
                f"{jax.local_device_count()} local chips = {per_host_batch} "
                f"per host, not divisible by TRAIN.GRAD_ACCUM_STEPS={accum}"
            )
        global_micro = per_host_batch * n_groups // accum
        if accum > 1 and global_micro % data_size:
            raise ValueError(
                f"micro-batch {global_micro} (global batch "
                f"{per_host_batch * n_groups} / "
                f"TRAIN.GRAD_ACCUM_STEPS={accum}) does not shard over the "
                f"data axis of size {data_size}; raise TRAIN.BATCH_SIZE or "
                "lower GRAD_ACCUM_STEPS"
            )
        if pipe_size > 1:
            per_shard = global_micro // data_size
            if per_shard % pipe_mb:
                raise ValueError(
                    f"per-data-shard batch {per_shard} not divisible by the "
                    f"{pipe_mb} GPipe microbatches (MESH.MICROBATCH, 0 → "
                    "2×PIPE); adjust TRAIN.BATCH_SIZE or MESH.MICROBATCH"
                )
        bn_g = (
            0 if cfg.MODEL.ARCH.startswith(("vit", "gpt"))
            else bn_group_from_cfg()
        )
        if bn_g > 0 and global_micro > bn_g and global_micro % bn_g:
            # _BNCore would raise the same condition at first train-step trace
            raise ValueError(
                f"ghost BN group {bn_g} (MODEL.BN_GROUP, 0 → "
                f"TRAIN.BATCH_SIZE) does not divide the per-step forward "
                f"batch {global_micro}; adjust MODEL.BN_GROUP / "
                "TRAIN.BATCH_SIZE / GRAD_ACCUM_STEPS"
            )
    else:
        global_micro = None

    if pipe_size > 1:
        eval_global = (
            cfg.TEST.BATCH_SIZE * jax.local_device_count() * n_groups
        )
        eval_per_shard = eval_global // data_size
        # mirrors PipelinedViT's guard: below pipe_mb it falls back to the
        # math-identical sequential stage path, no error
        if eval_per_shard >= pipe_mb and eval_per_shard % pipe_mb:
            raise ValueError(
                f"per-data-shard eval batch {eval_per_shard} "
                f"(TEST.BATCH_SIZE={cfg.TEST.BATCH_SIZE}) not divisible by "
                f"the {pipe_mb} GPipe microbatches; adjust TEST.BATCH_SIZE "
                "or MESH.MICROBATCH"
            )
    return global_micro


def _arm_exact_resume(train_loader, data_state, start_epoch: int, logger):
    """Hand a restored shards cursor (``_resume``'s ``data_state``) to the
    loader so epoch ``start_epoch`` CONTINUES at the exact next batch. Any
    mismatch (format/corpus/shuffle-identity/epoch drift) degrades to the
    epoch-granular resume with a warning — exactness is best-effort, the
    resume itself never fails on a cursor."""
    if data_state is None:
        return
    if int(data_state.get("epoch", -1)) != start_epoch:
        logger.warning(
            "saved data cursor is for epoch %s but resume starts at epoch "
            "%d — re-running from batch 0",
            data_state.get("epoch"), start_epoch,
        )
        return
    try:
        skip = train_loader.load_state_dict(data_state)
    except ValueError as e:
        logger.warning(
            "mid-epoch data cursor not restored (%s) — re-running epoch %d "
            "from batch 0", e, start_epoch + 1,
        )
        return
    if mesh_lib.is_primary():
        logger.info(
            "restored shards data cursor: epoch %d resumes after %d "
            "batches (global sample cursor %d)",
            start_epoch + 1, skip, int(data_state.get("cursor", -1)),
        )


def train_model():
    """End-to-end training (ref: trainer.py:106-173)."""
    mesh_lib.apply_backend_flags(cfg.DEVICE.DETERMINISTIC or cfg.CUDNN.DETERMINISTIC)
    mesh_lib.apply_platform(cfg.DEVICE.PLATFORM)
    mesh_lib.setup_distributed()
    topo = check_trainer_mesh()
    setup_env()
    logger = setup_logger()
    # armed FAULTS.* knobs with impossible arithmetic fail HERE, naming
    # the knobs and units — not hours later at the injection point
    faults.validate_cfg()
    setup_metrics_log(cfg.OUT_DIR, primary=mesh_lib.is_primary())
    # per-rank telemetry sink (telemetry/): spans, compile events, registry
    # snapshots, mirrored resilience events — rank-local signals survive on
    # every process, unlike the primary-only metrics.jsonl above
    telemetry.setup_from_cfg(cfg, rank=jax.process_index())
    # persistent compilation cache (COMPILE_CACHE): must be applied
    # before the first jit below — a restart then loads every
    # previously-compiled step program from disk instead of recompiling
    # (counted as jit.cache_hits, not jit.compiles)
    compile_cache.setup_from_cfg(cfg)
    mesh = mesh_lib.mesh_from_cfg(cfg)
    # cost.* records carry the resolved mesh/topology so post-mortem
    # consumers attribute comm volume per mesh axis (ISSUE 9 satellite)
    costmodel.set_mesh_extras(
        {"mesh": topo.axes, "topology": topo.class_name()}
    )
    key = setup_seed()

    accum = max(1, cfg.TRAIN.GRAD_ACCUM_STEPS)
    check_batch_geometry(mesh)

    # ONE lowering for every topology (parallel/partition/lowering.py):
    # dp / dp×tp / PP / ZeRO-1/3 / EP and their compositions all build
    # from the declared specs — no per-topology step assembly left here.
    model = build_model_from_cfg(topo)
    lowered = partition_lowering.lower(
        model, construct_optimizer(), effective_topk(), mesh=mesh,
        topology=topo, im_size=cfg.TRAIN.IM_SIZE,
        fold=max(1, cfg.TRAIN.STEPS_PER_CALL), accum=accum,
    )
    layout = lowered.layout
    state = create_train_state(model, key, mesh, cfg.TRAIN.IM_SIZE, layout=layout)
    m_params, mb = count_parameters(state.params)
    logger.info(
        "model %s: %.3fM params (%.2f MB fp32), mesh %s [%s]",
        cfg.MODEL.ARCH, m_params, mb, dict(mesh.shape), topo.class_name(),
    )

    train_loader = construct_train_loader()
    val_loader = construct_val_loader()
    train_step = lowered.train_step
    scan_step = lowered.scan_step
    eval_step = lowered.eval_step

    start_epoch, best_acc1, pending_eval = 0, 0.0, None
    resumed = False
    if cfg.TRAIN.AUTO_RESUME and ckpt.has_checkpoint():
        try:
            state, start_epoch, best_acc1, pending_eval, data_state = _resume(
                state, mesh
            )
            resumed = True
            _arm_exact_resume(train_loader, data_state, start_epoch, logger)
        except ckpt.NoValidCheckpointError as e:
            # every checkpoint on disk failed verification (all quarantined
            # to *.corrupt) — recover by starting over rather than crashing
            logger.warning("%s — falling through to a fresh start", e)
    if resumed:
        pass
    elif cfg.MODEL.PRETRAINED and cfg.MODEL.WEIGHTS:
        # warm start from pretrained weights (≙ the reference's URL-zoo
        # `pretrained=True` path, ref: resnet.py:309-311 — here the file may
        # be a torch pickle or an orbax dir)
        state = _with_restored_weights(state, cfg.MODEL.WEIGHTS, model)
        logger.info("warm-started from pretrained weights %s", cfg.MODEL.WEIGHTS)
    elif cfg.MODEL.PRETRAINED:
        # The reference downloads zoo weights on PRETRAINED=True
        # (ref: resnet.py:23-33). Connectivity-guarded equivalent: fetch
        # from the URL zoo when reachable; otherwise raise the actionable
        # offline error rather than silently train from random init.
        from distribuuuu_tpu.utils import url_zoo

        path = url_zoo.fetch(cfg.MODEL.ARCH)  # raises offline / unknown
        state = _with_restored_weights(state, path, model)
        logger.info("warm-started from pretrained URL zoo: %s", path)
    elif cfg.MODEL.WEIGHTS:
        logger.warning(
            "MODEL.WEIGHTS is ignored during training unless "
            "MODEL.PRETRAINED True (evaluation uses test_net.py)"
        )

    if cfg.TRAIN.PREEMPT_SAVE:
        preempt.install()

    def _preempt_exit(path, resume_epoch):
        # a boundary save submitted just before the signal may still be
        # committing in the background — the grace window ends with every
        # manifest durable, never with a half-written directory
        asyncplane.join_commits(reason="preemption exit")
        if telemetry.enabled():  # final counters survive the preemption
            telemetry.emit_snapshot()
        if mesh_lib.is_primary():
            logger.warning(
                "preempted: state saved to %s; rerun to resume at epoch %d",
                path, resume_epoch + 1,
            )
        return best_acc1

    def _epoch_telemetry(epoch):
        """Epoch-boundary sampling: device memory stats (TPU/GPU — the
        CPU backend reports none) and one registry snapshot (recompile
        counters, IO tallies) per rank — run_report reads the last.
        With the dispatch sequencer active, its running token/fence
        aggregates land as a ``dispatch.token`` record too."""
        if not telemetry.enabled():
            return
        if cfg.TELEMETRY.MEMSTATS:
            telemetry_runtime.sample_memstats(epoch=epoch + 1)
        sequencer.emit_stats(epoch=epoch + 1)
        telemetry.emit_snapshot(epoch=epoch + 1)

    # concurrent eval (TRAIN.CONCURRENT_EVAL — asyncplane/evalloop.py):
    # validate() runs against an on-device epoch-boundary snapshot on a
    # worker thread while the next train epoch dispatches; results join
    # (with best-acc bookkeeping + the eval/epoch records) one boundary
    # later. Multi-device processes run under the dispatch sequencer
    # (asyncplane/sequencer.py): train/eval/snapshot dispatches are
    # token-ordered into one global program sequence, which removes the
    # cross-thread collective deadlock PR 10 pinned on the
    # 8-virtual-device mesh. Multi-host additionally attaches the
    # cross-host dispatch ring (asyncplane/ring.py, ISSUE 18): process 0
    # publishes its grant order through the shared OUT_DIR, followers
    # grant only in that order — two SPMD programs from two host threads
    # enqueue in ONE per-device order on EVERY host, which lifts the
    # PR 11 degrade-to-sync. ASYNC.SEQUENCER=False on multi-device stays
    # the explicit escape hatch.
    conc_eval = None
    if cfg.TRAIN.CONCURRENT_EVAL:
        if jax.device_count() > 1 and not cfg.ASYNC.SEQUENCER:
            logger.warning(
                "TRAIN.CONCURRENT_EVAL requested with "
                "ASYNC.SEQUENCER=False and device_count=%d — without "
                "token-ordered dispatch two multi-device programs can "
                "interleave their collectives per-device and deadlock; "
                "falling back to synchronous eval (re-enable "
                "ASYNC.SEQUENCER to overlap)", jax.device_count(),
            )
        else:
            if jax.device_count() > 1:
                sequencer.install(cfg.TRAIN.STALL_TIMEOUT, logger=logger)
                logger.info(
                    "dispatch sequencer active: train/eval/snapshot "
                    "dispatches token-ordered across %d devices "
                    "(ASYNC.SEQUENCER)", jax.device_count(),
                )
            if jax.process_count() > 1:
                # leader opens (fresh-clears) the ring FIRST, then every
                # host syncs, then followers attach — a follower can
                # never read a stale OPEN/watermark from a previous
                # attempt of this OUT_DIR
                from jax.experimental import multihost_utils

                ring_root = os.path.join(cfg.OUT_DIR, ".dispatch_ring")
                rank, world = jax.process_index(), jax.process_count()
                if rank == 0:
                    sequencer.install_ring(
                        ring_root, rank, world, cfg.ASYNC.RING_DEADLINE_S,
                        detach_after_s=cfg.ASYNC.BARRIER_TIMEOUT_S,
                        logger=logger,
                    )
                multihost_utils.sync_global_devices("dtpu dispatch ring open")
                if rank != 0:
                    sequencer.install_ring(
                        ring_root, rank, world, cfg.ASYNC.RING_DEADLINE_S,
                        detach_after_s=cfg.ASYNC.BARRIER_TIMEOUT_S,
                        logger=logger,
                    )
                logger.info(
                    "cross-host dispatch ring active: host %d/%d %s via "
                    "%s (deadline %.0fs — see docs/RUNBOOK.md 'Async on "
                    "a pod, for real')", rank, world,
                    "publishes the grant order" if rank == 0
                    else "follows the published order", ring_root,
                    cfg.ASYNC.RING_DEADLINE_S,
                )
            conc_eval = asyncplane.ConcurrentEval(
                lambda snap, ep: validate(
                    val_loader, mesh, snap, eval_step, ep, logger,
                    quiet=True, watch_preemption=False,
                )
            )
            logger.info(
                "concurrent eval: validate() overlaps the next train "
                "epoch; results join one boundary later"
            )

    def _ring_degraded_boundary():
        """Did ANY host miss its ring deadline this epoch? The answer is
        collective (``requested_global`` idiom) because the degraded
        boundary dispatches a different program sequence — a host-local
        decision would re-create the very cross-host inversion the ring
        exists to prevent. Safe to run a collective here: the previous
        eval has joined and the epoch's train steps are dispatched, so
        every host appends this program at the same sequence point.
        Clears the sticky flag (a persistent wedge re-flags next epoch)."""
        if not sequencer.ring_installed():
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.int32(1 if sequencer.ring_wedged() else 0)
        )
        sequencer.clear_ring_wedge()
        return bool(np.asarray(flags).sum() > 0)

    def _join_concurrent_eval():
        """Join the in-flight eval (no-op when none): emit the deferred
        eval summary + epoch record, update best-tracking, and side-write
        the ``best`` checkpoint from the eval's own snapshot — exactly
        what the synchronous boundary does, one epoch later."""
        nonlocal best_acc1
        if conc_eval is None:
            return
        joined = conc_eval.join()
        if joined is None:
            return
        ep, result, snap = joined
        if result is None:  # defensive: the worker runs with watch off
            logger.warning(
                "concurrent eval for epoch %d returned no result", ep + 1
            )
            return
        acc1, topk_v, loss, n = result
        log_eval_result(logger, ep, acc1, topk_v, loss, n)
        is_best = acc1 > best_acc1
        best_acc1 = max(acc1, best_acc1)
        if is_best:
            ckpt.save_best_checkpoint(snap.params, snap.batch_stats, ep)
        if mesh_lib.is_primary():
            logger.info(
                "epoch %d done: Acc@1 %.3f (best %.3f)",
                ep + 1, acc1, best_acc1,
            )
            metrics_log("epoch", epoch=ep + 1, acc1=acc1, best_acc1=best_acc1)

    def _finish_epoch(epoch):
        """Validate + best-track + save for a completed epoch. Returns the
        preempt-checkpoint path if the eval itself was preempted, else
        None."""
        nonlocal best_acc1
        result = validate(val_loader, mesh, state, eval_step, epoch, logger)
        if result is None:  # preempted mid-eval; epoch's training is done
            return ckpt.save_preempt_checkpoint(
                _state_tree(state), epoch + 1, best_acc1, pending_eval=epoch
            )
        acc1 = result[0]
        is_best = acc1 > best_acc1
        best_acc1 = max(acc1, best_acc1)
        ckpt.save_checkpoint(_state_tree(state), epoch, best_acc1, is_best)
        if mesh_lib.is_primary():
            logger.info(
                "epoch %d done: Acc@1 %.3f (best %.3f)",
                epoch + 1, acc1, best_acc1,
            )
            metrics_log(
                "epoch", epoch=epoch + 1, acc1=acc1, best_acc1=best_acc1
            )
        return None

    if pending_eval is not None:
        # the interrupted run finished training epoch `pending_eval` but
        # was preempted before/during its eval: validate it NOW so it gets
        # best-tracking and a real epoch checkpoint (which also supersedes
        # the preempt checkpoint we just resumed from)
        if mesh_lib.is_primary():
            logger.info(
                "running epoch %d's validation (skipped by the preemption)",
                pending_eval + 1,
            )
        path = _finish_epoch(pending_eval)
        if path is not None:  # preempted again
            return _preempt_exit(path, pending_eval + 1)
        # the eval-preempt checkpoint (named pending_eval+1, holding this
        # epoch's end state) is now fully superseded by ckpt_ep_{pending};
        # without this prune it would outrank the real checkpoints on
        # every restart and the run could never cleanly terminate
        ckpt.prune_preempts(pending_eval + 1)

    epoch = start_epoch
    rollbacks_left = max(0, int(cfg.TRAIN.MAX_ROLLBACKS))
    try:
        while epoch < cfg.OPTIM.MAX_EPOCH:
            try:
                state, interrupted, batches_done = train_epoch(
                    loader=train_loader, mesh=mesh, state=state,
                    train_step=train_step, epoch=epoch, logger=logger,
                    first_epoch=start_epoch, scan_step=scan_step)
            except supervisor.NonFiniteLossError as e:
                # TRAIN.NONFINITE=rollback: reload the last intact checkpoint
                # and re-run from there — the transient-corruption recovery.
                # A deterministic NaN re-trips and surfaces once the budget
                # (TRAIN.MAX_ROLLBACKS) is spent; "raise" propagates directly.
                if cfg.TRAIN.NONFINITE != "rollback":
                    raise
                if rollbacks_left <= 0:
                    logger.error(
                        "rollback budget exhausted (TRAIN.MAX_ROLLBACKS=%d) — "
                        "the non-finite loss reproduces from the checkpoint; "
                        "this is not transient corruption",
                        cfg.TRAIN.MAX_ROLLBACKS,
                    )
                    raise
                if not ckpt.has_checkpoint():
                    logger.error(
                        "non-finite loss before any checkpoint exists — "
                        "nothing to roll back to"
                    )
                    raise
                rollbacks_left -= 1
                logger.warning(
                    "non-finite loss at epoch %d batch ~%d — rolling back to "
                    "the last intact checkpoint (%d attempt(s) left)",
                    e.epoch + 1, e.batch, rollbacks_left,
                )
                # quiesce the async plane before reloading: the in-flight
                # eval joins (its best bookkeeping applies, then _resume
                # restores the checkpointed best), and find_last_valid joins
                # any commit still in flight
                _join_concurrent_eval()
                state, epoch, best_acc1, rb_pending, rb_ds = _resume(state, mesh)
                # the pre-epoch state's buffers were DONATED to the step calls
                # (donate_argnums=0) — its key is deleted; re-attach the live
                # base key (the value is seed-derived, identical by definition)
                state = state.replace(key=key)
                # rolling back onto a preempt save: honor its data cursor too
                _arm_exact_resume(train_loader, rb_ds, epoch, logger)
                if rb_pending is not None:
                    # rolled back onto an eval-pending preempt save: finish
                    # that epoch's validation first, as a fresh start would
                    path = _finish_epoch(rb_pending)
                    if path is not None:
                        return _preempt_exit(path, rb_pending + 1)
                    ckpt.prune_preempts(rb_pending + 1)
                continue
            watching = cfg.TRAIN.PREEMPT_SAVE
            if interrupted:
                # mid-epoch preemption: persist now; the next run's AUTO_RESUME
                # prefers this checkpoint and re-runs this epoch from it
                # (utils/preempt.py has the full story). The shards pipeline
                # additionally embeds the loader's exact global cursor, so the
                # re-run CONTINUES at batch `batches_done` instead of batch 0.
                # The previous epoch's concurrent eval joins first — its best
                # bookkeeping must ride the preempt save.
                _join_concurrent_eval()
                data_state = (
                    train_loader.state_dict(batches_done)
                    if train_loader.can_save_state()
                    else None
                )
                path = ckpt.save_preempt_checkpoint(
                    _state_tree(state), epoch, best_acc1, data_state=data_state
                )
                return _preempt_exit(path, epoch)
            if watching and preempt.requested_global():
                # signaled between the last batch and validate: the epoch is
                # COMPLETE — skip the (possibly long) validation, save the
                # finished state marked eval-pending, exit inside the grace
                # window; the resume validates it before continuing
                _join_concurrent_eval()
                path = ckpt.save_preempt_checkpoint(
                    _state_tree(state), epoch + 1, best_acc1, pending_eval=epoch
                )
                return _preempt_exit(path, epoch + 1)
            if conc_eval is not None:
                # concurrent boundary: join the PREVIOUS epoch's eval (its
                # result, best-tracking, and log records land now), commit
                # this epoch's checkpoint (async snapshot inside when
                # CHECKPOINT.ASYNC), then launch this epoch's eval — the next
                # train epoch dispatches while it runs. The boundary save
                # records best_acc1 as of the previous eval (this epoch's is
                # in flight); the best side-write itself lands at join.
                _join_concurrent_eval()
                if _ring_degraded_boundary():
                    # a host missed its ring deadline this epoch: every
                    # host (collectively agreed) runs THIS epoch's eval
                    # synchronously — graceful degradation, never a hang;
                    # the next boundary re-tries the concurrent path
                    logger.warning(
                        "dispatch ring wedged during epoch %d — running "
                        "this epoch's eval synchronously (the ring "
                        "re-arms next epoch; persistent wedges re-flag)",
                        epoch + 1,
                    )
                    path = _finish_epoch(epoch)
                    if path is not None:
                        return _preempt_exit(path, epoch + 1)
                else:
                    ckpt.save_checkpoint(
                        _state_tree(state), epoch, best_acc1, is_best=False
                    )
                    conc_eval.launch(state, epoch)
            else:
                path = _finish_epoch(epoch)
                if path is not None:  # eval was preempted (validate → None)
                    return _preempt_exit(path, epoch + 1)
            _epoch_telemetry(epoch)
            if watching and preempt.requested_global():
                # signaled during the save: ckpt_ep_{epoch} is already on
                # disk (or committing in the background — _preempt_exit
                # drains) — nothing more to persist; the in-flight eval
                # joins so its result is not lost
                _join_concurrent_eval()
                return _preempt_exit(ckpt.get_checkpoint(epoch), epoch + 1)
            epoch += 1
        # end of run: the final epoch's eval joins (best-tracking + records),
        # and the committer drains — no process exits with an uncommitted save
        _join_concurrent_eval()
        asyncplane.join_commits(reason="exit")
        return best_acc1
    finally:
        # quiesce the async plane on EVERY exit — including an
        # exception (e.g. NonFiniteLossError under policy "raise")
        # propagating to the caller: a worker thread still
        # dispatching device work during interpreter teardown aborts
        # the whole process, and a clean exit must never abandon an
        # uncommitted save. On the normal path the loop already
        # joined, so these are no-ops.
        if conc_eval is not None and conc_eval.in_flight:
            try:
                conc_eval.join()
            except Exception as qe:
                logger.warning(
                    "concurrent eval quiesced with error: %s", qe
                )
        try:
            asyncplane.join_commits()
        except asyncplane.AsyncCommitError as qe:
            logger.warning("async committer quiesced with error: %s", qe)
        # the sequencer's final stats, then back to the zero-overhead
        # pass-through (process-global, like the committer's state)
        sequencer.emit_stats(final=True)
        sequencer.shutdown()


def test_model():
    """Evaluate MODEL.WEIGHTS on the val split (ref: trainer.py:176-209)."""
    mesh_lib.apply_backend_flags(cfg.DEVICE.DETERMINISTIC or cfg.CUDNN.DETERMINISTIC)
    mesh_lib.apply_platform(cfg.DEVICE.PLATFORM)
    mesh_lib.setup_distributed()
    topo = check_trainer_mesh()
    logger = setup_logger()
    telemetry.setup_from_cfg(cfg, rank=jax.process_index())
    compile_cache.setup_from_cfg(cfg)  # warm eval compiles on restart
    mesh = mesh_lib.mesh_from_cfg(cfg)
    costmodel.set_mesh_extras(
        {"mesh": topo.axes, "topology": topo.class_name()}
    )
    # eval-only checks (GPipe eval divisibility), before the compile — a
    # train-invalid config must not block a pure evaluation (ADVICE r3 #2)
    check_batch_geometry(mesh, eval_only=True)
    model = build_model_from_cfg(topo)
    key = jax.random.key(cfg.RNG_SEED or 0)
    layout = _state_layout(model, mesh, cfg.TRAIN.IM_SIZE)
    state = create_train_state(
        model, key, mesh, cfg.TRAIN.IM_SIZE, layout=layout
    )
    if cfg.MODEL.WEIGHTS:
        state = _with_restored_weights(state, cfg.MODEL.WEIGHTS, model)
        logger.info("loaded weights from %s", cfg.MODEL.WEIGHTS)
    val_loader = construct_val_loader()
    # ZeRO rest layouts evaluate under the same gather-once schedule the
    # train path uses (partition/lowering.make_gather_entry)
    eval_step = make_eval_step(
        model, effective_topk(), layout=layout if cfg.MESH.ZERO else None
    )
    result = validate(val_loader, mesh, state, eval_step, 0, logger)
    if result is None:  # preempted mid-eval (TRAIN.PREEMPT_SAVE)
        if mesh_lib.is_primary():
            logger.warning("evaluation preempted before completion")
        return None
    top1, topk = result[0], result[1]
    if telemetry.enabled():
        telemetry.emit_snapshot()
    if mesh_lib.is_primary():
        logger.info("TEST  Acc@1 %.3f  Acc@%d %.3f", top1, effective_topk(), topk)
    return top1, topk
