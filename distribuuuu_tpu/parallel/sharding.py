"""Sharding specs and host→device placement for the training step.

The reference moves per-GPU batches with ``.cuda(non_blocking=True)``
(ref: /root/reference/distribuuuu/trainer.py:40) and relies on DDP to keep
replicated params in sync. Here placement is declarative: the global batch is
sharded over the ``data`` mesh axis, params are replicated (or sharded over
``model`` when tensor parallelism is on), and XLA compiles the collectives.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch tensor: leading dim split over the data axis."""
    return NamedSharding(mesh, P("data"))


def replicate(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params, scalars)."""
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Place a host-local batch pytree as global device arrays sharded on
    ``data``.

    In multi-host runs each process holds its own shard (DistributedSampler
    semantics, ref: utils.py:141-143) and this assembles the global array
    from per-host shards; single-host it is a plain sharded device_put.
    """
    sharding = batch_sharding(mesh)

    def _put(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        global_shape = (x.shape[0] * jax.process_count(),) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree.map(_put, batch)
