"""Sharding specs and host→device placement for the training step.

The reference moves per-GPU batches with ``.cuda(non_blocking=True)``
(ref: /root/reference/distribuuuu/trainer.py:40) and relies on DDP to keep
replicated params in sync. Here placement is declarative: the global batch is
sharded over the ``data`` mesh axis, params are replicated (or sharded over
``model`` when tensor parallelism is on), and XLA compiles the collectives.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch tensor: leading dim split over the data axis."""
    return NamedSharding(mesh, P("data"))


def replicate(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params, scalars)."""
    return NamedSharding(mesh, P())


def _batch_leaf_specs(tree, batch_dim: int):
    """Per-leaf batch specs as a spec tree.

    Image/CNN batches keep the historical blanket layout — dim
    ``batch_dim`` over ``data``, everything else replicated. Token archs
    (``MODEL.ARCH`` gpt*) read ``specs.TOKEN_BATCH_TABLE`` instead, so
    ``[B, S]`` token leaves additionally shard the token dim over ``seq``
    (the dp×sp layout; the table collapses to the blanket form on seq=1
    meshes) while the per-sequence ``mask`` stays on ``data`` alone —
    which is why the spec must be PER LEAF: one shared spec cannot serve
    a rank-2 token leaf and the rank-1 mask at once.
    """
    from distribuuuu_tpu.config import cfg
    from distribuuuu_tpu.parallel.partition import specs as specs_lib

    blanket = P(*([None] * batch_dim + ["data"]))
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    if not str(cfg.MODEL.ARCH).startswith("gpt"):
        return jax.tree.unflatten(treedef, [blanket] * len(flat))
    table = specs_lib.batch_table_for(arch=str(cfg.MODEL.ARCH))
    out = []
    for path, _ in flat:
        try:
            base = table.spec_for(jax.tree_util.keystr(path))
        except specs_lib.UnknownLeafError:
            base = P("data")  # non-loader keys keep the blanket layout
        out.append(P(*([None] * batch_dim + list(tuple(base)))))
    return jax.tree.unflatten(treedef, out)


def _put_tree(mesh: Mesh, tree, batch_dim: int):
    """Place a host-local pytree with the dim ``batch_dim`` of every leaf
    sharded over ``data`` (dims before it unsharded) — plus, for token
    batches, the token dim over ``seq`` (``_batch_leaf_specs``).

    In multi-host runs each process holds its own shard of the batch dim
    (DistributedSampler semantics, ref: utils.py:141-143) and this assembles
    the global array from per-host shards; single-host it is a plain sharded
    device_put.
    """
    spec_tree = _batch_leaf_specs(tree, batch_dim)

    # the batch's global extent scales with DATA GROUPS, not processes:
    # processes sharing a data row (model/pipe axes spanning hosts) feed
    # identical copies of the same shard (parallel/mesh.data_process_groups)
    from distribuuuu_tpu.parallel.mesh import data_process_groups

    _, n_groups = data_process_groups(mesh)

    def _put(x, spec):
        x = np.asarray(x)
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        global_shape = tuple(
            d * n_groups if i == batch_dim else d
            for i, d in enumerate(x.shape)
        )
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree.map(_put, tree, spec_tree)


def shard_batch(mesh: Mesh, batch):
    """Place a host-local batch pytree as global device arrays sharded on
    ``data``."""
    return _put_tree(mesh, batch, batch_dim=0)


def shard_stacked_batch(mesh: Mesh, stacked):
    """Place a host-local *stack* of batches (leading dim = fold size,
    second dim = batch) sharded on ``data`` along the batch dim — the input
    layout for the folded ``lax.scan`` train step."""
    return _put_tree(mesh, stacked, batch_dim=1)


def _micro_split(tree, accum: int, batch_axis: int):
    """Zero-copy view splitting dim ``batch_axis`` (size B) into
    ``(accum, B/accum)``; raises with per-axis numbers if indivisible."""

    def _split(x):
        x = np.asarray(x)
        b = x.shape[batch_axis]
        if b % accum:
            raise ValueError(
                f"batch dim {b} not divisible by GRAD_ACCUM_STEPS={accum}"
            )
        return x.reshape(
            x.shape[:batch_axis] + (accum, b // accum) + x.shape[batch_axis + 1:]
        )

    return jax.tree.map(_split, tree)


def shard_micro_batch(mesh: Mesh, batch, accum: int):
    """Split a host batch into ``(accum, micro_batch, ...)`` (zero-copy) and
    place it with the micro_batch dim on ``data`` — the input layout for the
    gradient-accumulation train step (TRAIN.GRAD_ACCUM_STEPS)."""
    return _put_tree(mesh, _micro_split(batch, accum, 0), batch_dim=1)


def shard_stacked_micro_batch(mesh: Mesh, stacked, accum: int):
    """Folded + accumulated: ``(fold, accum, micro_batch, ...)`` with the
    micro_batch dim on ``data``."""
    return _put_tree(mesh, _micro_split(stacked, accum, 1), batch_dim=2)
