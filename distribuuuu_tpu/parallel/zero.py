"""ZeRO / FSDP-style redundancy elimination over the ``data`` mesh axis.

The reference replicates optimizer state on every rank the way torch DDP
does (ref: /root/reference/distribuuuu/utils.py:187-196 — each GPU holds a
full momentum buffer; ref: trainer.py:134 — DDP replicates params). At
N-way data parallelism that is N redundant copies of every state tensor.
ZeRO (Rajbhandari et al.) shards those copies across the data ranks; FSDP
additionally shards the params at rest.

TPU-first form: there is no hand-written bucketing/reduce-scatter runtime
like the GPU implementations — the layout is *declared* and GSPMD compiles
the data movement into the step:

  - state leaves get a sharding with ``data`` added on a free dimension
    (``add_data_axis``), so each rank holds a 1/N slice at rest;
  - the gradient is constrained to the same sharded layout right before
    the optimizer update, which XLA satisfies with a reduce-scatter (the
    cross-replica grad mean and the shard slicing fuse into one collective
    — exactly ZeRO's comm schedule, derived instead of scheduled);
  - at stage 3 the params live sharded and XLA inserts weight all-gathers
    at use sites (FSDP's gather-on-demand).

Stage semantics (``MESH.ZERO``):
  0 — off: params + optimizer state replicated over ``data`` (DDP layout).
  1 — optimizer state sharded over ``data``; grads reduce-scattered into
      the sharded update; updated params all-gathered back to replicated.
  3 — stage 1 + params sharded at rest (FSDP). Weight all-gathers move the
      same bytes the stage-1 update all-gather did, so the comm volume is
      unchanged while param memory drops to 1/N.
Stage 2 (gradient sharding) has no separate meaning in a fused jit step:
gradients are transient values inside the compiled program, and the stage-1
constraint already materializes them sharded. Accepting only {0, 1, 3}
keeps the knob honest.

The math is unchanged in every stage — same update, same result modulo
float reduction order (asserted in tests/test_zero.py); only the layout
and therefore the per-rank memory/communication differ.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"

# Leaves smaller than this stay replicated: sharding a 64-float bias saves
# nothing and costs a collective per leaf. 2**13 × 4 B = 32 KiB at rest.
MIN_SHARD_ELEMS = 8192


def _padded(spec: P, rank: int):
    """Spec entries padded with None to the leaf's rank."""
    entries = tuple(spec) if spec is not None else ()
    return entries + (None,) * (rank - len(entries))


def _entry_names(entry) -> tuple[str, ...]:
    """Axis names of one spec entry (None / str / tuple)."""
    if entry is None:
        return ()
    return (entry,) if isinstance(entry, str) else tuple(entry)


def strip_data_axis(spec: P | None) -> P:
    """The exact inverse of :func:`add_data_axis`: ``spec`` with the
    ``data`` axis removed from every entry — the layout a ZeRO-sharded
    leaf occupies DURING compute once its shard has been all-gathered
    (TP/PP annotations survive; a leaf ``data`` never touched is
    returned unchanged). This is the gather target of the gather-once
    schedule (partition/specs.gather_schedule)."""
    entries = []
    for entry in tuple(spec) if spec is not None else ():
        names = tuple(n for n in _entry_names(entry) if n != DATA_AXIS)
        if not names:
            entries.append(None)
        elif len(names) == 1:
            entries.append(names[0])
        else:
            entries.append(names)
    return P(*entries)


def add_data_axis(
    spec: P | None,
    shape: tuple[int, ...],
    data_size: int,
    axis_sizes: dict[str, int] | None = None,
) -> P:
    """``spec`` with ``data`` added on the best divisible dim.

    A dim qualifies if its *remaining* extent — size divided by the mesh
    extent of axes already sharding it (TP/PP annotations) — divides by
    ``data_size``. The winner is the largest remaining extent (best
    bandwidth per collective); ties prefer an unsharded dim. On an
    already-sharded dim ``data`` is appended to the axis tuple (e.g.
    ``('model', 'data')``) — valid GSPMD, and at ``model``-size 1 it is
    what makes TP-annotated kernels shardable at all. Leaves with no
    qualifying dim — or too small to be worth sharding — keep their base
    spec (replicated over ``data`` at rest): always correct, just not
    deduplicated.
    """
    base = P() if spec is None else spec
    axis_sizes = axis_sizes or {}
    size = 1
    for d in shape:
        size *= d
    if data_size <= 1 or size < MIN_SHARD_ELEMS:
        return base
    entries = _padded(base, len(shape))

    def _names(e):
        return () if e is None else ((e,) if isinstance(e, str) else tuple(e))

    best, best_ext, best_free = -1, 0, False
    for i, (e, d) in enumerate(zip(entries, shape)):
        names = _names(e)
        if DATA_AXIS in names:
            return base  # already ZeRO-sharded; idempotent
        taken = 1
        for n in names:
            taken *= axis_sizes.get(n, 1)
        if d % (taken * data_size):
            continue
        ext, free = d // taken, not names
        if ext > best_ext or (ext == best_ext and free and not best_free):
            best, best_ext, best_free = i, ext, free
    if best < 0:
        return base
    new = list(entries)
    new[best] = (
        DATA_AXIS if new[best] is None else _names(new[best]) + (DATA_AXIS,)
    )
    return P(*new)


def zero_shardings(mesh: Mesh, base_shardings: Any, abstract_tree: Any) -> Any:
    """ZeRO layout for a param-shaped tree: per leaf, the base sharding
    (replicated or TP/PP-annotated) with ``data`` added where it fits.

    ``base_shardings`` is a tree of NamedShardings (tp.param_shardings
    output); ``abstract_tree`` supplies leaf shapes (jax.eval_shape output,
    possibly flax-boxed — only ``.shape`` is read, which boxes forward).
    """
    sizes = dict(mesh.shape)
    data_size = sizes.get(DATA_AXIS, 1)

    def _one(sh: NamedSharding, leaf):
        return NamedSharding(
            mesh, add_data_axis(sh.spec, tuple(leaf.shape), data_size, sizes)
        )

    return jax.tree.map(_one, base_shardings, abstract_tree)


def constrain(tree: Any, shardings: Any, scope: str = "zero_constrain") -> Any:
    """with_sharding_constraint over a matching tree (call inside jit).

    ``scope`` names the attribution scope (jax.named_scope) the
    constraint — and therefore the collective GSPMD derives from it
    (reduce-scatter for the grad layout, all-gather for the rest
    layout) — carries in HLO op metadata, so trace_report / Perfetto can
    split comms from compute (callers pass e.g. "zero_reduce_scatter")."""
    with jax.named_scope(scope):
        return jax.tree.map(
            jax.lax.with_sharding_constraint, tree, shardings
        )
