"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pipe``
mesh axis.

Beyond the reference's capability set (it is DDP-only, SURVEY.md §2.3) —
pipeline parallelism is first-class here because multi-host scale is a core
goal. The design is the TPU-idiomatic SPMD pipeline: every device runs the
SAME compiled program; stage identity comes from ``lax.axis_index("pipe")``;
activations hop stage→stage+1 with ``ppermute`` inside one ``lax.scan`` over
schedule ticks. Differentiating straight through the schedule yields the
reverse pipeline (autodiff transposes ppermute to the opposite shift and the
scan to its reverse), so one ``jax.grad`` gives correct pipeline-parallel
training with no hand-written backward schedule.

Scope: stages must share one parameter structure and one activation shape —
the repeated-block regime PP is used for in practice (transformer stacks,
MLP towers). Stage params are a stacked pytree with leading dim S sharded
over ``pipe``; the heterogeneous-stage case (e.g. a CNN's shrinking
pyramid) is served by the framework's DP/TP/SP axes instead.

The schedule is plain GPipe (fill, steady state, drain): T = M + S - 1 ticks
for M microbatches over S stages. Bubble fraction (S-1)/T shrinks as M
grows; there is no interleaving — keep stages coarse.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distribuuuu_tpu.parallel.compat import axis_size, shard_map


_logged_schedules: set[tuple[int, int]] = set()


def log_bubble_fraction(S: int, M: int) -> None:
    """Record the statically-known GPipe bubble at step-build (trace) time:
    of the T = M + S - 1 schedule ticks, S - 1 are fill/drain — every stage
    idles for exactly that fraction of the step regardless of how fast the
    hardware runs. Emitted once per distinct (S, M) as a kind="pp_bubble"
    jsonlog record plus a rank-0 log line, so an operator sees the
    schedule-inherent ceiling next to the measured step time instead of
    hunting it in a trace (PERF.md "Pipeline bubble accounting")."""
    key = (int(S), int(M))
    if key in _logged_schedules:
        return
    _logged_schedules.add(key)
    T = M + S - 1
    bubble = (S - 1) / T
    from distribuuuu_tpu.utils.jsonlog import metrics_log

    metrics_log(
        "pp_bubble", stages=int(S), microbatches=int(M), ticks=int(T),
        bubble=round(bubble, 4),
    )
    if jax.process_index() == 0:
        from distribuuuu_tpu.utils.logger import get_logger

        get_logger().info(
            "PP schedule: %d stages × %d microbatches = %d ticks; "
            "statically-known bubble fraction (S-1)/(M+S-1) = %.3f "
            "(raise MESH.MICROBATCH to amortize fill/drain)",
            S, M, T, bubble,
        )


def stack_stage_params(param_list):
    """Stack per-stage param pytrees (same structure) into one pytree with a
    leading stage dim — shard that dim over ``pipe``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)


def stage_params_sharding(mesh, stacked):
    """NamedSharding pinning the leading (stage) dim to the pipe axis."""
    return jax.tree.map(
        lambda x: NamedSharding(
            mesh, P("pipe", *([None] * (np.ndim(x) - 1)))
        ),
        stacked,
    )


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    microbatches: jax.Array,
    *,
    axis: str = "pipe",
    stage_aux: bool = False,
):
    """Run the GPipe schedule. Call INSIDE shard_map/jit with ``axis`` bound.

    Args:
      stage_fn: ``(params_for_one_stage, x) -> y`` with ``y.shape == x.shape``
        (uniform activation contract; see module docstring). With
        ``stage_aux=True``: ``(params, x) -> (y, aux)`` where ``aux`` is a
        small pytree of per-application statistics (fixed structure/shapes).
      stacked_params: per-device slice of the stacked stage params — inside
        shard_map each device sees leading dim 1: its own stage's params.
      microbatches: ``[M, mb, ...]`` input microbatches (replicated over the
        pipe axis; only stage 0 reads them).
    Returns:
      ``[M, mb, ...]`` outputs of the LAST stage, valid on every device
      (broadcast via psum so the loss can be computed anywhere). With
      ``stage_aux=True``: ``(outputs, aux_mean)`` where ``aux_mean`` is THIS
      device's stage aux averaged over its M valid applications — fill/drain
      ticks, whose stage inputs are schedule garbage, are masked out of the
      accumulation (VERDICT r3 #2: the MoE balancing stats ride this
      channel; gradients flow through the scan carry, so an aux-derived
      loss term trains correctly through the pipeline).
    """
    S = axis_size(axis)
    s = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    T = M + S - 1
    log_bubble_fraction(S, M)  # static schedule cost, once per (S, M)
    my_params = jax.tree.map(lambda x: x[0], stacked_params)
    mb_shape = microbatches.shape[1:]

    perm = [(i, (i + 1) % S) for i in range(S)]  # stage i → i+1 ring

    if stage_aux:
        aux_shapes = jax.eval_shape(
            lambda p, x: stage_fn(p, x)[1],
            my_params, jax.ShapeDtypeStruct(mb_shape, microbatches.dtype),
        )
        aux_zero = jax.tree.map(
            lambda a: jnp.zeros(a.shape, a.dtype), aux_shapes
        )

    def tick(carry, t):
        if stage_aux:
            incoming, outputs, aux_acc = carry
        else:
            incoming, outputs = carry
        # stage 0 consumes microbatch t (clamped into range during drain);
        # other stages consume what arrived from the previous stage
        mb_idx = jnp.clip(t, 0, M - 1)
        x0 = jax.lax.dynamic_index_in_dim(
            microbatches, mb_idx, axis=0, keepdims=False
        )
        x = jnp.where(s == 0, x0, incoming)
        # attribution scopes: stage compute vs the ppermute hop land
        # named in HLO op metadata, so a device trace splits pipeline
        # compute from the stage→stage+1 communication (trace_report)
        if stage_aux:
            with jax.named_scope("pp_stage"):
                y, aux = stage_fn(my_params, x)
            # stage s processes microbatch t−s at tick t; anything else
            # (fill for s>t, drain re-runs on clamped inputs) is schedule
            # garbage and must not pollute the statistics
            aux_valid = jnp.logical_and(t >= s, t - s < M)
            aux_acc = jax.tree.map(
                lambda acc, a: acc + jnp.where(aux_valid, a, 0).astype(acc.dtype),
                aux_acc, aux,
            )
        else:
            with jax.named_scope("pp_stage"):
                y = stage_fn(my_params, x)
        # the last stage finished microbatch t-(S-1) at this tick
        out_idx = t - (S - 1)
        valid = jnp.logical_and(s == S - 1, out_idx >= 0)
        outputs = jax.lax.cond(
            valid,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, M - 1), axis=0
            ),
            lambda o: o,
            outputs,
        )
        # hop to the next stage (the wrap S-1 → 0 carries garbage that stage
        # 0 never reads — it always selects the microbatch path)
        with jax.named_scope("pp_hop"):
            incoming = jax.lax.ppermute(y, axis, perm)
        if stage_aux:
            return (incoming, outputs, aux_acc), None
        return (incoming, outputs), None

    init = (
        jnp.zeros(mb_shape, microbatches.dtype),
        jnp.zeros((M,) + mb_shape, microbatches.dtype),
    )
    if stage_aux:
        init = init + (aux_zero,)
        (_, outputs, aux_acc), _ = jax.lax.scan(tick, init, jnp.arange(T))
    else:
        (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(T))

    # broadcast last-stage outputs to every pipe rank so downstream loss /
    # metrics code is position-independent
    with jax.named_scope("pp_gather_out"):
        outputs = jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs))
        outputs = jax.lax.psum(outputs, axis)
    if stage_aux:
        return outputs, jax.tree.map(lambda a: a / M, aux_acc)
    return outputs


def pipelined(
    stage_fn: Callable,
    *,
    mesh,
    num_microbatches: int,
    axis: str = "pipe",
    data_axis: str | None = "data",
    stage_aux: bool = False,
    param_specs=None,
):
    """Wrap ``stage_fn`` into ``fn(stacked_params, batch) -> outputs`` that
    runs the pipeline over ``mesh`` under jit (shard_map inside).

    ``batch`` is ``[B, ...]`` (global); it is split into ``num_microbatches``
    equal microbatches. When ``data_axis`` is present in the mesh the batch
    dim is additionally sharded over it (PP × DP composition).

    ``param_specs``: optional pytree of ``PartitionSpec``s (same structure
    as the stacked params) replacing the default ``P(axis)`` — lets the
    caller split selected param dims over OTHER mesh axes at shard_map
    entry instead of replicating them per device (PP×EP expert tensors:
    ``P('pipe', 'model', ...)`` keeps per-device expert memory at O(E/n);
    ADVICE r3 #1). The stage_fn must expect the per-device local shards.

    ``stage_aux=True``: ``stage_fn`` returns ``(y, aux)`` and the wrapped
    function returns ``(outputs, aux_stacked)`` where each ``aux`` leaf
    gains a leading stage dim ``[S, ...]`` and holds that stage's statistic
    averaged over ALL the microbatches it processed — pmean'd over the data
    axis, so token-mean statistics equal the flat (non-pipelined) model's
    full-batch values exactly (see ops/moe.balance_stats). Replicated on
    every device.
    """
    S = mesh.shape[axis]
    M = num_microbatches

    data_sharded = bool(data_axis) and mesh.shape.get(data_axis, 1) > 1

    def per_device(stacked_params, batch):
        mb = batch.reshape((M, batch.shape[0] // M) + batch.shape[1:])
        if not stage_aux:
            return pipeline_apply(stage_fn, stacked_params, mb, axis=axis)
        out, aux = pipeline_apply(
            stage_fn, stacked_params, mb, axis=axis, stage_aux=True
        )
        if data_sharded:
            # each data shard accumulated stats over its own tokens; the
            # microbatch/shard token counts are equal, so the pmean IS the
            # full-batch token mean
            aux = jax.tree.map(
                lambda a: jax.lax.pmean(a, data_axis), aux
            )
        # stage s holds only its own stats — gather the stage dim so every
        # device returns the full [S, ...] (replicated ⇒ out_spec P())
        aux = jax.tree.map(lambda a: jax.lax.all_gather(a, axis), aux)
        return out, aux

    batch_spec = P(data_axis) if data_sharded else P()
    # per-device output is [M, mb, ...]: microbatch index replicated, the
    # per-microbatch batch dim sharded over data (when present)
    out_spec = P(None, data_axis) if data_sharded else P()

    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(param_specs if param_specs is not None else P(axis),
                  batch_spec),
        out_specs=(out_spec, P()) if stage_aux else out_spec,
    )

    def apply(stacked_params, batch):
        res = fn(stacked_params, batch)
        out = res[0] if stage_aux else res  # [M, mb_global, ...]
        if data_sharded:
            # each data shard microbatched its OWN contiguous slice of the
            # batch, so the gathered dim 1 is [dp × mb]; restore the original
            # row order (shard-major) before flattening
            dp = mesh.shape[data_axis]
            out = out.reshape((M, dp, -1) + out.shape[2:])
            out = jnp.moveaxis(out, 1, 0)
        out = out.reshape((-1,) + out.shape[out.ndim - (batch.ndim - 1):])
        return (out, res[1]) if stage_aux else out

    apply.num_stages = S
    apply.num_microbatches = M
    return apply
