"""Tensor (model) parallelism: param partitioning over the ``model`` mesh axis.

The reference has no tensor parallelism (SURVEY.md §2.3 — DDP only). Here TP
is declarative, the idiomatic JAX/XLA form: weight matrices carry
``nn.with_partitioning`` metadata naming the ``model`` axis, the trainer
places params by those specs (see trainer.create_train_state), and GSPMD
inserts the all-gathers/reduce-scatters — there is no hand-written collective
per layer the way Megatron structures its column/row pairs. At
``MESH.MODEL=1`` every spec collapses to replication, so the same code path
serves pure data parallelism (the reference's topology) and dp×tp meshes.

Conventions:
  - Conv kernels   [kh, kw, in, out] → shard ``out`` (head/channel parallel)
  - Dense kernels  [in, out]         → shard ``out`` (column parallel)
  - ``RowParallelDense``             → shard ``in``  (row parallel; pairs
    with a column-parallel producer so the activation stays sharded between
    the two matmuls and GSPMD reduces once at the end)
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"


def column_init(init: Callable) -> Callable:
    """Partition a Dense kernel [in, out] column-wise over ``model``."""
    return nn.with_partitioning(init, (None, MODEL_AXIS))


def row_init(init: Callable) -> Callable:
    """Partition a Dense kernel [in, out] row-wise over ``model``."""
    return nn.with_partitioning(init, (MODEL_AXIS, None))


def conv_init(init: Callable) -> Callable:
    """Partition a Conv kernel [kh, kw, in, out] on output channels."""
    return nn.with_partitioning(init, (None, None, None, MODEL_AXIS))


def constrain_like(tree, template_tree, template_shardings):
    """Constrain every subtree of ``tree`` that is param-tree-shaped.

    Optimizer states embed whole copies of the param tree (momentum buffers);
    this pins each such copy to the params' layout so TP-sharded kernels get
    TP-sharded momentum instead of whatever XLA picks for unconstrained
    outputs. Call inside jit.
    """
    tdef = jax.tree.structure(template_tree)

    def is_param_shaped(node):
        return jax.tree.structure(node) == tdef

    def constrain(node):
        if is_param_shaped(node):
            return jax.tree.map(
                jax.lax.with_sharding_constraint, node, template_shardings
            )
        return node

    # attribution scope: the resharding collectives GSPMD derives from
    # these constraints show up named in HLO op metadata (trace_report)
    with jax.named_scope("tp_constrain"):
        return jax.tree.map(constrain, tree, is_leaf=is_param_shaped)


def param_shardings(mesh: Mesh, abstract_variables) -> Any:
    """Map a (possibly boxed) variables tree to NamedShardings.

    ``nn.get_partition_spec`` yields the annotated PartitionSpec for boxed
    leaves and ``P()`` (replicated) for plain ones.
    """
    specs = nn.get_partition_spec(abstract_variables)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs)


class ColumnParallelDense(nn.Module):
    """Dense with the kernel sharded on the output dim (Megatron column)."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.features,
            use_bias=self.use_bias,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=column_init(self.kernel_init),
            bias_init=nn.with_partitioning(
                nn.initializers.zeros, (MODEL_AXIS,)
            ),
        )(x)


class RowParallelDense(nn.Module):
    """Dense with the kernel sharded on the input dim (Megatron row).

    Feed it the output of a ColumnParallelDense: the intermediate activation
    stays ``model``-sharded and GSPMD emits a single reduce at the output.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.features,
            use_bias=self.use_bias,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=row_init(self.kernel_init),
        )(x)
