"""Parallelism: device mesh bootstrap, collectives, sharding helpers."""

from distribuuuu_tpu.parallel.mesh import (  # noqa: F401
    build_mesh,
    get_local_rank,
    get_rank,
    get_world_size,
    is_primary,
    setup_distributed,
)
from distribuuuu_tpu.parallel.collectives import (  # noqa: F401
    barrier,
    broadcast_from_primary,
    host_all_reduce_mean,
    scaled_all_reduce,
)
from distribuuuu_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    replicate,
    shard_batch,
)
