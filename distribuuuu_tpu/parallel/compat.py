"""Version-compat wrappers for the shard_map surface: ``shard_map`` itself
(manual-collective semantics, no varying-axes checking) and ``axis_size``.

One shim for every shard_map user in the framework (ring/Ulysses attention,
pipeline parallelism, MoE dispatch, benches): jax >= 0.8 spells the API
``jax.shard_map`` with ``check_vma``; older releases spell it
``jax.experimental.shard_map.shard_map`` with ``check_rep``. All call sites
here want the classic per-device semantics where collectives are written
explicitly, so the check is always disabled.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def axis_size(name: str) -> int:
    """Size of the bound mesh axis ``name`` inside a shard_map/pmap body.

    ``jax.lax.axis_size`` only exists in newer JAX; on releases without it
    (0.4.x — this container) ``psum`` of the literal int 1 over the axis
    constant-folds to the axis size at trace time, with identical
    semantics including the NameError on an unbound axis name. Every
    in-graph axis-size read (pp/ring/moe/vit) routes through here: a bare
    ``jax.lax.axis_size`` call breaks every shard_map path on 0.4.x with
    an AttributeError (r6 finding — the whole PP/ring/dispatch-MoE tier
    was dead in this environment until this shim)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def shard_map(fn, *, mesh, in_specs, out_specs):
    try:  # jax >= 0.8 spells the kwarg check_vma; older spells it check_rep
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover - older jax
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
