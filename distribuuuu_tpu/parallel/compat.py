"""Version-compat wrapper for ``jax.shard_map`` with manual-collective
semantics (no varying-axes checking).

One shim for every shard_map user in the framework (ring/Ulysses attention,
pipeline parallelism, benches): jax >= 0.8 spells the API ``jax.shard_map``
with ``check_vma``; older releases spell it
``jax.experimental.shard_map.shard_map`` with ``check_rep``. All call sites
here want the classic per-device semantics where collectives are written
explicitly, so the check is always disabled.
"""

from __future__ import annotations

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(fn, *, mesh, in_specs, out_specs):
    try:  # jax >= 0.8 spells the kwarg check_vma; older spells it check_rep
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover - older jax
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
