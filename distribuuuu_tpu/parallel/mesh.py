"""Device-mesh bootstrap: the TPU-native replacement for process groups.

The reference initializes an NCCL process group from one of three bootstrap
modes — launcher env vars, Slurm derivation, or explicit TCP rendezvous
(ref: /root/reference/distribuuuu/utils.py:19-51, tutorial/mnmc_ddp_mp.py:41-66).
Here the same discovery logic feeds ``jax.distributed.initialize`` (one
process per *host*, all local chips attached), and the "process group" is a
``jax.sharding.Mesh`` over every chip in the slice. Collectives are not
called by user code: they are compiled into the step function by XLA from
sharding annotations and ride ICI within a slice / DCN across slices.

Mesh axes (configured by ``cfg.MESH``):
  - ``data``   — data parallelism (batch sharding; DDP equivalent)
  - ``model``  — tensor/model parallelism (params/heads sharding)
  - ``seq``    — sequence/context parallelism (ring attention)
  - ``pipe``   — GPipe pipeline parallelism (parallel/pp.py)
  - ``expert`` — dedicated MoE dispatch axis (composes EP with TP)
The reference only exercises data parallelism; the extra axes are
first-class so larger workloads shard without restructuring. Any stanza
is validated/classified by the partition-layer topology registry
(parallel/partition/topology.py) before a mesh is built.
"""

from __future__ import annotations

import functools
import os
import subprocess

import jax
import numpy as np
from jax.sharding import Mesh

_initialized = False
_DEFAULT_COORD_PORT = 29566  # matches the reference's default port (utils.py:35)

MESH_AXES = ("data", "model", "seq", "pipe", "expert")


def _slurm_env():
    """Derive process topology from Slurm env (ref: utils.py:26-40)."""
    proc_id = int(os.environ["SLURM_PROCID"])
    n_procs = int(os.environ["SLURM_NTASKS"])
    node_list = os.environ["SLURM_NODELIST"]
    # First hostname in the allocation is the coordinator.
    addr = subprocess.getoutput(
        f"scontrol show hostname {node_list} | head -n1"
    ).strip()
    return addr, n_procs, proc_id


def apply_backend_flags(deterministic: bool = False) -> None:
    """Append backend flags to XLA_FLAGS before backend initialization.

    The reference's cuDNN determinism toggle (ref: utils.py:64-68) maps here:
    XLA:TPU compilation is deterministic by default; the GPU-only flag is
    appended for completeness when running this framework on GPU. Must be
    called before any jax API touches the backend.
    """
    if deterministic:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_gpu_deterministic_ops" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_gpu_deterministic_ops=true"
            ).strip()


def apply_platform(platform: str) -> None:
    """Honor ``cfg.DEVICE.PLATFORM`` ("auto" keeps the ambient platform).

    Must run before any jax backend use. The env var alone is not enough:
    environment sitecustomize hooks may pin ``jax_platforms`` via
    jax.config, which beats ``JAX_PLATFORMS``.
    """
    if platform and platform != "auto":
        jax.config.update("jax_platforms", platform)


def setup_distributed(port: int | None = None) -> None:
    """Initialize multi-host JAX if a multi-process launch is detected.

    Bootstrap modes, mirroring the reference's three paths (ref:
    utils.py:19-51):
      (a) explicit env: ``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``
          (JAX-native) or torch-launcher-style ``MASTER_ADDR``/``WORLD_SIZE``/
          ``RANK``;
      (b) Slurm: derived from ``SLURM_PROCID``/``SLURM_NTASKS``/
          ``SLURM_NODELIST`` via scontrol;
      (c) single-process (the default): no-op — every local chip is already
          visible, which is JAX's analogue of single-node DataParallel.
    Safe to call multiple times; only the first call initializes.
    """
    global _initialized
    if _initialized:
        return
    # Multi-process detection uses env vars ONLY: jax.distributed.initialize
    # must run before anything initializes the XLA backend, so no jax API
    # (even jax.process_count()) may be touched on the way in.
    coord_port = port or int(os.environ.get("COORDINATOR_PORT", _DEFAULT_COORD_PORT))
    multi = (
        "COORDINATOR_ADDRESS" in os.environ
        or ("SLURM_PROCID" in os.environ
            and int(os.environ.get("SLURM_NTASKS", "1")) > 1)
        or ("MASTER_ADDR" in os.environ
            and int(os.environ.get("WORLD_SIZE", "1")) > 1)
    )
    if multi:
        # The CPU client ships its cross-process collectives behind a flag
        # that defaults to "none", and a none-collectives client REFUSES
        # every computation spanning processes ("Multiprocess computations
        # aren't implemented on the CPU backend") — which silently breaks
        # the whole multi-process drill suite on CPU hosts. Select gloo
        # before the backend initializes; harmless on TPU (the option only
        # shapes CPU client creation) and absent option names are ignored
        # for jax versions without the knob.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass
    if "COORDINATOR_ADDRESS" in os.environ:
        jax.distributed.initialize()  # JAX reads its own env contract
    elif "SLURM_PROCID" in os.environ and int(os.environ.get("SLURM_NTASKS", "1")) > 1:
        addr, n_procs, proc_id = _slurm_env()
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{coord_port}",
            num_processes=n_procs,
            process_id=proc_id,
        )
    elif "MASTER_ADDR" in os.environ and int(os.environ.get("WORLD_SIZE", "1")) > 1:
        jax.distributed.initialize(
            coordinator_address=f"{os.environ['MASTER_ADDR']}:{coord_port}",
            num_processes=int(os.environ["WORLD_SIZE"]),
            process_id=int(os.environ["RANK"]),
        )
    _initialized = True


def get_rank() -> int:
    """Global process index (≙ dist.get_rank() at host granularity)."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of host processes (≙ dist.get_world_size() over hosts)."""
    return jax.process_count()


def get_local_rank() -> int:
    """Index of this process among processes on the same node."""
    return int(os.environ.get("LOCAL_RANK", 0))


def is_primary() -> bool:
    """True on the logging/checkpointing process (≙ rank == 0 gates)."""
    return jax.process_index() == 0


def data_process_groups(mesh=None) -> tuple[int, int]:
    """``(data_rank, n_data_groups)`` for the host data pipeline.

    In the reference's pure-DP world every process owns a distinct slice
    of the batch, so ``(process_index, process_count)`` is the sampler
    shard (ref: utils.py:141-143). Once the model/pipe axes span
    *processes* (e.g. a 2×2 data×model mesh over 4 single-device hosts),
    processes in the same data row must load IDENTICAL data — their
    devices hold the same batch shard. This derives the data-group index
    from the mesh's device→process layout: processes whose devices cover
    the same set of data-axis rows form one group; samplers shard by
    group, not by process. Falls back to the classic (rank, world) in
    single-process runs and degenerates to exactly that whenever each
    process owns its own data rows.
    """
    if jax.process_count() == 1:
        return 0, 1
    if mesh is None:
        from distribuuuu_tpu.config import cfg

        mesh = mesh_from_cfg(cfg)
    return _data_groups_of_mesh(mesh)


@functools.lru_cache(maxsize=8)
def _data_groups_of_mesh(mesh) -> tuple[int, int]:
    # pure in the mesh (and this process's index) — cached because the
    # sharded-batch placement path calls it every step
    rows_by_proc: dict[int, set] = {}
    for idx, dev in np.ndenumerate(mesh.devices):
        rows_by_proc.setdefault(dev.process_index, set()).add(idx[0])
    keys = {p: tuple(sorted(s)) for p, s in rows_by_proc.items()}
    distinct = sorted(set(keys.values()))
    mine = keys.get(jax.process_index())
    if mine is None or any(
        a != b and set(a) & set(b) for a in distinct for b in distinct
    ):
        # a process outside the mesh, or groups that PARTIALLY overlap
        # data rows (a layout the host pipeline cannot feed correctly)
        raise ValueError(
            f"mesh device→process layout does not partition the data axis "
            f"into clean per-process-group row sets: {sorted(keys.items())}"
        )
    return distinct.index(mine), len(distinct)


def resolve_axis_sizes(
    sizes: list[int] | tuple[int, ...], n_devices: int
) -> list[int]:
    """Resolve ``-1``/``0`` wildcard entries against ``n_devices``.

    ``-1`` (and ``0``, accepted everywhere a size-1 axis is meant) on
    exactly one axis means "all remaining devices". The resolved product
    must equal the device count. Shared by mesh construction and the
    partition-layer topology registry, so stanza validation and the mesh
    actually built can never disagree on the resolved shape."""
    sizes = [1 if s == 0 else s for s in sizes]
    n_auto = sum(1 for s in sizes if s == -1)
    if n_auto > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {sizes}")
    fixed = int(np.prod([s for s in sizes if s != -1]))
    if fixed <= 0 or n_devices % fixed != 0:
        raise ValueError(
            f"Mesh axes {sizes} do not divide device count {n_devices}"
        )
    sizes = [n_devices // fixed if s == -1 else s for s in sizes]
    if int(np.prod(sizes)) != n_devices:
        raise ValueError(
            f"Mesh {dict(zip(MESH_AXES, sizes))} uses {int(np.prod(sizes))} "
            f"devices but {n_devices} are available"
        )
    return sizes


def build_mesh(
    data: int = -1, model: int = 1, seq: int = 1, pipe: int = 1,
    expert: int = 1, devices=None
) -> Mesh:
    """Build the global device mesh with axes
    ``(data, model, seq, pipe, expert)``.

    ``-1`` on exactly one axis means "all remaining devices". The total must
    divide the device count evenly. With defaults this is pure data
    parallelism over every chip — the reference's DDP topology.
    """
    devices = jax.devices() if devices is None else devices
    sizes = resolve_axis_sizes([data, model, seq, pipe, expert], len(devices))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def mesh_from_cfg(cfg, devices=None) -> Mesh:
    """Build the mesh described by ``cfg.MESH``."""
    return build_mesh(
        data=cfg.MESH.DATA,
        model=cfg.MESH.MODEL,
        seq=cfg.MESH.SEQ,
        pipe=cfg.MESH.PIPE,
        expert=cfg.MESH.get("EXPERT", 1),
        devices=devices,
    )
