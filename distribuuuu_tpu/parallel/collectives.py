"""Collectives: host-level and in-graph cross-replica reductions.

The reference's complete collective surface is: async summed ``all_reduce``
scaled by 1/world (ref: /root/reference/distribuuuu/utils.py:85-106), DDP's
implicit gradient allreduce + init-time param broadcast, and ``dist.barrier``
(ref: tutorial/imagenet.py:159). On TPU the gradient reduction disappears
into the compiled step (XLA inserts psums from sharding annotations); what
remains for user code is metric reduction, broadcast, and barrier — provided
here at host level — plus in-graph helpers for shard_map code paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import multihost_utils


def scaled_all_reduce(values):
    """Cross-replica mean of a list of scalar metrics.

    API mirror of the reference's ``scaled_all_reduce`` (utils.py:85-106):
    sum across replicas then scale by ``1/world``. Under global-array jit the
    metrics computed in-graph are already global means, so this is only
    needed for host-side (out-of-graph) values. No-op at world size 1
    (ref: utils.py:92-94).
    """
    if jax.process_count() == 1:
        return list(values)
    arr = jnp.asarray([jnp.asarray(v, jnp.float32) for v in values])
    summed = multihost_utils.process_allgather(arr).sum(axis=0)
    return list(summed / jax.process_count())


def host_all_reduce_mean(tree):
    """Mean-reduce an arbitrary pytree of host values across processes."""
    if jax.process_count() == 1:
        return tree
    gathered = multihost_utils.process_allgather(tree)
    return jax.tree.map(lambda x: x.mean(axis=0), gathered)


def barrier(name: str = "barrier") -> None:
    """Block until all processes arrive (≙ dist.barrier, imagenet.py:159)."""
    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(name)


def broadcast_from_primary(tree):
    """Broadcast a pytree from process 0 to all (≙ DDP's init param sync).

    Under jit with replicated shardings XLA keeps params consistent by
    construction, so this is only needed for host-side objects (e.g. the
    epoch index read from a checkpoint, or data-pipeline state).
    """
    if jax.process_count() == 1:
        return tree
    return multihost_utils.broadcast_one_to_all(tree)


# -- in-graph helpers (shard_map / pmap code paths) --------------------------

def pmean(x, axis_name: str = "data"):
    """In-graph cross-replica mean over a mesh axis."""
    return jax.lax.pmean(x, axis_name)


def psum(x, axis_name: str = "data"):
    """In-graph cross-replica sum over a mesh axis."""
    return jax.lax.psum(x, axis_name)
