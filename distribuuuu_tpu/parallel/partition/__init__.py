"""Unified partition layer: declarative per-leaf PartitionSpecs and ONE
lowering for arbitrary dp×tp×pp×ep×sp meshes (ROADMAP #3; grounding:
"Scalable Training of Language Models using JAX pjit and TPUv4",
arXiv:2204.06514 — every parallelism form expressed as per-leaf specs
over one mesh, one lowering; the ZeRO composition that falls out for
free is arXiv:2004.13336).

Three layers:

  specs.py     the spec layer — per-leaf PartitionSpec declaration
               (model annotations + a path-pattern rules table covering
               the zoo), spec algebra (validate / collapse-at-size-1 /
               canonicalize), and the TP/ZeRO/PP layouts expressed as
               spec TRANSFORMS over declared base specs
  topology.py  the topology registry — validates/classifies any MESH
               stanza up front (capability-derived errors replacing the
               scattered trainer refusals), enumerates the valid mesh
               space for the dryrun sweep, and feeds elastic-resume
               classification (resilience/manifest.py)
  lowering.py  the one pjit-style lowering — builds the train/eval/
               folded step from specs alone for ANY validated topology
               (the trainer's fold/accum/ZeRO/PP/EP case analysis
               collapsed into a single code path)

Compositions that previously had no code path — ZeRO-3 under PP, and a
3-axis dp×tp×ep mesh with ZeRO-1 — train through this layer from a YAML
mesh stanza alone; every pre-existing topology reproduces its trajectory
(lockstep-tolerance-pinned in tests/test_partition_lowering.py).
"""

from distribuuuu_tpu.parallel.partition.specs import (  # noqa: F401
    SpecTable,
    SpecRule,
    UnknownLeafError,
    SpecConflictError,
    batch_spec,
    canonicalize,
    collapse_unit_axes,
    state_layout,
    validate_leaf_spec,
)
from distribuuuu_tpu.parallel.partition.topology import (  # noqa: F401
    Topology,
    TopologyError,
    enumerate_topologies,
    from_cfg,
)
from distribuuuu_tpu.parallel.partition.lowering import (  # noqa: F401
    Lowered,
    lower,
    make_eval_step,
    make_scan_train_step,
    make_train_step,
)
