"""The one lowering: specs → compiled train/eval/folded steps.

This is where the per-leaf declarations (partition/specs.py) and the
validated topology (partition/topology.py) become executable programs.
There is ONE step body for every point of the mesh space — dp, dp×tp,
PP, ZeRO-1/3, MoE over the model or the dedicated expert axis, and the
compositions that previously had no code path (ZeRO-3 under PP, a
dp×tp×ep mesh with ZeRO-1). A topology changes WHICH constraints the
body applies, never which code runs:

  * the batch rides the declared ``data`` spec (specs.BATCH_TABLE);
  * params/opt/grads rest in the ``state_layout`` trees; with a ZeRO
    stage the gradient is constrained to the sharded layout right before
    the optimizer update (GSPMD satisfies it with a reduce-scatter fused
    with the cross-replica mean) and outputs are pinned back to the rest
    layout so buffer donation stays stable;
  * the ZeRO-3 gather SCHEDULE is gather-once (ISSUE 15): FSDP leaves
    are constrained to their gathered compute layout ONCE at step entry
    (``make_gather_entry`` from ``specs.gather_schedule`` — ~1
    all-gather/leaf/step instead of per-use), each gather/reduce-scatter
    an independent per-leaf op the latency-hiding scheduler can overlap
    with compute (``ZERO.OVERLAP``; False = barrier-joined sync control
    arm, bit-identical), and the fused optimizer update runs per-shard
    (``opt_update.per_shard_update``);
  * every spec-induced collective carries a ``jax.named_scope`` naming
    the mesh axes it runs over (``zero_reduce_scatter@data``, …) so
    trace_report / Perfetto / cost.* records attribute comm per axis on
    this path too (the PP hop scopes live in parallel/pp.py).

The step builders here ARE the trainer's — ``trainer.make_train_step``
et al. re-export them — so the hot-loop math is defined once and the
legacy call sites (tests, tools, serve) keep working unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.models.layers import head_dtype
from distribuuuu_tpu.parallel import sharding as sharding_lib, tp, zero
from distribuuuu_tpu.parallel.partition import specs as specs_lib
from distribuuuu_tpu.resilience import supervisor
from distribuuuu_tpu.utils import faults
from distribuuuu_tpu.utils.metrics import accuracy, cross_entropy


@flax.struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: Any  # scalar int32 — drives per-step RNG folding (dropout etc.)
    key: Any  # base PRNG key (not checkpointed; re-derived from RNG_SEED)


def make_image_prep():
    """In-graph half of ``DATA.DEVICE_NORMALIZE`` (captured at step-build
    time): the loader ships raw uint8, the step normalizes in fp32 —
    identical formula/order to the host path (data/transforms.py).

    Dtype-gated at trace time (r4, when the flag became default-True):
    only uint8 batches are normalized. Float batches are ALREADY
    normalized — by the host pipeline, or synthetic (bench.py, tests) —
    and must pass through untouched, else flipping the default would have
    silently re-normalized every float-feeding caller."""
    if not cfg.DATA.DEVICE_NORMALIZE:
        return lambda images: images
    from distribuuuu_tpu.data.transforms import normalize_in_graph

    def prep(images):
        if images.dtype == jnp.uint8:
            return normalize_in_graph(images)
        return images

    return prep


def _collective_scopes(layout) -> tuple[str, str, str]:
    """Attribution scope names for the three spec-induced state
    collectives — the gather-once entry all-gather of FSDP leaves, the
    reduce-scatter into the grads layout, and the all-gather back to the
    rest layout — suffixed with the mesh axes they run over (``@data``),
    so trace_report rollups and Perfetto split comm per axis (the
    overlap-fraction rollup measures compute concurrency against exactly
    these names). ``None`` layout never reaches these."""
    axes = ",".join(specs_lib.added_axes(layout)) or "data"
    return (
        f"zero_gather_once@{axes}",
        f"zero_reduce_scatter@{axes}",
        f"zero_rest_layout@{axes}",
    )


def _barrier(tree):
    """optimization_barrier over a pytree: joins every leaf before any
    consumer — the ZERO.OVERLAP=False control arm (collectives complete
    before the consuming compute starts; identity on values, so the
    ON ≡ OFF bit-identity pin holds by construction)."""
    leaves, treedef = jax.tree.flatten(tree)
    return jax.tree.unflatten(treedef, jax.lax.optimization_barrier(leaves))


def make_gather_entry(layout):
    """The gather-once transform (ROADMAP #1, arXiv:2004.13336): a
    function constraining the scheduled FSDP leaves of a param tree to
    their gathered compute layout ONCE at step entry, derived entirely
    from the spec algebra (specs.gather_schedule — no per-model code).

    Returns ``(gather_fn, n_hoisted)``; ``gather_fn`` is identity when
    nothing is scheduled (stage 0/1, or ``ZERO.GATHER_AHEAD=0``). The
    constraint is applied OUTSIDE the differentiated function, so the
    backward reduce-scatters grads exactly as the stage-1 schedule does
    (the explicit grads constraint in ``apply_grads``); the gathered
    value is one program value consumed by forward AND backward — one
    all-gather per leaf per step instead of one per use site (the PR 14
    census: 195 → ~21 on dp8·zero3[resnet18]). Each leaf's gather is an
    independent op with no serializing join under ``ZERO.OVERLAP``, so
    the latency-hiding scheduler can run layer k+1's gather under layer
    k's compute; ``ZERO.OVERLAP=False`` joins them all first (the
    synchronous A/B control arm)."""
    hoist = specs_lib.gather_schedule(layout, int(cfg.ZERO.GATHER_AHEAD))
    n_hoisted = sum(jax.tree.leaves(hoist))
    if not n_hoisted:
        return (lambda params: params), 0
    gather_to = specs_lib.compute_layout(layout)
    go_scope = _collective_scopes(layout)[0]
    overlap = bool(cfg.ZERO.OVERLAP)

    def gather_fn(params):
        with jax.named_scope(go_scope):
            gathered = jax.tree.map(
                lambda x, sh, h: (
                    jax.lax.with_sharding_constraint(x, sh) if h else x
                ),
                params, gather_to, hoist,
            )
        if not overlap:
            gathered = _barrier(gathered)
        return gathered

    return gather_fn, int(n_hoisted)


def train_step_body(model, optimizer, topk: int, accum_steps: int = 1,
                    layout=None, rest_layout=None):
    """The pure step function shared by the per-step and folded paths.

    ``layout`` (a ``specs.state_layout`` dict) is required when
    ``MESH.ZERO`` is on: the gradient is constrained to the ZeRO layout
    right before the optimizer update — GSPMD satisfies it with a
    reduce-scatter, fusing the cross-replica grad mean with the shard
    slicing — and the outputs are pinned back to the state's rest layout
    so buffer donation stays stable across steps. ``None`` (the default)
    adds no constraints: GSPMD propagates the replicated DDP layout
    exactly as before. Building a step WITHOUT a layout while
    ``MESH.ZERO`` is set is refused — the state (create_train_state)
    would rest ZeRO-sharded while the step neither reduce-scatters grads
    nor pins outputs back, silently skipping buffer donation and
    measuring a layout that is neither DDP nor ZeRO.

    ``accum_steps > 1`` runs that many sequential micro-batches, summing
    gradients in-graph before ONE optimizer update (config:
    ``TRAIN.GRAD_ACCUM_STEPS``). The batch must arrive pre-split as
    ``(accum, micro_batch, ...)`` with the micro_batch dim sharded on
    ``data`` (sharding.shard_micro_batch) — splitting on the host is a
    zero-copy view, whereas an in-graph reshape of the data-sharded batch
    dim would make GSPMD redistribute the whole batch over ICI every step.
    Gradients are exact (the mean-CE micro-grads average to the full-batch
    grad); BN stats are per-micro-batch — torch-DDP-with-accumulation
    semantics. HBM holds one micro-batch of activations at a time.

    ``rest_layout`` (the full ``state_layout`` dict, passed by
    :func:`lower` at EVERY stage) pins the output state back to the
    DECLARED rest layout when no ZeRO stage does it already. Without the
    pin, GSPMD is free to rest stage-0 outputs wherever propagation
    lands them — on TP/EP meshes it model-shards LayerNorm/bias leaves
    the declaration says are replicated — so the steady-state layout
    silently drifts from the declaration after the first step AND buffer
    donation quietly drops for every drifted leaf (an output cannot
    alias an input resting in a different sharding): state held twice.
    Found by the static analyzer's replication+donation passes
    (ISSUE 14); on all-replicated dp-only meshes the pin collapses to a
    no-op, so legacy stage-0 programs are untouched. ``None`` (legacy
    direct callers of the re-exported step builders) preserves the old
    unpinned behavior.
    """
    if layout is None and cfg.MESH.ZERO:
        raise ValueError(
            f"MESH.ZERO={cfg.MESH.ZERO} requires the step to be built with "
            "the ZeRO state layout (pass layout=state_layout(...)): the "
            "state rests ZeRO-sharded, and a layout-less step would neither "
            "reduce-scatter grads nor pin rest layouts — a silent "
            "neither-DDP-nor-ZeRO configuration."
        )

    # Non-finite loss guard (resilience/supervisor.py), compiled into the
    # step: metrics always carry a ``nonfinite`` flag; under "skip" the
    # poisoned update is discarded in-graph (pre-step state selected).
    nonfinite_policy = supervisor.validate_policy(str(cfg.TRAIN.NONFINITE))

    if layout is not None:
        _, rs_scope, ag_scope = _collective_scopes(layout)
        # gather-once (ROADMAP #1): the scheduled FSDP leaves are
        # all-gathered ONCE at step entry — see make_gather_entry
        gather_entry, _ = make_gather_entry(layout)
        overlap = bool(cfg.ZERO.OVERLAP)
    else:
        gather_entry, overlap = (lambda p: p), True

    # Kernel tier (ops/pallas/, KERNELS.OPT_UPDATE): the fused one-pass
    # optimizer update, resolved ONCE at step-build time. None ⇒ the
    # optax reference chain (the xla escape hatch / unsupported
    # optimizer); non-None is bit-exact vs it (pinned:
    # tests/test_pallas_kernels.py) and elementwise per leaf. Under a
    # ZeRO layout the kernel lowers PER-SHARD through shard_map over the
    # rest layout (opt_update.per_shard_update): each rank updates only
    # the 1/N slice it owns — the fused per-shard weight update of
    # arXiv:2004.13336, and the fusion point the gather-once schedule
    # feeds. (The r14 whole-leaf replicated-pin — gather everything,
    # update, re-scatter — is gone; its recognition in the collectives
    # lint went with it.)
    from distribuuuu_tpu.ops.pallas import opt_update as fused_opt

    fused_update = fused_opt.fused_update_for()
    if fused_update is not None and layout is not None:
        fused_update = fused_opt.per_shard_update(fused_update, layout)

    def apply_grads(state, grads, new_stats, metrics):
        if layout is not None:
            if not overlap:
                # sync control arm: the backward completes before the
                # first reduce-scatter is issued
                grads = _barrier(grads)
            # ZeRO: reduce-scatter the grad into the sharded update
            grads = zero.constrain(grads, layout["grads"], scope=rs_scope)
            if not overlap:
                # ... and every reduce-scatter lands before the update
                grads = _barrier(grads)
        with jax.named_scope("optimizer_update"):
            if fused_update is not None:
                new_params, new_opt_state = fused_update(
                    state.params, grads, state.opt_state
                )
            else:
                updates, new_opt_state = optimizer.update(
                    grads, state.opt_state, state.params
                )
                new_params = optax.apply_updates(state.params, updates)
        if layout is not None:
            # pin rest layouts (stage 1: params re-gathered to replicated;
            # stage 3: params stay data-sharded) — keeps donation stable
            new_params = zero.constrain(
                new_params, layout["params"], scope=ag_scope
            )
            new_opt_state = tp.constrain_like(
                new_opt_state, grads, layout["opt"]
            )
        elif rest_layout is not None:
            # stage 0: same pin, declared base layout (docstring above —
            # no-op on all-replicated meshes, drift+donation fix on
            # TP/EP meshes)
            new_params = zero.constrain(
                new_params, rest_layout["params"], scope="rest_layout"
            )
            new_opt_state = tp.constrain_like(
                new_opt_state, grads, rest_layout["opt"]
            )
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            step=state.step + 1,
            key=state.key,
        )
        return supervisor.guard_nonfinite(
            state, new_state, metrics, nonfinite_policy
        )

    # λ for the MoE load-balancing aux (models/vit.MoeMlp sows per-block
    # values into ``intermediates``); captured at step-build time. Zero
    # overhead for dense archs: the collection stays empty.
    moe_aux_weight = float(cfg.MODEL.MOE.AUX_WEIGHT)
    prep_images = make_image_prep()
    # FAULTS.NAN_STEP (utils/faults.py): trace-time gate — None (the
    # common case) compiles nothing in; an int multiplies the loss by
    # where(step==k, NaN, 1), poisoning loss AND grads at exactly step k.
    nan_step = faults.nan_injection_step()

    def loss_fn(params, stats, images, labels, key, step):
        images = prep_images(images)
        # attribution scope: the forward (and, through autodiff's
        # transpose, its backward as transpose(fwd)/...) is nameable in
        # HLO op metadata — trace_report / Perfetto split compute from
        # the collective/update scopes below
        with jax.named_scope("fwd"):
            logits, mutated = model.apply(
                {"params": params, "batch_stats": stats},
                images,
                train=True,
                mutable=["batch_stats", "intermediates", "moe_stats"],
                rngs={"dropout": key},
            )
        loss = cross_entropy(logits, labels)
        aux = jax.tree.leaves(mutated.get("intermediates", {}))
        if aux and moe_aux_weight:
            loss = loss + moe_aux_weight * sum(aux) / len(aux)
        if nan_step is not None:
            loss = loss * jnp.where(
                step == nan_step, jnp.float32(jnp.nan), jnp.float32(1.0)
            )
        # dispatch-MoE observability: per-block dropped-assignment
        # fractions (models/vit.MoeMlp sows the sum; empty for dense and
        # partial-MoE models — zero overhead there)
        dstats = jax.tree.leaves(mutated.get("moe_stats", {}))
        dropped = sum(dstats) / len(dstats) if dstats else None
        return loss, (logits, mutated.get("batch_stats", {}), dropped)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step_metrics(loss, logits, labels, dropped):
        acc1, acck = accuracy(logits, labels, topk=(1, topk))
        metrics = {"loss": loss, "top1": acc1, "topk": acck}
        if dropped is not None:
            metrics["moe_dropped"] = dropped
        return metrics

    def train_step(state: TrainState, batch):
        step_key = jax.random.fold_in(state.key, state.step)
        # gather-once: FSDP leaves are constrained to their gathered
        # compute layout HERE, outside grad_fn — forward and backward
        # consume the one gathered value, and the explicit grads
        # constraint in apply_grads stays the lone reduce-scatter
        params = gather_entry(state.params)
        (loss, (logits, new_stats, dropped)), grads = grad_fn(
            params, state.batch_stats, batch["image"], batch["label"],
            step_key, state.step,
        )
        return apply_grads(
            state, grads, new_stats,
            step_metrics(loss, logits, batch["label"], dropped),
        )

    def accum_train_step(state: TrainState, micro):
        step_key = jax.random.fold_in(state.key, state.step)
        # gather-once, OUTSIDE the microbatch scan: every micro-step
        # closes over the same gathered params (one gather per optimizer
        # step, not per microbatch); each micro-backward reduce-scatters
        # into the standing sharded grad-sum
        gathered_params = gather_entry(state.params)
        if micro["image"].shape[0] != accum_steps:
            raise ValueError(
                f"accum train step wants a pre-split (accum={accum_steps}, "
                f"micro_batch, ...) input, got leading dim "
                f"{micro['image'].shape[0]} — use sharding.shard_micro_batch"
            )

        def body(carry, mb):
            stats, gsum, i = carry
            mkey = jax.random.fold_in(step_key, i)
            (loss, (logits, new_stats, dropped)), grads = grad_fn(
                gathered_params, stats, mb["image"], mb["label"], mkey,
                state.step,
            )
            gsum = jax.tree.map(jnp.add, gsum, grads)
            return (new_stats, gsum, i + 1), step_metrics(
                loss, logits, mb["label"], dropped
            )

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        if layout is not None:
            # sharded accumulation buffer: each micro-grad reduce-scatters
            # into it (ZeRO-2 semantics during accumulation — the standing
            # grad-sum holds 1/N per rank)
            zeros = zero.constrain(zeros, layout["grads"])
        (new_stats, gsum, _), micro_metrics = jax.lax.scan(
            body, (state.batch_stats, zeros, jnp.int32(0)), micro,
            length=accum_steps,
        )
        grads = jax.tree.map(lambda g: g / accum_steps, gsum)
        metrics = jax.tree.map(jnp.mean, micro_metrics)
        return apply_grads(state, grads, new_stats, metrics)

    return accum_train_step if accum_steps > 1 else train_step


def make_train_step(model, optimizer, topk: int, accum_steps: int = 1,
                    layout=None, rest_layout=None):
    """Compile-once train step: fwd + CE loss + bwd + SGD + metrics
    (≙ the hot loop body, ref: trainer.py:37-58)."""
    return jax.jit(
        train_step_body(model, optimizer, topk, accum_steps, layout=layout,
                        rest_layout=rest_layout),
        donate_argnums=0,
    )


def make_scan_train_step(model, optimizer, topk: int, fold: int,
                         accum_steps: int = 1, layout=None,
                         rest_layout=None):
    """``fold`` optimizer steps in ONE compiled call via ``lax.scan``.

    Same math as ``fold`` sequential ``make_train_step`` calls (same body,
    same per-step RNG folding via ``state.step``; results agree up to XLA
    fusion-order float drift). The difference is dispatch: one host→device
    launch per ``fold`` steps, so the per-step host overhead (~4 ms on
    tunneled transports, PERF.md) amortizes away.
    Takes a stacked batch pytree with leading dim ``fold`` (leaf shape
    ``(fold, batch, ...)``) and returns stacked per-step metrics ``(fold,)``.
    """
    body = train_step_body(model, optimizer, topk, accum_steps, layout=layout,
                           rest_layout=rest_layout)

    def scan_steps(state: TrainState, stacked_batch):
        return jax.lax.scan(body, state, stacked_batch, length=fold)

    return jax.jit(scan_steps, donate_argnums=0)


def make_eval_step(model, topk: int, layout=None):
    """Masked eval step: per-batch metric sums + valid count
    (≙ validate body, ref: trainer.py:77-89).

    ``layout`` (passed by :func:`lower` when a ZeRO stage is on) applies
    the same gather-once schedule the train step uses: at stage 3 the
    FSDP leaves are gathered once at eval entry instead of per use site.
    ``None`` (legacy direct callers — serve, tools) keeps the old
    per-use behavior."""
    prep_images = make_image_prep()
    gather_entry = (
        make_gather_entry(layout)[0] if layout is not None else (lambda p: p)
    )

    def eval_step(state: TrainState, batch):
        params = gather_entry(state.params)
        with jax.named_scope("eval_fwd"):
            logits = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                prep_images(batch["image"]),
                train=False,
            )
        mask = batch["mask"]
        labels = batch["label"]
        if logits.ndim == 3:
            # per-token logits (the LM's [B, S, V]): every token of a
            # masked-in sequence is one example — flatten the token dim
            # and broadcast the per-sequence mask over it. The image path
            # ([B, C]) is byte-identical to before; this is the same
            # one-eval-step generalization utils/metrics.py applies.
            mask = jnp.broadcast_to(mask[:, None], labels.shape).reshape(-1)
            logits = logits.reshape(-1, logits.shape[-1])
            labels = labels.reshape(-1)
        logp = jax.nn.log_softmax(
            logits.astype(head_dtype(logits.dtype)), axis=-1
        )
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        _, pred = jax.lax.top_k(logits, topk)  # topk pre-clamped (effective_topk)
        hits = pred == labels[:, None]
        c1 = (hits[:, :1].any(axis=1) * mask).sum()
        ck = (hits.any(axis=1) * mask).sum()
        return {
            "loss_sum": (nll * mask).sum(),
            "correct1": c1,
            "correctk": ck,
            "count": mask.sum(),
        }

    return jax.jit(eval_step)


# ------------------------------------------------------------- the entry


@dataclass
class Lowered:
    """Everything the epoch loop needs for one validated topology — built
    from specs alone, no topology case analysis left at the call site."""

    mesh: Any
    topology: Any
    layout: dict           # {"params","opt","grads"} NamedSharding trees
    step_layout: dict | None  # layout when a ZeRO stage is on, else None
    train_step: Any
    eval_step: Any
    scan_step: Any = None  # folded step when fold > 1
    accum: int = 1
    fold: int = 1
    model: Any = None
    optimizer: Any = None  # kept so abstract_args can shape the opt state
    im_size: int = 32

    def init_state(self, key, im_size: int):
        """Fresh TrainState resting in this topology's layout."""
        from distribuuuu_tpu import trainer

        return trainer.create_train_state(
            self.model, key, self.mesh, im_size, layout=self.layout
        )

    def put_batch(self, host_batch):
        """Place one host batch per the declared batch specs (accum-aware)."""
        if self.accum > 1:
            return sharding_lib.shard_micro_batch(
                self.mesh, host_batch, self.accum
            )
        return sharding_lib.shard_batch(self.mesh, host_batch)

    def put_stacked(self, host_stacked):
        """Place a fold-stacked host batch per the declared batch specs."""
        if self.accum > 1:
            return sharding_lib.shard_stacked_micro_batch(
                self.mesh, host_stacked, self.accum
            )
        return sharding_lib.shard_stacked_batch(self.mesh, host_stacked)

    def abstract_args(self, batch_size: int | None = None, *,
                      with_mask: bool = False):
        """``(state_sds, batch_sds)`` — ShapeDtypeStructs carrying the
        DECLARED shardings for this topology's step arguments.

        The static analyzer (distribuuuu_tpu/analysis/) lowers and
        compiles the step against these to read GSPMD's verdict (compiled
        shardings, donation aliasing, the collective schedule) without
        ever materializing state or data — and without a second compile:
        every program pass shares the one lowered/compiled bundle. The
        placement mirrors ``trainer.create_train_state`` exactly: params
        per the declared layout, batch_stats replicated, optimizer state
        per the opt layout on param-structured subtrees (the abstract
        twin of ``tp.constrain_like``) and replicated elsewhere, batch
        leaves per ``specs.BATCH_TABLE``. ``batch_size`` defaults to two
        samples per data rank (shape-only — placement does not depend on
        batch geometry).
        """
        import flax
        import numpy as np

        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())

        def sds(leaf, sh):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

        abstract = specs_lib.abstract_state(self.model, self.im_size)
        unboxed = flax.linen.meta.unbox(abstract)
        params = jax.tree.map(sds, unboxed["params"], self.layout["params"])
        stats = jax.tree.map(
            lambda l: sds(l, repl), unboxed.get("batch_stats", {})
        )
        opt_abs = jax.eval_shape(self.optimizer.init, unboxed["params"])
        tdef = jax.tree.structure(unboxed["params"])

        def is_param_shaped(node):
            try:
                return jax.tree.structure(node) == tdef
            except (TypeError, ValueError):
                return False

        def place_opt(node):
            if is_param_shaped(node):
                return jax.tree.map(sds, node, self.layout["opt"])
            return jax.tree.map(lambda l: sds(l, repl), node)

        opt = jax.tree.map(place_opt, opt_abs, is_leaf=is_param_shaped)
        state = TrainState(
            params=params, batch_stats=stats, opt_state=opt,
            step=sds(jax.ShapeDtypeStruct((), np.int32), repl),
            key=sds(jax.eval_shape(lambda: jax.random.key(0)), repl),
        )

        data = int(dict(self.mesh.shape).get("data", 1))
        B = int(batch_size) if batch_size else max(8, 2 * data)
        dummy = specs_lib.model_dummy_input(self.model, self.im_size)
        image = jax.ShapeDtypeStruct((B,) + dummy.shape[1:], dummy.dtype)
        # token models label per token ([B, S]); image models per sample
        label_shape = (B,) + (dummy.shape[1:] if image.ndim == 2 else ())
        batch = {
            "image": image,
            "label": jax.ShapeDtypeStruct(label_shape, np.int32),
        }
        if with_mask:
            batch["mask"] = jax.ShapeDtypeStruct((B,), np.float32)
        # token models (a batch_spec_table hook) shard [B, S] leaves over
        # (data, seq); image models keep the blanket data-only layout
        table = specs_lib.batch_table_for(self.model)
        batch = {
            k: sds(v, NamedSharding(self.mesh, table.spec_for(k)))
            for k, v in batch.items()
        }
        return state, batch


def lower(model, optimizer, topk: int, *, mesh, topology, im_size: int,
          fold: int = 1, accum: int = 1) -> Lowered:
    """Build the train/eval(/folded) step for ANY validated topology from
    the declared specs — the single code path the trainer's per-topology
    case analysis collapsed into.

    The layout comes from ``specs.state_layout`` (base declarations +
    ZeRO transform per ``topology.zero``); the step body applies the
    layout constraints exactly when a stage is on, so stage-0 programs
    are bit-identical to the pre-partition trainer's.
    """
    layout = specs_lib.state_layout(model, mesh, im_size, topology.zero)
    step_layout = layout if topology.zero else None
    if step_layout is not None:
        _log_zero_schedule(step_layout, topology)
    train_step = make_train_step(
        model, optimizer, topk, accum_steps=accum, layout=step_layout,
        rest_layout=layout,
    )
    scan_step = None
    if fold > 1:
        scan_step = make_scan_train_step(
            model, optimizer, topk, fold, accum_steps=accum,
            layout=step_layout, rest_layout=layout,
        )
    return Lowered(
        mesh=mesh, topology=topology, layout=layout, step_layout=step_layout,
        train_step=train_step,
        eval_step=make_eval_step(model, topk, layout=step_layout),
        scan_step=scan_step, accum=max(1, accum), fold=max(1, fold),
        model=model, optimizer=optimizer, im_size=im_size,
    )


_logged_schedules: set = set()


def _log_zero_schedule(layout, topology) -> None:
    """Record the derived ZeRO collective schedule ONCE per distinct
    shape at lowering time (kind="zero.schedule", telemetry/schema.py):
    how many leaves rest ZeRO-sharded, how many entry gathers the
    gather-once transform hoisted, and the overlap knobs — so a run's
    telemetry states the schedule it trained under (the same facts the
    static analyzer's census referees post-hoc)."""
    hoist = specs_lib.gather_schedule(layout, int(cfg.ZERO.GATHER_AHEAD))
    sharded = sum(
        1 for sh in jax.tree.leaves(layout["grads"])
        if "data" in specs_lib.spec_axes(sh.spec)
    )
    key = (
        int(topology.zero), sharded, sum(jax.tree.leaves(hoist)),
        bool(cfg.ZERO.OVERLAP), int(cfg.ZERO.GATHER_AHEAD),
    )
    if key in _logged_schedules:
        return
    _logged_schedules.add(key)
    from distribuuuu_tpu.utils.jsonlog import metrics_log

    metrics_log(
        "zero.schedule", stage=key[0], leaves=len(jax.tree.leaves(layout["params"])),
        sharded=key[1], hoisted=key[2], overlap=key[3], gather_ahead=key[4],
    )
