"""The spec layer: per-leaf PartitionSpecs, declared and transformed.

Every parallelism form this framework ships reduces to a per-leaf
``PartitionSpec`` over the one device mesh (axes
``data/model/seq/pipe/expert`` — parallel/mesh.MESH_AXES):

  * TP / PP / EP placement is DECLARED at the parameter: flax
    ``nn.with_partitioning`` metadata names the mesh axes per dim
    (models/*.py, models/vit.PipelinedViT ``init_stages``). ``base_specs``
    reads those annotations back as the base spec tree.
  * ZeRO stage 1/3 is a spec TRANSFORM over the base: ``data`` added on
    the best divisible free dim per leaf (parallel/zero.add_data_axis) —
    optimizer state + grads at stage 1, params too at stage 3.
  * batch / activation placement comes from a path-pattern rules table
    (``BATCH_TABLE``): leading dim over ``data``, the layout every
    topology shares.

``state_layout`` is the single resolver the lowering and the trainer
place state with; the spec algebra below (validate / collapse /
canonicalize) is what the stanza gate (tests/test_mesh_stanzas.py)
compares declared layouts against compiled shardings with — a spec that
names a size-1 axis collapses to replication, so dp-only meshes and
dp×tp meshes flow through identical declarations.

The collective SCHEDULE is derived here too (ISSUE 15): ``gather_schedule``
decides per leaf — from the spec algebra alone — which ZeRO-3 all-gathers
the lowering hoists to one step-entry gather (gather-once, ~1 gather/leaf
vs the ~9.3/leaf per-use storm the analyzer priced), ``compute_layout`` is
the gathered target, and ``collective_expectations`` is the referee table
the static analyzer's collective lint scores compiled programs against.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class UnknownLeafError(KeyError):
    """A strict spec table was asked for a leaf no rule covers."""


class SpecConflictError(ValueError):
    """A per-leaf spec names the same mesh axis on more than one dim (or
    more axes than the leaf has dims)."""


# ----------------------------------------------------------- spec algebra


def _entry_names(entry) -> tuple[str, ...]:
    """Axis names of one spec entry: None → (), 'x' → ('x',), tuples pass."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def spec_axes(spec: P | None) -> tuple[str, ...]:
    """Every mesh axis named anywhere in ``spec`` (order of appearance)."""
    out: list[str] = []
    for entry in tuple(spec) if spec is not None else ():
        for name in _entry_names(entry):
            if name not in out:
                out.append(name)
    return tuple(out)


def validate_leaf_spec(
    path: str, spec: P | None, shape: tuple[int, ...],
    axis_sizes: dict[str, int],
) -> None:
    """Refuse malformed per-leaf specs BEFORE they reach GSPMD.

    Checks: (a) no mesh axis appears on more than one dim (GSPMD's
    error for that is a cryptic HLO dump); (b) the spec does not name
    more dims than the leaf has; (c) every named axis exists on the
    mesh. Raises :class:`SpecConflictError` with the leaf path.

    Deliberately NOT checked: per-dim divisibility — GSPMD pads a dim
    that does not divide evenly (e.g. a 10-class head kernel on a
    4-way model axis), which is legal and was always accepted; the ZeRO
    transform separately adds ``data`` only where it divides
    (parallel/zero.add_data_axis).
    """
    entries = tuple(spec) if spec is not None else ()
    if len(entries) > len(shape):
        raise SpecConflictError(
            f"leaf {path}: spec {spec} names {len(entries)} dims but the "
            f"leaf has rank {len(shape)}"
        )
    seen: dict[str, int] = {}
    for dim, entry in enumerate(entries):
        for name in _entry_names(entry):
            if name not in axis_sizes:
                raise SpecConflictError(
                    f"leaf {path}: spec {spec} names mesh axis {name!r} "
                    f"which does not exist on the mesh "
                    f"(axes: {sorted(axis_sizes)})"
                )
            if name in seen:
                raise SpecConflictError(
                    f"leaf {path}: spec {spec} names mesh axis {name!r} on "
                    f"both dim {seen[name]} and dim {dim} — an axis may "
                    "shard at most one dim of a leaf"
                )
            seen[name] = dim


def collapse_unit_axes(spec: P | None, axis_sizes: dict[str, int]) -> P:
    """Drop axes of size 1 from ``spec`` — a size-1 axis shards nothing,
    so the canonical form of its spec is replication on that dim. This is
    what lets ONE declaration serve every mesh: the TP annotation
    ``P(None, 'model')`` IS replication on a dp-only mesh."""
    entries = []
    for entry in tuple(spec) if spec is not None else ():
        names = tuple(
            n for n in _entry_names(entry) if axis_sizes.get(n, 1) > 1
        )
        if not names:
            entries.append(None)
        elif len(names) == 1:
            entries.append(names[0])
        else:
            entries.append(names)
    return P(*entries)


def canonicalize(spec: P | None, axis_sizes: dict[str, int]) -> P:
    """Canonical spec: unit axes collapsed, trailing ``None`` stripped —
    the equality the stanza gate compares declared vs compiled specs
    under (``P('data')`` ≡ ``P('data', None)`` ≡ ``P(('data',), None)``)."""
    entries = list(tuple(collapse_unit_axes(spec, axis_sizes)))
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


# ------------------------------------------------------------ rules table


@dataclass(frozen=True)
class SpecRule:
    """One path-pattern rule: leaves whose path matches ``pattern``
    (``re.search``) get ``spec``."""

    pattern: str
    spec: P


class SpecTable:
    """Ordered path-pattern → PartitionSpec rules covering a tree.

    ``strict=True`` refuses unknown leaves (:class:`UnknownLeafError`)
    instead of defaulting — the mode the stanza gate runs in, so a new
    batch key or renamed param cannot silently fall back to replication.
    """

    def __init__(self, rules=(), default: P | None = P(), strict: bool = False):
        self.rules = tuple(rules)
        self.default = default
        self.strict = strict

    def spec_for(self, path: str, shape: tuple[int, ...] | None = None) -> P:
        for rule in self.rules:
            if re.search(rule.pattern, path):
                return rule.spec
        if self.strict:
            raise UnknownLeafError(
                f"no spec rule covers leaf {path!r} (strict table; rules: "
                f"{[r.pattern for r in self.rules]})"
            )
        return self.default

    def tree_specs(self, tree: Any) -> Any:
        """Spec tree for ``tree``: one ``spec_for`` per leaf path."""
        flat = jax.tree_util.tree_flatten_with_path(tree)
        return jax.tree.unflatten(
            flat[1],
            [
                self.spec_for(jax.tree_util.keystr(path), getattr(leaf, "shape", None))
                for path, leaf in flat[0]
            ],
        )


# The batch layout every topology shares: the leading (batch) dim of every
# loader key is split over ``data``; everything else about a batch leaf is
# replicated. Declared here (not hard-coded at the device_put site) so the
# lowering, the sweep, and the stanza gate all read the same table.
BATCH_TABLE = SpecTable(
    rules=(
        SpecRule(r"(^|[/'\[\.])image", P("data")),
        SpecRule(r"(^|[/'\[\.])label", P("data")),
        SpecRule(r"(^|[/'\[\.])mask", P("data")),
    ),
    default=None,  # unknown batch keys are refused in strict mode
    strict=True,
)

# Token batches (the LM's ``[B, S]`` input/target leaves) additionally
# shard the TOKEN dim over ``seq`` — the declaration that makes a dp×sp
# stanza's batch arrive pre-split for the ring-attention shard_map instead
# of resting replicated over the seq axis (which this jax line would do
# silently). The per-sequence ``mask`` has no token dim and stays on
# ``data`` alone. On a seq=1 mesh the extra axis collapses to replication
# (collapse_unit_axes), so ONE declaration serves every LM topology.
TOKEN_BATCH_TABLE = SpecTable(
    rules=(
        SpecRule(r"(^|[/'\[\.])image", P("data", "seq")),
        SpecRule(r"(^|[/'\[\.])label", P("data", "seq")),
        SpecRule(r"(^|[/'\[\.])mask", P("data")),
    ),
    default=None,  # unknown batch keys are refused in strict mode
    strict=True,
)


def batch_table_for(model=None, arch: str | None = None) -> SpecTable:
    """The batch spec table for a model (or a config arch name): token
    models declare their own via a ``batch_spec_table`` hook (models/gpt.py
    → :data:`TOKEN_BATCH_TABLE`); every other arch rides
    :data:`BATCH_TABLE`. The single selector the lowering, the trainer and
    the host-placement layer (parallel/sharding.py) share."""
    if model is not None:
        fn = getattr(model, "batch_spec_table", None)
        if fn is not None:
            return fn()
        return BATCH_TABLE
    if arch is not None and arch.startswith("gpt"):
        return TOKEN_BATCH_TABLE
    return BATCH_TABLE


# Activations between layers: batch dim over ``data`` (GSPMD propagates it
# through the whole program from the batch placement; this constant is the
# declaration tools and docs reference).
ACTIVATION_SPEC = P("data")


def leaf_path(path) -> str:
    """A tree_flatten_with_path key path as the slash form the spec-table
    rules are written against (``tok_embed/embedding`` — readable in error
    messages, stable across jax keystr cosmetics)."""
    parts = []
    for k in path:
        name = getattr(k, "key", None)
        if name is None:
            name = getattr(k, "name", None)
        if name is None:
            name = getattr(k, "idx", None)
        parts.append(str(name) if name is not None else str(k))
    return "/".join(parts)


def lm_spec_table(moe_axis: str = "model") -> SpecTable:
    """The decoder-only LM's per-leaf placement rules (ISSUE 12): one
    path-pattern declaration per LM parameter family, applied by
    :func:`state_layout` on top of the flax annotations — which is ALL the
    new placement machinery an LM needs (zero new lowering code).

    Three leaf families are LM-specific and carry no flax annotation:

      * ``tok_embed/embedding`` ``[V, D]`` — feature-sharded over
        ``model`` (the same column family every Dense kernel uses, so the
        embedded activation arrives in the layout the first block's qkv
        matmul wants);
      * ``pos_embed`` ``[1, S, D]`` — replicated (tiny, read every step);
      * ``head/kernel`` ``[D, V]`` — column-parallel over ``model``:
        vocab-parallel logits, the transpose-consistent layout to the
        embedding.

    The attention/MLP kernel rules RESTATE what the shared modules already
    annotate (``tp.column_init``) — ``state_layout`` cross-checks rule
    against annotation and refuses on drift, so a renamed module or a
    silently-dropped annotation fails at layout derivation, not as a wrong
    compiled sharding. Expert tensors keep their ``MoeMlp`` annotations on
    ``moe_axis`` (restated here so the table documents the full LM family).
    """
    return SpecTable(
        rules=(
            SpecRule(r"tok_embed/embedding$", P(None, "model")),
            SpecRule(r"pos_embed$", P()),
            # head is a models/layers.Dense (wraps nn.Dense as Dense_0)
            SpecRule(r"head/Dense_0/kernel$", P(None, "model")),
            SpecRule(r"head/Dense_0/bias$", P("model")),
            # restatements of the flax annotations (cross-checked):
            SpecRule(r"Attention_0/Dense_\d+/Dense_0/kernel$",
                     P(None, "model")),
            SpecRule(r"Mlp_0/Dense_\d+/Dense_0/kernel$", P(None, "model")),
            SpecRule(r"MoeMlp_0/(w_in|w_out)$", P(moe_axis)),
            SpecRule(r"MoeMlp_0/(b_in|b_out)$", P(moe_axis)),
        ),
        default=None,  # unmatched leaves keep their annotation/replication
        strict=False,
    )


def lm_cache_spec() -> P:
    """Placement of the paged KV cache ``[L, B, H, C, Dh]`` under TP
    decode (ISSUE 17): heads sharded over ``model`` — the axis the qkv
    column-parallel kernels already split heads on, so each model shard
    writes and reads ONLY its own heads' pages and the cache never moves
    between shards. Every other dim (layers, slots, positions, head dim)
    is replicated."""
    return P(None, None, "model", None, None)


def lm_decode_shardings(mesh: Mesh, params) -> Any:
    """NamedSharding tree for a PLAIN (unboxed) GPTDecoder param tree:
    the :func:`lm_spec_table` path rules applied leaf-by-leaf, unmatched
    leaves replicated. The decoder mirrors the training GPT module names
    exactly (lm/generate.GPTDecoder), so the SAME declaration that places
    training state places decode state — zero decode-specific rules.
    Every derived spec is validated before it can reach GSPMD."""
    table = lm_spec_table()
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        pstr = leaf_path(path)
        spec = table.spec_for(pstr)
        if spec is None:
            spec = P()
        validate_leaf_spec(
            pstr, spec, tuple(jax.numpy.shape(leaf)), axis_sizes
        )
        out.append(NamedSharding(mesh, spec))
    return jax.tree.unflatten(treedef, out)


def apply_spec_table(base, table: SpecTable, mesh: Mesh):
    """Overlay a path-pattern table onto a NamedSharding tree (the
    annotation-derived base): a leaf a rule matches gets the rule's spec;
    a leaf whose ANNOTATION disagrees with a matching rule raises
    :class:`SpecConflictError` — the table is a declaration, and a
    declaration that contradicts the module annotations is drift, not an
    override. Unmatched leaves pass through untouched."""

    def _stripped(spec) -> tuple:
        entries = list(tuple(spec) if spec is not None else ())
        while entries and entries[-1] is None:
            entries.pop()
        return tuple(entries)

    flat, treedef = jax.tree_util.tree_flatten_with_path(base)
    out = []
    for path, sh in flat:
        pstr = leaf_path(path)
        spec = table.spec_for(pstr)
        if spec is None:
            out.append(sh)
            continue
        annotated = _stripped(sh.spec)
        if annotated and annotated != _stripped(spec):
            raise SpecConflictError(
                f"leaf {pstr}: spec-table rule declares {spec} but the "
                f"module annotation says {sh.spec} — fix the rule or the "
                "annotation; they are one declaration"
            )
        out.append(NamedSharding(mesh, spec))
    return jax.tree.unflatten(treedef, out)


def batch_spec(key: str, *, leading_dims: int = 0) -> P:
    """Spec for batch leaf ``key`` with ``leading_dims`` extra leading
    dims (fold / accum stacking) before the batch dim."""
    base = BATCH_TABLE.spec_for(key)
    return P(*([None] * leading_dims + list(tuple(base))))


# --------------------------------------------------------- state layouts


def base_specs(abstract_variables) -> Any:
    """The DECLARED base spec tree of a (possibly flax-boxed) variables
    tree: the ``nn.with_partitioning`` annotation for boxed leaves,
    ``P()`` (replicated) for plain ones. This is the per-leaf declaration
    every transform below starts from."""
    import flax.linen as nn

    return nn.get_partition_spec(abstract_variables)


def model_dummy_input(model, im_size: int):
    """The init-time dummy for a model: the model's own declaration
    (``model.dummy_input()`` — token models can't eat images, models/gpt.py)
    when present, the standard image dummy otherwise. The ONE place init
    shape assumptions live (abstract_state + trainer.create_train_state)."""
    import jax.numpy as jnp

    fn = getattr(model, "dummy_input", None)
    if fn is not None:
        return fn()
    return jnp.ones((2, im_size, im_size, 3), jnp.float32)


def abstract_state(model, im_size: int):
    """``jax.eval_shape`` of ``model.init`` on the standard dummy input —
    the shape/annotation source for every layout derivation (never runs
    compute)."""
    import functools

    dummy = model_dummy_input(model, im_size)
    return jax.eval_shape(
        functools.partial(model.init, train=False), jax.random.key(0), dummy
    )


def state_layout(model, mesh: Mesh, im_size: int, zero_stage: int) -> dict:
    """Resolved NamedSharding trees for the full train state:
    ``{"params", "opt", "grads"}`` — param-shaped trees.

    The single source the lowering AND the trainer place state with:
      stage 0  all three are the declared base layout (params replicated
               over ``data``, TP/PP annotations where present — the DDP
               topology);
      stage 1  ``opt``/``grads`` move to the ZeRO layout (``data`` added
               per leaf where divisible — parallel/zero.add_data_axis);
      stage 3  ``params`` too (FSDP): rest-sharded, gathered at use. On a
               pipelined model the gather happens at the stage shard_map
               boundary (GSPMD derives it from the in_specs), which is
               what makes ZeRO-3 × PP a layout, not a refusal.

    Every derived leaf spec is validated (:func:`validate_leaf_spec`)
    before it can reach GSPMD.
    """
    import flax

    from distribuuuu_tpu.parallel import tp, zero

    abstract = abstract_state(model, im_size)
    base = tp.param_shardings(mesh, abstract)["params"]
    # models carrying a path-pattern spec table (the LM — models/gpt.py
    # ``param_spec_table``) overlay it here: unannotated LM leaves
    # (embedding/positions/head) get their declared placement, annotated
    # leaves are cross-checked against the matching rule. The transforms
    # and validation below are untouched — this is declaration input, not
    # a new lowering path.
    table_fn = getattr(model, "param_spec_table", None)
    if table_fn is not None:
        base = apply_spec_table(base, table_fn(), mesh)
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    stage = int(zero_stage)
    if not stage:
        layout = {"params": base, "opt": base, "grads": base}
    else:
        abstract_params = flax.linen.meta.unbox(abstract)["params"]
        zsh = zero.zero_shardings(mesh, base, abstract_params)
        layout = {
            "params": zsh if stage == 3 else base,
            "opt": zsh,
            "grads": zsh,
        }
    # refuse malformed derivations before GSPMD sees them
    shapes = flax.linen.meta.unbox(abstract)["params"]
    for key in ("params", "opt", "grads"):
        flat = jax.tree_util.tree_flatten_with_path(layout[key])[0]
        shape_flat = jax.tree_util.tree_flatten_with_path(shapes)[0]
        for (path, sh), (_, leaf) in zip(flat, shape_flat):
            validate_leaf_spec(
                jax.tree_util.keystr(path), sh.spec, tuple(leaf.shape),
                axis_sizes,
            )
    return layout


# ------------------------------------------------- gather scheduling


def compute_layout(layout: dict) -> Any:
    """The params layout DURING compute: the rest layout with the ZeRO
    ``data`` axis stripped per leaf (zero.strip_data_axis — the exact
    inverse of the transform that added it). At stage 0/1 this equals the
    rest layout (identity); at stage 3 it is the gathered form the
    gather-once schedule constrains FSDP leaves to at step entry."""
    from distribuuuu_tpu.parallel import zero

    return jax.tree.map(
        lambda sh: NamedSharding(sh.mesh, zero.strip_data_axis(sh.spec)),
        layout["params"],
    )


def gather_groups(layout: dict) -> Any:
    """Per-leaf block-group index for gather scheduling, derived from the
    SAME path naming the spec-table rules match against: the first
    integer appearing in the leaf path (flax's numbered modules —
    ``ResNetStage_2/...``, ``blocks_5/...``, ``Dense_1/...``) names the
    leaf's group; un-numbered leaves (stem, embeddings, final norm) are
    group 0. Purely a scheduling coordinate — no effect on values — used
    by :func:`gather_schedule` to bound how many groups the gather-once
    transform hoists to step entry (``ZERO.GATHER_AHEAD``)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(layout["params"])
    out = []
    for path, _ in flat:
        m = re.search(r"(\d+)", leaf_path(path))
        out.append(int(m.group(1)) if m else 0)
    return jax.tree.unflatten(treedef, out)


def gather_schedule(layout: dict, ahead: int = -1) -> Any:
    """Per-leaf bool tree: True = this leaf's ZeRO all-gather is hoisted
    to step entry (gathered ONCE per step), False = the leaf keeps its
    rest layout into the step and GSPMD gathers at use sites.

    Derived from the spec algebra alone — a leaf qualifies iff the ZeRO
    transform added ``data`` to its rest spec (stage 3 FSDP leaves; at
    stage 0/1 params rest in the base layout and the schedule is empty).
    ``ahead`` is ``ZERO.GATHER_AHEAD``: -1 hoists every qualifying leaf
    (the default — ~1 gather/leaf/step, full gathered footprint), 0
    hoists none (the legacy per-use schedule), N >= 1 hoists only the
    leaves of the first N block-groups in :func:`gather_groups` order
    (bounds the gathered-live footprint)."""
    ahead = int(ahead)
    if ahead < -1:
        raise ValueError(
            f"ZERO.GATHER_AHEAD={ahead}: must be -1 (hoist the whole "
            "tree), 0 (legacy per-use gathers), or N >= 1 (hoist the "
            "first N block-groups)"
        )
    needs = jax.tree.map(
        lambda sh: "data" in spec_axes(sh.spec), layout["params"]
    )
    if ahead == -1:
        return needs
    if ahead == 0:
        return jax.tree.map(lambda _: False, needs)
    groups = gather_groups(layout)
    ordered = sorted({
        g for g, n in zip(jax.tree.leaves(groups), jax.tree.leaves(needs))
        if n
    })
    hoisted = set(ordered[:ahead])
    return jax.tree.map(lambda n, g: bool(n and g in hoisted), needs, groups)


def collective_expectations(layout: dict, topology,
                            gather_ahead: int | None = None) -> dict:
    """What the spec algebra predicts about the collective schedule of a
    step program lowered from ``layout`` under ``topology`` — the
    referee table the static analyzer's collective lint compares the
    compiled program's per-axis collective census against
    (analysis/passes/collectives.py), and the before/after ledger the
    ZeRO-overlap work (ROADMAP #1) scores itself with.

    Returns ``{"leaves", "zero_sharded", "tp_sharded", "ep_sharded",
    "allowed", "gather_bound", "ring"}``:

      * ``allowed`` maps each collective kind to the mesh-axis sets it
        may legitimately run over. Reductions (``all-reduce``) are
        unconstrained over populated axes — grad means, BN/loss
        reductions. Gather-class ops are the dangerous ones: an
        ``all-gather`` over ``data`` is only predicted when a ZeRO stage
        re-gathers rest layouts; in a plain-DDP program it means
        something rests sharded that the declaration says is replicated,
        i.e. a silent re-gather.
      * ``gather_bound`` bounds the non-metric all-gather count over the
        ``data`` axis. Stage 1: ~2 per rest-resharded leaf (the
        post-update re-gather plus slack for XLA splitting one). Stage 3
        under the gather-once schedule (``ZERO.GATHER_AHEAD`` -1, the
        default): ~1 per leaf — every FSDP leaf is gathered once at step
        entry and never again (the r16 model; the PR 14 census priced
        the per-use schedule at ~9.3/leaf and this bound is what makes a
        schedule regression a finding, not a waiver). With hoisting
        disabled or partial (``GATHER_AHEAD`` >= 0) the per-use ceiling
        (10×/leaf) applies — the escape hatch is priced, not flagged.
        Exceeding the bound is a gather storm even when gathers are
        expected at all.
      * ``ring`` (sp topologies only, else ``None``) is the ring-attention
        collective-permute census band: every attention layer routed over
        the seq axis contributes one ``lax.scan`` ring (2 ppermutes per
        body — the k and v hops, ops/ring_attention.py), the body appears
        ONCE in HLO text regardless of trip count, and autodiff transposes
        each ppermute to another ppermute in the backward scan. So a
        program with N attention layers must census at least N seq-axis
        permutes (a lower count means a ring lost its hops — the attention
        silently stopped rotating K/V and each shard attends only its
        local block) and at most ~8N + slack (an overshoot means extra
        seq-axis traffic the declaration does not predict — e.g. an
        activation bouncing between seq layouts). The analyzer's
        collective lint referees the band (analysis/passes/collectives.py).

    ``gather_ahead`` defaults to the live ``cfg.ZERO.GATHER_AHEAD`` (the
    knob the analyzed program was lowered under).
    """
    leaves = jax.tree.leaves(layout["params"])
    grads = jax.tree.leaves(layout["grads"])
    zero_sharded = sum(
        1 for g in grads if "data" in spec_axes(g.spec)
    )
    tp_sharded = sum(1 for p in leaves if "model" in spec_axes(p.spec))
    ep_sharded = sum(1 for p in leaves if "expert" in spec_axes(p.spec))
    zero = int(getattr(topology, "zero", 0))
    feats = topology.features() if hasattr(topology, "features") else set()
    if gather_ahead is None:
        from distribuuuu_tpu.config import cfg

        gather_ahead = int(cfg.ZERO.GATHER_AHEAD)

    gather_axes = set()
    if tp_sharded or "tp" in feats:
        gather_axes.add("model")
    if ep_sharded or "ep" in feats:
        gather_axes.add("expert")
    if "pp" in feats:
        gather_axes.add("pipe")
    if "sp" in feats:
        gather_axes.add("seq")
    if zero:
        gather_axes.add("data")

    gather_bound = None
    if zero == 1:
        gather_bound = 2 * zero_sharded
    elif zero == 3:
        # gather-once (the default schedule): one entry gather per FSDP
        # leaf + slack for metric/loss-adjacent gathers. Per-use (the
        # GATHER_AHEAD >= 0 escape hatch / partial hoisting): the
        # measured ~9.3-gathers/leaf legacy ceiling, rounded to 10.
        if gather_ahead == -1:
            gather_bound = zero_sharded + 4
        else:
            gather_bound = 10 * zero_sharded

    ring = None
    if "sp" in feats:
        n_attn = sum(
            1
            for path, _ in jax.tree_util.tree_flatten_with_path(
                layout["params"]
            )[0]
            if re.search(
                r"Attention_\d+/Dense_0/Dense_0/kernel$", leaf_path(path)
            )
        )
        if n_attn:
            ring = {
                "axis": "seq",
                "attn_layers": n_attn,
                # >= 1 permute per ring layer must survive compilation
                # (fwd k+v hops may fuse but cannot vanish); <= fwd+bwd
                # k/v pairs per layer doubled for XLA splitting, + slack
                # for layout moves at the shard_map boundary
                "min_permutes": n_attn,
                "max_permutes": 8 * n_attn + 4,
            }

    a2a_axes = set()
    if ep_sharded or "ep" in feats or "tp" in feats:
        a2a_axes |= {"expert", "model"}
    if zero:
        # resharding between two data-sharded layouts that shard
        # DIFFERENT dims (grads vs rest after a reshape) lowers to an
        # all-to-all over data — legitimate whenever a stage is on
        a2a_axes.add("data")
    allowed = {
        "all-reduce": None,  # reductions are always legitimate
        "all-gather": gather_axes,
        "reduce-scatter": (
            {"data"} if zero else set()) | (gather_axes - {"data"}),
        "all-to-all": a2a_axes,
        # point-to-point moves are the lowering's workhorse (GPipe hops,
        # ring decompositions of reduce/gather, MoE rotations, halo
        # exchanges) — censused in the ledger, never bounded here
        "collective-permute": None,
    }
    return {
        "leaves": len(leaves),
        "zero_sharded": zero_sharded,
        "tp_sharded": tp_sharded,
        "ep_sharded": ep_sharded,
        "allowed": allowed,
        "gather_bound": gather_bound,
        "ring": ring,
    }


def added_axes(layout: dict) -> tuple[str, ...]:
    """Mesh axes the ZeRO transform ADDED to the grads layout relative to
    the params-base declaration — the axes the spec-induced
    reduce-scatter/all-gather collectives run over (attribution scope
    names and cost records carry them)."""
    grads = {
        ax
        for leaf in jax.tree.leaves(layout["grads"])
        for ax in spec_axes(leaf.spec)
    }
    params = {
        ax
        for leaf in jax.tree.leaves(layout["params"])
        for ax in spec_axes(leaf.spec)
    }
    return tuple(sorted(grads - params)) or tuple(sorted(grads & {"data"}))
