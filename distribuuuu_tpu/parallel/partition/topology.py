"""The topology registry: validate / classify / enumerate mesh stanzas.

Before this layer every invalid ``MESH`` stanza died in a different place
— ``check_trainer_mesh`` refusals, a model constructor assert, a GSPMD
shape error three layers down — and whole valid regions of the mesh
space (ZeRO-3 under PP; a dp×tp×ep 3-axis mesh) had no code path because
no refusal had been *removed* for them. Here the mesh space is a first-
class object:

  * :func:`from_cfg` resolves a stanza (wildcards included) into a
    :class:`Topology` and validates it against a CAPABILITY table — one
    rule per (feature, arch-family) pair, each carrying the actionable
    error. A stanza that passes is guaranteed a code path through the
    partition lowering.
  * :func:`enumerate_topologies` walks every factorization of the device
    count over the mesh axes × ZeRO stages and yields the valid ones —
    the generator behind ``tools/mesh_sweep.py`` (the MULTICHIP dryrun
    matrix is generated, not hand-enumerated).
  * :meth:`Topology.describe` is the layout record checkpoint manifests
    embed, so elastic resume classifies partition-layer layouts
    (resilience/manifest.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from distribuuuu_tpu.parallel import mesh as mesh_lib


class TopologyError(ValueError):
    """A MESH stanza the capability table refuses (with the reason)."""


# depth of the shipped ViT archs — lets the registry refuse an indivisible
# pipe size at stanza validation instead of deep inside model.init
_VIT_DEPTH = {"vit_tiny": 12, "vit_small": 12, "vit_tiny_moe": 12}

_FEATURE_ORDER = ("dp", "tp", "sp", "pp", "ep", "zero1", "zero3")


@dataclass(frozen=True)
class Topology:
    """One resolved point of the mesh space: axis sizes + ZeRO stage
    (+ the GPipe microbatch count when a pipe axis is present)."""

    data: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1
    zero: int = 0
    microbatch: int = 0  # 0 → 2 × pipe (parallel/pp.py default)

    @property
    def axes(self) -> dict[str, int]:
        return {
            "data": self.data, "model": self.model, "seq": self.seq,
            "pipe": self.pipe, "expert": self.expert,
        }

    def devices(self) -> int:
        n = 1
        for v in self.axes.values():
            n *= v
        return n

    def features(self) -> frozenset[str]:
        feats = set()
        if self.data > 1:
            feats.add("dp")
        if self.model > 1:
            feats.add("tp")
        if self.seq > 1:
            feats.add("sp")
        if self.pipe > 1:
            feats.add("pp")
        if self.expert > 1:
            feats.add("ep")
        if self.zero == 1:
            feats.add("zero1")
        elif self.zero == 3:
            feats.add("zero3")
        return frozenset(feats)

    def class_name(self) -> str:
        """Stable human name, e.g. ``dp2·tp2·ep2·zero1`` (``dp1`` for the
        single-chip degenerate point)."""
        parts = []
        for feat, size in (
            ("dp", self.data), ("tp", self.model), ("sp", self.seq),
            ("pp", self.pipe), ("ep", self.expert),
        ):
            if size > 1:
                parts.append(f"{feat}{size}")
        if self.zero:
            parts.append(f"zero{self.zero}")
        return "·".join(parts) or "dp1"

    def mesh_stanza(self) -> dict:
        """The YAML ``MESH`` stanza reproducing this topology (the sweep
        writes these verbatim; merge with ``cfg.MESH``)."""
        out = {
            "DATA": self.data, "MODEL": self.model, "SEQ": self.seq,
            "PIPE": self.pipe, "EXPERT": self.expert, "ZERO": self.zero,
        }
        if self.pipe > 1:
            out["MICROBATCH"] = self.microbatch or 2 * self.pipe
        return out

    def describe(self) -> dict:
        """The layout record manifests embed (resilience/manifest.py):
        resolved axes, stage, feature set, class name."""
        return {
            "axes": self.axes,
            "zero": self.zero,
            "features": sorted(
                self.features(), key=_FEATURE_ORDER.index
            ),
            "class": self.class_name(),
        }

    def build_mesh(self, devices=None):
        return mesh_lib.build_mesh(
            data=self.data, model=self.model, seq=self.seq, pipe=self.pipe,
            expert=self.expert, devices=devices,
        )

    def moe_axis(self) -> str:
        """Mesh axis MoE expert tensors/dispatch ride: the dedicated
        ``expert`` axis when populated, else the legacy ``model`` axis."""
        return "expert" if self.expert > 1 else "model"


# ------------------------------------------------------- capability rules


@dataclass(frozen=True)
class Rule:
    """One capability-derived refusal: ``broken(topo, arch, moe)``
    returning an error string (or None when the stanza is fine)."""

    name: str
    broken: Callable

    def check(self, topo: Topology, arch: str, moe) -> str | None:
        return self.broken(topo, arch, moe)


def _is_vit(arch: str) -> bool:
    return arch.startswith("vit")


def _is_gpt(arch: str) -> bool:
    return arch.startswith("gpt")


def _is_moe(arch: str) -> bool:
    return arch.endswith("_moe")


def _rule_zero_stage(t, arch, moe):
    if t.zero not in (0, 1, 3):
        return (
            f"MESH.ZERO={t.zero}: stages are 0 (off), 1 (optimizer state "
            "sharded over data), 3 (params too — FSDP); stage 2 is "
            "subsumed by 1 in a fused jit step (parallel/zero.py)"
        )
    return None


def _rule_pipe_arch(t, arch, moe):
    if t.pipe > 1 and not _is_vit(arch):
        return (
            f"MESH.PIPE={t.pipe}: only the ViT archs satisfy the "
            "uniform-stage pipeline contract (parallel/pp.py); a CNN's "
            "shrinking stage pyramid does not — use MESH.DATA/MODEL "
            "for those archs"
        )
    return None


def _rule_pipe_depth(t, arch, moe):
    depth = _VIT_DEPTH.get(arch)
    if t.pipe > 1 and depth is not None and depth % t.pipe:
        return (
            f"MESH.PIPE={t.pipe}: depth {depth} of {arch!r} not divisible "
            "by pipe_stages (models/vit.PipelinedViT uniform-stage "
            "contract)"
        )
    return None


def _rule_pipe_moe_every(t, arch, moe):
    depth = _VIT_DEPTH.get(arch)
    if (
        t.pipe > 1 and _is_moe(arch) and depth is not None and moe is not None
        and (depth // t.pipe) % int(moe.EVERY)
    ):
        return (
            f"MESH.PIPE={t.pipe} with {arch!r}: PP×MoE needs "
            f"blocks-per-stage ({depth // t.pipe}) divisible by "
            f"MODEL.MOE.EVERY ({int(moe.EVERY)}); adjust MESH.PIPE or "
            "MODEL.MOE.EVERY"
        )
    return None


def _rule_pipe_seq(t, arch, moe):
    if t.pipe > 1 and t.seq > 1:
        return (
            f"MESH.PIPE={t.pipe} with MESH.SEQ={t.seq}: sequence-SHARDED "
            "(ring/ulysses) attention does not compose with the pipe axis "
            "— PP shards depth, SP shards tokens; per-device "
            "flash/blockwise attention inside stages is supported instead "
            "(DEVICE.ATTN_IMPL flash)"
        )
    return None


def _rule_seq_arch(t, arch, moe):
    if t.seq > 1 and not (_is_vit(arch) or _is_gpt(arch)):
        return (
            f"MESH.SEQ={t.seq}: only the ViT and GPT archs route "
            "attention over the seq axis (ring/ulysses, "
            "ops/ring_attention.py); CNN archs have no sequence dimension "
            "to shard (the axis would be silently replicated)"
        )
    return None


def _rule_expert_arch(t, arch, moe):
    if t.expert > 1 and not _is_moe(arch):
        return (
            f"MESH.EXPERT={t.expert}: only the *_moe archs dispatch "
            "experts; a dense arch would silently replicate the whole "
            "computation over the expert axis — use MESH.DATA/MODEL "
            "for those archs"
        )
    return None


def _rule_expert_divides(t, arch, moe):
    if t.expert > 1 and moe is not None and int(moe.NUM_EXPERTS) % t.expert:
        return (
            f"MESH.EXPERT={t.expert} must divide MODEL.MOE.NUM_EXPERTS="
            f"{int(moe.NUM_EXPERTS)} (each expert-axis rank owns an equal "
            "slice of the expert tensors)"
        )
    return None


def _rule_expert_seq(t, arch, moe):
    if t.expert > 1 and t.seq > 1:
        return (
            f"MESH.EXPERT={t.expert} with MESH.SEQ={t.seq}: sequence-"
            "sharded attention and dedicated-axis expert dispatch both "
            "want the token dim — compose EP with data/model/pipe axes "
            "instead"
        )
    return None


# NOTE what is deliberately ABSENT here: the old trainer refusal of
# MESH.ZERO=3 with MESH.PIPE>1. Under the partition layer FSDP params are
# a rest LAYOUT — GSPMD derives the gather at the stage shard_map
# boundary from the in_specs and autodiff transposes it to the
# reduce-scatter — so ZeRO-3 × PP is a supported composition, exercised
# by the dryrun sweep and tests/test_partition_lowering.py.
RULES: tuple[Rule, ...] = (
    Rule("zero_stage", _rule_zero_stage),
    Rule("pipe_arch", _rule_pipe_arch),
    Rule("pipe_depth", _rule_pipe_depth),
    Rule("pipe_moe_every", _rule_pipe_moe_every),
    Rule("pipe_seq", _rule_pipe_seq),
    Rule("seq_arch", _rule_seq_arch),
    Rule("expert_arch", _rule_expert_arch),
    Rule("expert_divides", _rule_expert_divides),
    Rule("expert_seq", _rule_expert_seq),
)


def validate(topo: Topology, arch: str, moe=None) -> Topology:
    """Run the capability table; raises :class:`TopologyError` with the
    first broken rule's actionable message, returns ``topo`` unchanged
    otherwise."""
    for rule in RULES:
        msg = rule.check(topo, arch, moe)
        if msg is not None:
            raise TopologyError(msg)
    return topo


def from_cfg(cfg, n_devices: int | None = None) -> Topology:
    """Resolve + validate the live config's MESH stanza.

    ``n_devices`` defaults to ``jax.device_count()`` (wildcard resolution
    needs it). Raises :class:`TopologyError` for stanzas the capability
    table refuses and ``ValueError`` for shapes that don't divide the
    device count — both BEFORE any expensive init/compile.
    """
    if n_devices is None:
        import jax

        n_devices = jax.device_count()
    raw = [
        cfg.MESH.DATA, cfg.MESH.MODEL, cfg.MESH.SEQ, cfg.MESH.PIPE,
        cfg.MESH.get("EXPERT", 1),
    ]
    sizes = mesh_lib.resolve_axis_sizes(raw, n_devices)
    topo = Topology(
        data=sizes[0], model=sizes[1], seq=sizes[2], pipe=sizes[3],
        expert=sizes[4], zero=int(cfg.MESH.ZERO),
        microbatch=int(cfg.MESH.MICROBATCH),
    )
    return validate(topo, cfg.MODEL.ARCH, cfg.MODEL.MOE)


# ------------------------------------------------------------ enumeration


def _factorizations(n: int, k: int):
    """All ordered k-tuples of positive ints with product n."""
    if k == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, k - 1):
                yield (d,) + rest


def default_arch_for(topo: Topology) -> str:
    """Representative zoo arch for a topology's feature set: MoE archs
    where an expert population needs dispatch, ViT where pipe/seq axes
    need the uniform-stage/attention contract, the CNN flagship
    otherwise."""
    feats = topo.features()
    if "ep" in feats:
        return "vit_tiny_moe"
    if "pp" in feats or "sp" in feats:
        return "vit_tiny"
    return "resnet18"


def enumerate_topologies(
    n_devices: int, zero_stages=(0, 1, 3), max_axes: int = 3,
):
    """Yield every VALID ``(topology, arch)`` over the device count:
    all factorizations of ``n_devices`` into the mesh axes (at most
    ``max_axes`` non-unit axes — 4-axis meshes on 8 devices degenerate
    to 2-way everything and add no coverage class) × ZeRO stages, each
    validated against its representative arch through the SAME rule
    table ``from_cfg`` runs. Deterministic order (sorted by class name).
    """
    from distribuuuu_tpu.config import cfg as _cfg

    seen = set()
    out = []
    for sizes in _factorizations(n_devices, 5):
        if sum(1 for s in sizes if s > 1) > max_axes:
            continue
        for zero in zero_stages:
            topo = Topology(
                data=sizes[0], model=sizes[1], seq=sizes[2],
                pipe=sizes[3], expert=sizes[4], zero=zero,
            )
            arch = default_arch_for(topo)
            try:
                validate(topo, arch, _cfg.MODEL.MOE)
            except TopologyError:
                continue
            key = (sizes, zero)
            if key in seen:
                continue
            seen.add(key)
            out.append((topo, arch))
    out.sort(key=lambda ta: (ta[0].class_name(), ta[0].axes["data"]))
    return out


def classify_transition(saved: dict | None, live: dict | None) -> tuple[str, str]:
    """Elastic-resume compatibility of two :meth:`Topology.describe`
    records: ``("exact"|"reshardable", detail)``.

    Partition-layer layouts are reshardable across EVERY axis/stage
    change — arrays re-place onto the live layout leaf by leaf
    (trainer._place_like; ZeRO shards reassemble through canonical leaf
    order) — so the classification's job is the DETAIL: which axes and
    stage moved, for the operator log and the resume drills. Model
    incompatibility is decided by the manifest's tree/fingerprint check,
    not here."""
    saved, live = saved or {}, live or {}
    s_axes, l_axes = saved.get("axes") or {}, live.get("axes") or {}
    diffs = [
        f"{ax} {s_axes.get(ax, 1)}→{l_axes.get(ax, 1)}"
        for ax in sorted(set(s_axes) | set(l_axes))
        if int(s_axes.get(ax, 1)) != int(l_axes.get(ax, 1))
    ]
    if saved.get("zero", 0) != live.get("zero", 0):
        diffs.append(f"zero {saved.get('zero', 0)}→{live.get('zero', 0)}")
    if not diffs:
        return "exact", ""
    return "reshardable", (
        f"partition layout {saved.get('class', '?')}→"
        f"{live.get('class', '?')} ({'; '.join(diffs)})"
    )
