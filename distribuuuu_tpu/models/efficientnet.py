"""EfficientNet-B0 (arXiv:1905.11946), implemented from scratch in flax.

The reference reaches this arch through timm (ref: /root/reference/
distribuuuu/trainer.py:123-128; config/efficientnet_b0.yaml). Param-count
oracle from the baseline table: 5.289M (ref: README.md:212).

MBConv: 1x1 expand → depthwise k×k → SE (ratio 0.25 of block input) →
1x1 project, residual when stride 1 and channels match. SiLU activations,
BN eps 1e-3 (torch momentum 0.01 ⇒ flax momentum 0.99).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distribuuuu_tpu.models.layers import (
    BatchNorm,
    Dense,
    PointwiseKernel,
    SqueezeExcite,
    conv_kernel_init,
    fused_pointwise_path,
    global_avg_pool,
    head_dtype,
)

# (expand_ratio, channels, repeats, stride, kernel)
_B0_BLOCKS = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)


def _BN(dtype, bn_group=0, name=None):
    # torch momentum 0.01 ⇒ flax momentum 0.99; eps 1e-3 (EfficientNet BN)
    return BatchNorm(dtype=dtype, momentum=0.99, epsilon=1e-3,
                     group_size=bn_group, name=name)


def _conv(features, kernel, strides=1, groups=1, dtype=jnp.bfloat16,
          name=None):
    k = (kernel, kernel)
    pad = [(kernel // 2, kernel // 2)] * 2
    return nn.Conv(
        features, k, strides=strides, padding=pad, feature_group_count=groups,
        use_bias=False, dtype=dtype, param_dtype=jnp.float32,
        kernel_init=conv_kernel_init, name=name,
    )


def _conv_bn_act(x, features, kernel, strides, groups, act, idx, dtype,
                 bn_group, train):
    """conv → BN → (act) under the canonical ``Conv_{idx}`` /
    ``BatchNorm_{idx}`` names, routed through the fused Pallas pointwise
    epilogue (ops/pallas/conv_epilogue.py) when ``KERNELS.CONV_EPILOGUE``
    selects it for this site — EfficientNet's expand/project/head 1×1s
    are exactly the memory-bound chains the kernel exists for. Explicit
    names keep the param tree identical on both paths (and to the
    pre-tier auto-named tree)."""
    k = (kernel, kernel)
    pad = [(kernel // 2, kernel // 2)] * 2
    if fused_pointwise_path(k, strides, pad, groups, act, train):
        from distribuuuu_tpu.ops import pallas as kernel_tier
        from distribuuuu_tpu.ops.pallas import conv_epilogue

        kern = PointwiseKernel(features, name=f"Conv_{idx}")(x.shape[-1])
        a, c = _BN(dtype, bn_group, name=f"BatchNorm_{idx}")(
            jnp.zeros((1, features), dtype), fold=True
        )
        return conv_epilogue.conv1x1_bn_act(
            x.astype(dtype), kern.astype(dtype), a, c,
            conv_epilogue.act_code(act),
            interpret=kernel_tier.interpret_mode(),
        )
    y = _conv(features, kernel, strides, groups, dtype,
              name=f"Conv_{idx}")(x)
    y = _BN(dtype, bn_group, name=f"BatchNorm_{idx}")(y, train=train)
    return act(y) if act is not None else y


class MBConv(nn.Module):
    in_ch: int
    out_ch: int
    expand_ratio: int
    strides: int
    kernel: int
    dtype: Any = jnp.bfloat16
    bn_group: int = 0

    @nn.compact
    def __call__(self, x, train: bool = False):
        inp = x
        ch = self.in_ch * self.expand_ratio
        idx = 0
        if self.expand_ratio != 1:
            x = _conv_bn_act(x, ch, 1, 1, 1, nn.silu, idx, self.dtype,
                             self.bn_group, train)
            idx += 1
        x = _conv_bn_act(x, ch, self.kernel, self.strides, ch, nn.silu, idx,
                         self.dtype, self.bn_group, train)
        idx += 1
        # SE, reduction relative to block input channels
        se_ch = max(1, self.in_ch // 4)
        x = SqueezeExcite(se_ch, act=nn.silu, dtype=self.dtype)(x)
        # project: 1×1, no activation (the "id" epilogue when fused)
        x = _conv_bn_act(x, self.out_ch, 1, 1, 1, None, idx, self.dtype,
                         self.bn_group, train)
        if self.strides == 1 and self.in_ch == self.out_ch:
            x = x + inp
        return x


class EfficientNet(nn.Module):
    blocks: tuple = _B0_BLOCKS
    stem_ch: int = 32
    head_ch: int = 1280
    num_classes: int = 1000
    dropout_rate: float = 0.2
    dtype: Any = jnp.bfloat16
    bn_group: int = 0

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = _conv_bn_act(x, self.stem_ch, 3, 2, 1, nn.silu, 0, self.dtype,
                         self.bn_group, train)
        in_ch = self.stem_ch
        for t, c, n, s, k in self.blocks:
            for i in range(n):
                x = MBConv(
                    in_ch=in_ch,
                    out_ch=c,
                    expand_ratio=t,
                    strides=s if i == 0 else 1,
                    kernel=k,
                    dtype=self.dtype,
                    bn_group=self.bn_group,
                )(x, train=train)
                in_ch = c
        # head 1×1: the zoo's widest pointwise chain (→1280 channels) —
        # the fused epilogue's flagship site
        x = _conv_bn_act(x, self.head_ch, 1, 1, 1, nn.silu, 1, self.dtype,
                         self.bn_group, train)
        x = global_avg_pool(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return Dense(self.num_classes, dtype=head_dtype(x.dtype))(
            x.astype(head_dtype(x.dtype))
        )


def efficientnet_b0(num_classes=1000, **kw):
    return EfficientNet(num_classes=num_classes, **kw)
