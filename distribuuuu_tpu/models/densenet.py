"""DenseNet family (arXiv:1608.06993), TPU-native flax implementation.

Capability parity with the reference (ref: /root/reference/distribuuuu/models/
densenet.py): dense layers (BN→relu→1x1 bottleneck→BN→relu→3x3) with
concatenative growth, transitions halving channels + 2x2 avgpool, and the
``memory_efficient`` option — the reference's torch.utils.checkpoint
(ref: densenet.py:81-86,104-110) maps to ``flax.linen.remat``
(jax.checkpoint): activations inside each dense layer are rematerialized in
the backward pass, trading FLOPs for HBM exactly like the torch version.

Constructors: densenet121/161/169/201 (ref: densenet.py:300-365).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distribuuuu_tpu.models.layers import (
    BatchNorm,
    Dense,
    StemConv7x7,
    conv_kernel_init,
    global_avg_pool,
    head_dtype,
    max_pool_3x3_s2,
)


class DenseLayer(nn.Module):
    """BN→relu→conv1x1(bn_size·k)→BN→relu→conv3x3(k) (ref: densenet.py:23-117)."""

    growth_rate: int
    bn_size: int = 4
    dtype: Any = jnp.bfloat16
    bn_group: int = 0

    @nn.compact
    def __call__(self, x, train: bool = False):
        out = BatchNorm(dtype=self.dtype, group_size=self.bn_group)(x, train=train)
        out = nn.relu(out)
        out = nn.Conv(
            self.bn_size * self.growth_rate, (1, 1), use_bias=False,
            dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=conv_kernel_init,
        )(out)
        out = BatchNorm(dtype=self.dtype, group_size=self.bn_group)(out, train=train)
        out = nn.relu(out)
        out = nn.Conv(
            self.growth_rate, (3, 3), padding=[(1, 1), (1, 1)], use_bias=False,
            dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=conv_kernel_init,
        )(out)
        return out


class DenseNet(nn.Module):
    """Stem + 4 dense blocks with transitions + BN head (ref: densenet.py:169-263)."""

    growth_rate: int = 32
    block_config: tuple = (6, 12, 24, 16)
    num_init_features: int = 64
    bn_size: int = 4
    num_classes: int = 1000
    memory_efficient: bool = False
    dtype: Any = jnp.bfloat16
    bn_group: int = 0
    s2d_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        # 7x7/s2 stem; the explicit name keeps the param at Conv_0/kernel in
        # both stem modes (StemConv7x7 computes the plain conv at s2d=False)
        x = StemConv7x7(
            self.num_init_features, s2d=self.s2d_stem, dtype=self.dtype,
            name="Conv_0",
        )(x)
        x = BatchNorm(dtype=self.dtype, group_size=self.bn_group)(x, train=train)
        x = nn.relu(x)
        x = max_pool_3x3_s2(x)

        layer_cls = DenseLayer
        if self.memory_efficient:
            # ≙ torch.utils.checkpoint on the bottleneck (densenet.py:81-86):
            # recompute the layer's activations during backprop.
            layer_cls = nn.remat(DenseLayer, static_argnums=(2,))

        num_features = self.num_init_features
        for i, num_layers in enumerate(self.block_config):
            for j in range(num_layers):
                # explicit names keep the param tree identical whether or not
                # memory_efficient wraps the class (checkpoints interchange)
                new = layer_cls(
                    growth_rate=self.growth_rate,
                    bn_size=self.bn_size,
                    dtype=self.dtype,
                    bn_group=self.bn_group,
                    name=f"block{i}_layer{j}",
                )(x, train)
                x = jnp.concatenate([x, new], axis=-1)
                num_features += self.growth_rate
            if i != len(self.block_config) - 1:
                # transition: BN→relu→1x1(half)→avgpool2 (ref: densenet.py:151-166)
                x = BatchNorm(dtype=self.dtype, group_size=self.bn_group)(x, train=train)
                x = nn.relu(x)
                num_features = num_features // 2
                # explicit Conv_{i+1}: the stem occupies the "Conv_0" name,
                # which would otherwise collide with flax auto-numbering
                x = nn.Conv(
                    num_features, (1, 1), use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32, kernel_init=conv_kernel_init,
                    name=f"Conv_{i + 1}",
                )(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))

        x = BatchNorm(dtype=self.dtype, group_size=self.bn_group)(x, train=train)
        x = nn.relu(x)
        x = global_avg_pool(x)
        return Dense(self.num_classes, dtype=head_dtype(x.dtype))(
            x.astype(head_dtype(x.dtype))
        )


def densenet121(num_classes=1000, **kw):
    return DenseNet(32, (6, 12, 24, 16), 64, num_classes=num_classes, **kw)


def densenet161(num_classes=1000, **kw):
    return DenseNet(48, (6, 12, 36, 24), 96, num_classes=num_classes, **kw)


def densenet169(num_classes=1000, **kw):
    return DenseNet(32, (6, 12, 32, 32), 64, num_classes=num_classes, **kw)


def densenet201(num_classes=1000, **kw):
    return DenseNet(32, (6, 12, 48, 32), 64, num_classes=num_classes, **kw)
