"""ResNet family, TPU-native flax implementation.

Capability parity with the reference's ResNet module (ref:
/root/reference/distribuuuu/models/resnet.py): BasicBlock (expansion 1),
Bottleneck (expansion 4, ResNet-V1.5 stride-on-3x3 placement, ref:
resnet.py:107-111), 7x7/s2 stem + 3x3/s2 maxpool, four stages, kaiming
fan-out init (ref: resnet.py:213-218), optional zero-init of the last BN
gamma per block (ref: resnet.py:223-228), and the same 9 constructors:
resnet18/34/50/101/152, resnext50_32x4d/101_32x8d, wide_resnet50_2/101_2
(ref: resnet.py:315-447).

Differences by design (TPU-first, not a translation): NHWC layout, bf16
compute / fp32 params, BN stats over the global (mesh-wide) batch.
"""

from __future__ import annotations

from typing import Any, Sequence, Type

import flax.linen as nn
import jax.numpy as jnp

from distribuuuu_tpu.models.layers import (
    BatchNorm,
    ConvBN,
    Dense,
    global_avg_pool,
    head_dtype,
    max_pool_3x3_s2,
)


class BasicBlock(nn.Module):
    """Two 3x3 convs (ref: resnet.py:57-103). expansion = 1."""

    features: int
    strides: int = 1
    downsample: bool = False
    groups: int = 1
    base_width: int = 64
    zero_init_residual: bool = False
    dtype: Any = jnp.bfloat16
    bn_group: int = 0

    expansion: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        identity = x
        out = ConvBN(
            self.features, (3, 3), self.strides, dtype=self.dtype, act=nn.relu,
            bn_group=self.bn_group,
        )(x, train=train)
        bn2_init = (
            nn.initializers.zeros if self.zero_init_residual else nn.initializers.ones
        )
        out = ConvBN(
            self.features, (3, 3), 1, dtype=self.dtype, bn_scale_init=bn2_init,
            bn_group=self.bn_group,
        )(out, train=train)
        if self.downsample:
            identity = ConvBN(
                self.features * self.expansion, (1, 1), self.strides,
                dtype=self.dtype, bn_group=self.bn_group,
            )(x, train=train)
        return nn.relu(out + identity)


class Bottleneck(nn.Module):
    """1x1 → 3x3(stride) → 1x1 with expansion 4 (ref: resnet.py:106-161).

    Stride lives on the 3x3 (ResNet-V1.5, ref comment resnet.py:107-111).
    """

    features: int
    strides: int = 1
    downsample: bool = False
    groups: int = 1
    base_width: int = 64
    zero_init_residual: bool = False
    dtype: Any = jnp.bfloat16
    bn_group: int = 0

    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        width = int(self.features * (self.base_width / 64.0)) * self.groups
        identity = x
        out = ConvBN(width, (1, 1), 1, dtype=self.dtype, act=nn.relu,
                     bn_group=self.bn_group)(x, train=train)
        out = ConvBN(
            width, (3, 3), self.strides, groups=self.groups, dtype=self.dtype,
            act=nn.relu, bn_group=self.bn_group,
        )(out, train=train)
        bn3_init = (
            nn.initializers.zeros if self.zero_init_residual else nn.initializers.ones
        )
        out = ConvBN(
            self.features * self.expansion, (1, 1), 1, dtype=self.dtype,
            bn_scale_init=bn3_init, bn_group=self.bn_group,
        )(out, train=train)
        if self.downsample:
            identity = ConvBN(
                self.features * self.expansion, (1, 1), self.strides,
                dtype=self.dtype, bn_group=self.bn_group,
            )(x, train=train)
        return nn.relu(out + identity)


class ResNet(nn.Module):
    """Stem + 4 stages + head (ref: resnet.py:164-297)."""

    block: Type[nn.Module]
    layers: Sequence[int]
    num_classes: int = 1000
    groups: int = 1
    width_per_group: int = 64
    zero_init_residual: bool = False
    dtype: Any = jnp.bfloat16
    bn_group: int = 0
    s2d_stem: bool = False
    # Rematerialize stages 1-2 (``TRAIN.REMAT``): their blocks hold the
    # largest activations (56²/28² maps), so on an HBM-bus-bound step
    # recomputing them in the backward trades spare MXU flops for the
    # stored-activation traffic. ``nn.remat`` is a lifted transform — the
    # param tree, init, and math are identical with the knob on or off
    # (step equivalence: tests/test_remat.py); checkpoints interchange.
    remat: bool = False
    stage_features = (64, 128, 256, 512)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        # stem: 7x7/s2 conv + BN + relu + 3x3/s2 maxpool (ref: resnet.py:194-199);
        # s2d_stem selects the space-to-depth compute path (layers.StemConv7x7)
        x = ConvBN(
            64, (7, 7), 2, padding=[(3, 3), (3, 3)], dtype=self.dtype,
            act=nn.relu, s2d_stem=self.s2d_stem, bn_group=self.bn_group,
        )(x, train=train)
        x = max_pool_3x3_s2(x)
        in_features = 64
        block_idx = 0
        for stage, (feats, n_blocks) in enumerate(
            zip(self.stage_features, self.layers)
        ):
            block_cls = self.block
            if self.remat and stage < 2:
                # train is arg 2 of __call__ (after self, x): static — it
                # selects the traced graph, it is not a tracer
                block_cls = nn.remat(self.block, static_argnums=(2,))
            strides = 1 if stage == 0 else 2
            for i in range(n_blocks):
                s = strides if i == 0 else 1
                needs_down = s != 1 or in_features != feats * self.block.expansion
                x = block_cls(
                    features=feats,
                    strides=s,
                    downsample=needs_down and i == 0,
                    groups=self.groups,
                    base_width=self.width_per_group,
                    zero_init_residual=self.zero_init_residual,
                    dtype=self.dtype,
                    bn_group=self.bn_group,
                    # the name auto-naming would give the UNwrapped class:
                    # nn.remat prefixes the class name ("CheckpointBasic
                    # Block_0"), which would fork the param tree between
                    # the two modes — pinning the name keeps checkpoints
                    # mode-independent
                    name=f"{self.block.__name__}_{block_idx}",
                )(x, train)  # positional: static_argnums above indexes it
                block_idx += 1
                in_features = feats * self.block.expansion
        x = global_avg_pool(x)
        x = Dense(self.num_classes, dtype=head_dtype(x.dtype))(
            x.astype(head_dtype(x.dtype))
        )
        return x


# ---------------------------------------------------------------------------
# Constructors (ref: resnet.py:315-447). PRETRAINED-URL loading is not
# replicated: torch zoo weights are NCHW torch pickles; weight ingestion is
# via the checkpoint system instead.
# ---------------------------------------------------------------------------

def _resnet(block, layers, num_classes=1000, **kw):
    return ResNet(block=block, layers=layers, num_classes=num_classes, **kw)


def resnet18(num_classes=1000, **kw):
    return _resnet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return _resnet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return _resnet(Bottleneck, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return _resnet(Bottleneck, [3, 4, 23, 3], num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return _resnet(Bottleneck, [3, 8, 36, 3], num_classes, **kw)


def resnext50_32x4d(num_classes=1000, **kw):
    return _resnet(Bottleneck, [3, 4, 6, 3], num_classes, groups=32, width_per_group=4, **kw)


def resnext101_32x8d(num_classes=1000, **kw):
    return _resnet(Bottleneck, [3, 4, 23, 3], num_classes, groups=32, width_per_group=8, **kw)


def wide_resnet50_2(num_classes=1000, **kw):
    return _resnet(Bottleneck, [3, 4, 6, 3], num_classes, width_per_group=128, **kw)


def wide_resnet101_2(num_classes=1000, **kw):
    return _resnet(Bottleneck, [3, 4, 23, 3], num_classes, width_per_group=128, **kw)
