"""Model registry (ref: /root/reference/distribuuuu/models/__init__.py:1-7).

The reference dispatches ``build_model(arch)`` through module globals with a
timm fallback at the call site (ref: trainer.py:123-128). timm does not exist
here; every baseline arch — including RegNet-X/Y and EfficientNet-B0, which
the reference outsources to timm — is implemented natively, so the registry
is closed and errors are explicit.
"""

from __future__ import annotations

from distribuuuu_tpu.models.resnet import (  # noqa: F401
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    resnext50_32x4d,
    resnext101_32x8d,
    wide_resnet50_2,
    wide_resnet101_2,
)
from distribuuuu_tpu.models.densenet import (  # noqa: F401
    densenet121,
    densenet161,
    densenet169,
    densenet201,
)
from distribuuuu_tpu.models.botnet import botnet50  # noqa: F401
from distribuuuu_tpu.models.regnet import (  # noqa: F401
    regnetx_160,
    regnety_160,
    regnety_320,
)
from distribuuuu_tpu.models.efficientnet import efficientnet_b0  # noqa: F401
from distribuuuu_tpu.models.vit import (  # noqa: F401
    vit_small,
    vit_tiny,
    vit_tiny_moe,
)
from distribuuuu_tpu.models.gpt import gpt_nano, gpt_nano_moe  # noqa: F401

_REGISTRY = {}


def register_model(fn):
    _REGISTRY[fn.__name__] = fn
    return fn


for _fn in (
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    resnext50_32x4d,
    resnext101_32x8d,
    wide_resnet50_2,
    wide_resnet101_2,
    densenet121,
    densenet161,
    densenet169,
    densenet201,
    botnet50,
    regnetx_160,
    regnety_160,
    regnety_320,
    efficientnet_b0,
    # TPU-native extensions (no reference analogue): seq-parallel-capable ViT
    vit_tiny,
    vit_small,
    # expert-parallel MoE variant (ops/moe.py over the model axis)
    vit_tiny_moe,
    # decoder-only LM workload plane (models/gpt.py, ISSUE 12): token
    # batches, causal attention, next-token CE through the same trainer
    gpt_nano,
    gpt_nano_moe,
):
    register_model(_fn)


def available_models():
    return sorted(_REGISTRY)


def build_model(arch: str, **kwargs):
    """Construct a model by name (≙ models.build_model + timm fallback)."""
    if arch not in _REGISTRY:
        raise KeyError(
            f"Unknown arch '{arch}'. Available: {', '.join(available_models())}. "
            "This zoo is closed — there is no timm fallback (ref: "
            "trainer.py:123-128); register a custom arch with "
            "@distribuuuu_tpu.models.register_model (see README 'Custom "
            "architectures')."
        )
    return _REGISTRY[arch](**kwargs)
