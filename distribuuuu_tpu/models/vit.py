"""Vision Transformer — TPU-native extension (no reference analogue).

The reference zoo is CNNs + one hybrid (BoTNet). ViT is added because it is
the workload the framework's sequence-parallel machinery exists for: token
count scales quadratically with resolution, and the attention can run
**sequence-sharded** — ``attn_impl="ring"`` / ``"ulysses"`` route through
ops/ring_attention.py over the mesh's ``seq`` axis, so high-resolution /
long-sequence training distributes without restructuring the model. With
``attn_impl="xla"`` (default) attention is a dense einsum and the model is a
standard data/tensor-parallel citizen.

Architecture follows the ViT paper (arXiv:2010.11929) with global average
pooling instead of a class token (keeps the token count a clean multiple of
the seq-axis size for sharding; accuracy-equivalent per the paper's
appendix) and pre-norm blocks.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distribuuuu_tpu.models.layers import Dense


class Mlp(nn.Module):
    hidden: int
    out: int
    dropout: float
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.gelu(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = Dense(self.out, dtype=self.dtype)(x)
        return nn.Dropout(self.dropout, deterministic=not train)(x)


def _axis_is_bound(name: str) -> bool:
    """True when ``name`` is a bound mesh axis in the current trace (i.e.
    we are inside a shard_map body). Trace-time check — resolves before
    compilation, so both branches stay jit-compatible.

    Pinned JAX behavior (ADVICE r3 #3): the axis-size probe raises
    ``NameError`` for an unbound axis name as of jax 0.4-0.7. That
    exception type is not a stable API, so any exception here is treated
    as 'unbound' — the safe default: selecting the fallback path at worst
    costs the inline optimization, while crashing at trace time would
    take the whole PP-MoE step down with a future JAX. Routed through
    parallel/compat.axis_size (r6): a bare ``jax.lax.axis_size`` does not
    exist on jax 0.4.x, so the probe ALWAYS took the except branch there
    and silently disabled the inline path."""
    from distribuuuu_tpu.parallel.compat import axis_size

    try:
        axis_size(name)
        return True
    except Exception:
        return False


class MoeMlp(nn.Module):
    """Mixture-of-experts FFN block (expert parallelism, ops/moe.py).

    Expert tensors are sharded over the ``model`` mesh axis (dim 0), so EP
    rides the same axis TP does — at ``MESH.MODEL=1`` everything is
    replicated and the math is the dense reference formulation. With a mesh,
    tokens stay on their data shard and each rank computes its local
    experts' partials + one psum (``moe_ffn_partial_batched``) — exact
    MoE, no token dropping.

    The switch-transformer load-balancing aux (arXiv:2101.03961) is sown
    into the ``intermediates`` collection under ``moe_aux``; the trainer
    adds ``MODEL.MOE.AUX_WEIGHT ×`` its mean to the task loss.

    ``impl`` selects the execution strategy (config ``MODEL.MOE.IMPL``):
    ``"partial"`` — every rank runs its local experts on all tokens, one
    psum; exact, O(E/n) compute per token — right for small E.
    ``"dispatch"`` — switch-style all_to_all routing at a fixed capacity
    (``MODEL.MOE.CAPACITY_FACTOR``); compute O(top_k) per token — the
    scalable-EP path for large E. Its dropped-assignment fraction is sown
    into the ``moe_stats`` collection (surfaced as the trainer's
    ``moe_dropped`` metric).
    """

    dim: int
    hidden: int
    num_experts: int
    top_k: int
    dtype: Any
    mesh: Any = None
    impl: str = "partial"
    capacity_factor: float = 2.0
    # True inside an enclosing shard_map (pipeline stages): run the
    # expert-partials body inline on bound axes instead of opening a
    # (nested, illegal) shard_map. Outside any shard_map this flag is
    # inert — the dense reference path runs (init, sequential fallback).
    axes_bound: bool = False
    # >0: the expert tensors this module RECEIVES hold only this many
    # (this rank's) experts — the PP×EP sharded-entry layout, where the
    # pipeline shard_map's in_specs split the expert dim over the MoE
    # axis (ADVICE r3 #1: O(E/n) per-device param memory, not O(E)). The
    # gate and the routing space stay global (num_experts). 0 = full.
    experts_local: int = 0
    # Mesh axis the expert tensors/dispatch ride: "model" (the legacy
    # layout — EP time-shares the TP axis) or "expert" (the dedicated
    # axis, MESH.EXPERT>1 — EP composes with TP on a dp×tp×ep mesh).
    moe_axis: str = "model"

    @nn.compact
    def __call__(self, x, train: bool = False):
        from distribuuuu_tpu.ops import moe as moe_ops

        MODEL_AXIS = self.moe_axis
        E = self.num_experts
        EL = self.experts_local or E
        d, f = self.dim, self.hidden
        scale_in = 1.0 / np.sqrt(d)
        scale_out = 1.0 / np.sqrt(f)

        def normal(scale):
            return nn.initializers.normal(stddev=scale)

        params = {
            "gate": self.param("gate", normal(scale_in), (d, E), jnp.float32),
            "w_in": self.param(
                "w_in",
                nn.with_partitioning(normal(scale_in), (MODEL_AXIS, None, None)),
                (EL, d, f), jnp.float32,
            ),
            "b_in": self.param(
                "b_in",
                nn.with_partitioning(nn.initializers.zeros, (MODEL_AXIS, None)),
                (EL, f), jnp.float32,
            ),
            "w_out": self.param(
                "w_out",
                nn.with_partitioning(normal(scale_out), (MODEL_AXIS, None, None)),
                (EL, f, d), jnp.float32,
            ),
            "b_out": self.param(
                "b_out",
                nn.with_partitioning(nn.initializers.zeros, (MODEL_AXIS, None)),
                (EL, d), jnp.float32,
            ),
        }
        B, S, _ = x.shape
        x = x.astype(self.dtype)
        data_size = (
            self.mesh.shape.get("data", 1) if self.mesh is not None else 1
        )
        # the dense reference path also covers batches that cannot shard
        # over data (the tiny init-time dummy) — identical math either way
        if self.impl not in ("partial", "dispatch"):
            raise ValueError(
                f"MODEL.MOE.IMPL must be 'partial' or 'dispatch', "
                f"got {self.impl!r}"
            )
        if EL != E and not (self.axes_bound and _axis_is_bound(MODEL_AXIS)):
            raise ValueError(
                f"experts_local={EL} (sharded-entry expert tensors) is "
                "only valid inside a pipeline stage's shard_map with the "
                "model axis bound"
            )
        if self.axes_bound and _axis_is_bound(MODEL_AXIS):
            # inside an enclosing shard_map (a pipeline stage): mesh axes
            # are already bound — run the strategy body INLINE (nested
            # shard_map is illegal; the collectives compose fine on the
            # bound axes). x is this rank's token shard. Collapses to the
            # dense loop + free collectives at model-axis size 1.
            from distribuuuu_tpu.parallel.compat import axis_size

            n = axis_size(MODEL_AXIS)
            r = jax.lax.axis_index(MODEL_AXIS)
            if E % n:
                raise ValueError(
                    f"model axis size {n} must divide num_experts {E}"
                )
            local_E = E // n
            if EL != E:
                # sharded entry (experts_local): the pipeline's in_specs
                # already split the expert dim over ``model`` — the
                # received tensors ARE this rank's experts (no slice, no
                # replicated copy; ADVICE r3 #1)
                if EL != local_E:
                    raise ValueError(
                        f"experts_local={EL} != num_experts {E} / "
                        f"model-axis size {n}"
                    )
                local = params
            else:
                # replicated entry: slice this rank's experts
                local = {
                    "gate": params["gate"],
                    **{
                        k: jax.lax.dynamic_slice_in_dim(
                            params[k], r * local_E, local_E, 0
                        )
                        for k in ("w_in", "b_in", "w_out", "b_out")
                    },
                }
            if self.impl == "dispatch":
                # switch-style all_to_all routing on the bound axis
                # (VERDICT r3 #3); dropped fraction rides the stage-aux
                # channel (parallel/pp.pipelined stage_aux) to the trainer
                out, dropped = moe_ops.dispatch_inline(
                    local, x, axis=MODEL_AXIS, top_k=self.top_k,
                    capacity_factor=self.capacity_factor,
                )
                self.sow(
                    "moe_stats", "dropped", dropped,
                    reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0,
                )
            else:
                # expert-partials: exact math (drops nothing), one psum
                out = moe_ops._rank_partials(
                    local, x.reshape(B * S, d), MODEL_AXIS, self.top_k
                ).reshape(B, S, d)
        elif (
            self.mesh is not None
            and self.mesh.shape.get(MODEL_AXIS, 1) > 1
            and B % data_size == 0
        ):
            if self.impl == "dispatch":
                out, dropped = moe_ops.moe_ffn_dispatch_batched(
                    params, x, mesh=self.mesh, axis=MODEL_AXIS,
                    top_k=self.top_k,
                    capacity_factor=self.capacity_factor,
                )
                self.sow(
                    "moe_stats", "dropped", dropped,
                    reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0,
                )
            else:
                out = moe_ops.moe_ffn_partial_batched(
                    params, x, mesh=self.mesh, axis=MODEL_AXIS,
                    top_k=self.top_k,
                )
        else:
            out = moe_ops.moe_ffn_reference(
                params, x.reshape(B * S, d), top_k=self.top_k
            ).reshape(B, S, d)
        if train:
            # aux from the same router function on the same tokens/gate the
            # expert paths used (identical values up to reduction order)
            probs = moe_ops.gating_probs(x.reshape(B * S, d), params["gate"])
            f, p = moe_ops.balance_stats(probs, self.top_k)
            self.sow(
                "intermediates", "moe_aux",
                moe_ops.aux_from_balance_stats(f, p),
            )
            # the same (f, p) vectors, sown unreduced: means over disjoint
            # token subsets AVERAGE exactly, so pipeline stages accumulate
            # these per microbatch and the full-batch aux is reconstructed
            # outside (PipelinedViT / parallel/pp.pipelined stage_aux).
            # Dead (DCE'd) whenever the ``moe_balance`` collection is not
            # mutable — i.e. always in flat mode, where the scalar above
            # is used instead.
            self.sow("moe_balance", "fp", jnp.stack([f, p]))
        return out


class Attention(nn.Module):
    dim: int
    num_heads: int
    dropout: float
    dtype: Any
    # "auto" | "xla" | "flash" | "blockwise" | "ring" | "ulysses".
    # "auto" resolves per shape at trace time: the Pallas flash kernel
    # (ops/flash_attention.py) for long sequences on TPU, dense XLA
    # otherwise. "flash" forces the kernel (falls back to the lax.scan
    # blockwise path off-TPU — same exact math).
    attn_impl: str = "xla"
    mesh: Any = None        # required for ring/ulysses
    # Causal (autoregressive) masking — the decoder-only LM (models/gpt.py)
    # reuses this exact module with causal=True; position i attends to
    # positions ≤ i. Every impl honors it: the dense path adds the
    # triangular mask before softmax, flash/blockwise/ring already take a
    # ``causal`` flag (ops/*_attention.py). Default False: image ViTs are
    # bidirectional and their programs are untouched.
    causal: bool = False

    # sequence length at/above which "auto" picks the flash kernel (the
    # kernel wins from ~1-2k tokens on a v5e; dense XLA wins below)
    FLASH_MIN_SEQ = 1024

    @staticmethod
    def resolve_impl(attn_impl: str, seq_len: int, dropout: float) -> str:
        """'auto' → 'flash' at ≥FLASH_MIN_SEQ tokens with dropout 0 (the
        flash kernel has no probability-dropout support), dense 'xla'
        otherwise. Exposed so the threshold branch is directly testable."""
        if attn_impl != "auto":
            return attn_impl
        if seq_len >= Attention.FLASH_MIN_SEQ and dropout == 0:
            return "flash"
        return "xla"

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.attn_impl not in (
            "auto", "xla", "flash", "blockwise", "ring", "ulysses"
        ):
            raise ValueError(
                f"vit attn_impl must be 'auto', 'xla', 'flash', 'blockwise', "
                f"'ring', or 'ulysses'; got {self.attn_impl!r}"
            )
        if self.attn_impl not in ("xla", "auto") and self.dropout > 0:
            raise ValueError(
                "attention-probability dropout is not supported under "
                "flash/blockwise/sequence-sharded attention; set dropout=0 "
                "or use attn_impl='xla'"
            )
        B, S, _ = x.shape
        impl = self.resolve_impl(self.attn_impl, S, self.dropout)
        H = self.num_heads
        D = self.dim // H
        qkv = Dense(3 * self.dim, dtype=self.dtype)(x)
        qkv = qkv.reshape(B, S, 3, H, D).transpose(2, 0, 3, 1, 4)  # [3,B,H,S,D]
        q, k, v = qkv[0], qkv[1], qkv[2]

        if impl in ("ring", "ulysses"):
            from distribuuuu_tpu.ops import ring_attention as ra

            assert self.mesh is not None, "seq-parallel attention needs a mesh"
            fn = (
                ra.ring_attention
                if impl == "ring"
                else ra.ulysses_attention
            )
            out = fn(q, k, v, self.mesh, causal=self.causal)
        elif impl == "flash":
            from distribuuuu_tpu.ops import flash_attention as fa

            # Pallas flash kernel on TPU; blockwise scan fallback elsewhere
            out = fa.flash_attention(q, k, v, causal=self.causal)
        elif impl == "blockwise":
            from distribuuuu_tpu.ops import ring_attention as ra

            # O(L·chunk) memory — high-resolution single-chip training
            out = ra.blockwise_attention(q, k, v, causal=self.causal)
        else:
            # the dense path deliberately runs the whole score→softmax→
            # weighted-sum region in f32 (bf16 logits overflow the -1e30
            # mask and lose softmax mass at long S); the named scope
            # declares the promotion to the static analyzer's dtype lint
            # (analysis/passes/dtype.py SAFE_SCOPES convention: a
            # *_fp32 scope is a documented numerical choice)
            with jax.named_scope("attn_softmax_fp32"):
                scale = D ** -0.5
                s = jnp.einsum(
                    "bhqd,bhkd->bhqk",
                    q.astype(jnp.float32), k.astype(jnp.float32),
                ) * scale
                if self.causal:
                    s = jnp.where(
                        jnp.tril(jnp.ones((S, S), bool))[None, None],
                        s, jnp.float32(-1e30),
                    )
                w = jax.nn.softmax(s, axis=-1)
                w = nn.Dropout(self.dropout, deterministic=not train)(w)
                out = jnp.einsum(
                    "bhqk,bhkd->bhqd", w, v.astype(jnp.float32)
                )
                # leave the region in compute dtype HERE so the exit
                # cast (and its autodiff transpose) carries the scope
                out = out.astype(self.dtype)

        out = out.astype(self.dtype).transpose(0, 2, 1, 3).reshape(B, S, self.dim)
        out = Dense(self.dim, dtype=self.dtype)(out)
        return nn.Dropout(self.dropout, deterministic=not train)(out)


class Block(nn.Module):
    dim: int
    num_heads: int
    mlp_ratio: float
    dropout: float
    dtype: Any
    attn_impl: str
    mesh: Any
    moe_experts: int = 0  # >0: MoE FFN instead of the dense Mlp
    moe_top_k: int = 2
    moe_impl: str = "partial"
    moe_capacity_factor: float = 2.0
    moe_axes_bound: bool = False  # inside a pipeline stage's shard_map
    moe_experts_local: int = 0  # PP×EP sharded entry (MoeMlp.experts_local)
    moe_axis: str = "model"  # mesh axis EP rides (MoeMlp.moe_axis)
    causal: bool = False  # autoregressive masking (models/gpt.py decoder)

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = x + Attention(
            self.dim, self.num_heads, self.dropout, self.dtype,
            self.attn_impl, self.mesh, causal=self.causal,
        )(y, train=train)
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        if self.moe_experts > 0:
            ffn = MoeMlp(
                self.dim, int(self.dim * self.mlp_ratio), self.moe_experts,
                self.moe_top_k, self.dtype, self.mesh,
                impl=self.moe_impl,
                capacity_factor=self.moe_capacity_factor,
                axes_bound=self.moe_axes_bound,
                experts_local=self.moe_experts_local,
                moe_axis=self.moe_axis,
            )
        else:
            ffn = Mlp(
                int(self.dim * self.mlp_ratio), self.dim, self.dropout,
                self.dtype,
            )
        x = x + ffn(y, train=train)
        return x


class _ViTCommon(nn.Module):
    """Shared patch-embed/head helpers for the ViT variants.

    Plain methods, NOT child modules: their params stay at the variant's
    top level under the original auto-names (``Conv_0``, ``pos_embed``,
    ``LayerNorm_0``, ``Dense_0``), so checkpoints keep their paths across
    variants and releases (the same stability contract
    models/layers.BatchNorm pins with its fixed child name)."""

    def _embed(self, x, train: bool):
        B, H, W, _ = x.shape
        assert H % self.patch == 0 and W % self.patch == 0, (
            f"input {H}x{W} not divisible by patch {self.patch}"
        )
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.dim, (self.patch, self.patch), strides=self.patch,
            dtype=self.dtype, param_dtype=jnp.float32,
        )(x)
        S = (H // self.patch) * (W // self.patch)
        x = x.reshape(B, S, self.dim)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, S, self.dim), jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        return nn.Dropout(self.dropout, deterministic=not train)(x)

    def _head(self, x):
        from distribuuuu_tpu.models.layers import head_dtype

        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = x.mean(axis=1)  # GAP over tokens
        hd = head_dtype(x.dtype)
        return Dense(self.num_classes, dtype=hd)(x.astype(hd))


class ViT(_ViTCommon):
    """Patch embed → pre-norm transformer blocks → LN → GAP → head."""

    num_classes: int = 1000
    patch: int = 16
    dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_ratio: float = 4.0
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attn_impl: str = "xla"
    mesh: Any = None
    moe_experts: int = 0  # >0: MoE FFN in every ``moe_every``-th block
    moe_top_k: int = 2
    moe_every: int = 2
    moe_impl: str = "partial"
    moe_capacity_factor: float = 2.0
    moe_axis: str = "model"  # mesh axis EP rides (MoeMlp.moe_axis)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = self._embed(x, train)
        for i in range(self.depth):
            # MoE in every moe_every-th block (odd indices at the default 2 —
            # the GShard/ViT-MoE placement); dense FFN elsewhere
            moe = (
                self.moe_experts
                if self.moe_experts > 0 and i % self.moe_every == self.moe_every - 1
                else 0
            )
            x = Block(
                self.dim, self.num_heads, self.mlp_ratio, self.dropout,
                self.dtype, self.attn_impl, self.mesh,
                moe_experts=moe, moe_top_k=self.moe_top_k,
                moe_impl=self.moe_impl,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_axis=self.moe_axis,
            )(x, train=train)
        return self._head(x)


class ViTStage(nn.Module):
    """``blocks_per_stage`` uniform transformer blocks — the pipeline-stage
    unit for :class:`PipelinedViT` (satisfies parallel/pp.py's uniform
    param-structure + activation-shape contract)."""

    dim: int
    num_heads: int
    mlp_ratio: float
    dropout: float
    dtype: Any
    blocks_per_stage: int
    attn_impl: str = "xla"
    moe_experts: int = 0  # PP×EP: MoE FFN in every moe_every-th block
    moe_top_k: int = 2
    moe_every: int = 2
    moe_impl: str = "partial"
    moe_capacity_factor: float = 2.0
    moe_experts_local: int = 0  # PP×EP sharded entry (MoeMlp.experts_local)
    moe_axis: str = "model"  # mesh axis EP rides (MoeMlp.moe_axis)

    @nn.compact
    def __call__(self, x, train: bool = False):
        for j in range(self.blocks_per_stage):
            # uniform per-stage placement; PipelinedViT enforces
            # blocks_per_stage % moe_every == 0 so the LOCAL pattern
            # coincides with the flat model's GLOBAL i % moe_every one
            # (checkpoint converters keep working)
            moe = (
                self.moe_experts
                if self.moe_experts > 0
                and j % self.moe_every == self.moe_every - 1
                else 0
            )
            x = Block(
                self.dim, self.num_heads, self.mlp_ratio, self.dropout,
                self.dtype, self.attn_impl, None,
                moe_experts=moe, moe_top_k=self.moe_top_k,
                moe_impl=self.moe_impl,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_axes_bound=True,
                moe_experts_local=self.moe_experts_local,
                moe_axis=self.moe_axis,
            )(x, train=train)
        return x


class PipelinedViT(_ViTCommon):
    """ViT with the block stack run as a GPipe pipeline over the ``pipe``
    mesh axis (parallel/pp.py).

    Params: patch embed / head are ordinary (replicated) children; the
    ``depth`` blocks live in ONE ``stages`` param — a stacked pytree with
    leading dim ``pipe_stages`` sharded over ``pipe`` (each device holds
    only its stage's blocks). Embed/head compute is replicated across pipe
    ranks (standard SPMD pipelining; it is tiny next to the blocks).

    The same stacked params also run **sequentially** (stage s applied in
    order) — used when the batch cannot be microbatched (e.g. ``init``) and
    as the correctness oracle in tests: GPipe is math-preserving, so both
    paths agree.

    PP×EP (``moe_experts > 0``): MoE blocks inside stages run their
    strategy INLINE on the already-bound ``model`` axis (models/vit.MoeMlp
    ``axes_bound`` — a nested shard_map would be illegal; the partial
    psum and the dispatch all_to_alls compose fine on bound axes). Expert
    placement must be uniform per stage: ``depth/pipe_stages`` divisible
    by ``moe_every`` (then it coincides with the flat model's placement
    and the checkpoint converters keep working). The load-balancing aux
    IS collected under PP (r4): MoE blocks sow their (f, p) balance
    vectors, the pipeline accumulates them per microbatch through the
    scan carry (``pp.pipelined`` ``stage_aux``), and ``_sow_moe_aux``
    reconstructs the full-batch aux exactly (the vectors are token means,
    so equal-size subsets average exactly — ops/moe.balance_stats); the
    dispatch strategy's dropped fraction rides the same channel. Expert
    tensors enter the stage shard_map SPLIT over ``model``
    (``_stage_param_specs`` + ``MoeMlp.experts_local``), so per-device
    parameter memory is O(E/n) like flat EP — the r3 replicated-entry
    O(E) caveat is closed (ADVICE r3 #1).
    """

    num_classes: int = 1000
    patch: int = 16
    dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_ratio: float = 4.0
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attn_impl: str = "xla"
    mesh: Any = None
    pipe_stages: int = 2
    pipe_microbatches: int = 0  # 0 → 2 × pipe_stages
    moe_experts: int = 0  # PP×EP (see _stage_module)
    moe_top_k: int = 2
    moe_every: int = 2
    moe_impl: str = "partial"
    moe_capacity_factor: float = 2.0
    moe_axis: str = "model"  # mesh axis EP rides (MoeMlp.moe_axis)

    def _stage_module(self, experts_local: int = 0):
        if self.depth % self.pipe_stages:
            raise ValueError(
                f"depth {self.depth} not divisible by pipe_stages "
                f"{self.pipe_stages}"
            )
        if self.moe_experts > 0:
            k = self.depth // self.pipe_stages
            if k % self.moe_every:
                # local placement j % every must equal the flat model's
                # global i % every (i = s·k + j) on every stage — holds
                # iff every | k; otherwise checkpoints/conversions and
                # the uniform-stage contract would silently diverge
                raise ValueError(
                    f"PP×MoE needs blocks-per-stage ({k} = depth "
                    f"{self.depth} / pipe {self.pipe_stages}) divisible "
                    f"by MODEL.MOE.EVERY ({self.moe_every}); adjust "
                    "MESH.PIPE or MODEL.MOE.EVERY"
                )
        if self.dropout > 0:
            raise ValueError(
                "dropout inside pipeline stages is not supported (stage "
                "apply runs under shard_map without an rng); set dropout=0"
            )
        if self.attn_impl in ("ring", "ulysses"):
            # sequence-SHARDED attention is genuinely incompatible: its
            # collectives run over the ``seq`` axis, which a pipe mesh
            # does not populate (PP shards depth, SP shards tokens — pick
            # one per dimension). Per-device kernels compose fine: flash
            # is an opaque pallas_call / blockwise a lax.scan, both legal
            # inside the pipeline's shard_map (VERDICT r2 #7 probe —
            # tests/test_pp_ep_trainer.py::test_pipe_with_flash_attention).
            raise ValueError(
                "sequence-sharded attention (ring/ulysses) does not "
                "compose with the pipe axis; use MESH.SEQ without PIPE, "
                f"or attn_impl in ('xla', 'flash', 'blockwise') "
                f"(got {self.attn_impl!r})"
            )
        return ViTStage(
            self.dim, self.num_heads, self.mlp_ratio, 0.0, self.dtype,
            self.depth // self.pipe_stages,
            attn_impl=self.attn_impl,
            moe_experts=self.moe_experts, moe_top_k=self.moe_top_k,
            moe_every=self.moe_every, moe_impl=self.moe_impl,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_experts_local=experts_local,
            moe_axis=self.moe_axis,
        )

    def _stage_param_specs(self, stage_mod):
        """Per-leaf shard_map in_specs for the stacked stage params:
        expert tensors (Partitioned with ``model`` on dim 0) get
        ``P('pipe', 'model', ...)`` so each device receives ONLY its
        experts — O(E/n) param memory instead of the replicated O(E)
        (ADVICE r3 #1); everything else enters ``P('pipe')`` (replicated
        over model — the stage body computes dense layers locally, with
        no TP collectives inside)."""
        from jax.sharding import PartitionSpec as P

        moe_axis = self.moe_axis

        dummy = jnp.zeros((1, 8, self.dim), jnp.float32)
        template = jax.eval_shape(
            lambda: stage_mod.init(
                jax.random.key(0), dummy, train=False
            )["params"]
        )

        def spec(t):
            if (
                isinstance(t, nn.Partitioned)
                and t.names
                and t.names[0] == moe_axis
            ):
                return P("pipe", moe_axis)
            return P("pipe")

        return jax.tree.map(
            spec, template, is_leaf=lambda x: isinstance(x, nn.Partitioned)
        )

    def _sow_moe_aux(self, aux):
        """Reconstruct full-batch MoE statistics from per-stage collections
        (each leaf [S, ...]: stage dim from ``pp.pipelined``'s gather or the
        sequential fallback's stack) and sow them where the trainer looks:

        - ``intermediates/moe_aux``: ONE scalar — the mean over all MoE
          blocks of the switch aux computed from the ACCUMULATED (f, p)
          vectors. Exactly the flat model's ``mean(per-block aux)`` (up to
          reduction order): f/p are token means, so per-microbatch values
          average to the full-batch value before the bilinear E·Σf·p.
        - ``moe_stats/dropped``: the blocks' mean dropped fraction
          (dispatch strategy only; microbatch fractions average exactly —
          every microbatch has the same assignment total).
        """
        from distribuuuu_tpu.ops import moe as moe_ops

        bal = jax.tree.leaves(aux.get("moe_balance", {}))  # [S, 2, E] each
        if bal:
            per_block = [
                jax.vmap(
                    lambda fp: moe_ops.aux_from_balance_stats(fp[0], fp[1])
                )(fp)  # [S]
                for fp in bal
            ]
            self.sow(
                "intermediates", "moe_aux", jnp.stack(per_block).mean()
            )
        drp = jax.tree.leaves(aux.get("moe_stats", {}))  # [S] each
        if drp:
            self.sow(
                "moe_stats", "dropped", jnp.stack(drp).mean(),
                reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0,
            )

    @nn.compact
    def __call__(self, x, train: bool = False):
        from distribuuuu_tpu.parallel import pp

        stage_mod = self._stage_module()
        S = self.pipe_stages
        M = self.pipe_microbatches or 2 * S

        def init_stages(key):
            keys = jax.random.split(key, S)
            dummy = jnp.zeros((1, 8, self.dim), jnp.float32)

            def one(k):
                return stage_mod.init(k, dummy, train=False)["params"]

            template = jax.eval_shape(one, keys[0])  # boxed: TP names
            stacked = jax.vmap(lambda k: nn.meta.unbox(one(k)))(keys)

            def rebox(t, v):
                # stage dim 0 → "pipe"; inner TP names preserved (PP × TP)
                if isinstance(t, nn.Partitioned):
                    return nn.Partitioned(v, names=("pipe",) + tuple(t.names))
                return nn.Partitioned(
                    v, names=("pipe",) + (None,) * (np.ndim(v) - 1)
                )

            return jax.tree.map(
                rebox, template, stacked,
                is_leaf=lambda n: isinstance(n, nn.Partitioned),
            )

        x = self._embed(x, train)
        stages = self.param("stages", init_stages)
        B = x.shape[0]

        # collect MoE statistics (balance aux + dispatch drop fraction)
        # through the stage-aux channel whenever they exist
        collect = train and self.moe_experts > 0

        def make_stage_fn(mod):
            def stage_fn(p, a):
                if not collect:
                    return mod.apply({"params": p}, a, train=train)
                return mod.apply(
                    {"params": p}, a, train=train,
                    mutable=["moe_balance", "moe_stats"],
                )

            return stage_fn

        mesh = self.mesh
        pipe_on_mesh = mesh is not None and mesh.shape.get("pipe", 1) == S
        # each data shard needs M whole microbatches
        need = M * (mesh.shape.get("data", 1) if pipe_on_mesh else 1)
        if pipe_on_mesh and S > 1 and B >= need:
            if B % need:
                raise ValueError(
                    f"batch {B} does not split into {M} GPipe microbatches "
                    f"per data shard (need a multiple of {need}; "
                    "MESH.MICROBATCH × data axis)"
                )
            # PP×EP sharded entry (ADVICE r3 #1): split the expert dim over
            # ``model`` in the shard_map in_specs and give the stage a
            # module declaring the LOCAL expert count — O(E/n) per-device
            # param memory; the inline MoE paths skip their slice
            ep_n = mesh.shape.get(self.moe_axis, 1)
            sharded_ep = (
                self.moe_experts > 0
                and ep_n > 1
                and self.moe_experts % ep_n == 0
            )
            if sharded_ep:
                run_mod = self._stage_module(
                    experts_local=self.moe_experts // ep_n
                )
                param_specs = self._stage_param_specs(stage_mod)
            else:
                run_mod, param_specs = stage_mod, None
            piped = pp.pipelined(
                make_stage_fn(run_mod), mesh=mesh, num_microbatches=M,
                stage_aux=collect, param_specs=param_specs,
            )
            if collect:
                x, aux = piped(stages, x)
                self._sow_moe_aux(aux)
            else:
                x = piped(stages, x)
        else:
            # sequential fallback: same params, same math (used for the
            # tiny init-time dummy batch and on meshes without a pipe axis)
            stage_fn = make_stage_fn(stage_mod)
            muts = []
            for s in range(S):
                out = stage_fn(jax.tree.map(lambda a: a[s], stages), x)
                if collect:
                    x, mut = out
                    muts.append(mut)
                else:
                    x = out
            if collect:
                # stack per-stage collections into the same [S, ...] layout
                # the pipelined path gathers (stats here are full-batch per
                # stage — no microbatching — so the combiner is exact too)
                self._sow_moe_aux(
                    jax.tree.map(lambda *xs: jnp.stack(xs), *muts)
                )
        return self._head(x)


def _is_boxed(x):
    return isinstance(x, nn.Partitioned)


def pipe_to_flat_params(params):
    """PipelinedViT params → plain ViT params (same weights).

    The stacked ``stages`` tree (leading dim S, blocks ``Block_j`` within a
    stage) scatters to top-level ``Block_{s·k+j}``; embed/head params keep
    their shared top-level names (``_ViTCommon``), so the result loads
    straight into the non-pipelined :class:`ViT` — train pipelined,
    evaluate (or resume) anywhere.

    Partitioning metadata is handled: slicing drops the leading ``pipe``
    axis name along with the stage dim, and leaves whose remaining names
    are all ``None`` unbox back to plain arrays — the exact inverse of
    ``init_stages``' rebox, so boxed ``model.init`` output converts to the
    layout a plain ViT's init produces.
    """
    stages = params["stages"]
    block_names = sorted(stages, key=lambda n: int(n.split("_")[-1]))
    k = len(block_names)
    S = jax.tree.leaves(stages)[0].shape[0]

    def slice_leaf(a, s):
        if _is_boxed(a):
            names = tuple(a.names)[1:]  # drop the 'pipe' axis name
            if any(n is not None for n in names):
                return nn.Partitioned(a.value[s], names=names)
            return a.value[s]
        return a[s]

    out = {}
    for name, sub in params.items():
        if name != "stages":
            out[name] = sub
    for s in range(S):
        for j, bname in enumerate(block_names):
            out[f"Block_{s * k + j}"] = jax.tree.map(
                lambda a: slice_leaf(a, s), stages[bname], is_leaf=_is_boxed
            )
    return out


def flat_to_pipe_params(params, pipe_stages: int):
    """Plain ViT params → PipelinedViT params (inverse of
    :func:`pipe_to_flat_params`): ``Block_{s·k+j}`` stacks into
    ``stages/Block_j`` with leading dim ``pipe_stages``, every stacked
    leaf boxed with a leading ``pipe`` axis name (inner TP names
    preserved) — the same metadata ``PipelinedViT``'s ``init_stages``
    establishes, so sharding derivation places the result correctly."""
    blocks = {
        int(n.split("_")[-1]): sub
        for n, sub in params.items()
        if n.startswith("Block_")
    }
    depth = len(blocks)
    if depth % pipe_stages:
        raise ValueError(
            f"{depth} blocks do not split into {pipe_stages} stages"
        )
    k = depth // pipe_stages

    def stack_leaves(*xs):
        if _is_boxed(xs[0]):
            vals = jnp.stack([x.value for x in xs])
            return nn.Partitioned(vals, names=("pipe",) + tuple(xs[0].names))
        vals = jnp.stack(xs)
        return nn.Partitioned(vals, names=("pipe",) + (None,) * xs[0].ndim)

    out = {n: sub for n, sub in params.items() if not n.startswith("Block_")}
    stages = {}
    for j in range(k):
        stages[f"Block_{j}"] = jax.tree.map(
            stack_leaves,
            *[blocks[s * k + j] for s in range(pipe_stages)],
            is_leaf=_is_boxed,
        )
    out["stages"] = stages
    return out


def _vit(num_classes, kw, **defaults):
    for k, v in defaults.items():
        kw.setdefault(k, v)
    pipe = kw.pop("pipe_stages", 0)
    if pipe and pipe > 1:
        kw.setdefault("pipe_microbatches", 0)
        return PipelinedViT(num_classes=num_classes, pipe_stages=pipe, **kw)
    kw.pop("pipe_microbatches", None)
    return ViT(num_classes=num_classes, **kw)


def vit_tiny(num_classes=1000, **kw):
    """ViT-Ti/16: 192 dim, 12 blocks, 3 heads (~5.5M params at 1000 cls)."""
    return _vit(num_classes, kw, dim=192, depth=12, num_heads=3)


def vit_small(num_classes=1000, **kw):
    """ViT-S/16: 384 dim, 12 blocks, 6 heads (~21.7M params at 1000 cls)."""
    return _vit(num_classes, kw, dim=384, depth=12, num_heads=6)


def vit_tiny_moe(num_classes=1000, **kw):
    """ViT-Ti/16 with MoE FFN in every 2nd block (8 experts, top-2 by
    default — override via MODEL.MOE.*). The trainer-reachable
    expert-parallel arch: expert tensors shard over the ``model`` axis."""
    kw.setdefault("moe_experts", 8)
    return _vit(num_classes, kw, dim=192, depth=12, num_heads=3)
