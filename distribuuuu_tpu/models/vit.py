"""Vision Transformer — TPU-native extension (no reference analogue).

The reference zoo is CNNs + one hybrid (BoTNet). ViT is added because it is
the workload the framework's sequence-parallel machinery exists for: token
count scales quadratically with resolution, and the attention can run
**sequence-sharded** — ``attn_impl="ring"`` / ``"ulysses"`` route through
ops/ring_attention.py over the mesh's ``seq`` axis, so high-resolution /
long-sequence training distributes without restructuring the model. With
``attn_impl="xla"`` (default) attention is a dense einsum and the model is a
standard data/tensor-parallel citizen.

Architecture follows the ViT paper (arXiv:2010.11929) with global average
pooling instead of a class token (keeps the token count a clean multiple of
the seq-axis size for sharding; accuracy-equivalent per the paper's
appendix) and pre-norm blocks.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distribuuuu_tpu.models.layers import Dense


class Mlp(nn.Module):
    hidden: int
    out: int
    dropout: float
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.gelu(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = Dense(self.out, dtype=self.dtype)(x)
        return nn.Dropout(self.dropout, deterministic=not train)(x)


class Attention(nn.Module):
    dim: int
    num_heads: int
    dropout: float
    dtype: Any
    attn_impl: str = "xla"  # "xla" | "blockwise" | "ring" | "ulysses"
    mesh: Any = None        # required for ring/ulysses

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.attn_impl not in ("xla", "blockwise", "ring", "ulysses"):
            raise ValueError(
                f"vit attn_impl must be 'xla', 'blockwise', 'ring', or "
                f"'ulysses'; got {self.attn_impl!r}"
            )
        if self.attn_impl != "xla" and self.dropout > 0:
            raise ValueError(
                "attention-probability dropout is not supported under "
                "blockwise/sequence-sharded attention; set dropout=0 or "
                "use attn_impl='xla'"
            )
        B, S, _ = x.shape
        H = self.num_heads
        D = self.dim // H
        qkv = Dense(3 * self.dim, dtype=self.dtype)(x)
        qkv = qkv.reshape(B, S, 3, H, D).transpose(2, 0, 3, 1, 4)  # [3,B,H,S,D]
        q, k, v = qkv[0], qkv[1], qkv[2]

        if self.attn_impl in ("ring", "ulysses"):
            from distribuuuu_tpu.ops import ring_attention as ra

            assert self.mesh is not None, "seq-parallel attention needs a mesh"
            fn = (
                ra.ring_attention
                if self.attn_impl == "ring"
                else ra.ulysses_attention
            )
            out = fn(q, k, v, self.mesh, causal=False)
        elif self.attn_impl == "blockwise":
            from distribuuuu_tpu.ops import ring_attention as ra

            # O(L·chunk) memory — high-resolution single-chip training
            out = ra.blockwise_attention(q, k, v, causal=False)
        else:
            scale = D ** -0.5
            s = jnp.einsum(
                "bhqd,bhkd->bhqk",
                q.astype(jnp.float32), k.astype(jnp.float32),
            ) * scale
            w = jax.nn.softmax(s, axis=-1)
            w = nn.Dropout(self.dropout, deterministic=not train)(w)
            out = jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32))

        out = out.astype(self.dtype).transpose(0, 2, 1, 3).reshape(B, S, self.dim)
        out = Dense(self.dim, dtype=self.dtype)(out)
        return nn.Dropout(self.dropout, deterministic=not train)(out)


class Block(nn.Module):
    dim: int
    num_heads: int
    mlp_ratio: float
    dropout: float
    dtype: Any
    attn_impl: str
    mesh: Any

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = x + Attention(
            self.dim, self.num_heads, self.dropout, self.dtype,
            self.attn_impl, self.mesh,
        )(y, train=train)
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = x + Mlp(
            int(self.dim * self.mlp_ratio), self.dim, self.dropout, self.dtype
        )(y, train=train)
        return x


class ViT(nn.Module):
    """Patch embed → pre-norm transformer blocks → LN → GAP → head."""

    num_classes: int = 1000
    patch: int = 16
    dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_ratio: float = 4.0
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attn_impl: str = "xla"
    mesh: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, H, W, _ = x.shape
        assert H % self.patch == 0 and W % self.patch == 0, (
            f"input {H}x{W} not divisible by patch {self.patch}"
        )
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.dim, (self.patch, self.patch), strides=self.patch,
            dtype=self.dtype, param_dtype=jnp.float32,
        )(x)
        S = (H // self.patch) * (W // self.patch)
        x = x.reshape(B, S, self.dim)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, S, self.dim), jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for _ in range(self.depth):
            x = Block(
                self.dim, self.num_heads, self.mlp_ratio, self.dropout,
                self.dtype, self.attn_impl, self.mesh,
            )(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = x.mean(axis=1)  # GAP over tokens
        return Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )


def vit_tiny(num_classes=1000, **kw):
    """ViT-Ti/16: 192 dim, 12 blocks, 3 heads (~5.5M params at 1000 cls)."""
    kw.setdefault("dim", 192)
    kw.setdefault("depth", 12)
    kw.setdefault("num_heads", 3)
    return ViT(num_classes=num_classes, **kw)


def vit_small(num_classes=1000, **kw):
    """ViT-S/16: 384 dim, 12 blocks, 6 heads (~21.7M params at 1000 cls)."""
    kw.setdefault("dim", 384)
    kw.setdefault("depth", 12)
    kw.setdefault("num_heads", 6)
    return ViT(num_classes=num_classes, **kw)
